"""Repo-native developer tooling (no third-party dependencies).

``tools/tslint`` is the static-analysis pass wired into
``scripts/lint.sh`` / ``scripts/repro.sh`` (see ANALYSIS.md).
"""
