"""tslint: repo-native static analysis for the failure classes ruff's
E/F/W set cannot see (ANALYSIS.md).

Rules: TS001 jit-purity, TS002 host-sync-in-hot-loop, TS003
monotonic-clock, TS004 lock-discipline, TS005 broad-except, TS006
donation-aliasing.  Stdlib-only (``ast``): no third-party dependency,
same no-network constraint as scripts/lint.sh.

API:
    from tools.tslint import analyze            # engine entry
    python -m tools.tslint --baseline tools/tslint/baseline.json
"""

from tools.tslint.engine import (  # noqa: F401
    AnalysisResult,
    Finding,
    analyze,
    load_baseline,
    match_baseline,
    write_baseline,
)
from tools.tslint.rules import RULES  # noqa: F401

__version__ = "1.0"
