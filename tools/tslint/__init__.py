"""tslint: repo-native static analysis for the failure classes ruff's
E/F/W set cannot see (ANALYSIS.md).

Per-file rules: TS001 jit-purity, TS002 host-sync-in-hot-loop, TS003
monotonic-clock, TS004 lock-discipline, TS005 broad-except, TS006
donation-aliasing.  Interprocedural concurrency rules (v2, riding the
callgraph.py thread/lock model): TS007 lock-order-cycle, TS008
blocking-under-lock, TS009 cross-thread-unlocked-write, TS010
future-single-resolution.  Stdlib-only (``ast``): no third-party
dependency, same no-network constraint as scripts/lint.sh.

API:
    from tools.tslint import analyze            # engine entry
    python -m tools.tslint --baseline tools/tslint/baseline.json
"""

from tools.tslint.engine import (  # noqa: F401
    AnalysisResult,
    Finding,
    analyze,
    load_baseline,
    lock_graph,
    match_baseline,
    write_baseline,
)
from tools.tslint.rules import RULES  # noqa: F401
from tools.tslint.concurrency import PROJECT_RULES  # noqa: F401

#: per-file rules + interprocedural concurrency rules, in id order
ALL_RULES = tuple(RULES) + tuple(PROJECT_RULES)

__version__ = "2.0"
