"""The six tslint rules (ANALYSIS.md documents each failure mode).

| Rule  | Catches |
|-------|---------|
| TS001 | Python side effects inside jit-traced functions (run at trace
|       | time only, silently absent from the compiled step)
| TS002 | blocking device->host syncs inside declared hot loops
| TS003 | durations computed from the jumpable wall clock (time.time())
| TS004 | writes to lock-protected attributes outside the lock
| TS005 | `except Exception` that swallows (no re-raise, no typed
|       | mapping, no obs error counter)
| TS006 | a buffer-donated argument referenced after the jitted call
|       | (the buffer is dead — reads return garbage or crash)

Every rule is a pure function over one ``engine.FileContext``; rules
never import the analyzed code (AST only), so they are safe on files
that would crash on import.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from tools.tslint.engine import FileContext, walk_within


def _dotted(node: Optional[ast.AST]) -> Optional[str]:
    """'jax.lax.scan' for Attribute chains, 'x' for Names, else None
    (any Subscript/Call in the chain breaks it — by design: `a.at[i].set`
    must not read as a dotted name rooted at `a`)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def _defs(tree: ast.AST) -> List[ast.AST]:
    return [n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]


def _assign_targets(node: ast.AST) -> List[ast.AST]:
    if isinstance(node, ast.Assign):
        out: List[ast.AST] = []
        for t in node.targets:
            out.extend(t.elts if isinstance(t, ast.Tuple) else [t])
        return out
    if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        return [node.target]
    return []


def _prefix_match(dotted: str, roots: Sequence[str]) -> bool:
    return any(dotted == r or dotted.startswith(r + ".") for r in roots)


# --------------------------------------------------------------------------
# TS001 — jit purity
# --------------------------------------------------------------------------

_JIT_WRAPPERS = {"jax.jit", "jit", "pjit", "jax.pjit"}
_TRACE_SINKS = _JIT_WRAPPERS | {
    "jax.vmap", "vmap", "jax.pmap", "pmap",
    "jax.grad", "jax.value_and_grad",
    "jax.lax.scan", "lax.scan",
    "jax.lax.while_loop", "lax.while_loop",
    "jax.lax.cond", "lax.cond",
    "jax.lax.fori_loop", "lax.fori_loop",
    "jax.lax.map", "lax.map",
    "jax.checkpoint", "jax.remat",
    "shard_map", "jax.experimental.shard_map.shard_map",
    "pl.pallas_call", "pallas_call",
}
_PARTIAL = {"functools.partial", "partial"}
_IMPURE_BUILTINS = {"print", "input", "breakpoint", "open"}
_METRIC_MUTATORS = {"inc", "dec", "observe", "set"}


def _traced_defs(ctx: FileContext) -> Set[ast.AST]:
    """Function/lambda nodes whose bodies run under a JAX trace: jit/pjit
    decorated (incl. functools.partial(jax.jit, ...)), passed by name to
    a trace sink (jit, vmap, grad, lax.scan/while_loop/cond, shard_map,
    pallas_call — possibly through a functools.partial alias), returned
    by a local factory whose call is handed to a sink
    (``jax.jit(make_train_step(hps))``), or lexically nested in any of
    those."""
    tree = ctx.tree
    defs = _defs(tree)
    defs_by_name: Dict[str, List[ast.AST]] = {}
    for d in defs:
        defs_by_name.setdefault(d.name, []).append(d)

    # factory name -> local defs it returns (``def make(): def f(): ...;
    # return f``) — jitting the factory's RESULT traces those defs
    factory_returns: Dict[str, List[ast.AST]] = {}
    for d in defs:
        nested = {n.name: n for n in ast.walk(d)
                  if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                  and n is not d}
        for r in ast.walk(d):
            if isinstance(r, ast.Return) and isinstance(r.value, ast.Name) \
                    and r.value.id in nested:
                factory_returns.setdefault(d.name, []).append(
                    nested[r.value.id])

    # x = functools.partial(f, ...)  ->  alias x -> f
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call) \
                and _dotted(node.value.func) in _PARTIAL \
                and node.value.args \
                and isinstance(node.value.args[0], ast.Name):
            aliases[node.targets[0].id] = node.value.args[0].id

    traced: Set[ast.AST] = set()

    def mark_arg(arg: ast.AST) -> None:
        if isinstance(arg, ast.Name):
            name = aliases.get(arg.id, arg.id)
            traced.update(defs_by_name.get(name, ()))
        elif isinstance(arg, ast.Lambda):
            traced.add(arg)
        elif isinstance(arg, ast.Call):
            fd = _dotted(arg.func)
            if fd in _PARTIAL and arg.args:
                mark_arg(arg.args[0])
            elif isinstance(arg.func, ast.Name) \
                    and arg.func.id in factory_returns:
                traced.update(factory_returns[arg.func.id])

    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _dotted(node.func) in _TRACE_SINKS:
            for a in node.args:
                mark_arg(a)

    for d in defs:
        for dec in d.decorator_list:
            dd = _dotted(dec)
            if dd in _JIT_WRAPPERS:
                traced.add(d)
            elif isinstance(dec, ast.Call):
                dfd = _dotted(dec.func)
                if dfd in _JIT_WRAPPERS or (
                        dfd in _PARTIAL and dec.args
                        and _dotted(dec.args[0]) in _JIT_WRAPPERS):
                    traced.add(d)

    # nested defs/lambdas inside traced functions are traced too
    for root in list(traced):
        for node in ast.walk(root):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not root:
                traced.add(node)
    return traced


def check_ts001(ctx: FileContext) -> None:
    cfg = ctx.rule_config("TS001")
    impure_roots = tuple(cfg.get("impure_roots", ()))
    allowed = tuple(cfg.get("allowed_prefixes", ()))
    traced = _traced_defs(ctx)
    # report from root-most traced nodes only (avoids double reports on
    # nested traced defs)
    roots = [n for n in traced
             if not any(a in traced for a in _ancestors(n))]
    for root in roots:
        for node in ast.walk(root):
            if isinstance(node, ast.Call):
                _ts001_call(ctx, node, impure_roots, allowed)
            elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                for t in _assign_targets(node):
                    td = _dotted(t)
                    if td and (td == "self" or td.startswith("self.")):
                        ctx.report(
                            "TS001", node,
                            f"mutation of {td!r} inside a jit-traced "
                            f"function happens at trace time only (the "
                            f"compiled step never re-runs it); return the "
                            f"value instead")
            elif isinstance(node, (ast.Global, ast.Nonlocal)):
                ctx.report(
                    "TS001", node,
                    "global/nonlocal rebinding inside a jit-traced function "
                    "is a trace-time side effect; thread state through "
                    "arguments/returns")


def _ancestors(node: ast.AST):
    p = getattr(node, "_ts_parent", None)
    while p is not None:
        yield p
        p = getattr(p, "_ts_parent", None)


def _ts001_call(ctx: FileContext, node: ast.Call,
                impure_roots: Tuple[str, ...],
                allowed: Tuple[str, ...]) -> None:
    if isinstance(node.func, ast.Name) and node.func.id in _IMPURE_BUILTINS:
        ctx.report(
            "TS001", node,
            f"{node.func.id}() inside a jit-traced function runs at trace "
            f"time only (use jax.debug.print for runtime output)")
        return
    fd = _dotted(node.func)
    if fd:
        if _prefix_match(fd, allowed):
            return
        if _prefix_match(fd, impure_roots):
            ctx.report(
                "TS001", node,
                f"call to {fd}() inside a jit-traced function is a "
                f"trace-time side effect (it will NOT run per step on "
                f"device); hoist it out of the traced function")
            return
    if isinstance(node.func, ast.Attribute) \
            and node.func.attr in _METRIC_MUTATORS:
        rec = _dotted(node.func.value)
        if rec and (rec == "self" or rec.startswith("self.")):
            ctx.report(
                "TS001", node,
                f"metric mutation {rec}.{node.func.attr}() inside a "
                f"jit-traced function fires once at trace time, not per "
                f"step; record metrics outside the traced function")


# --------------------------------------------------------------------------
# TS002 — host sync in hot loop
# --------------------------------------------------------------------------

_SYNC_CALLS = {"jax.device_get", "jax.block_until_ready",
               "np.asarray", "np.array", "numpy.asarray", "numpy.array"}
_SYNC_METHODS = {"item", "block_until_ready"}
_SYNC_CASTS = {"float", "int"}


def check_ts002(ctx: FileContext) -> None:
    cfg = ctx.rule_config("TS002")
    hot = [re.compile(p) for p in cfg.get("hot_functions", ())]
    exempt = [re.compile(p) for p in cfg.get("exempt_functions", ())]
    for d in _defs(ctx.tree):
        qn = getattr(d, "_ts_scope", d.name)
        if not any(p.search(qn) for p in hot):
            continue
        if any(p.search(qn) for p in exempt):
            continue
        # one walk per function, loop membership decided by ancestry —
        # a sync nested two loops deep is still ONE finding
        for node in walk_within(d):
            if isinstance(node, ast.Call) and _inside_loop(node, d):
                _ts002_call(ctx, node)


def _inside_loop(node: ast.AST, fn: ast.AST) -> bool:
    for a in _ancestors(node):
        if a is fn:
            return False
        if isinstance(a, (ast.For, ast.AsyncFor, ast.While)):
            return True
    return False


def _ts002_call(ctx: FileContext, node: ast.Call) -> None:
    fd = _dotted(node.func)
    if fd in _SYNC_CALLS:
        ctx.report(
            "TS002", node,
            f"{fd}() inside a hot loop is a blocking device->host sync "
            f"that serializes dispatch; batch it into the metrics-flush "
            f"window or move it off the per-step path")
        return
    if isinstance(node.func, ast.Attribute) \
            and node.func.attr in _SYNC_METHODS and not node.args:
        ctx.report(
            "TS002", node,
            f".{node.func.attr}() inside a hot loop is a blocking "
            f"device->host sync that serializes dispatch")
        return
    if isinstance(node.func, ast.Name) and node.func.id in _SYNC_CASTS \
            and len(node.args) == 1 \
            and isinstance(node.args[0], (ast.Name, ast.Attribute, ast.IfExp)):
        ctx.report(
            "TS002", node,
            f"{node.func.id}(...) on a (likely device) value inside a hot "
            f"loop forces a device->host sync; keep metrics on device and "
            f"fetch them in a batched flush")


# --------------------------------------------------------------------------
# TS003 — monotonic clock for durations
# --------------------------------------------------------------------------

_WALL_CLOCKS = {"time.time"}


def check_ts003(ctx: FileContext) -> None:
    scopes: List[ast.AST] = [ctx.tree] + _defs(ctx.tree)
    for scope in scopes:
        wall_vars: Set[str] = set()
        for node in walk_within(scope):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call) \
                    and _dotted(node.value.func) in _WALL_CLOCKS:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        wall_vars.add(t.id)

        def is_wall(n: ast.AST) -> bool:
            if isinstance(n, ast.Call) and _dotted(n.func) in _WALL_CLOCKS:
                return True
            return isinstance(n, ast.Name) and n.id in wall_vars

        for node in walk_within(scope):
            if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub) \
                    and (is_wall(node.left) or is_wall(node.right)):
                ctx.report(
                    "TS003", node,
                    "duration computed from the wall clock (time.time() "
                    "jumps under NTP slew/suspend); use time.monotonic() — "
                    "keep time.time() only for serialized epoch timestamps")


# --------------------------------------------------------------------------
# TS004 — lock discipline
# --------------------------------------------------------------------------

_LOCK_FACTORIES = ("Lock", "RLock", "Condition",
                   # obs/locksan.py wrappers — sanitized locks must stay
                   # visible to the lock-discipline rules
                   "make_lock", "make_rlock", "make_condition")
_CONTAINER_MUTATORS = {
    "append", "appendleft", "extend", "insert", "pop", "popleft", "popitem",
    "remove", "discard", "clear", "update", "add", "setdefault", "sort",
    "reverse",
}
_INIT_METHODS = {"__init__", "__new__", "__post_init__"}


@dataclasses.dataclass
class _Mutation:
    attr: str
    node: ast.AST
    in_lock: bool  # lexically inside `with self.<lock>:`


def check_ts004(ctx: FileContext) -> None:
    for cls in ast.walk(ctx.tree):
        if isinstance(cls, ast.ClassDef):
            _ts004_class(ctx, cls)


def _ts004_class(ctx: FileContext, cls: ast.ClassDef) -> None:
    methods = [n for n in cls.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    lock_attrs: Set[str] = set()
    for m in methods:
        for node in walk_within(m):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                vd = _dotted(node.value.func) or ""
                factory = vd.rsplit(".", 1)[-1] in _LOCK_FACTORIES
                if not factory:
                    continue
                for t in node.targets:
                    td = _dotted(t)
                    if td and td.startswith("self.") and td.count(".") == 1:
                        lock_attrs.add(td.split(".", 1)[1])
    if not lock_attrs:
        return

    mutations: Dict[str, List[_Mutation]] = {}  # method name -> mutations
    callsites: Dict[str, List[Tuple[str, bool]]] = {}  # callee -> (caller, in_lock)

    def is_lock_cm(item: ast.withitem) -> bool:
        d = _dotted(item.context_expr)
        return bool(d and d.startswith("self.")
                    and d.split(".", 1)[1] in lock_attrs)

    def scan(node: ast.AST, mname: str, in_lock: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue  # nested scopes own their own discipline
            child_lock = in_lock
            if isinstance(child, (ast.With, ast.AsyncWith)) \
                    and any(is_lock_cm(i) for i in child.items):
                child_lock = True
            if isinstance(child, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                for t in _assign_targets(child):
                    base = t
                    if isinstance(base, ast.Subscript):
                        base = base.value
                    td = _dotted(base)
                    if td and td.startswith("self.") and td.count(".") == 1:
                        mutations.setdefault(mname, []).append(
                            _Mutation(td.split(".", 1)[1], child, child_lock))
            if isinstance(child, ast.Call):
                fd = _dotted(child.func)
                if fd and fd.startswith("self.") and fd.count(".") == 1:
                    callsites.setdefault(fd.split(".", 1)[1], []).append(
                        (mname, child_lock))
                if isinstance(child.func, ast.Attribute) \
                        and child.func.attr in _CONTAINER_MUTATORS:
                    rd = _dotted(child.func.value)
                    if rd and rd.startswith("self.") and rd.count(".") == 1:
                        mutations.setdefault(mname, []).append(
                            _Mutation(rd.split(".", 1)[1], child, child_lock))
            scan(child, mname, child_lock)

    for m in methods:
        scan(m, m.name, False)

    # fixpoint: a private helper whose EVERY intra-class call site holds
    # the lock (lexically, or transitively through lock-held callers) is
    # itself lock-held — `_set_state` called only under `with self._lock`
    # is disciplined, not a finding
    lock_held: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for m in methods:
            if m.name in lock_held or m.name in _INIT_METHODS:
                continue
            sites = callsites.get(m.name)
            if sites and all(il or caller in lock_held
                             for caller, il in sites):
                lock_held.add(m.name)
                changed = True

    def effective(mut_in_lock: bool, mname: str) -> bool:
        return mut_in_lock or mname in lock_held

    protected: Set[str] = set()
    for mname, muts in mutations.items():
        if mname in _INIT_METHODS:
            continue
        for mut in muts:
            if effective(mut.in_lock, mname):
                protected.add(mut.attr)
    protected -= lock_attrs

    for mname, muts in mutations.items():
        if mname in _INIT_METHODS:
            continue
        for mut in muts:
            if mut.attr in protected and not effective(mut.in_lock, mname):
                ctx.report(
                    "TS004", mut.node,
                    f"attribute 'self.{mut.attr}' is written under a lock "
                    f"elsewhere in {cls.name} but mutated here without "
                    f"holding it (static race); take the lock or document "
                    f"the single-writer invariant with a suppression")


# --------------------------------------------------------------------------
# TS005 — broad except without re-raise / typed mapping / obs counter
# --------------------------------------------------------------------------

_BROAD = {"Exception", "BaseException"}


def check_ts005(ctx: FileContext) -> None:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            broad = True
        elif isinstance(node.type, ast.Tuple):
            broad = any(_dotted(e) in _BROAD for e in node.type.elts)
        else:
            broad = _dotted(node.type) in _BROAD
        if not broad:
            continue
        has_raise = False
        has_counter = False
        for stmt in node.body:
            for n in ast.walk(stmt):
                if isinstance(n, ast.Raise):
                    has_raise = True
                if isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Attribute) \
                        and n.func.attr == "inc":
                    has_counter = True
        if not (has_raise or has_counter):
            ctx.report(
                "TS005", node,
                "broad `except Exception` swallows the failure: re-raise, "
                "map to a typed resilience.errors exception, or increment "
                "an obs error counter (suppress inline with a one-line "
                "justification if intentional)")


# --------------------------------------------------------------------------
# TS006 — donated buffer referenced after the jitted call
# --------------------------------------------------------------------------

def _donated_positions(call: ast.Call) -> Optional[List[int]]:
    """[positions] when `call` is jax.jit/pjit with donate_argnums."""
    if _dotted(call.func) not in _JIT_WRAPPERS:
        return None
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return [v.value]
            if isinstance(v, (ast.Tuple, ast.List)):
                out = [e.value for e in v.elts
                       if isinstance(e, ast.Constant)
                       and isinstance(e.value, int)]
                return out or None
    return None


def check_ts006(ctx: FileContext) -> None:
    scopes: List[ast.AST] = [ctx.tree] + _defs(ctx.tree)
    for scope in scopes:
        _ts006_scope(ctx, scope)


def _ts006_scope(ctx: FileContext, scope: ast.AST) -> None:
    donated: Dict[str, List[int]] = {}  # callable expr -> donated positions
    for node in walk_within(scope):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            td = _dotted(node.targets[0])
            if not td:
                continue
            values = [node.value]
            if isinstance(node.value, ast.IfExp):
                values = [node.value.body, node.value.orelse]
            for v in values:
                if isinstance(v, ast.Call):
                    pos = _donated_positions(v)
                    if pos:
                        donated[td] = sorted(set(donated.get(td, []) + pos))

    # loads/stores of every dotted expr in this scope, in line order
    loads: Dict[str, List[Tuple[int, ast.AST]]] = {}
    stores: Dict[str, List[int]] = {}
    for node in walk_within(scope):
        if isinstance(node, (ast.Name, ast.Attribute)):
            d = _dotted(node)
            if d is None:
                continue
            if isinstance(getattr(node, "ctx", None), ast.Store):
                stores.setdefault(d, []).append(node.lineno)
            elif isinstance(getattr(node, "ctx", None), ast.Load):
                loads.setdefault(d, []).append((node.lineno, node))

    watches: List[Tuple[str, int, str]] = []  # (arg expr, call line, callee)
    for node in walk_within(scope):
        if not isinstance(node, ast.Call):
            continue
        positions: Optional[List[int]] = None
        callee = _dotted(node.func)
        if callee and callee in donated:
            positions = donated[callee]
        elif isinstance(node.func, ast.Call):  # jax.jit(f, donate...)(x)
            positions = _donated_positions(node.func)
            callee = "jax.jit(...)"
        if not positions:
            continue
        for i in positions:
            if i < len(node.args):
                ad = _dotted(node.args[i])
                if ad:
                    watches.append((ad, node.lineno, callee or "?"))

    for expr, call_line, callee in watches:
        uses = sorted(
            ((ln, n) for d, entries in loads.items()
             if d == expr or d.startswith(expr + ".")
             for ln, n in entries if ln > call_line),
            key=lambda t: t[0])
        # >= call_line: `state = step(state, b)` rebinds on the call
        # line itself — that store clears the watch
        store_lines = sorted(ln for ln in stores.get(expr, ())
                             if ln >= call_line)
        for use_line, use_node in uses:
            redefined = any(s <= use_line for s in store_lines)
            if redefined:
                break
            ctx.report(
                "TS006", use_node,
                f"{expr!r} was donated to {callee} (its device buffer is "
                f"consumed by the call) but is referenced again here; "
                f"donated inputs are dead after dispatch — use the "
                f"returned value or drop donate_argnums")
            break


# --------------------------------------------------------------------------
# Registry
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    name: str
    summary: str
    check: Callable[[FileContext], None]


RULES: Tuple[Rule, ...] = (
    Rule("TS001", "jit-purity",
         "Python side effects inside jit-traced functions run at trace "
         "time only", check_ts001),
    Rule("TS002", "host-sync-in-hot-loop",
         "blocking device->host syncs inside declared hot loops serialize "
         "dispatch", check_ts002),
    Rule("TS003", "monotonic-clock",
         "durations must use time.monotonic(), not the jumpable wall "
         "clock", check_ts003),
    Rule("TS004", "lock-discipline",
         "lock-protected attributes must not be mutated outside the lock",
         check_ts004),
    Rule("TS005", "broad-except",
         "except Exception must re-raise, map to a typed error, or count "
         "the failure", check_ts005),
    Rule("TS006", "donation-aliasing",
         "donated jit arguments are dead after the call and must not be "
         "referenced", check_ts006),
)
