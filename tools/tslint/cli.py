"""tslint command line.

    python -m tools.tslint                          # scan the package
    python -m tools.tslint path/to/file.py          # scan specific paths
    python -m tools.tslint --baseline tools/tslint/baseline.json
    python -m tools.tslint --write-baseline         # regenerate baseline
    python -m tools.tslint --format json
    python -m tools.tslint --select TS003,TS005
    python -m tools.tslint --list-rules

Exit codes: 0 clean (every finding baselined/suppressed), 1 new
findings, 2 usage/internal error — the same contract ruff gives
scripts/lint.sh.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from tools.tslint import engine
from tools.tslint.config import DEFAULT_BASELINE, DEFAULT_PATHS


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.tslint",
        description="Repo-native static analysis: JAX purity, host-sync, "
                    "clock, and lock discipline (ANALYSIS.md).")
    p.add_argument("paths", nargs="*", default=list(DEFAULT_PATHS),
                   help=f"files/directories to scan (default: "
                        f"{' '.join(DEFAULT_PATHS)})")
    p.add_argument("--root", default=None,
                   help="repo root paths are resolved against (default: cwd)")
    p.add_argument("--baseline", default=None,
                   help=f"baseline JSON of grandfathered findings (default: "
                        f"{DEFAULT_BASELINE} when it exists)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline, report every finding")
    p.add_argument("--write-baseline", action="store_true",
                   help="regenerate the baseline from the current findings "
                        "and exit 0")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--select", default=None,
                   help="comma-separated rule subset, e.g. TS003,TS005")
    p.add_argument("--list-rules", action="store_true")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)
    if args.list_rules:
        from tools.tslint.rules import RULES

        for r in RULES:
            print(f"{r.id}  {r.name:<22} {r.summary}")
        return 0

    root = os.path.abspath(args.root or os.getcwd())
    select = ({s.strip().upper() for s in args.select.split(",") if s.strip()}
              if args.select else None)
    try:
        result = engine.analyze(args.paths, root=root, select=select)
    except FileNotFoundError as e:
        print(f"tslint: {e}", file=sys.stderr)
        return 2

    baseline_path = args.baseline
    if baseline_path is None and not args.no_baseline:
        cand = os.path.join(root, DEFAULT_BASELINE)
        if os.path.exists(cand):
            baseline_path = cand
    elif baseline_path is not None:
        if not os.path.isabs(baseline_path):
            baseline_path = os.path.join(root, baseline_path)
        if not args.write_baseline and not os.path.exists(baseline_path):
            # an explicit baseline that is missing must be a loud usage
            # error, not a silent no-baseline run (the gate would then
            # report grandfathered findings as new — or worse, pass
            # while the operator believes the baseline was checked)
            print(f"tslint: baseline not found: {baseline_path} "
                  f"(generate it with --write-baseline)", file=sys.stderr)
            return 2

    if args.write_baseline:
        out = baseline_path or os.path.join(root, DEFAULT_BASELINE)
        engine.write_baseline(result.findings, out)
        print(f"tslint: wrote {len(result.findings)} finding(s) to "
              f"{os.path.relpath(out, root)}")
        return 0

    baselined = 0
    stale: list = []
    new = result.findings
    if baseline_path and os.path.exists(baseline_path) \
            and not args.no_baseline:
        try:
            baseline = engine.load_baseline(baseline_path)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"tslint: bad baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2
        new, baselined, stale = engine.match_baseline(result.findings,
                                                      baseline)

    if args.format == "json":
        print(json.dumps({
            "files": result.files,
            "new": [f.as_json() for f in new],
            "baselined": baselined,
            "suppressed": result.suppressed,
            "stale_baseline": stale,
        }, indent=2))
    else:
        for f in new:
            print(f.format_text())
        for e in stale:
            print(f"tslint: stale baseline entry (fixed? regenerate with "
                  f"--write-baseline): {e['rule']} {e['path']} "
                  f"[{e.get('scope', '?')}]", file=sys.stderr)
        summary = (f"tslint: {result.files} file(s), "
                   f"{len(new)} new finding(s), {baselined} baselined, "
                   f"{result.suppressed} suppressed inline")
        print(summary, file=sys.stderr if new else sys.stdout)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
