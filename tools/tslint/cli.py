"""tslint command line.

    python -m tools.tslint                          # scan the package
    python -m tools.tslint path/to/file.py          # scan specific paths
    python -m tools.tslint --baseline tools/tslint/baseline.json
    python -m tools.tslint --write-baseline         # regenerate baseline
    python -m tools.tslint --format json
    python -m tools.tslint --rules TS007,TS008    # concurrency subset
    python -m tools.tslint --changed origin/main  # only changed files
    python -m tools.tslint --lock-graph /tmp/lockgraph.json
    python -m tools.tslint --list-rules

Exit codes: 0 clean (every finding baselined/suppressed), 1 new
findings, 2 usage/internal error — the same contract ruff gives
scripts/lint.sh.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import List, Optional

from tools.tslint import engine
from tools.tslint.config import DEFAULT_BASELINE, DEFAULT_PATHS


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.tslint",
        description="Repo-native static analysis: JAX purity, host-sync, "
                    "clock, and lock discipline (ANALYSIS.md).")
    p.add_argument("paths", nargs="*", default=list(DEFAULT_PATHS),
                   help=f"files/directories to scan (default: "
                        f"{' '.join(DEFAULT_PATHS)})")
    p.add_argument("--root", default=None,
                   help="repo root paths are resolved against (default: cwd)")
    p.add_argument("--baseline", default=None,
                   help=f"baseline JSON of grandfathered findings (default: "
                        f"{DEFAULT_BASELINE} when it exists)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline, report every finding")
    p.add_argument("--write-baseline", action="store_true",
                   help="regenerate the baseline from the current findings "
                        "and exit 0")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--select", default=None,
                   help="comma-separated rule subset, e.g. TS003,TS005")
    p.add_argument("--rules", default=None, dest="rules",
                   help="alias of --select (combined when both given)")
    p.add_argument("--changed", nargs="?", const="HEAD", default=None,
                   metavar="BASE",
                   help="analyze only files changed vs BASE (git diff "
                        "--name-only BASE, plus untracked; default HEAD). "
                        "NOTE: the interprocedural rules then see only "
                        "the changed subset — the full-tree gate stays "
                        "in scripts/lint.sh")
    p.add_argument("--lock-graph", default=None, metavar="OUT",
                   help="write the statically derived lock-order graph "
                        "as JSON (for TS_LOCKSAN_GRAPH) and exit")
    p.add_argument("--list-rules", action="store_true")
    return p


def _changed_files(root: str, base: str, scan_paths: List[str]) -> List[str]:
    """Root-relative .py files changed vs `base` (committed, staged, or
    worktree) plus untracked ones, restricted to the requested paths."""
    out: set = set()
    for cmd in (["git", "diff", "--name-only", base],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        proc = subprocess.run(cmd, cwd=root, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"{' '.join(cmd)}: {proc.stderr.strip() or 'failed'}")
        out.update(l.strip() for l in proc.stdout.splitlines() if l.strip())
    prefixes = [os.path.normpath(p).replace(os.sep, "/")
                for p in scan_paths]
    selected = []
    for rel in sorted(out):
        if not rel.endswith(".py"):
            continue
        if not os.path.exists(os.path.join(root, rel)):
            continue  # deleted in the diff — nothing to analyze
        if any(p in (".", rel) or rel.startswith(p + "/")
               for p in prefixes):
            selected.append(rel)
    return selected


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)
    if args.list_rules:
        from tools.tslint import ALL_RULES

        for r in ALL_RULES:
            print(f"{r.id}  {r.name:<28} {r.summary}")
        return 0

    root = os.path.abspath(args.root or os.getcwd())
    spec = ",".join(s for s in (args.select, args.rules) if s)
    select = ({s.strip().upper() for s in spec.split(",") if s.strip()}
              if spec else None)

    if args.lock_graph:
        try:
            payload = engine.lock_graph(args.paths, root=root)
        except FileNotFoundError as e:
            print(f"tslint: {e}", file=sys.stderr)
            return 2
        out = args.lock_graph
        if not os.path.isabs(out):
            out = os.path.join(root, out)
        with open(out, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=2)
            f.write("\n")
        print(f"tslint: wrote {len(payload['edges'])} lock-order edge(s) "
              f"over {len(payload['locks'])} lock(s) to {args.lock_graph}")
        return 0

    scan_paths = list(args.paths)
    if args.changed is not None:
        try:
            scan_paths = _changed_files(root, args.changed, scan_paths)
        except (OSError, RuntimeError) as e:
            print(f"tslint: --changed: {e}", file=sys.stderr)
            return 2
        if not scan_paths:
            print("tslint: no changed python files under "
                  f"{' '.join(args.paths)} vs {args.changed}")
            return 0

    try:
        result = engine.analyze(scan_paths, root=root, select=select)
    except FileNotFoundError as e:
        print(f"tslint: {e}", file=sys.stderr)
        return 2

    baseline_path = args.baseline
    if baseline_path is None and not args.no_baseline:
        cand = os.path.join(root, DEFAULT_BASELINE)
        if os.path.exists(cand):
            baseline_path = cand
    elif baseline_path is not None:
        if not os.path.isabs(baseline_path):
            baseline_path = os.path.join(root, baseline_path)
        if not args.write_baseline and not os.path.exists(baseline_path):
            # an explicit baseline that is missing must be a loud usage
            # error, not a silent no-baseline run (the gate would then
            # report grandfathered findings as new — or worse, pass
            # while the operator believes the baseline was checked)
            print(f"tslint: baseline not found: {baseline_path} "
                  f"(generate it with --write-baseline)", file=sys.stderr)
            return 2

    if args.write_baseline:
        out = baseline_path or os.path.join(root, DEFAULT_BASELINE)
        # merge semantics: entries for files this scan did not visit are
        # carried forward (a --changed subset run must not clobber the
        # rest of the tree's debt), entries for deleted files are pruned
        extra: list = []
        pruned = 0
        if os.path.exists(out):
            try:
                old = engine.load_baseline(out)
            except (OSError, ValueError, json.JSONDecodeError) as e:
                print(f"tslint: bad baseline {out}: {e}", file=sys.stderr)
                return 2
            scanned = set(result.paths_scanned)
            for e in old.get("findings", ()):
                p = e.get("path", "")
                if p in scanned:
                    continue  # replaced by this scan's findings
                if not os.path.exists(os.path.join(root, p)):
                    pruned += 1
                    continue  # the file is gone — stale debt
                extra.append(e)
        engine.write_baseline(result.findings, out, extra_entries=extra)
        msg = (f"tslint: wrote {len(result.findings)} finding(s) to "
               f"{os.path.relpath(out, root)}")
        if extra:
            msg += f" (+{len(extra)} carried from unscanned files)"
        if pruned:
            msg += f" ({pruned} deleted-file entr{'y' if pruned == 1 else 'ies'} pruned)"
        print(msg)
        return 0

    baselined = 0
    stale: list = []
    new = result.findings
    if baseline_path and os.path.exists(baseline_path) \
            and not args.no_baseline:
        try:
            baseline = engine.load_baseline(baseline_path)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"tslint: bad baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2
        new, baselined, stale = engine.match_baseline(result.findings,
                                                      baseline, select)

    if args.format == "json":
        print(json.dumps({
            "files": result.files,
            "new": [f.as_json() for f in new],
            "baselined": baselined,
            "suppressed": result.suppressed,
            "stale_baseline": stale,
        }, indent=2))
    else:
        for f in new:
            print(f.format_text())
        for e in stale:
            print(f"tslint: stale baseline entry (fixed? regenerate with "
                  f"--write-baseline): {e['rule']} {e['path']} "
                  f"[{e.get('scope', '?')}]", file=sys.stderr)
        summary = (f"tslint: {result.files} file(s), "
                   f"{len(new)} new finding(s), {baselined} baselined, "
                   f"{result.suppressed} suppressed inline")
        print(summary, file=sys.stderr if new else sys.stdout)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
