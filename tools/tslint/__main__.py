"""``python -m tools.tslint`` entry point."""

import sys

from tools.tslint.cli import main

if __name__ == "__main__":
    sys.exit(main())
