"""Interprocedural layer for tslint: a package-wide call graph with
thread-entry inference and lock-region analysis.

The per-file rules (TS001–TS006) see one AST at a time; the concurrency
rules (TS007–TS010, tools/tslint/concurrency.py) need to know *who calls
whom from which thread while holding which lock*.  This module builds
that picture from the same annotated ASTs — stdlib-``ast`` only, best
effort by design: resolution that cannot be decided statically produces
NO edge (under-approximate calls) but DOES count unknown callback
registrations as potential thread roots (over-approximate concurrency),
which is the right polarity for a race detector.

What is modelled:

* **Functions** — module-level ``def``s and methods of top-level
  classes.  Nested closures are folded into their owner (their calls
  and blocking primitives belong to the enclosing function for
  reachability; their bodies are *excluded* from lexical lock regions,
  since a closure runs later, on whatever thread invokes it).
* **Call edges** — resolved through: ``self.method()`` (including
  single-inheritance lookup), bare names (same module, or imported via
  ``from x import f``), ``ClassName(...)`` → ``__init__``, and
  ``self.attr.method()`` / ``var.method()`` where the attr/var was
  assigned ``ClassName(...)`` (constructor type inference).
* **Thread entries** — ``threading.Thread(target=...)``, ``Thread``
  subclasses' ``run``, ``*RequestHandler`` subclasses' ``do_*`` /
  ``handle`` methods, ``atexit.register`` / ``signal.signal`` hooks,
  and escaped method references (``obj.attr = self._cb`` or an
  ``on_*=``/``callback=`` keyword) — each escape site is its own
  potential root, because a stored callback may fire on any thread.
* **Lock regions** — per-class lock attributes (``self._x =
  threading.Lock()`` / ``RLock`` / ``Condition`` or the
  ``obs.locksan`` factories), ``Condition(self._lock)`` aliasing back
  to the underlying lock, lexical ``with self._lock:`` nesting, and a
  *transitive lock-held fixpoint*: if ``f`` calls ``g`` while holding
  ``L``, then ``g`` (and everything it calls) may run with ``L`` held.

Lock identity is ``ClassName.attr`` after condition aliasing — the same
naming the runtime sanitizer (obs/locksan.py) uses, so the statically
derived order graph and the runtime acquisition order cross-check.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

#: rsplit(".")[-1] factory names that mark ``self.x = <factory>(...)``
#: as a lock attribute (threading stdlib + the obs/locksan wrappers).
LOCK_FACTORIES = ("Lock", "RLock", "Condition",
                  "make_lock", "make_rlock", "make_condition")
_CONDITION_FACTORIES = ("Condition", "make_condition")

#: base-class name fragments whose subclasses' handler methods run on
#: server-spawned threads (ThreadingHTTPServer and socketserver kin).
_HANDLER_BASE_FRAGMENTS = ("RequestHandler",)

#: keyword names whose argument, when it is a resolvable function
#: reference, is treated as an escaping callback (potential thread root).
_CALLBACK_KWARG_NAMES = ("callback", "cb", "on_done")

MAIN_ROOT = "main"


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclasses.dataclass
class FuncInfo:
    fid: str                       # "relpath::Class.method" | "relpath::func"
    relpath: str
    qualname: str                  # "Class.method" | "func"
    name: str
    class_name: Optional[str]
    node: ast.AST
    ctx: Any                       # engine.FileContext


@dataclasses.dataclass
class ClassInfo:
    name: str
    relpath: str
    node: ast.ClassDef
    bases: List[str]
    methods: Dict[str, FuncInfo]
    lock_attrs: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: condition attr -> underlying lock attr (itself when the condition
    #: owns its lock): ``self._nf = Condition(self._lock)`` -> _lock
    cond_underlying: Dict[str, str] = dataclasses.field(default_factory=dict)
    attr_types: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class CallSite:
    caller: str                    # fid
    callee: str                    # fid
    node: ast.AST
    in_closure: bool               # inside a nested def/lambda of caller


class CallGraph:
    """The package-wide model; built once per analyze() run."""

    def __init__(self) -> None:
        self.functions: Dict[str, FuncInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}       # unique name -> info
        self._ambiguous_classes: Set[str] = set()
        self.edges: Dict[str, List[CallSite]] = {}
        self.callers: Dict[str, List[CallSite]] = {}
        #: fid -> labels like "thread:Class._run", "handler:H.do_GET",
        #: "atexit:fn", "signal:fn", "callback:<registration scope>"
        self.entry_labels: Dict[str, Set[str]] = {}
        self._roots: Optional[Dict[str, Set[str]]] = None
        self._held: Optional[Dict[str, Set[str]]] = None
        #: fid -> {lock -> (caller fid, line)} provenance for held locks
        self.held_via: Dict[str, Dict[str, Tuple[str, int]]] = {}

    # -- identity helpers ---------------------------------------------------

    def func(self, fid: str) -> FuncInfo:
        return self.functions[fid]

    def lock_id(self, class_name: str, attr: str) -> Optional[str]:
        ci = self.classes.get(class_name)
        if ci is None:
            return None
        attr = ci.cond_underlying.get(attr, attr)
        if attr in ci.lock_attrs:
            return f"{class_name}.{attr}"
        return None

    # -- thread-root reachability -------------------------------------------

    def roots(self, fid: str) -> Set[str]:
        """Thread roots this function may run under.  A function with no
        entry label reaching it runs on whatever called into the package
        — the synthetic ``main`` root."""
        if self._roots is None:
            self._roots = self._compute_roots()
        return self._roots.get(fid, {MAIN_ROOT})

    def _compute_roots(self) -> Dict[str, Set[str]]:
        reach: Dict[str, Set[str]] = {}
        for fid in self.functions:
            labels = set(self.entry_labels.get(fid, ()))
            if not labels and not self.callers.get(fid):
                labels = {MAIN_ROOT}
            reach[fid] = labels
        self._propagate(reach)
        # call cycles with no outside caller never got seeded: they run
        # under whatever called into the package — main — and so do
        # their callees (second fixpoint)
        leftover = [fid for fid, labels in reach.items() if not labels]
        if leftover:
            for fid in leftover:
                reach[fid].add(MAIN_ROOT)
            self._propagate(reach)
        return reach

    def _propagate(self, reach: Dict[str, Set[str]]) -> None:
        changed = True
        while changed:
            changed = False
            for fid, sites in self.edges.items():
                src = reach.get(fid, ())
                if not src:
                    continue  # not yet reached — nothing to push
                for s in sites:
                    dst = reach.setdefault(s.callee, set())
                    before = len(dst)
                    dst |= src
                    if len(dst) != before:
                        changed = True

    # -- lock regions --------------------------------------------------------

    def _lock_of_expr(self, expr: ast.AST, finfo: FuncInfo) -> Optional[str]:
        """Canonical lock id for ``self._x`` when _x is a (condition-
        aliased) lock attr of the owning class."""
        if finfo.class_name is None:
            return None
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"):
            return self.lock_id(finfo.class_name, expr.attr)
        return None

    def in_closure(self, node: ast.AST, finfo: FuncInfo) -> bool:
        cur = getattr(node, "_ts_parent", None)
        while cur is not None and cur is not finfo.node:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return True
            cur = getattr(cur, "_ts_parent", None)
        return False

    def lexical_locks(self, finfo: FuncInfo, node: ast.AST) -> List[str]:
        """Locks held at `node` by enclosing ``with self._x:`` blocks of
        the same function (innermost last).  Empty inside closures — a
        nested def's body runs later, outside these regions."""
        if self.in_closure(node, finfo):
            return []
        out: List[str] = []
        cur = getattr(node, "_ts_parent", None)
        prev: ast.AST = node
        while cur is not None and cur is not finfo.node:
            # a node still inside a withitem (the context expr itself)
            # runs BEFORE that with-block's locks are held
            if isinstance(cur, ast.With) and not isinstance(
                    prev, ast.withitem):
                for item in cur.items:
                    lid = self._lock_of_expr(item.context_expr, finfo)
                    if lid is not None and lid not in out:
                        out.append(lid)
            prev = cur
            cur = getattr(cur, "_ts_parent", None)
        out.reverse()  # outermost first
        return out

    def acquisition_sites(self, finfo: FuncInfo) -> List[Tuple[str, ast.AST]]:
        """(lock id, node) for every ``with self._x:`` item and every
        ``self._x.acquire()`` call in the function body (closures
        excluded — they acquire on their own thread's schedule)."""
        out: List[Tuple[str, ast.AST]] = []
        for node in ast.walk(finfo.node):
            if self.in_closure(node, finfo):
                continue
            if isinstance(node, ast.With):
                for item in node.items:
                    lid = self._lock_of_expr(item.context_expr, finfo)
                    if lid is not None:
                        out.append((lid, item.context_expr))
            elif (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "acquire"):
                lid = self._lock_of_expr(node.func.value, finfo)
                if lid is not None:
                    out.append((lid, node))
        return out

    def held_on_entry(self) -> Dict[str, Set[str]]:
        """Transitive lock-held fixpoint: held_on_entry[g] is the union
        over call sites (f -> g) of (locks lexically held at the site
        plus held_on_entry[f]).  May-hold semantics."""
        if self._held is not None:
            return self._held
        held: Dict[str, Set[str]] = {fid: set() for fid in self.functions}
        via: Dict[str, Dict[str, Tuple[str, int]]] = {}
        changed = True
        while changed:
            changed = False
            for fid, sites in self.edges.items():
                finfo = self.functions[fid]
                base = held.get(fid, set())
                for s in sites:
                    at_site = set(self.lexical_locks(finfo, s.node)) | base
                    dst = held.setdefault(s.callee, set())
                    for lock in at_site:
                        if lock not in dst:
                            dst.add(lock)
                            via.setdefault(s.callee, {}).setdefault(
                                lock, (fid, getattr(s.node, "lineno", 0)))
                            changed = True
        self._held = held
        self.held_via = via
        return held

    def lock_order_edges(self) -> List[Tuple[str, str, FuncInfo, ast.AST]]:
        """(held, acquired, function, site) for every acquisition made
        while another lock is held — lexically nested ``with`` blocks
        plus locks inherited from callers via the fixpoint."""
        held_entry = self.held_on_entry()
        out: List[Tuple[str, str, FuncInfo, ast.AST]] = []
        for fid in sorted(self.functions):
            finfo = self.functions[fid]
            entry = held_entry.get(fid, set())
            for lock, node in self.acquisition_sites(finfo):
                held = set(self.lexical_locks(finfo, node)) | entry
                for h in sorted(held):
                    if h != lock:
                        out.append((h, lock, finfo, node))
        return out


# --------------------------------------------------------------------------
# Builder
# --------------------------------------------------------------------------

class _FileScope:
    """Per-file name environment: module functions, classes, imports."""

    def __init__(self, ctx: Any) -> None:
        self.ctx = ctx
        self.module_funcs: Dict[str, FuncInfo] = {}
        self.imported: Dict[str, str] = {}  # local name -> original name


def build(contexts: Sequence[Any]) -> CallGraph:
    """Build the graph from engine.FileContext objects (their trees are
    already scope/parent annotated)."""
    g = CallGraph()
    scopes: List[_FileScope] = []

    # pass 1: declare functions, classes, lock attrs, imports
    for ctx in contexts:
        scope = _FileScope(ctx)
        scopes.append(scope)
        for stmt in ctx.tree.body:
            if isinstance(stmt, (ast.Import, ast.ImportFrom)):
                for alias in stmt.names:
                    scope.imported[alias.asname or alias.name.split(".")[0]] \
                        = alias.name
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = _declare(g, ctx, stmt, None)
                scope.module_funcs[stmt.name] = fi
            elif isinstance(stmt, ast.ClassDef):
                ci = ClassInfo(
                    name=stmt.name, relpath=ctx.relpath, node=stmt,
                    bases=[b for b in map(_dotted, stmt.bases) if b],
                    methods={})
                for sub in stmt.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        ci.methods[sub.name] = _declare(g, ctx, sub,
                                                        stmt.name)
                if stmt.name in g.classes or stmt.name in g._ambiguous_classes:
                    g._ambiguous_classes.add(stmt.name)
                    g.classes.pop(stmt.name, None)
                else:
                    g.classes[stmt.name] = ci
        # lock attrs + constructor attr types need the class table, done
        # in pass 2 — but lock attrs only need THIS class, do them now
        for stmt in ctx.tree.body:
            if isinstance(stmt, ast.ClassDef) and stmt.name in g.classes:
                _collect_class_attrs(g.classes[stmt.name])

    # pass 2: attr types (needs the global class table), then edges/entries
    for scope in scopes:
        for stmt in scope.ctx.tree.body:
            if isinstance(stmt, ast.ClassDef) and stmt.name in g.classes:
                _collect_attr_types(g, g.classes[stmt.name])
    for scope in scopes:
        for fid in sorted(g.functions):
            fi = g.functions[fid]
            if fi.relpath == scope.ctx.relpath:
                _extract(g, scope, fi)
    return g


def _declare(g: CallGraph, ctx: Any, node: ast.AST,
             class_name: Optional[str]) -> FuncInfo:
    qual = f"{class_name}.{node.name}" if class_name else node.name
    fid = f"{ctx.relpath}::{qual}"
    fi = FuncInfo(fid=fid, relpath=ctx.relpath, qualname=qual,
                  name=node.name, class_name=class_name, node=node, ctx=ctx)
    g.functions[fid] = fi
    g.edges.setdefault(fid, [])
    return fi


def _collect_class_attrs(ci: ClassInfo) -> None:
    for fi in ci.methods.values():
        for node in ast.walk(fi.node):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                continue
            tgt = node.targets[0]
            if not (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                continue
            if not isinstance(node.value, ast.Call):
                continue
            vd = _dotted(node.value.func)
            if vd is None:
                continue
            factory = vd.rsplit(".", 1)[-1]
            if factory not in LOCK_FACTORIES:
                continue
            ci.lock_attrs[tgt.attr] = factory
            if factory in _CONDITION_FACTORIES:
                # Condition(self._other) shares _other's mutex; a bare
                # Condition() owns its own (aliases to itself)
                under = tgt.attr
                for arg in node.value.args:
                    if (isinstance(arg, ast.Attribute)
                            and isinstance(arg.value, ast.Name)
                            and arg.value.id == "self"):
                        under = arg.attr
                ci.cond_underlying[tgt.attr] = under


def _collect_attr_types(g: CallGraph, ci: ClassInfo) -> None:
    for fi in ci.methods.values():
        for node in ast.walk(fi.node):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                continue
            tgt = node.targets[0]
            if not (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                continue
            if isinstance(node.value, ast.Call):
                vd = _dotted(node.value.func)
                cls = vd.rsplit(".", 1)[-1] if vd else None
                if cls in g.classes:
                    ci.attr_types[tgt.attr] = cls


def _method_in_hierarchy(g: CallGraph, class_name: str,
                         meth: str, depth: int = 0) -> Optional[FuncInfo]:
    ci = g.classes.get(class_name)
    if ci is None or depth > 8:
        return None
    if meth in ci.methods:
        return ci.methods[meth]
    for base in ci.bases:
        found = _method_in_hierarchy(g, base.rsplit(".", 1)[-1], meth,
                                     depth + 1)
        if found is not None:
            return found
    return None


def _subclasses_thread(g: CallGraph, ci: ClassInfo, depth: int = 0) -> bool:
    if depth > 8:
        return False
    for base in ci.bases:
        leaf = base.rsplit(".", 1)[-1]
        if leaf == "Thread":
            return True
        bci = g.classes.get(leaf)
        if bci is not None and _subclasses_thread(g, bci, depth + 1):
            return True
    return False


def _is_handler_class(g: CallGraph, ci: ClassInfo, depth: int = 0) -> bool:
    if depth > 8:
        return False
    for base in ci.bases:
        leaf = base.rsplit(".", 1)[-1]
        if any(f in leaf for f in _HANDLER_BASE_FRAGMENTS):
            return True
        bci = g.classes.get(leaf)
        if bci is not None and _is_handler_class(g, bci, depth + 1):
            return True
    return False


class _Extractor:
    """Resolve call edges + entry registrations inside one function."""

    def __init__(self, g: CallGraph, scope: _FileScope, fi: FuncInfo) -> None:
        self.g = g
        self.scope = scope
        self.fi = fi
        self.local_types: Dict[str, str] = {}  # var -> class name
        self._collect_local_types()

    def _collect_local_types(self) -> None:
        ci = (self.g.classes.get(self.fi.class_name)
              if self.fi.class_name else None)
        for node in ast.walk(self.fi.node):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            var = node.targets[0].id
            if isinstance(node.value, ast.Call):
                vd = _dotted(node.value.func)
                cls = vd.rsplit(".", 1)[-1] if vd else None
                if cls in self.g.classes:
                    self.local_types[var] = cls
            elif (ci is not None and isinstance(node.value, ast.Attribute)
                    and isinstance(node.value.value, ast.Name)
                    and node.value.value.id == "self"
                    and node.value.attr in ci.attr_types):
                self.local_types[var] = ci.attr_types[node.value.attr]

    # reference resolution: a Name/Attribute in NON-call position that
    # denotes a function or method of the package
    def resolve_ref(self, expr: ast.AST) -> Optional[FuncInfo]:
        if isinstance(expr, ast.Name):
            fi = self.scope.module_funcs.get(expr.id)
            if fi is not None:
                return fi
            orig = self.scope.imported.get(expr.id)
            if orig is not None:
                cands = [f for f in self.g.functions.values()
                         if f.class_name is None
                         and f.name == orig.rsplit(".", 1)[-1]]
                if len(cands) == 1:
                    return cands[0]
            return None
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if isinstance(base, ast.Name):
                if base.id == "self" and self.fi.class_name:
                    return _method_in_hierarchy(self.g, self.fi.class_name,
                                                expr.attr)
                cls = self.local_types.get(base.id)
                if cls is not None:
                    return _method_in_hierarchy(self.g, cls, expr.attr)
                if base.id in self.g.classes:  # ClassName.method ref
                    return _method_in_hierarchy(self.g, base.id, expr.attr)
            elif (isinstance(base, ast.Attribute)
                    and isinstance(base.value, ast.Name)
                    and base.value.id == "self" and self.fi.class_name):
                ci = self.g.classes.get(self.fi.class_name)
                if ci is not None:
                    cls = ci.attr_types.get(base.attr)
                    if cls is not None:
                        return _method_in_hierarchy(self.g, cls, expr.attr)
        return None

    def resolve_call(self, call: ast.Call) -> Optional[FuncInfo]:
        func = call.func
        if isinstance(func, ast.Name):
            direct = self.resolve_ref(func)
            if direct is not None:
                return direct
            if func.id in self.g.classes:  # ClassName(...) -> __init__
                return _method_in_hierarchy(self.g, func.id, "__init__")
            orig = self.scope.imported.get(func.id)
            if orig is not None:
                leaf = orig.rsplit(".", 1)[-1]
                if leaf in self.g.classes:
                    return _method_in_hierarchy(self.g, leaf, "__init__")
            return None
        return self.resolve_ref(func)

    def run(self) -> None:
        g, fi = self.g, self.fi
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func) or ""
            leaf = dotted.rsplit(".", 1)[-1]
            in_clo = g.in_closure(node, fi)

            # thread spawn: threading.Thread(target=...)
            if leaf == "Thread" and leaf not in g.classes:
                tgt = next((kw.value for kw in node.keywords
                            if kw.arg == "target"), None)
                ref = self.resolve_ref(tgt) if tgt is not None else None
                if ref is not None:
                    g.entry_labels.setdefault(ref.fid, set()).add(
                        f"thread:{ref.qualname}")
                continue
            # atexit.register(f) / signal.signal(sig, f)
            if dotted in ("atexit.register", "signal.signal"):
                kind = dotted.split(".", 1)[0]
                for arg in node.args:
                    ref = self.resolve_ref(arg)
                    if ref is not None:
                        g.entry_labels.setdefault(ref.fid, set()).add(
                            f"{kind}:{ref.qualname}")
                continue

            callee = self.resolve_call(node)
            if callee is not None:
                site = CallSite(caller=fi.fid, callee=callee.fid,
                                node=node, in_closure=in_clo)
                g.edges[fi.fid].append(site)
                g.callers.setdefault(callee.fid, []).append(site)

            # escaping callbacks via on_*=/callback= keywords
            for kw in node.keywords:
                if kw.arg and (kw.arg.startswith("on_")
                               or kw.arg in _CALLBACK_KWARG_NAMES):
                    ref = self.resolve_ref(kw.value)
                    if ref is not None:
                        g.entry_labels.setdefault(ref.fid, set()).add(
                            f"callback:{fi.qualname}")

        # escaping callbacks via ``obj.attr = <method ref>`` (but NOT
        # ``self.x = self.y`` aliasing inside the same object's init)
        for node in ast.walk(fi.node):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Attribute)):
                continue
            ref = self.resolve_ref(node.value)
            if ref is None:
                continue
            tgt = node.targets[0]
            same_self = (isinstance(tgt.value, ast.Name)
                         and tgt.value.id == "self"
                         and isinstance(node.value, ast.Attribute)
                         and isinstance(node.value.value, ast.Name)
                         and node.value.value.id == "self")
            if not same_self:
                self.g.entry_labels.setdefault(ref.fid, set()).add(
                    f"callback:{fi.qualname}")


def _extract(g: CallGraph, scope: _FileScope, fi: FuncInfo) -> None:
    _Extractor(g, scope, fi).run()

    # Thread subclass run() + request-handler entry methods
    if fi.class_name is not None:
        ci = g.classes.get(fi.class_name)
        if ci is not None:
            if fi.name == "run" and _subclasses_thread(g, ci):
                g.entry_labels.setdefault(fi.fid, set()).add(
                    f"thread:{fi.qualname}")
            if ((fi.name.startswith("do_") or fi.name == "handle")
                    and _is_handler_class(g, ci)):
                g.entry_labels.setdefault(fi.fid, set()).add(
                    f"handler:{fi.qualname}")
