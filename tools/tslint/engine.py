"""tslint engine: file walking, scope annotation, inline suppression,
baseline matching, and reporters.

The engine is deliberately stdlib-only (``ast`` + ``json``): it mirrors
``scripts/lint.sh``'s no-network constraint — the container bakes its
toolchain, so the analyzer must run wherever ``python`` runs.

Pipeline per file:
  1. parse (a SyntaxError becomes a TS000 finding — the gate must not
     crash on the exact broken file it exists to catch);
  2. annotate every node with its enclosing qualname (``Class.method``)
     and a parent pointer (rules use both);
  3. run each enabled rule; ``FileContext.report`` drops findings whose
     line carries ``# tslint: disable=<RULE>[,<RULE>...]`` (or
     ``disable=all``) and records the suppression count;
  4. match surviving findings against the baseline (a committed JSON
     multiset of finding fingerprints — grandfathered debt, regenerated
     with ``--write-baseline``).

Fingerprints hash (rule, path, scope, source-line text), NOT line
numbers, so unrelated edits above a grandfathered finding don't
invalidate the baseline; moving or editing the offending line does.
"""

from __future__ import annotations

import ast
import collections
import dataclasses
import hashlib
import json
import os
import re
from typing import Any, Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

from tools.tslint.config import merge_config

#: matches ``# tslint: disable=TS001`` / ``disable=TS001,TS004`` /
#: ``disable=all``; the marker may share a comment with other markers
#: (``# pragma: no cover - tslint: disable=TS005``), and anything after
#: the rule list (a justification — which every suppression should
#: carry) is ignored.
SUPPRESS_RE = re.compile(
    r"#.*?tslint:\s*disable=([A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)")

PARSE_RULE = "TS000"  # synthetic rule id for unparseable files


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # root-relative posix path
    line: int
    col: int
    message: str
    scope: str  # enclosing qualname, "<module>" at top level
    snippet: str  # stripped source text of the offending line

    @property
    def fingerprint(self) -> str:
        key = "|".join((self.rule, self.path, self.scope, self.snippet))
        return hashlib.sha1(key.encode("utf-8")).hexdigest()[:16]

    def format_text(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"{self.message} [in {self.scope}]")

    def as_json(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["fingerprint"] = self.fingerprint
        return d


def annotate_tree(tree: ast.AST) -> None:
    """Attach ``_ts_scope`` (enclosing qualname; a def/class node's scope
    includes its own name) and ``_ts_parent`` to every node."""
    tree._ts_scope = ""  # type: ignore[attr-defined]
    tree._ts_parent = None  # type: ignore[attr-defined]

    def visit(node: ast.AST, scope: str) -> None:
        for child in ast.iter_child_nodes(node):
            child._ts_parent = node  # type: ignore[attr-defined]
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                child_scope = f"{scope}.{child.name}" if scope else child.name
            else:
                child_scope = scope
            child._ts_scope = child_scope  # type: ignore[attr-defined]
            visit(child, child_scope)

    visit(tree, "")


def walk_within(root: ast.AST, *, skip_defs: bool = True) -> Iterator[ast.AST]:
    """Yield descendants of `root` without descending into nested
    function/class/lambda bodies (the default) — rules that reason about
    one scope's control flow must not leak into closures, which own their
    own scope."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        yield node
        if skip_defs and isinstance(node, (ast.FunctionDef,
                                           ast.AsyncFunctionDef,
                                           ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


class FileContext:
    """One parsed file handed to every rule."""

    def __init__(self, relpath: str, source: str, tree: ast.AST,
                 config: Dict[str, Any]):
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.config = config
        self.findings: List[Finding] = []
        self.suppressed = 0
        self._suppressions = self._parse_suppressions()
        annotate_tree(tree)

    def rule_config(self, rule_id: str) -> Dict[str, Any]:
        return self.config.get("rules", {}).get(rule_id, {})

    def _parse_suppressions(self) -> Dict[int, Set[str]]:
        out: Dict[int, Set[str]] = {}
        for i, line in enumerate(self.lines, 1):
            m = SUPPRESS_RE.search(line)
            if m:
                out[i] = {r.strip().upper() for r in m.group(1).split(",")}
        return out

    def is_suppressed(self, rule: str, line: int) -> bool:
        rules = self._suppressions.get(line)
        return rules is not None and (rule in rules or "ALL" in rules)

    def report(self, rule: str, node: Optional[ast.AST], message: str,
               line: Optional[int] = None, col: Optional[int] = None) -> None:
        line = line if line is not None else getattr(node, "lineno", 1)
        col = col if col is not None else getattr(node, "col_offset", 0)
        if self.is_suppressed(rule, line):
            self.suppressed += 1
            return
        snippet = (self.lines[line - 1].strip()
                   if 0 < line <= len(self.lines) else "")
        scope = getattr(node, "_ts_scope", "") or "<module>"
        self.findings.append(Finding(rule, self.relpath, line, col, message,
                                     scope, snippet))


# --------------------------------------------------------------------------
# File discovery + analysis
# --------------------------------------------------------------------------

def _iter_py_files(paths: Sequence[str], root: str,
                   exclude_dirs: Set[str]) -> Iterator[str]:
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(ap):
            yield ap
        elif os.path.isdir(ap):
            for dirpath, dirnames, filenames in os.walk(ap):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in exclude_dirs)
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)
        else:
            raise FileNotFoundError(f"tslint: no such path: {p}")


@dataclasses.dataclass
class AnalysisResult:
    findings: List[Finding]
    suppressed: int
    files: int
    #: root-relative paths of every file that was actually parsed and
    #: analyzed (``--write-baseline`` merge semantics key on this)
    paths_scanned: List[str] = dataclasses.field(default_factory=list)


def _rule_active(rule_id: str, cfg: Dict[str, Any],
                 select: Optional[Set[str]]) -> bool:
    if select is not None and rule_id not in select:
        return False
    return bool(cfg.get("rules", {}).get(rule_id, {}).get("enabled", True))


def parse_files(paths: Sequence[str], root: str, cfg: Dict[str, Any],
                ) -> Tuple[List["FileContext"], List[Finding], List[str]]:
    """Parse every .py under `paths` into FileContexts; syntax errors
    become TS000 findings.  Returns (contexts, parse_findings, relpaths
    scanned — including the unparseable ones)."""
    exclude = set(cfg.get("exclude_dirs", ()))
    contexts: List[FileContext] = []
    parse_findings: List[Finding] = []
    scanned: List[str] = []
    for abspath in _iter_py_files(paths, root, exclude):
        relpath = os.path.relpath(abspath, root).replace(os.sep, "/")
        scanned.append(relpath)
        with open(abspath, "r", encoding="utf-8") as f:
            source = f.read()
        try:
            tree = ast.parse(source, filename=relpath)
        except SyntaxError as e:
            parse_findings.append(Finding(
                PARSE_RULE, relpath, e.lineno or 1, e.offset or 0,
                f"file does not parse: {e.msg}", "<module>",
                (e.text or "").strip()))
            continue
        contexts.append(FileContext(relpath, source, tree, cfg))
    return contexts, parse_findings, scanned


def analyze(paths: Sequence[str], root: Optional[str] = None,
            config: Optional[Dict[str, Any]] = None,
            select: Optional[Set[str]] = None) -> AnalysisResult:
    """Run every enabled rule over `paths` (files or directories,
    resolved against `root`, default cwd).  `select` restricts to a rule
    subset; `config` is deep-merged over tools.tslint.config.DEFAULT.

    Two passes: the per-file rules (TS001–TS006) see one FileContext at
    a time; the project rules (TS007–TS010) then run once over ALL
    contexts riding the package-wide call graph (callgraph.py)."""
    from tools.tslint import rules as rules_mod

    root = os.path.abspath(root or os.getcwd())
    cfg = merge_config(config)
    contexts, findings, scanned = parse_files(paths, root, cfg)
    for ctx in contexts:
        for rule in rules_mod.RULES:
            if _rule_active(rule.id, cfg, select):
                rule.check(ctx)

    from tools.tslint import concurrency
    project_rules = [r for r in concurrency.PROJECT_RULES
                     if _rule_active(r.id, cfg, select)]
    if project_rules and contexts:
        from tools.tslint import callgraph
        graph = callgraph.build(contexts)
        pctx = concurrency.ProjectContext(contexts, graph, cfg)
        for rule in project_rules:
            rule.check(pctx)

    suppressed = 0
    for ctx in contexts:
        findings.extend(ctx.findings)
        suppressed += ctx.suppressed
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return AnalysisResult(findings=findings, suppressed=suppressed,
                          files=len(scanned), paths_scanned=scanned)


def lock_graph(paths: Sequence[str], root: Optional[str] = None,
               config: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Statically derived lock-order graph for the runtime sanitizer
    (obs/locksan.py cross-checks real acquisition order against these
    edges when ``TS_LOCKSAN_GRAPH`` points at the exported JSON)."""
    root = os.path.abspath(root or os.getcwd())
    cfg = merge_config(config)
    contexts, _, _ = parse_files(paths, root, cfg)
    from tools.tslint import callgraph
    graph = callgraph.build(contexts)
    edges = sorted({(a, b) for a, b, _, _ in graph.lock_order_edges()})
    locks = sorted({f"{c}.{ci.cond_underlying.get(attr, attr)}"
                    for c, ci in graph.classes.items()
                    for attr in ci.lock_attrs})
    return {"version": 1, "tool": "tslint",
            "locks": locks, "edges": [list(e) for e in edges]}


# --------------------------------------------------------------------------
# Baseline
# --------------------------------------------------------------------------

def load_baseline(path: str) -> Dict[str, Any]:
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict) or "findings" not in data:
        raise ValueError(f"malformed baseline file: {path}")
    return data


def write_baseline(findings: Sequence[Finding], path: str,
                   extra_entries: Sequence[Dict[str, Any]] = ()) -> None:
    """`extra_entries` carries forward raw baseline entries for files a
    subset scan (``--changed``) did not visit — already pruned of
    deleted files by the caller."""
    entries = [{
        "fingerprint": f.fingerprint,
        "rule": f.rule,
        "path": f.path,
        "scope": f.scope,
        "snippet": f.snippet,
        "message": f.message,
        "line": f.line,  # informational only — matching is by fingerprint
    } for f in findings]
    entries.extend(extra_entries)
    entries.sort(key=lambda e: (e.get("path", ""), e.get("line", 0),
                                e.get("rule", "")))
    payload = {"version": 1, "tool": "tslint", "findings": entries}
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=False)
        f.write("\n")


def match_baseline(findings: Sequence[Finding], baseline: Dict[str, Any],
                   select: Optional[Set[str]] = None,
                   ) -> Tuple[List[Finding], int, List[Dict[str, Any]]]:
    """Split findings into (new, baselined_count, stale_entries).
    Matching is a multiset over fingerprints: N identical grandfathered
    findings absorb at most N live ones; entries no live finding matched
    are reported stale so the baseline shrinks as debt is paid.  With
    `select`, entries for rules OUTSIDE the selected subset are ignored
    entirely — a filtered run (--rules TS007,TS008) can neither match
    nor stale-flag the other rules' grandfathered debt."""
    entries = [e for e in baseline.get("findings", ())
               if select is None or e.get("rule") in select]
    baseline = {"findings": entries}
    counts: collections.Counter = collections.Counter(
        e["fingerprint"] for e in entries)
    used: collections.Counter = collections.Counter()
    new: List[Finding] = []
    for f in findings:
        fp = f.fingerprint
        if used[fp] < counts.get(fp, 0):
            used[fp] += 1
        else:
            new.append(f)
    stale: List[Dict[str, Any]] = []
    remaining = collections.Counter(
        {fp: c - used[fp] for fp, c in counts.items() if c > used[fp]})
    for e in baseline.get("findings", ()):
        fp = e["fingerprint"]
        if remaining.get(fp, 0) > 0:
            remaining[fp] -= 1
            stale.append(e)
    baselined = sum(used.values())
    return new, baselined, stale
