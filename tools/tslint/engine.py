"""tslint engine: file walking, scope annotation, inline suppression,
baseline matching, and reporters.

The engine is deliberately stdlib-only (``ast`` + ``json``): it mirrors
``scripts/lint.sh``'s no-network constraint — the container bakes its
toolchain, so the analyzer must run wherever ``python`` runs.

Pipeline per file:
  1. parse (a SyntaxError becomes a TS000 finding — the gate must not
     crash on the exact broken file it exists to catch);
  2. annotate every node with its enclosing qualname (``Class.method``)
     and a parent pointer (rules use both);
  3. run each enabled rule; ``FileContext.report`` drops findings whose
     line carries ``# tslint: disable=<RULE>[,<RULE>...]`` (or
     ``disable=all``) and records the suppression count;
  4. match surviving findings against the baseline (a committed JSON
     multiset of finding fingerprints — grandfathered debt, regenerated
     with ``--write-baseline``).

Fingerprints hash (rule, path, scope, source-line text), NOT line
numbers, so unrelated edits above a grandfathered finding don't
invalidate the baseline; moving or editing the offending line does.
"""

from __future__ import annotations

import ast
import collections
import dataclasses
import hashlib
import json
import os
import re
from typing import Any, Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

from tools.tslint.config import merge_config

#: matches ``# tslint: disable=TS001`` / ``disable=TS001,TS004`` /
#: ``disable=all``; the marker may share a comment with other markers
#: (``# pragma: no cover - tslint: disable=TS005``), and anything after
#: the rule list (a justification — which every suppression should
#: carry) is ignored.
SUPPRESS_RE = re.compile(
    r"#.*?tslint:\s*disable=([A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)")

PARSE_RULE = "TS000"  # synthetic rule id for unparseable files


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # root-relative posix path
    line: int
    col: int
    message: str
    scope: str  # enclosing qualname, "<module>" at top level
    snippet: str  # stripped source text of the offending line

    @property
    def fingerprint(self) -> str:
        key = "|".join((self.rule, self.path, self.scope, self.snippet))
        return hashlib.sha1(key.encode("utf-8")).hexdigest()[:16]

    def format_text(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"{self.message} [in {self.scope}]")

    def as_json(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["fingerprint"] = self.fingerprint
        return d


def annotate_tree(tree: ast.AST) -> None:
    """Attach ``_ts_scope`` (enclosing qualname; a def/class node's scope
    includes its own name) and ``_ts_parent`` to every node."""
    tree._ts_scope = ""  # type: ignore[attr-defined]
    tree._ts_parent = None  # type: ignore[attr-defined]

    def visit(node: ast.AST, scope: str) -> None:
        for child in ast.iter_child_nodes(node):
            child._ts_parent = node  # type: ignore[attr-defined]
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                child_scope = f"{scope}.{child.name}" if scope else child.name
            else:
                child_scope = scope
            child._ts_scope = child_scope  # type: ignore[attr-defined]
            visit(child, child_scope)

    visit(tree, "")


def walk_within(root: ast.AST, *, skip_defs: bool = True) -> Iterator[ast.AST]:
    """Yield descendants of `root` without descending into nested
    function/class/lambda bodies (the default) — rules that reason about
    one scope's control flow must not leak into closures, which own their
    own scope."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        yield node
        if skip_defs and isinstance(node, (ast.FunctionDef,
                                           ast.AsyncFunctionDef,
                                           ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


class FileContext:
    """One parsed file handed to every rule."""

    def __init__(self, relpath: str, source: str, tree: ast.AST,
                 config: Dict[str, Any]):
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.config = config
        self.findings: List[Finding] = []
        self.suppressed = 0
        self._suppressions = self._parse_suppressions()
        annotate_tree(tree)

    def rule_config(self, rule_id: str) -> Dict[str, Any]:
        return self.config.get("rules", {}).get(rule_id, {})

    def _parse_suppressions(self) -> Dict[int, Set[str]]:
        out: Dict[int, Set[str]] = {}
        for i, line in enumerate(self.lines, 1):
            m = SUPPRESS_RE.search(line)
            if m:
                out[i] = {r.strip().upper() for r in m.group(1).split(",")}
        return out

    def is_suppressed(self, rule: str, line: int) -> bool:
        rules = self._suppressions.get(line)
        return rules is not None and (rule in rules or "ALL" in rules)

    def report(self, rule: str, node: Optional[ast.AST], message: str,
               line: Optional[int] = None, col: Optional[int] = None) -> None:
        line = line if line is not None else getattr(node, "lineno", 1)
        col = col if col is not None else getattr(node, "col_offset", 0)
        if self.is_suppressed(rule, line):
            self.suppressed += 1
            return
        snippet = (self.lines[line - 1].strip()
                   if 0 < line <= len(self.lines) else "")
        scope = getattr(node, "_ts_scope", "") or "<module>"
        self.findings.append(Finding(rule, self.relpath, line, col, message,
                                     scope, snippet))


# --------------------------------------------------------------------------
# File discovery + analysis
# --------------------------------------------------------------------------

def _iter_py_files(paths: Sequence[str], root: str,
                   exclude_dirs: Set[str]) -> Iterator[str]:
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(ap):
            yield ap
        elif os.path.isdir(ap):
            for dirpath, dirnames, filenames in os.walk(ap):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in exclude_dirs)
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)
        else:
            raise FileNotFoundError(f"tslint: no such path: {p}")


@dataclasses.dataclass
class AnalysisResult:
    findings: List[Finding]
    suppressed: int
    files: int


def analyze(paths: Sequence[str], root: Optional[str] = None,
            config: Optional[Dict[str, Any]] = None,
            select: Optional[Set[str]] = None) -> AnalysisResult:
    """Run every enabled rule over `paths` (files or directories,
    resolved against `root`, default cwd).  `select` restricts to a rule
    subset; `config` is deep-merged over tools.tslint.config.DEFAULT."""
    from tools.tslint import rules as rules_mod

    root = os.path.abspath(root or os.getcwd())
    cfg = merge_config(config)
    exclude = set(cfg.get("exclude_dirs", ()))
    findings: List[Finding] = []
    suppressed = 0
    nfiles = 0
    for abspath in _iter_py_files(paths, root, exclude):
        relpath = os.path.relpath(abspath, root).replace(os.sep, "/")
        nfiles += 1
        with open(abspath, "r", encoding="utf-8") as f:
            source = f.read()
        try:
            tree = ast.parse(source, filename=relpath)
        except SyntaxError as e:
            findings.append(Finding(
                PARSE_RULE, relpath, e.lineno or 1, e.offset or 0,
                f"file does not parse: {e.msg}", "<module>",
                (e.text or "").strip()))
            continue
        ctx = FileContext(relpath, source, tree, cfg)
        for rule in rules_mod.RULES:
            if select is not None and rule.id not in select:
                continue
            if not cfg.get("rules", {}).get(rule.id, {}).get("enabled", True):
                continue
            rule.check(ctx)
        findings.extend(ctx.findings)
        suppressed += ctx.suppressed
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return AnalysisResult(findings=findings, suppressed=suppressed,
                          files=nfiles)


# --------------------------------------------------------------------------
# Baseline
# --------------------------------------------------------------------------

def load_baseline(path: str) -> Dict[str, Any]:
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict) or "findings" not in data:
        raise ValueError(f"malformed baseline file: {path}")
    return data


def write_baseline(findings: Sequence[Finding], path: str) -> None:
    entries = [{
        "fingerprint": f.fingerprint,
        "rule": f.rule,
        "path": f.path,
        "scope": f.scope,
        "snippet": f.snippet,
        "message": f.message,
        "line": f.line,  # informational only — matching is by fingerprint
    } for f in findings]
    payload = {"version": 1, "tool": "tslint", "findings": entries}
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=False)
        f.write("\n")


def match_baseline(findings: Sequence[Finding], baseline: Dict[str, Any],
                   ) -> Tuple[List[Finding], int, List[Dict[str, Any]]]:
    """Split findings into (new, baselined_count, stale_entries).
    Matching is a multiset over fingerprints: N identical grandfathered
    findings absorb at most N live ones; entries no live finding matched
    are reported stale so the baseline shrinks as debt is paid."""
    counts: collections.Counter = collections.Counter(
        e["fingerprint"] for e in baseline.get("findings", ()))
    used: collections.Counter = collections.Counter()
    new: List[Finding] = []
    for f in findings:
        fp = f.fingerprint
        if used[fp] < counts.get(fp, 0):
            used[fp] += 1
        else:
            new.append(f)
    stale: List[Dict[str, Any]] = []
    remaining = collections.Counter(
        {fp: c - used[fp] for fp, c in counts.items() if c > used[fp]})
    for e in baseline.get("findings", ()):
        fp = e["fingerprint"]
        if remaining.get(fp, 0) > 0:
            remaining[fp] -= 1
            stale.append(e)
    baselined = sum(used.values())
    return new, baselined, stale
