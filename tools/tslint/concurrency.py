"""TS007–TS010: interprocedural concurrency rules over the call graph.

These are *project* rules — they run once over every parsed file after
the per-file rules, riding tools/tslint/callgraph.py.  Findings are
reported through the owning FileContext, so inline ``# tslint:
disable=...`` suppressions and the fingerprint baseline work unchanged.

TS007 lock-order-cycle
    Build the lock acquisition-order graph: an edge A -> B for every
    site that acquires B while A is held (lexically nested ``with``
    blocks, plus locks inherited from callers through the held-on-entry
    fixpoint).  Any cycle is a deadlock risk: two threads entering the
    cycle from different points block each other forever.

TS008 blocking-under-lock
    A blocking primitive (socket connect/recv, subprocess wait/
    communicate, urlopen, time.sleep, event waits) — or a call that
    transitively reaches one — inside a ``with self._lock:`` region
    stalls every thread contending on that lock for the primitive's
    full latency (the procfleet scrape path is the motivating shape:
    a wedged child must cost the scraper a timeout, never the router).
    ``cond.wait()`` on a condition whose underlying mutex is the held
    lock is exempt — that wait *releases* the lock by contract.

TS009 cross-thread-unlocked-write
    An instance attribute written (outside ``__init__``) from methods
    whose inferred thread roots differ — supervisor thread vs router
    tick vs stored callback — where at least one write is outside any
    lock region, is a data race.

TS010 future-single-resolution
    Settle-state discipline for future-like classes: a class with a
    ``_finish``-style funnel must write its settle attrs (and fire its
    done-event) ONLY inside the funnel; a class with a ``_settled``
    guard flag must write that flag in every method that resolves or
    rejects a member future.  Exactly-once resolution is what the
    router's first-wins hedging and kill-requeue paths stand on.
"""

from __future__ import annotations

import ast
from typing import Any, Dict, List, Optional, Set, Tuple

from tools.tslint import callgraph
from tools.tslint.rules import Rule


class ProjectContext:
    """All FileContexts plus the built call graph."""

    def __init__(self, contexts: List[Any], graph: callgraph.CallGraph,
                 config: Dict[str, Any]) -> None:
        self.contexts = {c.relpath: c for c in contexts}
        self.graph = graph
        self.config = config

    def rule_config(self, rule_id: str) -> Dict[str, Any]:
        return self.config.get("rules", {}).get(rule_id, {})

    def report(self, rule: str, relpath: str, node: Optional[ast.AST],
               message: str) -> None:
        ctx = self.contexts.get(relpath)
        if ctx is not None:
            ctx.report(rule, node, message)


# --------------------------------------------------------------------------
# TS007: lock-order cycles
# --------------------------------------------------------------------------

def _sccs(nodes: Set[str], adj: Dict[str, Set[str]]) -> List[Set[str]]:
    """Tarjan strongly-connected components, iterative."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: List[Set[str]] = []
    counter = [0]

    for start in sorted(nodes):
        if start in index:
            continue
        work: List[Tuple[str, int]] = [(start, 0)]
        while work:
            v, pi = work.pop()
            if pi == 0:
                index[v] = low[v] = counter[0]
                counter[0] += 1
                stack.append(v)
                on_stack.add(v)
            recurse = False
            succs = sorted(adj.get(v, ()))
            for i in range(pi, len(succs)):
                w = succs[i]
                if w not in index:
                    work.append((v, i + 1))
                    work.append((w, 0))
                    recurse = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if recurse:
                continue
            if low[v] == index[v]:
                scc: Set[str] = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.add(w)
                    if w == v:
                        break
                out.append(scc)
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[v])
    return out


def check_ts007(pctx: ProjectContext) -> None:
    g = pctx.graph
    edges = g.lock_order_edges()
    adj: Dict[str, Set[str]] = {}
    nodes: Set[str] = set()
    for held, acq, _, _ in edges:
        adj.setdefault(held, set()).add(acq)
        nodes.add(held)
        nodes.add(acq)
    cyclic: Set[frozenset] = set()
    for scc in _sccs(nodes, adj):
        if len(scc) > 1:
            cyclic.add(frozenset(scc))
    if not cyclic:
        return
    seen: Set[Tuple[str, str]] = set()
    for held, acq, finfo, node in edges:
        scc = next((s for s in cyclic if held in s and acq in s), None)
        if scc is None or (held, acq) in seen:
            continue
        seen.add((held, acq))
        members = " <-> ".join(sorted(scc))
        pctx.report(
            "TS007", finfo.relpath, node,
            f"lock-order cycle: acquires {acq} while holding {held}, but "
            f"the reverse order also occurs ({members}) — two threads "
            f"entering from opposite ends deadlock")


# --------------------------------------------------------------------------
# TS008: blocking call while a lock is held
# --------------------------------------------------------------------------

def _blocking_primitives(pctx: ProjectContext, finfo: callgraph.FuncInfo,
                         ) -> List[Tuple[ast.AST, str]]:
    cfg = pctx.rule_config("TS008")
    roots = tuple(cfg.get("blocking_roots", ()))
    methods = set(cfg.get("blocking_methods", ()))
    out: List[Tuple[ast.AST, str]] = []
    for node in ast.walk(finfo.node):
        if not isinstance(node, ast.Call):
            continue
        dotted = callgraph._dotted(node.func)
        if dotted is not None and dotted in roots:
            out.append((node, dotted))
            continue
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in methods):
            out.append((node, f".{node.func.attr}()"))
    return out


def _wait_exempt(g: callgraph.CallGraph, finfo: callgraph.FuncInfo,
                 node: ast.AST, held: List[str]) -> bool:
    """``self._cv.wait()`` releases _cv's underlying mutex — waiting on
    a condition whose mutex is the held lock is the sanctioned pattern,
    not a stall."""
    if not (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("wait", "wait_for")):
        return False
    lid = g._lock_of_expr(node.func.value, finfo)
    return lid is not None and lid in held


def check_ts008(pctx: ProjectContext) -> None:
    g = pctx.graph
    # transitive "does this function block, and through what" map
    blocking: Dict[str, str] = {}
    for fid in sorted(g.functions):
        prims = _blocking_primitives(pctx, g.functions[fid])
        if prims:
            blocking[fid] = prims[0][1]
    changed = True
    while changed:
        changed = False
        for fid in sorted(g.functions):
            if fid in blocking:
                continue
            for site in g.edges.get(fid, ()):
                label = blocking.get(site.callee)
                if label is not None:
                    callee = g.functions[site.callee].qualname
                    blocking[fid] = f"{callee} -> {label}"
                    changed = True
                    break

    for fid in sorted(g.functions):
        finfo = g.functions[fid]
        reported: Set[int] = set()
        for node, label in _blocking_primitives(pctx, finfo):
            held = g.lexical_locks(finfo, node)
            if not held or _wait_exempt(g, finfo, node, held):
                continue
            line = getattr(node, "lineno", 0)
            if line in reported:
                continue
            reported.add(line)
            pctx.report(
                "TS008", finfo.relpath, node,
                f"blocking call {label} while holding "
                f"{', '.join(held)} — every thread contending on the "
                f"lock stalls for the call's full latency")
        for site in g.edges.get(fid, ()):
            label = blocking.get(site.callee)
            if label is None:
                continue
            held = g.lexical_locks(finfo, site.node)
            if not held:
                continue
            line = getattr(site.node, "lineno", 0)
            if line in reported:
                continue
            reported.add(line)
            callee = g.functions[site.callee].qualname
            pctx.report(
                "TS008", finfo.relpath, site.node,
                f"call to {callee} (blocks via {label}) while holding "
                f"{', '.join(held)}")


# --------------------------------------------------------------------------
# TS009: cross-thread writes outside any lock
# --------------------------------------------------------------------------

def _self_attr_writes(finfo: callgraph.FuncInfo) -> List[Tuple[str, ast.AST]]:
    out: List[Tuple[str, ast.AST]] = []
    for node in ast.walk(finfo.node):
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for tgt in targets:
            if isinstance(tgt, ast.Tuple):
                targets.extend(tgt.elts)
                continue
            if (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                out.append((tgt.attr, node))
    return out


def check_ts009(pctx: ProjectContext) -> None:
    import re as _re
    g = pctx.graph
    held_entry = g.held_on_entry()
    init_re = _re.compile(pctx.rule_config("TS009").get(
        "init_method_re", r"^(__init__|__new__|__post_init__|_init[a-z_]*)$"))
    for cname in sorted(g.classes):
        ci = g.classes[cname]
        # attr -> [(method, node, protected)]
        writes: Dict[str, List[Tuple[callgraph.FuncInfo, ast.AST, bool]]] = {}
        for mname in sorted(ci.methods):
            if init_re.search(mname):
                # construction-time writes happen before the object is
                # shared across threads (happens-before via Thread.start)
                continue
            finfo = ci.methods[mname]
            entry_held = held_entry.get(finfo.fid, set())
            for attr, node in _self_attr_writes(finfo):
                if attr in ci.lock_attrs:
                    continue
                if g.in_closure(node, finfo):
                    continue  # a closure writes on its own schedule
                protected = bool(g.lexical_locks(finfo, node) or entry_held)
                writes.setdefault(attr, []).append((finfo, node, protected))
        for attr in sorted(writes):
            sites = writes[attr]
            roots: Set[str] = set()
            for finfo, _, _ in sites:
                roots |= g.roots(finfo.fid)
            if len(roots) < 2:
                continue
            unlocked = [(f, n) for f, n, prot in sites if not prot]
            if not unlocked:
                continue
            finfo, node = unlocked[0]
            writers = sorted({f.qualname for f, _, _ in sites})
            pctx.report(
                "TS009", finfo.relpath, node,
                f"self.{attr} is written from {len(roots)} thread roots "
                f"({', '.join(sorted(roots))}; writers: "
                f"{', '.join(writers)}) with this write outside any lock "
                f"— cross-thread data race")


# --------------------------------------------------------------------------
# TS010: future settle paths must funnel through one method
# --------------------------------------------------------------------------

def check_ts010(pctx: ProjectContext) -> None:
    g = pctx.graph
    cfg = pctx.rule_config("TS010")
    funnels = tuple(cfg.get("funnel_methods", ("_finish",)))
    flags = tuple(cfg.get("settle_flags", ("_settled",)))
    resolvers = tuple(cfg.get("resolver_methods",
                              ("_finish", "_resolve", "_reject")))
    for cname in sorted(g.classes):
        ci = g.classes[cname]
        funnel_name = next((f for f in funnels if f in ci.methods), None)

        # clause A: settle attrs of the funnel are written nowhere else
        if funnel_name is not None:
            funnel = ci.methods[funnel_name]
            state: Set[str] = {a for a, _ in _self_attr_writes(funnel)}
            for node in ast.walk(funnel.node):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "set"):
                    inner = node.func.value
                    if (isinstance(inner, ast.Attribute)
                            and isinstance(inner.value, ast.Name)
                            and inner.value.id == "self"):
                        state.add(inner.attr)
            state -= set(ci.lock_attrs)
            for mname in sorted(ci.methods):
                if mname in (funnel_name, "__init__", "__new__"):
                    continue
                finfo = ci.methods[mname]
                for attr, node in _self_attr_writes(finfo):
                    if attr in state:
                        pctx.report(
                            "TS010", finfo.relpath, node,
                            f"settle state self.{attr} written outside the "
                            f"{cname}.{funnel_name} funnel — double "
                            f"resolution becomes possible")
                for node in ast.walk(finfo.node):
                    if (isinstance(node, ast.Call)
                            and isinstance(node.func, ast.Attribute)
                            and node.func.attr == "set"):
                        inner = node.func.value
                        if (isinstance(inner, ast.Attribute)
                                and isinstance(inner.value, ast.Name)
                                and inner.value.id == "self"
                                and inner.attr in state):
                            pctx.report(
                                "TS010", finfo.relpath, node,
                                f"settle event self.{inner.attr}.set() "
                                f"fired outside the {cname}.{funnel_name} "
                                f"funnel — waiters can observe an "
                                f"unsettled future as done")

        # clause B: any method resolving a member future must write the
        # class's settle guard flag (first-wins discipline)
        flag = None
        for mname, finfo in ci.methods.items():
            for attr, _ in _self_attr_writes(finfo):
                if attr in flags:
                    flag = attr
                    break
            if flag:
                break
        if flag is None:
            continue
        for mname in sorted(ci.methods):
            if mname in ("__init__", "__new__"):
                continue
            finfo = ci.methods[mname]
            writes_flag = any(a == flag for a, _ in _self_attr_writes(finfo))
            for node in ast.walk(finfo.node):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in resolvers):
                    continue
                # only member-future resolution (self.<attr>._resolve())
                recv = node.func.value
                if not (isinstance(recv, ast.Attribute)
                        and isinstance(recv.value, ast.Name)
                        and recv.value.id == "self"):
                    continue
                if not writes_flag:
                    pctx.report(
                        "TS010", finfo.relpath, node,
                        f"{cname}.{mname} settles self.{recv.attr}."
                        f"{node.func.attr}() without writing the "
                        f"self.{flag} guard — a racing settle path can "
                        f"resolve the future twice")


PROJECT_RULES = (
    Rule("TS007", "lock-order-cycle",
         "cyclic lock acquisition order across the call graph "
         "(deadlock risk)", check_ts007),
    Rule("TS008", "blocking-under-lock",
         "socket/subprocess/sleep/wait reachable inside a lock region",
         check_ts008),
    Rule("TS009", "cross-thread-unlocked-write",
         "attr written from >=2 inferred thread roots with an unlocked "
         "write", check_ts009),
    Rule("TS010", "future-single-resolution",
         "future settle state must funnel through the one _finish-style "
         "method", check_ts010),
)
