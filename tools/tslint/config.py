"""tslint configuration: per-rule options + deep merge.

The defaults are tuned to THIS repo (the hot-function list names the
train/decode/input loops whose per-step host syncs erase kernel wins —
see ANALYSIS.md for why each entry is hot).  Tests and other checkouts
override by passing a partial config dict to ``engine.analyze`` — it is
deep-merged over these defaults, so overriding one rule key keeps the
rest.
"""

from __future__ import annotations

import copy
from typing import Any, Dict, Optional

#: Default scan target for the CLI when no paths are given.
DEFAULT_PATHS = ("textsummarization_on_flink_tpu",)

#: Default baseline location (relative to the scan root) the CLI picks
#: up when --baseline is not given and the file exists.
DEFAULT_BASELINE = "tools/tslint/baseline.json"

DEFAULT: Dict[str, Any] = {
    "exclude_dirs": {"__pycache__", ".git", ".jax_cache", "exp"},
    "rules": {
        "TS001": {
            "enabled": True,
            # dotted-call roots that are side effects at trace time: the
            # call runs ONCE while jit traces and never again on device
            "impure_roots": ["time", "os", "random", "logging", "log",
                             "obs", "np.random"],
            # sanctioned escape hatches (run on device / at runtime)
            "allowed_prefixes": ["jax.debug"],
        },
        "TS002": {
            "enabled": True,
            # qualname regexes of per-step/per-token loops where one
            # stray sync serializes dispatch (matched with re.search)
            "hot_functions": [
                r"^Trainer\._train_steps$",
                r"^Evaluator\.run$",
                r"^DevicePrefetcher\.next_batch$",
                r"^Batcher\.next_batch$",
                r"^BeamSearchDecoder\.decode$",
                # the continuous-serving dispatch path (ISSUE 6): one
                # stray per-slot sync here serializes every resident
                # request's chunk cadence
                r"^ContinuousBatcher\.(tick|_refill|_harvest|_evict_expired)$",
                r"^ServingServer\._run_continuous$",
                r"^SlotDecodeEngine\.(pack|step|unpack|prefill)$",
                # prefill/decode disaggregation (ISSUE 11): the prefill
                # stage runs once per admission on the dispatch thread,
                # and the blocked/masked attention closures trace into
                # every decode chunk — a host sync (or trace-time side
                # effect) in any of them stalls resident decodes
                r"^ContinuousBatcher\._prefill_stage$",
                r"^_attend_shared_blocked",
                r"^cross_attend_layer",
                # the telemetry plane's own per-tick/per-step code
                # (ISSUE 9): frame recording and heartbeats run inside
                # every hot loop above — a host sync smuggled into THEM
                # would serialize the loops they observe
                r"^ContinuousBatcher\._record_frame$",
                r"^FlightRecorder\.record$",
                r"^HeartbeatBoard\.beat$",
                r"^ServeFuture\._finish$",
                # the decode byte diet's restructured search (ISSUE 7):
                # the backpointer body and the finalize backtrack are the
                # per-step/per-retire hot code — one stray host sync (or
                # trace-time side effect) here serializes every dispatch
                r"^_make_beam_body",  # covers the <locals>.body closure
                r"^_finalize_beam",  # covers the <locals>.back backtrack
                # the unified sharded step builder (ISSUE 8): its traced
                # closures (train_step body, the wire-dtype grad fn) run
                # every optimizer step on every chip — a stray host sync
                # or trace-time side effect here poisons the whole mesh
                r"^make_sharded_train_step",
                r"^_make_wire_grad_fn",
                # the speculative fast path (ISSUE 10): the draft-verify
                # cycle body and the parallel verify run once per
                # emitted-token group, and the AAN decode step once per
                # draft token — a host sync in any of them serializes
                # the spec tier back to per-token dispatch
                r"^_spec_body",  # covers the <locals>.body cycle closure
                r"^spec_verify",
                r"^decode_onestep",  # pg + avg_attention decode steps
                # the distilled-narrow-draft spec tier (ISSUE 12): the
                # distillation step loop dispatches once per draft
                # optimizer step, the adaptive host loop dispatches
                # once per draft-verify CYCLE (its single histogram
                # fetch is the sanctioned, suppressed controller
                # input), and the controller's observe/update run
                # between every pair of cycles — a stray sync in any
                # of them serializes the tier back to per-token cost
                r"^DistillTrainer\._distill_steps$",
                r"^run_spec_decode_adaptive$",
                r"^SpecKController\.(observe|update)$",
                # the elastic fleet's router loops (ISSUE 13): tick runs
                # on every router round, the hedge scan walks every
                # in-flight request, and the swap step gates each
                # replica's drain — a host sync in any of them stalls
                # routing (and hedging timing) for the whole fleet
                r"^FleetRouter\.(tick|_hedge_scan|_swap_step"
                r"|_maybe_chaos_kill)$",
                r"^ServingServer\.(_continuous_round|tick_once)$",
                # the serving front door (ISSUE 14): open/admit run on
                # EVERY submit, the leader-done callback on the
                # dispatch thread at resolve time, and the queue's
                # fair-pickup loop once per dequeue — a host sync in
                # any of them serializes admission (or the dispatch
                # loop) for every caller at once
                r"^FrontDoor\.(open|admit_tenant|_leader_done|_close)$",
                r"^SummaryCache\.(get|put)$",
                r"^RequestQueue\.(_put|_pop|_pick_tenant|get"
                r"|get_nowait)$",
                # the fleet telemetry plane (ISSUE 15): the SLO window
                # evaluator runs once per dispatch/router round and its
                # record side inside every future's resolve fan-out;
                # the fleet merge loop runs on every /fleet/* scrape —
                # a stray device sync in either stalls every replica's
                # dispatch (or every scrape) at once
                r"^SloEngine\.(record|evaluate)$",
                r"^merge_fleet_series$",
                r"^Registry\.series$",
                # the performance attribution plane (ISSUE 16): phase
                # timers close on every tick/dispatch, the compile
                # ledger wraps every jitted decode call, and the
                # divergence sentinel judges every priced dispatch — a
                # stray sync in any record path becomes a per-chunk
                # stall on the very path it is supposed to measure
                r"^Profiler\.(start|end|end_wall)$",
                r"^Profiler\.(record_compile|record_hit"
                r"|observe_dispatch)$",
                r"^compiled_call$",
                # ISSUE 17: the process-fleet supervision tick and the
                # remote-handle scrape/rotation reads run at router-tick
                # cadence against every replica — a device sync inside
                # any of them multiplies by fleet size per tick
                r"^ReplicaProcess\.tick$",
                r"^RemoteReplicaHandle\.(healthy|load)$",
                r"^RemoteReplica\.(scrape_healthz|_on_reply|load)$",
                r"^_ReplySource\.rows$",
                r"^ProcFleet\.(supervise_once|_supervise_loop)$",
                # the hierarchical summarizer's fan-out driver (ISSUE
                # 19): _fan_out runs once per document on the submit
                # path, and the chunk-done/record/map-complete/reduce-
                # done chain runs inside the SERVER's resolve callbacks
                # — a host sync in any of them stalls the dispatch
                # thread for every resident request, and the frame
                # assembler feeds on every pipeline row
                r"^HierarchicalSummarizer\.(_fan_out|_chunk_done"
                r"|_record_chunk|_map_complete|_reduce_done)$",
                r"^DocumentAssembler\.feed$",
                # the paged resident state (ISSUE 20): page alloc/free
                # run inside every admission/harvest on the dispatch
                # thread, the engine's page accounting gates every
                # refill, and the arena-occupancy observer fires every
                # tick — pure-numpy by design; a device sync (or a
                # blocking call) in any of them stalls every resident
                # request's chunk cadence
                r"^PageArena\.(alloc|free)$",
                r"^SlotDecodeEngine\.(pages_needed|free_pages"
                r"|arena_stats|_free_slot_pages)$",
                r"^ContinuousBatcher\.(_arena_backpressure"
                r"|_observe_arena)$",
            ],
            # the sanctioned sync windows (metrics flush batches one D2H
            # transfer per metrics_every steps by design)
            "exempt_functions": [r"\._flush_metrics$", r"\._dump_nan_batch$"],
        },
        "TS003": {"enabled": True},
        "TS004": {"enabled": True},
        "TS005": {"enabled": True},
        "TS006": {"enabled": True},
        # -- interprocedural concurrency rules (callgraph.py) --
        "TS007": {"enabled": True},
        "TS008": {
            "enabled": True,
            # dotted call roots that block the calling thread outright
            "blocking_roots": [
                "time.sleep",
                "socket.create_connection",
                "urllib.request.urlopen",
                "subprocess.run", "subprocess.call",
                "subprocess.check_call", "subprocess.check_output",
            ],
            # attribute-call names that block on sockets / processes /
            # events; ``cond.wait()`` on the held lock's own condition
            # is exempted by the rule (it RELEASES that lock)
            "blocking_methods": [
                "recv", "recvfrom", "accept", "connect", "connect_ex",
                "sendall", "communicate", "wait", "urlopen", "sleep",
            ],
        },
        "TS009": {
            "enabled": True,
            # writers matching this run at construction time, before the
            # object escapes to other threads (happens-before via
            # Thread.start) — they don't count as racing accesses
            "init_method_re":
                r"^(__init__|__new__|__post_init__|_init[a-z_]*)$",
        },
        "TS010": {
            "enabled": True,
            # the single sanctioned settle funnel (clause A) and the
            # first-wins guard-flag discipline (clause B)
            "funnel_methods": ["_finish"],
            "settle_flags": ["_settled"],
            "resolver_methods": ["_finish", "_resolve", "_reject"],
        },
    },
}


def merge_config(override: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """DEFAULT deep-merged with `override` (override wins per key; rule
    dicts merge key-by-key rather than wholesale)."""
    cfg = copy.deepcopy(DEFAULT)
    if not override:
        return cfg
    for key, value in override.items():
        if key == "rules" and isinstance(value, dict):
            for rid, rcfg in value.items():
                if isinstance(rcfg, dict):
                    cfg["rules"].setdefault(rid, {}).update(rcfg)
                elif isinstance(rcfg, bool):  # {"TS004": False} shorthand
                    cfg["rules"].setdefault(rid, {})["enabled"] = rcfg
                else:
                    raise ValueError(
                        f"rule config for {rid} must be a dict or bool, "
                        f"got {type(rcfg).__name__}")
        else:
            cfg[key] = value
    return cfg
