"""Decoder driver: end-to-end decode over the checkpoint layer + writers."""

import json
import os

import numpy as np
import pytest

from textsummarization_on_flink_tpu.checkpoint import checkpointer as ckpt_lib
from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.data.batcher import Batcher
from textsummarization_on_flink_tpu.data.vocab import Vocab
from textsummarization_on_flink_tpu.decode import decoder as dec_lib
from textsummarization_on_flink_tpu.train import trainer as trainer_lib

WORDS = ("the a cat dog sat ran mat home big small quick brown fox jumped "
         "over lazy it was day night").split()

HPS = HParams(batch_size=2, hidden_dim=8, emb_dim=6, vocab_size=24,
              max_enc_steps=16, max_dec_steps=8, beam_size=2,
              min_dec_steps=1, max_oov_buckets=4, mode="decode",
              single_pass=True)


@pytest.fixture(scope="module")
def vocab():
    return Vocab(words=WORDS)


def article(i):
    return f"the quick brown fox {WORDS[i % len(WORDS)]} over the lazy dog ."


def abstract(i):
    return f"<s> the fox {WORDS[i % len(WORDS)]} . </s>"


def make_source(n):
    def src():
        return iter([(article(i), abstract(i)) for i in range(n)])
    return src


@pytest.fixture(scope="module")
def train_dir(tmp_path_factory, vocab):
    d = str(tmp_path_factory.mktemp("train"))
    state = trainer_lib.init_train_state(HPS, vocab.size(), seed=0)
    ckpt_lib.Checkpointer(d, hps=HPS).save(state)
    return d


def test_words_to_sentences():
    ws = "the cat sat . a dog ran . tail".split()
    assert dec_lib.words_to_sentences(ws) == \
        ["the cat sat .", "a dog ran .", "tail"]
    assert dec_lib.words_to_sentences([]) == []


def test_make_html_safe():
    assert dec_lib.make_html_safe("<s> a </s>") == "&lt;s&gt; a &lt;/s&gt;"


def test_decode_dir_name():
    name = dec_lib.get_decode_dir_name(HPS, "/x/model.ckpt-42.npz")
    assert name == "decode_ckpt-42_16maxenc_2beam_1mindec_8maxdec"


def test_single_pass_decode_with_rouge(tmp_path, vocab, train_dir):
    hps = HPS
    batcher = Batcher("", vocab, hps, single_pass=True,
                      decode_batch_mode="distinct",
                      example_source=make_source(3))
    d = dec_lib.BeamSearchDecoder(hps, vocab, batcher, train_dir=train_dir,
                                  decode_root=str(tmp_path),
                                  max_ckpt_retries=0)
    results = d.decode(with_rouge=True)
    assert results is not None and "rouge_1" in results
    dec_dir = os.path.join(str(tmp_path),
                           dec_lib.get_decode_dir_name(hps, d._ckpt_path))
    ref_files = sorted(os.listdir(os.path.join(dec_dir, "reference")))
    dec_files = sorted(os.listdir(os.path.join(dec_dir, "decoded")))
    assert len(ref_files) == 3 and len(dec_files) == 3
    assert os.path.exists(os.path.join(dec_dir, "ROUGE_results.txt"))
    # reference files hold the abstract sentences
    with open(os.path.join(dec_dir, "reference", ref_files[0])) as f:
        assert "fox" in f.read()


def test_single_pass_refuses_existing_dir(tmp_path, vocab, train_dir):
    batcher = Batcher("", vocab, HPS, single_pass=True,
                      example_source=make_source(1))
    d = dec_lib.BeamSearchDecoder(HPS, vocab, batcher, train_dir=train_dir,
                                  decode_root=str(tmp_path),
                                  max_ckpt_retries=0)
    with pytest.raises(FileExistsError):
        dec_lib.BeamSearchDecoder(HPS, vocab, batcher, train_dir=train_dir,
                                  decode_root=str(tmp_path),
                                  max_ckpt_retries=0)
    del d


def test_continuous_decode_sink_and_attnvis(tmp_path, vocab, train_dir):
    hps = HPS.replace(single_pass=False)
    batcher = Batcher("", vocab, hps, single_pass=True,  # finite source
                      decode_batch_mode="repeat",
                      example_source=make_source(2))
    d = dec_lib.BeamSearchDecoder(hps, vocab, batcher, train_dir=train_dir,
                                  decode_root=str(tmp_path),
                                  max_ckpt_retries=0)
    rows = []
    d.decode(result_sink=lambda r: rows.append(r.as_row()))
    # repeat-mode batches collapse to one distinct article each
    assert len(rows) == 2
    uuid, art, summary, ref = rows[0]
    assert "fox" in art
    assert isinstance(summary, str)
    vis = os.path.join(str(tmp_path), "decode", "attn_vis_data.json")
    with open(vis) as f:
        data = json.load(f)
    assert set(data) >= {"article_lst", "decoded_lst", "abstract_str",
                         "attn_dists"}
    assert "p_gens" in data  # pointer_gen on
    # attention rows align with the article token count
    assert all(len(row) <= len(data["article_lst"])
               for row in data["attn_dists"])


def test_decode_batch_emits_valid_words(tmp_path, vocab, train_dir):
    hps = HPS.replace(single_pass=False)
    batcher = Batcher("", vocab, hps, single_pass=True,
                      decode_batch_mode="distinct",
                      example_source=make_source(2))
    d = dec_lib.BeamSearchDecoder(hps, vocab, batcher, train_dir=train_dir,
                                  decode_root=str(tmp_path),
                                  max_ckpt_retries=0)
    batch = batcher.next_batch()
    results = d.decode_batch(batch)
    assert 1 <= len(results) <= hps.batch_size
    for r in results:
        for w in r.decoded_words:
            assert isinstance(w, str) and w  # real words, never raw ids
            assert w != "[STOP]"


def test_identical_input_rows_get_one_result_each(tmp_path, vocab, train_dir):
    """Two legitimately identical input rows (same uuid AND article — e.g.
    a retried request) must each produce an output row; only batcher-tagged
    padding rows are dropped (VERDICT r1 weak #5)."""
    hps = HPS.replace(single_pass=False)

    def source():
        for _ in range(2):
            yield ("uuid-dup", article(0), abstract(0), "ref")

    batcher = Batcher("", vocab, hps, single_pass=True,
                      decode_batch_mode="distinct", example_source=source)
    d = dec_lib.BeamSearchDecoder(hps, vocab, batcher, train_dir=train_dir,
                                  decode_root=str(tmp_path),
                                  max_ckpt_retries=0)
    batch = batcher.next_batch()
    assert batch.real_mask == [True, True]
    results = d.decode_batch(batch)
    assert len(results) == 2
    assert [r.uuid for r in results] == ["uuid-dup", "uuid-dup"]


def test_empty_article_row_serves_without_nan(tmp_path, vocab, train_dir):
    """A streamed row with an EMPTY article (fully-masked encoder) must
    not poison the batch with NaNs (clamped softmax denominators,
    ADVICE r1) and must still produce one output row per real input."""
    hps = HPS.replace(single_pass=False)

    def source():
        yield ("u-empty", "", "<s> the . </s>", "r")
        yield ("u-real", article(0), abstract(0), "r")

    batcher = Batcher("", vocab, hps, single_pass=True,
                      decode_batch_mode="distinct", example_source=source)
    d = dec_lib.BeamSearchDecoder(hps, vocab, batcher, train_dir=train_dir,
                                  decode_root=str(tmp_path),
                                  max_ckpt_retries=0)
    rows = []
    d.decode(result_sink=lambda r: rows.append(r.as_row()), log_results=False)
    assert sorted(r[0] for r in rows) == ["u-empty", "u-real"]
    for uuid, art, summary, ref in rows:
        # a NaN-poisoned search emits out-of-range token ids, which
        # outputids2words rejects with ValueError (verified by mutation:
        # removing the softmax-denominator clamp fails here)
        assert isinstance(summary, str)


def test_decoder_multichip_dp(tmp_path, vocab, train_dir):
    """BeamSearchDecoder with dp>1 serves through the sharded search."""
    hps = HPS.replace(single_pass=False, dp=4, batch_size=4)
    batcher = Batcher("", vocab, hps, single_pass=True,
                      decode_batch_mode="distinct",
                      example_source=make_source(4))
    d = dec_lib.BeamSearchDecoder(hps, vocab, batcher, train_dir=train_dir,
                                  decode_root=str(tmp_path),
                                  max_ckpt_retries=0)
    assert d._sharded_search is not None
    rows = []
    d.decode(result_sink=lambda r: rows.append(r.as_row()), log_results=False)
    assert len(rows) == 4
    for uuid, art, summary, ref in rows:
        assert isinstance(summary, str)


def test_attnvis_viewer_covers_written_fields(tmp_path):
    """tools/attn_vis.html must reference every field write_for_attnvis
    actually emits (decode.py:225-249 layout) — the expected list is
    derived by CALLING the writer, so a rename on the python side fails
    this test instead of silently breaking the in-repo visualizer."""
    import numpy as np

    class _Host:  # the two attributes write_for_attnvis reads
        _decode_dir = str(tmp_path)
        _hps = HPS

    res = dec_lib.DecodedResult(
        "u1", "the quick <fox>", ["quick", "."], "ref", ["a ref ."],
        attn_dists=np.full((2, 3), 1 / 3), p_gens=np.array([0.25, 0.75]))
    dec_lib.BeamSearchDecoder.write_for_attnvis(_Host(), res)
    with open(tmp_path / "attn_vis_data.json") as f:
        emitted = json.load(f)
    assert "p_gens" in emitted  # pointer_gen on in HPS
    html = open(os.path.join(os.path.dirname(__file__), "..", "tools",
                             "attn_vis.html"), encoding="utf-8").read()
    for field in emitted:
        assert field in html, f"viewer never references {field!r}"
    # the writer html-escapes tokens (make_html_safe); the viewer must
    # unescape before textContent rendering or '<fox>' shows as
    # '&lt;fox&gt;'
    assert emitted["article_lst"][2] == "&lt;fox&gt;"
    assert "unescape" in html
