"""The committed SLO burn-rate gate (ISSUE 15 acceptance;
SLO_POLICY.json at the repo root).

Same discipline as tests/test_serve_slo.py: the REAL serving stack
(ServingServer, RequestQueue, ContinuousBatcher, the obs/slo.py engine
installed by the server itself) driven single-threaded over VIRTUAL
time — the engine's clock is the gate's clock, so breach and recovery
are exact scheduling facts, no sleeps, no CI flake.

The committed scenario (SLO_POLICY.json "gate"): a victim tenant
trickles short articles while an attacker tenant submits long ones
whose end-to-end latency breaches the ``tenant_latency`` objective's
threshold.  Enforced here, in tier-1:

  * the attacker's fast-window burn rate drives its objective past the
    PAGE threshold within the fast window of the first breach;
  * the victim tenant's objective stays ``ok`` at every evaluation;
  * the page CLEARS after the breach ends (the multi-window rule: a
    clean fast window recovers the alert even while the slow window
    still remembers the breach);
  * the page transition dumps the flight-recorder ring
    (``flight_slo_burn.jsonl``) with every frame strictly pre-breach;
  * exemplar round-trip — the p99 bucket's exemplar trace_id
    reconstructs the offending request end-to-end through
    ``scripts/trace_summary.py --request`` from one events.jsonl.

Plus unit coverage of the engine itself: burn-rate arithmetic, the
multi-window min rule, declarative-objective validation, and the
hostile-tenant series bound.
"""

import json
import os
import sys

import pytest

from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.data.vocab import Vocab
from textsummarization_on_flink_tpu.decode.decoder import DecodedResult
from textsummarization_on_flink_tpu.obs import slo as slo_lib
from textsummarization_on_flink_tpu.obs.registry import Registry
from textsummarization_on_flink_tpu.serve.server import ServingServer

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))
import trace_summary  # noqa: E402

POLICY_PATH = os.path.join(os.path.dirname(__file__), "..",
                           "SLO_POLICY.json")

WORDS = ["w"]


@pytest.fixture(scope="module")
def policy():
    with open(POLICY_PATH) as f:
        return json.load(f)


class _VClock:
    """The gate's virtual clock, in ms (seconds out of ``now`` — the
    server/engine clock unit)."""

    def __init__(self):
        self.ms = 0.0

    def now(self) -> float:
        return self.ms / 1000.0


class _NullDecoder:
    def maybe_reload_checkpoint(self, last):
        return last


class GateSimEngine:
    """SlotDecodeEngine protocol over the SHARED virtual clock: each
    step() advances it by chunk * step_cost_ms and every active slot by
    ``chunk`` steps, so a long article's harvest lands ``long_steps *
    step_cost_ms`` virtual ms after its pack — the latency the
    ``tenant_latency`` objective classifies."""

    def __init__(self, wl, vclock):
        self.slots = wl["slots"]
        self.chunk = wl["chunk"]
        self._wl = wl
        self._vclock = vclock
        self._remaining = [0] * self.slots
        self._active = [False] * self.slots

    def pack(self, idx, example):
        assert not self._active[idx]
        short = example.enc_len <= self._wl["short_words"]
        self._active[idx] = True
        self._remaining[idx] = (self._wl["short_steps"] if short
                                else self._wl["long_steps"])

    def step(self):
        self._vclock.ms += self.chunk * self._wl["step_cost_ms"]
        fin = []
        for i in range(self.slots):
            if self._active[i]:
                self._remaining[i] -= self.chunk
                if self._remaining[i] <= 0:
                    fin.append(i)
        return fin

    def unpack(self, idx, example):
        assert self._active[idx]
        self._active[idx] = False
        return DecodedResult(
            uuid=example.uuid, article=example.original_article,
            decoded_words=["ok", "."], reference=example.reference,
            abstract_sents=[])

    def release(self, idx):
        self._active[idx] = False


def _alert_state(reg, key: str) -> float:
    """The slo/alert_state gauge for (tenant_latency, key): 0 ok,
    1 warn, 2 page."""
    return reg.gauge("slo/alert_state").labels(
        objective="tenant_latency", key=key).value


@pytest.fixture(scope="module")
def gate_run(policy, tmp_path_factory):
    """ONE deterministic run of the committed breach-and-recover
    scenario; every gate test below reads its facts."""
    wl = policy["gate"]
    tmp = tmp_path_factory.mktemp("slo_gate")
    events_dir = str(tmp / "events")
    vocab = Vocab(words=WORDS)
    vclock = _VClock()
    hps = HParams(
        mode="decode", batch_size=wl["slots"], vocab_size=vocab.size(),
        max_enc_steps=wl["long_words"], max_dec_steps=wl["long_steps"],
        beam_size=2, min_dec_steps=1, max_oov_buckets=4,
        serve_max_queue=wl["queue"],
        serve_mode="continuous", serve_slots=wl["slots"],
        serve_refill_chunk=wl["chunk"],
        serve_fair_weights=wl["fair_weights"],
        log_root=str(tmp), exp_name="slo_gate")
    reg = Registry()
    sink = obs.install_event_sink(events_dir, flush_secs=0.05, reg=reg)
    sim = GateSimEngine(wl, vclock)
    server = ServingServer(hps, vocab, decoder=_NullDecoder(),
                           engine=sim, registry=reg, clock=vclock.now)
    assert reg.slo is not None, \
        "ServingServer must install the committed SLO engine"
    futures = []
    page_at_s = None
    ticks_at_page = None
    victim_states = []
    attacker_trajectory = []  # (virtual s, attacker state) per round
    rounds = wl["rounds_breach"] + wl["rounds_recover"]
    for rnd in range(rounds):
        futures.append(server.submit(
            " ".join(WORDS * wl["short_words"]), uuid=f"v{rnd}",
            tenant="victim"))
        n_words = (wl["long_words"] if rnd < wl["rounds_breach"]
                   else wl["short_words"])
        futures.append(server.submit(
            " ".join(WORDS * n_words), uuid=f"a{rnd}",
            tenant="attacker"))
        server.tick_once(poll=0.0)
        a_state = _alert_state(reg, "attacker")
        victim_states.append(_alert_state(reg, "victim"))
        attacker_trajectory.append((vclock.now(), a_state))
        if page_at_s is None and a_state == 2:
            page_at_s = vclock.now()
            ticks_at_page = rnd + 1
    # drain: every admitted request resolves exactly once
    for _ in range(100):
        if all(f.done() for f in futures):
            break
        server.tick_once(poll=0.0)
    results = [f.result(timeout=0) for f in futures]
    server.stop()
    sink.close()
    events_path = None
    for root, _, names in os.walk(events_dir):
        if "events.jsonl" in names:
            events_path = os.path.join(root, "events.jsonl")
    assert events_path is not None
    return {
        "wl": wl, "reg": reg, "results": results,
        "page_at_s": page_at_s, "ticks_at_page": ticks_at_page,
        "victim_states": victim_states,
        "attacker_trajectory": attacker_trajectory,
        "final_attacker_state": _alert_state(reg, "attacker"),
        "dump_dir": str(tmp / "slo_gate"),
        "events_path": events_path,
    }


def test_attacker_breach_pages_within_fast_window(gate_run):
    """The committed paging promise: a sustained latency breach by one
    tenant drives ITS fast-window burn rate past the page threshold
    within the fast window of the breach starting (t=0 virtual)."""
    wl = gate_run["wl"]
    assert gate_run["page_at_s"] is not None, \
        "attacker latency breach never paged"
    assert gate_run["page_at_s"] <= wl["page_within_secs"], (
        f"page came at +{gate_run['page_at_s']:.0f} virtual s (committed "
        f"within {wl['page_within_secs']:.0f}) — the fast window is not "
        f"doing its job")
    burn = gate_run["reg"].gauge("slo/burn_rate_fast").labels(
        objective="tenant_latency", key="attacker")
    # the gauge family is live: SOME evaluation pushed the attacker's
    # fast burn past the page threshold (it may have recovered since)
    assert any(s == 2 for _, s in gate_run["attacker_trajectory"])
    assert burn is not None


def test_victim_objective_stays_ok_throughout(gate_run):
    """Tenant isolation, telemetry edition: the attacker's breach is
    attributed to the attacker — the victim's objective never leaves
    ``ok`` at any evaluation of the run."""
    assert all(s == 0 for s in gate_run["victim_states"]), (
        f"victim alert states left ok: "
        f"{sorted(set(gate_run['victim_states']))}")


def test_alert_recovers_after_breach_ends(gate_run):
    """Symmetric recovery (the multi-window min rule): once the
    attacker's traffic goes clean and the fast window slides past the
    breach, the page clears — even though the slow window still
    remembers it."""
    assert gate_run["final_attacker_state"] == 0, (
        "attacker objective still not ok after "
        f"{gate_run['wl']['rounds_recover']} clean rounds")
    # and the recovery happened AFTER a real page (not vacuous)
    states = [s for _, s in gate_run["attacker_trajectory"]]
    assert states.index(2) < len(states) - 1 and states[-1] == 0


def test_slo_burn_flight_dump_ring_strictly_pre_breach(gate_run):
    """The page transition dumps the flight ring exactly like
    ``train_nan``: ``flight_slo_burn.jsonl`` lands next to the decode
    output, its header names the paged (objective, key), and every
    ring frame precedes the breach evaluation (ticks <= the round the
    page fired on)."""
    path = os.path.join(gate_run["dump_dir"], "flight_slo_burn.jsonl")
    assert os.path.exists(path), (
        f"no slo_burn flight dump in {gate_run['dump_dir']}")
    with open(path) as f:
        recs = [json.loads(ln) for ln in f if ln.strip()]
    header, frames = recs[0], recs[1:]
    assert header["kind"] == "flight" and header["reason"] == "slo_burn"
    assert header["context"]["objective"] == "tenant_latency"
    assert header["context"]["key"] == "attacker"
    assert header["context"]["burn_fast"] >= 8.0  # the committed page
    assert frames, "empty ring dumped"
    ticks = [fr["tick"] for fr in frames if "tick" in fr]
    assert ticks and max(ticks) <= gate_run["ticks_at_page"], (
        f"ring frames past the breach: max tick {max(ticks)} vs page at "
        f"tick {gate_run['ticks_at_page']}")


def test_exemplar_round_trip_through_trace_summary(gate_run):
    """ISSUE 15 acceptance, exemplar leg: the e2e histogram's p99
    bucket carries a trace_id exemplar, and that trace_id — pasted
    straight into ``trace_summary.py --request`` — reconstructs the
    offending request's full timeline from the run's one
    events.jsonl."""
    reg = gate_run["reg"]
    h = reg.get("serve/e2e_latency_seconds")
    # the histogram runs on wall time (the engine is simulated, the
    # scheduler is real); the exemplar contract is about the JUMP, not
    # the magnitude: the bucket holding the p99 names a trace_id
    p99 = h.percentile(99)
    fat = next(e for e in h.exemplars()
               if e["le"] == "+Inf" or float(e["le"]) >= p99)
    tl = trace_summary.request_timeline(
        [gate_run["events_path"]], fat["trace_id"])
    assert tl["events"], f"exemplar {fat['trace_id']} matched no events"
    assert tl["trace_id"] == fat["trace_id"]
    # ...and the trace resolves back to one real request of the run
    assert tl["uuid"] and tl["uuid"][0] in ("a", "v"), tl["uuid"]
    stages = {e["event"] for e in tl["events"]}
    assert {"enqueue", "slot", "finish", "resolve"} <= stages, stages
    assert tl["phases"].get("total_ms") is not None


def test_every_future_resolved_exactly_once(gate_run):
    uuids = [r.uuid for r in gate_run["results"]]
    assert len(uuids) == len(set(uuids)) == 2 * (
        gate_run["wl"]["rounds_breach"] + gate_run["wl"]["rounds_recover"])


# --------------------------------------------------------------------------
# engine unit coverage
# --------------------------------------------------------------------------

def _mini_policy(**over):
    pol = {
        "windows": {"fast_secs": 10.0, "slow_secs": 100.0,
                    "bucket_secs": 1.0},
        "thresholds": {"warn": 2.0, "page": 10.0},
        "objectives": [{"name": "lat", "signal": "latency",
                        "by": "tenant", "latency_threshold_ms": 1000.0,
                        "target": 0.9}],
    }
    pol.update(over)
    return pol


class TestSloEngine:
    def test_burn_rate_arithmetic_exact(self):
        t = [100.0]
        eng = slo_lib.SloEngine(_mini_policy(), Registry(),
                                clock=lambda: t[0])
        for _ in range(8):
            eng.record("a", "beam", 0.5)   # good
        for _ in range(2):
            eng.record("a", "beam", 2.0)   # bad: over the 1s threshold
        rows = eng.evaluate()
        (row,) = rows
        # frac_bad 0.2 / budget 0.1 -> burn 2.0, exactly
        assert row["burn_fast"] == 2.0 and row["burn_slow"] == 2.0
        assert row["state"] == "warn"
        assert row["events_fast"] == 10

    def test_multi_window_min_rule(self):
        """Bad events older than the fast window cannot page on their
        own: effective burn is min(fast, slow)."""
        t = [0.0]
        eng = slo_lib.SloEngine(_mini_policy(), Registry(),
                                clock=lambda: t[0])
        for _ in range(10):
            eng.record("a", "beam", 5.0)  # all bad -> burn 10 both
        (row,) = eng.evaluate()
        assert row["state"] == "page"
        # slide past the fast window with clean traffic
        t[0] = 50.0
        for _ in range(10):
            eng.record("a", "beam", 0.1)
        (row,) = eng.evaluate()
        assert row["burn_fast"] == 0.0
        assert row["burn_slow"] > 0.0  # the slow window still remembers
        assert row["state"] == "ok"

    def test_error_signal_objective(self):
        pol = _mini_policy(objectives=[{
            "name": "errs", "signal": "error", "by": "tier",
            "target": 0.5}])
        t = [0.0]
        eng = slo_lib.SloEngine(pol, Registry(), clock=lambda: t[0])
        eng.record("a", "beam", 0.1, error=True)
        eng.record("a", "beam", 0.1, error=False)
        (row,) = eng.evaluate()
        assert row["key"] == "beam" and row["burn_fast"] == 1.0

    def test_objective_validation(self):
        with pytest.raises(ValueError):
            slo_lib.Objective({"name": "x", "signal": "nope"})
        with pytest.raises(ValueError):
            slo_lib.Objective({"name": "x", "by": "region"})
        with pytest.raises(ValueError):
            slo_lib.Objective({"name": "x", "target": 1.5})
        with pytest.raises(ValueError):
            slo_lib.Objective({"name": "x", "signal": "latency",
                               "latency_threshold_ms": 0})

    def test_hostile_tenant_series_bound(self, monkeypatch):
        monkeypatch.setattr(slo_lib, "MAX_SLO_SERIES", 8)
        reg = Registry()
        t = [0.0]
        eng = slo_lib.SloEngine(_mini_policy(), reg, clock=lambda: t[0])
        for i in range(100):
            eng.record(f"hostile-{i}", "beam", 0.1)
        assert len(eng._series) == 8
        assert reg.counter("slo/series_evictions_total").value == 92

    def test_alerts_payload_without_engine(self):
        payload = slo_lib.alerts_payload(Registry())
        assert payload == {"status": "ok", "installed": False,
                           "objectives": []}

    def test_install_with_missing_policy_is_noop(self, monkeypatch):
        monkeypatch.setenv(slo_lib.ENV_POLICY, "/nonexistent/slo.json")
        reg = Registry()
        assert slo_lib.install_slo_engine(reg) is None
        assert reg.slo is None

    def test_slo_label_caps_match_engine_series_bound(self):
        """The slo/* metrics must hold one labeled child per live
        engine series — a cap below MAX_SLO_SERIES would LRU-thrash the
        gauge children every evaluate() and drop paging series from
        the scraped exposition."""
        reg = Registry()
        slo_lib.SloEngine(_mini_policy(), reg)
        for name in ("slo/burn_rate_fast", "slo/burn_rate_slow",
                     "slo/alert_state", "slo/good_total",
                     "slo/bad_total"):
            assert reg.get(name)._max_label_sets >= \
                slo_lib.MAX_SLO_SERIES, name

    def test_track_request_helper_counts_once_and_classifies(self):
        """The shared ingress helper (serve/queue.py): one labeled
        requests_total inc, one SLO record on the future's exactly-once
        resolution, latency on the caller's clock."""
        from textsummarization_on_flink_tpu.serve.queue import (
            ServeFuture,
            track_request,
        )

        reg = Registry()
        eng = slo_lib.install_slo_engine(reg, policy=_mini_policy())
        t = [0.0]
        fut = ServeFuture("u1", registry=reg)
        track_request(reg, lambda: t[0], fut, "", "beam")
        assert reg.counter("serve/requests_total").labels(
            tenant="default", tier="beam").value == 1
        t[0] = 5.0  # resolves 5 virtual s later: over the 1s threshold
        fut._resolve("ok")
        (row,) = eng.evaluate()
        assert row["key"] == "default" and row["events_fast"] == 1
        assert row["burn_fast"] == 10.0  # frac_bad 1.0 / budget 0.1

    def test_committed_policy_loads(self, policy):
        """SLO_POLICY.json itself parses into a working engine."""
        eng = slo_lib.SloEngine(policy, Registry())
        assert {o.name for o in eng.objectives} == {
            "tenant_latency", "tier_latency", "tier_errors"}
        assert eng.page == policy["thresholds"]["page"]
