"""Sequence-level distillation of the narrow draft (ISSUE 12).

The committed BYTE_BUDGET.json ``spec.distill`` gate: a tiny
transformer teacher is trained on a LEARNABLE synthetic task (copy the
article prefix — the pointer mechanism's native move), the narrow
draft (draft_hidden < H, factored vocab head) is distilled on the
teacher's greedy outputs through the shared
``transformer.train_output_tail`` loss head, and the measured
acceptance rate on a HELD-OUT synthetic set must clear the committed
floor — while the undistilled fresh draft must sit far below it, so
the gate measures distillation, not luck.  Plus DistillTrainer
mechanics: the (full, draft) checkpoint-pair sidecar, the
teacher-array feed-back rules, and token exactness of the distilled
spec tier.
"""

import json
import os
import tempfile

import numpy as np
import jax
import pytest

from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.checkpoint.checkpointer import (
    Checkpointer,
)
from textsummarization_on_flink_tpu.config import HParams, derive_draft_hps
from textsummarization_on_flink_tpu.data.vocab import (
    START_ID,
    STOP_ID,
    UNK_ID,
)
from textsummarization_on_flink_tpu.models import avg_attention
from textsummarization_on_flink_tpu.obs import Registry
from textsummarization_on_flink_tpu.train import distill
from textsummarization_on_flink_tpu.train import trainer as trainer_lib
from tests.test_speculative import assert_spec_matches_greedy, make_arrays

BUDGET_PATH = os.path.join(os.path.dirname(__file__), "..",
                           "BYTE_BUDGET.json")


@pytest.fixture(autouse=True)
def _isolated_obs():
    with obs.use_registry(Registry()) as reg:
        yield reg


@pytest.fixture(scope="module")
def dbudget():
    with open(BUDGET_PATH) as f:
        return json.load(f)["spec"]["distill"]


@pytest.fixture(scope="module")
def dhparams(dbudget) -> HParams:
    hps = HParams(**dbudget["scale"])
    hps.validate()
    return hps


class _ArraysBatch:
    """Minimal ``next_batch`` payload: the distillation path consumes
    only ``as_arrays()`` (the teacher writes the decoder side)."""

    def __init__(self, arrays):
        self._arrays = arrays

    def as_arrays(self):
        return self._arrays


class _CycleBatcher:
    def __init__(self, batches):
        self._batches = batches
        self._i = 0

    def next_batch(self):
        b = self._batches[self._i % len(self._batches)]
        self._i += 1
        return b


def copy_task_arrays(arr, hps: HParams):
    """Synthetic supervised task the TEACHER learns first: emit the
    article's first T_dec-1 extended tokens then STOP — learnable by
    the pointer mechanism (copy attention), hence a teacher whose
    greedy function GENERALIZES to held-out articles.  (A random-init
    teacher's greedy output is an unlearnable hash of the article;
    distilling it can only memorize — the honest negative case.)"""
    B = arr["enc_batch"].shape[0]
    T = hps.max_dec_steps
    dec = np.zeros((B, T), np.int32)
    tgt = np.zeros((B, T), np.int32)
    mask = np.ones((B, T), np.float32)
    for b in range(B):
        gen = arr["enc_batch_extend_vocab"][b, :T - 1].astype(np.int64)
        gen = np.concatenate([gen, [STOP_ID]])
        inputs = np.concatenate([[START_ID], gen[:-1]])
        dec[b] = np.where(inputs >= hps.vocab_size, UNK_ID, inputs)
        tgt[b] = gen
    return {**{k: v for k, v in arr.items() if k.startswith("enc_")},
            "dec_batch": dec, "target_batch": tgt,
            "dec_padding_mask": mask}


@pytest.fixture(scope="module")
def teacher(dbudget, dhparams):
    """The frozen full model, trained on the copy task for the
    committed step count (a few seconds on CPU)."""
    hps = dhparams.replace(mode="train")
    state = trainer_lib.init_train_state(hps, hps.vocab_size, seed=0)
    step = jax.jit(trainer_lib.make_train_step(hps))
    n_batches = int(dbudget["teacher_batches"])
    data = [copy_task_arrays(make_arrays(dhparams, dhparams.batch_size,
                                         seed=1000 + s), dhparams)
            for s in range(n_batches)]
    for i in range(int(dbudget["teacher_task_steps"])):
        state, _ = step(state, data[i % n_batches])
    return jax.device_get(state.params)


@pytest.fixture(scope="module")
def heldout(dhparams):
    """Articles NEITHER the teacher nor the draft ever saw."""
    return make_arrays(dhparams, dhparams.batch_size, seed=100)


@pytest.fixture(scope="module")
def distilled(dbudget, dhparams, teacher):
    """The committed distillation run, through the REAL DistillTrainer
    (cached teacher: each batch is teacher-decoded once, later epochs
    pay only the draft step)."""
    batches = [_ArraysBatch(make_arrays(dhparams, dhparams.batch_size,
                                        seed=s))
               for s in range(int(dbudget["distill_batches"]))]
    with obs.use_registry(Registry()):
        dt = distill.DistillTrainer(
            dhparams, dhparams.vocab_size, _CycleBatcher(batches),
            teacher, cache_teacher=True, seed=7)
        dt.distill(int(dbudget["distill_steps"]))
    return jax.device_get(dt.draft_params())


# -- the committed gate -----------------------------------------------------

def test_distilled_acceptance_clears_committed_floor(dbudget, dhparams,
                                                     teacher, heldout,
                                                     distilled):
    """THE ISSUE-12 distillation claim: held-out acceptance of the
    distilled narrow draft at or above the committed floor."""
    got = distill.acceptance_rate(teacher, distilled, dhparams, heldout)
    floor = float(dbudget["min_accept_rate"])
    assert got >= floor, (
        f"distilled narrow draft's held-out acceptance fell to "
        f"{got:.3f} (committed floor {floor}) — distillation through "
        f"the shared loss head stopped transferring the teacher's "
        f"greedy behavior (see BYTE_BUDGET.json spec._comment)")


def test_fresh_draft_sits_below_the_floor(dbudget, dhparams, teacher,
                                          heldout):
    """The control: an UNdistilled fresh narrow draft must be far below
    the floor, or the gate would measure the task, not the training."""
    dhps = derive_draft_hps(dhparams)
    fresh = avg_attention.init_params(dhps, dhparams.vocab_size,
                                      jax.random.PRNGKey(7))
    got = distill.acceptance_rate(teacher, fresh, dhparams, heldout)
    assert got <= float(dbudget["max_fresh_accept_rate"]), (
        f"fresh narrow draft already accepts at {got:.3f} — the gate "
        f"scale lost its discriminating power; re-pin spec.distill")


def test_distilled_spec_output_token_exact(dhparams, teacher, heldout,
                                           distilled):
    """Exactness is draft-independent by construction — pinned here for
    the DISTILLED draft specifically (both quality regimes covered:
    high-acceptance distilled here, near-zero fresh in
    test_speculative)."""
    assert_spec_matches_greedy(teacher, distilled, dhparams, heldout)


# -- DistillTrainer mechanics -----------------------------------------------

def test_teacher_arrays_feedback_rules(dhparams, teacher):
    """Targets keep extended-vocab ids (the pointer loss scores copies
    against the article); inputs are the targets shifted right behind
    START and UNK-mapped; the mask covers exactly the teacher's
    emitted length."""
    arrays = make_arrays(dhparams, dhparams.batch_size, seed=3)
    out = distill.teacher_arrays(teacher, dhparams, arrays)
    V = dhparams.vocab_size
    B, T = out["dec_batch"].shape
    assert (out["dec_batch"] < V).all(), "inputs must be UNK-mapped"
    for b in range(B):
        n = int(out["dec_padding_mask"][b].sum())
        assert n >= 1
        assert out["dec_batch"][b, 0] == START_ID
        tgt = out["target_batch"][b, :n]
        inp = out["dec_batch"][b, 1:n]
        want = np.where(tgt[:n - 1] >= V, UNK_ID, tgt[:n - 1])
        np.testing.assert_array_equal(inp, want)
        assert (out["target_batch"][b, n:] == 0).all()


def test_checkpoint_pair_roundtrip_and_teacher_guard(dbudget, dhparams,
                                                     teacher):
    """The (full, draft) pair contract: the draft checkpoint rides the
    standard Checkpointer format plus a teacher-fingerprint sidecar;
    restore resumes the exact state, the loader hands back the params,
    and a MISMATCHED teacher is refused typed."""
    batches = [_ArraysBatch(make_arrays(dhparams, dhparams.batch_size,
                                        seed=s)) for s in range(2)]
    tmp = tempfile.mkdtemp(prefix="distill_ckpt_")
    ck = Checkpointer(tmp)
    dt = distill.DistillTrainer(dhparams, dhparams.vocab_size,
                                _CycleBatcher(batches), teacher,
                                checkpointer=ck, cache_teacher=True,
                                seed=7)
    dt.distill(4)
    assert os.path.exists(os.path.join(tmp, distill.TEACHER_SIDECAR))
    # resume: a new trainer restores the saved draft state
    dt2 = distill.DistillTrainer(dhparams, dhparams.vocab_size,
                                 _CycleBatcher(batches), teacher,
                                 checkpointer=Checkpointer(tmp),
                                 cache_teacher=True, seed=99)
    assert int(dt2.state.step) == 4
    for a, b in zip(jax.tree_util.tree_leaves(dt.draft_params()),
                    jax.tree_util.tree_leaves(dt2.draft_params())):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # the serve-side loader verifies the pair
    loaded = distill.load_distilled_draft(tmp, full_params=teacher)
    np.testing.assert_array_equal(
        np.asarray(loaded["out_bias"]),
        np.asarray(dt.draft_params()["out_bias"]))
    wrong = dict(teacher)
    wrong["out_bias"] = np.asarray(teacher["out_bias"]) + 1.0
    with pytest.raises(ValueError, match="teacher"):
        distill.load_distilled_draft(tmp, full_params=wrong)


def test_distill_metrics_and_nan_watchdog(dhparams, teacher,
                                          _isolated_obs):
    """train/distill_steps_total counts steps; a poisoned teacher
    target stream surfaces the typed NonFiniteLossError through the
    windowed flush."""
    batches = [_ArraysBatch(make_arrays(dhparams, dhparams.batch_size,
                                        seed=0))]
    dt = distill.DistillTrainer(dhparams, dhparams.vocab_size,
                                _CycleBatcher(batches), teacher,
                                cache_teacher=True, seed=7,
                                metrics_every=2)
    dt.distill(3)
    assert _isolated_obs.counter(
        "train/distill_steps_total").value == 3
    # poison the draft state -> non-finite loss -> typed error
    bad = jax.tree_util.tree_map(lambda x: x, dt.state.params)
    bad["out_bias"] = np.full_like(np.asarray(bad["out_bias"]), np.nan)
    dt.state = dt.state._replace(params=bad)
    with pytest.raises(trainer_lib.NonFiniteLossError):
        dt.distill(2)
