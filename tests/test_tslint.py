"""tools/tslint test suite (ISSUE 3).

Three layers:
  * per-rule fixtures — a positive (the bug class the rule exists for)
    and a negative (the disciplined version) per rule, plus inline
    suppression and baseline round-trip semantics;
  * CLI contract — exit 0 clean / 1 new findings / 2 usage error, the
    codes scripts/lint.sh keys off;
  * repo self-check — the committed baseline keeps the package clean,
    and the baseline stays near-empty (<= 5 grandfathered findings, the
    ISSUE 3 acceptance bound).

The engine is stdlib-only, so none of these tests need jax.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from tools.tslint import analyze, load_baseline, match_baseline, write_baseline
from tools.tslint.config import DEFAULT_BASELINE
from tools.tslint.rules import RULES

REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
PACKAGE = "textsummarization_on_flink_tpu"

#: fixture-friendly TS002 config: every function is hot
HOT_ALL = {"rules": {"TS002": {"hot_functions": [r".*"],
                               "exempt_functions": [r"_flush"]}}}


def run_snippet(tmp_path, code, config=None, select=None):
    f = tmp_path / "snippet.py"
    f.write_text(textwrap.dedent(code), encoding="utf-8")
    result = analyze([str(f)], root=str(tmp_path), config=config,
                     select=select)
    return result


def rules_of(result):
    return [f.rule for f in result.findings]


# --------------------------------------------------------------------------
# TS001 — jit purity
# --------------------------------------------------------------------------

def test_ts001_print_in_jitted_fn(tmp_path):
    r = run_snippet(tmp_path, """
        import jax

        def step(params, batch):
            print("loss", params)
            return params

        train = jax.jit(step)
    """)
    assert rules_of(r) == ["TS001"]


def test_ts001_factory_returned_step_is_traced(tmp_path):
    # the repo's make_train_step shape: jax.jit(make_step(hps)) traces
    # the factory's returned def
    r = run_snippet(tmp_path, """
        import time
        import jax

        def make_step(lr):
            def step(params, batch):
                t0 = time.time()
                return params - lr * batch, t0
            return step

        train = jax.jit(make_step(0.1))
    """)
    assert rules_of(r) == ["TS001"]


def test_ts001_lax_scan_body_and_self_mutation(tmp_path):
    r = run_snippet(tmp_path, """
        import jax

        class Model:
            def fit(self, xs):
                def body(c, x):
                    self.last = x
                    return c + x, c
                return jax.lax.scan(body, 0.0, xs)
    """)
    assert rules_of(r) == ["TS001"]
    assert "self.last" in r.findings[0].message


def test_ts001_metric_mutation_via_partial_decorator(tmp_path):
    r = run_snippet(tmp_path, """
        import functools
        import jax

        class T:
            @functools.partial(jax.jit, static_argnames=("k",))
            def step(self, x, k):
                self._c_steps.inc()
                return x * k
    """)
    assert rules_of(r) == ["TS001"]


def test_ts001_negative_pure_step_and_jax_debug(tmp_path):
    r = run_snippet(tmp_path, """
        import jax
        import jax.numpy as jnp

        def make_step(lr):
            def step(params, batch):
                jax.debug.print("loss {}", params)
                g = jax.grad(lambda p: jnp.sum(p * batch))(params)
                return params - lr * g
            return step

        train = jax.jit(make_step(0.1))

        def host_side():
            print("this print is NOT traced")
    """)
    assert rules_of(r) == []


# --------------------------------------------------------------------------
# TS002 — host sync in hot loop
# --------------------------------------------------------------------------

def test_ts002_syncs_in_hot_loop(tmp_path):
    r = run_snippet(tmp_path, """
        import jax
        import numpy as np

        class Loop:
            def run(self, steps, state):
                for _ in range(steps):
                    state, metrics = self.step(state)
                    loss = float(metrics.loss)
                    host = jax.device_get(metrics)
                    arr = np.asarray(state.step)
                    scalar = metrics.loss.item()
                return state
    """, config=HOT_ALL)
    assert rules_of(r) == ["TS002"] * 4


def test_ts002_flush_window_exempt_and_cold_code_ignored(tmp_path):
    r = run_snippet(tmp_path, """
        import jax

        class Loop:
            def _flush(self, pending):
                for m in pending:
                    yield float(m.loss)  # sanctioned sync window

        def cold_path(xs):
            for x in xs:
                jax.device_get(x)  # not a declared hot function? still .*
    """, config={"rules": {"TS002": {
        "hot_functions": [r"^Loop\."], "exempt_functions": [r"_flush"]}}})
    assert rules_of(r) == []


def test_ts002_nested_loop_reports_once(tmp_path):
    # a sync two loops deep is ONE finding, not one per enclosing loop
    # (duplicates would also inflate --write-baseline and the
    # suppressed count)
    r = run_snippet(tmp_path, """
        class Loop:
            def run(self, batches):
                while True:
                    for b in batches:
                        x = b.loss.item()
    """, config=HOT_ALL)
    assert rules_of(r) == ["TS002"]


def test_rule_config_bool_shorthand_disables(tmp_path):
    code = """
        def f():
            try:
                g()
            except Exception:
                pass
    """
    r = run_snippet(tmp_path, code, config={"rules": {"TS005": False}})
    assert rules_of(r) == []
    with pytest.raises(ValueError):
        run_snippet(tmp_path, code, config={"rules": {"TS005": "nope"}})


def test_ts002_default_config_names_repo_hot_loops():
    from tools.tslint.config import DEFAULT

    pats = DEFAULT["rules"]["TS002"]["hot_functions"]
    assert any("_train_steps" in p for p in pats)
    assert any("next_batch" in p for p in pats)


# --------------------------------------------------------------------------
# TS003 — monotonic clock
# --------------------------------------------------------------------------

def test_ts003_direct_and_var_tracked_subtraction(tmp_path):
    r = run_snippet(tmp_path, """
        import time

        def direct(t0):
            return time.time() - t0

        def tracked():
            t0 = time.time()
            work()
            dur = now() - t0
            return dur
    """)
    assert rules_of(r) == ["TS003", "TS003"]


def test_ts003_regression_batcher_timeout_pattern(tmp_path):
    # the exact bug PR 2 fixed by hand in batcher._get_example: a poll
    # deadline budgeted from the wall clock stretches unboundedly when
    # the clock jumps — tslint now catches the class statically
    r = run_snippet(tmp_path, """
        import time

        def get_example(q, timeout):
            deadline = time.time() + timeout
            while True:
                remaining = deadline - time.time()
                if remaining <= 0:
                    return None
    """)
    assert rules_of(r) == ["TS003"]


def test_ts003_negative_monotonic_and_serialized_epoch(tmp_path):
    r = run_snippet(tmp_path, """
        import time

        def good():
            t0 = time.monotonic()
            dur = time.monotonic() - t0
            record = {"ts": time.time()}  # serialized epoch: legitimate
            return dur, record
    """)
    assert rules_of(r) == []


# --------------------------------------------------------------------------
# TS004 — lock discipline
# --------------------------------------------------------------------------

def test_ts004_unlocked_write_to_protected_attr(tmp_path):
    r = run_snippet(tmp_path, """
        import threading

        class Registry:
            def __init__(self):
                self._lock = threading.Lock()
                self._metrics = {}

            def register(self, name, m):
                with self._lock:
                    self._metrics[name] = m

            def sneak(self, name, m):
                self._metrics[name] = m
    """)
    assert rules_of(r) == ["TS004"]
    assert r.findings[0].scope == "Registry.sneak"


def test_ts004_lock_held_helper_fixpoint(tmp_path):
    # a private helper called ONLY under the lock (directly or through
    # another lock-held helper) is disciplined — the CircuitBreaker
    # _set_state shape must not be a finding
    r = run_snippet(tmp_path, """
        import threading

        class Breaker:
            def __init__(self):
                self._lock = threading.Lock()
                self._state = "closed"

            def _set_state(self, s):
                self._state = s

            def _maybe_open(self):
                self._set_state("open")

            def trip(self):
                with self._lock:
                    self._maybe_open()

            def reset(self):
                with self._lock:
                    self._set_state("closed")
    """)
    assert rules_of(r) == []


def test_ts004_unprotected_attrs_and_lockless_classes_ignored(tmp_path):
    r = run_snippet(tmp_path, """
        import threading

        class NoLock:
            def set(self, v):
                self.value = v

        class Flag:
            def __init__(self):
                self._lock = threading.Lock()
                self.done = False  # never touched under the lock

            def finish(self):
                self.done = True
    """)
    assert rules_of(r) == []


# --------------------------------------------------------------------------
# TS005 — broad except
# --------------------------------------------------------------------------

def test_ts005_swallowing_handler(tmp_path):
    r = run_snippet(tmp_path, """
        def load(path):
            try:
                return open(path).read()
            except Exception:
                return None
    """)
    assert rules_of(r) == ["TS005"]


def test_ts005_reraise_typed_mapping_and_counter_pass(tmp_path):
    r = run_snippet(tmp_path, """
        def a():
            try:
                work()
            except Exception:
                raise

        def b():
            try:
                work()
            except Exception as e:
                raise CheckpointCorruptError("bad") from e

        def c(reg):
            try:
                work()
            except Exception:
                reg.counter("errors_total").inc()

        def d():
            try:
                work()
            except (OSError, ValueError):
                pass  # narrow: not TS005's business
    """)
    assert rules_of(r) == []


def test_ts005_bare_except_flagged(tmp_path):
    r = run_snippet(tmp_path, """
        def f():
            try:
                work()
            except:
                pass
    """)
    assert rules_of(r) == ["TS005"]


# --------------------------------------------------------------------------
# TS006 — donation aliasing
# --------------------------------------------------------------------------

def test_ts006_donated_arg_read_after_call(tmp_path):
    r = run_snippet(tmp_path, """
        import jax

        def train(state, batch):
            step = jax.jit(update, donate_argnums=0)
            new_state = step(state, batch)
            return new_state, state.step
    """)
    assert rules_of(r) == ["TS006"]
    assert "'state'" in r.findings[0].message


def test_ts006_reassignment_clears_and_no_donation_ok(tmp_path):
    r = run_snippet(tmp_path, """
        import jax

        def loop(state, batches):
            step = jax.jit(update, donate_argnums=0)
            for b in batches:
                state = step(state, b)  # rebound every iteration
            return state

        def undonated(state, batch):
            step = jax.jit(update)
            new = step(state, batch)
            return new, state.step
    """)
    assert rules_of(r) == []


# --------------------------------------------------------------------------
# suppression + baseline + engine mechanics
# --------------------------------------------------------------------------

def test_inline_suppression_and_disable_all(tmp_path):
    r = run_snippet(tmp_path, """
        def a():
            try:
                work()
            except Exception:  # tslint: disable=TS005 — fixture: intentional
                pass

        def b():
            try:
                work()
            except Exception:  # tslint: disable=all
                pass

        def c():
            try:
                work()
            except Exception:  # tslint: disable=TS003 — wrong rule: no effect
                pass
    """)
    assert rules_of(r) == ["TS005"]
    assert r.suppressed == 2
    assert r.findings[0].scope == "c"


def test_suppression_shares_comment_with_pragma(tmp_path):
    r = run_snippet(tmp_path, """
        def f():
            try:
                work()
            except Exception:  # pragma: no cover - tslint: disable=TS005 — teardown
                pass
    """)
    assert rules_of(r) == []
    assert r.suppressed == 1


def test_baseline_round_trip(tmp_path):
    code = """
        import time

        def slow():
            t0 = time.time()
            return time.time() - t0
    """
    r = run_snippet(tmp_path, code)
    assert rules_of(r) == ["TS003"]
    bl_path = tmp_path / "baseline.json"
    write_baseline(r.findings, str(bl_path))
    baseline = load_baseline(str(bl_path))
    assert len(baseline["findings"]) == 1

    # same findings -> fully absorbed
    new, baselined, stale = match_baseline(r.findings, baseline)
    assert (len(new), baselined, stale) == (0, 1, [])

    # a NEW bug is not absorbed by the grandfathered one
    r2 = run_snippet(tmp_path, code + """
        def worse(t_start):
            return time.time() - t_start
    """)
    new, baselined, stale = match_baseline(r2.findings, baseline)
    assert baselined == 1
    assert [f.rule for f in new] == ["TS003"]
    assert new[0].scope == "worse"

    # the bug got fixed -> the baseline entry is reported stale
    new, baselined, stale = match_baseline([], baseline)
    assert (new, baselined) == ([], 0)
    assert len(stale) == 1


def test_baseline_fingerprint_survives_line_drift(tmp_path):
    base = """
        import time

        def slow():
            t0 = time.time()
            return time.time() - t0
    """
    r1 = run_snippet(tmp_path, base)
    # unrelated code added ABOVE the finding moves its line number
    r2 = run_snippet(tmp_path, "\nHEADER = 1\n\n" + textwrap.dedent(base))
    assert r1.findings[0].line != r2.findings[0].line
    assert r1.findings[0].fingerprint == r2.findings[0].fingerprint


def test_syntax_error_becomes_ts000_finding(tmp_path):
    r = run_snippet(tmp_path, "def broken(:\n    pass\n")
    assert rules_of(r) == ["TS000"]


def test_select_restricts_rules(tmp_path):
    code = """
        import time

        def f():
            t0 = time.time()
            try:
                return time.time() - t0
            except Exception:
                return None
    """
    r = run_snippet(tmp_path, code, select={"TS005"})
    assert rules_of(r) == ["TS005"]


# --------------------------------------------------------------------------
# CLI contract
# --------------------------------------------------------------------------

def _cli(args, cwd=REPO_ROOT):
    return subprocess.run(
        [sys.executable, "-m", "tools.tslint", *args],
        cwd=cwd, capture_output=True, text=True,
        env={**os.environ, "PYTHONPATH": REPO_ROOT})


def test_cli_exits_nonzero_on_fixture_bug(tmp_path):
    bug = tmp_path / "bug.py"
    bug.write_text(textwrap.dedent("""
        import time

        def f(t0):
            return time.time() - t0
    """), encoding="utf-8")
    proc = _cli(["--no-baseline", "--root", str(tmp_path), str(bug)])
    assert proc.returncode == 1
    assert "TS003" in proc.stdout


def test_cli_exits_zero_on_clean_file(tmp_path):
    ok = tmp_path / "ok.py"
    ok.write_text("import time\n\n\ndef f():\n    return time.monotonic()\n",
                  encoding="utf-8")
    proc = _cli(["--no-baseline", "--root", str(tmp_path), str(ok)])
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_write_baseline_then_clean(tmp_path):
    bug = tmp_path / "bug.py"
    bug.write_text(textwrap.dedent("""
        import time

        def f(t0):
            return time.time() - t0
    """), encoding="utf-8")
    bl = tmp_path / "bl.json"
    proc = _cli(["--root", str(tmp_path), "--baseline", str(bl),
                 "--write-baseline", str(bug)])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    proc = _cli(["--root", str(tmp_path), "--baseline", str(bl), str(bug)])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "1 baselined" in proc.stdout


def test_cli_json_format(tmp_path):
    bug = tmp_path / "bug.py"
    bug.write_text("def f():\n    try:\n        g()\n    except Exception:\n"
                   "        pass\n", encoding="utf-8")
    proc = _cli(["--no-baseline", "--format", "json", "--root",
                 str(tmp_path), str(bug)])
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert payload["new"][0]["rule"] == "TS005"
    assert payload["new"][0]["fingerprint"]


def test_cli_missing_path_is_usage_error(tmp_path):
    proc = _cli(["--no-baseline", "--root", str(tmp_path), "nope.py"])
    assert proc.returncode == 2


def test_cli_missing_explicit_baseline_is_usage_error(tmp_path):
    ok = tmp_path / "ok.py"
    ok.write_text("X = 1\n", encoding="utf-8")
    proc = _cli(["--root", str(tmp_path), "--baseline",
                 str(tmp_path / "gone.json"), str(ok)])
    assert proc.returncode == 2
    assert "baseline not found" in proc.stderr


def test_cli_list_rules():
    proc = _cli(["--list-rules"])
    assert proc.returncode == 0
    for rule in RULES:
        assert rule.id in proc.stdout


# --------------------------------------------------------------------------
# repo self-check (the lint.sh gate, in-process)
# --------------------------------------------------------------------------

def test_repo_is_clean_against_committed_baseline():
    result = analyze([PACKAGE], root=REPO_ROOT)
    baseline = load_baseline(os.path.join(REPO_ROOT, DEFAULT_BASELINE))
    new, baselined, stale = match_baseline(result.findings, baseline)
    assert new == [], "\n".join(f.format_text() for f in new)
    assert stale == [], (
        "baseline entries no longer match any finding — regenerate with "
        "python -m tools.tslint --write-baseline: "
        + json.dumps(stale, indent=2))


def test_committed_baseline_stays_near_empty():
    baseline = load_baseline(os.path.join(REPO_ROOT, DEFAULT_BASELINE))
    assert len(baseline["findings"]) <= 5  # ISSUE 3 acceptance bound


def test_every_rule_is_exercised_by_this_suite():
    # the per-file rules live here; the interprocedural concurrency
    # rules (TS007–TS010) are covered by tests/test_tslint_concurrency.py
    ids = {r.id for r in RULES}
    assert ids == {"TS001", "TS002", "TS003", "TS004", "TS005", "TS006"}
    from tools.tslint import ALL_RULES

    assert {r.id for r in ALL_RULES} == ids | {"TS007", "TS008", "TS009",
                                               "TS010"}


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-q"]))
