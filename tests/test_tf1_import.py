"""End-to-end TF1 checkpoint import (VERDICT r1 missing #2).

Writes a REAL TF1-format checkpoint bundle — tf.compat.v1.train.Saver
over variables carrying the exact reference graph names
(/root/reference/src/main/python/pointer-generator/model.py scopes; TF1.2
fused lstm_cell/kernel naming) — then proves checkpoint/tf1_import reads
it back into a servable parameter tree: values land on the right leaves,
conv-shaped attention tensors are squeezed, optimizer slots are skipped,
and the imported model's forward pass is identical to the source params'.
"""

import os

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

import jax  # noqa: E402

from textsummarization_on_flink_tpu.checkpoint import (  # noqa: E402
    checkpointer as ckpt_lib,
)
from textsummarization_on_flink_tpu.checkpoint import tf1_import  # noqa: E402
from textsummarization_on_flink_tpu.config import HParams  # noqa: E402
from textsummarization_on_flink_tpu.models import (  # noqa: E402
    pointer_generator as pg,
)

v1 = tf.compat.v1


def hps_tiny(**kw):
    base = dict(batch_size=2, max_enc_steps=6, max_dec_steps=5,
                min_dec_steps=1, hidden_dim=4, emb_dim=3, max_oov_buckets=2,
                vocab_size=10, coverage=True)
    base.update(kw)
    return HParams(**base)


def _lookup(tree, path):
    node = tree
    for p in path:
        node = node[p]
    return node


def _unsqueeze_for_tf1(name, arr):
    """Back to the reference's conv shapes: W_h [2H,D]->[1,1,2H,D]
    (attention_decoder.py:66), w_c [D]->[1,1,1,D] (:105)."""
    if name.endswith("/W_h"):
        return arr[None, None, :, :]
    if name.endswith("/coverage/w_c"):
        return arr[None, None, None, :]
    return arr


def params_to_tf1_vars(params):
    """Inverse of TF1_NAME_MAP: our pytree rendered as the reference's
    TF1 {name: ndarray} layout."""
    out = {}
    for name, (path, _squeeze) in tf1_import.TF1_NAME_MAP.items():
        try:
            arr = np.asarray(_lookup(params, path))
        except KeyError:
            continue  # e.g. coverage params absent
        out[name] = _unsqueeze_for_tf1(name, arr)
    return out


def write_tf1_bundle(tf1_vars, directory, with_slots=True):
    """A genuine TF1 checkpoint bundle via compat.v1 Saver."""
    g = v1.Graph()
    with g.as_default():
        tfvars = [v1.Variable(val, name=name, dtype=tf.float32)
                  for name, val in tf1_vars.items()]
        if with_slots:  # optimizer slots + bookkeeping the import must skip
            tfvars.append(v1.Variable(
                np.zeros_like(tf1_vars["seq2seq/embedding/embedding"]),
                name="seq2seq/embedding/embedding/Adagrad",
                dtype=tf.float32))
            tfvars.append(v1.Variable(np.int64(123), name="global_step",
                                      dtype=tf.int64))
        saver = v1.train.Saver(var_list=tfvars)
        with v1.Session(graph=g) as sess:
            sess.run(v1.variables_initializer(tfvars))
            return saver.save(sess, os.path.join(directory, "model.ckpt"))


@pytest.fixture(scope="module")
def source():
    hps = hps_tiny()
    params = pg.init_params(hps, hps.vocab_size, jax.random.PRNGKey(7))
    return hps, params


def test_roundtrip_through_real_bundle(source, tmp_path):
    hps, params = source
    prefix = write_tf1_bundle(params_to_tf1_vars(params), str(tmp_path))
    imported = tf1_import.import_tf1_checkpoint(prefix)
    flat_src = jax.tree_util.tree_leaves_with_path(params)
    flat_imp = jax.tree_util.tree_flatten(imported)[0]
    assert len(flat_src) == len(flat_imp)
    for (path, leaf), got in zip(
            sorted(flat_src, key=lambda kv: str(kv[0])),
            [leaf for _, leaf in sorted(
                jax.tree_util.tree_leaves_with_path(imported),
                key=lambda kv: str(kv[0]))]):
        np.testing.assert_array_equal(np.asarray(leaf), got,
                                      err_msg=str(path))


def test_forward_identical_after_import(source, tmp_path):
    hps, params = source
    from __graft_entry__ import _example_arrays

    prefix = write_tf1_bundle(params_to_tf1_vars(params), str(tmp_path))
    imported = tf1_import.import_tf1_checkpoint(prefix)
    arrays = _example_arrays(hps, np.random.RandomState(0))
    out_src = pg.forward_train(params, hps, arrays)
    out_imp = pg.forward_train(imported, hps, arrays)
    assert np.isfinite(float(out_imp.loss))
    np.testing.assert_allclose(float(out_imp.loss), float(out_src.loss),
                               rtol=1e-6)


def test_infer_hps_from_params(source):
    hps, params = source
    got = tf1_import.infer_hps_from_params(params)
    assert (got.vocab_size, got.emb_dim, got.hidden_dim) == (10, 3, 4)
    assert got.coverage  # w_c present


def test_import_to_train_dir_is_servable(source, tmp_path):
    """bundle -> train_dir -> Checkpointer.restore: the decoder's exact
    load path (decode/decoder.py uses load_ckpt on train_dir)."""
    hps, params = source
    prefix = write_tf1_bundle(params_to_tf1_vars(params), str(tmp_path))
    train_dir = str(tmp_path / "train")
    saved = tf1_import.import_to_train_dir(prefix, train_dir)
    assert os.path.exists(saved + ".npz") or os.path.exists(saved)
    state = ckpt_lib.Checkpointer(train_dir, hps=hps).restore()
    assert state is not None
    np.testing.assert_array_equal(
        np.asarray(state.params["embedding"]), np.asarray(params["embedding"]))
    # Adagrad accumulators re-initialized, not imported
    accs = jax.tree_util.tree_leaves(state.opt_state.accumulators)
    assert all(np.allclose(np.asarray(a), hps.adagrad_init_acc) for a in accs)


def test_noncoverage_bundle_gets_fresh_coverage_params(tmp_path):
    hps = hps_tiny(coverage=False)
    params = pg.init_params(hps, hps.vocab_size, jax.random.PRNGKey(3))
    tf1_vars = params_to_tf1_vars(params)
    # a checkpoint trained WITHOUT coverage has no w_c variable
    del tf1_vars["seq2seq/decoder/attention_decoder/coverage/w_c"]
    prefix = write_tf1_bundle(tf1_vars, str(tmp_path))
    train_dir = str(tmp_path / "train")
    tf1_import.import_to_train_dir(prefix, train_dir,
                                   hps=HParams(coverage=True))
    state = ckpt_lib.Checkpointer(train_dir).restore()
    assert "w_c" in state.params["decoder"]["attention"]


def test_missing_required_variable_raises(source, tmp_path):
    hps, params = source
    tf1_vars = params_to_tf1_vars(params)
    del tf1_vars["seq2seq/output_projection/w"]
    prefix = write_tf1_bundle(tf1_vars, str(tmp_path), with_slots=False)
    with pytest.raises(KeyError, match="output_projection"):
        tf1_import.import_tf1_checkpoint(prefix)


def test_unmapped_variable_strict_vs_lenient(source, tmp_path):
    hps, params = source
    tf1_vars = params_to_tf1_vars(params)
    tf1_vars["some/new/variable"] = np.zeros((2, 2), np.float32)
    prefix = write_tf1_bundle(tf1_vars, str(tmp_path), with_slots=False)
    with pytest.raises(KeyError, match="unmapped"):
        tf1_import.import_tf1_checkpoint(prefix, strict=True)
    imported = tf1_import.import_tf1_checkpoint(prefix, strict=False)
    assert "embedding" in imported


def test_rouge_anchor_harness_end_to_end(source, tmp_path):
    """scripts/rouge_anchor.py runs the full pipeline — synthetic TF1
    bundle -> import -> beam decode over a chunked test split -> ROUGE —
    so only the Google-Drive fetch is untested offline."""
    import importlib.util
    import json

    from textsummarization_on_flink_tpu.data.chunks import write_chunked
    from textsummarization_on_flink_tpu.data.tfexample import Example

    hps, params = source
    prefix = write_tf1_bundle(params_to_tf1_vars(params), str(tmp_path))

    words = ["the", "cat", "sat", "on", "mat", "dog", "ran", "."]
    vocab_path = tmp_path / "vocab"
    vocab_path.write_text("".join(f"{w} {100 - i}\n"
                                  for i, w in enumerate(words)))
    exs = [Example().set_bytes("article", f"the cat sat on mat {i} .".encode())
           .set_bytes("abstract", b"<s> the cat sat . </s>")
           for i in range(4)]
    write_chunked(str(tmp_path / "test"), exs, chunk_size=2)

    spec = importlib.util.spec_from_file_location(
        "rouge_anchor", os.path.join(os.path.dirname(__file__), "..",
                                     "scripts", "rouge_anchor.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rc = mod.main([
        "--bundle", prefix,
        "--data", str(tmp_path / "test_*"),
        "--vocab", str(vocab_path),
        "--log_root", str(tmp_path / "rouge_run"),
        "--max_articles", "4",
        "--tolerance", "100",  # random weights: only the plumbing is under test
    ])
    assert rc == 0
    # ROUGE_results.txt written in the decode dir (decode.py:280-301 parity)
    found = list((tmp_path / "rouge_run").rglob("ROUGE_results.txt"))
    assert found


def test_rouge_anchor_real_artifacts_gated(tmp_path):
    """Full ROUGE-vs-anchor run against the REAL pretrained bundle and
    CNN/DM test split (VERDICT r1 #4's gated slow test).  The artifacts
    come from scripts/download_data.sh + scripts/download_model.sh; on
    hosts without them (e.g. zero-egress CI) this skips.  With them, the
    imported checkpoint must land within 0.5 ROUGE-L F1 of the See et
    al. anchor on a 256-article slice.  Opt in with TS_RUN_ANCHOR=1 —
    the decode takes a long time, so artifact presence alone must not
    drag it into a routine pytest run."""
    import glob as glob_mod
    import importlib.util

    if os.environ.get("TS_RUN_ANCHOR") != "1":
        pytest.skip("set TS_RUN_ANCHOR=1 to run the slow ROUGE anchor test")
    repo = os.path.join(os.path.dirname(__file__), "..")
    bundle = os.path.join(repo, "log", "pretrained_model_tf1.2.1",
                          "model-238410")
    data = os.path.join(repo, "data", "cnn-dailymail", "finished_files",
                        "chunked", "test_*")
    vocab = os.path.join(repo, "data", "cnn-dailymail", "finished_files",
                         "vocab")
    if not (os.path.exists(bundle + ".index") and glob_mod.glob(data)
            and os.path.exists(vocab)):
        pytest.skip("pretrained bundle / CNN-DM artifacts not on disk "
                    "(run scripts/download_data.sh + download_model.sh)")

    spec = importlib.util.spec_from_file_location(
        "rouge_anchor", os.path.join(repo, "scripts", "rouge_anchor.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    rc = mod.main([
        "--bundle", bundle,
        "--data", data,
        "--vocab", vocab,
        "--log_root", str(tmp_path / "anchor_run"),
        "--max_articles", "256",
        "--tolerance", "0.5",
    ])
    assert rc == 0
