"""Elastic serving fleet (ISSUE 13; SERVING.md "Elastic fleet"):
FleetRouter routing, rotation health, hedging exactly-once, replica
kill + typed requeue, rolling hot-swap (including the injected
ckpt.load failure satellite), and the cross-replica trace timeline.

The virtual-time SLO scenarios (swap p99 ratio, hedge win/rate gate,
the kill chaos gate) live in tests/test_serve_slo.py against the
committed SERVE_SLO.json "fleet" section; this file covers the router
mechanics at unit granularity plus the pieces that need a real decoder
(hot-swap failure) or a real events.jsonl (trace reconstruction)."""

import glob
import os
import threading

import pytest

from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.data.vocab import Vocab
from textsummarization_on_flink_tpu.obs import Registry, flightrec
from textsummarization_on_flink_tpu.obs import http as obs_http
from textsummarization_on_flink_tpu.resilience.policy import CircuitBreaker
from textsummarization_on_flink_tpu.serve.errors import (
    ReplicaKilledError,
    ServeClosedError,
    ServeOverloadError,
)
from textsummarization_on_flink_tpu.serve.fleet import FleetRouter, _Routed
from textsummarization_on_flink_tpu.serve.queue import ServeFuture
from textsummarization_on_flink_tpu.serve.router import pick_replica

WORDS = ["the", "cat", "sat", "on", "mat", "."]


class _Clock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def now(self) -> float:
        return self.t


class FakeReplicaServer:
    """ServingServer surface the router consumes, with MANUALLY
    resolvable futures — hedge/requeue interleavings become exact."""

    def __init__(self, registry=None, load=0, admission="closed"):
        self.registry = registry if registry is not None else Registry()
        self._load = load
        self.admission = admission
        self.submits = []  # [(uuid, future)]
        self.killed = False
        self.started = False
        self.swaps = 0

    # -- router surface --
    def stats(self):
        return {"queue_depth": self._load, "serve_mode": "continuous",
                "admission": self.admission}

    def load(self):
        return self._load + len([f for _, f in self.submits
                                 if not f.done()])

    def submit(self, article, uuid="", reference="", block=False,
               timeout=None, tier="", trace=None, tenant=""):
        if self.killed:
            raise ServeClosedError("killed")
        fut = ServeFuture(uuid, registry=self.registry)
        fut.trace = trace
        self.submits.append((uuid, fut))
        return fut

    def kill(self, error=None):
        self.killed = True
        err = error or ReplicaKilledError("killed")
        n = 0
        for _, f in self.submits:
            if not f.done():
                f._reject(err)
                n += 1
        return n

    def start(self):
        self.started = True
        return self

    def stop(self, timeout=None):
        for _, f in self.submits:
            if not f.done():
                f._reject(ServeClosedError("stopped"))

    def idle(self):
        return all(f.done() for _, f in self.submits)

    def hot_swap(self):
        self.swaps += 1
        return True

    # test sugar
    def resolve(self, uuid, result="ok"):
        for u, f in self.submits:
            if u == uuid and not f.done():
                f._resolve(result)
                return
        raise AssertionError(f"no pending submit {uuid!r}")


def make_fleet(n=3, hedge_ms=0.0, ratio=0.5, clock=None, registry=None,
               reset_secs=1.0, faults=None, **hps_kw):
    clock = clock or _Clock()
    hps = HParams(serve_hedge_ms=hedge_ms, serve_hedge_max_ratio=ratio,
                  serve_replicas=n, **hps_kw)
    servers = [FakeReplicaServer() for _ in range(n)]
    router = FleetRouter(servers, hps,
                         registry=registry or Registry(),
                         clock=clock.now, replica_reset_secs=reset_secs,
                         faults=faults)
    return router, servers, clock


class TestRouting:
    def test_least_loaded_pick_is_stable(self):
        router, servers, _ = make_fleet(3)
        servers[0]._load, servers[1]._load, servers[2]._load = 2, 0, 0
        h = pick_replica(router.replicas())
        assert h.rid == "r1"  # least loaded; earliest wins the tie

    def test_submit_routes_and_resolves_through_router_future(self):
        router, servers, _ = make_fleet(2)
        fut = router.submit("a b", uuid="u0")
        assert not fut.done()
        sub = [s for s in servers if s.submits]
        assert len(sub) == 1
        sub[0].resolve("u0", result="res")
        assert fut.result(timeout=1) == "res"
        assert router.registry.counter(
            "serve/fleet_submitted_total").value == 1

    def test_no_replica_in_rotation_sheds_typed(self):
        router, servers, _ = make_fleet(2)
        for h in router.replicas():
            h.killed = True
        with pytest.raises(ServeOverloadError):
            router.submit("a", uuid="u0")

    def test_all_replicas_closed_surfaces_closed_not_overload(self):
        """A terminal ServeClosedError from the replicas must reach the
        caller AS closed (stop submitting), not be masked as retryable
        overload."""
        router, servers, _ = make_fleet(2)
        for s in servers:
            s.killed = True  # submit raises ServeClosedError
        with pytest.raises(ServeClosedError):
            router.submit("a", uuid="u0")

    def test_draining_replica_receives_no_new_requests(self):
        router, servers, _ = make_fleet(2)
        router.handle("r0").draining = True
        for i in range(4):
            router.submit("a", uuid=f"u{i}")
        assert not servers[0].submits
        assert len(servers[1].submits) == 4

    def test_overloaded_replica_leaves_rotation_and_request_reroutes(self):
        router, servers, _ = make_fleet(2)

        class Full(FakeReplicaServer):
            def submit(self, *a, **kw):
                raise ServeOverloadError("queue full")

        full = Full()
        router.replicas()[0].server = full
        servers[0] = full
        full._load = -10  # force it to be picked first
        fut = router.submit("a", uuid="u0")
        # the full replica recorded a rotation-breaker failure and the
        # request landed on the healthy one
        assert router.handle("r0").breaker.state == CircuitBreaker.OPEN
        assert len(servers[1].submits) == 1
        servers[1].resolve("u0")
        assert fut.result(timeout=1) == "ok"


class TestRotationHealth:
    def _stale_board(self, reg, clock):
        board = obs_http.HeartbeatBoard(clock=clock.now)
        reg.heartbeats = board
        return board

    def test_stale_heartbeat_removes_then_probe_readmits(self):
        clock = _Clock()
        router, servers, _ = make_fleet(2, clock=clock, reset_secs=5.0)
        board = self._stale_board(servers[0].registry, clock)
        board.beat("serve/dispatch", period=1.0)
        router.tick()
        assert router.handle("r0").in_rotation()
        # the heartbeat goes stale (> 3x its declared period)
        clock.t = 10.0
        router.tick()
        assert not router.handle("r0").in_rotation()
        assert router.in_rotation() == 1
        # fresh beats alone do not readmit before the breaker reset
        board.beat("serve/dispatch", period=1.0)
        router.tick()
        assert not router.handle("r0").in_rotation()
        # past reset_secs the HALF_OPEN health probe readmits
        clock.t = 16.0
        board.beat("serve/dispatch", period=1.0)
        router.tick()
        assert router.handle("r0").in_rotation()
        assert router.in_rotation() == 2

    def test_still_stale_probe_reopens(self):
        clock = _Clock()
        router, servers, _ = make_fleet(2, clock=clock, reset_secs=5.0)
        board = self._stale_board(servers[0].registry, clock)
        board.beat("serve/dispatch", period=1.0)
        clock.t = 10.0
        router.tick()  # removed
        clock.t = 16.0  # probe window, but the heartbeat is STILL stale
        router.tick()
        assert not router.handle("r0").in_rotation()
        assert router.handle("r0").breaker.state == CircuitBreaker.OPEN

    def test_open_admission_breaker_is_unhealthy(self):
        router, servers, _ = make_fleet(2)
        servers[0].admission = "open"
        router.tick()
        assert not router.handle("r0").in_rotation()


class TestHedging:
    def test_hedge_first_wins_and_loser_is_discarded(self):
        clock = _Clock()
        router, servers, _ = make_fleet(2, hedge_ms=50.0, ratio=1.0,
                                        clock=clock)
        fut = router.submit("a", uuid="u0")
        primary = [s for s in servers if s.submits][0]
        loser = primary
        clock.t = 0.1  # 100 ms > the 50 ms budget
        router.tick()
        reg = router.registry
        assert reg.counter("serve/hedges_total").value == 1
        twin = [s for s in servers if s.submits and s is not primary][0]
        # the twin resolves first: the router future resolves ONCE with
        # its result and counts the win
        twin.resolve("u0", result="twin")
        assert fut.result(timeout=1) == "twin"
        assert reg.counter("serve/hedge_wins_total").value == 1
        # the straggling primary finishing later is discarded, not a
        # double resolution
        loser.resolve("u0", result="late")
        assert fut.result(timeout=1) == "twin"

    def test_primary_win_is_not_a_hedge_win(self):
        clock = _Clock()
        router, servers, _ = make_fleet(2, hedge_ms=50.0, ratio=1.0,
                                        clock=clock)
        fut = router.submit("a", uuid="u0")
        primary = [s for s in servers if s.submits][0]
        clock.t = 0.1
        router.tick()
        primary.resolve("u0", result="primary")
        assert fut.result(timeout=1) == "primary"
        assert router.registry.counter("serve/hedge_wins_total").value == 0
        assert router.registry.counter("serve/hedges_total").value == 1

    def test_hedge_rate_ceiling_suppresses(self):
        clock = _Clock()
        # ratio 0.5 over 2 submissions = at most 1 hedge
        router, servers, _ = make_fleet(3, hedge_ms=50.0, ratio=0.5,
                                        clock=clock)
        f0 = router.submit("a", uuid="u0")
        f1 = router.submit("a", uuid="u1")
        clock.t = 0.1
        router.tick()
        reg = router.registry
        assert reg.counter("serve/hedges_total").value == 1
        assert reg.counter("serve/hedge_suppressed_total").value == 1
        for s in servers:
            for u, f in list(s.submits):
                if not f.done():
                    s.resolve(u)
        assert f0.result(timeout=1) and f1.result(timeout=1)

    def test_failed_hedge_submit_does_not_burn_the_hedge(self):
        """A twin that refuses the hedge submit (queue full) must leave
        the request hedge-ELIGIBLE: once the twin's rotation probe
        readmits it, the next scan buys the hedge that failed before."""
        clock = _Clock()
        router, servers, _ = make_fleet(2, hedge_ms=50.0, ratio=1.0,
                                        clock=clock, reset_secs=5.0)

        class Moody(FakeReplicaServer):
            reject = True

            def submit(self, *a, **kw):
                if self.reject:
                    raise ServeOverloadError("queue full")
                return super().submit(*a, **kw)

        moody = Moody()
        router.replicas()[1].server = moody
        servers[1] = moody
        fut = router.submit("a", uuid="u0")
        assert servers[0].submits  # primary landed on the good replica
        clock.t = 0.1
        router.tick()  # hedge attempt fails: twin refuses the submit
        reg = router.registry
        assert reg.counter("serve/hedges_total").value == 0
        # the twin's refusal also took it out of rotation; readmit it
        moody.reject = False
        clock.t = 6.0  # past the rotation breaker's reset window
        router.tick()  # health probe readmits + the scan re-hedges
        assert reg.counter("serve/hedges_total").value == 1
        assert len(moody.submits) == 1
        moody.resolve("u0", result="twin")
        assert fut.result(timeout=1) == "twin"
        assert reg.counter("serve/hedge_wins_total").value == 1

    def test_hedging_off_by_default(self):
        clock = _Clock()
        router, servers, _ = make_fleet(2, hedge_ms=0.0, clock=clock)
        router.submit("a", uuid="u0")
        clock.t = 99.0
        router.tick()
        assert router.registry.counter("serve/hedges_total").value == 0


class TestKillAndRequeue:
    def test_kill_requeues_on_survivor_exactly_once(self):
        router, servers, _ = make_fleet(2, registry=Registry())
        fut = router.submit("a", uuid="u0")
        primary = [s for s in servers if s.submits][0]
        survivor = [s for s in servers if s is not primary][0]
        rid = [h.rid for h in router.replicas()
               if h.server is primary][0]
        router.kill_replica(rid)
        reg = router.registry
        assert reg.counter("serve/replica_kills_total").value == 1
        assert reg.counter("serve/requeued_total").value == 1
        assert len(survivor.submits) == 1
        survivor.resolve("u0", result="requeued-res")
        assert fut.result(timeout=1) == "requeued-res"

    def test_whole_fleet_dead_rejects_typed(self):
        router, servers, _ = make_fleet(2)
        fut = router.submit("a", uuid="u0")
        for h in router.replicas():
            router.kill_replica(h.rid)
        with pytest.raises(ReplicaKilledError):
            fut.result(timeout=1)

    def test_kill_is_idempotent_and_never_kills_twice(self):
        router, servers, _ = make_fleet(2)
        router.kill_replica("r0")
        assert router.kill_replica("r0") == 0
        assert router.registry.counter(
            "serve/replica_kills_total").value == 1

    def test_chaos_point_kills_most_loaded_but_never_last(self):
        from textsummarization_on_flink_tpu.resilience import faultinject

        plan = faultinject.FaultPlan(
            faultinject.parse("serve.replica_kill:1.0:0:3"),
            registry=Registry())
        router, servers, _ = make_fleet(2, faults=plan)
        servers[1]._load = 5
        router.tick()  # fire 1: kills the loaded r1
        assert router.handle("r1").killed
        assert not router.handle("r0").killed
        router.tick()  # fire 2: refuses to kill the last replica
        router.tick()  # fire 3: same
        assert not router.handle("r0").killed
        assert router.registry.counter(
            "serve/replica_kills_total").value == 1

    def test_replica_kill_triggers_flight_dump(self, tmp_path):
        reg = Registry()
        flightrec.install_flight_recorder(reg, str(tmp_path), capacity=8)
        router, servers, _ = make_fleet(2, registry=reg)
        router.tick()  # leave at least one fleet_tick frame behind
        router.kill_replica("r0")
        dumps = glob.glob(str(tmp_path / "flight_replica_kill*.jsonl"))
        assert len(dumps) == 1


class TestRoutedExactlyOnce:
    def _routed(self, uuid="u0"):
        return _Routed(uuid, "a", "", "", ServeFuture(uuid, Registry()),
                       None, submit_t=0.0)

    def test_error_defers_while_a_twin_is_outstanding(self):
        r = self._routed()
        r.add_outstanding()
        r.add_outstanding()
        assert not r.offer_error(RuntimeError("primary died"))
        assert not r.future.done()
        assert r.offer_result("twin")
        assert r.future.result(timeout=1) == "twin"

    def test_last_error_standing_rejects_once(self):
        r = self._routed()
        r.add_outstanding()
        r.add_outstanding()
        r.offer_error(RuntimeError("one"))
        assert r.offer_error(RuntimeError("two"))
        with pytest.raises(RuntimeError, match="two"):
            r.future.result(timeout=1)

    def test_second_success_is_discarded(self):
        r = self._routed()
        r.add_outstanding()
        r.add_outstanding()
        assert r.offer_result("first")
        assert not r.offer_result("second")
        assert r.future.result(timeout=1) == "first"

    def test_drop_after_deferred_error_settles_instead_of_hanging(self):
        """The requeue race: a replacement attempt that errors in the
        window between its registration and the dead attempt's
        drop_outstanding left a phantom slot deferring the error — the
        drop must settle the future, never leave it hanging."""
        r = self._routed()
        r.add_outstanding()      # the dead attempt (kill in flight)
        r.add_outstanding()      # its requeued replacement
        # the replacement fails BEFORE the dead slot is retired: the
        # error defers against the phantom outstanding attempt
        assert not r.offer_error(RuntimeError("survivor rejected it"))
        assert not r.future.done()
        r.drop_outstanding()     # retiring the phantom must settle
        assert r.future.done()
        with pytest.raises(RuntimeError, match="survivor rejected"):
            r.future.result(timeout=1)

    def test_concurrent_offers_resolve_exactly_once(self):
        r = self._routed()
        wins = []
        n = 8
        for _ in range(n):
            r.add_outstanding()
        barrier = threading.Barrier(n)

        def offer(i):
            barrier.wait()
            if r.offer_result(f"res{i}"):
                wins.append(i)

        threads = [threading.Thread(target=offer, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(wins) == 1
        assert r.future.result(timeout=1) == f"res{wins[0]}"


class TestMicrobatchDrainAccounting:
    def test_coalescing_group_counts_as_in_flight(self):
        """The rolling-swap drain predicate must see requests the
        micro-batcher already popped off the queue but has not yet
        dispatched (the coalescing window): queue-empty alone is a
        false idle."""
        from tests.test_serve import StubDecoder
        from textsummarization_on_flink_tpu.serve.server import ServingServer

        vocab = Vocab(words=WORDS)
        hps = HParams(mode="decode", batch_size=4, vocab_size=vocab.size(),
                      max_enc_steps=8, max_dec_steps=4, beam_size=2,
                      min_dec_steps=1, max_oov_buckets=4,
                      serve_max_wait_ms=0.0, serve_max_queue=8)
        server = ServingServer(hps, vocab, decoder=StubDecoder(),
                               registry=Registry())
        assert server.idle()
        server.submit("the cat .", uuid="u0")
        server.submit("the mat .", uuid="u1")
        assert not server.idle() and server.load() == 2
        group = server._batcher.next_group(poll=0.01)
        assert len(group) == 2
        # the queue is empty now, but the popped group is ADMITTED work
        assert server.pending() == 0
        assert not server.idle(), "coalesced group invisible to idle()"
        assert server.load() == 2
        server._batcher.end_group()
        assert server.idle()


class TestRollingSwap:
    def test_swap_visits_every_replica_one_at_a_time(self):
        router, servers, _ = make_fleet(3)
        router.start_rolling_swap()
        seen_out = []
        for _ in range(12):
            if router.swap_active():
                out = [h.rid for h in router.replicas() if h.draining]
                assert len(out) <= 1, "rolling swap drained two at once"
                seen_out.extend(out)
            router.tick()
        assert not router.swap_active()
        assert [s.swaps for s in servers] == [1, 1, 1]
        assert router.in_rotation() == 3
        assert router.registry.counter(
            "serve/fleet_swaps_total").value == 3

    def test_swap_waits_for_drain(self):
        router, servers, _ = make_fleet(2)
        fut = router.submit("a", uuid="u0")
        primary = [s for s in servers if s.submits][0]
        router.start_rolling_swap()
        for _ in range(4):
            router.tick()
        # r0 first in order; if it holds the request it cannot swap yet
        if primary is servers[0]:
            assert servers[0].swaps == 0
            primary.resolve("u0")
            for _ in range(6):
                router.tick()
        else:
            primary.resolve("u0")
            for _ in range(6):
                router.tick()
        assert not router.swap_active()
        assert [s.swaps for s in servers] == [1, 1]
        assert fut.done()

    def test_double_start_raises(self):
        router, _, _ = make_fleet(2)
        router.start_rolling_swap()
        with pytest.raises(RuntimeError, match="already in progress"):
            router.start_rolling_swap()

    def test_killed_replica_is_skipped_mid_swap(self):
        router, servers, _ = make_fleet(3)
        router.start_rolling_swap()
        router.kill_replica("r1")
        for _ in range(12):
            router.tick()
        assert not router.swap_active()
        assert servers[0].swaps == 1 and servers[2].swaps == 1
        assert servers[1].swaps == 0


class TestHotSwapFailureMidServe:
    """The ISSUE-13 satellite: inject a ``ckpt.load`` fault during a
    router-orchestrated swap — the replica must keep serving on its old
    snapshot, count ``serve/ckpt_reload_errors_total``, and STAY IN
    ROTATION."""

    def test_injected_ckpt_fault_keeps_replica_serving_old_snapshot(
            self, tmp_path):
        from textsummarization_on_flink_tpu.checkpoint import (
            checkpointer as ckpt_lib,
        )
        from textsummarization_on_flink_tpu.decode.decoder import (
            BeamSearchDecoder,
        )
        from textsummarization_on_flink_tpu.resilience import faultinject
        from textsummarization_on_flink_tpu.serve.server import ServingServer
        from textsummarization_on_flink_tpu.train import trainer as t_lib

        vocab = Vocab(words=WORDS)
        hps = HParams(mode="decode", batch_size=2, hidden_dim=8, emb_dim=6,
                      vocab_size=vocab.size(), max_enc_steps=8,
                      max_dec_steps=4, beam_size=2, min_dec_steps=1,
                      max_oov_buckets=4, serve_max_wait_ms=5.0,
                      serve_max_queue=16, serve_buckets="8")
        train_dir = str(tmp_path / "train")
        ck = ckpt_lib.Checkpointer(train_dir, hps=hps)
        state = t_lib.init_train_state(hps, vocab.size(), seed=0)
        ck.save(state)
        reg = Registry()
        with obs.use_registry(reg):
            decoder = BeamSearchDecoder(
                hps, vocab, batcher=None, train_dir=train_dir,
                decode_root=str(tmp_path / "dec"), max_ckpt_retries=0)
            server = ServingServer(hps, vocab, decoder=decoder,
                                   registry=reg)
            router = FleetRouter([server], hps, registry=Registry())
            router.start()
            try:
                assert router.submit(
                    "the cat sat .", uuid="u0").result(timeout=120)
                ckpt_before = decoder._params_snapshot()[1]
                # a NEWER checkpoint lands; its load is chaos-killed
                ck.save(state._replace(step=state.step + 5))
                plan = faultinject.FaultPlan(
                    faultinject.parse("ckpt.load:1.0:0:8"), registry=reg)
                with faultinject.use_plan(plan):
                    router.rolling_swap(timeout=60.0)
                # the swap failed but degraded the UPGRADE, not the fleet
                assert reg.counter(
                    "serve/ckpt_reload_errors_total").value == 1
                assert decoder._params_snapshot()[1] == ckpt_before
                assert router.handle("r0").in_rotation()
                assert router.submit(
                    "the mat .", uuid="u1").result(timeout=120)
                # with the chaos unarmed, the next swap picks up the
                # new checkpoint (the failure was the fault, not the
                # orchestration)
                router.rolling_swap(timeout=60.0)
                assert decoder._params_snapshot()[1] != ckpt_before
            finally:
                router.stop()


class TestCrossReplicaTrace:
    """ISSUE-13 acceptance: one request's cross-replica timeline
    (enqueue -> route -> kill -> requeued -> route -> ... -> resolve)
    reconstructs from the unified events.jsonl via
    scripts/trace_summary.py --request."""

    def test_requeued_request_timeline_reconstructs(self, tmp_path):
        import importlib
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "scripts"))
        trace_summary = importlib.import_module("trace_summary")

        from textsummarization_on_flink_tpu.obs.export import (
            install_event_sink,
        )
        from tests.test_serve import StubEngine
        from textsummarization_on_flink_tpu.serve.server import ServingServer

        vocab = Vocab(words=WORDS)
        hps = HParams(mode="decode", batch_size=2, hidden_dim=8, emb_dim=6,
                      vocab_size=vocab.size(), max_enc_steps=8,
                      max_dec_steps=4, beam_size=2, min_dec_steps=1,
                      max_oov_buckets=4, serve_max_queue=16,
                      serve_mode="continuous", serve_slots=1,
                      serve_refill_chunk=1)
        fleet_reg = Registry()
        sink = install_event_sink(fleet_reg, str(tmp_path))
        servers = [
            ServingServer(hps, vocab, decoder=_NullD(),
                          engine=StubEngine(slots=1,
                                            chunks_for=lambda ex: 3),
                          registry=Registry())
            for _ in range(2)]
        router = FleetRouter(servers, hps, registry=fleet_reg)
        fut = router.submit("the cat sat .", uuid="u7")
        primary = next(s for s in servers if s.pending())
        rid = [h.rid for h in router.replicas()
               if h.server is primary][0]
        # one tick makes it RESIDENT (mid-decode), then the kill
        primary.tick_once(poll=0.0)
        router.kill_replica(rid)
        survivor = [s for s in servers if s is not primary][0]
        for _ in range(8):
            if fut.done():
                break
            survivor.tick_once(poll=0.0)
        assert fut.result(timeout=1).uuid == "u7"
        router.stop()
        sink.close()
        path = os.path.join(str(tmp_path), "events.jsonl")
        tl = trace_summary.request_timeline([path], "u7")
        names = [e["event"] for e in tl["events"]]
        # the cross-replica story, in order: routed to the victim,
        # admitted, died typed, requeued to the survivor, re-routed,
        # re-admitted, finished, and resolved EXACTLY ONCE at the end
        assert names[0] == "route"
        assert "requeued" in names
        i_requeue = names.index("requeued")
        assert "admit" in names[:i_requeue], "victim never admitted it"
        assert "route" in names[i_requeue:], "no re-route after requeue"
        assert "finish" in names[i_requeue:]
        assert names[-1] == "resolve"
        # ONE trace id stitches the whole cross-replica lifecycle
        assert len(tl["trace_ids"]) == 1
        # phases close: total runs enqueue -> the TERMINAL resolve
        assert tl["phases"]["total_ms"] >= 0.0


class _NullD:
    def maybe_reload_checkpoint(self, last):
        return last


class TestFleetTelemetryPlane:
    """ISSUE 15: replica identity threading and the /fleet/* source
    map the router wires at construction."""

    def test_replica_ids_threaded_to_replica_registries(self):
        router, servers, _ = make_fleet(n=3)
        for i, s in enumerate(servers):
            assert s.registry.replica_id == f"r{i}"
        # the router's own registry is the fleet view, not a replica
        assert router.registry.replica_id == ""

    def test_fleet_sources_wired_on_router_and_replicas(self):
        router, servers, _ = make_fleet(n=2)
        srcs = router.registry.fleet_sources()
        # the router's own registry rides first: the fleet-level cost
        # accounting (door hits/sheds, hedges) lives there
        assert list(srcs) == ["router", "r0", "r1"]
        assert srcs["router"] is router.registry
        assert srcs["r0"] is servers[0].registry
        # replicas can answer /fleet/* too (whoever owns the http port)
        assert servers[1].registry.fleet_sources() == srcs

    def test_fleet_sources_dedupe_shared_registry(self):
        """bench --serve-replicas wiring: router and replicas sharing
        ONE registry must merge as one source, not N copies (a /fleet
        scrape would otherwise report every counter at Nx truth)."""
        shared = Registry()
        servers = [FakeReplicaServer(registry=shared) for _ in range(3)]
        router = FleetRouter(servers, HParams(serve_replicas=3),
                             registry=shared, clock=_Clock().now)
        shared.counter("serve/completed_total").inc(5)
        srcs = router.registry.fleet_sources()
        assert list(srcs) == ["router"]
        from textsummarization_on_flink_tpu.obs.registry import (
            merge_fleet_snapshot,
        )

        snap = merge_fleet_snapshot(srcs)
        assert snap["metrics"]["serve/completed_total"]["value"] == 5.0

    def test_request_events_carry_replica_tag(self):
        from textsummarization_on_flink_tpu.obs.export import MemorySink

        _, servers, _ = make_fleet(n=2)
        reg = servers[0].registry
        sink = MemorySink()
        reg.event_sink = sink
        obs.spans.request_event(reg, "enqueue", None, "u1")
        (rec,) = sink.records()
        assert rec["replica"] == "r0"

    def test_fleet_metrics_merge_sums_replica_counters(self):
        router, servers, _ = make_fleet(n=2)
        servers[0].registry.counter("serve/completed_total").inc(2)
        servers[1].registry.counter("serve/completed_total").inc(5)
        from textsummarization_on_flink_tpu.obs.registry import (
            merge_fleet_snapshot,
        )

        snap = merge_fleet_snapshot(router.registry.fleet_sources())
        assert snap["metrics"]["serve/completed_total"]["value"] == 7.0

    def test_hedge_spend_labeled_by_tenant(self):
        clock = _Clock()
        router, servers, _ = make_fleet(2, hedge_ms=50.0, ratio=1.0,
                                        clock=clock)
        fut = router.submit("a", uuid="u0", tenant="acme")
        clock.t = 0.1
        router.tick()
        reg = router.registry
        assert reg.counter("serve/hedges_total").labels(
            tenant="acme").value == 1
        twin = [s for s in servers if s.submits][-1]
        twin.resolve("u0", result="twin")
        assert fut.result(timeout=1) == "twin"
        assert reg.counter("serve/hedge_wins_total").labels(
            tenant="acme").value == 1
        # the unlabeled totals keep their historical meaning (roll-up)
        assert reg.counter("serve/hedges_total").value == 1

    def test_fleet_requests_total_labeled(self):
        router, servers, _ = make_fleet(n=2)
        router.submit("a", uuid="u0", tenant="acme", tier="greedy")
        c = router.registry.counter("serve/requests_total")
        assert c.labels(tenant="acme", tier="greedy").value == 1
        assert c.value == 1

    def test_fleet_shed_feeds_slo_burn_windows(self):
        """A fleet-ingress shed (tenant throttle, every replica full)
        is a BAD event for the SLO burn windows.  The router owns the
        fleet's ingress tracking (replica tracking is disabled), so
        without this a total admission outage at the fleet front door
        — the exact outage the engine pages on — would read as a
        healthy SLO."""
        from textsummarization_on_flink_tpu.obs import slo as slo_lib
        from textsummarization_on_flink_tpu.serve.errors import (
            TenantThrottledError,
        )

        clock = _Clock()
        reg = Registry()
        pol = {"windows": {"fast_secs": 10.0, "slow_secs": 100.0},
               "objectives": [{"name": "lat", "signal": "latency",
                               "by": "tenant",
                               "latency_threshold_ms": 1000.0,
                               "target": 0.9}]}
        slo_lib.install_slo_engine(reg, policy=pol, clock=clock.now)
        router, servers, _ = make_fleet(2, clock=clock, registry=reg,
                                        serve_tenant_rate=1.0,
                                        serve_tenant_burst=1)
        bad = reg.counter("slo/bad_total")
        router.submit("a", uuid="u0", tenant="evil")  # spends the burst
        with pytest.raises(TenantThrottledError):
            router.submit("a", uuid="u1", tenant="evil")
        assert bad.labels(objective="lat", key="evil").value == 1
        for h in router.replicas():  # fleet-wide overload: no rotation
            h.killed = True
        with pytest.raises(ServeOverloadError):
            router.submit("b", uuid="u2", tenant="evil")
        assert bad.labels(objective="lat", key="evil").value == 2

    def test_stop_retires_fleet_sources_everywhere(self):
        """A stopped fleet must not pin its replicas in memory through
        a long-lived registry nor keep answering /fleet/* with a dead
        fleet's registries."""
        router, servers, _ = make_fleet(n=2)
        assert router.registry.fleet_sources is not None
        router.stop()
        assert router.registry.fleet_sources is None
        assert all(s.registry.fleet_sources is None for s in servers)
