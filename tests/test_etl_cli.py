"""ETL pipeline (make_datafiles parity) + CLI mode dispatch end-to-end."""

import collections
import os

import pytest

from textsummarization_on_flink_tpu import cli
from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.data import chunks, etl
from textsummarization_on_flink_tpu.data.vocab import Vocab


# -- tokenizer ---------------------------------------------------------------

def test_word_tokenize_punctuation_and_contractions():
    toks = etl.word_tokenize("Don't stop the U.S. team, it's 1,000.5 mi-les!")
    assert "n't" in toks and "Do" in toks
    assert "u.s." in [t.lower() for t in toks]
    assert "1,000.5" in toks
    assert "," in toks and "!" in toks
    assert "mi-les" in toks


def test_fix_missing_period():
    assert etl.fix_missing_period("headline here") == "headline here ."
    assert etl.fix_missing_period("done.") == "done."
    assert etl.fix_missing_period("quote”") == "quote”"
    assert etl.fix_missing_period("@highlight") == "@highlight"
    assert etl.fix_missing_period("") == ""


def test_get_art_abs():
    story = ("The Quick Brown Fox jumped\n\n@highlight\n\nFox Jumps\n\n"
             "@highlight\n\nDog Sleeps.")
    article, abstract = etl.get_art_abs(story)
    assert article == "the quick brown fox jumped ."
    assert abstract == "<s> fox jumps . </s> <s> dog sleeps . </s>"


def test_hashhex_stable():
    # sha1 of a known string (make_datafiles hashhex)
    assert etl.hashhex("abc") == "a9993e364706816aba3e25717850c26c9cd0d89d"


# -- write_to_bin / vocab / chunking -----------------------------------------

@pytest.fixture
def stories(tmp_path):
    paths = []
    for i in range(5):
        p = tmp_path / f"story{i}.story"
        p.write_text(f"the cat number {i} sat down\n\n@highlight\n\ncat {i} sat")
        paths.append(str(p))
    return paths


def test_write_to_bin_round_trip(tmp_path, stories):
    counter = collections.Counter()
    out = etl.write_to_bin(stories, str(tmp_path / "train"), makevocab=True,
                           vocab_counter=counter, chunk_size=2)
    assert len(out) == 3  # 5 examples, chunk_size 2
    exs = list(chunks.example_generator(str(tmp_path / "train_*.bin"),
                                        single_pass=True))
    assert len(exs) == 5
    assert exs[0].get_str("article").startswith("the cat number")
    assert "<s>" in exs[0].get_str("abstract")
    assert counter["cat"] == 10  # article + abstract per story
    assert "<s>" not in counter  # specials excluded from vocab


def test_make_datafiles_full_pipeline(tmp_path, stories):
    url_dir = tmp_path / "urls"
    stories_dir = tmp_path / "hashed"
    url_dir.mkdir()
    stories_dir.mkdir()
    urls = {"train": ["http://a/0", "http://a/1", "http://a/2"],
            "val": ["http://a/3"], "test": ["http://a/4"]}
    for i, (split, us) in enumerate(urls.items()):
        (url_dir / f"all_{split}.txt").write_text("\n".join(us) + "\n")
    for i, u in enumerate(u for us in urls.values() for u in us):
        h = etl.hashhex(u)
        (stories_dir / f"{h}.story").write_text(
            open(stories[i]).read())
    out_dir = tmp_path / "finished"
    etl.make_datafiles(str(stories_dir), str(url_dir), str(out_dir))
    assert os.path.exists(out_dir / "train_000.bin")
    assert os.path.exists(out_dir / "val_000.bin")
    assert os.path.exists(out_dir / "test_000.bin")
    vocab_lines = (out_dir / "vocab").read_text().splitlines()
    assert all(len(l.split()) == 2 for l in vocab_lines)
    # vocab usable by Vocab
    v = Vocab(str(out_dir / "vocab"))
    assert v.size() > 4


def test_missing_story_raises(tmp_path):
    url_dir = tmp_path / "urls"
    url_dir.mkdir()
    for split in ("train", "val", "test"):
        (url_dir / f"all_{split}.txt").write_text("http://missing\n")
    with pytest.raises(FileNotFoundError):
        etl.make_datafiles(str(tmp_path), str(url_dir), str(tmp_path / "o"))


# -- CLI ---------------------------------------------------------------------

WORDS = ("the cat number sat down quick brown fox jumped over lazy dog "
         "0 1 2 3 4").split()


@pytest.fixture
def data_env(tmp_path, stories):
    counter = collections.Counter()
    etl.write_to_bin(stories, str(tmp_path / "train"), makevocab=True,
                     vocab_counter=counter)
    etl.write_vocab(counter, str(tmp_path / "vocab"))
    return tmp_path


def cli_argv(tmp_path, mode, **kw):
    base = dict(mode=mode, data_path=str(tmp_path / "train_*.bin"),
                vocab_path=str(tmp_path / "vocab"), log_root=str(tmp_path),
                exp_name="exp", batch_size=2, hidden_dim=8, emb_dim=6,
                vocab_size=20, max_enc_steps=10, max_dec_steps=5,
                beam_size=2, min_dec_steps=1, max_oov_buckets=4)
    base.update(kw)
    return [f"--{k}={v}" for k, v in base.items()]


@pytest.mark.slow
def test_cli_train_then_eval_then_decode(data_env):
    assert cli.main(cli_argv(data_env, "train", num_steps=2,
                             single_pass=True)) == 0
    train_dir = os.path.join(str(data_env), "exp", "train")
    assert any(f.startswith("model.ckpt") for f in os.listdir(train_dir))

    hps = HParams.from_argv(cli_argv(data_env, "eval"))
    vocab = Vocab(hps.vocab_path, hps.vocab_size)
    loss = cli.run_eval(hps, vocab, max_iters=2)
    assert loss > 0
    eval_dir = os.path.join(str(data_env), "exp", "eval")
    assert any(f.startswith("bestmodel") for f in os.listdir(eval_dir))

    assert cli.main(cli_argv(data_env, "decode", single_pass=True)) == 0
    decode_dirs = [d for d in os.listdir(os.path.join(str(data_env), "exp"))
                   if d.startswith("decode_")]
    assert decode_dirs
    assert os.path.exists(os.path.join(str(data_env), "exp", decode_dirs[0],
                                       "ROUGE_results.txt"))


def test_cli_surgery_flags(data_env):
    cli.main(cli_argv(data_env, "train", num_steps=1, single_pass=True))
    assert cli.main(cli_argv(data_env, "train",
                             convert_to_coverage_model=True)) == 0
    train_dir = os.path.join(str(data_env), "exp", "train")
    assert any("_cov_init" in f for f in os.listdir(train_dir))


def test_cli_raw_text_inference(data_env, tmp_path):
    cli.main(cli_argv(data_env, "train", num_steps=1, single_pass=True))
    raw_dir = tmp_path / "raw"
    raw_dir.mkdir(exist_ok=True)
    (raw_dir / "a.txt").write_text("the quick brown fox jumped over the dog")
    argv = cli_argv(data_env, "decode", inference=True,
                    data_path=str(raw_dir / "*.txt"))
    assert cli.main(argv) == 0


def test_cli_bad_mode_raises(data_env):
    with pytest.raises(ValueError):
        cli.main(cli_argv(data_env, "bogus"))
