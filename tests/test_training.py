"""Training-step tests: TF-Adagrad parity, clipping, overfit, watchdog."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.data import Vocab
from textsummarization_on_flink_tpu.data.batching import Batch, SummaryExample
from textsummarization_on_flink_tpu.train import optim
from textsummarization_on_flink_tpu.train.trainer import (
    Evaluator,
    NonFiniteLossError,
    Trainer,
    calc_running_avg_loss,
    init_train_state,
    make_train_step,
)


def hps_tiny(**kw):
    base = dict(batch_size=2, max_enc_steps=6, max_dec_steps=5, min_dec_steps=1,
                hidden_dim=4, emb_dim=3, max_oov_buckets=2, vocab_size=0,
                lr=0.15, adagrad_init_acc=0.1, max_grad_norm=2.0)
    base.update(kw)
    return HParams(**base)


class TestOptim:
    def test_adagrad_matches_tf_formula(self):
        params = {"w": jnp.asarray([1.0, 2.0])}
        state = optim.adagrad_init(params, 0.1)
        grads = {"w": jnp.asarray([0.5, -1.0])}
        new_params, new_state = optim.adagrad_update(grads, state, params, 0.15)
        acc = 0.1 + np.array([0.25, 1.0])
        want = np.array([1.0, 2.0]) - 0.15 * np.array([0.5, -1.0]) / np.sqrt(acc)
        np.testing.assert_allclose(np.asarray(new_params["w"]), want, rtol=1e-6)
        np.testing.assert_allclose(np.asarray(new_state.accumulators["w"]), acc,
                                   rtol=1e-6)

    def test_clip_by_global_norm(self):
        tree = {"a": jnp.asarray([3.0, 4.0])}  # norm 5
        clipped, norm = optim.clip_by_global_norm(tree, 2.0)
        assert float(norm) == pytest.approx(5.0)
        np.testing.assert_allclose(np.asarray(clipped["a"]), [1.2, 1.6], rtol=1e-6)
        # below the limit: untouched
        clipped2, _ = optim.clip_by_global_norm(tree, 10.0)
        np.testing.assert_allclose(np.asarray(clipped2["a"]), [3.0, 4.0])


class FixedBatcher:
    """Yields the same batch n times then None."""

    def __init__(self, batch, n):
        self.batch, self.n = batch, n

    def next_batch(self):
        if self.n <= 0:
            return None
        self.n -= 1
        return self.batch


def make_batch(hps, vocab):
    exs = [SummaryExample.build("a b c d", ["b c ."], vocab, hps),
           SummaryExample.build("c d e f", ["d e ."], vocab, hps)]
    return Batch(exs, hps, vocab)


class TestTrainStep:
    def test_overfit_tiny_batch(self, tmp_path):
        """Loss must drop substantially when training repeatedly on one
        batch — end-to-end check of grads + optimizer."""
        hps = hps_tiny(log_root=str(tmp_path), exp_name="t")
        vocab = Vocab(words=["a", "b", "c", "d", "e", "f", "."])
        batch = make_batch(hps, vocab)
        trainer = Trainer(hps, vocab.size(), FixedBatcher(batch, 100))
        probe = jax.jit(make_train_step(hps))  # non-donating probe step
        _, m0 = probe(trainer.state, batch.as_arrays())
        state = trainer.train()
        _, m1 = probe(state, batch.as_arrays())
        assert float(m1.loss) < 0.5 * float(m0.loss)
        assert int(state.step) == 100
        # summaries written
        events = (tmp_path / "t" / "train" / "events.jsonl").read_text()
        assert len(events.splitlines()) == 100

    def test_num_steps_limit(self, tmp_path):
        hps = hps_tiny(log_root=str(tmp_path), exp_name="t", num_steps=3)
        vocab = Vocab(words=["a", "b", "c", "d", "e", "f", "."])
        batch = make_batch(hps, vocab)
        trainer = Trainer(hps, vocab.size(), FixedBatcher(batch, 100))
        state = trainer.train()
        assert int(state.step) == 3

    def test_nan_watchdog(self, tmp_path):
        hps = hps_tiny(log_root=str(tmp_path), exp_name="t", lr=1e6)
        vocab = Vocab(words=["a", "b", "c", "d", "e", "f", "."])
        batch = make_batch(hps, vocab)
        trainer = Trainer(hps, vocab.size(), FixedBatcher(batch, 50))
        with pytest.raises(NonFiniteLossError):
            trainer.train()

    def test_nan_watchdog_debug_dumps_batch(self, tmp_path):
        """--debug pins the metrics window to 1 step and dumps the exact
        offending batch (the reference's tfdbg has_inf_or_nan hook,
        run_summarization.py:216-218)."""
        hps = hps_tiny(log_root=str(tmp_path), exp_name="t", lr=1e6,
                       debug=True)
        vocab = Vocab(words=["a", "b", "c", "d", "e", "f", "."])
        batch = make_batch(hps, vocab)
        trainer = Trainer(hps, vocab.size(), FixedBatcher(batch, 50))
        assert trainer.metrics_every == 1
        with pytest.raises(NonFiniteLossError):
            trainer.train()
        dumps = list((tmp_path / "t" / "train").glob("nan_batch_step*.npz"))
        assert len(dumps) == 1
        loaded = np.load(dumps[0])
        np.testing.assert_array_equal(loaded["enc_batch"],
                                      batch.as_arrays()["enc_batch"])

    def test_metrics_window_writes_per_step_records(self, tmp_path):
        """Deferred metrics fetch (one D2H sync per window) must still
        produce one summary record per step."""
        hps = hps_tiny(log_root=str(tmp_path), exp_name="w")
        vocab = Vocab(words=["a", "b", "c", "d", "e", "f", "."])
        batch = make_batch(hps, vocab)
        trainer = Trainer(hps, vocab.size(), FixedBatcher(batch, 7),
                          metrics_every=3)  # 7 steps -> 2 full + 1 partial
        trainer.train()
        import json

        lines = (tmp_path / "w" / "train" / "events.jsonl").read_text() \
            .strip().splitlines()
        assert [json.loads(ln)["step"] for ln in lines] == list(range(1, 8))

    def test_coverage_objective_used(self, tmp_path):
        hps = hps_tiny(coverage=True)
        vocab = Vocab(words=["a", "b", "c", "d", "e", "f", "."])
        batch = make_batch(hps, vocab)
        state = init_train_state(hps, vocab.size())
        step = jax.jit(make_train_step(hps))
        _, m = step(state, batch.as_arrays())
        assert float(m.total_loss) == pytest.approx(
            float(m.loss) + hps.cov_loss_wt * float(m.coverage_loss), rel=1e-5)


class TestRunningAvg:
    def test_semantics(self):
        assert calc_running_avg_loss(5.0, 0.0) == 5.0
        v = calc_running_avg_loss(4.0, 5.0)
        assert v == pytest.approx(5.0 * 0.99 + 4.0 * 0.01)
        assert calc_running_avg_loss(100.0, 50.0) == 12  # clip


class TestEvaluator:
    def test_best_model_tracking(self, tmp_path):
        hps = hps_tiny(log_root=str(tmp_path), exp_name="t")
        vocab = Vocab(words=["a", "b", "c", "d", "e", "f", "."])
        batch = make_batch(hps, vocab)
        saved = []
        ev = Evaluator(hps, vocab.size(), FixedBatcher(batch, 2),
                       best_saver=lambda p, l, s: saved.append((l, s)))
        state = init_train_state(hps, vocab.size())
        avg = ev.run(state.params, step=1)
        assert np.isfinite(avg)
        assert len(saved) == 1  # first run is always an improvement
        # second run with same params: avg unchanged-ish, no new best
        ev.batcher = FixedBatcher(batch, 2)
        ev.run(state.params, step=2)
        assert len(saved) == 1

    def test_best_model_updates_per_eval_iteration(self, tmp_path):
        """The best check runs after EVERY eval batch (the reference saves
        inside its loop, run_summarization.py:281-292), so improving
        losses within one run() produce multiple saves."""
        from textsummarization_on_flink_tpu.train.trainer import StepMetrics

        hps = hps_tiny(log_root=str(tmp_path), exp_name="t2")
        vocab = Vocab(words=["a", "b", "c", "d", "e", "f", "."])
        batch = make_batch(hps, vocab)
        saved = []
        ev = Evaluator(hps, vocab.size(), FixedBatcher(batch, 3),
                       best_saver=lambda p, l, s: saved.append(l))
        losses = iter([5.0, 4.0, 3.0])  # strictly improving per batch

        def fake_eval(params, arrays):
            v = jnp.asarray(next(losses))
            return StepMetrics(loss=v, coverage_loss=jnp.zeros(()),
                               total_loss=v, global_norm=jnp.zeros(()))

        ev._eval_fn = fake_eval
        ev.run(object(), step=1)
        # running avg: 5.0 -> 4.99 -> 4.9701, each a new best
        assert len(saved) == 3
        assert saved == sorted(saved, reverse=True)


class TestProfiler:
    def test_profile_dir_captures_trace(self, tmp_path, monkeypatch):
        """TS_PROFILE_DIR wiring (SURVEY §5.1): a training run traces
        steps 2-7 post-compilation and leaves an XPlane trace on disk."""
        import os

        prof_dir = str(tmp_path / "prof")
        monkeypatch.setenv("TS_PROFILE_DIR", prof_dir)
        hps = hps_tiny()
        vocab = Vocab(words=["a", "b", "c", "d", "e", "f", "."])
        batch = make_batch(hps, vocab)
        tr = Trainer(hps, vocab.size(), FixedBatcher(batch, 12),
                     train_dir=str(tmp_path / "train"), metrics_every=3)
        tr.train(num_steps=10)
        traces = []
        for root, _, files in os.walk(prof_dir):
            traces += [f for f in files if f.endswith((".xplane.pb",
                                                       ".trace.json.gz"))]
        assert traces, f"no profiler trace written under {prof_dir}"


class TestDebugAndMultihostHelpers:
    def test_apply_debug_mode_toggles_jax_debug_nans(self):
        from textsummarization_on_flink_tpu.utils import apply_debug_mode

        try:
            apply_debug_mode(hps_tiny(debug=False))
            assert not jax.config.jax_debug_nans
            apply_debug_mode(hps_tiny(debug=True))
            assert jax.config.jax_debug_nans
        finally:
            jax.config.update("jax_debug_nans", False)

    def test_local_batch_hps_single_process_passthrough(self):
        from textsummarization_on_flink_tpu.utils import local_batch_hps

        hps = hps_tiny(batch_size=16)
        assert local_batch_hps(hps) is hps

    def test_local_batch_hps_divides(self, monkeypatch):
        import textsummarization_on_flink_tpu.utils as utils_mod

        monkeypatch.setattr(jax, "process_count", lambda: 4)
        hps = hps_tiny(batch_size=16)
        assert utils_mod.local_batch_hps(hps).batch_size == 4
        with pytest.raises(ValueError, match="divisible"):
            utils_mod.local_batch_hps(hps_tiny(batch_size=6))

    def test_multihost_rejects_single_pass(self, monkeypatch, tmp_path):
        hps = hps_tiny(log_root=str(tmp_path), exp_name="t",
                       single_pass=True, num_steps=3)
        vocab = Vocab(words=["a", "b", "c", "d", "e", "f", "."])
        batch = make_batch(hps, vocab)
        trainer = Trainer(hps, vocab.size(), FixedBatcher(batch, 5))
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        with pytest.raises(ValueError, match="single_pass"):
            trainer.train()

    def test_multihost_requires_checkpoint_steps(self, monkeypatch,
                                                 tmp_path):
        """VERDICT r3 weak#5: a wall-clock cadence would desync the
        collective save, and the old seconds-as-steps reinterpretation
        was a silent unit swap — now a hard error."""
        hps = hps_tiny(log_root=str(tmp_path), exp_name="t", num_steps=3)
        vocab = Vocab(words=["a", "b", "c", "d", "e", "f", "."])
        batch = make_batch(hps, vocab)

        class NullCkpt:
            def save(self, state):
                return ""

        trainer = Trainer(hps, vocab.size(), FixedBatcher(batch, 5),
                          checkpointer=NullCkpt())
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        with pytest.raises(ValueError, match="checkpoint_steps"):
            trainer.train()
        # an explicit step cadence passes the guard (run then fails later
        # for unrelated mesh reasons only if sharded; here it trains)
        trainer2 = Trainer(hps, vocab.size(), FixedBatcher(batch, 5),
                           checkpointer=NullCkpt(), checkpoint_steps=2)
        assert trainer2.checkpoint_steps == 2


def test_trainer_auto_shards_on_mesh(tmp_path):
    """hps with dp*tp>1 makes Trainer build the sharded step itself (the
    CLI/estimator path to multi-chip: no explicit mesh plumbing needed)."""
    from textsummarization_on_flink_tpu.data.batching import Batch, SummaryExample
    from textsummarization_on_flink_tpu.data.vocab import Vocab

    words = "the quick brown fox jumped over lazy dog".split()
    vocab = Vocab(words=words)
    hps = HParams(batch_size=4, hidden_dim=8, emb_dim=6, vocab_size=12,
                  max_enc_steps=8, max_dec_steps=4, max_oov_buckets=4,
                  dp=2, tp=2, sp=2, log_root=str(tmp_path), exp_name="m")

    class OneBatch:
        def __init__(self):
            exs = [SummaryExample.build("the quick brown fox .",
                                        ["fox jumped ."], vocab, hps)
                   for _ in range(hps.batch_size)]
            self._batches = [Batch(exs, hps, vocab)] * 3

        def next_batch(self):
            return self._batches.pop() if self._batches else None

    from textsummarization_on_flink_tpu.train import trainer as trainer_lib

    tr = trainer_lib.Trainer(hps, vocab.size(), OneBatch(),
                             train_dir=str(tmp_path / "train"))
    state = tr.train(num_steps=0)  # until batcher drains
    assert int(state.step) == 3
    # params actually live on the 8-device mesh
    leaf = jax.tree_util.tree_leaves(state.params)[0]
    assert len(leaf.sharding.device_set) == 8


class TestStepsPerDispatch:
    """steps_per_dispatch=k runs k optimizer steps as one on-device scan
    (the TPU steps_per_execution pattern) — must be step-for-step
    identical to k=1 in losses, summaries, and final params."""

    def _run(self, tmp_path, k, n_batches=10, num_steps=10, **hkw):
        import json as json_lib

        hps = hps_tiny(log_root=str(tmp_path), exp_name=f"k{k}",
                       steps_per_dispatch=k, **hkw)
        vocab = Vocab(words=["a", "b", "c", "d", "e", "f", "."])
        batch = make_batch(hps, vocab)
        trainer = Trainer(hps, vocab.size(), FixedBatcher(batch, n_batches),
                          metrics_every=3)
        state = trainer.train(num_steps=num_steps)
        trainer.writer.close()
        path = tmp_path / f"k{k}" / "train" / "events.jsonl"
        recs = [json_lib.loads(l) for l in open(path)]
        return state, recs

    @pytest.mark.slow
    def test_k4_matches_k1(self, tmp_path):
        s1, r1 = self._run(tmp_path, 1)
        s4, r4 = self._run(tmp_path, 4)
        assert [r["step"] for r in r1] == [r["step"] for r in r4]
        losses1 = [r["loss"] for r in r1]
        losses4 = [r["loss"] for r in r4]
        np.testing.assert_allclose(losses4, losses1, rtol=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(s1.params),
                        jax.tree_util.tree_leaves(s4.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-7)
        assert int(np.asarray(s4.step)) == 10

    def test_limit_exact_when_k_does_not_divide(self, tmp_path):
        # 10 steps at k=4 -> dispatches of 4, 4, 2
        state, recs = self._run(tmp_path, 4, n_batches=50, num_steps=10)
        assert int(np.asarray(state.step)) == 10
        assert [r["step"] for r in recs] == list(range(1, 11))

    def test_exhaustion_tail_single_host(self, tmp_path):
        # 7 batches, no limit: k=4 dispatches 4 then the 3-batch tail
        state, recs = self._run(tmp_path, 4, n_batches=7, num_steps=0)
        assert int(np.asarray(state.step)) == 7
        assert [r["step"] for r in recs] == list(range(1, 8))

    def test_debug_forces_k1(self, tmp_path):
        hps = hps_tiny(steps_per_dispatch=8, debug=True)
        vocab = Vocab(words=["a", "b", "c", "d", "e", "f", "."])
        batch = make_batch(hps, vocab)
        trainer = Trainer(hps, vocab.size(), FixedBatcher(batch, 2))
        assert trainer.steps_per_dispatch == 1

    def test_watchdog_fires_inside_multi_dispatch(self, tmp_path):
        hps = hps_tiny(log_root=str(tmp_path), exp_name="nan",
                       steps_per_dispatch=4)
        vocab = Vocab(words=["a", "b", "c", "d", "e", "f", "."])
        batch = make_batch(hps, vocab)
        trainer = Trainer(hps, vocab.size(), FixedBatcher(batch, 20),
                          metrics_every=4)
        bad = jax.tree_util.tree_map(
            lambda x: np.full_like(np.asarray(x), np.nan),
            jax.device_get(trainer.state.params))
        trainer.state = trainer.state._replace(params=jax.device_put(bad))
        with pytest.raises(NonFiniteLossError, match="windowed"):
            trainer.train(num_steps=12)
