"""Data-layer unit tests: tf.Example codec, vocab, chunk IO, OOV mapping.

The reference has no Python unit tests at all (SURVEY.md §4); these cover
the exact-parity behaviors: special-token ids, OOV temp-id assignment,
chunk wire format, abstract sentence splitting.
"""

import struct

import pytest

from textsummarization_on_flink_tpu.data import (
    PAD_TOKEN,
    START_DECODING,
    STOP_DECODING,
    TFExample,
    UNKNOWN_TOKEN,
    Vocab,
    abstract2ids,
    abstract2sents,
    article2ids,
    example_generator,
    outputids2words,
    read_chunk_file,
    show_abs_oovs,
    show_art_oovs,
    write_chunk_file,
)
from textsummarization_on_flink_tpu.data.chunks import bin2txt, write_chunked


def make_vocab(words=("the", "cat", "sat", "on", "mat")):
    return Vocab(words=words)


class TestTFExample:
    def test_roundtrip_bytes(self):
        ex = TFExample().set_bytes("article", b"hello world").set_bytes("uuid", b"u-1")
        back = TFExample.parse(ex.serialize())
        assert back.get_str("article") == "hello world"
        assert back.get_str("uuid") == "u-1"

    def test_roundtrip_floats_ints(self):
        ex = TFExample().set_floats("f", 1.5, -2.25).set_ints("i", 7, -3, 1 << 40)
        back = TFExample.parse(ex.serialize())
        assert back.features["f"] == [1.5, -2.25]
        assert back.features["i"] == [7, -3, 1 << 40]

    def test_unicode(self):
        ex = TFExample().set_bytes("a", "héllo wörld ✓")
        assert TFExample.parse(ex.serialize()).get_str("a") == "héllo wörld ✓"

    def test_tensorflow_wire_compat(self):
        """Golden bytes produced by tf.train.Example for {"x": [b"ab"]}:
        feature map entry key=1 string, value=2 Feature{bytes_list=1}."""
        golden = bytes.fromhex("0a0d0a0b0a017812060a040a026162")
        back = TFExample.parse(golden)
        assert back.get_str("x") == "ab"
        assert TFExample().set_bytes("x", b"ab").serialize() == golden


class TestVocab:
    def test_special_ids(self):
        v = make_vocab()
        assert v.word2id(UNKNOWN_TOKEN) == 0
        assert v.word2id(PAD_TOKEN) == 1
        assert v.word2id(START_DECODING) == 2
        assert v.word2id(STOP_DECODING) == 3
        assert v.word2id("the") == 4
        assert v.size() == 9

    def test_unk_for_oov(self):
        v = make_vocab()
        assert v.word2id("zebra") == 0
        with pytest.raises(ValueError):
            v.id2word(9999)

    def test_file_loading_max_size_and_malformed(self, tmp_path):
        p = tmp_path / "vocab"
        p.write_text("the 100\ncat 50\nmalformed\nsat 10\non 5\n")
        v = Vocab(str(p), max_size=6)  # 4 specials + 2 words
        assert v.size() == 6
        assert v.word2id("cat") == 5
        assert v.word2id("sat") == 0  # cut off by max_size -> UNK

    def test_forbidden_and_duplicate(self, tmp_path):
        p = tmp_path / "vocab"
        p.write_text("<s> 5\n")
        with pytest.raises(ValueError):
            Vocab(str(p))
        p.write_text("cat 5\ncat 3\n")
        with pytest.raises(ValueError):
            Vocab(str(p))

    def test_write_metadata(self, tmp_path):
        v = make_vocab(("a", "b"))
        f = tmp_path / "meta.tsv"
        v.write_metadata(str(f))
        assert f.read_text().splitlines() == [
            "[UNK]", "[PAD]", "[START]", "[STOP]", "a", "b"]


class TestOOV:
    def test_article2ids(self):
        v = make_vocab()
        ids, oovs = article2ids("the cat zebra sat zebra yak".split(), v)
        assert oovs == ["zebra", "yak"]
        assert ids == [4, 5, v.size(), 6, v.size(), v.size() + 1]

    def test_abstract2ids(self):
        v = make_vocab()
        _, oovs = article2ids("the zebra".split(), v)
        ids = abstract2ids("the zebra emu".split(), v, oovs)
        assert ids == [4, v.size(), 0]  # emu: out-of-article OOV -> UNK

    def test_outputids2words_roundtrip(self):
        v = make_vocab()
        ids, oovs = article2ids("the cat zebra".split(), v)
        assert outputids2words(ids, v, oovs) == ["the", "cat", "zebra"]
        with pytest.raises(ValueError):
            outputids2words([v.size() + 5], v, oovs)

    def test_abstract2sents(self):
        s = "<s> first sent . </s> <s> second . </s>"
        assert abstract2sents(s) == [" first sent . ", " second . "]
        assert abstract2sents("no tags here") == []

    def test_show_oovs(self):
        v = make_vocab()
        assert show_art_oovs("the zebra sat", v) == "the __zebra__ sat"
        out = show_abs_oovs("the zebra emu", v, ["zebra"])
        assert out == "the __zebra__ !!__emu__!!"


class TestChunks:
    def _examples(self, n):
        return [
            TFExample().set_bytes("article", f"article {i}".encode())
            .set_bytes("abstract", f"<s> abstract {i} . </s>".encode())
            for i in range(n)
        ]

    def test_roundtrip(self, tmp_path):
        path = str(tmp_path / "c.bin")
        exs = self._examples(5)
        assert write_chunk_file(path, exs) == 5
        back = list(read_chunk_file(path))
        assert back == exs

    def test_wire_format_length_prefix(self, tmp_path):
        path = str(tmp_path / "c.bin")
        ex = self._examples(1)[0]
        write_chunk_file(path, [ex])
        raw = open(path, "rb").read()
        (ln,) = struct.unpack("<q", raw[:8])
        assert ln == len(raw) - 8
        assert TFExample.parse(raw[8:]) == ex

    def test_generator_single_pass_sorted(self, tmp_path):
        write_chunked(str(tmp_path / "train"), self._examples(25), chunk_size=10)
        assert len(list((tmp_path).glob("train_*.bin"))) == 3
        got = [ex.get_str("article")
               for ex in example_generator(str(tmp_path / "train_*.bin"), True)]
        assert got == [f"article {i}" for i in range(25)]

    def test_generator_empty_glob_asserts(self, tmp_path):
        with pytest.raises(AssertionError):
            next(example_generator(str(tmp_path / "nope_*.bin"), True))

    def test_native_reader_parity(self, tmp_path, monkeypatch):
        """The C++ chunk reader (native/chunkio.cpp) yields byte-identical
        records to the pure-Python framing loop."""
        from textsummarization_on_flink_tpu.data import chunks as chunks_mod
        from textsummarization_on_flink_tpu.pipeline import bridge

        if not bridge.native_available():
            pytest.skip("native library not built")
        path = str(tmp_path / "c.bin")
        write_chunk_file(path, self._examples(50))
        monkeypatch.setenv("TS_NATIVE_IO", "auto")
        blobs = chunks_mod._native_read_blobs(path)
        assert blobs is not None and len(blobs) == 50
        native = list(read_chunk_file(path))
        monkeypatch.setenv("TS_NATIVE_IO", "off")
        assert chunks_mod._native_read_blobs(path) is None
        assert native == list(read_chunk_file(path))

    @pytest.mark.parametrize("io_mode", ["auto", "off"])
    def test_reader_rejects_corrupt_framing(self, tmp_path, monkeypatch,
                                            io_mode):
        """Native and pure-Python readers raise the SAME error messages
        on the same corrupt inputs."""
        from textsummarization_on_flink_tpu.pipeline import bridge

        if io_mode == "auto" and not bridge.native_available():
            pytest.skip("native library not built")
        monkeypatch.setenv("TS_NATIVE_IO", io_mode)
        bad = str(tmp_path / "bad.bin")
        for payload in (struct.pack("<q", 5) + b"ab",  # claims 5, has 2
                        struct.pack("<q", -7) + b"ab"):  # negative length
            with open(bad, "wb") as f:
                f.write(payload)
            with pytest.raises(ValueError, match="truncated record"):
                list(read_chunk_file(bad))
        with open(bad, "wb") as f:
            f.write(b"\x01\x02\x03")  # not even a full prefix
        with pytest.raises(ValueError, match="truncated length prefix"):
            list(read_chunk_file(bad))

    def test_bin2txt(self, tmp_path):
        write_chunked(str(tmp_path / "t"), self._examples(3), chunk_size=10)
        out = str(tmp_path / "out.jsonl")
        assert bin2txt(str(tmp_path / "t_*.bin"), out) == 3
        import json
        lines = [json.loads(l) for l in open(out)]
        assert lines[0]["article"] == "article 0"


class TestHParams:
    def test_defaults_match_reference_flags(self):
        from textsummarization_on_flink_tpu.config import HParams
        h = HParams()
        assert (h.hidden_dim, h.emb_dim, h.batch_size) == (256, 128, 16)
        assert (h.max_enc_steps, h.max_dec_steps, h.beam_size) == (400, 100, 4)
        assert (h.min_dec_steps, h.vocab_size) == (35, 50000)
        assert (h.lr, h.adagrad_init_acc, h.max_grad_norm) == (0.15, 0.1, 2.0)
        assert h.pointer_gen and not h.coverage and h.cov_loss_wt == 1.0

    def test_argv_roundtrip(self):
        from textsummarization_on_flink_tpu.config import HParams
        argv = ("--mode decode --batch_size=4 --coverage=True --lr 0.01 "
                "--exp_name pretrained --single_pass").split(" ")
        h = HParams.from_argv(argv)
        assert h.mode == "decode" and h.batch_size == 4 and h.coverage
        assert h.lr == 0.01 and h.exp_name == "pretrained" and h.single_pass
        h2 = HParams.from_argv(h.to_argv().split(" "))
        assert h2 == h

    def test_bare_bool_then_positional(self):
        from textsummarization_on_flink_tpu.config import HParams
        h = HParams.from_argv(["--single_pass", "train_*.bin", "--mode", "eval"])
        assert h.single_pass is True and h.mode == "eval"
        # non-bool flag with missing value is skipped, not crashed
        h2 = HParams.from_argv(["--num_steps", "--mode=eval"])
        assert h2.num_steps == 0 and h2.mode == "eval"

    def test_from_string_quoted_spaces(self):
        from textsummarization_on_flink_tpu.config import HParams
        h = HParams(data_path="/data/my runs/train_*.bin")
        h2 = HParams.from_string(h.to_argv())
        assert h2 == h

    def test_json_roundtrip_and_validate(self):
        from textsummarization_on_flink_tpu.config import HParams
        h = HParams(mode="eval", hidden_dim=512)
        assert HParams.from_json(h.to_json()) == h
        h.validate()
        import pytest as _pytest
        with _pytest.raises(ValueError):
            HParams(mode="bogus").validate()
        with _pytest.raises(ValueError, match="scan_unroll"):
            HParams(scan_unroll=0).validate()
        with _pytest.raises(ValueError, match="steps_per_dispatch"):
            HParams(steps_per_dispatch=0).validate()
