"""Bench-script contract tests (ISSUE 5 satellites; advisor r5 #3/#4).

scripts/bench_all.sh's run() classifies the bench child's last stdout
line and routes it into BENCH_ALL.jsonl; a bug here silently poisons the
sweep record every sweep.  The BENCH_SWEEP_SINGLE hook in the script
exercises ONE run() invocation — the exact shipped function — against a
stubbed bench.py whose output the test controls, asserting the
exit-code/append/DID_MEASURE contract for live JSON, stale JSON, error
JSON, and garbage.  Plus the bench._file_digest same-second-regen
regression (cache key must include st_mtime_ns).
"""

import importlib.util
import json
import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.join(os.path.dirname(__file__), "..")

spec = importlib.util.spec_from_file_location(
    "bench_digest_under_test", os.path.join(REPO, "bench.py"))
bench = importlib.util.module_from_spec(spec)
sys.modules["bench_digest_under_test"] = bench
spec.loader.exec_module(bench)

# A stub bench.py honoring the pieces run() touches: importable with a
# _config_fingerprint (the liveness check imports it), prints
# FAKE_BENCH_OUTPUT verbatim when executed.  It deliberately does NOT
# self-append, so the test can observe run()'s own append decisions.
STUB_BENCH = '''
import os, sys


def _config_fingerprint():
    return {"mode": os.environ.get("BENCH_MODE", "train")}


if __name__ == "__main__":
    out = os.environ.get("FAKE_BENCH_OUTPUT", "")
    if out:
        sys.stdout.write(out + "\\n")
'''


def _sandbox(tmp_path):
    scripts = tmp_path / "repo" / "scripts"
    scripts.mkdir(parents=True)
    for name in ("bench_all.sh", "bench_latest.py"):
        shutil.copy(os.path.join(REPO, "scripts", name), scripts / name)
    (tmp_path / "repo" / "bench.py").write_text(STUB_BENCH)
    return tmp_path / "repo"


def _run_single(repo, tag, fake_output):
    env = dict(os.environ)
    env.update(PYTHONPATH="", BENCH_SWEEP_SINGLE=tag,
               FAKE_BENCH_OUTPUT=fake_output)
    proc = subprocess.run(["bash", "scripts/bench_all.sh"], cwd=repo,
                          env=env, capture_output=True, text=True,
                          timeout=120)
    assert proc.returncode == 0, proc.stderr[-1000:]
    out_path = repo / "BENCH_ALL.jsonl"
    lines = [json.loads(s)
             for s in out_path.read_text().strip().splitlines() if s]
    did_measure = None
    for ln in proc.stdout.splitlines():
        if ln.startswith("DID_MEASURE="):
            did_measure = int(ln.split("=", 1)[1])
    assert did_measure is not None, proc.stdout[-500:]
    return lines, did_measure, proc


def test_live_json_arms_did_measure_and_lands_in_jsonl(tmp_path):
    repo = _sandbox(tmp_path)
    live = json.dumps({"metric": "m", "value": 1.5, "unit": "x",
                       "vs_baseline": 1.0})
    lines, did_measure, proc = _run_single(repo, "row_a", live)
    assert did_measure == 1
    # the stub never self-appends, so run()'s fallback append must fire
    assert "self-append missing" in proc.stderr
    assert len(lines) == 1 and lines[0]["value"] == 1.5


def test_stale_json_appends_tagged_and_does_not_arm(tmp_path):
    repo = _sandbox(tmp_path)
    stale = json.dumps({"metric": "m", "value": 2.0, "unit": "x",
                        "vs_baseline": 1.0, "stale": True})
    lines, did_measure, _ = _run_single(repo, "row_b", stale)
    assert did_measure == 0
    assert len(lines) == 1
    assert lines[0]["stale"] is True and lines[0]["run"] == "row_b"


def test_error_json_appends_tagged_and_does_not_arm(tmp_path):
    repo = _sandbox(tmp_path)
    err = json.dumps({"metric": "m", "value": 0.0, "unit": "n/a",
                      "vs_baseline": 0.0, "error": "boom"})
    lines, did_measure, _ = _run_single(repo, "row_c", err)
    assert did_measure == 0
    assert len(lines) == 1
    assert lines[0]["error"] == "boom" and lines[0]["run"] == "row_c"


@pytest.mark.parametrize("garbage", [
    "Traceback (most recent call last):",   # not JSON at all
    '["metric", "not-a-dict"]',             # JSON but not an object
    '{"value": 1.0}',                       # object but no metric field
])
def test_garbage_appends_error_stub_never_the_raw_line(tmp_path, garbage):
    """advisor r5 #4: unparseable child output must become a typed error
    stub — never the raw garbage line (which would poison the JSONL for
    every reader) and never a live classification (which would arm the
    denominator pairing off nothing)."""
    repo = _sandbox(tmp_path)
    lines, did_measure, proc = _run_single(repo, "row_d", garbage)
    assert did_measure == 0
    assert "unparseable" in proc.stderr
    assert len(lines) == 1
    assert lines[0] == {"run": "row_d", "error": "unparseable bench output"}
    assert garbage not in (repo / "BENCH_ALL.jsonl").read_text()


def test_empty_output_appends_no_output_stub(tmp_path):
    repo = _sandbox(tmp_path)
    lines, did_measure, _ = _run_single(repo, "row_e", "")
    assert did_measure == 0
    assert lines == [{"run": "row_e", "error": "no output"}]


def test_file_digest_same_second_same_size_regen(tmp_path):
    """advisor r5 #3: a regenerated fixture with the same byte size in
    the same mtime SECOND must get a fresh digest — the cache key
    includes st_mtime_ns, not the truncated-second mtime."""
    fx = tmp_path / "fixture.npz"
    fx.write_bytes(b"fixture content A")
    os.utime(fx, ns=(1_000_000_000, 5_000_000_000))
    d1 = bench._file_digest(str(fx))
    # same size, same integer second (5), different nanoseconds
    fx.write_bytes(b"fixture content B")
    os.utime(fx, ns=(1_000_000_000, 5_000_000_500))
    d2 = bench._file_digest(str(fx))
    assert d1 != d2, ("same-second same-size regen served a stale "
                      "content digest")
    # identical stat -> cache hit (no rehash needed): digest stable
    assert bench._file_digest(str(fx)) == d2
