"""The process boundary (ISSUE 17; SERVING.md "Process fleet").

Process-grain supervision and the socket transport, tested at the
seams that CAN be wrong without a fleet running:

  * a hung child ``/healthz`` costs the supervisor ONE scrape timeout
    per cache window — never a frozen router tick loop;
  * the portfile handshake is incarnation-checked — a stale file left
    by a previous (or foreign) pid never resolves;
  * the reply transport is exactly-once: ring replay after a child
    restart collapses under the (uuid, seq) dedup, and an orphan frame
    for an already-settled future is dropped, not double-resolved;
  * the crash-loop breaker CONTAINS a restart storm: K consecutive
    deaths trip it, the flight ring dumps, the incident reaches
    /alerts, restarts stop at half-open probe cadence — and a mixed
    fleet keeps serving off the healthy replica the whole time.

The full 3-OS-process chaos gate (real SIGKILL mid-decode on a real
model, typed requeues witnessed in survivors' events.jsonl) runs in
``scripts/fleet_smoke.py --transport=proc`` (repro.sh; the armed
``serve.proc_kill`` sweep in chaos.sh); the socket/scrape byte budgets
are enforced by tests/test_serve_slo.py off SERVE_SLO.json
``process_fleet``.
"""

import glob
import json
import os
import socket
import sys
import threading
import time

import pytest

from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.obs import Registry
from textsummarization_on_flink_tpu.obs import flightrec
from textsummarization_on_flink_tpu.obs import http as obs_http
from textsummarization_on_flink_tpu.pipeline.io import Message, \
    ResilientSource
from textsummarization_on_flink_tpu.resilience.policy import CircuitBreaker
from textsummarization_on_flink_tpu.serve import procfleet
from textsummarization_on_flink_tpu.serve.errors import ServeOverloadError
from textsummarization_on_flink_tpu.serve.queue import ServeFuture

CRASH_CMD = [sys.executable, "-c", "raise SystemExit(13)"]
SLEEP_CMD = [sys.executable, "-c", "import time; time.sleep(600)"]


def _hps(**overrides):
    base = dict(mode="decode", batch_size=2, vocab_size=8, max_enc_steps=8,
                max_dec_steps=4, min_dec_steps=1, beam_size=2,
                max_oov_buckets=2, serve_max_queue=8, serve_slots=2)
    base.update(overrides)
    return HParams(**base)


class _FakeProc:
    """The ReplicaProcess surface RemoteReplica reads, without an OS
    child: tests point ``ports`` at their own sockets."""

    def __init__(self, ports=None, pid=-1):
        self.rid = "r0"
        self._ports = ports
        self._pid = pid

    def ports(self):
        return self._ports

    def pid(self):
        return self._pid

    def ready(self):
        return True

    def start(self):
        pass


# -- satellite 1: explicit scrape timeouts ---------------------------------

class TestScrapeTimeout:
    @pytest.fixture
    def hung_port(self):
        """A listener that accepts and then never speaks: the wedged
        child's /healthz."""
        srv = socket.create_server(("127.0.0.1", 0))
        held = []
        stop = threading.Event()

        def accept_loop():
            srv.settimeout(0.2)
            while not stop.is_set():
                try:
                    conn, _ = srv.accept()
                    held.append(conn)  # keep it open, say nothing
                except OSError:
                    continue

        t = threading.Thread(target=accept_loop, daemon=True)
        t.start()
        yield srv.getsockname()[1]
        stop.set()
        t.join(timeout=2.0)
        for c in held:
            c.close()
        srv.close()

    def test_hung_healthz_costs_one_timeout_not_a_frozen_router(
            self, hung_port):
        """The regression the satellite names: a child whose /healthz
        hangs must cost the router ONE serve_scrape_timeout_ms wait per
        scrape window — the failure is cached, so the tick loop (which
        calls healthy() every rotation refresh) never blocks again
        until the window rolls."""
        hps = _hps(serve_scrape_timeout_ms=150.0,
                   serve_scrape_interval_ms=60_000.0)
        reg = Registry()
        remote = procfleet.RemoteReplica(
            "r0", _FakeProc(ports={"obs_port": hung_port}, pid=4242),
            hps, registry=reg)
        handle = procfleet.RemoteReplicaHandle("r0", remote, registry=reg)

        t0 = time.monotonic()
        assert remote.scrape_healthz() is None
        first = time.monotonic() - t0
        assert 0.1 <= first < 2.0, (
            f"scrape took {first:.3f}s — the timeout is not bounding it")
        errors = reg.counter(
            "serve/replica_scrape_errors_total").labels(replica="r0")
        assert errors.value == 1

        # 50 rotation refreshes against the wedged child: all served
        # from the (negative) cache — no further timeout waits, no
        # further error counts, and the handle reads unhealthy
        t0 = time.monotonic()
        for _ in range(50):
            assert not handle.healthy()
        assert time.monotonic() - t0 < 0.1, (
            "cached scrape failures are re-scraping inside the window")
        assert errors.value == 1

    def test_scrape_recovers_when_child_answers(self):
        """The same path against a LIVE /healthz: payload lands, the
        fingerprint is cached, the handle turns healthy only when the
        pid matches the supervisor's incarnation view."""
        reg_child = Registry()
        reg_child.replica_id = "r0"
        with obs_http.ObsHttpServer(reg_child, port=0).start() as srv:
            hps = _hps(serve_scrape_interval_ms=0.0)
            reg = Registry()
            remote = procfleet.RemoteReplica(
                "r0", _FakeProc(ports={"obs_port": srv.port},
                                pid=os.getpid()),
                hps, registry=reg)
            handle = procfleet.RemoteReplicaHandle("r0", remote,
                                                   registry=reg)
            payload = remote.scrape_healthz()
            assert payload is not None and payload["status"] == "ok"
            assert payload["pid"] == os.getpid()
            assert handle.healthy()
            # wrong incarnation: same port answering, different pid
            remote2 = procfleet.RemoteReplica(
                "r0", _FakeProc(ports={"obs_port": srv.port}, pid=99999),
                hps, registry=Registry())
            handle2 = procfleet.RemoteReplicaHandle(
                "r0", remote2, registry=Registry())
            assert not handle2.healthy()


# -- portfile handshake ----------------------------------------------------

class TestPortfileHandshake:
    def test_stale_portfile_never_resolves(self, tmp_path):
        """ports() pid-checks the portfile: a file written by a
        previous (or foreign) incarnation is invisible — readiness can
        only pass against OUR child's published ports."""
        proc = procfleet.ReplicaProcess(
            "r0", SLEEP_CMD, dict(os.environ), str(tmp_path),
            registry=Registry())
        proc.start()
        try:
            assert proc.ports() is None  # child never writes one
            stale = {"pid": proc.pid() + 12345, "obs_port": 1,
                     "ingress_port": 2, "reply_port": 3}
            with open(proc.portfile, "w", encoding="utf-8") as f:
                json.dump(stale, f)
            assert proc.ports() is None, (
                "a portfile with a foreign pid resolved — stale "
                "incarnations can pass readiness")
            good = dict(stale, pid=proc.pid())
            with open(proc.portfile, "w", encoding="utf-8") as f:
                json.dump(good, f)
            assert proc.ports() == good
        finally:
            proc.halt()

    def test_spawn_unlinks_previous_portfile(self, tmp_path):
        """A restart must not race against the corpse's portfile: the
        fresh spawn removes it before the child can be probed."""
        proc = procfleet.ReplicaProcess(
            "r0", SLEEP_CMD, dict(os.environ), str(tmp_path),
            registry=Registry(), restart_base_delay=0.01,
            restart_max_delay=0.02)
        proc.start()
        try:
            with open(proc.portfile, "w", encoding="utf-8") as f:
                json.dump({"pid": proc.pid(), "obs_port": 1,
                           "ingress_port": 2, "reply_port": 3}, f)
            assert proc.ports() is not None
            proc.restart_for_swap()
            assert not os.path.exists(proc.portfile)
            assert proc.ports() is None
            assert proc.incarnation == 2
        finally:
            proc.halt()


# -- satellite 3: exactly-once reply transport -----------------------------

class _ReplayServer:
    """A fake child reply port that DIES once: connection 1 streams its
    frames then drops (the restart); connection 2 REPLAYS the ring from
    the start plus the post-restart frames — the at-least-once behavior
    _ReplyHub really has."""

    def __init__(self, first, second):
        self._payloads = [first, second]
        self.done = threading.Event()
        self._srv = socket.create_server(("127.0.0.1", 0))
        self.port = self._srv.getsockname()[1]
        threading.Thread(target=self._serve, daemon=True).start()

    def _serve(self):
        for i, frames in enumerate(self._payloads):
            conn, _ = self._srv.accept()
            for frame in frames:
                conn.sendall((frame + "\n").encode("utf-8"))
            if i == len(self._payloads) - 1:
                self.done.set()
            conn.close()
        self._srv.close()


def _frame(uuid, seq, summary="s ."):
    d = json.loads(Message(uuid, f"article {uuid}", summary=summary,
                           reference="r").to_json())
    d["seq"] = seq
    return json.dumps(d, sort_keys=True)


class TestReplyExactlyOnce:
    def test_ring_replay_across_restart_dedups_on_uuid_seq(self):
        """The satellite's scenario end to end at the transport layer:
        uuid X delivered, the stream dies, the reconnect replays X
        (same seq) before the new frame Y — the ResilientSource LRU
        collapses the replay, while a RE-submitted X under a fresh seq
        (a router requeue landing back here) passes."""
        srv = _ReplayServer(
            first=[_frame("X", 0)],
            second=[_frame("X", 0), _frame("Y", 1), _frame("X", 7)])

        def ports_fn():
            if srv.done.is_set():
                raise procfleet._ReaderStopped()
            return {"reply_port": srv.port}

        seen = []
        source = ResilientSource(
            lambda: procfleet._ReplySource(
                ports_fn, 5.0, lambda s: None,
                Registry().counter("x").labels(replica="r0")),
            max_reconnects=1_000_000, base_delay=0.001, max_delay=0.001,
            seed=0, dedup=True, dedup_window=65536,
            schema=procfleet._REPLY_SCHEMA, sleep=lambda d: None)
        with pytest.raises(procfleet._ReaderStopped):
            for key, msg in source.rows():
                seen.append((msg.uuid, key[1]))
        assert seen == [("X", 0), ("Y", 1), ("X", 7)], (
            f"replayed frames leaked through the dedup window: {seen}")

    def test_orphan_reply_frame_is_dropped_not_double_resolved(self):
        """Above the transport: _on_reply settles the FIFO pending
        entry exactly once; a second frame for the same uuid (a replay
        that outran the dedup window, or a reply racing the death path)
        finds no pending entry and is dropped."""
        remote = procfleet.RemoteReplica("r0", _FakeProc(), _hps(),
                                         registry=Registry())
        fut = ServeFuture("X")
        remote._pending["X"] = [(fut, "article X", "ref", "")]
        remote._on_reply(Message("X", "article X", summary="ok .",
                                 reference="ref"))
        res = fut.result(timeout=1)
        assert (res.summary, res.reference) == ("ok .", "ref")
        assert remote.load() == 0
        # the replay: no pending entry -> dropped, result unchanged
        remote._on_reply(Message("X", "article X", summary="DIFFERENT",
                                 reference="ref"))
        assert fut.result(timeout=1).summary == "ok ."

    def test_error_frame_rejects_typed(self):
        """A child-side shed crosses the wire as ``error`` and rejects
        the local future with the SAME exception type the in-process
        server would have raised — the router's shed accounting cannot
        tell the transports apart."""
        remote = procfleet.RemoteReplica("r0", _FakeProc(), _hps(),
                                         registry=Registry())
        fut = ServeFuture("Y")
        remote._pending["Y"] = [(fut, "a", "r", "")]
        remote._on_reply(Message("Y", "a",
                                 error="ServeOverloadError: queue full"))
        with pytest.raises(ServeOverloadError, match="queue full"):
            fut.result(timeout=1)


# -- crash-loop containment ------------------------------------------------

class TestCrashLoop:
    def _drive_to_containment(self, proc, deadline_s=30.0):
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            proc.tick()
            if proc.breaker.state == CircuitBreaker.OPEN:
                return
            time.sleep(0.01)
        pytest.fail(f"crash-loop breaker never tripped "
                    f"(deaths={proc.deaths}, state={proc.state})")

    def test_crashloop_trips_breaker_dumps_flight_files_incident(
            self, tmp_path):
        """The containment gate: a child dying K consecutive times
        trips the breaker, counts the crashloop, dumps the flight ring,
        files the /alerts incident — and restarts STOP (no spawn storm)
        until the half-open probe window."""
        reg = Registry()
        flightrec.install_flight_recorder(reg, str(tmp_path))
        proc = procfleet.ReplicaProcess(
            "p0", CRASH_CMD, dict(os.environ), str(tmp_path),
            registry=reg, restart_base_delay=0.01, restart_max_delay=0.02,
            crashloop_threshold=2, crashloop_window=600.0)
        proc.start()
        self._drive_to_containment(proc)
        proc.halt()

        assert proc.deaths >= 2
        assert proc.last_exit_code == 13
        spawned = proc.incarnation
        assert spawned <= 3, (
            f"{spawned} spawns before containment — the breaker is not "
            f"bounding the restart storm")
        # OPEN sheds every restart: ticks do not spawn incarnations
        for _ in range(25):
            proc.tick()
        assert proc.incarnation == spawned
        crashloops = reg.counter(
            "serve/replica_crashloops_total").labels(replica="p0")
        assert crashloops.value == 1
        deaths = reg.counter(
            "serve/replica_deaths_total").labels(replica="p0")
        assert deaths.value == proc.deaths
        dumps = glob.glob(str(tmp_path / "flight_replica_crashloop*.jsonl"))
        assert dumps, "containment did not dump the flight ring"
        with open(dumps[0], "r", encoding="utf-8") as f:
            header = json.loads(f.readline())
        assert header["reason"] == "replica_crashloop"
        kinds = [i["kind"] for i in obs_http.incidents(reg)]
        assert "replica_crashloop" in kinds, (
            "the crashloop never reached the /alerts incident feed")

    def test_half_open_probe_readmits_a_recovered_child(self, tmp_path):
        """After the hold-out window the breaker hands out ONE probe
        spawn; a child that stays up closes the breaker and clears
        containment (driven on an injected clock — no wall-clock
        waits on the window)."""
        clock = [100.0]
        reg = Registry()
        proc = procfleet.ReplicaProcess(
            "p0", SLEEP_CMD, dict(os.environ), str(tmp_path),
            registry=reg, clock=lambda: clock[0],
            restart_base_delay=0.01, restart_max_delay=0.02,
            crashloop_threshold=1, crashloop_window=30.0)
        # one death trips the threshold-1 breaker
        proc.state = proc.BACKOFF
        proc.incarnation = 1
        proc._on_exit(13)
        assert proc.breaker.state == CircuitBreaker.OPEN
        assert proc._contained
        clock[0] += 0.05
        proc.tick()  # inside the hold-out: OPEN sheds the restart
        assert proc.proc is None and proc.incarnation == 1
        clock[0] += 31.0  # the window rolls -> half-open probe spawn
        proc.tick()
        try:
            assert proc.state == proc.STARTING and proc.incarnation == 2
            # fake the probe reaching readiness (the sleep child has no
            # obs plane): _mark_ready closes the breaker + uncontains
            proc._mark_ready()
            assert proc.breaker.state == CircuitBreaker.CLOSED
            assert not proc._contained
        finally:
            proc.halt()

    def test_fleet_keeps_serving_around_a_crashlooping_replica(
            self, tmp_path):
        """The acceptance clause: one replica crash-looping into
        containment must not take the fleet down — its handle leaves
        rotation on the first detected death and every request resolves
        on the healthy replica."""
        from textsummarization_on_flink_tpu.data.vocab import Vocab
        from textsummarization_on_flink_tpu.decode.decoder import \
            DecodedResult
        from textsummarization_on_flink_tpu.serve.fleet import FleetRouter
        from textsummarization_on_flink_tpu.serve.server import \
            ServingServer

        class _NullDecoder:
            def maybe_reload_checkpoint(self, last):
                return last

        class _OkEngine:
            """2-slot, 2-chunk-per-request sim engine (jax-free)."""

            def __init__(self):
                self.slots, self.chunk = 2, 1
                self._rem = [0, 0]

            def pack(self, idx, ex):
                self._rem[idx] = 2
                self._ex = getattr(self, "_ex", {})
                self._ex[idx] = ex

            def step(self):
                fin = []
                for i in range(self.slots):
                    if self._rem[i] > 0:
                        self._rem[i] -= 1
                        if self._rem[i] == 0:
                            fin.append(i)
                return fin

            def unpack(self, idx, ex):
                return DecodedResult(
                    uuid=ex.uuid, article=ex.original_article,
                    decoded_words=["ok", "."], reference=ex.reference,
                    abstract_sents=[])

            def release(self, idx):
                self._rem[idx] = 0

        reg = Registry()
        vocab = Vocab(words=["w"])
        hps = _hps(serve_mode="continuous", serve_refill_chunk=1,
                   serve_replicas=2, vocab_size=vocab.size())
        good = ServingServer(hps, vocab, decoder=_NullDecoder(),
                             engine=_OkEngine(), registry=Registry())

        proc = procfleet.ReplicaProcess(
            "bad", CRASH_CMD, dict(os.environ), str(tmp_path),
            registry=reg, restart_base_delay=0.01, restart_max_delay=0.02,
            crashloop_threshold=2, crashloop_window=600.0)
        remote = procfleet.RemoteReplica("bad", proc, hps, registry=reg)
        bad = procfleet.RemoteReplicaHandle("bad", remote, registry=reg)
        remote.handle = bad
        proc.on_death = remote.on_child_death

        router = FleetRouter({"good": good, "bad": bad}, hps, registry=reg)
        proc.start()
        self._drive_to_containment(proc)
        assert not bad.in_rotation(), (
            "a crash-looping replica is still in routing rotation")

        futs = [router.submit("w w .", uuid=f"u{i}") for i in range(4)]
        rounds = 0
        while not all(f.done() for f in futs):
            rounds += 1
            assert rounds < 500, "fleet did not drain around the corpse"
            router.tick()
            good.tick_once(poll=0.0)
        assert [f.result(timeout=1).uuid for f in futs] == \
            [f"u{i}" for i in range(4)]
        router.stop()
        proc.halt()


# -- PR 18: lock discipline on the scrape and ingress paths ----------------

class TestScrapeLockDiscipline:
    """TS008/TS009 regression (tools/tslint v2): the scrape cache is
    lock-protected, but the HTTP probe itself must run with NO lock
    held — a wedged child costs the scraping thread one timeout, never
    every reader queued behind the scrape lock."""

    def _remote(self, payloads):
        hps = HParams(serve_scrape_timeout_ms=150.0,
                      serve_scrape_interval_ms=60_000.0)
        remote = procfleet.RemoteReplica(
            "r0", _FakeProc(ports={"obs_port": 1}), hps,
            registry=Registry())
        return remote

    def test_http_probe_runs_outside_the_scrape_lock(self, monkeypatch):
        remote = self._remote(None)
        held_during_http = []

        def fake_healthz(port, timeout_s):
            held_during_http.append(remote._scrape_lock.locked())
            return {"serve": {"params_fingerprint": "fp0"}}

        monkeypatch.setattr(procfleet, "_http_healthz", fake_healthz)
        assert remote.scrape_healthz() is not None
        assert held_during_http == [False], (
            "the HTTP scrape ran WITH the scrape lock held — a wedged "
            "child would stall every cache reader for the timeout")
        assert remote.params_fingerprint == "fp0"
        # cache hit: no second probe inside the window
        assert remote.scrape_healthz() is not None
        assert len(held_during_http) == 1

    def test_supervisor_invalidation_races_cleanly_with_a_scrape(
            self, monkeypatch):
        """on_child_ready/on_child_death clear the cache under the same
        lock the scraper writes through: an invalidation landing MID
        scrape must neither crash nor be silently lost forever — the
        next read re-probes within one window."""
        remote = self._remote(None)
        in_http = threading.Event()
        release_http = threading.Event()

        def fake_healthz(port, timeout_s):
            in_http.set()
            release_http.wait(timeout=5.0)
            return {"serve": {}}

        monkeypatch.setattr(procfleet, "_http_healthz", fake_healthz)
        t = threading.Thread(target=remote.scrape_healthz)
        t.start()
        assert in_http.wait(timeout=5.0)
        remote.on_child_death(exit_code=9)  # must not block on the probe
        release_http.set()
        t.join(timeout=5.0)
        assert not t.is_alive()
        # last-write-wins is allowed; what is NOT allowed is a wedge or
        # an exception — and a fresh scrape still works afterward
        remote.on_child_ready(remote._proc)
        assert remote.scrape_healthz() is not None


class TestIngressLockDiscipline:
    """TS008 regression: connection ESTABLISHMENT happens with the
    ingress lock dropped (a refusing/slow child stalls one connector,
    not every sender); only the sendall stays serialized."""

    def _remote(self, port):
        hps = HParams(serve_scrape_timeout_ms=200.0)
        return procfleet.RemoteReplica(
            "r0", _FakeProc(ports={"ingress_port": port, "obs_port": 1}),
            hps, registry=Registry())

    def test_connect_runs_outside_the_ingress_lock(self, monkeypatch):
        srv = socket.create_server(("127.0.0.1", 0))
        srv.listen(1)
        port = srv.getsockname()[1]
        remote = self._remote(port)
        held_during_connect = []
        real_connect = socket.create_connection

        def spy_connect(addr, timeout=None):
            held_during_connect.append(remote._ingress_lock.locked())
            return real_connect(addr, timeout=timeout)

        monkeypatch.setattr(procfleet.socket, "create_connection",
                            spy_connect)
        try:
            remote._send_ingress("hello")
            conn, _ = srv.accept()
            conn.settimeout(2.0)
            assert conn.recv(64) == b"hello\n"
            conn.close()
        finally:
            remote._close_ingress()
            srv.close()
        assert held_during_connect == [False], (
            "socket.create_connection ran WITH _ingress_lock held — "
            "every sender stalls for the connect timeout")

    def test_refused_connect_still_raises_after_retry(self, monkeypatch):
        # a dead port: both attempts fail, the typed OSError surfaces,
        # and the lock is left unheld for the next submit
        srv = socket.create_server(("127.0.0.1", 0))
        port = srv.getsockname()[1]
        srv.close()  # nothing listens here any more
        remote = self._remote(port)
        attempts = []
        real_connect = socket.create_connection

        def spy_connect(addr, timeout=None):
            attempts.append(remote._ingress_lock.locked())
            return real_connect(addr, timeout=timeout)

        monkeypatch.setattr(procfleet.socket, "create_connection",
                            spy_connect)
        with pytest.raises(OSError):
            remote._send_ingress("hello")
        assert attempts == [False, False]
        assert not remote._ingress_lock.locked()
