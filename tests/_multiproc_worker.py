"""Worker process for the real 2-process jax.distributed test.

NOT a pytest file (leading underscore): tests/test_multiprocess.py spawns
two of these, each with 2 virtual CPU devices, so the multi-host paths —
`jax.distributed.initialize`, `make_host_local_transfer` /
`host_local_array_to_global_array`, the collective checkpoint gather
(`process_allgather`), chief-only writers, `barrier()` — run with a REAL
process_count of 2 instead of a monkeypatched one (the reference has no
multi-worker tests at all, SURVEY §4; this rebuild claims the capability
so it must prove it).

Usage (spawned by the test, not by hand):
    python _multiproc_worker.py <port> <process_id> <workdir> [dp,tp[,wire]]

[dp,tp] defaults to "4,1" (pure data parallelism, replicated params —
the easy checkpoint gather).  "2,2" additionally shards params over the
tp axis ACROSS the two hosts, so the collective checkpoint gather must
fetch non-addressable shards (checkpointer.state_to_arrays's
process_allgather path) — the hard case.  An optional third component
("2,2,bfloat16") sets --grad_allreduce_dtype, running the unified
step's wire-annotated gradient all-reduce (ISSUE 8) across the two
REAL processes — the dp x tp composition the retired shard_map path
rejected.
"""

import json
import os
import sys


def main() -> int:
    port, pid, workdir = (int(sys.argv[1]), int(sys.argv[2]), sys.argv[3])
    parts = (sys.argv[4] if len(sys.argv) > 4 else "4,1").split(",")
    dp, tp = int(parts[0]), int(parts[1])
    wire = parts[2] if len(parts) > 2 else "float32"

    import jax
    import numpy as np

    from textsummarization_on_flink_tpu.config import HParams
    from textsummarization_on_flink_tpu.checkpoint.checkpointer import (
        Checkpointer,
        state_to_arrays,
    )
    from textsummarization_on_flink_tpu.data import Vocab
    from textsummarization_on_flink_tpu.data.batching import (
        Batch,
        SummaryExample,
    )
    from textsummarization_on_flink_tpu.parallel import distributed
    from textsummarization_on_flink_tpu.train.trainer import Trainer
    from textsummarization_on_flink_tpu.utils import local_batch_hps

    distributed.initialize(coordinator_address=f"localhost:{port}",
                           num_processes=2, process_id=pid)
    info = {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": jax.local_device_count(),
        "global_devices": jax.device_count(),
        "is_chief": distributed.is_chief(),
    }
    assert info["process_count"] == 2, info
    assert info["global_devices"] == 4, info

    # Global batch 8 over the dp axis: each host feeds its own rows
    # (that IS data parallelism — the transfer must not interleave
    # them).  With tp>1 the vocab-axis params shard across hosts.
    hps = HParams(batch_size=8, max_enc_steps=6, max_dec_steps=5,
                  min_dec_steps=1, hidden_dim=4, emb_dim=3,
                  max_oov_buckets=2, vocab_size=0, dp=dp, tp=tp,
                  grad_allreduce_dtype=wire,
                  log_root=workdir, exp_name="mp")
    # 8 words + 4 specials = vocab 12: divisible by tp=2 for the
    # sharded-projection variant
    vocab = Vocab(words=["a", "b", "c", "d", "e", "f", "g", "."])
    local_hps = local_batch_hps(hps)
    assert local_hps.batch_size == 4
    # different text per host: host-local batches are NOT replicas
    texts = (["a b c d", "b c d e", "c d e f", "d e f a"] if pid == 0
             else ["f e d c", "e d c b", "d c b a", "c b a f"])
    exs = [SummaryExample.build(t, [t.split()[0] + " ."], vocab, local_hps)
           for t in texts]
    local_batch = Batch(exs, local_hps, vocab)

    class FixedBatcher:
        def __init__(self, batch, n):
            self.batch, self.n = batch, n

        def next_batch(self):
            if self.n <= 0:
                return None
            self.n -= 1
            return self.batch

    train_dir = os.path.join(workdir, "mp", "train")
    ckpt = Checkpointer(train_dir, hps=hps)
    trainer = Trainer(hps, vocab.size(), FixedBatcher(local_batch, 50),
                      checkpointer=ckpt, checkpoint_steps=3,
                      metrics_every=2, train_dir=train_dir)
    state = trainer.train(num_steps=5)
    # the production collective fetch path (same call the checkpointer
    # makes; every host must participate)
    info["final_step"] = int(np.asarray(state_to_arrays(state)["step"]))

    distributed.barrier("post-train")

    # every host restores the chief-written checkpoint identically
    restored = ckpt.restore()
    assert restored is not None, "no checkpoint found after training"
    info["restored_step"] = int(np.asarray(restored.step))
    leaves = jax.tree_util.tree_leaves(restored.params)
    info["param_checksum"] = float(
        sum(np.abs(np.asarray(leaf)).sum() for leaf in leaves))
    info["ckpt_files"] = sorted(
        os.path.basename(p) for p in os.listdir(train_dir)
        if p.endswith(".npz"))

    # resume-from-checkpoint must keep collectives in lockstep too; the
    # resumed run also exercises multi-step dispatch (steps_per_dispatch
    # scans k sharded steps — with their dp-axis psums — in ONE dispatch
    # per host)
    trainer2 = Trainer(hps.replace(steps_per_dispatch=2), vocab.size(),
                       FixedBatcher(local_batch, 50),
                       state=restored, checkpointer=ckpt,
                       checkpoint_steps=3, train_dir=train_dir)
    state2 = trainer2.train(num_steps=7)  # 2 more steps past the restore
    info["resumed_step"] = int(np.asarray(state_to_arrays(state2)["step"]))

    distributed.barrier("post-resume")
    with open(os.path.join(workdir, f"worker{pid}.json"), "w") as f:
        json.dump(info, f)
    return 0


if __name__ == "__main__":
    sys.exit(main())
