"""Backtrack-reconstruction parity vs a materialized-history mirror
(ISSUE 7).

The decode byte diet replaced the beam search's per-hypothesis
trajectory buffers (tokens/attention/p_gen gathered by parent every
step) with backpointer columns and a `_finalize_beam` backtrack.  This
module re-implements the PRE-PR bookkeeping — full per-hypothesis
buffers, host-side, gathered by parent each step — around the SAME
jitted family step closures, so any disagreement isolates the
backpointer/backtrack translation, not the numerics.  Pinned for BOTH
model families across all three loop kinds and the slot kernels, plus
the bf16 KV-cache drift envelope and the engine compile-count claim.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from test_beam_search import make_arrays

from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.data.vocab import START_ID, STOP_ID, UNK_ID
from textsummarization_on_flink_tpu.decode import beam_search
from textsummarization_on_flink_tpu.models import get_family
from textsummarization_on_flink_tpu.obs import Registry
from textsummarization_on_flink_tpu.obs import profile as profile_lib

PG_HPS = HParams(batch_size=2, hidden_dim=8, emb_dim=6, vocab_size=24,
                 max_enc_steps=12, max_dec_steps=8, beam_size=3,
                 min_dec_steps=2, max_oov_buckets=4, mode="decode")
TF_HPS = PG_HPS.replace(model_family="transformer", hidden_dim=8, emb_dim=8,
                        num_heads=2, enc_layers=2, dec_layers=2)
# the AAN draft family (ISSUE 10) rides the same generic mirror: its
# beam-adapter parity through while/scan/chunked AND the slot kernels
# is exactly this module's parametrization
AAN_HPS = TF_HPS.replace(model_family="avg_attention")

FAMILY_CASES = [
    pytest.param("pointer_generator", PG_HPS, id="pg"),
    pytest.param("transformer", TF_HPS, id="tf"),
    pytest.param("avg_attention", AAN_HPS, id="aan"),
]


@dataclasses.dataclass
class Hyp:
    """One materialized hypothesis: FULL token/attention/p_gen
    trajectories carried explicitly — the pre-PR representation."""

    tokens: list
    lp: np.float32
    attn: list  # one [T_enc] row per generated token
    pgens: list
    slot: int  # row in the stacked device state

    @property
    def avg(self):
        return self.lp / len(self.tokens)


def materialized_search(params, hps, family, arrays, b):
    """The pre-PR search transliterated to the host: list-of-Hypothesis
    with materialized histories, parent gathers via tree_map(x[parents])
    on the family's opaque decode state, same triage order."""
    enc_view = family.beam_encode(params, hps, arrays)
    enc_one = jax.tree_util.tree_map(lambda x: x[b], enc_view)
    mask = jnp.asarray(arrays["enc_padding_mask"][b])
    ext = jnp.asarray(arrays["enc_batch_extend_vocab"][b])
    init_state_fn, step_fn = family.beam_adapter(hps)
    state = init_state_fn(params, enc_one)
    step_jit = jax.jit(lambda t, latest, st: step_fn(
        params, enc_one, mask, ext, t, latest, st))
    K = hps.beam_size
    hyps = [Hyp([START_ID], np.float32(0.0), [], [], i) for i in range(K)]
    results = []
    steps = 0
    while steps < hps.max_dec_steps and len(results) < K:
        latest = np.array([h.tokens[-1] for h in hyps], np.int32)
        latest = np.where(latest >= hps.vocab_size, UNK_ID, latest)
        out = step_jit(jnp.int32(steps), jnp.asarray(latest), state)
        topk_ids = np.asarray(out.topk_ids)
        topk_lp = np.asarray(out.topk_log_probs, np.float32)
        attn = np.asarray(out.attn_dist)
        pgen = np.asarray(out.p_gen)
        cands = []  # hyp-major, like the device's stable argsort
        num_orig = 1 if steps == 0 else K
        for i in range(num_orig):
            for j in range(2 * K):
                cands.append((hyps[i], int(topk_ids[i, j]),
                              np.float32(hyps[i].lp + topk_lp[i, j]), i))
        new_hyps = []
        for h, tok, lp, parent in sorted(cands, key=lambda c: -c[2]):
            if tok == STOP_ID:
                if steps >= hps.min_dec_steps:
                    results.append(Hyp(h.tokens + [tok], lp,
                                       h.attn + [attn[parent]],
                                       h.pgens + [pgen[parent]], -1))
            else:
                new_hyps.append(Hyp(h.tokens + [tok], lp,
                                    h.attn + [attn[parent]],
                                    h.pgens + [pgen[parent]], parent))
            if len(new_hyps) == K or len(results) == K:
                break
        if len(results) < K:
            assert len(new_hyps) == K, "mirror beam underfilled"
        parents = np.array(
            [h.slot for h in new_hyps] + [0] * (K - len(new_hyps)),
            np.int32)
        state = jax.tree_util.tree_map(
            lambda x: x[jnp.asarray(parents)], out.state)
        for i, h in enumerate(new_hyps):
            h.slot = i
        hyps = new_hyps if new_hyps else hyps
        steps += 1
    pool = results if results else hyps
    return sorted(pool, key=lambda h: h.avg, reverse=True)[0]


def assert_matches_mirror(out, b, ref):
    """Device BeamSearchOutput row b vs a mirror Hyp: tokens exact,
    reconstructed attention/p_gen rows exact, zero-fill past the end."""
    n = int(out.length[b])
    assert list(np.asarray(out.tokens[b])[:n]) == ref.tokens
    np.testing.assert_allclose(np.asarray(out.avg_log_prob[b]), ref.avg,
                               rtol=2e-5, atol=2e-6)
    gen = n - 1  # generated tokens incl a final STOP, if any
    assert len(ref.attn) == gen
    np.testing.assert_allclose(np.asarray(out.attn_dists[b])[:gen],
                               np.stack(ref.attn), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(out.p_gens[b])[:gen],
                               np.array(ref.pgens), rtol=1e-5, atol=1e-6)
    # rows past the trajectory are zero, exactly like the pre-PR buffers
    np.testing.assert_array_equal(np.asarray(out.attn_dists[b])[gen:], 0.0)
    np.testing.assert_array_equal(np.asarray(out.p_gens[b])[gen:], 0.0)


@pytest.mark.parametrize("loop", ["while", "scan", "chunked"])
@pytest.mark.parametrize("family_name,hps", FAMILY_CASES)
def test_backtrack_matches_materialized_mirror(family_name, hps, loop):
    """The tentpole parity claim: backpointer histories + the finalize
    backtrack reproduce the materialized-history search token-exactly
    (tokens, length, avg_log_prob, attn_dists, p_gens) for both model
    families and every loop kind."""
    family = get_family(family_name)
    params = family.init_params(hps, hps.vocab_size, jax.random.PRNGKey(3))
    arrays = make_arrays(hps, seed=6)
    out = beam_search.run_beam_search_jit(
        params, hps, arrays, loop=loop,
        chunk=3 if loop == "chunked" else None)
    for b in range(hps.batch_size):
        ref = materialized_search(params, hps, family, arrays, b)
        assert_matches_mirror(out, b, ref)


@pytest.mark.parametrize("family_name,hps", FAMILY_CASES)
def test_backtrack_matches_mirror_no_early_exit(family_name, hps):
    """The live-beam fallback path of the backtrack (n_res == 0 at the
    horizon): min_dec_steps near the horizon discards most STOPs, so
    reconstruction anchors on the live beam."""
    hps = hps.replace(min_dec_steps=hps.max_dec_steps - 1)
    family = get_family(family_name)
    params = family.init_params(hps, hps.vocab_size, jax.random.PRNGKey(5))
    arrays = make_arrays(hps, seed=2)
    out = beam_search.run_beam_search_jit(params, hps, arrays, loop="scan")
    for b in range(hps.batch_size):
        ref = materialized_search(params, hps, family, arrays, b)
        assert_matches_mirror(out, b, ref)


def _drive_slots(params, hps, state, slots, chunk=3, max_chunks=16):
    active = np.ones(slots, bool)
    done = {}
    for _ in range(max_chunks):
        state, fin = beam_search.step_slots_jit(params, hps, state,
                                                active, chunk)
        for s in np.nonzero(np.asarray(fin))[0]:
            done[int(s)] = beam_search.unpack_slot_jit(hps, state, int(s))
            active[s] = False
        if not active.any():
            break
    return done


def _assert_slot_matches_mirror(out, ref):
    n = int(out.length)
    assert list(np.asarray(out.tokens)[:n]) == ref.tokens
    np.testing.assert_allclose(np.asarray(out.avg_log_prob), ref.avg,
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(np.asarray(out.attn_dists)[:n - 1],
                               np.stack(ref.attn), rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("family_name,hps", FAMILY_CASES)
def test_slot_kernels_match_materialized_mirror(family_name, hps):
    """The slot kernels (continuous serving) run the same backpointer
    body per resident article: prefill -> pack -> chunked steps ->
    unpack must match the materialized mirror exactly, for both
    families (and the AAN draft tier)."""
    family = get_family(family_name)
    params = family.init_params(hps, hps.vocab_size, jax.random.PRNGKey(3))
    arrays = make_arrays(hps, seed=6)
    slots = hps.batch_size
    zero = {k: np.zeros((slots,) + v.shape[1:], v.dtype)
            for k, v in arrays.items()}
    state = beam_search.init_slots_jit(params, hps, zero)
    for slot in range(slots):
        one = {k: v[slot:slot + 1] for k, v in arrays.items()}
        state = beam_search.pack_slot_jit(
            params, hps, state, slot,
            beam_search.prefill_jit(params, hps, one))
    done = _drive_slots(params, hps, state, slots)
    assert sorted(done) == list(range(slots))
    for b in range(slots):
        ref = materialized_search(params, hps, family, arrays, b)
        _assert_slot_matches_mirror(done[b], ref)


# -- prefill/decode disaggregation parity (ISSUE 11) -----------------------
#
# The mirror is the FULL-WIDTH dense search; the slot path now prefills
# each article at its BUCKET shape and decodes with the valid-length
# mask and the blocked (conditional-chain) cross-attention.  Exactness
# across bucket lengths is the claim that disaggregation changed the
# COST story, not the numerics: the encoders are pad-invariant, the
# padded encoder tail sits behind the valid-length mask, and an
# uncovered key block's energies land on the same masked floor dense
# padding does.

#: articles engineered at the satellite's edge cases, as true lengths
#: against buckets (4, 8, 12) at the 12-wide test scale: a 1-token
#: article, one exactly AT a bucket boundary, one mid-bucket, and one
#: at the top bucket — packed together (mixed-length occupancy).
_DISAGG_LENS = (1, 4, 7, 12)
_DISAGG_BUCKETS = (4, 8, 12)


def _arrays_with_lens(hps, lens, seed=0):
    arrays = make_arrays(hps, seed=seed, B=len(lens))
    T = hps.max_enc_steps
    lens = np.asarray(lens, np.int32)
    mask = (np.arange(T)[None, :] < lens[:, None]).astype(np.float32)
    arrays["enc_lens"] = lens
    arrays["enc_padding_mask"] = mask
    arrays["enc_batch"] = (arrays["enc_batch"] * mask).astype(np.int32)
    ext = arrays["enc_batch_extend_vocab"]
    arrays["enc_batch_extend_vocab"] = np.where(mask > 0, ext,
                                                0).astype(np.int32)
    return arrays


@pytest.mark.parametrize("family_name,hps", FAMILY_CASES)
def test_bucketed_prefill_matches_mirror_at_every_length(family_name, hps):
    """Mixed-length slot occupancy through the DISAGGREGATED path:
    each article prefilled at its own bucket (1-token -> bucket 4,
    boundary article -> its exact bucket, top-length article -> the
    resident width), decoded together under the blocked cross-attention
    in the multi-block regime (decode_enc_block=4 at T_enc=12), and
    every trajectory must still match the full-width materialized
    mirror token-exactly."""
    hps = hps.replace(batch_size=len(_DISAGG_LENS), decode_enc_block=4)
    family = get_family(family_name)
    params = family.init_params(hps, hps.vocab_size, jax.random.PRNGKey(3))
    arrays = _arrays_with_lens(hps, _DISAGG_LENS, seed=6)
    slots = len(_DISAGG_LENS)
    zero = {k: np.zeros((slots,) + v.shape[1:], v.dtype)
            for k, v in arrays.items()}
    state = beam_search.init_slots_jit(params, hps, zero)
    for slot, true_len in enumerate(_DISAGG_LENS):
        bucket = next(b for b in _DISAGG_BUCKETS if true_len <= b)
        one = {k: (v[slot:slot + 1, :bucket] if v.ndim == 2
                   else v[slot:slot + 1])
               for k, v in arrays.items()}
        pre = beam_search.prefill_jit(params, hps, one)
        assert int(np.asarray(pre.enc_valid_len)[0]) == true_len
        state = beam_search.pack_slot_jit(params, hps, state, slot, pre)
    # the resident state records every article's TRUE length, not its
    # bucket or the padded width
    np.testing.assert_array_equal(
        np.asarray(state.enc_valid_len), np.asarray(_DISAGG_LENS))
    done = _drive_slots(params, hps, state, slots)
    assert sorted(done) == list(range(slots))
    for b in range(slots):
        ref = materialized_search(params, hps, family, arrays, b)
        _assert_slot_matches_mirror(done[b], ref)


class TestBf16KVCache:
    """--decode_cache_dtype=bfloat16 (transformer): the cache narrows in
    storage only — attention math stays f32 — with a pinned drift
    envelope vs the f32 cache."""

    def _outputs(self, dtype):
        hps = TF_HPS.replace(decode_cache_dtype=dtype)
        family = get_family("transformer")
        params = family.init_params(hps, hps.vocab_size,
                                    jax.random.PRNGKey(7))
        arrays = make_arrays(hps, seed=4)
        return beam_search.run_beam_search_jit(params, hps, arrays,
                                               loop="scan")

    def test_pg_family_ignores_cache_dtype(self):
        """The LSTM family has no KV cache: bf16 must be a no-op."""
        hps = PG_HPS.replace(decode_cache_dtype="bfloat16")
        family = get_family("pointer_generator")
        params = family.init_params(hps, hps.vocab_size,
                                    jax.random.PRNGKey(7))
        arrays = make_arrays(hps, seed=4)
        a = beam_search.run_beam_search_jit(params, hps, arrays, loop="scan")
        b = beam_search.run_beam_search_jit(
            params, PG_HPS, arrays, loop="scan")
        np.testing.assert_array_equal(np.asarray(a.tokens),
                                      np.asarray(b.tokens))
        np.testing.assert_array_equal(np.asarray(a.avg_log_prob),
                                      np.asarray(b.avg_log_prob))

    def test_bf16_cache_drift_envelope(self):
        """End-to-end drift envelope: same params/articles decoded with
        the f32 and bf16 caches must agree to bf16 resolution — the
        searches emit valid trajectories whose per-article average
        log-prob drifts by < 2e-2 (bf16 has ~3 significant digits; the
        f32 softmax math keeps the rounding from compounding)."""
        a = self._outputs("float32")
        b = self._outputs("bfloat16")
        np.testing.assert_allclose(np.asarray(a.avg_log_prob),
                                   np.asarray(b.avg_log_prob), atol=2e-2)
        assert np.asarray(b.length).min() >= 2
        # attention rows remain distributions under the narrowed cache
        for row, n in zip(np.asarray(b.attn_dists),
                          np.asarray(b.length)):
            np.testing.assert_allclose(row[: n - 1].sum(axis=-1), 1.0,
                                       atol=1e-4)

    def test_bf16_cache_single_step_envelope(self):
        """One controlled adapter step, identical inputs, f32 vs bf16
        cache: top-2K log-probs and attention within bf16 tolerance (the
        direct storage-only claim, no search dynamics in the way)."""
        family = get_family("transformer")
        outs = {}
        for dtype in ("float32", "bfloat16"):
            hps = TF_HPS.replace(decode_cache_dtype=dtype)
            params = family.init_params(hps, hps.vocab_size,
                                        jax.random.PRNGKey(7))
            arrays = make_arrays(hps, seed=4)
            enc_view = family.beam_encode(params, hps, arrays)
            enc_one = jax.tree_util.tree_map(lambda x: x[0], enc_view)
            init_state_fn, step_fn = family.beam_adapter(hps)
            state = init_state_fn(params, enc_one)
            latest = jnp.full((hps.beam_size,), START_ID, jnp.int32)
            out = step_fn(params, enc_one,
                          jnp.asarray(arrays["enc_padding_mask"][0]),
                          jnp.asarray(arrays["enc_batch_extend_vocab"][0]),
                          jnp.int32(0), latest, state)
            outs[dtype] = out
        np.testing.assert_allclose(
            np.asarray(outs["bfloat16"].topk_log_probs),
            np.asarray(outs["float32"].topk_log_probs), atol=2e-2)
        np.testing.assert_allclose(np.asarray(outs["bfloat16"].attn_dist),
                                   np.asarray(outs["float32"].attn_dist),
                                   atol=1e-2)
        assert outs["bfloat16"].state["cache_k"].dtype == jnp.bfloat16
        assert outs["float32"].state["cache_k"].dtype == jnp.float32


def test_finalize_adds_at_most_one_compile_to_warm_set():
    """ISSUE 7 acceptance detail: the backtrack lives INSIDE
    unpack_slot_jit, so a fresh config still warms the slot engine with
    exactly four compiles (init/pack/step/unpack) — the finalize pass
    adds at most one executable (unpack's own), not a fifth kernel.
    Asserted through the shared compile ledger (obs/profile.py, ISSUE
    16): every kernel call routes through compiled_call, whose
    jit-cache diff IS the growth this test used to read by hand."""
    # a config no other test compiles, so cache deltas are attributable
    hps = PG_HPS.replace(max_oov_buckets=6, beam_size=2)
    family = get_family("pointer_generator")
    params = family.init_params(hps, hps.vocab_size, jax.random.PRNGKey(1))
    arrays = make_arrays(hps, seed=8)
    slots = 2
    zero = {k: np.zeros((slots,) + v.shape[1:], v.dtype)
            for k, v in arrays.items()}
    with obs.use_registry(Registry()) as reg:
        def call(site, fn, *args):
            return profile_lib.compiled_call(reg, site, fn, *args)

        state = call("decode/init_slots_jit", beam_search.init_slots_jit,
                     params, hps, zero)
        one = {k: v[0:1] for k, v in arrays.items()}
        pre = call("decode/prefill_jit", beam_search.prefill_jit,
                   params, hps, one)
        state = call("decode/pack_slot_jit", beam_search.pack_slot_jit,
                     params, hps, state, 0, pre)
        state, _ = call("decode/step_slots_jit",
                        beam_search.step_slots_jit, params, hps, state,
                        np.array([True, False]), 2)
        call("decode/unpack_slot_jit", beam_search.unpack_slot_jit,
             hps, state, 0)
        stats = profile_lib.profiler_for(reg).compile_stats()
    growth = {site: st["compiles"] for site, st in stats.items()
              if site != "decode/prefill_jit"}
    assert growth == {"decode/init_slots_jit": 1,
                      "decode/pack_slot_jit": 1,
                      "decode/step_slots_jit": 1,
                      "decode/unpack_slot_jit": 1}, stats


def test_warm_set_is_four_plus_one_prefill_per_bucket():
    """The ISSUE 11 compile-count pin: a fresh config warms the engine
    with exactly FOUR decode compiles (init/pack/step/unpack — slot
    index, occupancy, and valid length all traced) plus ONE prefill
    compile per bucket actually used — and after that warm set, no
    occupancy pattern, slot choice, article length, or length MIX
    recompiles anything.  Asserted through the shared compile ledger
    (obs/profile.py, ISSUE 16): warm_set_size() is the 4 + one-per-
    bucket committed number, the per-bucket prefill keys are named, and
    the post-warm churn must land as ledger HITS, not compiles."""
    # a config no other test compiles, so cache deltas are attributable
    hps = PG_HPS.replace(max_oov_buckets=6, beam_size=2,
                         decode_enc_block=4, batch_size=3)
    family = get_family("pointer_generator")
    params = family.init_params(hps, hps.vocab_size, jax.random.PRNGKey(2))
    arrays = _arrays_with_lens(hps, (2, 7, 12), seed=5)
    slots = 3
    zero = {k: np.zeros((slots,) + v.shape[1:], v.dtype)
            for k, v in arrays.items()}
    buckets = (4, 8, 12)
    with obs.use_registry(Registry()) as reg:
        prof = profile_lib.install_profiler(reg)
        for kernel in ("decode/init_slots_jit", "decode/pack_slot_jit",
                       "decode/step_slots_jit", "decode/unpack_slot_jit"):
            prof.set_compile_budget(kernel, 1)
        prof.set_compile_budget("decode/prefill_jit", len(buckets))

        def call(site, fn, *args, key=""):
            return profile_lib.compiled_call(reg, site, fn, *args, key=key)

        def pre_at(slot, bucket):
            one = {k: (v[slot:slot + 1, :bucket] if v.ndim == 2
                       else v[slot:slot + 1])
                   for k, v in arrays.items()}
            return call("decode/prefill_jit", beam_search.prefill_jit,
                        params, hps, one, key=bucket)

        state = call("decode/init_slots_jit", beam_search.init_slots_jit,
                     params, hps, zero)
        for slot, bucket in enumerate(buckets):  # warm every bucket
            state = call("decode/pack_slot_jit", beam_search.pack_slot_jit,
                         params, hps, state, slot, pre_at(slot, bucket))
        state, _ = call("decode/step_slots_jit",
                        beam_search.step_slots_jit, params, hps, state,
                        np.array([True, True, True]), 2)
        call("decode/unpack_slot_jit", beam_search.unpack_slot_jit,
             hps, state, 1)
        stats = prof.compile_stats()
        growth = {site: st["compiles"] for site, st in stats.items()}
        assert growth == {"decode/init_slots_jit": 1,
                          "decode/pack_slot_jit": 1,
                          "decode/step_slots_jit": 1,
                          "decode/unpack_slot_jit": 1,
                          "decode/prefill_jit": len(buckets)}, stats
        # the committed warm set: 4 decode kernels + one prefill/bucket
        assert prof.warm_set_size() == 4 + len(buckets)
        assert stats["decode/prefill_jit"]["keys"] == sorted(
            str(b) for b in buckets), stats
        # churn: different slots, buckets, occupancy patterns, length
        # mixes — every call must land as a ledger HIT
        state = call("decode/pack_slot_jit", beam_search.pack_slot_jit,
                     params, hps, state, 1, pre_at(0, 4))
        state, _ = call("decode/step_slots_jit",
                        beam_search.step_slots_jit, params, hps, state,
                        np.array([False, True, True]), 2)
        state = call("decode/pack_slot_jit", beam_search.pack_slot_jit,
                     params, hps, state, 0, pre_at(2, 8))
        state, _ = call("decode/step_slots_jit",
                        beam_search.step_slots_jit, params, hps, state,
                        np.array([True, False, False]), 2)
        call("decode/unpack_slot_jit", beam_search.unpack_slot_jit,
             hps, state, 0)
        after = prof.compile_stats()
        assert prof.warm_set_size() == 4 + len(buckets), after
        churn_hits = sum(st["hits"] for st in after.values()) \
            - sum(st["hits"] for st in stats.values())
        assert churn_hits == 7, after  # 2 prefills + 2 packs + 2 steps + 1 unpack
        # within budget on every site => the storm trigger stayed silent
        assert profile_lib.profile_alerts(reg)["compile_storm"] is None


# -- paged resident state parity (ISSUE 20) --------------------------------
#
# The page arena replaced the slot state's worst-case per-slot leaves
# with pools of decode_enc_block-row pages addressed through a per-slot
# page table (data, not shape).  The mirror stays the FULL-WIDTH dense
# search: exactness across page-boundary article lengths, arena-full
# backpressure, and harvest-then-reuse page recycling is the claim that
# paging changed the MEMORY story, not the numerics.

from textsummarization_on_flink_tpu.decode.arena import (  # noqa: E402
    ArenaExhaustedError,
    PageArena,
)

#: article lengths at the page-layout edge cases for block=4 on the
#: 12-wide test scale (b_max=3): exactly ONE full page, straddling a
#: page boundary (block+1), the minimal 1-token article, and the full
#: 3-page grid — packed together (mixed page-count occupancy).
_PAGED_LENS = (4, 5, 1, 12)


def _scratch_row(row_ids, b_max, pages):
    row = np.full(b_max, pages, np.int32)
    row[:len(row_ids)] = row_ids
    return row


def _drive_slots_paged(params, hps, state, table, slots, chunk=3,
                       max_chunks=16):
    active = np.ones(slots, bool)
    done = {}
    for _ in range(max_chunks):
        state, fin = beam_search.step_slots_paged_jit(
            params, hps, state, active, np.asarray(table), chunk)
        for s in np.nonzero(np.asarray(fin))[0]:
            done[int(s)] = beam_search.unpack_slot_paged_jit(
                hps, state, int(s), np.asarray(table)[int(s)])
            active[s] = False
        if not active.any():
            break
    return state, done


@pytest.mark.parametrize("family_name,hps", FAMILY_CASES)
def test_paged_kernels_match_mirror_at_page_boundaries(family_name, hps):
    """Mixed page-count occupancy through the PAGED slot path: each
    article allocated ceil(len/block) real arena pages (scratch fill
    beyond), decoded together through the page-table gather, and every
    trajectory must match the full-width materialized mirror
    token-exactly — including the article whose length is exactly one
    page and the one straddling a page boundary."""
    hps = hps.replace(batch_size=len(_PAGED_LENS), decode_enc_block=4)
    family = get_family(family_name)
    params = family.init_params(hps, hps.vocab_size, jax.random.PRNGKey(3))
    arrays = _arrays_with_lens(hps, _PAGED_LENS, seed=6)
    slots = len(_PAGED_LENS)
    block, b_max = 4, 3
    arena = PageArena(9)  # 1+2+1+3 pages needed of 9
    zero = {k: np.zeros((slots,) + v.shape[1:], v.dtype)
            for k, v in arrays.items()}
    state = beam_search.init_slots_paged_jit(params, hps, zero,
                                             arena.capacity)
    table = np.full((slots, b_max), arena.capacity, np.int32)
    for slot, true_len in enumerate(_PAGED_LENS):
        bucket = next(b for b in _DISAGG_BUCKETS if true_len <= b)
        one = {k: (v[slot:slot + 1, :bucket] if v.ndim == 2
                   else v[slot:slot + 1])
               for k, v in arrays.items()}
        pre = beam_search.prefill_jit(params, hps, one)
        ids = arena.alloc(max(1, -(-true_len // block)))
        row = _scratch_row(ids, b_max, arena.capacity)
        state = beam_search.pack_slot_paged_jit(params, hps, state, slot,
                                                pre, row)
        table[slot] = row
    assert arena.pages_in_use == 7
    np.testing.assert_array_equal(
        np.asarray(state.enc_valid_len), np.asarray(_PAGED_LENS))
    _, done = _drive_slots_paged(params, hps, state, table, slots)
    assert sorted(done) == list(range(slots))
    for b in range(slots):
        ref = materialized_search(params, hps, family, arrays, b)
        _assert_slot_matches_mirror(done[b], ref)


@pytest.mark.parametrize("family_name,hps", FAMILY_CASES)
def test_paged_arena_full_backpressure_then_recycle_exact(family_name,
                                                          hps):
    """The backpressure + recycling contract at the kernel level: with
    the arena sized for ONE full-length resident, the second admission's
    allocation fails TYPED and all-or-nothing (no pages leak, the
    resident is untouched); after the first article harvests and frees,
    the retried admission reuses the very same page ids in a DIFFERENT
    slot — and still decodes token-exactly against the mirror, proving
    recycled pages carry no ghost of their previous tenant (the
    harvested slot's stale table row routes to scratch, never to the
    reused pages)."""
    hps = hps.replace(batch_size=2, decode_enc_block=4)
    family = get_family(family_name)
    params = family.init_params(hps, hps.vocab_size, jax.random.PRNGKey(3))
    arrays = _arrays_with_lens(hps, (12, 12), seed=6)
    block, b_max = 4, 3
    arena = PageArena(3)  # exactly one 3-page resident fits
    zero = {k: np.zeros((2,) + v.shape[1:], v.dtype)
            for k, v in arrays.items()}
    state = beam_search.init_slots_paged_jit(params, hps, zero,
                                             arena.capacity)
    table = np.full((2, b_max), arena.capacity, np.int32)

    def pack(slot, src_row, ids):
        one = {k: v[src_row:src_row + 1] for k, v in arrays.items()}
        pre = beam_search.prefill_jit(params, hps, one)
        row = _scratch_row(ids, b_max, arena.capacity)
        table[slot] = row
        return beam_search.pack_slot_paged_jit(params, hps, state, slot,
                                               pre, row)

    ids_a = arena.alloc(3)
    state = pack(0, 0, ids_a)
    # the second full-length admission cannot get pages: typed, carries
    # the shortfall, allocates NOTHING
    with pytest.raises(ArenaExhaustedError) as exc:
        arena.alloc(3)
    assert exc.value.needed == 3 and exc.value.free == 0
    assert arena.free_pages == 0 and arena.pages_in_use == 3
    # drive the resident alone to completion — the blocked admission
    # never touched it
    active = np.array([True, False])
    done0 = None
    for _ in range(16):
        state, fin = beam_search.step_slots_paged_jit(
            params, hps, state, active, table, 3)
        if np.asarray(fin)[0]:
            done0 = beam_search.unpack_slot_paged_jit(hps, state, 0,
                                                      table[0])
            break
    assert done0 is not None
    ref0 = materialized_search(params, hps, family, arrays, 0)
    _assert_slot_matches_mirror(done0, ref0)
    # harvest frees the pages; the retried admission reuses the SAME ids
    arena.free(ids_a.tolist())
    table[0] = arena.capacity  # stale row -> scratch (engine contract)
    ids_b = arena.alloc(3)
    assert sorted(ids_b.tolist()) == sorted(ids_a.tolist())
    state = pack(1, 1, ids_b)
    _, done = _drive_slots_paged(params, hps, state, table, 2,
                                 chunk=3)
    ref1 = materialized_search(params, hps, family, arrays, 1)
    _assert_slot_matches_mirror(done[1], ref1)


def test_paged_warm_set_allocation_churn_never_recompiles():
    """The ISSUE 20 compile pin: the paged engine warms with the SAME
    four decode compiles (page-table contents, allocation pattern,
    page-count mix, and occupancy are all traced data) plus one prefill
    per bucket — and after the warm set, page recycling, permuted
    allocation orders, different page counts per slot, and table
    rewrites all land as ledger HITS, never compiles."""
    # max_oov_buckets=5 keeps every aval distinct from the dense
    # warm-set tests above, so the ledger counts FRESH compiles even in
    # a shared-process run (the global jit caches persist across tests)
    hps = PG_HPS.replace(max_oov_buckets=5, beam_size=2,
                         decode_enc_block=4, batch_size=3)
    family = get_family("pointer_generator")
    params = family.init_params(hps, hps.vocab_size, jax.random.PRNGKey(2))
    arrays = _arrays_with_lens(hps, (2, 7, 12), seed=5)
    slots, b_max, pages = 3, 3, 7
    zero = {k: np.zeros((slots,) + v.shape[1:], v.dtype)
            for k, v in arrays.items()}
    buckets = (4, 8, 12)
    with obs.use_registry(Registry()) as reg:
        prof = profile_lib.install_profiler(reg)
        for kernel in ("decode/init_slots_jit", "decode/pack_slot_jit",
                       "decode/step_slots_jit", "decode/unpack_slot_jit"):
            prof.set_compile_budget(kernel, 1)
        prof.set_compile_budget("decode/prefill_jit", len(buckets))

        def call(site, fn, *args, key=""):
            return profile_lib.compiled_call(reg, site, fn, *args, key=key)

        def pre_at(slot, bucket):
            one = {k: (v[slot:slot + 1, :bucket] if v.ndim == 2
                       else v[slot:slot + 1])
                   for k, v in arrays.items()}
            return call("decode/prefill_jit", beam_search.prefill_jit,
                        params, hps, one, key=bucket)

        table = np.full((slots, b_max), pages, np.int32)

        def pack(slot, bucket, ids):
            row = _scratch_row(np.asarray(ids, np.int32), b_max, pages)
            table[slot] = row
            return call("decode/pack_slot_jit",
                        beam_search.pack_slot_paged_jit, params, hps,
                        state, slot, pre_at(slot, bucket), row)

        state = call("decode/init_slots_jit",
                     beam_search.init_slots_paged_jit, params, hps, zero,
                     pages)
        # warm: every bucket, differing page counts (1, 2, 3 pages)
        state = pack(0, 4, [0])
        state = pack(1, 8, [1, 2])
        state = pack(2, 12, [3, 4, 5])
        state, _ = call("decode/step_slots_jit",
                        beam_search.step_slots_paged_jit, params, hps,
                        state, np.array([True, True, True]), table, 2)
        call("decode/unpack_slot_jit", beam_search.unpack_slot_paged_jit,
             hps, state, 1, table[1])
        stats = prof.compile_stats()
        growth = {site: st["compiles"] for site, st in stats.items()}
        assert growth == {"decode/init_slots_jit": 1,
                          "decode/pack_slot_jit": 1,
                          "decode/step_slots_jit": 1,
                          "decode/unpack_slot_jit": 1,
                          "decode/prefill_jit": len(buckets)}, stats
        assert prof.warm_set_size() == 4 + len(buckets)
        # allocation-pattern churn: recycled ids out of order, a
        # different page count in the same slot, a non-contiguous
        # allocation, shifting occupancy — all HITS
        state = pack(1, 4, [6])                    # fewer pages, new id
        state = pack(0, 8, [5, 1])                 # recycled, permuted
        state, _ = call("decode/step_slots_jit",
                        beam_search.step_slots_paged_jit, params, hps,
                        state, np.array([True, False, True]), table, 2)
        state = pack(2, 12, [2, 0, 4])             # recycled, shuffled
        state, _ = call("decode/step_slots_jit",
                        beam_search.step_slots_paged_jit, params, hps,
                        state, np.array([False, True, True]), table, 2)
        call("decode/unpack_slot_jit", beam_search.unpack_slot_paged_jit,
             hps, state, 2, table[2])
        after = prof.compile_stats()
        assert prof.warm_set_size() == 4 + len(buckets), after
        churn_hits = sum(st["hits"] for st in after.values()) \
            - sum(st["hits"] for st in stats.values())
        assert churn_hits == 9, after  # 3 prefills + 3 packs + 2 steps + 1 unpack
        assert profile_lib.profile_alerts(reg)["compile_storm"] is None


class TestPageArena:
    """The host allocator's contract: LIFO reuse, all-or-nothing
    allocation, loud double-free."""

    def test_alloc_free_roundtrip_and_fill(self):
        a = PageArena(4)
        ids = a.alloc(3)
        assert sorted(ids.tolist()) == [0, 1, 2]
        assert (a.capacity, a.free_pages, a.pages_in_use) == (4, 1, 3)
        assert a.fill == 0.75
        a.free(ids.tolist())
        assert a.free_pages == 4 and a.fill == 0.0

    def test_alloc_is_all_or_nothing(self):
        a = PageArena(4)
        a.alloc(3)
        with pytest.raises(ArenaExhaustedError) as exc:
            a.alloc(2)
        assert exc.value.needed == 2 and exc.value.free == 1
        assert a.free_pages == 1  # the failed alloc took nothing

    def test_lifo_reuse(self):
        a = PageArena(4)
        first = a.alloc(2)
        a.free(first.tolist())
        again = a.alloc(2)
        assert sorted(again.tolist()) == sorted(first.tolist())

    def test_double_free_and_bad_ids_raise(self):
        a = PageArena(2)
        ids = a.alloc(1)
        a.free(ids.tolist())
        with pytest.raises(ValueError):
            a.free(ids.tolist())
        with pytest.raises(ValueError):
            a.free([7])
        with pytest.raises(ValueError):
            PageArena(0)
