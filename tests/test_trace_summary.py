"""scripts/trace_summary.py: the offline summarizer for TS_PROFILE_DIR
captures (scripts/capture_window_extras.sh banks the trace in a tunnel
window; the summary names the bottleneck op for BASELINE.md)."""

import gzip
import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))

import trace_summary  # noqa: E402


def _write_trace(path, events):
    with gzip.open(path, "wt") as f:
        json.dump({"traceEvents": events}, f)


@pytest.fixture
def trace_dir(tmp_path):
    d = tmp_path / "cap" / "plugins" / "profile" / "2026_07_31_00_00_00"
    d.mkdir(parents=True)
    _write_trace(d / "vm.trace.json.gz", [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 1, "tid": 1, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        {"ph": "M", "pid": 1, "tid": 2, "name": "thread_name",
         "args": {"name": "XLA Modules"}},
        {"ph": "M", "pid": 2, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        # device OP line: fusion dominates
        {"ph": "X", "pid": 1, "tid": 1, "name": "fusion.42",
         "ts": 0, "dur": 900.0},
        {"ph": "X", "pid": 1, "tid": 1, "name": "fusion.42",
         "ts": 1000, "dur": 600.0},
        {"ph": "X", "pid": 1, "tid": 1, "name": "copy.3",
         "ts": 2000, "dur": 100.0},
        # device MODULE line: one enclosing event spanning the same wall
        # time — must NOT be summed into the op line (double count)
        {"ph": "X", "pid": 1, "tid": 2, "name": "jit_multi",
         "ts": 0, "dur": 2100.0},
        # host lane: one op event + python frames (dropped by default)
        {"ph": "X", "pid": 2, "tid": 9, "name": "PjitFunction(multi)",
         "ts": 0, "dur": 50.0},
        {"ph": "X", "pid": 2, "tid": 9, "name": "$threading.py:323 wait",
         "ts": 0, "dur": 5000.0},
        # non-X events are ignored
        {"ph": "B", "pid": 1, "tid": 1, "name": "ignored", "ts": 0},
    ])
    return tmp_path / "cap"


def test_summarize_groups_ops_per_thread_lane_and_drops_host_frames(
        trace_dir):
    files = trace_summary.find_trace_files(str(trace_dir))
    assert len(files) == 1
    lanes = trace_summary.summarize(trace_summary.load_events(files[0]))
    assert [lane["lane"] for lane in lanes] == [
        "/device:TPU:0/XLA Modules", "/device:TPU:0/XLA Ops", "/host:CPU"]
    mod, dev, host = lanes
    # the module line stays its own lane: its enclosing event neither
    # inflates the op line's busy time nor tops its op table
    assert mod["ops"] == [{"name": "jit_multi", "total_us": 2100.0,
                           "count": 1}]
    # fusion.42 aggregated across occurrences, ops sorted by total time
    assert dev["ops"][0] == {"name": "fusion.42", "total_us": 1500.0,
                             "count": 2}
    assert dev["ops"][1]["name"] == "copy.3"
    assert dev["busy_us"] == 1600.0
    # the $python-frame event is dropped: busy time counts real ops only
    assert [op["name"] for op in host["ops"]] == ["PjitFunction(multi)"]
    assert host["busy_us"] == 50.0
    # opt-in keeps the frames
    lanes_all = trace_summary.summarize(
        trace_summary.load_events(files[0]), include_host_frames=True)
    host_all = next(lane for lane in lanes_all if lane["pid"] == 2)
    assert host_all["busy_us"] == 5050.0


def test_cli_renders_table_and_json(trace_dir, capsys):
    assert trace_summary.main([str(trace_dir), "--top", "1"]) == 0
    out = capsys.readouterr().out
    assert "fusion.42" in out and "/device:TPU:0" in out
    assert "copy.3" not in out  # --top 1
    assert trace_summary.main([str(trace_dir), "--json"]) == 0
    rec = json.loads(capsys.readouterr().out)
    ops_lane = next(lane for lane in rec["lanes"]
                    if lane["lane"].endswith("XLA Ops"))
    assert ops_lane["ops"][0]["name"] == "fusion.42"


def test_cli_errors_without_capture(tmp_path, capsys):
    assert trace_summary.main([str(tmp_path)]) == 1
    assert "capture" in capsys.readouterr().err
