"""scripts/trace_summary.py: the offline summarizer for TS_PROFILE_DIR
captures (scripts/capture_window_extras.sh banks the trace in a tunnel
window; the summary names the bottleneck op for BASELINE.md)."""

import gzip
import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))

import trace_summary  # noqa: E402


def _write_trace(path, events):
    with gzip.open(path, "wt") as f:
        json.dump({"traceEvents": events}, f)


@pytest.fixture
def trace_dir(tmp_path):
    d = tmp_path / "cap" / "plugins" / "profile" / "2026_07_31_00_00_00"
    d.mkdir(parents=True)
    _write_trace(d / "vm.trace.json.gz", [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 1, "tid": 1, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        {"ph": "M", "pid": 1, "tid": 2, "name": "thread_name",
         "args": {"name": "XLA Modules"}},
        {"ph": "M", "pid": 2, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        # device OP line: fusion dominates
        {"ph": "X", "pid": 1, "tid": 1, "name": "fusion.42",
         "ts": 0, "dur": 900.0},
        {"ph": "X", "pid": 1, "tid": 1, "name": "fusion.42",
         "ts": 1000, "dur": 600.0},
        {"ph": "X", "pid": 1, "tid": 1, "name": "copy.3",
         "ts": 2000, "dur": 100.0},
        # device MODULE line: one enclosing event spanning the same wall
        # time — must NOT be summed into the op line (double count)
        {"ph": "X", "pid": 1, "tid": 2, "name": "jit_multi",
         "ts": 0, "dur": 2100.0},
        # host lane: one op event + python frames (dropped by default)
        {"ph": "X", "pid": 2, "tid": 9, "name": "PjitFunction(multi)",
         "ts": 0, "dur": 50.0},
        {"ph": "X", "pid": 2, "tid": 9, "name": "$threading.py:323 wait",
         "ts": 0, "dur": 5000.0},
        # non-X events are ignored
        {"ph": "B", "pid": 1, "tid": 1, "name": "ignored", "ts": 0},
    ])
    return tmp_path / "cap"


def test_summarize_groups_ops_per_thread_lane_and_drops_host_frames(
        trace_dir):
    files = trace_summary.find_trace_files(str(trace_dir))
    assert len(files) == 1
    lanes = trace_summary.summarize(trace_summary.load_events(files[0]))
    assert [lane["lane"] for lane in lanes] == [
        "/device:TPU:0/XLA Modules", "/device:TPU:0/XLA Ops", "/host:CPU"]
    mod, dev, host = lanes
    # the module line stays its own lane: its enclosing event neither
    # inflates the op line's busy time nor tops its op table
    assert mod["ops"] == [{"name": "jit_multi", "total_us": 2100.0,
                           "count": 1}]
    # fusion.42 aggregated across occurrences, ops sorted by total time
    assert dev["ops"][0] == {"name": "fusion.42", "total_us": 1500.0,
                             "count": 2}
    assert dev["ops"][1]["name"] == "copy.3"
    assert dev["busy_us"] == 1600.0
    # the $python-frame event is dropped: busy time counts real ops only
    assert [op["name"] for op in host["ops"]] == ["PjitFunction(multi)"]
    assert host["busy_us"] == 50.0
    # opt-in keeps the frames
    lanes_all = trace_summary.summarize(
        trace_summary.load_events(files[0]), include_host_frames=True)
    host_all = next(lane for lane in lanes_all if lane["pid"] == 2)
    assert host_all["busy_us"] == 5050.0


def test_cli_renders_table_and_json(trace_dir, capsys):
    assert trace_summary.main([str(trace_dir), "--top", "1"]) == 0
    out = capsys.readouterr().out
    assert "fusion.42" in out and "/device:TPU:0" in out
    assert "copy.3" not in out  # --top 1
    assert trace_summary.main([str(trace_dir), "--json"]) == 0
    rec = json.loads(capsys.readouterr().out)
    ops_lane = next(lane for lane in rec["lanes"]
                    if lane["lane"].endswith("XLA Ops"))
    assert ops_lane["ops"][0]["name"] == "fusion.42"


def test_cli_errors_without_capture(tmp_path, capsys):
    assert trace_summary.main([str(tmp_path)]) == 1
    assert "capture" in capsys.readouterr().err


# -- request timelines (ISSUE 9 satellite) ---------------------------------

@pytest.fixture
def events_file(tmp_path):
    t0 = 1_700_000_000_000_000
    recs = [
        {"kind": "request", "event": "enqueue", "uuid": "u7",
         "trace_id": "t7", "span_id": "s7", "ts_us": t0,
         "attrs": {"depth": 1}},
        {"kind": "request", "event": "admit", "uuid": "u7",
         "trace_id": "t7", "span_id": "s7", "ts_us": t0 + 2_000,
         "attrs": {"queue_ms": 2.0}},
        {"kind": "request", "event": "slot", "uuid": "u7",
         "trace_id": "t7", "span_id": "s7", "ts_us": t0 + 2_100,
         "attrs": {"slot": 3, "tick": 9}},
        {"kind": "span", "name": "serve/dispatch", "trace_id": "t7",
         "span_id": "sp1", "ts_us": t0 + 2_200, "dur_us": 1_000,
         "pid": 1, "tid": 1},
        {"kind": "request", "event": "finish", "uuid": "u7",
         "trace_id": "t7", "span_id": "s7", "ts_us": t0 + 9_000,
         "attrs": {"chunks": 4}},
        {"kind": "request", "event": "resolve", "uuid": "u7",
         "trace_id": "t7", "span_id": "s7", "ts_us": t0 + 9_500},
        # a NEIGHBOR request: must not leak into u7's timeline
        {"kind": "request", "event": "enqueue", "uuid": "u8",
         "trace_id": "t8", "span_id": "s8", "ts_us": t0 + 100},
        # scalar record + junk line tolerance
        {"step": 3, "loss": 2.5},
    ]
    p = tmp_path / "events.jsonl"
    with open(p, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
        f.write("{broken tail\n")
    return p


class TestRequestTimeline:
    def test_reconstructs_phases_and_spans(self, events_file):
        tl = trace_summary.request_timeline([str(events_file)], "u7")
        assert [e["event"] for e in tl["events"]] == [
            "enqueue", "admit", "slot", "finish", "resolve"]
        assert tl["trace_id"] == "t7"
        assert tl["phases"] == {"queue_ms": 2.0, "resident_ms": 7.0,
                                "resolve_ms": 0.5, "total_ms": 9.5}
        # the trace's spans ride along; the neighbor's do not
        assert [s["name"] for s in tl["spans"]] == ["serve/dispatch"]

    def test_evicted_request_resident_falls_back_to_resolve(self, tmp_path):
        recs = [
            {"kind": "request", "event": "enqueue", "uuid": "u1",
             "trace_id": "t1", "span_id": "s1", "ts_us": 1_000_000},
            {"kind": "request", "event": "admit", "uuid": "u1",
             "trace_id": "t1", "span_id": "s1", "ts_us": 1_500_000},
            {"kind": "request", "event": "evict", "uuid": "u1",
             "trace_id": "t1", "span_id": "s1", "ts_us": 1_600_000},
            {"kind": "request", "event": "resolve", "uuid": "u1",
             "trace_id": "t1", "span_id": "s1", "ts_us": 1_700_000,
             "attrs": {"error": "DeadlineExceededError"}},
        ]
        p = tmp_path / "events.jsonl"
        p.write_text("".join(json.dumps(r) + "\n" for r in recs))
        tl = trace_summary.request_timeline([str(p)], "u1")
        assert tl["phases"]["resident_ms"] == 200.0  # admit -> resolve
        assert "resolve_ms" not in tl["phases"]
        assert tl["phases"]["total_ms"] == 700.0

    def test_cli_text_and_json(self, events_file, capsys):
        assert trace_summary.main(
            [str(events_file), "--request", "u7"]) == 0
        out = capsys.readouterr().out
        assert "request 'u7' (trace t7)" in out
        assert "slot (slot=3, tick=9)" in out
        assert "queue 2.000 ms" in out and "total 9.500 ms" in out
        assert "serve/dispatch" in out
        assert trace_summary.main(
            [str(events_file), "--request", "u7", "--json"]) == 0
        tl = json.loads(capsys.readouterr().out)
        assert tl["phases"]["total_ms"] == 9.5

    def test_cli_directory_argument(self, events_file, capsys):
        assert trace_summary.main(
            [str(events_file.parent), "--request", "u8", "--json"]) == 0
        tl = json.loads(capsys.readouterr().out)
        assert [e["event"] for e in tl["events"]] == ["enqueue"]

    def test_unknown_uuid_errors(self, events_file, capsys):
        assert trace_summary.main(
            [str(events_file), "--request", "nope"]) == 1
        assert "no request events" in capsys.readouterr().err

    def test_no_events_jsonl_errors(self, tmp_path, capsys):
        assert trace_summary.main(
            [str(tmp_path), "--request", "u1"]) == 1
        assert "events.jsonl" in capsys.readouterr().err
