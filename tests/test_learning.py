"""End-to-end LEARNING tests: the full stack trains a model that solves a
synthetic copy task, for both model families.

Unlike the plumbing/parity tests, this checks the system as a learning
machine: batch packing -> pointer loss -> Adagrad updates -> on-device
beam decode must cooperate well enough that 300 steps of training yields
a model that copies the first three article tokens (the pointer
mechanism's raison d'être, model.py:146-183 in the reference).
"""

import jax
import numpy as np
import pytest

from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.data import oov as oov_lib
from textsummarization_on_flink_tpu.data.batching import Batch, SummaryExample
from textsummarization_on_flink_tpu.data.vocab import Vocab
from textsummarization_on_flink_tpu.decode import beam_search
from textsummarization_on_flink_tpu.train import trainer as trainer_lib

WORDS = [f"tok{i}" for i in range(26)]


def _decode_first_words(state, hps, vocab, exs):
    """Beam-decode fresh examples; returns the per-example decoded word
    lists (START/[STOP] stripped) — shared by the learning tests."""
    dec_hps = hps.replace(mode="decode")
    batch = Batch(exs, dec_hps, vocab)
    enc = {k: v for k, v in batch.as_arrays().items()
           if k.startswith("enc_")}
    out = beam_search.run_beam_search(state.params, dec_hps, enc)
    decoded = []
    for i in range(len(exs)):
        ids = [int(t) for t in out.tokens[i][1 : int(out.length[i])]]
        decoded.append([w for w in oov_lib.outputids2words(
            ids, vocab, batch.art_oovs[i]) if w != "[STOP]"])
    return decoded


@pytest.mark.parametrize("family", ["pointer_generator", "transformer"])
@pytest.mark.slow
def test_learns_oov_copy_through(family):
    """The defining pointer capability: decoded output contains words that
    are NOT in the vocabulary — reachable only through the extended-vocab
    copy path (article2ids temp ids -> final-dist mixing ->
    outputids2words).  Train on articles whose first token is always a
    fresh out-of-vocab entity the abstract copies."""
    hps = family_hps(family).replace(max_dec_steps=4)
    vocab = Vocab(words=WORDS, max_size=hps.vocab_size)
    rng = np.random.RandomState(0)

    def make_ex():
        ent = f"entity{rng.randint(1000)}"  # never in vocab
        rest = list(rng.choice(WORDS, 7))
        return SummaryExample.build(" ".join([ent] + rest),
                                    [" ".join([ent, rest[0]])], vocab, hps)

    state = trainer_lib.init_train_state(hps, vocab.size(), seed=0)
    step = jax.jit(trainer_lib.make_train_step(hps), donate_argnums=0)
    for _ in range(300):
        batch = Batch([make_ex() for _ in range(8)], hps, vocab)
        state, metrics = step(state, batch.as_arrays())
    assert float(metrics.loss) < 0.1

    exs = [make_ex() for _ in range(8)]
    decoded = _decode_first_words(state, hps, vocab, exs)
    hits = 0
    for ex, words in zip(exs, decoded):
        ent = ex.original_article.split()[0]
        assert vocab.word2id(ent) == 0  # really out-of-vocab (UNK id)
        hits += bool(words) and words[0] == ent
    assert hits >= 7, f"{family} copied the OOV entity in only {hits}/8"


@pytest.mark.slow
def test_two_phase_coverage_recipe(tmp_path):
    """The reference's training recipe as ONE flow (SURVEY §5.4): train
    without coverage, convert the checkpoint (fresh w_c + accumulator),
    resume WITH coverage, and keep training — step counter continuous,
    coverage loss live in the summaries."""
    import json
    import os

    from textsummarization_on_flink_tpu import cli
    from textsummarization_on_flink_tpu.checkpoint import (
        checkpointer as ckpt_lib,
    )
    from textsummarization_on_flink_tpu.data.batcher import Batcher

    hps = HParams(hidden_dim=16, emb_dim=8, batch_size=4, max_enc_steps=10,
                  max_dec_steps=5, beam_size=2, min_dec_steps=1,
                  vocab_size=30, max_oov_buckets=4,
                  log_root=str(tmp_path), exp_name="exp")
    vocab = Vocab(words=WORDS, max_size=hps.vocab_size)
    rng = np.random.RandomState(0)

    def source():
        while True:
            art = " ".join(rng.choice(WORDS, 8))
            yield art, "<s> " + " ".join(art.split()[:3]) + " </s>"

    def batcher():
        return Batcher("", vocab, hps, single_pass=False,
                       example_source=source)

    state = cli.setup_training(hps.replace(num_steps=3), vocab, batcher())
    assert int(state.step) == 3
    train_dir = os.path.join(str(tmp_path), "exp", "train")

    out = ckpt_lib.convert_to_coverage_model(train_dir, hps, seed=1)
    assert out.endswith("_cov_init.npz")

    hps_cov = hps.replace(coverage=True, num_steps=6)
    state = cli.setup_training(hps_cov, vocab, batcher())
    assert int(state.step) == 6
    with open(os.path.join(train_dir, "events.jsonl")) as f:
        recs = [json.loads(line) for line in f]
    # the coverage phase must have RESUMED from the converted step-3 ckpt:
    # exactly steps 4-6 carry coverage_loss (a silent fresh init would
    # emit six coverage records starting at step 1)
    cov_steps = [r["step"] for r in recs if "coverage_loss" in r]
    assert cov_steps == [4, 5, 6], cov_steps
    assert all(np.isfinite(r["coverage_loss"]) for r in recs
               if "coverage_loss" in r)


def family_hps(family: str) -> HParams:
    base = dict(batch_size=8, max_enc_steps=10, max_dec_steps=5,
                beam_size=2, min_dec_steps=1, vocab_size=30,
                max_oov_buckets=4, model_family=family)
    if family == "transformer":
        return HParams(hidden_dim=32, emb_dim=32, num_heads=4, enc_layers=2,
                       dec_layers=2, lr=0.3, **base)
    return HParams(hidden_dim=32, emb_dim=16, lr=0.5, **base)


@pytest.mark.parametrize("family", ["pointer_generator", "transformer"])
@pytest.mark.slow
def test_learns_copy_task(family):
    hps = family_hps(family)
    vocab = Vocab(words=WORDS, max_size=hps.vocab_size)
    rng = np.random.RandomState(0)

    def make_ex():
        art_words = list(rng.choice(WORDS, 8))
        return SummaryExample.build(" ".join(art_words),
                                    [" ".join(art_words[:3])], vocab, hps)

    state = trainer_lib.init_train_state(hps, vocab.size(), seed=0)
    step = jax.jit(trainer_lib.make_train_step(hps), donate_argnums=0)
    first_loss = last_loss = None
    for i in range(300):
        batch = Batch([make_ex() for _ in range(8)], hps, vocab)
        state, metrics = step(state, batch.as_arrays())
        if i == 0:
            first_loss = float(metrics.loss)
    last_loss = float(metrics.loss)
    assert np.isfinite(last_loss)
    assert last_loss < 0.1 < first_loss, (first_loss, last_loss)

    # fresh articles, full on-device beam decode
    exs = [make_ex() for _ in range(8)]
    decoded = _decode_first_words(state, hps, vocab, exs)
    acc = 0.0
    for ex, words in zip(exs, decoded):
        tgt = ex.original_abstract.split()
        acc += sum(1 for a, b in zip(words, tgt) if a == b) / len(tgt)
    acc /= len(exs)
    assert acc >= 0.9, f"{family} copy accuracy {acc}"
