"""Labeled metrics, trace exemplars, and the fleet aggregation plane
(ISSUE 15 tentpole, pieces 1 and 3).

Pins the load-bearing contracts:

  * label-cardinality BOUNDS — a hostile stream of 10k distinct tenant
    names cannot grow a metric's child map (LRU eviction, counted in
    ``obs/label_evictions_total``) nor its rendered exposition;
  * counter/histogram children ROLL UP into the unlabeled parent (the
    aggregate survives eviction), gauges do not;
  * histogram bucket exemplars (last trace_id per bucket) ride
    ``render_text`` in OpenMetrics syntax and the /exemplars payload;
  * fleet merge correctness — bucket-wise histogram sums across 3
    registries equal the hand-computed merged exposition, counters sum,
    gauges come back ``replica=``-labeled, and a bucket-layout mismatch
    degrades to honest per-replica series, never a wrong sum.
"""

import math

import numpy as np

from textsummarization_on_flink_tpu.obs import http as obs_http
from textsummarization_on_flink_tpu.obs.registry import (
    Registry,
    merge_fleet_series,
    merge_fleet_snapshot,
    render_fleet_text,
)


# --------------------------------------------------------------------------
# labeled children: API, roll-up, identity
# --------------------------------------------------------------------------

class TestLabeledMetrics:
    def test_counter_children_roll_up_into_parent(self):
        r = Registry()
        c = r.counter("serve/requests_total")
        c.labels(tenant="a", tier="beam").inc(3)
        c.labels(tenant="b", tier="beam").inc(2)
        assert c.value == 5.0
        assert c.labels(tenant="a", tier="beam").value == 3.0

    def test_label_identity_is_order_insensitive(self):
        r = Registry()
        c = r.counter("t/c")
        assert c.labels(a="1", b="2") is c.labels(b="2", a="1")
        # different values are different series
        assert c.labels(a="1") is not c.labels(a="2")

    def test_gauge_children_do_not_roll_up(self):
        r = Registry()
        g = r.gauge("serve/queue_depth")
        g.labels(replica="r0").set(4)
        g.labels(replica="r1").set(7)
        assert g.value == 0.0  # last-write-wins parents stay untouched
        assert g.labels(replica="r1").value == 7.0

    def test_labels_on_child_raises(self):
        r = Registry()
        c = r.counter("t/c")
        child = c.labels(tenant="a")
        try:
            child.labels(tier="beam")
        except ValueError as e:
            assert "already-labeled" in str(e)
        else:  # pragma: no cover
            raise AssertionError("labels() on a child must raise")

    def test_histogram_children_share_buckets_and_roll_up(self):
        r = Registry()
        h = r.histogram("t/h", buckets=(1.0, 2.0, 4.0))
        h.labels(tier="beam").observe(1.5)
        h.labels(tier="greedy").observe(3.0)
        assert h.count == 2
        assert h.sum == 4.5
        assert h.labels(tier="beam").buckets == (1.0, 2.0, 4.0)
        assert h.labels(tier="beam").count == 1

    def test_snapshot_and_render_carry_children(self):
        r = Registry()
        r.counter("t/c").labels(tenant="a").inc()
        snap = r.snapshot(compact=True)
        assert snap['t/c{tenant="a"}']["value"] == 1.0
        assert snap["t/c"]["value"] == 1.0  # rolled-up parent
        text = r.render_text()
        assert 't_c{tenant="a"} 1' in text

    def test_label_values_escaped_in_exposition(self):
        r = Registry()
        r.counter("t/c").labels(tenant='ev"il\n').inc()
        text = r.render_text()
        assert 'tenant="ev\\"il\\n"' in text


# --------------------------------------------------------------------------
# cardinality bounds (ISSUE 15 satellite)
# --------------------------------------------------------------------------

class TestLabelCardinality:
    def test_hostile_tenant_stream_is_lru_bounded(self):
        r = Registry(max_label_sets=64)
        c = r.counter("serve/tenant_shed_total")
        for i in range(10_000):
            c.labels(tenant=f"hostile-{i}").inc()
        assert len(c.label_children()) == 64
        # every inc rolled up before its child was evicted: aggregate
        # truth survives the bound
        assert c.value == 10_000.0
        evicted = r.counter("obs/label_evictions_total").value
        assert evicted == 10_000 - 64
        # the newest names survive (LRU), the oldest are gone
        survivors = {kv[0][1] for kv in
                     (ch.labels_kv for ch in c.label_children())}
        assert "hostile-9999" in survivors
        assert "hostile-0" not in survivors

    def test_render_stays_bounded_under_hostile_labels(self):
        r = Registry(max_label_sets=32)
        h = r.histogram("t/h", buckets=(1.0,))
        for i in range(5_000):
            h.labels(tenant=f"t{i}").observe(0.5)
        text = r.render_text()
        # 32 children * 4 lines (+inf bucket, 1.0 bucket, sum, count)
        # + parent + TYPE lines + eviction counter: bounded, not 5k rows
        assert len(text.splitlines()) < 200

    def test_touch_refreshes_lru_position(self):
        r = Registry(max_label_sets=2)
        c = r.counter("t/c")
        c.labels(t="a").inc()
        c.labels(t="b").inc()
        c.labels(t="a").inc()  # refresh a
        c.labels(t="c").inc()  # evicts b, not a
        names = {kv[0][1] for kv in
                 (ch.labels_kv for ch in c.label_children())}
        assert names == {"a", "c"}


# --------------------------------------------------------------------------
# trace exemplars
# --------------------------------------------------------------------------

class TestExemplars:
    def test_bucket_exemplar_last_write_wins(self):
        r = Registry()
        h = r.histogram("serve/e2e_latency_seconds", buckets=(1.0, 10.0))
        h.observe(0.5, trace_id="t-early")
        h.observe(0.7, trace_id="t-late")
        h.observe(5.0, trace_id="t-slow")
        h.observe(3.0)  # untraced observations never clobber exemplars
        exs = {e["le"]: e for e in h.exemplars()}
        assert exs["1"]["trace_id"] == "t-late"
        assert exs["10"]["trace_id"] == "t-slow"
        assert exs["10"]["value"] == 5.0

    def test_exemplars_render_in_openmetrics_syntax(self):
        r = Registry()
        h = r.histogram("t/h", buckets=(1.0,))
        h.observe(0.5, trace_id="abc123")
        text = r.render_text(openmetrics=True)
        assert '# {trace_id="abc123"} 0.5' in text
        # the DEFAULT render is a valid exposition in either format:
        # 0.0.4 without negotiation carries no OpenMetrics annotations
        assert "trace_id" not in r.render_text()

    def test_child_exemplars_roll_up_to_parent(self):
        r = Registry()
        h = r.histogram("t/h", buckets=(1.0,))
        h.labels(tier="beam").observe(0.5, trace_id="via-child")
        assert h.exemplars()[0]["trace_id"] == "via-child"

    def test_exemplars_endpoint_payload(self):
        r = Registry()
        h = r.histogram("serve/e2e_latency_seconds", buckets=(1.0,))
        h.labels(tier="beam").observe(0.2, trace_id="deadbeef")
        rows = obs_http.exemplars(r)
        mets = {row["metric"] for row in rows}
        assert "serve/e2e_latency_seconds" in mets
        assert 'serve/e2e_latency_seconds{tier="beam"}' in mets
        assert all(row["trace_id"] == "deadbeef" for row in rows)

    def test_p99_bucket_exemplar_names_the_slow_request(self):
        """The operator story: the exemplar of the bucket holding the
        p99 names a request whose latency is in the tail."""
        r = Registry()
        h = r.histogram("t/h", buckets=(0.1, 1.0, 10.0))
        for i in range(50):
            h.observe(0.05, trace_id=f"fast-{i}")
        h.observe(5.0, trace_id="the-straggler")
        p99 = h.percentile(99)
        fat = next(e for e in h.exemplars()
                   if e["le"] == "+Inf" or float(e["le"]) >= p99)
        assert fat["trace_id"] == "the-straggler"


# --------------------------------------------------------------------------
# fleet merge correctness (ISSUE 15 satellite)
# --------------------------------------------------------------------------

def _three_registries():
    regs = {}
    rng = np.random.RandomState(7)
    for i, rid in enumerate(("r0", "r1", "r2")):
        r = Registry()
        r.counter("serve/completed_total").inc(10 * (i + 1))
        r.counter("serve/completed_total").labels(tenant="a").inc(i + 1)
        r.gauge("serve/queue_depth").set(i)
        h = r.histogram("serve/e2e_latency_seconds",
                        buckets=(0.1, 1.0, 10.0))
        for v in rng.uniform(0.01, 12.0, size=20):
            h.observe(float(v))
        regs[rid] = r
    return regs


class TestFleetMerge:
    def test_counters_sum_across_registries(self):
        regs = _three_registries()
        rows = {(n, kv): p for n, kv, k, p in merge_fleet_series(regs)
                if k == "counter"}
        # parent: 10+20+30 plus the rolled-up labeled incs 1+2+3
        assert rows[("serve/completed_total", ())] == 66.0
        assert rows[("serve/completed_total",
                     (("tenant", "a"),))] == 6.0

    def test_gauges_come_back_replica_labeled(self):
        regs = _three_registries()
        gauge_rows = [(kv, p) for n, kv, k, p in merge_fleet_series(regs)
                      if k == "gauge" and n == "serve/queue_depth"]
        assert ((("replica", "r1"),), 1.0) in gauge_rows
        assert len(gauge_rows) == 3

    def test_histogram_bucketwise_sum_matches_hand_computed(self):
        regs = _three_registries()
        merged = next(p for n, kv, k, p in merge_fleet_series(regs)
                      if k == "histogram" and kv == ())
        hand = [0] * 4
        total, vsum = 0, 0.0
        vmin, vmax = math.inf, -math.inf
        for r in regs.values():
            s = r.get("serve/e2e_latency_seconds").snapshot()
            for j, c in enumerate(s["counts"]):
                hand[j] += c
            total += s["count"]
            vsum += s["sum"]
            vmin = min(vmin, s["min"])
            vmax = max(vmax, s["max"])
        assert merged["counts"] == hand
        assert merged["count"] == total == 60
        assert abs(merged["sum"] - vsum) < 1e-9
        assert merged["min"] == vmin and merged["max"] == vmax

    def test_merged_exposition_equals_one_registry_seeing_all(self):
        """The committed merge semantics: the fleet exposition is what
        ONE registry observing every replica's stream would render."""
        regs = _three_registries()
        one = Registry()
        one.counter("serve/completed_total").inc(60)
        one.counter("serve/completed_total").labels(tenant="a").inc(6)
        h = one.histogram("serve/e2e_latency_seconds",
                          buckets=(0.1, 1.0, 10.0))
        rng = np.random.RandomState(7)
        for _ in range(3):
            for v in rng.uniform(0.01, 12.0, size=20):
                h.observe(float(v))
        fleet_text = render_fleet_text(regs)
        for line in fleet_text.splitlines():
            if line.startswith("serve_e2e_latency_seconds_bucket"):
                assert line in one.render_text(), line

    def test_layout_mismatch_degrades_to_per_replica_series(self):
        ra, rb = Registry(), Registry()
        ra.histogram("t/h", buckets=(1.0, 2.0)).observe(0.5)
        rb.histogram("t/h", buckets=(5.0,)).observe(0.5)
        rows = [(kv, p) for n, kv, k, p in
                merge_fleet_series({"a": ra, "b": rb})
                if k == "histogram"]
        assert len(rows) == 2
        labels = {kv for kv, _ in rows}
        assert labels == {(("replica", "a"),), (("replica", "b"),)}

    def test_fleet_snapshot_percentiles_over_merged_buckets(self):
        regs = _three_registries()
        snap = merge_fleet_snapshot(regs)
        assert snap["replicas"] == ["r0", "r1", "r2"]
        m = snap["metrics"]["serve/e2e_latency_seconds"]
        assert m["count"] == 60
        assert m["min"] <= m["p50"] <= m["p99"] <= m["max"]
        assert snap["metrics"]["serve/completed_total"]["value"] == 66.0

    def test_fleet_snapshot_carries_replica_health(self):
        regs = _three_registries()
        obs_http.set_health_info(regs["r1"], serve_mode="continuous")
        snap = merge_fleet_snapshot(regs)
        assert snap["health"] == {"r1": {"serve_mode": "continuous"}}

    def test_already_replica_labeled_gauge_not_double_tagged(self):
        r = Registry()
        r.gauge("t/g").labels(replica="self").set(1.0)
        rows = [(kv, p) for n, kv, k, p in
                merge_fleet_series({"rX": r}) if k == "gauge"]
        assert rows == [((("replica", "self"),), 1.0)]
