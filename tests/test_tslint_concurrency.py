"""tools/tslint v2 test suite: interprocedural concurrency rules.

Three layers, mirroring tests/test_tslint.py:
  * callgraph units — thread-entry inference (Thread targets, Thread
    subclasses, handler classes, atexit/signal hooks, escaped-callback
    refs), root propagation, lock identity (Condition aliasing), and
    the held-on-entry fixpoint;
  * per-rule fixtures — a positive (the deadlock/race/stall the rule
    exists for) and a negative (the disciplined version) for each of
    TS007–TS010, plus inline suppression riding the same machinery;
  * CLI contract — the seeded-deadlock fixture exits 1, --rules
    filters, --changed scans the git-diff subset, --write-baseline
    prunes deleted-file entries, --lock-graph emits the sanitizer's
    cross-check JSON.

Stdlib-only (ast + subprocess) — none of these tests need jax.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from tools.tslint import ALL_RULES, PROJECT_RULES, analyze, lock_graph
from tools.tslint import callgraph
from tools.tslint.config import merge_config
from tools.tslint.engine import parse_files

REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
PACKAGE = "textsummarization_on_flink_tpu"

CONCURRENCY = {"TS007", "TS008", "TS009", "TS010"}


def run_project(tmp_path, files, select=CONCURRENCY, config=None):
    """Write {name: code} under tmp_path and analyze the tree."""
    for name, code in files.items():
        f = tmp_path / name
        f.parent.mkdir(parents=True, exist_ok=True)
        f.write_text(textwrap.dedent(code), encoding="utf-8")
    return analyze([str(tmp_path)], root=str(tmp_path), select=select,
                   config=config)


def run_snippet(tmp_path, code, **kw):
    return run_project(tmp_path, {"snippet.py": code}, **kw)


def rules_of(result):
    return [f.rule for f in result.findings]


def build_graph(tmp_path, files):
    for name, code in files.items():
        (tmp_path / name).write_text(textwrap.dedent(code),
                                     encoding="utf-8")
    contexts, parse_findings, _ = parse_files(
        [str(tmp_path)], str(tmp_path), merge_config(None))
    assert not parse_findings
    return callgraph.build(contexts)


# --------------------------------------------------------------------------
# callgraph units
# --------------------------------------------------------------------------

DEADLOCK = """
    import threading

    class Transfer:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def deposit(self):
            with self._a:
                with self._b:
                    return 1

        def withdraw(self):
            with self._b:
                with self._a:
                    return 2
"""


def test_callgraph_thread_target_entry(tmp_path):
    g = build_graph(tmp_path, {"m.py": """
        import threading

        class Pump:
            def start(self):
                self._t = threading.Thread(target=self._loop)
                self._t.start()

            def _loop(self):
                self._step()

            def _step(self):
                pass
    """})
    loop = g.functions["m.py::Pump._loop"]
    step = g.functions["m.py::Pump._step"]
    assert g.roots(loop.fid) == {"thread:Pump._loop"}
    # reachability: the root flows through the call edge
    assert g.roots(step.fid) == {"thread:Pump._loop"}


def test_callgraph_thread_subclass_run_entry(tmp_path):
    g = build_graph(tmp_path, {"m.py": """
        import threading

        class Worker(threading.Thread):
            def run(self):
                self._body()

            def _body(self):
                pass
    """})
    assert "thread:Worker.run" in g.roots(g.functions["m.py::Worker._body"].fid)


def test_callgraph_handler_class_entry(tmp_path):
    g = build_graph(tmp_path, {"m.py": """
        from http.server import BaseHTTPRequestHandler

        class Healthz(BaseHTTPRequestHandler):
            def do_GET(self):
                self._reply()

            def _reply(self):
                pass
    """})
    assert "handler:Healthz.do_GET" in g.roots(
        g.functions["m.py::Healthz._reply"].fid)


def test_callgraph_atexit_and_callback_escape_entries(tmp_path):
    g = build_graph(tmp_path, {"m.py": """
        import atexit

        class App:
            def install(self, sink):
                atexit.register(self._cleanup)
                sink.on_death = self._on_death

            def _cleanup(self):
                pass

            def _on_death(self):
                pass
    """})
    assert any(r.startswith("atexit:") for r in g.roots(
        g.functions["m.py::App._cleanup"].fid))
    assert any(r.startswith("callback:") for r in g.roots(
        g.functions["m.py::App._on_death"].fid))


def test_callgraph_main_root_for_uncalled_public_method(tmp_path):
    g = build_graph(tmp_path, {"m.py": """
        class Api:
            def public(self):
                return 1
    """})
    assert g.roots(g.functions["m.py::Api.public"].fid) == {callgraph.MAIN_ROOT}


def test_callgraph_lock_id_condition_aliases_to_underlying(tmp_path):
    g = build_graph(tmp_path, {"m.py": """
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._not_empty = threading.Condition(self._lock)
    """})
    # acquiring the condition IS acquiring the underlying mutex
    assert g.lock_id("Q", "_not_empty") == "Q._lock"
    assert g.lock_id("Q", "_lock") == "Q._lock"


def test_callgraph_held_on_entry_fixpoint(tmp_path):
    g = build_graph(tmp_path, {"m.py": """
        import threading

        class S:
            def __init__(self):
                self._mu = threading.Lock()

            def outer(self):
                with self._mu:
                    self.inner()

            def inner(self):
                self.leaf()

            def leaf(self):
                pass
    """})
    held = g.held_on_entry()
    assert held.get("m.py::S.inner") == {"S._mu"}
    assert held.get("m.py::S.leaf") == {"S._mu"}  # transitive
    assert not held.get("m.py::S.outer")


def test_callgraph_lock_order_edges_cross_method(tmp_path):
    g = build_graph(tmp_path, {"m.py": DEADLOCK})
    pairs = {(a, b) for a, b, _, _ in g.lock_order_edges()}
    assert ("Transfer._a", "Transfer._b") in pairs
    assert ("Transfer._b", "Transfer._a") in pairs


# --------------------------------------------------------------------------
# TS007 — lock-order-cycle
# --------------------------------------------------------------------------

def test_ts007_ab_ba_deadlock(tmp_path):
    r = run_snippet(tmp_path, DEADLOCK)
    assert rules_of(r) == ["TS007", "TS007"]  # one per inverted edge


def test_ts007_cycle_through_helper_call(tmp_path):
    # the inversion hides behind a call: withdraw acquires B then CALLS
    # a helper that acquires A — only the held-on-entry fixpoint sees it
    r = run_snippet(tmp_path, """
        import threading

        class Transfer:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def deposit(self):
                with self._a:
                    with self._b:
                        return 1

            def withdraw(self):
                with self._b:
                    return self._under_a()

            def _under_a(self):
                with self._a:
                    return 2
    """)
    assert "TS007" in rules_of(r)


def test_ts007_consistent_order_is_clean(tmp_path):
    r = run_snippet(tmp_path, """
        import threading

        class Transfer:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def deposit(self):
                with self._a:
                    with self._b:
                        return 1

            def withdraw(self):
                with self._a:
                    with self._b:
                        return 2
    """)
    assert rules_of(r) == []


# --------------------------------------------------------------------------
# TS008 — blocking-under-lock
# --------------------------------------------------------------------------

def test_ts008_sleep_under_lock(tmp_path):
    r = run_snippet(tmp_path, """
        import threading
        import time

        class Poller:
            def __init__(self):
                self._lock = threading.Lock()

            def poll(self):
                with self._lock:
                    time.sleep(0.1)
    """)
    assert rules_of(r) == ["TS008"]


def test_ts008_blocking_reached_through_helper(tmp_path):
    # the procfleet shape: the scrape call chain blocks, the lock is
    # held at the CALL site — the report lands on the held region
    r = run_snippet(tmp_path, """
        import socket
        import threading

        class Scraper:
            def __init__(self):
                self._lock = threading.Lock()

            def _fetch(self):
                return socket.create_connection(("127.0.0.1", 80))

            def scrape(self):
                with self._lock:
                    return self._fetch()
    """)
    assert rules_of(r) == ["TS008"]


def test_ts008_blocking_outside_lock_is_clean(tmp_path):
    r = run_snippet(tmp_path, """
        import threading
        import time

        class Poller:
            def __init__(self):
                self._lock = threading.Lock()

            def poll(self):
                time.sleep(0.1)
                with self._lock:
                    return 1
    """)
    assert rules_of(r) == []


def test_ts008_condition_wait_on_held_lock_is_exempt(tmp_path):
    # cond.wait() RELEASES the held mutex by contract — the stdlib
    # Queue discipline must not be flagged
    r = run_snippet(tmp_path, """
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._not_empty = threading.Condition(self._lock)

            def get(self):
                with self._not_empty:
                    self._not_empty.wait()
    """)
    assert rules_of(r) == []


# --------------------------------------------------------------------------
# TS009 — cross-thread-unlocked-write
# --------------------------------------------------------------------------

def test_ts009_unlocked_write_from_two_roots(tmp_path):
    r = run_snippet(tmp_path, """
        import threading

        class Counter:
            def __init__(self):
                self._n = 0
                self._t = threading.Thread(target=self._work)

            def _work(self):
                self._n += 1

            def bump(self):
                self._n += 1
    """)
    assert rules_of(r) == ["TS009"]


def test_ts009_locked_writes_are_clean(tmp_path):
    r = run_snippet(tmp_path, """
        import threading

        class Counter:
            def __init__(self):
                self._mu = threading.Lock()
                self._n = 0
                self._t = threading.Thread(target=self._work)

            def _work(self):
                with self._mu:
                    self._n += 1

            def bump(self):
                with self._mu:
                    self._n += 1
    """)
    assert rules_of(r) == []


def test_ts009_single_root_is_clean(tmp_path):
    # both writers run on the main thread — no race to report
    r = run_snippet(tmp_path, """
        class Counter:
            def __init__(self):
                self._n = 0

            def bump(self):
                self._n += 1

            def reset(self):
                self._n = 0
    """)
    assert rules_of(r) == []


def test_ts009_init_helper_writes_are_exempt(tmp_path):
    # construction-time writers (happens-before Thread.start) don't race
    r = run_snippet(tmp_path, """
        import threading

        class Board:
            def __init__(self):
                self._init_labels()
                self._t = threading.Thread(target=self._work)

            def _init_labels(self):
                self._labels = {}

            def _work(self):
                with self._mu:
                    self._labels = {}
    """)
    assert rules_of(r) == []


def test_ts009_lock_inherited_from_caller_counts(tmp_path):
    # the write site holds the lock via its caller (held-on-entry), not
    # lexically — still protected
    r = run_snippet(tmp_path, """
        import threading

        class Counter:
            def __init__(self):
                self._mu = threading.Lock()
                self._n = 0
                self._t = threading.Thread(target=self._work)

            def _work(self):
                with self._mu:
                    self._bump_locked()

            def _bump_locked(self):
                self._n += 1

            def bump(self):
                with self._mu:
                    self._bump_locked()
    """)
    assert rules_of(r) == []


# --------------------------------------------------------------------------
# TS010 — future-single-resolution
# --------------------------------------------------------------------------

def test_ts010_settle_state_written_outside_funnel(tmp_path):
    r = run_snippet(tmp_path, """
        import threading

        class Future:
            def __init__(self):
                self._event = threading.Event()
                self._result = None

            def _finish(self, value):
                self._result = value
                self._event.set()

            def force(self, value):
                self._result = value
                self._event.set()
    """)
    assert rules_of(r) == ["TS010", "TS010"]  # state write + event fire


def test_ts010_funnel_discipline_is_clean(tmp_path):
    r = run_snippet(tmp_path, """
        import threading

        class Future:
            def __init__(self):
                self._event = threading.Event()
                self._result = None

            def _finish(self, value):
                self._result = value
                self._event.set()

            def resolve(self, value):
                self._finish(value)

            def reject(self, err):
                self._finish(err)
    """)
    assert rules_of(r) == []


def test_ts010_resolver_without_settle_guard(tmp_path):
    # clause B: offer() writes the first-wins flag, force() settles the
    # member future WITHOUT it — the hedging double-resolve shape
    r = run_snippet(tmp_path, """
        class Routed:
            def __init__(self, fut):
                self._settled = False
                self.future = fut

            def offer(self, value):
                if not self._settled:
                    self._settled = True
                    self.future._resolve(value)

            def force(self, err):
                self.future._reject(err)
    """)
    assert rules_of(r) == ["TS010"]


def test_ts010_guarded_resolvers_are_clean(tmp_path):
    r = run_snippet(tmp_path, """
        class Routed:
            def __init__(self, fut):
                self._settled = False
                self.future = fut

            def offer(self, value):
                if not self._settled:
                    self._settled = True
                    self.future._resolve(value)

            def force(self, err):
                if not self._settled:
                    self._settled = True
                    self.future._reject(err)
    """)
    assert rules_of(r) == []


# --------------------------------------------------------------------------
# suppression + reporting plumbing
# --------------------------------------------------------------------------

def test_project_rule_inline_suppression(tmp_path):
    r = run_snippet(tmp_path, """
        import threading
        import time

        class Poller:
            def __init__(self):
                self._lock = threading.Lock()

            def poll(self):
                with self._lock:
                    time.sleep(0.1)  # tslint: disable=TS008 -- fixture
    """)
    assert rules_of(r) == []
    assert r.suppressed == 1


def test_concurrency_findings_span_files(tmp_path):
    # the inversion is only visible when BOTH files are in the graph
    r = run_project(tmp_path, {
        "a.py": """
            import threading

            class Transfer:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def deposit(self):
                    with self._a:
                        with self._b:
                            return 1
        """,
        "b.py": """
            class Drain:
                def run(self, t):
                    with t._b:
                        with t._a:
                            return 2
        """,
    })
    # cross-file attribute locks resolve only for self.<attr>; the
    # SAME-class inversion in a.py alone must stay clean
    ra = analyze([str(tmp_path / "a.py")], root=str(tmp_path),
                 select=CONCURRENCY)
    assert rules_of(ra) == []
    assert r.files == 2


# --------------------------------------------------------------------------
# CLI contract
# --------------------------------------------------------------------------

def _cli(args, cwd=REPO_ROOT):
    return subprocess.run(
        [sys.executable, "-m", "tools.tslint", *args],
        cwd=cwd, capture_output=True, text=True,
        env={**os.environ, "PYTHONPATH": REPO_ROOT})


def _write(tmp_path, name, code):
    f = tmp_path / name
    f.write_text(textwrap.dedent(code), encoding="utf-8")
    return f


def test_cli_seeded_deadlock_exits_1(tmp_path):
    bug = _write(tmp_path, "bug.py", DEADLOCK)
    proc = _cli(["--no-baseline", "--root", str(tmp_path), str(bug)])
    assert proc.returncode == 1
    assert "TS007" in proc.stdout


def test_cli_rules_filter(tmp_path):
    # the fixture trips TS007 AND TS003 (time.time); --rules must hide
    # the rules not selected
    bug = _write(tmp_path, "bug.py", DEADLOCK + """
    def stamp(t0):
        import time
        return time.time() - t0
    """)
    proc = _cli(["--no-baseline", "--root", str(tmp_path),
                 "--rules", "TS003", str(bug)])
    assert proc.returncode == 1
    assert "TS003" in proc.stdout and "TS007" not in proc.stdout
    proc = _cli(["--no-baseline", "--root", str(tmp_path),
                 "--rules", "TS007,TS008", str(bug)])
    assert proc.returncode == 1
    assert "TS007" in proc.stdout and "TS003" not in proc.stdout


def _git(tmp_path, *args):
    return subprocess.run(
        ["git", *args], cwd=str(tmp_path), capture_output=True, text=True,
        env={**os.environ,
             "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
             "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t"})


def test_cli_changed_scans_only_the_diff(tmp_path):
    assert _git(tmp_path, "init", "-q").returncode == 0
    _write(tmp_path, "clean.py", """
        import time

        def f(t0):
            return time.time() - t0
    """)
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    # clean.py has a TS003 at HEAD; the NEW file carries a TS007
    _write(tmp_path, "fresh.py", DEADLOCK)
    proc = _cli(["--no-baseline", "--root", str(tmp_path),
                 "--changed", "HEAD", "."])
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "fresh.py" in proc.stdout
    assert "clean.py" not in proc.stdout  # unchanged vs HEAD — skipped


def test_cli_changed_with_no_changes_exits_0(tmp_path):
    assert _git(tmp_path, "init", "-q").returncode == 0
    _write(tmp_path, "a.py", "X = 1\n")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    proc = _cli(["--no-baseline", "--root", str(tmp_path),
                 "--changed", "HEAD", "."])
    assert proc.returncode == 0
    assert "no changed python files" in proc.stdout


def test_cli_write_baseline_prunes_deleted_files(tmp_path):
    doomed = _write(tmp_path, "doomed.py", """
        import time

        def f(t0):
            return time.time() - t0
    """)
    keeper = _write(tmp_path, "keeper.py", DEADLOCK)
    bl = tmp_path / "bl.json"
    proc = _cli(["--root", str(tmp_path), "--baseline", str(bl),
                 "--write-baseline", str(tmp_path)])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    entries = json.loads(bl.read_text())["findings"]
    assert {e["path"] for e in entries} == {"doomed.py", "keeper.py"}
    # the file dies; a rewrite scanning ONLY keeper.py must still drop
    # the stale doomed.py debt instead of carrying it forever
    doomed.unlink()
    proc = _cli(["--root", str(tmp_path), "--baseline", str(bl),
                 "--write-baseline", str(keeper)])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "pruned" in proc.stdout
    entries = json.loads(bl.read_text())["findings"]
    assert {e["path"] for e in entries} == {"keeper.py"}


def test_cli_write_baseline_carries_unscanned_files(tmp_path):
    _write(tmp_path, "a.py", DEADLOCK)
    _write(tmp_path, "b.py", """
        import time

        def f(t0):
            return time.time() - t0
    """)
    bl = tmp_path / "bl.json"
    _cli(["--root", str(tmp_path), "--baseline", str(bl),
          "--write-baseline", str(tmp_path)])
    before = {e["path"] for e in json.loads(bl.read_text())["findings"]}
    assert before == {"a.py", "b.py"}
    # subset rewrite: a.py's debt must survive a b.py-only scan
    proc = _cli(["--root", str(tmp_path), "--baseline", str(bl),
                 "--write-baseline", str(tmp_path / "b.py")])
    assert "carried" in proc.stdout
    after = {e["path"] for e in json.loads(bl.read_text())["findings"]}
    assert after == {"a.py", "b.py"}


def test_cli_lock_graph_output(tmp_path):
    _write(tmp_path, "m.py", DEADLOCK)
    out = tmp_path / "graph.json"
    proc = _cli(["--root", str(tmp_path), "--lock-graph", str(out),
                 str(tmp_path)])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(out.read_text())
    assert payload["tool"] == "tslint"
    assert set(payload["locks"]) == {"Transfer._a", "Transfer._b"}
    edges = {tuple(e) for e in payload["edges"]}
    assert ("Transfer._a", "Transfer._b") in edges
    assert ("Transfer._b", "Transfer._a") in edges


def test_lock_graph_api_matches_repo_locks():
    payload = lock_graph([PACKAGE], root=REPO_ROOT)
    # the sanitizer names its locks Class.attr — the graph must carry
    # the real serving locks the smokes exercise
    assert "RequestQueue._lock" in payload["locks"]
    assert "RemoteReplica._ingress_lock" in payload["locks"]


# --------------------------------------------------------------------------
# rule registry
# --------------------------------------------------------------------------

def test_project_rule_registry():
    assert [r.id for r in PROJECT_RULES] == ["TS007", "TS008", "TS009",
                                             "TS010"]
    ids = {r.id for r in ALL_RULES}
    assert ids == {f"TS{i:03d}" for i in range(1, 11)}


def test_repo_tools_tree_is_clean_on_concurrency_rules():
    # the analyzer's own code (and the whole package) must pass the
    # concurrency rules it enforces — the lint.sh stage-3 gate, in-proc
    result = analyze([PACKAGE, "tools"], root=REPO_ROOT,
                     select=CONCURRENCY)
    assert result.findings == [], "\n".join(
        f.format_text() for f in result.findings)


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-q"]))
