"""Transformer model family: training, KV-cache decode, sharding, serving.

The family must be a drop-in behind every subsystem the pointer-generator
uses: Trainer/Evaluator (same TrainOutput contract), the generic beam
search (adapter protocol), checkpointing (list-bearing pytrees), and the
(dp, tp, sp) mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.data.batching import Batch, SummaryExample
from textsummarization_on_flink_tpu.data.vocab import Vocab
from textsummarization_on_flink_tpu.decode import beam_search
from textsummarization_on_flink_tpu.models import get_family
from textsummarization_on_flink_tpu.models import transformer as tfm
from textsummarization_on_flink_tpu.parallel import mesh as mesh_lib
from textsummarization_on_flink_tpu.train import trainer as trainer_lib


def _has_force_tpu_interpret() -> bool:
    """The flash-interpret tests execute the Pallas TPU flash kernel on
    CPU via pltpu.force_tpu_interpret_mode, which this jax build (0.4.x)
    does not ship — skip them there (ISSUE 7 satellite) so tier-1
    reports 0 failures and a real regression is visible again."""
    try:
        from jax.experimental.pallas import tpu as pltpu
    except ImportError:  # pragma: no cover - pallas absent entirely
        return False
    return hasattr(pltpu, "force_tpu_interpret_mode")


needs_force_tpu_interpret = pytest.mark.skipif(
    not _has_force_tpu_interpret(),
    reason="pltpu.force_tpu_interpret_mode is absent from this jax build")


def tiny_hps(**kw) -> HParams:
    base = dict(model_family="transformer", hidden_dim=16, emb_dim=16,
                batch_size=8, max_enc_steps=16, max_dec_steps=6, beam_size=2,
                min_dec_steps=2, vocab_size=64, max_oov_buckets=8,
                num_heads=4, enc_layers=2, dec_layers=2)
    base.update(kw)
    return HParams(**base)


def tiny_vocab(n: int = 64) -> Vocab:
    return Vocab(words=[f"w{i}" for i in range(n - 4)], max_size=n)


def make_batch(hps, vocab, seed=0):
    rng = np.random.RandomState(seed)
    exs = []
    for i in range(hps.batch_size):
        n_art = rng.randint(5, hps.max_enc_steps)
        n_abs = rng.randint(2, hps.max_dec_steps)
        art = " ".join(rng.choice([f"w{j}" for j in range(50)] + ["zzz_oov"],
                                  n_art))
        abs_ = " ".join(rng.choice([f"w{j}" for j in range(50)], n_abs))
        exs.append(SummaryExample.build(art, [abs_], vocab, hps))
    return Batch(exs, hps, vocab)


@pytest.fixture(scope="module")
def setup():
    hps = tiny_hps(coverage=True)
    vocab = tiny_vocab(hps.vocab_size)
    batch = make_batch(hps, vocab)
    state = trainer_lib.init_train_state(hps, vocab.size(), seed=7)
    return hps, vocab, batch, state


def test_get_family_dispatch():
    assert get_family("transformer") is tfm
    with pytest.raises(ValueError, match="unknown model_family"):
        get_family("perceptron")


def test_validate_rejects_bad_heads():
    with pytest.raises(ValueError, match="num_heads"):
        tiny_hps(hidden_dim=16, num_heads=3).validate()


def test_forward_train_shapes_and_finite(setup):
    hps, vocab, batch, state = setup
    out = jax.jit(lambda p, a: tfm.forward_train(p, hps, a))(
        state.params, batch.as_arrays())
    B, T_dec, T_enc = hps.batch_size, hps.max_dec_steps, hps.max_enc_steps
    assert out.attn_dists.shape == (B, T_dec, T_enc)
    assert out.p_gens.shape == (B, T_dec)
    assert np.isfinite(float(out.loss))
    assert float(out.coverage_loss) >= 0
    # copy distribution is a (masked) probability distribution per step
    sums = np.asarray(out.attn_dists).sum(-1)
    assert np.all(sums < 1.0 + 1e-4)
    pg = np.asarray(out.p_gens)
    assert np.all((pg >= 0) & (pg <= 1))


def test_training_loss_decreases(setup):
    hps, vocab, batch, state = setup
    step = jax.jit(trainer_lib.make_train_step(hps))
    losses = []
    for _ in range(8):
        state, metrics = step(state, batch.as_arrays())
        losses.append(float(metrics.loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_kv_cache_matches_teacher_forcing(setup):
    """Incremental decoding with the static KV cache must reproduce the
    teacher-forced forward pass exactly: feed the gold prefix through the
    beam-adapter step and compare per-step copy attention and p_gen."""
    hps, vocab, batch, state = setup
    hps1 = hps.replace(beam_size=1)  # K=1: one forced hypothesis
    arrays = batch.as_arrays()
    ref = tfm.forward_train(state.params, hps, arrays)

    enc_view = tfm.beam_encode(state.params, hps1, arrays)
    init_state_fn, step_fn = tfm.beam_adapter(hps1)
    b = 2  # probe one article
    enc_one = jax.tree_util.tree_map(lambda x: x[b], enc_view)
    enc_mask = arrays["enc_padding_mask"][b]
    ext_ids = arrays["enc_batch_extend_vocab"][b]
    st = init_state_fn(state.params, enc_one)
    n_steps = int(np.sum(arrays["dec_padding_mask"][b]))
    for t in range(n_steps):
        latest = arrays["dec_batch"][b, t][None]  # [K=1]
        out = step_fn(state.params, enc_one, enc_mask, ext_ids,
                      np.int32(t), latest, st)
        st = out.state
        np.testing.assert_allclose(np.asarray(out.attn_dist[0]),
                                   np.asarray(ref.attn_dists[b, t]),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(float(out.p_gen[0]),
                                   float(ref.p_gens[b, t]),
                                   rtol=1e-4, atol=1e-5)


def test_bf16_blocks_compute_in_bf16(setup):
    """bf16 activations against f32 master params must NOT silently
    promote the layer matmuls back to f32 (half the MXU's bf16 rate):
    _mha and _ffn_block cast params to the activation dtype, so their
    outputs stay bf16."""
    hps, vocab, batch, state = setup
    rng = np.random.RandomState(0)
    layer = state.params["encoder"]["layers"][0]
    x = jnp.asarray(rng.randn(2, 8, hps.hidden_dim) * 0.1, jnp.bfloat16)
    mask = jnp.ones((2, 1, 8), jnp.float32)
    out, probs = tfm._mha(hps, layer["self_attn"], x, x, mask)
    assert out.dtype == jnp.bfloat16
    assert probs.dtype == jnp.float32  # copy distribution stays f32
    assert tfm._ffn_block(layer["ffn"], x).dtype == jnp.bfloat16


def test_bf16_forward_train_close_to_f32(setup):
    hps, vocab, batch, state = setup
    arrays = batch.as_arrays()
    out32 = tfm.forward_train(state.params, hps, arrays)
    out16 = tfm.forward_train(state.params,
                              hps.replace(compute_dtype="bfloat16"), arrays)
    assert np.isfinite(float(out16.loss))
    np.testing.assert_allclose(float(out16.loss), float(out32.loss),
                               rtol=3e-2)


def test_flash_gating(monkeypatch):
    """Flash self-attention needs a TPU backend (the kernel has no
    CPU/GPU lowering); TS_FLASH=off always wins; =on engages on ANY
    shape (unaligned T/head_dim get zero-padded to the 128 grid); auto
    — the frozen default — keeps the conservative natively-aligned
    T >= 1024 rule."""
    hps_small = tiny_hps()  # hd=4 -> auto never fires
    assert not tfm._use_flash(hps_small, 400)
    hps_big = tiny_hps(hidden_dim=1024, num_heads=8)  # hd=128
    monkeypatch.setenv("TS_FLASH", "on")
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert tfm._use_flash(hps_big, 1024)
    assert tfm._use_flash(hps_big, 400)  # forced: padded path handles it
    monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
    assert not tfm._use_flash(hps_big, 1024)  # forced, but no TPU
    monkeypatch.setenv("TS_FLASH", "off")
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert not tfm._use_flash(hps_big, 1024)
    monkeypatch.setenv("TS_FLASH", "auto")
    monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
    assert not tfm._use_flash(hps_big, 1024)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert tfm._use_flash(hps_big, 1024)
    assert not tfm._use_flash(hps_big, 512)  # auto needs T >= 1024


@needs_force_tpu_interpret
def test_flash_branch_matches_einsum_interpret(monkeypatch):
    """Execute the ACTUAL flash branch (segment ids, head transposes,
    sm_scale) in Pallas interpret mode on CPU and compare real-row outputs
    against the einsum path."""
    from jax.experimental.pallas import tpu as pltpu

    hps = tiny_hps(hidden_dim=128, num_heads=1)  # hd=128, lane-aligned
    T, B, H = 128, 2, 128
    rng = np.random.RandomState(0)
    p = {k: jnp.asarray(rng.randn(H, H) * 0.05, jnp.float32)
         for k in ("wq", "wk", "wv", "wo")}
    x = jnp.asarray(rng.randn(B, T, H) * 0.3, jnp.float32)
    lens = np.array([T, T // 2])
    mask = jnp.asarray((np.arange(T)[None] < lens[:, None]), jnp.float32)

    monkeypatch.setenv("TS_FLASH", "off")
    ref = tfm._self_attention(hps, p, x, mask, causal=False)
    monkeypatch.setenv("TS_FLASH", "on")
    # _use_flash requires a TPU backend even when forced (the kernel has
    # no CPU lowering); interpret mode stands in for the hardware here
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert tfm._use_flash(hps, T)
    with pltpu.force_tpu_interpret_mode():
        got = tfm._self_attention(hps, p, x, mask, causal=False)
        got_causal = tfm._self_attention(hps, p, x, None, causal=True)
    monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
    monkeypatch.setenv("TS_FLASH", "off")
    ref_causal = tfm._self_attention(hps, p, x, None, causal=True)
    real = np.asarray(mask)[:, :, None] > 0
    np.testing.assert_allclose(np.where(real, np.asarray(got), 0),
                               np.where(real, np.asarray(ref), 0),
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(got_causal), np.asarray(ref_causal),
                               rtol=2e-3, atol=2e-3)


@needs_force_tpu_interpret
def test_flash_padded_unaligned_matches_einsum_interpret(monkeypatch):
    """TS_FLASH=on at UNALIGNED shapes (reference-class T=40, hd=32)
    zero-pads q/k/v to the 128 grid — fwd AND grad must match the
    einsum path exactly on real rows, both encoder (padding mask) and
    causal decoder.  This is the correctness gate under the
    train_transformer_flash sweep row (BASELINE.md roofline: the einsum
    path's materialized score tensors dominate the transformer step's
    bytes)."""
    from jax.experimental.pallas import tpu as pltpu

    hps = tiny_hps(hidden_dim=128, num_heads=4)  # hd=32: not lane-aligned
    T, B, H = 40, 2, 128
    rng = np.random.RandomState(0)
    p = {k: jnp.asarray(rng.randn(H, H) * 0.05, jnp.float32)
         for k in ("wq", "wk", "wv", "wo")}
    x = jnp.asarray(rng.randn(B, T, H) * 0.3, jnp.float32)
    lens = np.array([T, T - 13])
    mask = jnp.asarray((np.arange(T)[None] < lens[:, None]), jnp.float32)

    def f_enc(x):
        out = tfm._self_attention(hps, p, x, mask, causal=False)
        return jnp.sum((out * mask[:, :, None]) ** 2)  # mask garbage rows

    def f_dec(x):
        return jnp.sum(tfm._self_attention(hps, p, x, None, causal=True)
                       ** 2)

    monkeypatch.setenv("TS_FLASH", "off")
    refs = [f(x) for f in (f_enc, f_dec)]
    grefs = [jax.grad(f)(x) for f in (f_enc, f_dec)]
    monkeypatch.setenv("TS_FLASH", "on")
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert tfm._use_flash(hps, T)
    with pltpu.force_tpu_interpret_mode():
        gots = [f(x) for f in (f_enc, f_dec)]
        ggots = [jax.grad(f)(x) for f in (f_enc, f_dec)]
    for ref, got in zip(refs, gots):
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-5)
    for gref, gflash in zip(grefs, ggots):
        err = float(jnp.max(jnp.abs(gref - gflash)))
        scale = float(jnp.max(jnp.abs(gref)))
        assert err < 1e-5 * max(scale, 1.0), (err, scale)


@pytest.mark.slow
@needs_force_tpu_interpret
def test_flash_grad_parity_bench_scale(monkeypatch):
    """The EXACT correctness gate bench.py's flash mode runs on hardware
    (fwd+bwd through a masked sum-of-squares loss at T=2048), executed in
    Pallas interpret mode on CPU — so only the flash *timing* ever waits
    on the TPU tunnel (VERDICT r2 #6).  ~17s on CPU."""
    from jax.experimental.pallas import tpu as pltpu

    hps = tiny_hps(hidden_dim=128, num_heads=1)
    T, B, H = 2048, 1, 128
    rng = np.random.RandomState(0)
    p = {k: jnp.asarray(rng.randn(H, H) * 0.05, jnp.float32)
         for k in ("wq", "wk", "wv", "wo")}
    x = jnp.asarray(rng.randn(B, T, H) * 0.3, jnp.float32)
    lens = np.array([T - 256])  # real padding tail
    mask = jnp.asarray((np.arange(T)[None] < lens[:, None]), jnp.float32)

    def f(x):
        out = tfm._self_attention(hps, p, x, mask, causal=False)
        # mask the loss: padding-query rows legitimately differ between
        # the paths and must not leak gradient into the comparison
        return jnp.sum((out * mask[:, :, None]) ** 2)

    monkeypatch.setenv("TS_FLASH", "off")
    g_ref = jax.grad(f)(x)
    monkeypatch.setenv("TS_FLASH", "on")
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert tfm._use_flash(hps, T)
    with pltpu.force_tpu_interpret_mode():
        g_flash = jax.grad(f)(x)
    real = np.asarray(mask)[:, :, None] > 0
    err = float(jnp.max(jnp.abs(jnp.where(real, g_ref - g_flash, 0.0))))
    scale = float(jnp.max(jnp.abs(jnp.where(real, g_ref, 0.0))))
    assert err <= 1e-2 * max(scale, 1.0), (err, scale)  # bench's gate
    assert err < 1e-6  # and far tighter in practice (observed ~3e-9)


@pytest.mark.slow
def test_remat_gradient_parity(setup):
    """--remat recomputes layer activations in backward; gradients must
    match the stored-activation path (up to FP reassociation)."""
    hps, vocab, batch, state = setup
    arrays = batch.as_arrays()
    g0 = jax.grad(
        lambda p: tfm.forward_train(p, hps, arrays).total_loss)(state.params)
    g1 = jax.grad(
        lambda p: tfm.forward_train(p, hps.replace(remat=True),
                                    arrays).total_loss)(state.params)
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        a, b = np.asarray(a), np.asarray(b)
        scale = np.max(np.abs(a)) + 1e-12
        assert np.max(np.abs(a - b)) / scale < 1e-5


def test_beam_search_generic_driver(setup):
    hps, vocab, batch, state = setup
    enc_only = {k: v for k, v in batch.as_arrays().items()
                if k.startswith("enc_")}
    out = beam_search.run_beam_search(state.params, hps, enc_only)
    B, T = hps.batch_size, hps.max_dec_steps
    assert out.tokens.shape == (B, T + 1)
    assert np.all(out.tokens[:, 0] == 2)  # START
    assert np.all((out.length >= 2) & (out.length <= T + 1))
    assert np.all(np.isfinite(out.avg_log_prob))
    assert out.attn_dists.shape == (B, T, hps.max_enc_steps)


def test_checkpoint_roundtrip_with_layer_lists(setup, tmp_path):
    from textsummarization_on_flink_tpu.checkpoint import (
        checkpointer as ckpt_lib,
    )

    hps, vocab, batch, state = setup
    ck = ckpt_lib.Checkpointer(str(tmp_path), hps=hps)
    ck.save(state)
    path, flat = ckpt_lib.load_ckpt(str(tmp_path), max_retries=0)
    restored = ckpt_lib.arrays_to_state(flat)
    assert isinstance(restored.params["encoder"]["layers"], list)
    assert len(restored.params["encoder"]["layers"]) == hps.enc_layers
    ref_leaves = jax.tree_util.tree_leaves(jax.device_get(state.params))
    got_leaves = jax.tree_util.tree_leaves(restored.params)
    assert len(ref_leaves) == len(got_leaves)
    for r, g in zip(ref_leaves, got_leaves):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(g))


def test_coverage_conversion_rejects_transformer(setup, tmp_path):
    from textsummarization_on_flink_tpu.checkpoint import (
        checkpointer as ckpt_lib,
    )

    hps, vocab, batch, state = setup
    ckpt_lib.Checkpointer(str(tmp_path), hps=hps).save(state)
    with pytest.raises(ValueError, match="pointer_generator family only"):
        ckpt_lib.convert_to_coverage_model(str(tmp_path), hps)


@pytest.mark.parametrize("dp,tp,sp", [(8, 1, 1), (2, 2, 2)])
@pytest.mark.slow
def test_sharded_train_step_matches_single_device(setup, dp, tp, sp):
    hps, vocab, batch, state = setup
    single = jax.jit(trainer_lib.make_train_step(hps))
    ref_state, ref_metrics = single(state, batch.as_arrays())
    hps_m = hps.replace(dp=dp, tp=tp, sp=sp)
    mesh_lib.validate_divisibility(hps_m, state.params)
    plan = mesh_lib.make_mesh(hps_m)
    sharded_state = mesh_lib.shard_train_state(plan, state)
    step = mesh_lib.make_sharded_train_step(plan, donate=False)
    new_state, metrics = step(sharded_state, batch.as_arrays())
    np.testing.assert_allclose(float(metrics.loss), float(ref_metrics.loss),
                               rtol=2e-5)
    ref_leaves = jax.tree_util.tree_leaves(jax.device_get(ref_state.params))
    got_leaves = jax.tree_util.tree_leaves(jax.device_get(new_state.params))
    for r, g in zip(ref_leaves, got_leaves):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r), rtol=1e-4,
                                   atol=1e-6)


def test_ring_attention_op_matches_full_attention():
    """Standalone ring op vs full masked softmax attention on a 4-device
    sp ring (padding spanning whole blocks included)."""
    from jax.sharding import Mesh
    from textsummarization_on_flink_tpu.parallel import ring_attention as ra

    devs = np.asarray(jax.devices()[:4]).reshape(4)
    mesh = Mesh(devs, ("sp",))
    B, T, nh, hd = 2, 32, 2, 8
    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(B, T, nh, hd), jnp.float32)
               for _ in range(3))
    lens = np.array([T, T // 4])  # row 1: 3 of 4 blocks are pure padding
    mask = jnp.asarray((np.arange(T)[None] < lens[:, None]), jnp.float32)
    scale = hd ** -0.5
    logits = jnp.einsum("bqnd,bknd->bnqk", q, k) * scale
    logits = jnp.where(mask[:, None, None, :] > 0, logits, -1e30)
    p = jax.nn.softmax(logits, -1) * (mask[:, None, None, :] > 0)
    ref = jnp.einsum("bnqk,bknd->bqnd", p, v)
    out = jax.jit(ra.make_ring_attention(mesh, "sp"))(q, k, v, mask, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_ring_attention_sharded_step_matches_single_device(setup):
    """Full transformer train step with --sp_attention=ring under a
    (dp=2, sp=4) mesh == the single-device step without it."""
    hps, vocab, batch, state = setup
    single = jax.jit(trainer_lib.make_train_step(hps))
    ref_state, ref_metrics = single(state, batch.as_arrays())

    hps_m = hps.replace(dp=2, tp=1, sp=4, sp_attention="ring")
    plan = mesh_lib.make_mesh(hps_m)
    sharded_state = mesh_lib.shard_train_state(plan, state)
    step = mesh_lib.make_sharded_train_step(plan, donate=False)
    new_state, metrics = step(sharded_state, batch.as_arrays())
    np.testing.assert_allclose(float(metrics.loss), float(ref_metrics.loss),
                               rtol=2e-5)
    ref_leaves = jax.tree_util.tree_leaves(jax.device_get(ref_state.params))
    got_leaves = jax.tree_util.tree_leaves(jax.device_get(new_state.params))
    for r, g in zip(ref_leaves, got_leaves):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r), rtol=1e-4,
                                   atol=1e-6)


def test_ulysses_attention_op_matches_full_attention():
    """All-to-all SP layout vs full masked softmax attention."""
    from jax.sharding import Mesh
    from textsummarization_on_flink_tpu.parallel import ring_attention as ra

    devs = np.asarray(jax.devices()[:4]).reshape(4)
    mesh = Mesh(devs, ("sp",))
    B, T, nh, hd = 2, 32, 4, 8  # nh % sp == 0
    rng = np.random.RandomState(1)
    q, k, v = (jnp.asarray(rng.randn(B, T, nh, hd), jnp.float32)
               for _ in range(3))
    lens = np.array([T, T // 4])
    mask = jnp.asarray((np.arange(T)[None] < lens[:, None]), jnp.float32)
    scale = hd ** -0.5
    logits = jnp.einsum("bqnd,bknd->bnqk", q, k) * scale
    logits = jnp.where(mask[:, None, None, :] > 0, logits, -1e30)
    p = jax.nn.softmax(logits, -1) * (mask[:, None, None, :] > 0)
    ref = jnp.einsum("bnqk,bknd->bqnd", p, v)
    out = jax.jit(ra.make_sp_attention(mesh, "ulysses"))(q, k, v, mask, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_ulysses_sharded_step_matches_single_device(setup):
    """Full transformer train step with --sp_attention=ulysses under a
    (dp=2, sp=4) mesh == the single-device step (num_heads=4 % sp ok)."""
    hps, vocab, batch, state = setup
    single = jax.jit(trainer_lib.make_train_step(hps))
    ref_state, ref_metrics = single(state, batch.as_arrays())
    hps_m = hps.replace(dp=2, tp=1, sp=4, sp_attention="ulysses")
    mesh_lib.validate_divisibility(hps_m, state.params)
    plan = mesh_lib.make_mesh(hps_m)
    sharded_state = mesh_lib.shard_train_state(plan, state)
    step = mesh_lib.make_sharded_train_step(plan, donate=False)
    _, metrics = step(sharded_state, batch.as_arrays())
    np.testing.assert_allclose(float(metrics.loss), float(ref_metrics.loss),
                               rtol=2e-5)


def test_ulysses_rejects_indivisible_heads(setup):
    hps, vocab, batch, state = setup
    with pytest.raises(ValueError, match="must divide num_heads"):
        mesh_lib.validate_divisibility(
            hps.replace(sp=8, max_enc_steps=16, num_heads=4,
                        sp_attention="ulysses"))


def test_ring_attention_rejects_tp(setup):
    hps, vocab, batch, state = setup
    with pytest.raises(ValueError, match="sp_attention with tp>1"):
        mesh_lib.validate_divisibility(
            hps.replace(dp=2, tp=2, sp=2, sp_attention="ring"), state.params)


def test_ring_attention_serving_matches_plain(setup):
    """Sharded beam search under --sp_attention=ring (sp>1) returns the
    same hypotheses as the single-device search without it — the serving
    path gets the mesh context too."""
    hps, vocab, batch, state = setup
    enc_only = {k: v for k, v in batch.as_arrays().items()
                if k.startswith("enc_")}
    plain = beam_search.run_beam_search(state.params, hps, enc_only)
    hps_m = hps.replace(dp=2, tp=1, sp=4, sp_attention="ring",
                        mode="decode")
    plan = mesh_lib.make_mesh(hps_m)
    fn = mesh_lib.make_sharded_beam_search(plan)
    sharded_params = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, plan.named(s)), state.params,
        mesh_lib.param_pspecs(state.params),
        is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec))
    out = fn(sharded_params, mesh_lib.shard_batch(plan, enc_only))
    np.testing.assert_array_equal(np.asarray(out.tokens), plain.tokens)
    np.testing.assert_array_equal(np.asarray(out.length), plain.length)


def test_tp_shards_megatron_layout(setup):
    hps, vocab, batch, state = setup
    plan = mesh_lib.make_mesh(hps.replace(dp=4, tp=2))
    sharded = mesh_lib.shard_train_state(plan, state)
    p = sharded.params
    assert p["embedding"].sharding.spec == mesh_lib.P("tp", None)
    assert p["out_bias"].sharding.spec == mesh_lib.P("tp")
    layer = p["decoder"]["layers"][0]
    assert layer["self_attn"]["wq"].sharding.spec == mesh_lib.P(None, "tp")
    assert layer["self_attn"]["wo"].sharding.spec == mesh_lib.P("tp", None)
    assert layer["ffn"]["w1"].sharding.spec == mesh_lib.P(None, "tp")
    assert layer["ffn"]["w2"].sharding.spec == mesh_lib.P("tp", None)
    assert layer["ln1"]["scale"].sharding.spec == mesh_lib.P()


def test_estimator_pipeline_with_transformer(tmp_path):
    """The reference's testInferenceAfterTraining path (fit -> transform,
    weights via checkpoint dir) with model_family=transformer selected
    through the hyper-params argv string — the full L6 pipeline surface."""
    import shlex

    from textsummarization_on_flink_tpu.pipeline import estimator as est_lib
    from textsummarization_on_flink_tpu.pipeline.io import (
        CollectionSink,
        CollectionSource,
        DataTypes,
    )

    words = ("article reference the a quick brown fox jumped over lazy dog "
             "0 1 2 3 4 5 6 7").split()
    vocab = Vocab(words=words)

    def hp(mode):
        hps = HParams(mode=mode, num_steps=2, batch_size=4, hidden_dim=8,
                      emb_dim=8, vocab_size=24, max_enc_steps=12,
                      max_dec_steps=6, beam_size=2, min_dec_steps=1,
                      max_oov_buckets=4, log_root=str(tmp_path),
                      exp_name="exp", model_family="transformer",
                      num_heads=2, enc_layers=1, dec_layers=1)
        return shlex.split(hps.to_argv())

    e = est_lib.SummarizationEstimator()
    (e.set_train_selected_cols(["uuid", "article", "reference"])
      .set_train_output_cols(["uuid"])
      .set_train_output_types([DataTypes.STRING]))
    e.set_train_hyper_params(hp("train"))
    (e.set_inference_selected_cols(["uuid", "article", "reference"])
      .set_inference_output_cols(["uuid", "article", "summary", "reference"])
      .set_inference_output_types([DataTypes.STRING] * 4))
    e.set_inference_hyper_params(hp("decode"))
    e.with_vocab(vocab)

    rows = [(f"uuid-{i}", f"article {i} .", "", f"reference {i} .")
            for i in range(8)]
    model = e.fit(CollectionSource(rows))
    sink = CollectionSink()
    model.with_vocab(vocab)
    model.transform(CollectionSource(rows), sink)
    assert len(sink.rows) == 8
    for uuid, article, summary, reference in sink.rows:
        assert uuid.startswith("uuid-")
        assert isinstance(summary, str)


def test_decoder_serving_end_to_end(setup, tmp_path):
    """BeamSearchDecoder serves the transformer through the same stack:
    checkpoint dir -> batcher -> beam search -> result rows."""
    from textsummarization_on_flink_tpu.checkpoint import (
        checkpointer as ckpt_lib,
    )
    from textsummarization_on_flink_tpu.data.batcher import Batcher
    from textsummarization_on_flink_tpu.decode import decoder as dec_lib

    hps, vocab, batch, state = setup
    dec_hps = hps.replace(mode="decode", batch_size=2, single_pass=False,
                          min_dec_steps=1)
    train_dir = str(tmp_path / "train")
    ckpt_lib.Checkpointer(train_dir, hps=dec_hps).save(state)

    def source():
        for i in range(2):
            yield (f"u{i}", f"w1 w2 w3 article {i}", "<s> w1 w2 . </s>", "r")

    batcher = Batcher("", vocab, dec_hps, single_pass=True,
                      decode_batch_mode="distinct", example_source=source)
    d = dec_lib.BeamSearchDecoder(dec_hps, vocab, batcher,
                                  train_dir=train_dir,
                                  decode_root=str(tmp_path / "dec"),
                                  max_ckpt_retries=0)
    rows = []
    d.decode(result_sink=lambda r: rows.append(r.as_row()), log_results=False)
    assert len(rows) == 2
    for uuid, art, summary, ref in rows:
        assert isinstance(summary, str)
