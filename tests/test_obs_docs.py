"""Doc-drift gate (ISSUE 9 satellite): OBSERVABILITY.md's metric
inventory is load-bearing documentation — this test greps the
instrumented call sites and fails when the two drift, in either
direction:

  * a metric emitted in code but absent from the inventory table
    (undocumented telemetry), or
  * an inventory row naming a metric no code emits (stale row).

Literal names only: dynamically-scoped families (f-string names like
``resilience/<name>/retries_total``) are covered by the inventory's
``resilience/*`` wildcard row and excluded below.
"""

import pathlib
import re

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "textsummarization_on_flink_tpu"
DOC = REPO / "OBSERVABILITY.md"

#: prefixes the inventory documents as a wildcard family rather than
#: row-per-metric (the resilience/* row points at RESILIENCE.md)
WILDCARD_PREFIXES = ("resilience/",)

#: a metric name as this repo spells them: <layer>/<name>
NAME_RE = re.compile(r"^[a-z]+/[A-Za-z0-9_./]+$")

#: literal first-argument of a counter/gauge/histogram call (f-strings
#: and computed names never match — by design, see module docstring)
EMIT_RE = re.compile(r'(?:counter|gauge|histogram)\(\s*"([^"{}]+)"')


def _package_sources():
    return [p for p in PKG.rglob("*.py") if "__pycache__" not in p.parts]


def emitted_metric_names():
    names = set()
    for path in _package_sources():
        for m in EMIT_RE.finditer(path.read_text(encoding="utf-8")):
            name = m.group(1)
            if NAME_RE.match(name):
                names.add(name)
    assert len(names) > 50, "emit-site scan looks broken"
    return names


def inventory_table_names():
    """Backticked metric names from the doc's inventory table rows
    (lines between the 'Current inventory:' marker and the next ##
    heading)."""
    lines = DOC.read_text(encoding="utf-8").splitlines()
    start = next(i for i, ln in enumerate(lines)
                 if "Current inventory" in ln)
    names = set()
    for ln in lines[start:]:
        if ln.startswith("## "):
            break
        if not ln.lstrip().startswith("|"):
            continue
        for tok in re.findall(r"`([^`]+)`", ln):
            if NAME_RE.match(tok) and "*" not in tok:
                names.add(tok)
    assert len(names) > 40, "inventory-table scan looks broken"
    return names


def test_every_emitted_metric_is_documented():
    doc_names = inventory_table_names()
    undocumented = sorted(
        n for n in emitted_metric_names()
        if n not in doc_names
        and not any(n.startswith(p) for p in WILDCARD_PREFIXES))
    assert not undocumented, (
        f"metrics emitted in code but missing from OBSERVABILITY.md's "
        f"inventory table: {undocumented} — add a row (or a wildcard "
        f"family entry) for each")


def test_no_stale_inventory_rows():
    """Every inventory row's metric must appear as a quoted literal
    somewhere in the package (this catches renamed/deleted metrics whose
    doc row survived)."""
    sources = "\n".join(p.read_text(encoding="utf-8")
                        for p in _package_sources())
    stale = sorted(n for n in inventory_table_names()
                   if f'"{n}"' not in sources)
    assert not stale, (
        f"OBSERVABILITY.md inventory rows with no emitting call site "
        f"left in the package: {stale} — delete or fix the rows")


def test_wildcard_families_really_exist():
    """The wildcard rows must stay honest too: at least one dynamic
    emit site per documented family prefix."""
    sources = "\n".join(p.read_text(encoding="utf-8")
                        for p in _package_sources())
    for prefix in WILDCARD_PREFIXES:
        assert f'f"{prefix}' in sources or f'"{prefix}' in sources, (
            f"no emit sites under the documented wildcard family "
            f"{prefix}*")


@pytest.mark.parametrize("span_name", [
    "serve/dispatch", "decode/batch", "decode/slot_chunk",
    "train/metrics_flush",
])
def test_documented_span_names_exist_in_code(span_name):
    """The doc's span-name list points at real span call sites."""
    doc = DOC.read_text(encoding="utf-8")
    assert f"`{span_name}`" in doc, f"{span_name} missing from doc"
    sources = "\n".join(p.read_text(encoding="utf-8")
                        for p in _package_sources())
    assert f'"{span_name}"' in sources
