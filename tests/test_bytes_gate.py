"""The committed byte-budget regression gate (ISSUE 5; PERF.md 'Byte
diet').

With the TPU tunnel down, byte-cutting claims would otherwise sit
unmeasured like the decode p50 once did.  XLA's cost model is
backend-portable enough to hold the LEVERS accountable on CPU: this
module compiles the REAL train step (grad + clip + Adagrad) at the small
vocab-dominated gate scale pinned in BYTE_BUDGET.json and asserts, in
tier-1, that

  * each config's bytes accessed stays under its committed budget, and
  * each byte-diet lever (--loss_chunk streaming vocab loss,
    --opt_state_dtype=bfloat16, both) still delivers at least its
    committed reduction vs the baseline config.

Absolute bytes depend on fusion decisions, so budgets carry headroom and
the REDUCTION floors are the real claims (see BYTE_BUDGET.json's
_comment for the re-baselining rule).
"""

import json
import os

import pytest

from textsummarization_on_flink_tpu.config import HParams, derive_draft_hps
from __graft_entry__ import (
    _analytic_step_flops,
    decode_resident_bytes,
    decode_state_bytes,
    decode_step_cost,
    decode_step_flops,
    prefill_cost,
    train_step_comms,
    train_step_cost,
)

BUDGET_PATH = os.path.join(os.path.dirname(__file__), "..",
                           "BYTE_BUDGET.json")


def _cost_bytes(hps: HParams):
    """(bytes accessed, peak temp bytes | None) of the compiled step —
    through the ONE shared compile-and-read helper, so the gate measures
    exactly what BENCH_MODE=bytes and the roofline report."""
    cost = train_step_cost(hps)
    return cost["bytes"], cost["temp_bytes"]


@pytest.fixture(scope="module")
def budget():
    with open(BUDGET_PATH) as f:
        return json.load(f)


@pytest.fixture(scope="module")
def measured(budget):
    """Compile each budgeted config once; ~3-7s per program on CPU (and
    the persistent compile cache makes suite re-runs near-free)."""
    chunk = int(budget["loss_chunk"])
    pg = HParams(**budget["gate_scale"]["pointer_generator"])
    tf = HParams(**budget["gate_scale"]["transformer"])
    configs = {
        "pg_base": pg,
        "pg_losschunk": pg.replace(loss_chunk=chunk),
        "pg_optbf16": pg.replace(opt_state_dtype="bfloat16"),
        "pg_bytediet": pg.replace(loss_chunk=chunk,
                                  opt_state_dtype="bfloat16"),
        "transformer_base": tf,
        "transformer_losschunk": tf.replace(loss_chunk=chunk),
    }
    assert set(configs) == set(budget["budgets"]), (
        "BYTE_BUDGET.json budgets and the gate's config map must cover "
        "the same keys")
    return {name: dict(zip(("bytes", "temp"), _cost_bytes(hps)))
            for name, hps in configs.items()}


_BASE_OF = {
    "pg_losschunk": "pg_base",
    "pg_optbf16": "pg_base",
    "pg_bytediet": "pg_base",
    "transformer_losschunk": "transformer_base",
}


def test_bytes_within_committed_budgets(budget, measured):
    over = {
        name: (c["bytes"], budget["budgets"][name]["max_bytes"])
        for name, c in measured.items()
        if c["bytes"] > budget["budgets"][name]["max_bytes"]
    }
    assert not over, (
        f"bytes-accessed regression past the committed budget: {over} "
        f"(see BYTE_BUDGET.json _comment for the re-baselining rule)")


@pytest.mark.parametrize("lever", sorted(_BASE_OF))
def test_lever_reduction_floors_hold(budget, measured, lever):
    floor = budget["budgets"][lever]["min_reduction_vs_base"]
    base = measured[_BASE_OF[lever]]["bytes"]
    reduction = 1.0 - measured[lever]["bytes"] / base
    assert reduction >= floor, (
        f"{lever}: byte reduction vs {_BASE_OF[lever]} fell to "
        f"{reduction:.1%} (committed floor {floor:.1%}) — the lever "
        f"stopped cutting bytes")


@pytest.mark.parametrize("lever", sorted(
    k for k in _BASE_OF if k.endswith("losschunk")))
def test_peak_temp_floors_hold(budget, measured, lever):
    """PEAK TEMP memory (compiled.memory_analysis()) is fusion- and
    loop-counting-independent: the streaming loss must shrink the live
    set by at least the committed fraction — the direct evidence that
    the [T_dec, B, V] scores value + autodiff residual no longer exist."""
    floor = budget["budgets"][lever]["min_temp_reduction_vs_base"]
    base = measured[_BASE_OF[lever]]["temp"]
    temp = measured[lever]["temp"]
    if base is None or temp is None:
        pytest.skip("backend provides no compiled memory stats")
    reduction = 1.0 - temp / base
    assert reduction >= floor, (
        f"{lever}: peak-temp reduction vs {_BASE_OF[lever]} fell to "
        f"{reduction:.1%} (committed floor {floor:.1%}) — the scores "
        f"residual is materializing again")


# --------------------------------------------------------------------------
# Decode byte diet gate (ISSUE 7; PERF.md "Decode byte diet")
# --------------------------------------------------------------------------
#
# Same contract as the train gate, for the compiled beam SEARCH: the
# committed `decode` section pins bytes-per-emitted-token and peak-temp
# budgets per family and loop kind (plus the step_slots_jit slot kernel)
# against the PRE-PR materialized-history baseline measured before the
# backpointer restructure landed.  A regression that reintroduces
# per-step history gathers fails tier-1 on CPU, hardware or no hardware.

_DECODE_KINDS = ("while", "scan", "chunked", "slot")


def _decode_hps(budget, family: str) -> HParams:
    gs = dict(budget["gate_scale"][family])
    gs.update(budget["decode"]["gate_scale_overrides"])
    return HParams(**gs)


@pytest.fixture(scope="module")
def decode_measured(budget):
    """Compile each budgeted decode config once (~2-5s per program on
    CPU; the persistent compile cache makes suite re-runs near-free)."""
    chunk = int(budget["decode"]["chunk"])
    out = {}
    for family in ("pointer_generator", "transformer"):
        hps = _decode_hps(budget, family)
        out[family] = {
            kind: (decode_step_cost(hps, path="slot", chunk=chunk)
                   if kind == "slot"
                   else decode_step_cost(
                       hps, loop=kind,
                       chunk=chunk if kind == "chunked" else None))
            for kind in _DECODE_KINDS
        }
    return out


def test_decode_budget_covers_every_kind(budget):
    dec = budget["decode"]
    for family in ("pointer_generator", "transformer"):
        assert set(dec["budgets"][family]) == set(_DECODE_KINDS)
        assert set(dec["baseline"][family]) == set(_DECODE_KINDS)


@pytest.mark.parametrize("family", ["pointer_generator", "transformer"])
def test_decode_bytes_per_token_within_budgets(budget, decode_measured,
                                               family):
    budgets = budget["decode"]["budgets"][family]
    over = {
        kind: (c["bytes_per_token"], budgets[kind]["max_bytes_per_token"])
        for kind, c in decode_measured[family].items()
        if c["bytes_per_token"] > budgets[kind]["max_bytes_per_token"]
    }
    assert not over, (
        f"{family}: decode bytes-per-token regression past the committed "
        f"budget: {over} (see BYTE_BUDGET.json decode._comment for the "
        f"re-baselining rule)")


@pytest.mark.parametrize("family", ["pointer_generator", "transformer"])
@pytest.mark.parametrize("kind", _DECODE_KINDS)
def test_decode_reduction_floors_hold(budget, decode_measured, family, kind):
    """The backpointer-history claim: per-step search traffic dropped vs
    the committed pre-PR (materialized-history) baseline and stays
    dropped — >=25% bytes/token for every pointer-generator loop kind
    (the ISSUE 7 acceptance floor), transformer floors from
    measurement."""
    floor = budget["decode"]["budgets"][family][kind]["min_reduction_vs_base"]
    base = budget["decode"]["baseline"][family][kind]["bytes_per_token"]
    reduction = 1.0 - decode_measured[family][kind]["bytes_per_token"] / base
    assert reduction >= floor, (
        f"{family}/{kind}: decode bytes-per-token reduction vs the pre-PR "
        f"baseline fell to {reduction:.1%} (committed floor {floor:.1%}) — "
        f"per-hypothesis history traffic is back")


@pytest.mark.parametrize("family", ["pointer_generator", "transformer"])
@pytest.mark.parametrize("kind", _DECODE_KINDS)
def test_decode_peak_temp_floors_hold(budget, decode_measured, family, kind):
    """Peak live-temp is the fusion- and loop-counting-independent
    evidence the [K, T, T_enc] trajectory buffers (live + result pool +
    candidate intermediates) no longer exist as materialized state."""
    floor = budget["decode"]["budgets"][family][kind][
        "min_temp_reduction_vs_base"]
    base = budget["decode"]["baseline"][family][kind]["temp_bytes"]
    temp = decode_measured[family][kind]["temp_bytes"]
    if temp is None:
        pytest.skip("backend provides no compiled memory stats")
    reduction = 1.0 - temp / base
    assert reduction >= floor, (
        f"{family}/{kind}: decode peak-temp reduction vs the pre-PR "
        f"baseline fell to {reduction:.1%} (committed floor {floor:.1%}) — "
        f"the trajectory buffers are materializing again")


# --------------------------------------------------------------------------
# Prefill/decode disaggregation gate (ISSUE 11)
# --------------------------------------------------------------------------
#
# Two committed claims (BYTE_BUDGET.json decode.length_axis /
# decode.prefill): (1) the length-masked slot chunk's cost scales with
# the longest active resident's TRUE article length (the traced block
# chain — decode_step_cost's enc_len axis prices exactly the blocks the
# served program executes at that length); (2) the prefill stage's
# encoder work scales with the article's BUCKET instead of the full
# max_enc_steps every admission used to pay.

_DISAGG_FAMILIES = ("pointer_generator", "transformer")


@pytest.fixture(scope="module")
def length_axis_measured(budget):
    la = budget["decode"]["length_axis"]
    chunk = int(budget["decode"]["chunk"])
    out = {}
    for family in _DISAGG_FAMILIES:
        hps = _decode_hps(budget, family).replace(
            decode_enc_block=int(la["enc_block"]))
        out[family] = {
            int(L): decode_step_cost(hps, path="slot", chunk=chunk,
                                     enc_len=int(L))
            for L in la["lengths"]
        }
    return out


@pytest.fixture(scope="module")
def prefill_measured(budget):
    pf = budget["decode"]["prefill"]
    out = {}
    for family in _DISAGG_FAMILIES:
        hps = _decode_hps(budget, family)
        out[family] = {int(b): prefill_cost(hps, int(b))
                       for b in pf["buckets"]}
    return out


@pytest.mark.parametrize("family", _DISAGG_FAMILIES)
def test_length_axis_bytes_within_budgets(budget, length_axis_measured,
                                          family):
    budgets = budget["decode"]["length_axis"]["budgets"][family]
    over = {
        L: (c["bytes_per_token"], budgets["max_bytes_per_token"][str(L)])
        for L, c in length_axis_measured[family].items()
        if c["bytes_per_token"] > budgets["max_bytes_per_token"][str(L)]
    }
    assert not over, (
        f"{family}: masked-slot bytes/token past the committed budget at "
        f"{over} (see BYTE_BUDGET.json decode.length_axis._comment)")


@pytest.mark.parametrize("family", _DISAGG_FAMILIES)
def test_length_axis_cost_scales_with_true_length(budget,
                                                  length_axis_measured,
                                                  family):
    """The acceptance claim: a chunk whose longest active resident is a
    T_enc/4 (or T_enc/2) article costs at most the committed ratio of
    the full-length chunk — cost follows TRUE length, not padding."""
    la = budget["decode"]["length_axis"]
    full_len = max(int(L) for L in la["lengths"])
    full = length_axis_measured[family][full_len]["bytes_per_token"]
    for L, ceiling in la["budgets"][family]["max_ratio_vs_full"].items():
        ratio = length_axis_measured[family][int(L)]["bytes_per_token"] \
            / full
        assert ratio <= ceiling, (
            f"{family}: masked-slot bytes/token at length {L} is "
            f"{ratio:.3f}x the full-length chunk (committed max "
            f"{ceiling}) — decode cost is following padding again")


@pytest.mark.parametrize("family", _DISAGG_FAMILIES)
def test_length_axis_beats_uniform_padding_baseline(budget,
                                                    length_axis_measured,
                                                    family):
    """Reduction floors vs the PRE-CHANGE uniform-padding slot step
    (every resident paid full-width cross-attention regardless of
    article length, measured before disaggregation landed)."""
    la = budget["decode"]["length_axis"]
    uniform = la["uniform_baseline"][family]
    floors = la["budgets"][family]["min_reduction_vs_uniform"]
    for L, floor in floors.items():
        got = length_axis_measured[family][int(L)]["bytes_per_token"]
        reduction = 1.0 - got / uniform
        assert reduction >= floor, (
            f"{family}: masked-slot reduction vs the uniform-padding "
            f"baseline at length {L} fell to {reduction:.1%} (committed "
            f"floor {floor:.1%})")


@pytest.mark.parametrize("family", _DISAGG_FAMILIES)
def test_length_axis_is_monotone(length_axis_measured, family):
    """Longer max-active-resident lengths can only cost more — the
    block chain has no pathological cliffs."""
    costs = [length_axis_measured[family][L]["bytes_per_token"]
             for L in sorted(length_axis_measured[family])]
    assert costs == sorted(costs), costs


@pytest.mark.parametrize("family", _DISAGG_FAMILIES)
def test_prefill_cost_scales_with_bucket(budget, prefill_measured, family):
    """Quarter-bucket prefill under the committed ratios of the
    pre-change full-width pack (encoder at max_enc_steps on EVERY
    admission) — bytes AND flops — plus monotonicity in the bucket."""
    pf = budget["decode"]["prefill"]
    base = pf["uniform_pack_baseline"][family]
    limits = pf["budgets"][family]
    quarter = min(prefill_measured[family])
    got = prefill_measured[family][quarter]
    byte_ratio = got["bytes"] / base["bytes"]
    flops_ratio = got["flops"] / base["flops"]
    assert byte_ratio <= limits["max_bytes_ratio_quarter"], (
        f"{family}: quarter-bucket prefill bytes are {byte_ratio:.3f}x "
        f"the pre-change full-width pack (committed max "
        f"{limits['max_bytes_ratio_quarter']}) — the encoder stage is "
        f"paying padded width again")
    assert flops_ratio <= limits["max_flops_ratio_quarter"], (
        f"{family}: quarter-bucket prefill flops are {flops_ratio:.3f}x "
        f"the pre-change full-width pack (committed max "
        f"{limits['max_flops_ratio_quarter']})")
    buckets = sorted(prefill_measured[family])
    for axis in ("bytes", "flops"):
        vals = [prefill_measured[family][b][axis] for b in buckets]
        assert vals == sorted(vals), (family, axis, vals)


# --------------------------------------------------------------------------
# Paged resident-state gate (ISSUE 20; PERF.md "Paged resident state")
# --------------------------------------------------------------------------
#
# The committed `decode.resident` section pins what one ADMITTED slot
# holds in HBM, dense vs paged, via decode_resident_bytes (eval_shape
# accounting of the REAL init_slots_jit / init_slots_paged_jit states)
# at the decode gate scale: the dense worst-case-provisioned baseline is
# re-measured and pinned, the paged per-slot cost at the bimodal mix
# stays under its ceiling, and the reduction floors — the "HBM holds
# more residents" claim priced per slot — hold.


@pytest.fixture(scope="module")
def resident_measured(budget):
    rs = budget["decode"]["resident"]
    out = {}
    for family in _DISAGG_FAMILIES:
        hps = _decode_hps(budget, family).replace(
            decode_enc_block=int(rs["enc_block"]))
        out[family] = decode_resident_bytes(
            hps, pages=int(rs["arena_pages"]), mix=rs["mix"])
    return out


@pytest.mark.parametrize("family", _DISAGG_FAMILIES)
def test_resident_dense_baseline_pinned(budget, resident_measured, family):
    """The comparison cannot drift silently: the re-measured dense
    per-slot bytes must sit within dense_slack of the committed
    pre-change baseline (eval_shape is deterministic — a move here
    means the dense slot state itself changed, which requires
    re-baselining IN THE SAME COMMIT)."""
    rs = budget["decode"]["resident"]
    committed = rs["baseline"][family]["dense_bytes_per_slot"]
    got = resident_measured[family]["dense_bytes_per_slot"]
    slack = rs["dense_slack"]
    assert abs(got - committed) <= slack * committed, (
        f"{family}: dense resident bytes/slot moved to {got} (committed "
        f"{committed} ± {slack:.0%}) — the dense SlotState changed under "
        f"the paged comparison (see BYTE_BUDGET.json "
        f"decode.resident._comment)")


@pytest.mark.parametrize("family", _DISAGG_FAMILIES)
def test_resident_paged_bytes_within_budget(budget, resident_measured,
                                            family):
    ceiling = budget["decode"]["resident"]["budgets"][family][
        "max_paged_bytes_per_slot"]
    got = resident_measured[family]["paged_bytes_per_slot"]
    assert got <= ceiling, (
        f"{family}: paged resident bytes/slot at the bimodal mix rose to "
        f"{got} (committed ceiling {ceiling}) — the fixed share or the "
        f"page grew (see BYTE_BUDGET.json decode.resident._comment)")


@pytest.mark.parametrize("family", _DISAGG_FAMILIES)
def test_resident_reduction_floor_holds(budget, resident_measured, family):
    """The headline claim per slot: at the bimodal mix, a paged resident
    holds at least the committed fraction less HBM than the dense
    worst-case slot — the capacity the arena converts into extra
    residents (the serving-level half is SERVE_SLO.json 'paged')."""
    floor = budget["decode"]["resident"]["budgets"][family][
        "min_reduction_vs_dense"]
    dense = resident_measured[family]["dense_bytes_per_slot"]
    paged = resident_measured[family]["paged_bytes_per_slot"]
    reduction = 1.0 - paged / dense
    assert reduction >= floor, (
        f"{family}: paged-vs-dense resident reduction fell to "
        f"{reduction:.1%} (committed floor {floor:.1%}) — paging no "
        f"longer buys resident capacity at the bimodal mix")


@pytest.mark.parametrize("family", _DISAGG_FAMILIES)
def test_resident_accounting_is_structural(resident_measured, family):
    """Honesty check on the accounting itself: the pooled leaves of the
    PagedSlotState must price to exactly (arena_pages + 1 scratch) x
    page_bytes — i.e. page_bytes really is the marginal HBM cost of one
    admitted page, not a model."""
    rb = resident_measured[family]
    pools = rb["paged_total_bytes"] \
        - rb["paged_fixed_bytes_per_slot"] * rb["slots"]
    assert pools == (rb["arena_pages"] + 1) * rb["page_bytes"], rb
# --------------------------------------------------------------------------
#
# The committed `spec` section pins the draft tier's per-token cost
# against the full model (FLOPs ratio ceilings from cost_analysis), the
# AAN family's O(1)-in-history resident state, and the honesty of the
# committed acceptance-rate -> expected-speedup curve (recomputed from
# the bandwidth-model formula at the committed reference-scale analytic
# ratio).  See BYTE_BUDGET.json spec._comment for the ceilings' story
# and the stated kill condition.


def _spec_hps(budget, family: str) -> HParams:
    gs = dict(budget["gate_scale"][family])
    gs.update(budget["decode"]["gate_scale_overrides"])
    hps = HParams(**gs).replace(spec_k=int(budget["spec"]["spec_k"]),
                                **budget["spec"]["draft_overrides"])
    hps.validate()
    return hps


@pytest.fixture(scope="module")
def spec_measured(budget):
    """One decode_step_flops call per family (~4 small step compiles
    each; the persistent compile cache makes re-runs near-free)."""
    return {family: decode_step_flops(_spec_hps(budget, family))
            for family in ("pointer_generator", "transformer")}


@pytest.mark.parametrize("family", ["pointer_generator", "transformer"])
def test_spec_draft_flops_ratio_within_ceiling(budget, spec_measured,
                                               family):
    ceiling = budget["spec"]["max_draft_flops_ratio"][family]
    got = spec_measured[family]["draft_full_ratio"]
    assert got <= ceiling, (
        f"{family}: draft/full decode-step FLOPs ratio rose to "
        f"{got:.3f} (committed ceiling {ceiling}) — the draft tier "
        f"stopped being cheap (see BYTE_BUDGET.json spec._comment)")


@pytest.mark.parametrize("family", ["pointer_generator", "transformer"])
def test_spec_draft_state_ratio_within_ceiling(budget, spec_measured,
                                               family):
    ceiling = budget["spec"]["max_draft_state_ratio"][family]
    got = spec_measured[family]["draft_state_ratio"]
    assert got <= ceiling, (
        f"{family}: draft/full resident decode-state ratio rose to "
        f"{got:.4f} (committed ceiling {ceiling}) — the AAN slot-cost "
        f"advantage eroded")


def test_spec_draft_state_is_o1_in_history(budget):
    """THE AAN claim: the draft's resident decode state does not grow
    with max_dec_steps (the transformer's KV cache does — sanity-check
    that the measurement isn't vacuous)."""
    hps = _spec_hps(budget, "transformer")
    draft = derive_draft_hps(hps).replace(beam_size=1, mode="decode")
    short = decode_state_bytes(draft)
    long_ = decode_state_bytes(draft.replace(max_dec_steps=4 * hps.max_dec_steps))
    assert short == long_, (
        f"AAN draft decode state grew with max_dec_steps "
        f"({short} -> {long_} bytes): the O(1)-in-history property is "
        f"gone — a history-sized buffer crept into the adapter state")
    full_short = decode_state_bytes(hps.replace(beam_size=1))
    full_long = decode_state_bytes(
        hps.replace(beam_size=1, max_dec_steps=4 * hps.max_dec_steps))
    assert full_long > full_short  # the contrast that makes O(1) a win


def test_spec_expected_speedup_curve_is_honest(budget):
    """The committed acceptance->speedup curve must equal the model
    formula evaluated at the committed REFERENCE-scale analytic
    draft/full ratio, and that ratio must still be reproduced by the
    analytic step-FLOPs model (no compile — instant).  Ref scale uses
    ``ref_overrides`` (H/2-wide draft, rank-64 factored head — the
    distilled-narrow-draft recipe at H=256), not the gate-scale
    ``draft_overrides``."""
    from textsummarization_on_flink_tpu.decode.speculative import (
        expected_speedup,
    )

    spec = budget["spec"]
    k = int(spec["spec_k"])
    ref = HParams(model_family="transformer",
                  **spec["ref_overrides"])
    got_ratio = (_analytic_step_flops(derive_draft_hps(ref))
                 / _analytic_step_flops(ref))
    want_ratio = spec["ref_analytic_ratio"]["transformer"]
    assert abs(got_ratio - want_ratio) < 0.005, (
        f"reference-scale analytic draft/full ratio drifted to "
        f"{got_ratio:.4f} (committed {want_ratio}) — re-baseline the "
        f"spec section (and PERF.md) or fix the regression")
    for alpha, want in spec["expected_speedup"]["transformer"].items():
        recomputed = expected_speedup(float(alpha), k, want_ratio)
        assert abs(recomputed - want) / want < 0.02, (
            f"committed expected_speedup[{alpha}]={want} no longer "
            f"matches the formula ({recomputed:.4f}) — the curve and "
            f"the model drifted apart")


def test_spec_narrow_draft_meets_issue12_bar(budget):
    """The ISSUE-12 acceptance bar, pinned against the committed
    numbers themselves: the transformer draft/full FLOPs ceiling is at
    most 0.5 (down from the equal-width 0.95), the ref-scale analytic
    ratio sits under it, and the re-pinned curve's FLOPs break-even
    reaches 0.5 acceptance (speedup >= 1 there — the equal-width draft
    managed 0.42)."""
    spec = budget["spec"]
    assert spec["max_draft_flops_ratio"]["transformer"] <= 0.5
    assert spec["ref_analytic_ratio"]["transformer"] <= \
        spec["max_draft_flops_ratio"]["transformer"]
    assert spec["expected_speedup"]["transformer"]["0.5"] >= 1.0


def test_spec_verify_scores_positions_cheaper_than_steps(budget,
                                                         spec_measured):
    """The 'one fat step' claim: the parallel verify's per-position
    FLOPs must not exceed the incremental greedy step's (it amortizes
    the cache scatter and shares one pass)."""
    m = spec_measured["transformer"]
    assert m["verify_flops_per_position"] is not None
    assert m["verify_flops_per_position"] <= m["tiers"]["greedy"]["flops"], (
        f"parallel verify costs {m['verify_flops_per_position']:.0f} "
        f"FLOPs/position vs {m['tiers']['greedy']['flops']:.0f} for an "
        f"incremental step — the batched pass lost its advantage")


# --------------------------------------------------------------------------
# One-mesh comms gate (ISSUE 8; PERF.md "One mesh")
# --------------------------------------------------------------------------
#
# The unified sharded step's per-step collective bytes, enforced per mesh
# shape from the committed `comms` section: on wire=bf16 meshes the
# dp-axis all-reduce must move exactly the registry-predicted gradient
# elements (the retired lowp shard_map path's reduction set), priced at
# the registry wire dtype; tp overhead stays under committed ceilings.


@pytest.fixture(scope="module")
def comms_measured(budget):
    """Compile the unified step once per committed mesh shape (~3-6s
    each on CPU; persistent compile cache makes re-runs near-free)."""
    gs = budget["gate_scale"]["pointer_generator"]
    out = {}
    for name, entry in budget["comms"]["meshes"].items():
        hps = HParams(**gs).replace(**entry["overrides"])
        hps.validate()
        out[name] = train_step_comms(hps)
    return out


def test_comms_ref_scale_analytic_pins_lowp_wire_bytes(budget):
    """The headline equality: at reference scale the unified step's dp
    gradient wire carries the retired lowp path's committed 43.0 MB/step
    under the bf16 annotation (86.0 at f32) — registry analytics, no
    compile."""
    from textsummarization_on_flink_tpu.parallel import (
        sharding as sharding_lib,
    )

    ref = budget["comms"]["ref_dp_wire_mb"]
    for wire, want_mb in ref.items():
        hps = HParams(batch_size=16, compute_dtype="bfloat16",
                      grad_allreduce_dtype=wire)
        got = sharding_lib.analytic_comms(hps)["dp_wire_bytes"] / 1e6
        assert round(got, 1) == want_mb, (
            f"analytic ref-scale dp wire bytes at {wire} drifted to "
            f"{got:.2f} MB (committed {want_mb}) — the registry's "
            f"reduction set no longer matches the retired lowp path's")


@pytest.mark.parametrize("mesh_name", ["dp4_bf16", "dp2_tp2_bf16"])
def test_comms_dp_elements_match_registry_exactly(budget, comms_measured,
                                                  mesh_name):
    """Wire-annotated meshes reduce EXACTLY the registry's predicted
    gradient elements over dp (slack covers only the scalar metric
    pmeans): nothing double-reduced, nothing skipped, on pure-dp AND
    dp x tp — the restriction the shard_map step had is gone."""
    slack = budget["comms"]["element_slack"]
    c = comms_measured[mesh_name]
    want = c["analytic"]["dp_grad_elements"]
    got = c["dp"]["elements"]
    assert want <= got <= want + slack, (
        f"{mesh_name}: dp all-reduce moves {got} elements/step, registry "
        f"predicts {want} (+{slack} scalar slack) — the unified step's "
        f"reduction set drifted from the registry spec")


@pytest.mark.parametrize("mesh_name", ["dp4_bf16", "dp2_tp2_bf16",
                                       "dp2_tp2_f32"])
def test_comms_wire_bytes_within_ceilings(budget, comms_measured, mesh_name):
    entry = budget["comms"]["meshes"][mesh_name]
    c = comms_measured[mesh_name]
    assert c["dp_wire_bytes"] <= entry["max_dp_wire_bytes"], (
        f"{mesh_name}: dp wire bytes {c['dp_wire_bytes']} over the "
        f"committed ceiling {entry['max_dp_wire_bytes']}")
    assert c["tp"]["bytes_hlo"] <= entry["max_tp_bytes_hlo"], (
        f"{mesh_name}: tp collective bytes {c['tp']['bytes_hlo']} over "
        f"the committed ceiling {entry['max_tp_bytes_hlo']}")


def test_comms_no_stray_axes(budget, comms_measured):
    """No sp or mixed-group collectives on the committed meshes: every
    collective is attributable to the axis the registry assigns it."""
    for name, c in comms_measured.items():
        assert c["sp"]["instructions"] == 0, (name, c["sp"])
        assert c["mixed"]["instructions"] == 0, (name, c["mixed"])


def test_comms_bf16_wire_halves_dp_bytes(comms_measured):
    """The annotation is the lever: same mesh, same reduction set —
    wire bytes halve from f32 to bf16 (identical element counts would
    be ideal, but the f32 path lets GSPMD pick its own reduction
    placement, so assert the priced ratio on the registry analytics)."""
    b = comms_measured["dp2_tp2_bf16"]["analytic"]
    f = comms_measured["dp2_tp2_f32"]["analytic"]
    assert b["dp_grad_elements"] == f["dp_grad_elements"]
    assert b["dp_wire_bytes"] * 2 == f["dp_wire_bytes"]


def test_base_configs_are_vocab_dominated(budget, measured):
    """The gate scale must keep the scores tensor the dominant byte sink
    (that is what makes it a stand-in for reference scale): the
    streaming-loss saving must exceed one full copy of the f32 scores
    tensor, i.e. the lever removed value+residual traffic, not noise."""
    # T_dec * B * V * 4 bytes: one copy of the f32 scores tensor
    gs = budget["gate_scale"]["pointer_generator"]
    one_scores = (gs["max_dec_steps"] * gs["batch_size"]
                  * gs["vocab_size"] * 4)
    saved = measured["pg_base"]["bytes"] - measured["pg_losschunk"]["bytes"]
    assert saved > one_scores, (
        f"streaming loss saved {saved / 1e6:.1f} MB, less than ONE copy "
        f"of the scores tensor ({one_scores / 1e6:.1f} MB) — the value "
        f"+ residual elimination claim does not hold")
