"""Real 2-process jax.distributed integration test (VERDICT r2 #5).

Every multi-host code path — coordination bring-up, host-local batch
assembly, the collective checkpoint gather, chief-only writing,
barrier(), resume — previously ran only with a monkeypatched
process_count.  Here two actual processes (2 virtual CPU devices each,
4 global) train a (dp=4) mesh together through the public Trainer API;
the reference has no multi-worker test at all (SURVEY §4).
"""

import json
import os
import socket
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "_multiproc_worker.py")
_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _worker_env() -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    # scrub the axon TPU plugin: with the tunnel down its presence on
    # PYTHONPATH can hang jax import even under JAX_PLATFORMS=cpu
    pypath = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
              if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join([_REPO] + pypath)
    env.pop("JAX_PLATFORM_NAME", None)
    return env


@pytest.mark.parametrize("mesh", ["4,1", "2,2", "2,2,bfloat16"])
@pytest.mark.slow
def test_two_process_distributed_train_checkpoint_resume(tmp_path, mesh):
    """mesh='4,1': pure dp, replicated params (easy checkpoint gather).
    mesh='2,2': params tp-shard ACROSS the two hosts, so the collective
    save must gather non-addressable shards — the hard path of
    checkpointer.state_to_arrays.  mesh='2,2,bfloat16': the same shape
    with the registry's bf16 gradient wire annotation (ISSUE 8) — the
    dp x tp composition the retired shard_map builder rejected, now
    running its bf16 dp all-reduce across two real processes."""
    port = _free_port()
    env = _worker_env()
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(port), str(pid), str(tmp_path),
             mesh],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multi-process workers hung (collective desync?); "
                    "partial output:\n" + "\n---\n".join(
                        (p.communicate()[0] or "") for p in procs))
    for p, out in zip(procs, outs):
        assert p.returncode == 0, \
            f"worker rc={p.returncode}; output:\n{out[-4000:]}"

    infos = []
    for pid in (0, 1):
        with open(tmp_path / f"worker{pid}.json") as f:
            infos.append(json.load(f))

    # cluster shape seen from inside
    assert [i["process_index"] for i in infos] == [0, 1]
    assert all(i["process_count"] == 2 for i in infos)
    assert all(i["global_devices"] == 4 for i in infos)
    assert [i["is_chief"] for i in infos] == [True, False]

    # both hosts agree on training progress and the restored checkpoint
    assert all(i["final_step"] == 5 for i in infos), infos
    # latest checkpoint is the final step-5 save (not the step-3 cadence
    # save) — and both hosts restore the same one
    assert all(i["restored_step"] == 5 for i in infos), infos
    assert infos[0]["param_checksum"] == pytest.approx(
        infos[1]["param_checksum"], rel=0, abs=0), \
        "hosts restored different parameters from the shared checkpoint"
    assert all(i["resumed_step"] == 7 for i in infos), infos

    # chief-only writing: ONE events.jsonl record per step, even with
    # two processes sharing the train dir
    train_dir = tmp_path / "mp" / "train"
    with open(train_dir / "events.jsonl") as f:
        steps = [json.loads(line)["step"] for line in f if line.strip()]
    assert len(steps) == len(set(steps)), \
        f"duplicate per-step records — non-chief host wrote too: {steps}"
    # training ran steps 1..5 then resumed 6..7 (post-step numbering)
    assert set(steps) == set(range(1, 8)), steps

    # retention: checkpoints exist, written by the chief, readable
    ckpts = infos[0]["ckpt_files"]
    assert ckpts == infos[1]["ckpt_files"]
    assert "model.ckpt-3.npz" in ckpts and "model.ckpt-5.npz" in ckpts, \
        ckpts  # step-3 cadence save + final save, chief-written
