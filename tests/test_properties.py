"""Property-based tests (hypothesis) for the pure data plumbing: the
tf.Example wire codec, the pointer-generator OOV id machinery, and the
chunk container.  These layers sit on the wire between the pipeline and
the model (SURVEY §2.2/§2.3) — adversarial inputs (unicode, empty
strings, duplicate OOVs, arbitrary byte blobs) must round-trip exactly,
which example-based tests can only spot-check."""

import os

import pytest

# optional dependency: without hypothesis these skip instead of breaking
# collection for the whole suite
pytest.importorskip("hypothesis")

from hypothesis import given, settings  # noqa: E402
from hypothesis import strategies as st  # noqa: E402

from textsummarization_on_flink_tpu.data import TFExample, Vocab
from textsummarization_on_flink_tpu.data.chunks import (
    example_generator,
    write_chunked,
)
from textsummarization_on_flink_tpu.data.oov import (
    abstract2ids,
    article2ids,
    outputids2words,
)

# keep each property fast: the suite runs these on every fast-tier pass
FAST = settings(max_examples=60, deadline=None)

words_in_vocab = ["the", "quick", "brown", "fox", "dog", "."]


def make_vocab():
    return Vocab(words=list(words_in_vocab))


tokens = st.text(
    alphabet=st.characters(blacklist_categories=("Cs",),
                           blacklist_characters=" \t\r\n"),
    min_size=1, max_size=12)


@FAST
@given(st.lists(st.sampled_from(words_in_vocab) | tokens, max_size=40))
def test_article_roundtrip_through_extended_ids(article_words):
    """article2ids -> outputids2words is the identity on the article
    (data.py:144-219 contract): in-vocab words map to their own id,
    every OOV gets a stable extended id, and decoding any produced id
    recovers the exact surface word."""
    vocab = make_vocab()
    ids, oovs = article2ids(article_words, vocab)
    assert len(ids) == len(article_words)
    # extended ids are dense, start at vocab.size(), and deduplicate
    assert sorted(set(i for i in ids if i >= vocab.size())) == \
        list(range(vocab.size(), vocab.size() + len(oovs)))
    assert len(set(oovs)) == len(oovs)
    assert outputids2words(ids, vocab, oovs) == list(article_words)


@FAST
@given(st.lists(st.sampled_from(words_in_vocab) | tokens, max_size=30),
       st.lists(st.sampled_from(words_in_vocab) | tokens, max_size=30))
def test_abstract_ids_copy_only_article_oovs(article_words, abstract_words):
    """abstract2ids maps abstract OOVs to the article's extended id when
    copyable and to UNK otherwise (data.py:171-193)."""
    vocab = make_vocab()
    _, oovs = article2ids(article_words, vocab)
    ids = abstract2ids(abstract_words, vocab, oovs)
    unk = vocab.word2id("[UNK]")
    for w, i in zip(abstract_words, ids):
        if vocab.word2id(w) != unk:
            assert i == vocab.word2id(w)
        elif w in oovs:
            assert i == vocab.size() + oovs.index(w)
            assert outputids2words([i], vocab, oovs) == [w]
        else:
            assert i == unk


feature_values = st.one_of(
    st.lists(st.binary(max_size=40), min_size=1, max_size=4),
    st.lists(st.integers(min_value=-2 ** 63, max_value=2 ** 63 - 1),
             min_size=1, max_size=6),
)


@FAST
@given(st.dictionaries(tokens, feature_values, max_size=5))
def test_tfexample_wire_roundtrip(features):
    """serialize -> parse is the identity for bytes and int64 features
    (the tf.Example wire format the whole data plane rides on)."""
    ex = TFExample()
    for key, values in features.items():
        if values and isinstance(values[0], bytes):
            ex.set_bytes(key, *values)
        else:
            ex.set_ints(key, *values)
    back = TFExample.parse(ex.serialize())
    for key, values in features.items():
        if values and isinstance(values[0], bytes):
            for idx, v in enumerate(values):
                assert back.get_bytes(key, index=idx) == v
        else:
            assert list(back.features[key]) == list(values)


@FAST
@given(st.lists(st.binary(max_size=120), min_size=1, max_size=12),
       st.integers(min_value=1, max_value=5))
def test_chunk_container_roundtrip(payloads, chunk_size):
    """write_chunked -> example_generator returns every example once, in
    order, across arbitrary chunk boundaries (data.py:108-141 reader)."""
    import shutil
    import tempfile

    tmp = tempfile.mkdtemp(prefix="prop_chunks_")
    try:
        exs = [TFExample().set_bytes("article", p) for p in payloads]
        write_chunked(os.path.join(tmp, "t"), exs, chunk_size=chunk_size)
        got = [e.get_bytes("article")
               for e in example_generator(os.path.join(tmp, "t_*.bin"),
                                          single_pass=True)]
        assert got == payloads
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
