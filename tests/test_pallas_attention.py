"""Pallas fused attention: kernel (interpret mode) vs XLA reference,
gradient correctness, and padding behavior."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from textsummarization_on_flink_tpu.ops import attention as attn_ops
from textsummarization_on_flink_tpu.ops import pallas_attention as pa


def make_inputs(B=3, T=37, D=24, seed=0, frac_valid=0.7):
    rng = np.random.RandomState(seed)
    enc_states = rng.randn(B, T, D).astype(np.float32)
    enc_feats = rng.randn(B, T, D).astype(np.float32)
    lens = np.maximum((np.full(B, T) * frac_valid).astype(int), 1)
    mask = (np.arange(T)[None, :] < lens[:, None]).astype(np.float32)
    dec_feats = rng.randn(B, D).astype(np.float32)
    coverage = np.abs(rng.randn(B, T)).astype(np.float32) * mask
    v = rng.randn(D).astype(np.float32)
    w_c = rng.randn(D).astype(np.float32)
    return enc_states, enc_feats, mask, dec_feats, coverage, v, w_c


@pytest.mark.parametrize("use_coverage", [False, True])
def test_kernel_matches_xla_reference(use_coverage):
    args = make_inputs()
    ctx_ref, attn_ref = pa._attention_xla(*args, use_coverage)
    ctx_k, attn_k = pa._attention_pallas(*args, use_coverage, interpret=True)
    np.testing.assert_allclose(np.asarray(ctx_k), np.asarray(ctx_ref),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(attn_k), np.asarray(attn_ref),
                               rtol=1e-5, atol=1e-6)


def test_kernel_attn_is_masked_distribution():
    args = make_inputs(T=50)
    mask = args[2]
    _, attn = pa._attention_pallas(*args, True, interpret=True)
    attn = np.asarray(attn)
    np.testing.assert_allclose(attn.sum(axis=1), 1.0, atol=1e-5)
    assert np.abs(attn * (1 - mask)).max() == 0.0  # nothing on padding


def test_xla_path_matches_legacy_masked_softmax():
    """Energy-level masking == softmax->mask->renorm (the reference
    pipeline, attention_decoder.py:96-101)."""
    args = make_inputs(seed=3)
    enc_states, enc_feats, mask, dec_feats, coverage, v, w_c = args
    feats = enc_feats + dec_feats[:, None, :] \
        + coverage[:, :, None] * w_c[None, None, :]
    e = np.sum(v * np.tanh(feats), axis=-1)
    legacy = np.asarray(attn_ops.masked_softmax(jnp.asarray(e),
                                                jnp.asarray(mask)))
    _, attn = pa._attention_xla(*args, True)
    np.testing.assert_allclose(np.asarray(attn), legacy, rtol=1e-5, atol=1e-6)


def test_fused_attention_gradients_match_reference():
    args = make_inputs(B=2, T=20, D=16, seed=1)
    enc_states, enc_feats, mask, dec_feats, coverage, v, w_c = [
        jnp.asarray(a) for a in args]

    def loss_fused(es, ef, df, cov, vv, wc):
        ctx, attn = pa.fused_attention(es, ef, mask, df, cov, vv, wc, True)
        return jnp.sum(ctx ** 2) + jnp.sum(attn * attn)

    def loss_ref(es, ef, df, cov, vv, wc):
        ctx, attn = pa._attention_xla(es, ef, mask, df, cov, vv, wc, True)
        return jnp.sum(ctx ** 2) + jnp.sum(attn * attn)

    g_fused = jax.grad(loss_fused, argnums=(0, 1, 2, 3, 4, 5))(
        enc_states, enc_feats, dec_feats, coverage, v, w_c)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2, 3, 4, 5))(
        enc_states, enc_feats, dec_feats, coverage, v, w_c)
    for a, b in zip(g_fused, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_attend_still_satisfies_model_contract():
    """attend() through the fused path: context/attn shapes, coverage
    accumulation (attention_decoder.py:113-123)."""
    rng = np.random.RandomState(0)
    B, T, H = 2, 11, 8
    D = 2 * H
    params = {
        "W_h": rng.randn(D, D).astype(np.float32),
        "v": rng.randn(D).astype(np.float32),
        "w_c": rng.randn(D).astype(np.float32),
        "linear_kernel": rng.randn(2 * H, D).astype(np.float32),
        "linear_bias": np.zeros(D, np.float32),
    }
    enc_states = jnp.asarray(rng.randn(B, T, D).astype(np.float32))
    enc_feats = attn_ops.encoder_features(params, enc_states)
    mask = jnp.asarray((np.arange(T)[None, :] < 8).astype(np.float32)
                       .repeat(B, 0).reshape(B, T))
    state = (jnp.asarray(rng.randn(B, H).astype(np.float32)),
             jnp.asarray(rng.randn(B, H).astype(np.float32)))
    cov = jnp.zeros((B, T))
    ctx, attn, new_cov = attn_ops.attend(params, enc_states, enc_feats, mask,
                                         state, cov, True)
    assert ctx.shape == (B, D) and attn.shape == (B, T)
    np.testing.assert_allclose(np.asarray(new_cov),
                               np.asarray(cov + attn), atol=1e-7)
    np.testing.assert_allclose(np.asarray(attn).sum(1), 1.0, atol=1e-5)


@pytest.mark.parametrize("use_coverage", [False, True])
def test_blocked_kernel_matches_xla_reference(use_coverage):
    """Flash-style T-blocked variant (long-context path) vs reference."""
    args = make_inputs(B=2, T=300, D=16, seed=5)
    ctx_ref, attn_ref = pa._attention_xla(*args, use_coverage)
    ctx_k, attn_k = pa._attention_pallas_blocked(
        *args, use_coverage, block_t=128, interpret=True)
    np.testing.assert_allclose(np.asarray(ctx_k), np.asarray(ctx_ref),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(attn_k), np.asarray(attn_ref),
                               rtol=1e-4, atol=1e-6)


def test_blocked_kernel_long_sequence_distribution():
    args = make_inputs(B=1, T=1000, D=8, seed=6, frac_valid=0.9)
    _, attn = pa._attention_pallas_blocked(*args, True, block_t=256,
                                           interpret=True)
    attn = np.asarray(attn)
    np.testing.assert_allclose(attn.sum(axis=1), 1.0, atol=1e-4)
    assert (attn[:, 900:] == 0).all()


def make_inputs_with_empty_row(B=3, T=37, D=24):
    """Row 0 fully masked (an empty streamed article)."""
    args = list(make_inputs(B=B, T=T, D=D))
    mask = args[2].copy()
    mask[0, :] = 0.0
    args[2] = mask
    return tuple(args)


@pytest.mark.parametrize("use_coverage", [False, True])
def test_fully_masked_row_is_finite_xla(use_coverage):
    """ADVICE r1: an all-zero enc_padding_mask must give zero attention
    and a finite context, not 0/0 NaN that trips the watchdog."""
    args = make_inputs_with_empty_row()
    ctx, attn = pa._attention_xla(*args, use_coverage)
    assert np.isfinite(np.asarray(ctx)).all()
    assert np.isfinite(np.asarray(attn)).all()
    np.testing.assert_array_equal(np.asarray(attn)[0], 0.0)
    # other rows unaffected: still proper distributions
    np.testing.assert_allclose(np.asarray(attn)[1:].sum(axis=1), 1.0,
                               atol=1e-5)


def test_fully_masked_row_is_finite_simple_kernel():
    args = make_inputs_with_empty_row()
    ctx, attn = pa._attention_pallas(*args, True, interpret=True)
    assert np.isfinite(np.asarray(ctx)).all()
    assert np.isfinite(np.asarray(attn)).all()
    np.testing.assert_array_equal(np.asarray(attn)[0], 0.0)


def test_fully_masked_row_is_finite_blocked_kernel():
    args = make_inputs_with_empty_row(B=2, T=64, D=24)
    ctx, attn = pa._attention_pallas_blocked(*args, True, block_t=32,
                                             interpret=True)
    assert np.isfinite(np.asarray(ctx)).all()
    assert np.isfinite(np.asarray(attn)).all()


def test_fully_masked_row_is_finite_masked_softmax():
    e = jnp.asarray(np.random.RandomState(0).randn(2, 9).astype(np.float32))
    mask = jnp.asarray(np.stack([np.zeros(9), np.ones(9)]).astype(np.float32))
    out = np.asarray(attn_ops.masked_softmax(e, mask))
    assert np.isfinite(out).all()
    np.testing.assert_array_equal(out[0], 0.0)


@pytest.mark.parametrize("blocked", [False, True])
def test_kernels_accept_bf16_encoder_stream(blocked):
    """compute_dtype=bfloat16 hands the kernels bf16 es/ef; the upcast
    must happen IN VMEM (f32 math inside), matching the XLA formula fed
    the same bf16 inputs."""
    args = list(make_inputs(B=2, T=40, D=16, seed=7))
    args[0] = jnp.asarray(args[0], jnp.bfloat16)  # enc_states
    args[1] = jnp.asarray(args[1], jnp.bfloat16)  # enc_feats
    ctx_ref, attn_ref = pa._attention_xla(*args, True)
    if blocked:
        ctx_k, attn_k = pa._attention_pallas_blocked(*args, True, block_t=16,
                                                     interpret=True)
    else:
        ctx_k, attn_k = pa._attention_pallas(*args, True, interpret=True)
    assert ctx_k.dtype == jnp.float32 and attn_k.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(ctx_k), np.asarray(ctx_ref),
                               rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(np.asarray(attn_k), np.asarray(attn_ref),
                               rtol=1e-2, atol=1e-3)
