"""End-to-end Estimator/Model pipeline on the 8-row synthetic table.

Mirrors the reference integration suite (TensorFlowTest.java):
  * testInferenceAfterTraining (:68-91): fit, then transform, weights
    traveling via the checkpoint dir only;
  * testJsonExportImport (:142-168): model persistence is params-JSON only;
  * testPipeline (:170-202): estimator AND model composed in ONE pipeline —
    the half the reference had to comment out.
"""

import json
import os

import pytest

from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.data.vocab import Vocab
from textsummarization_on_flink_tpu.pipeline import estimator as est_lib
from textsummarization_on_flink_tpu.pipeline import params as P_lib
from textsummarization_on_flink_tpu.pipeline.io import (
    CollectionSink,
    CollectionSource,
    DataTypes,
)

WORDS = ("article reference the a quick brown fox jumped over lazy dog "
         "0 1 2 3 4 5 6 7").split()


def article_rows(n=8):
    # TensorFlowTest.createArticleData (:204-217): uuid-i / "article i." /
    # "" / "reference i."
    return [(f"uuid-{i}", f"article {i} .", "", f"reference {i} .")
            for i in range(n)]


def hyper_params(tmp_path, mode, num_steps=2):
    hps = HParams(mode=mode, num_steps=num_steps, batch_size=4,
                  hidden_dim=8, emb_dim=6, vocab_size=24, max_enc_steps=12,
                  max_dec_steps=6, beam_size=2, min_dec_steps=1,
                  max_oov_buckets=4, log_root=str(tmp_path), exp_name="exp")
    import shlex
    return shlex.split(hps.to_argv())


def make_estimator(tmp_path, vocab):
    e = est_lib.SummarizationEstimator()
    (e.set_train_selected_cols(["uuid", "article", "reference"])
      .set_train_output_cols(["uuid"])
      .set_train_output_types([DataTypes.STRING]))
    e.set_train_hyper_params(hyper_params(tmp_path, "train"))
    (e.set_inference_selected_cols(["uuid", "article", "reference"])
      .set_inference_output_cols(["uuid", "article", "summary", "reference"])
      .set_inference_output_types([DataTypes.STRING] * 4))
    e.set_inference_hyper_params(hyper_params(tmp_path, "decode"))
    e.with_vocab(vocab)
    return e


@pytest.fixture(scope="module")
def vocab():
    return Vocab(words=WORDS)


def test_sent_tokenize_fallback():
    sents = est_lib.sent_tokenize("one sentence . another one ! third ?")
    assert len(sents) == 3


def test_reference_to_abstract_wraps_sentences():
    a = est_lib.reference_to_abstract("hello there . bye now .")
    assert a.count("<s>") == 2 and a.count("</s>") == 2


@pytest.mark.slow
def test_inference_after_training(tmp_path, vocab):
    source = CollectionSource(article_rows())
    model = make_estimator(tmp_path, vocab).fit(source)
    # weights travel via checkpoint dir only (SURVEY §3.1)
    train_dir = os.path.join(str(tmp_path), "exp", "train")
    assert any(f.startswith("model.ckpt") for f in os.listdir(train_dir))

    sink = model.transform(CollectionSource(article_rows()))
    assert isinstance(sink, CollectionSink)
    assert len(sink.rows) == 8
    uuids = sorted(r[0] for r in sink.rows)
    assert uuids == sorted(f"uuid-{i}" for i in range(8))
    for uuid, article, summary, reference in sink.rows:
        assert article.startswith("article")
        assert isinstance(summary, str)
        assert reference.startswith("reference")


@pytest.mark.slow
def test_json_export_import(tmp_path, vocab):
    source = CollectionSource(article_rows())
    model = make_estimator(tmp_path, vocab).fit(source)
    j = model.to_json()
    parsed = json.loads(j)
    assert "inference_selected_cols" in parsed  # config-only JSON
    assert len(j) < 10_000  # config-only: no weight blobs inside
    assert all(isinstance(v, (str, int, float, bool, list, type(None)))
               for v in parsed.values())
    m2 = est_lib.SummarizationModel().load_json(j).with_vocab(vocab)
    sink = m2.transform(CollectionSource(article_rows(3)))
    assert len(sink.rows) == 3


@pytest.mark.slow
def test_pipeline_estimator_and_model_single_job(tmp_path, vocab):
    """Pipeline(estimator) -> fit -> transform in one process — the
    one-TFUtils-call-per-job blocker does not exist here."""
    pipe = est_lib.Pipeline([make_estimator(tmp_path, vocab)])
    fitted = pipe.fit(CollectionSource(article_rows()))
    assert isinstance(fitted.stages[0], est_lib.SummarizationModel)
    sink = fitted.transform(CollectionSource(article_rows(4)))
    assert len(sink.rows) == 4


class SelectColTransformer(est_lib.Model, P_lib.HasTrainSelectedCols):
    """The reference test's column-subset transformer
    (TensorFlowTest.java:268-279: input.select(trainSelectedCols))."""

    def __init__(self):
        P_lib.WithParams.__init__(self)

    def transform(self, source, sink=None):
        sink = sink if sink is not None else CollectionSink()
        cols = self.get_train_selected_cols()
        for row in source.rows():
            sink.write(source.schema.project_row(row, cols))
        sink.close()
        return sink

    def output_schema(self, input_schema):
        return input_schema.select(self.get_train_selected_cols())


class _RecordingEstimator(est_lib.Estimator):
    """Records the exact rows/schema fit() received, returning a no-op
    Model — pins Pipeline.fit's stage-chaining contract in isolation."""

    def __init__(self):
        P_lib.WithParams.__init__(self)
        self.seen_rows = None
        self.seen_schema = None

    def fit(self, source):
        self.seen_rows = list(source.rows())
        self.seen_schema = source.schema

        class _Identity(est_lib.Model):
            def __init__(self):
                P_lib.WithParams.__init__(self)

            def transform(self, source, sink=None):
                sink = sink if sink is not None else CollectionSink()
                for row in source.rows():
                    sink.write(row)
                sink.close()
                return sink

        return _Identity()


def test_pipeline_fit_chains_stage_outputs():
    """flink-ml Pipeline.fit semantics: an Estimator is fitted on the
    table as transformed by every preceding stage, not the raw source
    (round-4 review: transformers used to pass sources through
    unchanged, so SelectColTransformer->estimator fitted on the
    UNtransformed table)."""
    sel = SelectColTransformer().set_train_selected_cols(
        ["uuid", "article", "reference"])
    rec = _RecordingEstimator()
    fitted = est_lib.Pipeline([sel, rec]).fit(
        CollectionSource(article_rows(3)))
    # the estimator saw 3-col rows (summary dropped) + narrowed schema
    assert rec.seen_rows == [(f"uuid-{i}", f"article {i} .",
                              f"reference {i} .") for i in range(3)]
    assert rec.seen_schema.names == ["uuid", "article", "reference"]
    # the fitted pipeline keeps the transformer + the fitted model, in order
    assert fitted.stages[0] is sel
    assert isinstance(fitted.stages[1], est_lib.Model)
    assert not isinstance(fitted.stages[1], est_lib.Estimator)


def test_pipeline_fit_is_lazy_without_downstream_estimator():
    """A Model AFTER the last Estimator is never transform()ed during
    fit — the common estimator->model pipeline must not beam-decode its
    own training set (flink-ml materializes stage outputs only as later
    stages consume them)."""

    class _Exploding(est_lib.Model):
        def __init__(self):
            P_lib.WithParams.__init__(self)

        def transform(self, source, sink=None):
            raise AssertionError("fit must not transform trailing stages")

    rec = _RecordingEstimator()
    fitted = est_lib.Pipeline([rec, _Exploding()]).fit(
        CollectionSource(article_rows(2)))
    assert len(rec.seen_rows) == 2  # 4-col raw rows, no prior stages
    assert len(fitted.stages) == 2


@pytest.mark.slow
def test_pipeline_select_col_then_estimator_end_to_end(tmp_path, vocab):
    """The exact shape TensorFlowTest.testPipeline (:170-202) wanted and
    couldn't run: Pipeline(SelectColTransformer -> estimator), fit on the
    8-row table, then transform the fitted pipeline — one process."""
    sel = SelectColTransformer().set_train_selected_cols(
        ["uuid", "article", "reference"])
    pipe = est_lib.Pipeline([sel, make_estimator(tmp_path, vocab)])
    fitted = pipe.fit(CollectionSource(article_rows()))
    assert isinstance(fitted.stages[1], est_lib.SummarizationModel)
    sink = fitted.transform(CollectionSource(article_rows(4)))
    assert len(sink.rows) == 4
    for uuid, article, summary, reference in sink.rows:
        assert uuid.startswith("uuid-")
        assert article.startswith("article")
        assert isinstance(summary, str)
        assert reference.startswith("reference")


@pytest.mark.slow
def test_training_resumes_from_checkpoint(tmp_path, vocab):
    est = make_estimator(tmp_path, vocab)
    est.fit(CollectionSource(article_rows()))
    # second fit resumes from the saved step (num_steps=2 already reached:
    # trains 2 more to step 4)
    est.set_train_hyper_params(hyper_params(tmp_path, "train", num_steps=4))
    est.fit(CollectionSource(article_rows()))
    from textsummarization_on_flink_tpu.checkpoint import checkpointer as C
    st = C.Checkpointer(os.path.join(str(tmp_path), "exp", "train")).restore()
    assert int(st.step) == 4


def test_failed_source_fails_fit(tmp_path, vocab):
    from textsummarization_on_flink_tpu.pipeline.io import Source

    class ExplodingSource(Source):
        schema = CollectionSource(article_rows()).schema

        def rows(self):
            yield from article_rows(4)
            raise ConnectionError("stream dropped")

    est = make_estimator(tmp_path, vocab)
    with pytest.raises(RuntimeError, match="source stream failed"):
        est.fit(ExplodingSource())


def test_fit_cancels_unconsumed_stream(tmp_path, vocab):
    """num_steps stops training before the source drains: fit must return
    promptly, cancel the feeder thread, and not raise."""
    big = article_rows(200)
    est = make_estimator(tmp_path, vocab)
    model = est.fit(CollectionSource(big))
    assert isinstance(model, est_lib.SummarizationModel)
    import threading as _t
    feeders = [t for t in _t.enumerate() if "Thread-" in t.name and t.is_alive()
               and getattr(t, "_target", None) is not None
               and "_BridgeFeeder" in str(getattr(t, "_target", ""))]
    assert not feeders  # no leaked feeder threads
