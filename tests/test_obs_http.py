"""obs/http.py: the live exposition plane (ISSUE 9 tentpole, piece 2).

Acceptance-critical properties: a /metrics scrape byte-parses as the
SAME counter set as ``registry.render_text()``, and /healthz flips to
degraded when a registered heartbeat goes stale — simulated through the
injectable monotonic clock, never with sleeps.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.obs import http as obs_http
from textsummarization_on_flink_tpu.obs import spans as spans_lib
from textsummarization_on_flink_tpu.obs.registry import Registry
from textsummarization_on_flink_tpu.resilience.policy import CircuitBreaker


def _get(port, route):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{route}", timeout=10) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _parse_metrics(text):
    """Prometheus text -> {name: value} for counters/gauges plus the
    set of TYPE declarations (histogram series collapse to their name)."""
    values, types = {}, {}
    for line in text.splitlines():
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split()
            types[name] = kind
        elif line and not line.startswith("#"):
            name, _, val = line.rpartition(" ")
            base = name.split("{", 1)[0]
            values[base] = float(val)
    return types, values


@pytest.fixture
def served():
    reg = Registry()
    srv = obs_http.ObsHttpServer(reg, port=0).start()
    try:
        yield reg, srv
    finally:
        srv.close()


class TestEndpoints:
    def test_metrics_scrape_matches_render_text(self, served):
        reg, srv = served
        reg.counter("serve/completed_total").inc(5)
        reg.gauge("serve/queue_depth").set(2)
        reg.histogram("serve/e2e_latency_seconds",
                      buckets=(0.1, 1.0)).observe(0.05)
        status, body = _get(srv.port, "/metrics")
        assert status == 200
        rendered = reg.render_text()
        assert body.decode("utf-8") == rendered
        # and the scrape byte-parses as the same counter set
        t_scrape, v_scrape = _parse_metrics(body.decode("utf-8"))
        t_local, v_local = _parse_metrics(rendered)
        assert t_scrape == t_local and v_scrape == v_local
        assert t_scrape["serve_completed_total"] == "counter"
        assert v_scrape["serve_completed_total"] == 5.0

    def test_snapshot_json(self, served):
        reg, srv = served
        reg.counter("train/steps_total").inc(7)
        status, body = _get(srv.port, "/snapshot")
        assert status == 200
        snap = json.loads(body)
        assert snap["train/steps_total"]["value"] == 7.0

    def test_spans_json_with_trace_ids(self, served):
        reg, srv = served
        ctx = spans_lib.TraceContext.new()
        with spans_lib.span(reg, "serve/dispatch", parent=ctx, fill=1):
            pass
        status, body = _get(srv.port, "/spans")
        assert status == 200
        (rec,) = json.loads(body)
        assert rec["name"] == "serve/dispatch"
        assert rec["trace_id"] == ctx.trace_id

    def test_spans_n_limits(self, served):
        reg, srv = served
        for i in range(5):
            with spans_lib.span(reg, f"s{i}"):
                pass
        status, body = _get(srv.port, "/spans?n=2")
        assert [r["name"] for r in json.loads(body)] == ["s3", "s4"]

    def test_unknown_route_404(self, served):
        _, srv = served
        status, body = _get(srv.port, "/nope")
        assert status == 404
        assert "/metrics" in json.loads(body)["routes"]

    def test_concurrent_scrapes_consistent(self, served):
        """A loaded plane: writers mutating metrics while scrapers pull
        — every response parses; no torn exposition."""
        reg, srv = served
        stop = threading.Event()

        def writer():
            c = reg.counter("serve/completed_total")
            while not stop.is_set():
                c.inc()

        bodies = []

        def scraper():
            for _ in range(10):
                status, body = _get(srv.port, "/metrics")
                assert status == 200
                bodies.append(body)

        w = threading.Thread(target=writer)
        scrapers = [threading.Thread(target=scraper) for _ in range(3)]
        w.start()
        for t in scrapers:
            t.start()
        for t in scrapers:
            t.join()
        stop.set()
        w.join()
        for body in bodies:
            types, values = _parse_metrics(body.decode("utf-8"))
            assert types.get("serve_completed_total") == "counter"
            assert values["serve_completed_total"] >= 0


class TestHealthz:
    def test_ok_then_degraded_on_stale_heartbeat_no_sleeps(self, served):
        reg, srv = served
        clock = [100.0]
        board = obs_http.board_for(reg)
        board._clock = lambda: clock[0]
        board.beat("serve/dispatch", period=1.0)
        status, body = _get(srv.port, "/healthz")
        payload = json.loads(body)
        assert status == 200 and payload["status"] == "ok"
        assert payload["components"]["serve/dispatch"]["ok"]
        # time passes (simulated): 3x the period + epsilon -> stale
        clock[0] += 3.5
        status, body = _get(srv.port, "/healthz")
        payload = json.loads(body)
        assert status == 503 and payload["status"] == "degraded"
        assert not payload["components"]["serve/dispatch"]["ok"]
        assert payload["components"]["serve/dispatch"]["age_seconds"] == 3.5
        # a fresh beat recovers it
        board.beat("serve/dispatch", period=1.0)
        status, body = _get(srv.port, "/healthz")
        assert status == 200

    def test_healthz_carries_serve_routing_inputs(self, served):
        """The ISSUE-13 satellite: the FleetRouter's routing inputs —
        queue depth, free slots, effective serve_mode — ride the
        /healthz JSON body (scrapeable, not in-process only), while the
        503 policy stays exactly heartbeat-staleness."""
        reg, srv = served
        status, body = _get(srv.port, "/healthz")
        assert "serve" not in json.loads(body)  # absent until published
        reg.gauge("serve/queue_depth").set(3)
        reg.gauge("serve/slots_free").set(2)
        obs_http.set_health_info(reg, serve_mode="continuous")
        status, body = _get(srv.port, "/healthz")
        payload = json.loads(body)
        assert status == 200 and payload["status"] == "ok"
        assert payload["serve"] == {"queue_depth": 3, "slots_free": 2,
                                    "serve_mode": "continuous"}
        # routing inputs are informational: a deep queue never 503s
        reg.gauge("serve/queue_depth").set(10_000)
        status, _ = _get(srv.port, "/healthz")
        assert status == 200

    def test_serving_server_publishes_healthz_serve_section(self):
        """End to end through a real continuous ServingServer: the
        health payload carries the gauges the server maintains plus its
        effective mode."""
        from textsummarization_on_flink_tpu.config import HParams
        from textsummarization_on_flink_tpu.data.vocab import Vocab
        from textsummarization_on_flink_tpu.serve.server import ServingServer
        from tests.test_serve import StubEngine

        reg = Registry()
        vocab = Vocab(words=["a", "b", "."])
        hps = HParams(mode="decode", batch_size=2, vocab_size=vocab.size(),
                      max_enc_steps=8, max_dec_steps=4, beam_size=2,
                      min_dec_steps=1, max_oov_buckets=4,
                      serve_mode="continuous", serve_slots=2,
                      serve_refill_chunk=1, serve_max_queue=8)

        class _NullDecoder:
            def maybe_reload_checkpoint(self, last):
                return last

        ServingServer(hps, vocab, decoder=_NullDecoder(),
                      engine=StubEngine(slots=2), registry=reg)
        payload = obs_http.health(reg)
        assert payload["serve"]["serve_mode"] == "continuous"
        assert payload["serve"]["slots_free"] == 2
        assert payload["serve"]["queue_depth"] == 0

    def test_open_breaker_reported_but_informational(self, served):
        """An OPEN breaker is visible on /healthz but must NOT 503 it:
        503-ing an open ADMISSION breaker drains the instance, which
        starves the half-open probe, which pins the breaker open — a
        self-sustaining trap.  Degradation is heartbeat-staleness only
        (the ISSUE-9 contract)."""
        reg, srv = served
        br = CircuitBreaker(threshold=1, reset_secs=1e9,
                            name="serve.admission", registry=reg)
        status, body = _get(srv.port, "/healthz")
        assert json.loads(body)["breakers"] == {"serve.admission": "closed"}
        br.record_failure()
        status, body = _get(srv.port, "/healthz")
        payload = json.loads(body)
        assert status == 200 and payload["status"] == "ok"
        assert payload["breakers"]["serve.admission"] == "open"

    def test_health_helper_without_server(self):
        reg = Registry()
        obs_http.heartbeat(reg, "train/loop", period=10.0)
        payload = obs_http.health(reg)
        assert payload["status"] == "ok"
        assert "train/loop" in payload["components"]
        # disabled registry: no components, never degraded
        assert obs_http.health(Registry(enabled=False))["status"] == "ok"

    def test_healthz_carries_incarnation_identity(self, served):
        """The ISSUE-17 satellite: /healthz carries pid, process
        start_time, and the stamped replica_id so a process supervisor
        can verify WHICH incarnation answered — a stale portfile
        pointing at a previous (or recycled) pid must not pass the
        readiness handshake (procfleet.ReplicaProcess keys on exactly
        these fields)."""
        reg, srv = served
        _, body = _get(srv.port, "/healthz")
        payload = json.loads(body)
        assert payload["pid"] == os.getpid()
        assert payload["start_time"] == pytest.approx(
            obs_http._PROCESS_START_TIME)
        assert payload["start_time"] <= time.time()
        assert payload["replica_id"] == ""  # unstamped registry
        reg.replica_id = "p7"
        _, body = _get(srv.port, "/healthz")
        assert json.loads(body)["replica_id"] == "p7"


class TestGating:
    def test_resolve_port_precedence(self, monkeypatch):
        from textsummarization_on_flink_tpu.config import HParams

        monkeypatch.delenv("TS_OBS_HTTP", raising=False)
        assert obs_http.resolve_http_port(None) == 0
        assert obs_http.resolve_http_port(HParams()) == 0
        monkeypatch.setenv("TS_OBS_HTTP", "9464")
        assert obs_http.resolve_http_port(HParams()) == 9464
        # explicit HParams port wins over the env
        assert obs_http.resolve_http_port(
            HParams(obs_http_port=9465)) == 9465
        monkeypatch.setenv("TS_OBS_HTTP", "not-a-port")
        assert obs_http.resolve_http_port(None) == 0

    def test_maybe_serve_off_by_default(self, monkeypatch):
        monkeypatch.delenv("TS_OBS_HTTP", raising=False)
        assert obs_http.maybe_serve(Registry()) is None
        assert obs_http.maybe_serve(Registry(enabled=False)) is None

    def test_hparams_validation(self):
        from textsummarization_on_flink_tpu.config import HParams

        with pytest.raises(ValueError, match="obs_http_port"):
            HParams(obs_http_port=70000).validate()
        with pytest.raises(ValueError, match="flight_frames"):
            HParams(flight_frames=-1).validate()
        HParams(obs_http_port=9464, flight_frames=16).validate()

    def test_facade_serve_http(self):
        reg = Registry()
        with obs.use_registry(reg):
            srv = obs.serve_http(0)
        try:
            status, _ = _get(srv.port, "/metrics")
            assert status == 200
        finally:
            srv.close()


class TestIssue15Endpoints:
    """/alerts, /exemplars, /fleet/* and the /snapshot health_info ride
    (ISSUE 15)."""

    def test_snapshot_carries_health_info(self, served):
        reg, srv = served
        reg.counter("serve/completed_total").inc()
        obs_http.set_health_info(reg, serve_mode="continuous",
                                 params_fingerprint="abc123")
        status, body = _get(srv.port, "/snapshot")
        snap = json.loads(body)
        assert snap["health_info"] == {"serve_mode": "continuous",
                                       "params_fingerprint": "abc123"}
        # metrics still ride alongside: one scrape, both facts
        assert snap["serve/completed_total"]["value"] == 1.0

    def test_snapshot_without_health_info_unchanged(self, served):
        reg, srv = served
        reg.counter("t/c").inc()
        _, body = _get(srv.port, "/snapshot")
        assert "health_info" not in json.loads(body)

    def test_alerts_quiet_ok_without_engine(self, served):
        _, srv = served
        status, body = _get(srv.port, "/alerts")
        assert status == 200
        payload = json.loads(body)
        assert payload["status"] == "ok" and not payload["installed"]

    def test_alerts_reports_installed_engine(self, served):
        from textsummarization_on_flink_tpu.obs import slo as slo_lib

        reg, srv = served
        pol = {"windows": {"fast_secs": 10.0, "slow_secs": 100.0},
               "thresholds": {"warn": 2.0, "page": 10.0},
               "objectives": [{"name": "lat", "signal": "latency",
                               "by": "tenant",
                               "latency_threshold_ms": 1000.0,
                               "target": 0.9}]}
        eng = slo_lib.install_slo_engine(reg, policy=pol)
        eng.record("a", "beam", 5.0)  # every request bad -> page
        eng.evaluate()  # the tick side computes; /alerts only reads
        status, body = _get(srv.port, "/alerts")
        payload = json.loads(body)
        assert payload["installed"] and payload["status"] == "page"
        (row,) = payload["objectives"]
        assert row["key"] == "a" and row["state"] == "page"

    def test_exemplars_endpoint(self, served):
        reg, srv = served
        reg.histogram("serve/e2e_latency_seconds",
                      buckets=(1.0,)).observe(0.5, trace_id="tr-1")
        status, body = _get(srv.port, "/exemplars")
        assert status == 200
        (row,) = json.loads(body)
        assert row == {"metric": "serve/e2e_latency_seconds", "le": "1",
                       "trace_id": "tr-1", "value": 0.5}

    def test_fleet_routes_404_without_sources(self, served):
        _, srv = served
        status, body = _get(srv.port, "/fleet/metrics")
        assert status == 404
        assert "fleet" in json.loads(body)["error"]

    def test_fleet_metrics_and_snapshot(self, served):
        reg, srv = served
        r0, r1 = Registry(), Registry()
        r0.counter("serve/completed_total").inc(3)
        r1.counter("serve/completed_total").inc(4)
        r0.gauge("serve/queue_depth").set(2)
        reg.fleet_sources = lambda: {"r0": r0, "r1": r1}
        status, body = _get(srv.port, "/fleet/metrics")
        assert status == 200
        text = body.decode("utf-8")
        assert "serve_completed_total 7" in text
        assert 'serve_queue_depth{replica="r0"} 2' in text
        status, body = _get(srv.port, "/fleet/snapshot")
        snap = json.loads(body)
        assert snap["replicas"] == ["r0", "r1"]
        assert snap["metrics"]["serve/completed_total"]["value"] == 7.0

    def test_metrics_exemplars_only_under_openmetrics_accept(self, served):
        """Exemplar annotations are OpenMetrics syntax: a plain
        Prometheus-0.0.4 scrape must not see them (a 0.0.4 parser
        rejects the trailing `# {...}` and loses the whole scrape);
        a negotiated scrape gets the annotated body verbatim."""
        reg, srv = served
        reg.histogram("t/h", buckets=(1.0,)).observe(0.5, trace_id="tr-9")
        status, plain = _get(srv.port, "/metrics")
        assert status == 200 and b"trace_id" not in plain
        assert plain.decode("utf-8") == reg.render_text(exemplars=False)
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/metrics",
            headers={"Accept": "application/openmetrics-text"})
        with urllib.request.urlopen(req, timeout=10) as r:
            assert "openmetrics-text" in r.headers["Content-Type"]
            body = r.read()
        assert b'# {trace_id="tr-9"} 0.5' in body
        assert body.decode("utf-8") == reg.render_text(openmetrics=True)
