"""Model-core numeric tests.

The key test reimplements the reference's math (model.py /
attention_decoder.py formulas) as a slow, explicit numpy loop and checks
the scan-based JAX model against it on tiny dimensions — an independent
derivation, not a copy of the implementation under test.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.data import Vocab
from textsummarization_on_flink_tpu.data.batching import Batch, SummaryExample
from textsummarization_on_flink_tpu.models import pointer_generator as pg
from textsummarization_on_flink_tpu.ops import losses as loss_ops
from textsummarization_on_flink_tpu.ops import lstm as lstm_ops


def sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def hps_tiny(**kw):
    base = dict(batch_size=2, max_enc_steps=5, max_dec_steps=4, min_dec_steps=1,
                hidden_dim=3, emb_dim=2, max_oov_buckets=3, vocab_size=0,
                beam_size=2, coverage=True)
    base.update(kw)
    return HParams(**base)


def make_vocab():
    return Vocab(words=["a", "b", "c", "d", "e", "f"])  # size 10


def make_batch(hps, vocab):
    exs = [
        SummaryExample.build("a b zulu c", ["b zulu ."], vocab, hps),
        SummaryExample.build("d e f a b", ["e f a b c d"], vocab, hps),
    ]
    return Batch(exs, hps, vocab)


def np_lstm_step(kernel, bias, x, c, h):
    z = np.concatenate([x, h], -1) @ kernel + bias
    i, j, f, o = np.split(z, 4, axis=-1)
    nc = c * sigmoid(f + 1.0) + sigmoid(i) * np.tanh(j)
    nh = np.tanh(nc) * sigmoid(o)
    return nc, nh


def np_forward(params, hps, arrays, vsize):
    """Slow numpy re-derivation of the full train forward pass."""
    p = jax.tree_util.tree_map(np.asarray, params)
    enc_batch = arrays["enc_batch"]
    enc_mask = arrays["enc_padding_mask"]
    enc_lens = arrays["enc_lens"]
    B, T_enc = enc_batch.shape
    H, E = hps.hidden_dim, hps.emb_dim

    # encoder: manual fw/bw loops with dynamic_rnn length semantics
    emb = p["embedding"][enc_batch]
    fw_out = np.zeros((B, T_enc, H)); bw_out = np.zeros((B, T_enc, H))
    fw_c = np.zeros((B, H)); fw_h = np.zeros((B, H))
    for t in range(T_enc):
        nc, nh = np_lstm_step(p["encoder"]["fw"]["kernel"],
                              p["encoder"]["fw"]["bias"], emb[:, t], fw_c, fw_h)
        m = enc_mask[:, t:t + 1]
        fw_c = np.where(m > 0, nc, fw_c); fw_h = np.where(m > 0, nh, fw_h)
        fw_out[:, t] = nh * m
    bw_c = np.zeros((B, H)); bw_h = np.zeros((B, H))
    for b in range(B):
        c = np.zeros(H); h = np.zeros(H)
        L = int(enc_lens[b])
        for t in range(L - 1, -1, -1):
            nc, nh = np_lstm_step(p["encoder"]["bw"]["kernel"],
                                  p["encoder"]["bw"]["bias"],
                                  emb[b, t][None], c[None], h[None])
            c, h = nc[0], nh[0]
            bw_out[b, t] = h
        bw_c[b], bw_h[b] = c, h
    enc_states = np.concatenate([fw_out, bw_out], -1)  # [B, T, 2H]

    r = p["reduce"]
    dec_c = np.maximum(np.concatenate([fw_c, bw_c], -1) @ r["w_reduce_c"]
                       + r["bias_reduce_c"], 0)
    dec_h = np.maximum(np.concatenate([fw_h, bw_h], -1) @ r["w_reduce_h"]
                       + r["bias_reduce_h"], 0)

    a = p["decoder"]["attention"]
    enc_feats = enc_states @ a["W_h"]

    def attend(c, h, cov):
        dec_feats = np.concatenate([c, h], -1) @ a["linear_kernel"] + a["linear_bias"]
        feats = enc_feats + dec_feats[:, None, :]
        if hps.coverage:
            feats = feats + cov[:, :, None] * a["w_c"][None, None, :]
        e = np.sum(a["v"] * np.tanh(feats), -1)
        ex = np.exp(e - e.max(-1, keepdims=True))
        sm = ex / ex.sum(-1, keepdims=True)
        attn = sm * enc_mask
        attn = attn / attn.sum(-1, keepdims=True)
        ctx = np.einsum("bt,btd->bd", attn, enc_states)
        return ctx, attn

    dp = p["decoder"]
    emb_dec = p["embedding"][arrays["dec_batch"]]
    T_dec = arrays["dec_batch"].shape[1]
    context = np.zeros((B, 2 * H))
    coverage = np.zeros((B, T_enc))
    nlls = np.zeros((B, T_dec)); covlosses = np.zeros((B, T_dec))
    for t in range(T_dec):
        x = np.concatenate([emb_dec[:, t], context], -1) @ \
            dp["input_linear"]["kernel"] + dp["input_linear"]["bias"]
        dec_c, dec_h = np_lstm_step(dp["cell"]["kernel"], dp["cell"]["bias"],
                                    x, dec_c, dec_h)
        context, attn = attend(dec_c, dec_h, coverage)
        covlosses[:, t] = np.sum(np.minimum(attn, coverage), -1)
        if hps.coverage:
            coverage = coverage + attn
        p_gen = sigmoid(np.concatenate([context, dec_c, dec_h, x], -1)
                        @ dp["pgen_linear"]["kernel"]
                        + dp["pgen_linear"]["bias"])[:, 0]
        output = np.concatenate([dec_h, context], -1) @ \
            dp["output_linear"]["kernel"] + dp["output_linear"]["bias"]
        scores = output @ p["output_projection"]["w"] + p["output_projection"]["v"]
        sm = np.exp(scores - scores.max(-1, keepdims=True))
        vocab_dist = sm / sm.sum(-1, keepdims=True)
        # explicit extended-vocab scatter, then gather the gold entry
        ext_V = vsize + hps.max_oov_buckets
        final = np.zeros((B, ext_V))
        final[:, :vsize] = p_gen[:, None] * vocab_dist
        for b in range(B):
            for i in range(T_enc):
                final[b, arrays["enc_batch_extend_vocab"][b, i]] += \
                    (1 - p_gen[b]) * attn[b, i]
        gold = final[np.arange(B), arrays["target_batch"][:, t]]
        nlls[:, t] = -np.log(gold)

    dec_mask = arrays["dec_padding_mask"]
    dec_lens = dec_mask.sum(1)
    loss = np.mean((nlls * dec_mask).sum(1) / dec_lens)
    cov = np.mean((covlosses * dec_mask).sum(1) / dec_lens)
    return loss, cov


class TestForwardParity:
    @pytest.mark.parametrize("coverage", [True, False])
    def test_matches_numpy_rederivation(self, coverage):
        hps = hps_tiny(coverage=coverage)
        vocab = make_vocab()
        params = pg.init_params(hps, vocab.size(), jax.random.PRNGKey(0))
        batch = make_batch(hps, vocab)
        arrays = batch.as_arrays()
        out = pg.forward_train(params, hps, arrays)
        np_loss, np_cov = np_forward(params, hps, arrays, vocab.size())
        np.testing.assert_allclose(float(out.loss), np_loss, rtol=2e-5)
        if coverage:
            np.testing.assert_allclose(
                float(out.coverage_loss), np_cov, rtol=2e-5, atol=1e-7)
            np.testing.assert_allclose(
                float(out.total_loss),
                np_loss + hps.cov_loss_wt * np_cov, rtol=2e-5)
        else:
            assert float(out.coverage_loss) == 0.0

    def test_jit_and_grad(self):
        hps = hps_tiny()
        vocab = make_vocab()
        params = pg.init_params(hps, vocab.size(), jax.random.PRNGKey(0))
        arrays = make_batch(hps, vocab).as_arrays()

        @jax.jit
        def loss_fn(p):
            return pg.forward_train(p, hps, arrays).total_loss

        g = jax.grad(loss_fn)(params)
        flat = jax.tree_util.tree_leaves(g)
        assert all(np.all(np.isfinite(x)) for x in flat)
        # every parameter (incl. w_c with coverage on) receives gradient
        nonzero = [float(np.abs(x).sum()) > 0 for x in flat]
        assert all(nonzero), "some params got exactly-zero gradients"


class TestEncoderSemantics:
    def test_outputs_zero_past_length_and_state_frozen(self):
        hps = hps_tiny()
        key = jax.random.PRNGKey(1)
        B, T, E, H = 2, 5, 2, 3
        fw = {"kernel": jax.random.normal(key, (E + H, 4 * H)),
              "bias": jnp.zeros((4 * H,))}
        bw = {"kernel": jax.random.normal(jax.random.PRNGKey(2), (E + H, 4 * H)),
              "bias": jnp.zeros((4 * H,))}
        x = jax.random.normal(jax.random.PRNGKey(3), (B, T, E))
        lens = jnp.array([3, 5]); mask = (jnp.arange(T)[None] < lens[:, None]).astype(jnp.float32)
        out, fw_st, bw_st = lstm_ops.bidirectional_encoder(fw, bw, x, lens, mask)
        assert np.allclose(out[0, 3:], 0.0)
        # shortening example 0's tail must not change its outputs/states
        x2 = x.at[0, 3:].set(99.0)
        out2, fw_st2, bw_st2 = lstm_ops.bidirectional_encoder(fw, bw, x2, lens, mask)
        np.testing.assert_allclose(out[0], out2[0], rtol=1e-6)
        np.testing.assert_allclose(fw_st[0][0], fw_st2[0][0], rtol=1e-6)
        np.testing.assert_allclose(bw_st[1][0], bw_st2[1][0], rtol=1e-6)

    def test_reverse_sequence(self):
        x = jnp.arange(10).reshape(1, 10, 1).astype(jnp.float32)
        lens = jnp.array([4])
        r = lstm_ops.reverse_sequence(x, lens)
        np.testing.assert_array_equal(
            r[0, :, 0], [3, 2, 1, 0, 4, 5, 6, 7, 8, 9])


class TestLossOps:
    def test_coverage_loss_closed_form_vs_loop(self):
        rng = np.random.default_rng(0)
        attn = rng.random((2, 4, 6)).astype(np.float32)
        attn /= attn.sum(-1, keepdims=True)
        mask = np.array([[1, 1, 1, 0], [1, 1, 1, 1]], np.float32)
        got = float(loss_ops.coverage_loss(jnp.asarray(attn), jnp.asarray(mask)))
        cov = np.zeros((2, 6)); per_step = np.zeros((2, 4))
        for t in range(4):
            per_step[:, t] = np.minimum(attn[:, t], cov).sum(-1)
            cov += attn[:, t]
        want = np.mean((per_step * mask).sum(1) / mask.sum(1))
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_gold_mixture_equals_scatter_gather(self):
        rng = np.random.default_rng(1)
        B, V, T, ext = 3, 7, 5, 9
        vocab_dist = rng.random((B, V)).astype(np.float32)
        vocab_dist /= vocab_dist.sum(-1, keepdims=True)
        attn = rng.random((B, T)).astype(np.float32)
        attn /= attn.sum(-1, keepdims=True)
        p_gen = rng.random(B).astype(np.float32)
        ext_ids = rng.integers(0, ext, (B, T))
        target = np.array([2, 8, 5])
        got = np.asarray(loss_ops.gold_mixture_prob(
            jnp.asarray(vocab_dist), jnp.asarray(attn), jnp.asarray(p_gen),
            jnp.asarray(target), jnp.asarray(ext_ids)))
        final = np.zeros((B, ext))
        final[:, :V] = p_gen[:, None] * vocab_dist
        for b in range(B):
            for i in range(T):
                final[b, ext_ids[b, i]] += (1 - p_gen[b]) * attn[b, i]
        want = final[np.arange(B), target]
        np.testing.assert_allclose(got, want, rtol=1e-6)


class TestDecodeStep:
    def test_shapes_and_distribution(self):
        hps = hps_tiny(coverage=True)
        vocab = make_vocab()
        params = pg.init_params(hps, vocab.size(), jax.random.PRNGKey(0))
        batch = make_batch(hps, vocab)
        arrays = {k: jnp.asarray(v) for k, v in batch.as_arrays().items()}
        enc = pg.run_encoder(params, hps, arrays)
        B = hps.batch_size
        state = enc.dec_in_state
        cov = jnp.zeros((B, hps.max_enc_steps))
        toks = jnp.full((B,), 2)  # [START]
        out = pg.decode_onestep(params, hps, enc, arrays["enc_padding_mask"],
                                arrays["enc_batch_extend_vocab"], toks, state, cov)
        assert out.topk_ids.shape == (B, 2 * hps.beam_size)
        assert out.topk_log_probs.shape == (B, 2 * hps.beam_size)
        assert np.all(np.asarray(out.topk_log_probs) <= 0.0)
        # coverage advanced by exactly the previous-state attention dist
        assert not np.allclose(np.asarray(out.coverage), 0.0)
        np.testing.assert_allclose(np.asarray(out.coverage).sum(-1), 1.0,
                                   rtol=1e-5)

    def test_final_distribution_sums_to_one(self):
        hps = hps_tiny()
        vocab = make_vocab()
        V = vocab.size()
        rng = np.random.default_rng(2)
        vocab_dist = rng.random((2, V)).astype(np.float32)
        vocab_dist /= vocab_dist.sum(-1, keepdims=True)
        attn = rng.random((2, hps.max_enc_steps)).astype(np.float32)
        attn /= attn.sum(-1, keepdims=True)
        p_gen = jnp.asarray([0.3, 0.9], jnp.float32)
        ext_ids = jnp.asarray(rng.integers(0, V + 2, (2, hps.max_enc_steps)))
        fd = pg.final_distribution(hps, jnp.asarray(vocab_dist),
                                   jnp.asarray(attn), p_gen, ext_ids)
        assert fd.shape == (2, V + hps.max_oov_buckets)
        np.testing.assert_allclose(np.asarray(fd).sum(-1), 1.0, rtol=1e-5)


def test_bf16_forward_close_to_f32():
    """compute_dtype=bfloat16 (encoder LSTM + output projection in bf16,
    attention/decoder-state f32) must track the f32 loss closely."""
    hps = hps_tiny(hidden_dim=8, emb_dim=6)
    vocab = make_vocab()
    batch = make_batch(hps, vocab)
    hps = hps.replace(vocab_size=vocab.size())
    params = pg.init_params(hps, vocab.size(), jax.random.PRNGKey(5))
    arrays = batch.as_arrays()
    out32 = pg.forward_train(params, hps, arrays)
    out16 = pg.forward_train(params, hps.replace(compute_dtype="bfloat16"),
                             arrays)
    # the encoder stream (re-read every decoder step) must actually be
    # bf16 — that is the HBM-bandwidth point of bf16 mode
    enc16 = pg.encode(params, hps.replace(compute_dtype="bfloat16"),
                      arrays["enc_batch"], arrays["enc_lens"],
                      arrays["enc_padding_mask"])
    assert enc16.enc_states.dtype == jnp.bfloat16
    assert enc16.enc_features.dtype == jnp.bfloat16
    enc32 = pg.encode(params, hps, arrays["enc_batch"], arrays["enc_lens"],
                      arrays["enc_padding_mask"])
    assert enc32.enc_states.dtype == jnp.float32
    assert np.isfinite(float(out16.loss))
    np.testing.assert_allclose(float(out16.loss), float(out32.loss),
                               rtol=3e-2)
    np.testing.assert_allclose(float(out16.coverage_loss),
                               float(out32.coverage_loss), rtol=5e-2,
                               atol=1e-3)


@pytest.mark.slow
def test_pg_remat_gradient_parity():
    """--remat recomputes the hoisted [T_dec, B, V] scores tensor in
    backward instead of holding it as a residual (ADVICE r2: the
    residual doubles peak HBM at reference scale); gradients must match
    the stored path bit-for-bit up to FP reassociation."""
    hps = hps_tiny()
    vocab = make_vocab()
    batch = make_batch(hps, vocab)
    params = pg.init_params(hps, vocab.size(), jax.random.PRNGKey(3))
    arrays = batch.as_arrays()
    g0 = jax.grad(
        lambda p: pg.forward_train(p, hps, arrays).total_loss)(params)
    g1 = jax.grad(
        lambda p: pg.forward_train(p, hps.replace(remat=True),
                                   arrays).total_loss)(params)
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        a, b = np.asarray(a), np.asarray(b)
        scale = np.max(np.abs(a)) + 1e-12
        assert np.max(np.abs(a - b)) / scale < 1e-5


def test_scan_unroll_numeric_identity():
    """hps.scan_unroll only changes how XLA schedules the recurrence
    (loop-overhead amortization, PERF.md); forward loss and gradients
    must be identical to the unroll=1 schedule up to FP reassociation."""
    hps = hps_tiny(scan_unroll=1)
    vocab = make_vocab()
    batch = make_batch(hps, vocab)
    params = pg.init_params(hps, vocab.size(), jax.random.PRNGKey(5))
    arrays = batch.as_arrays()

    def loss(p, h):
        return pg.forward_train(p, h, arrays).total_loss

    l1 = float(loss(params, hps))
    l8 = float(loss(params, hps.replace(scan_unroll=8)))
    assert l1 == pytest.approx(l8, rel=1e-6)
    g1 = jax.grad(loss)(params, hps)
    g8 = jax.grad(loss)(params, hps.replace(scan_unroll=8))
    for a, b in zip(jax.tree_util.tree_leaves(g1),
                    jax.tree_util.tree_leaves(g8)):
        a, b = np.asarray(a), np.asarray(b)
        scale = np.max(np.abs(a)) + 1e-12
        assert np.max(np.abs(a - b)) / scale < 1e-5
