"""serve/ subsystem: queue admission, micro-batching, buckets, futures,
and the ServingServer end-to-end contracts (ISSUE 4).

The acceptance test (TestServingIntegration) drives >= 32 concurrent
requests through a ServingServer over a REAL tiny model and checks:
(a) measured mean batch fill > 1 (coalescing happened), (b) every
request resolves exactly once with its own uuid, (c) with
serve_max_queue forced small, excess requests get ServeOverloadError
while admitted ones still complete.
"""

import threading
import time
from concurrent.futures import ThreadPoolExecutor

import pytest

from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.config import HParams, parse_bucket_spec
from textsummarization_on_flink_tpu.data.batching import SummaryExample
from textsummarization_on_flink_tpu.data.vocab import Vocab
from textsummarization_on_flink_tpu.decode.decoder import DecodedResult
from textsummarization_on_flink_tpu.obs import Registry
from textsummarization_on_flink_tpu.pipeline import io as io_lib
from textsummarization_on_flink_tpu.resilience.errors import (
    DeadlineExceededError,
)
from textsummarization_on_flink_tpu.resilience.policy import (
    CircuitBreaker,
    Deadline,
)
from textsummarization_on_flink_tpu.serve import (
    MicroBatcher,
    RequestQueue,
    ServeClosedError,
    ServeOverloadError,
    ServeRequest,
    resolve_buckets,
)
from textsummarization_on_flink_tpu.serve.queue import ServeFuture
from textsummarization_on_flink_tpu.serve.server import ServingServer

WORDS = ("the a cat dog sat ran mat home big small quick brown fox "
         "jumped over lazy it was day night").split()


@pytest.fixture(autouse=True)
def _isolated_obs():
    with obs.use_registry(Registry()) as reg:
        yield reg


def make_vocab():
    return Vocab(words=WORDS)


def tiny_hps(**kw):
    base = dict(mode="decode", batch_size=4, hidden_dim=8, emb_dim=6,
                vocab_size=24, max_enc_steps=16, max_dec_steps=6,
                beam_size=2, min_dec_steps=1, max_oov_buckets=4,
                serve_max_wait_ms=50.0, serve_max_queue=64)
    base.update(kw)
    return HParams(**base)


def make_request(hps, vocab, uuid="u0", article="the cat sat .", **kw):
    ex = SummaryExample.build(article, [], vocab, hps, uuid=uuid)
    return ServeRequest(uuid, article, "", ex, **kw)


class StubEngine:
    """SlotDecodeEngine-protocol stub (jax-free): per-request decode
    cost in CHUNKS derived from the example via `chunks_for`, optional
    per-chunk delay — scheduling semantics without a device."""

    def __init__(self, slots=2, chunk=2, chunks_for=None, delay=0.0):
        self.slots = slots
        self.chunk = chunk
        self.delay = delay
        self._chunks_for = chunks_for or (lambda ex: 1)
        self._remaining = [0] * slots
        self._active = [False] * slots
        self.packs = 0
        self.steps = 0

    def pack(self, idx, example):
        assert not self._active[idx], f"slot {idx} double-packed"
        self._active[idx] = True
        self._remaining[idx] = self._chunks_for(example)
        self.packs += 1

    def step(self):
        if self.delay:
            time.sleep(self.delay)
        self.steps += 1
        fin = []
        for i in range(self.slots):
            if self._active[i]:
                self._remaining[i] -= 1
                if self._remaining[i] <= 0:
                    fin.append(i)
        return fin

    def unpack(self, idx, example):
        assert self._active[idx]
        self._active[idx] = False
        return DecodedResult(
            uuid=example.uuid, article=example.original_article,
            decoded_words=["ok", "."], reference=example.reference,
            abstract_sents=[])

    def release(self, idx):
        self._active[idx] = False


class PrefillStubEngine(StubEngine):
    """StubEngine with the disaggregated prefill surface (ISSUE 11):
    the ContinuousBatcher routes requests through prefill() into its
    ready queue before pack().  `fail_for` injects a prefill failure
    for matching uuids (the blast-radius tests)."""

    class Handle:
        def __init__(self, example, bucket):
            self.example = example
            self.bucket = bucket

    def __init__(self, *args, fail_for=None, **kw):
        super().__init__(*args, **kw)
        self._fail_for = fail_for or (lambda ex: False)
        self.prefills = 0
        self.prefills_before_first_unpack = None
        self.unpacks = 0

    def prefill(self, example):
        if self._fail_for(example):
            raise RuntimeError(f"injected prefill failure for "
                               f"{example.uuid!r}")
        self.prefills += 1
        return self.Handle(example, bucket=example.enc_len)

    def pack(self, idx, handle):
        assert isinstance(handle, self.Handle), \
            "prefill engines must be packed from the prefill queue"
        super().pack(idx, handle.example)

    def unpack(self, idx, example):
        if self.unpacks == 0:
            self.prefills_before_first_unpack = self.prefills
        self.unpacks += 1
        return super().unpack(idx, example)


class StubDecoder:
    """decode_batch-compatible stub: optional per-batch delay, results
    echo the batch's real rows (one per real_mask=True slot).  Mirrors
    the real decoder's tier surface (should_degrade / has_draft /
    decode_batch(tier=)) so the server's per-request re-tiering is
    testable without jax."""

    def __init__(self, delay: float = 0.0, degrade_under: float = 0.0,
                 has_draft: bool = False):
        self.delay = delay
        self.degrade_under = degrade_under
        self.has_draft = has_draft
        self.batches = []
        self.tiers = []  # tier of each dispatched batch, in order
        self.reload_calls = 0

    def should_degrade(self, deadline):
        return bool(
            self.degrade_under and deadline is not None and deadline.bounded
            and deadline.remaining() < self.degrade_under)

    def decode_batch(self, batch, deadline=None, tier=None):
        time.sleep(self.delay)
        self.batches.append(batch)
        self.tiers.append(tier)
        degraded = tier is None and self.should_degrade(deadline)
        return [DecodedResult(
                    uuid=batch.uuids[b], article=batch.original_articles[b],
                    decoded_words=["ok", "."], reference=batch.references[b],
                    abstract_sents=[], degraded=degraded,
                    tier=tier or "beam")
                for b in range(len(batch.uuids)) if batch.real_mask[b]]

    def maybe_reload_checkpoint(self, last):
        self.reload_calls += 1
        return last


# -- buckets ---------------------------------------------------------------

class TestBuckets:
    def test_auto_buckets_reference_scale(self):
        assert parse_bucket_spec("", 400) == [100, 200, 400]

    def test_auto_buckets_tiny_drops_sub64(self):
        # tiny configs get ONE bucket — a 4-token bucket saves nothing
        # and costs a whole extra jit compile
        assert parse_bucket_spec("", 16) == [16]

    def test_explicit_spec_appends_max(self):
        assert parse_bucket_spec("8,4", 16) == [4, 8, 16]

    def test_bad_specs_raise(self):
        with pytest.raises(ValueError, match="not an integer"):
            parse_bucket_spec("8,x", 16)
        with pytest.raises(ValueError, match="exceeds max_enc_steps"):
            parse_bucket_spec("32", 16)
        with pytest.raises(ValueError, match=">= 1"):
            parse_bucket_spec("0", 16)

    def test_bucket_for_picks_smallest_cover(self, _isolated_obs):
        hps = tiny_hps(serve_buckets="4,8,16")
        q = RequestQueue(8)
        mb = MicroBatcher(hps, make_vocab(), q)
        assert mb.bucket_for(1) == 4
        assert mb.bucket_for(4) == 4
        assert mb.bucket_for(5) == 8
        assert mb.bucket_for(16) == 16

    def test_resolve_buckets_from_hps(self):
        assert resolve_buckets(tiny_hps(serve_buckets="8")) == [8, 16]


# -- futures ---------------------------------------------------------------

class TestServeFuture:
    def test_result_blocks_then_returns(self):
        fut = ServeFuture("u1")
        threading.Timer(0.05, lambda: fut._resolve("ok")).start()
        assert fut.result(timeout=5.0) == "ok"
        assert fut.done()

    def test_reject_reraises(self):
        fut = ServeFuture("u1")
        fut._reject(ValueError("boom"))
        with pytest.raises(ValueError, match="boom"):
            fut.result(timeout=0.1)
        assert fut.error is not None

    def test_resolves_exactly_once(self):
        fut = ServeFuture("u1")
        fut._resolve("ok")
        with pytest.raises(AssertionError, match="twice"):
            fut._resolve("again")
        with pytest.raises(AssertionError, match="twice"):
            fut._reject(ValueError("late"))

    def test_timeout_raises(self):
        with pytest.raises(TimeoutError):
            ServeFuture("u1").result(timeout=0.01)

    def test_callback_after_done_runs_immediately(self):
        fut = ServeFuture("u1")
        seen = []
        fut.add_done_callback(lambda f: seen.append(("pre", f.error)))
        fut._resolve("ok")
        fut.add_done_callback(lambda f: seen.append(("post", f.error)))
        assert seen == [("pre", None), ("post", None)]

    def test_callback_error_counted_not_fatal(self, _isolated_obs):
        fut = ServeFuture("u1", registry=_isolated_obs)

        def bad(_f):
            raise RuntimeError("sink died")

        fut.add_done_callback(bad)
        fut._resolve("ok")  # must not raise
        assert _isolated_obs.counter(
            "serve/callback_errors_total").value == 1


# -- queue / admission -----------------------------------------------------

class TestRequestQueue:
    def test_full_queue_rejects_typed(self, _isolated_obs):
        hps, vocab = tiny_hps(), make_vocab()
        q = RequestQueue(2, registry=_isolated_obs)
        q.submit(make_request(hps, vocab, "a"))
        q.submit(make_request(hps, vocab, "b"))
        with pytest.raises(ServeOverloadError, match="queue full"):
            q.submit(make_request(hps, vocab, "c"))
        assert _isolated_obs.counter("serve/shed_total").value == 1
        assert _isolated_obs.counter("serve/submitted_total").value == 2

    def test_breaker_opens_under_sustained_overload(self, _isolated_obs):
        hps, vocab = tiny_hps(), make_vocab()
        clock = [0.0]
        breaker = CircuitBreaker(threshold=3, reset_secs=30.0,
                                 name="serve.admission",
                                 clock=lambda: clock[0],
                                 registry=_isolated_obs)
        q = RequestQueue(1, breaker=breaker, registry=_isolated_obs)
        q.submit(make_request(hps, vocab, "a"))
        for i in range(3):  # 3 consecutive rejects trip the breaker
            with pytest.raises(ServeOverloadError):
                q.submit(make_request(hps, vocab, f"r{i}"))
        assert breaker.state == CircuitBreaker.OPEN
        # open breaker sheds BEFORE touching the queue — even though
        # space exists now
        assert q.get(timeout=0.1) is not None
        with pytest.raises(ServeOverloadError, match="breaker open"):
            q.submit(make_request(hps, vocab, "x"))
        # reset window elapses: the half-open probe admission heals it
        clock[0] = 31.0
        q.submit(make_request(hps, vocab, "y"))
        assert breaker.state == CircuitBreaker.CLOSED

    def test_blocking_submit_backpressures(self, _isolated_obs):
        hps, vocab = tiny_hps(), make_vocab()
        q = RequestQueue(1, registry=_isolated_obs)
        q.submit(make_request(hps, vocab, "a"))
        threading.Timer(0.05, q.get).start()
        t0 = time.monotonic()
        q.submit(make_request(hps, vocab, "b"), block=True, timeout=5.0)
        assert time.monotonic() - t0 < 5.0  # waited for space, not full 5s
        with pytest.raises(ServeOverloadError):
            q.submit(make_request(hps, vocab, "c"), block=True, timeout=0.05)

    def test_closed_queue_refuses(self, _isolated_obs):
        hps, vocab = tiny_hps(), make_vocab()
        q = RequestQueue(4, registry=_isolated_obs)
        q.close()
        with pytest.raises(ServeClosedError):
            q.submit(make_request(hps, vocab, "a"))

    def test_drain_reject_resolves_pending(self, _isolated_obs):
        hps, vocab = tiny_hps(), make_vocab()
        q = RequestQueue(4, registry=_isolated_obs)
        reqs = [make_request(hps, vocab, f"u{i}") for i in range(3)]
        for r in reqs:
            q.submit(r)
        assert q.drain_reject(ServeClosedError("stopping")) == 3
        for r in reqs:
            with pytest.raises(ServeClosedError):
                r.future.result(timeout=0.1)


# -- micro-batcher ---------------------------------------------------------

class TestMicroBatcher:
    def test_coalesces_up_to_max_batch(self, _isolated_obs):
        hps, vocab = tiny_hps(serve_max_wait_ms=200.0), make_vocab()
        q = RequestQueue(16, registry=_isolated_obs)
        for i in range(6):
            q.submit(make_request(hps, vocab, f"u{i}"))
        mb = MicroBatcher(hps, vocab, q, registry=_isolated_obs)
        g1 = mb.next_group()
        g2 = mb.next_group()
        assert [r.uuid for r in g1] == ["u0", "u1", "u2", "u3"]
        assert [r.uuid for r in g2] == ["u4", "u5"]
        assert mb.next_group(poll=0.01) is None  # idle

    def test_serve_max_batch_caps_below_batch_size(self, _isolated_obs):
        hps, vocab = tiny_hps(serve_max_batch=2), make_vocab()
        q = RequestQueue(16, registry=_isolated_obs)
        for i in range(4):
            q.submit(make_request(hps, vocab, f"u{i}"))
        mb = MicroBatcher(hps, vocab, q, registry=_isolated_obs)
        assert len(mb.next_group()) == 2

    def test_window_ships_partial_batch(self, _isolated_obs):
        hps, vocab = tiny_hps(serve_max_wait_ms=30.0), make_vocab()
        q = RequestQueue(16, registry=_isolated_obs)
        q.submit(make_request(hps, vocab, "only"))
        mb = MicroBatcher(hps, vocab, q, registry=_isolated_obs)
        t0 = time.monotonic()
        group = mb.next_group()
        dt = time.monotonic() - t0
        assert [r.uuid for r in group] == ["only"]
        assert dt < 5.0  # waited ~the window, not forever

    def test_build_pads_batch_and_bucket(self, _isolated_obs):
        hps, vocab = tiny_hps(serve_buckets="4,8,16"), make_vocab()
        q = RequestQueue(16, registry=_isolated_obs)
        mb = MicroBatcher(hps, vocab, q, registry=_isolated_obs)
        reqs = [make_request(hps, vocab, "a", article="the cat sat ."),
                make_request(hps, vocab, "b",
                             article="the quick brown fox ran over it")]
        batch = mb.build(reqs)
        # batch axis padded to batch_size, encoder axis to the 8-bucket
        # (longest article = 7 tokens)
        assert batch.enc_batch.shape == (4, 8)
        assert batch.real_mask == [True, True, False, False]
        assert batch.uuids[:2] == ["a", "b"]
        assert _isolated_obs.counter("serve/pad_rows_total").value == 2
        fill = _isolated_obs.histogram("serve/batch_fill")
        assert fill.count == 1 and fill.mean == 2.0


# -- server (stub decoder: queue/dispatch semantics, no jax) ---------------

class TestServingServerStub:
    def test_requests_resolve_with_own_uuid(self, _isolated_obs):
        hps, vocab = tiny_hps(), make_vocab()
        server = ServingServer(hps, vocab, decoder=StubDecoder(0.01),
                               registry=_isolated_obs)
        with server:
            futs = [server.submit("the cat sat .", uuid=f"u{i}")
                    for i in range(10)]
            results = [f.result(timeout=30) for f in futs]
        assert [r.uuid for r in results] == [f"u{i}" for i in range(10)]
        assert _isolated_obs.counter("serve/completed_total").value == 10

    def test_submit_after_stop_raises_closed(self, _isolated_obs):
        hps, vocab = tiny_hps(), make_vocab()
        server = ServingServer(hps, vocab, decoder=StubDecoder(),
                               registry=_isolated_obs)
        server.start()
        server.stop()
        with pytest.raises(ServeClosedError):
            server.submit("the cat .")

    def test_stop_drains_admitted_requests(self, _isolated_obs):
        hps, vocab = tiny_hps(serve_max_wait_ms=5.0), make_vocab()
        server = ServingServer(hps, vocab, decoder=StubDecoder(0.02),
                               registry=_isolated_obs)
        server.start()
        futs = [server.submit("the cat .", uuid=f"u{i}") for i in range(8)]
        server.stop()  # drain-then-join: every admitted request resolves
        assert all(f.done() for f in futs)
        assert [f.result(0.1).uuid for f in futs] == \
            [f"u{i}" for i in range(8)]

    def test_dispatch_failure_rejects_batch_only(self, _isolated_obs):
        hps, vocab = tiny_hps(serve_max_wait_ms=100.0,
                              faults="serve.dispatch:1.0:0:1"), make_vocab()
        server = ServingServer(hps, vocab, decoder=StubDecoder(),
                               registry=_isolated_obs)
        with server:
            # batch 1 eats the injected fault and is rejected wholesale
            bad = [server.submit("the cat .", uuid=f"bad{i}")
                   for i in range(2)]
            for f in bad:
                with pytest.raises(RuntimeError, match="injected"):
                    f.result(timeout=30)
            # the server survives: batch 2 serves normally
            ok = server.submit("the dog ran .", uuid="ok")
            assert ok.result(timeout=30).uuid == "ok"
        assert _isolated_obs.counter("serve/errors_total").value == 2
        assert _isolated_obs.counter("serve/completed_total").value == 1

    def test_tightest_deadline_drives_degradation_tag(self, _isolated_obs):
        # stub degrades when the batch deadline budget is under 10s:
        # the per-request deadline (from enqueue) reaches the decoder
        hps, vocab = tiny_hps(decode_deadline_secs=5.0), make_vocab()
        server = ServingServer(hps, vocab,
                               decoder=StubDecoder(degrade_under=10.0),
                               registry=_isolated_obs)
        with server:
            res = server.submit("the cat .", uuid="d0").result(timeout=30)
        assert res.degraded
        assert _isolated_obs.counter("serve/degraded_total").value == 1
        assert _isolated_obs.counter(
            "serve/tier_degraded_beam_total").value == 1

    def test_sharded_decoder_rejects_non_beam_tiers_at_submit(
            self, _isolated_obs):
        """A mesh decoder's search is jit-built once for the plan: any
        non-beam tier must fail synchronously at submit, not
        asynchronously at dispatch (burning an error + flight dump)."""
        dec = StubDecoder(has_draft=True)
        dec.sharded = True
        server = ServingServer(tiny_hps(), make_vocab(), decoder=dec,
                               registry=_isolated_obs)
        with server:
            with pytest.raises(ValueError, match="beam tier only"):
                server.submit("the cat .", tier="greedy")
            with pytest.raises(ValueError, match="beam tier only"):
                server.submit("the cat .", tier="spec")
            assert server.submit("the cat .", uuid="b0",
                                 tier="beam").result(timeout=30).uuid == "b0"

    def test_degradation_is_per_request_not_per_batch(self, _isolated_obs):
        """The ISSUE-10 satellite fix: one tight-deadline member no
        longer drags its batchmates down to greedy — the group splits
        into per-tier sub-dispatches and only the pressed request
        degrades (counted per request AND per requested tier)."""

        class AlternatingDecoder(StubDecoder):
            # per-REQUEST predicate: degrade every second ask (the
            # server consults it once per group member)
            def __init__(self):
                super().__init__()
                self.asks = 0
                self.has_draft = False

            def should_degrade(self, deadline):
                self.asks += 1
                return self.asks % 2 == 0

        dec = AlternatingDecoder()
        hps, vocab = tiny_hps(serve_max_wait_ms=200.0,
                              decode_deadline_secs=30.0), make_vocab()
        server = ServingServer(hps, vocab, decoder=dec,
                               registry=_isolated_obs)
        server.start()
        # fill one coalescing window with 4 requests BEFORE dispatch
        futs = [server.submit("the cat .", uuid=f"m{i}") for i in range(4)]
        results = {f.result(timeout=30).uuid: f.result(timeout=30)
                   for f in futs}
        server.stop()
        degraded = sorted(u for u, r in results.items() if r.degraded)
        kept = sorted(u for u, r in results.items() if not r.degraded)
        assert len(degraded) == 2 and len(kept) == 2, results
        # the mixed group split into one beam and one greedy dispatch
        assert sorted(t for t in dec.tiers if t) == ["beam", "greedy"]
        by_tier = {t: b for t, b in zip(dec.tiers, dec.batches)}
        greedy_real = [u for u, m in zip(by_tier["greedy"].uuids,
                                         by_tier["greedy"].real_mask) if m]
        assert sorted(greedy_real) == degraded
        assert _isolated_obs.counter("serve/degraded_total").value == 2
        assert _isolated_obs.counter(
            "serve/tier_degraded_beam_total").value == 2
        assert _isolated_obs.counter("serve/tier_beam_total").value == 2
        assert _isolated_obs.counter("serve/tier_greedy_total").value == 2

    def test_expired_in_queue_evicted_typed_not_dispatched(
            self, _isolated_obs):
        """The ISSUE-6 eviction bugfix, micro-batch side: a request
        whose enqueue-measured Deadline died while it waited in the
        queue is resolved with the typed DeadlineExceededError at group
        pickup (and counted) instead of burning dispatch time."""
        hps, vocab = tiny_hps(serve_max_wait_ms=5.0,
                              decode_deadline_secs=0.15), make_vocab()
        server = ServingServer(hps, vocab, decoder=StubDecoder(delay=0.3),
                               registry=_isolated_obs)
        with server:
            fresh = server.submit("the cat .", uuid="fresh")
            time.sleep(0.05)  # let the first group dispatch alone
            # ages out behind the 0.3s dispatch: 0.25s queued > 0.15s
            stale = server.submit("the dog .", uuid="stale")
            assert fresh.result(timeout=30).uuid == "fresh"
            with pytest.raises(DeadlineExceededError, match="queued"):
                stale.result(timeout=30)
        assert _isolated_obs.counter(
            "serve/deadline_evictions_total").value == 1
        assert _isolated_obs.counter("serve/completed_total").value == 1
        assert _isolated_obs.counter("serve/errors_total").value == 0

    def test_serve_drives_source_to_sink(self, _isolated_obs):
        hps, vocab = tiny_hps(), make_vocab()
        rows = [(f"uuid-{i}", f"the cat sat {i} .", "", f"ref {i}")
                for i in range(8)]
        server = ServingServer(hps, vocab, decoder=StubDecoder(0.01),
                               registry=_isolated_obs)
        sink = io_lib.CollectionSink()
        with server:
            out = server.serve(io_lib.CollectionSource(rows), sink)
        assert out is sink
        assert {r[0] for r in sink.rows} == {f"uuid-{i}" for i in range(8)}
        # (uuid, article, summary, reference) row shape, per-record flush
        uuid, article, summary, reference = sink.rows[0]
        assert summary == "ok ."
        assert _isolated_obs.counter("serve/sink_rows_total").value == 8

    def test_reload_failure_does_not_kill_dispatcher(self, _isolated_obs):
        """A failed between-batch checkpoint reload is counted and the
        server keeps serving on its current params — it must never
        unwind the dispatch thread (which would hang every queued and
        future request)."""
        class ReloadBomb(StubDecoder):
            def maybe_reload_checkpoint(self, last):
                raise FileNotFoundError("checkpoint dir vanished")

        hps, vocab = tiny_hps(serve_max_wait_ms=5.0), make_vocab()
        server = ServingServer(hps, vocab, decoder=ReloadBomb(),
                               registry=_isolated_obs)
        with server:
            first = server.submit("the cat .", uuid="a").result(timeout=30)
            # the reload after batch 1 raised; batch 2 must still serve
            second = server.submit("the dog .", uuid="b").result(timeout=30)
        assert (first.uuid, second.uuid) == ("a", "b")
        assert _isolated_obs.counter(
            "serve/ckpt_reload_errors_total").value >= 1
        assert _isolated_obs.counter("serve/errors_total").value == 0

    def test_serve_max_count_bounds_unbounded_source(self, _isolated_obs):
        """serve(max_count=N) stops pulling after N rows — the bound
        transform(serving=True, max_batches=...) maps onto."""
        hps, vocab = tiny_hps(), make_vocab()

        def endless():
            i = 0
            while True:
                yield (f"uuid-{i}", "the cat .", "", "r")
                i += 1

        src = io_lib.IteratorSource(endless)
        server = ServingServer(hps, vocab, decoder=StubDecoder(),
                               registry=_isolated_obs)
        sink = io_lib.CollectionSink()
        with server:
            server.serve(src, sink, max_count=6)
        assert len(sink.rows) == 6

    def test_serve_dispatch_error_counts_once_per_request(
            self, _isolated_obs):
        """serve/errors_total is counted at the rejection site only:
        the serve() drain loop must not double-count failed futures."""
        hps, vocab = tiny_hps(serve_max_wait_ms=100.0,
                              faults="serve.dispatch:1.0:0"), make_vocab()
        rows = [(f"uuid-{i}", "the cat .", "", "r") for i in range(2)]
        server = ServingServer(hps, vocab, decoder=StubDecoder(),
                               registry=_isolated_obs)
        with server:
            with pytest.raises(RuntimeError, match="injected"):
                server.serve(io_lib.CollectionSource(rows),
                             io_lib.CollectionSink())
        assert _isolated_obs.counter("serve/errors_total").value == 2

    def test_serve_rejects_schema_mismatch_typed(self, _isolated_obs):
        hps, vocab = tiny_hps(), make_vocab()
        src = io_lib.CollectionSource(
            [("only-two", "cols")],
            schema=io_lib.RowSchema(["uuid", "article"],
                                    [io_lib.DataTypes.STRING] * 2))
        server = ServingServer(hps, vocab, decoder=StubDecoder(),
                               registry=_isolated_obs)
        with server:
            with pytest.raises(io_lib.SchemaProjectionError):
                server.serve(src, io_lib.CollectionSink())
        assert _isolated_obs.counter(
            "pipeline/feeder_errors_total").value == 1


# -- continuous batching (stub engine: scheduling semantics, no jax) -------

def cont_hps(**kw):
    base = dict(serve_mode="continuous", serve_slots=2, serve_refill_chunk=2)
    base.update(kw)
    return tiny_hps(**base)


class TestContinuousServingStub:
    def test_requests_resolve_with_own_uuid(self, _isolated_obs):
        hps, vocab = cont_hps(), make_vocab()
        engine = StubEngine(slots=2, chunks_for=lambda ex: 2)
        server = ServingServer(hps, vocab, decoder=StubDecoder(),
                               engine=engine, registry=_isolated_obs)
        with server:
            futs = [server.submit("the cat sat .", uuid=f"u{i}")
                    for i in range(10)]
            results = [f.result(timeout=30) for f in futs]
        assert [r.uuid for r in results] == [f"u{i}" for i in range(10)]
        assert _isolated_obs.counter("serve/completed_total").value == 10
        assert _isolated_obs.counter("serve/slot_refills_total").value == 10
        # every request sat resident for exactly its 2 chunks
        resident = _isolated_obs.histogram("serve/request_resident_chunks")
        assert resident.count == 10 and resident.mean == 2.0
        # occupancy was observed once per chunk step
        assert _isolated_obs.histogram("serve/slot_occupancy").count > 0

    def test_refill_beats_the_batch_barrier(self, _isolated_obs):
        """The continuous claim at its smallest: one long request plus a
        stream of short ones.  The shorts keep flowing through the OTHER
        slot while the long one stays resident — so the long request
        sees more refills happen around it than any fixed batch would
        allow (a micro-batch would hold all of them hostage)."""
        hps, vocab = cont_hps(), make_vocab()
        engine = StubEngine(
            slots=2,
            chunks_for=lambda ex: 12 if "long" in ex.original_article else 1)
        server = ServingServer(hps, vocab, decoder=StubDecoder(),
                               engine=engine, registry=_isolated_obs)
        with server:
            futs = [server.submit("a long long ride .", uuid="long")]
            futs += [server.submit("the cat .", uuid=f"s{i}")
                     for i in range(6)]
            results = [f.result(timeout=30) for f in futs]
        assert {r.uuid for r in results} == {"long"} | {
            f"s{i}" for i in range(6)}
        # the long request resolved LAST even though it was admitted
        # first — neighbors never waited on it
        resident = _isolated_obs.histogram("serve/request_resident_chunks")
        assert resident.count == 7
        assert _isolated_obs.counter("serve/slot_refills_total").value == 7

    def test_dispatch_fault_fails_resident_only(self, _isolated_obs):
        hps, vocab = cont_hps(
            faults="serve.dispatch:1.0:0:1"), make_vocab()
        engine = StubEngine(slots=2, chunks_for=lambda ex: 1)
        server = ServingServer(hps, vocab, decoder=StubDecoder(),
                               engine=engine, registry=_isolated_obs)
        # enqueue BEFORE start so both are resident when the fault fires
        bad = [server.submit("the cat .", uuid=f"bad{i}") for i in range(2)]
        with server:
            for f in bad:
                with pytest.raises(RuntimeError, match="injected"):
                    f.result(timeout=30)
            # the server survives at slot granularity: next request ok
            ok = server.submit("the dog ran .", uuid="ok")
            assert ok.result(timeout=30).uuid == "ok"
        assert _isolated_obs.counter("serve/errors_total").value == 2
        assert _isolated_obs.counter("serve/completed_total").value == 1

    def test_deadline_evicts_queued_and_resident(self, _isolated_obs):
        """The ISSUE-6 eviction bugfix, both sites: a resident request
        whose budget runs out is evicted at a chunk boundary; a request
        whose budget died while QUEUED is resolved typed at refill —
        each with DeadlineExceededError, both counted."""
        hps, vocab = cont_hps(serve_slots=1,
                              decode_deadline_secs=0.1), make_vocab()
        engine = StubEngine(
            slots=1, delay=0.06,
            chunks_for=lambda ex: 50 if "long" in ex.original_article else 1)
        server = ServingServer(hps, vocab, decoder=StubDecoder(),
                               engine=engine, registry=_isolated_obs)
        long_f = server.submit("a long long ride .", uuid="long")
        short_f = server.submit("the cat .", uuid="short")
        with server:
            # the long request occupies the ONLY slot past its budget ->
            # evicted resident; the short one ages out in the queue
            # behind it -> evicted at refill
            with pytest.raises(DeadlineExceededError, match="resident"):
                long_f.result(timeout=30)
            with pytest.raises(DeadlineExceededError, match="queued"):
                short_f.result(timeout=30)
            # a fresh request (fresh budget) still serves
            ok = server.submit("the dog ran .", uuid="ok")
            assert ok.result(timeout=30).uuid == "ok"
        assert _isolated_obs.counter(
            "serve/deadline_evictions_total").value == 2
        assert _isolated_obs.counter("serve/completed_total").value == 1
        # evictions are deadline OUTCOMES, not server errors
        assert _isolated_obs.counter("serve/errors_total").value == 0

    def test_stop_drains_admitted_requests(self, _isolated_obs):
        hps, vocab = cont_hps(), make_vocab()
        engine = StubEngine(slots=2, chunks_for=lambda ex: 2, delay=0.01)
        server = ServingServer(hps, vocab, decoder=StubDecoder(),
                               engine=engine, registry=_isolated_obs)
        server.start()
        futs = [server.submit("the cat .", uuid=f"u{i}") for i in range(6)]
        server.stop()  # drain-then-join: every admitted request resolves
        assert all(f.done() for f in futs)
        assert [f.result(0.1).uuid for f in futs] == \
            [f"u{i}" for i in range(6)]


class TestContinuousPrefillStub:
    """The ContinuousBatcher prefill queue (ISSUE 11), stub engine:
    routing, telemetry, lookahead, and failure blast radius — no jax."""

    def test_requests_route_through_prefill_exactly_once(
            self, _isolated_obs):
        hps, vocab = cont_hps(), make_vocab()
        engine = PrefillStubEngine(slots=2, chunks_for=lambda ex: 2)
        server = ServingServer(hps, vocab, decoder=StubDecoder(),
                               engine=engine, registry=_isolated_obs)
        with server:
            futs = [server.submit("the cat sat .", uuid=f"u{i}")
                    for i in range(8)]
            results = [f.result(timeout=30) for f in futs]
        assert [r.uuid for r in results] == [f"u{i}" for i in range(8)]
        assert engine.prefills == 8
        assert _isolated_obs.counter("serve/prefill_total").value == 8
        assert _isolated_obs.counter("serve/prefill_errors_total").value == 0
        bucket_h = _isolated_obs.histogram("serve/prefill_bucket_len")
        assert bucket_h.count == 8

    def test_prefill_lookahead_runs_ahead_of_free_slots(
            self, _isolated_obs):
        """serve_prefill_depth=2 on a 1-slot engine: the first tick
        packs one request and prefills TWO more ahead of it, so a freed
        slot refills from an already-encoded article."""
        hps, vocab = cont_hps(serve_slots=1,
                              serve_prefill_depth=2), make_vocab()
        engine = PrefillStubEngine(slots=1, chunks_for=lambda ex: 3)
        server = ServingServer(hps, vocab, decoder=StubDecoder(),
                               engine=engine, registry=_isolated_obs)
        # everything enqueued BEFORE the dispatch thread exists, so the
        # first tick's prefill target (1 free + depth 2) is deterministic
        futs = [server.submit("the cat sat .", uuid=f"u{i}")
                for i in range(4)]
        with server:
            results = [f.result(timeout=30) for f in futs]
        assert [r.uuid for r in results] == [f"u{i}" for i in range(4)]
        assert engine.prefills_before_first_unpack == 3

    def test_prefill_failure_rejects_its_request_only(self, _isolated_obs):
        """A prefill failure resolves ITS request typed and rides the
        standard dispatch-failure path (fail_resident blast radius);
        the server lives on and later requests serve normally."""
        hps, vocab = cont_hps(), make_vocab()
        engine = PrefillStubEngine(
            slots=2, chunks_for=lambda ex: 1,
            fail_for=lambda ex: ex.uuid == "boom")
        server = ServingServer(hps, vocab, decoder=StubDecoder(),
                               engine=engine, registry=_isolated_obs)
        with server:
            bad = server.submit("the cat sat .", uuid="boom")
            with pytest.raises(RuntimeError, match="injected prefill"):
                bad.result(timeout=30)
            ok = server.submit("the dog ran .", uuid="ok")
            assert ok.result(timeout=30).uuid == "ok"
        assert _isolated_obs.counter("serve/prefill_errors_total").value \
            == 1
        assert _isolated_obs.counter("serve/completed_total").value == 1

    def test_drain_waits_for_prefilled_backlog(self, _isolated_obs):
        """The drain-condition regression: a tick can harvest EVERY
        resident right after the prefill stage drained the queue's tail
        into the prefill queue — the loop must keep ticking for those
        admitted-but-unslotted requests (busy() is false, pending() is
        true), not let stop() reject them."""
        from textsummarization_on_flink_tpu.serve.batcher import (
            ContinuousBatcher,
        )

        hps, vocab = cont_hps(serve_slots=1,
                              serve_prefill_depth=2), make_vocab()
        engine = PrefillStubEngine(slots=1, chunks_for=lambda ex: 1)
        q = RequestQueue(8, registry=_isolated_obs)
        cont = ContinuousBatcher(hps, q, engine, registry=_isolated_obs)
        reqs = [make_request(hps, vocab, uuid=f"u{i}") for i in range(3)]
        for r in reqs:
            q.submit(r)
        # tick 1: prefill pops ALL THREE (1 free + depth 2), packs one,
        # its single chunk finishes and harvests -> no residents, empty
        # queue, but two prefilled entries pending
        assert cont.tick(poll=0.01)
        assert q.empty() and not cont.busy()
        assert cont.pending()  # the server's drain condition keys on this
        assert cont.tick(poll=0.01)
        assert cont.tick(poll=0.01)
        assert not cont.pending()
        for r in reqs:
            assert r.future.result(timeout=1).uuid == r.uuid

    def test_stop_drains_prefilled_backlog_through_server(
            self, _isolated_obs):
        """Server-level: stop() right after submit must still resolve
        every admitted request with a RESULT (the exactly-once drain
        contract), including ones sitting in the prefill queue when the
        stop flag lands."""
        hps, vocab = cont_hps(serve_slots=1,
                              serve_prefill_depth=2), make_vocab()
        engine = PrefillStubEngine(slots=1, chunks_for=lambda ex: 1)
        server = ServingServer(hps, vocab, decoder=StubDecoder(),
                               engine=engine, registry=_isolated_obs)
        server.start()
        futs = [server.submit("the cat .", uuid=f"u{i}") for i in range(5)]
        server.stop()
        assert [f.result(0.1).uuid for f in futs] == \
            [f"u{i}" for i in range(5)]

    def test_fail_pending_resolves_prefilled_backlog(self, _isolated_obs):
        """The shutdown backstop: prefilled-but-unslotted entries must
        resolve exactly once if the loop dies with them queued."""
        from textsummarization_on_flink_tpu.serve.batcher import (
            ContinuousBatcher,
        )

        hps, vocab = cont_hps(), make_vocab()
        engine = PrefillStubEngine(slots=1)
        cont = ContinuousBatcher(hps, RequestQueue(8,
                                                   registry=_isolated_obs),
                                 engine, registry=_isolated_obs)
        req = make_request(hps, vocab, uuid="stranded")
        cont._prefilled.append((req, engine.prefill(req.example)))
        n = cont.fail_pending(ServeClosedError("stopped"))
        assert n == 1
        with pytest.raises(ServeClosedError):
            req.future.result(timeout=1)

    def test_prefill_trace_event_carries_bucket(self, tmp_path,
                                                _isolated_obs):
        import json

        reg = _isolated_obs
        sink = obs.install_event_sink(str(tmp_path), flush_secs=0.05,
                                      reg=reg)
        hps, vocab = cont_hps(), make_vocab()
        engine = PrefillStubEngine(slots=2, chunks_for=lambda ex: 1)
        server = ServingServer(hps, vocab, decoder=StubDecoder(),
                               engine=engine, registry=reg)
        with server:
            server.submit("the cat sat .", uuid="u0").result(timeout=30)
        sink.close()
        recs = [json.loads(ln)
                for ln in open(tmp_path / "events.jsonl",
                               encoding="utf-8")]
        events = [r for r in recs if r.get("kind") == "request"
                  and r["uuid"] == "u0"]
        stages = [e["event"] for e in events]
        # the disaggregated lifecycle, in order, one connected trace
        assert stages[0] == "enqueue" and stages[-1] == "resolve"
        for required in ("admit", "prefill", "slot", "finish"):
            assert required in stages, stages
        assert stages.index("prefill") < stages.index("slot")
        pre = next(e for e in events if e["event"] == "prefill")
        assert pre["attrs"]["bucket"] >= 1
        assert len({e["trace_id"] for e in events}) == 1


# -- acceptance: >= 32 concurrent requests against a real tiny model -------

class TestServingIntegration:
    @pytest.fixture(scope="class")
    def model_setup(self):
        from textsummarization_on_flink_tpu.train import trainer as trainer_lib

        vocab = make_vocab()
        hps = tiny_hps(vocab_size=vocab.size(), serve_max_wait_ms=150.0,
                       serve_buckets="16")
        params = trainer_lib.init_train_state(hps, vocab.size(),
                                              seed=0).params
        return hps, vocab, params

    def test_32_concurrent_requests_coalesce_and_resolve_once(
            self, model_setup, tmp_path, _isolated_obs):
        """Acceptance (a)+(b): 32 concurrent submitters share device
        dispatches (mean fill > 1) and each future resolves exactly
        once with its own uuid."""
        hps, vocab, params = model_setup
        reg = _isolated_obs
        server = ServingServer(hps, vocab, params=params,
                               decode_root=str(tmp_path / "serve"),
                               registry=reg)
        resolved = []
        resolved_lock = threading.Lock()

        def count_resolution(fut):
            with resolved_lock:
                resolved.append(fut.uuid)

        with server:
            # warm the jit cache so the compile doesn't eat the window
            server.submit("the cat sat .", uuid="warm").result(timeout=300)
            fills_before = reg.histogram("serve/batch_fill").count
            with ThreadPoolExecutor(max_workers=8) as ex:
                futs = list(ex.map(
                    lambda i: server.submit(
                        "the quick brown fox jumped over the lazy dog .",
                        uuid=f"u{i}"), range(32)))
            for f in futs:
                f.add_done_callback(count_resolution)
            results = [f.result(timeout=300) for f in futs]
        # (b) exactly once, own uuid: in-order zip, one callback each
        assert [r.uuid for r in results] == [f"u{i}" for i in range(32)]
        assert sorted(resolved) == sorted(f"u{i}" for i in range(32))
        for r in results:
            assert isinstance(r.summary, str)
        # (a) coalescing happened: 32 requests over < 32 dispatches
        fill = reg.histogram("serve/batch_fill")
        n_batches = fill.count - fills_before
        assert n_batches < 32
        mean_fill = (fill.sum - 1) / n_batches  # minus the fill-1 warm
        assert mean_fill > 1.0
        assert reg.counter("serve/completed_total").value == 33

    def test_continuous_mode_parity_and_bounded_jit_cache(
            self, model_setup, tmp_path, _isolated_obs):
        """Continuous acceptance against the REAL tiny model: (a) every
        request resolves exactly once with its own uuid, (b) summaries
        are token-identical to micro-batch mode on the same inputs (the
        slot loop is the same masked chunk body — routing, not
        semantics), (c) the slot-kernel jit cache does NOT grow after
        warmup (no per-request recompiles), (d) occupancy/refill
        telemetry is recorded."""
        hps, vocab, params = model_setup
        reg = _isolated_obs
        articles = [
            "the quick brown fox jumped over the lazy dog .",
            "a big dog ran home .",
            "the cat sat .",
            "it was day and night and day .",
        ]
        hps_c = hps.replace(serve_mode="continuous", serve_slots=3,
                            serve_refill_chunk=2)
        server = ServingServer(hps_c, vocab, params=params,
                               decode_root=str(tmp_path / "cont"),
                               registry=reg)
        with server:
            server.submit(articles[0], uuid="warm").result(timeout=300)
            engine = server._cont._engine
            sizes_warm = engine.cache_sizes()
            futs = [server.submit(articles[i % 4], uuid=f"u{i}")
                    for i in range(12)]
            results = [f.result(timeout=300) for f in futs]
            sizes_after = engine.cache_sizes()
        assert [r.uuid for r in results] == [f"u{i}" for i in range(12)]
        # (c) bounded compile cache: slot index, occupancy, and article
        # content are traced — 12 more requests, zero new executables
        assert sizes_after == sizes_warm and sizes_warm
        # (d) continuous telemetry
        assert reg.counter("serve/slot_refills_total").value == 13
        assert reg.histogram("serve/slot_occupancy").count > 0
        assert reg.histogram("serve/request_resident_chunks").count == 13
        # (b) mode parity: the same articles through micro-batch mode
        server_mb = ServingServer(hps, vocab, params=params,
                                  decode_root=str(tmp_path / "mb"),
                                  registry=reg)
        with server_mb:
            futs_mb = [server_mb.submit(articles[i % 4], uuid=f"u{i}")
                       for i in range(12)]
            results_mb = [f.result(timeout=300) for f in futs_mb]
        assert [r.summary for r in results] == \
            [r.summary for r in results_mb]

    def test_small_queue_sheds_excess_but_serves_admitted(
            self, model_setup, tmp_path, _isolated_obs):
        """Acceptance (c): serve_max_queue forced small + slow batches
        -> excess requests get the typed ServeOverloadError while every
        admitted one still completes."""
        hps, vocab, params = model_setup
        hps = hps.replace(serve_max_queue=2, serve_max_wait_ms=5.0)
        reg = _isolated_obs
        from textsummarization_on_flink_tpu.decode.decoder import (
            BeamSearchDecoder,
        )

        inner = BeamSearchDecoder(hps, vocab, batcher=None, params=params,
                                  decode_root=str(tmp_path / "serve2"))

        class SlowDecoder:
            def decode_batch(self, batch, deadline=None):
                time.sleep(0.15)  # hold the dispatcher so the queue fills
                return inner.decode_batch(batch, deadline=deadline)

            def maybe_reload_checkpoint(self, last):
                return last

        server = ServingServer(hps, vocab, decoder=SlowDecoder(),
                               registry=reg)
        admitted, sheds = [], 0
        with server:
            server.submit("the cat sat .", uuid="warm").result(timeout=300)
            for i in range(32):
                try:
                    admitted.append(server.submit(
                        "a big dog ran home .", uuid=f"u{i}"))
                except ServeOverloadError:
                    sheds += 1
            results = [f.result(timeout=300) for f in admitted]
        assert sheds > 0
        assert len(admitted) >= 1
        # every ADMITTED request completed, with its own uuid
        assert [r.uuid for r in results] == [f.uuid for f in admitted]
        assert reg.counter("serve/shed_total").value == sheds
        assert reg.counter("serve/completed_total").value == \
            len(admitted) + 1


# -- request-scoped tracing acceptance (ISSUE 9) ---------------------------

class TestRequestTracing:
    """Acceptance: in a 32-concurrent-request run, every admitted uuid's
    events in events.jsonl form ONE connected trace (enqueue->resolve,
    one trace_id, no orphans) — in BOTH serve modes."""

    N = 32

    def _run_server(self, tmp_path, reg, hps, **server_kw):
        import json

        sink = obs.install_event_sink(str(tmp_path), flush_secs=0.05,
                                      reg=reg)
        server = ServingServer(hps, make_vocab(), registry=reg,
                               **server_kw)
        with server:
            with ThreadPoolExecutor(max_workers=8) as ex:
                futs = list(ex.map(
                    lambda i: server.submit("the cat sat .", uuid=f"u{i}",
                                            block=True),
                    range(self.N)))
            results = [f.result(timeout=60) for f in futs]
        sink.close()
        assert sorted(r.uuid for r in results) == sorted(
            f"u{i}" for i in range(self.N))
        recs = [json.loads(ln)
                for ln in open(tmp_path / "events.jsonl", encoding="utf-8")]
        by_uuid = {}
        for r in recs:
            if r.get("kind") == "request":
                by_uuid.setdefault(r["uuid"], []).append(r)
        return recs, by_uuid

    def _assert_connected(self, by_uuid, required):
        assert sorted(by_uuid) == sorted(f"u{i}" for i in range(self.N))
        trace_ids = {}
        for uuid, events in by_uuid.items():
            stages = [e["event"] for e in events]
            assert required <= set(stages), (uuid, stages)
            # connected: ONE trace_id and ONE root span_id across every
            # event of the request — no orphan fragments
            assert len({e["trace_id"] for e in events}) == 1, uuid
            assert len({e["span_id"] for e in events}) == 1, uuid
            # ordered: lifecycle timestamps never run backwards
            ts = [e["ts_us"] for e in events]
            assert ts == sorted(ts), uuid
            assert stages[0] == "enqueue" and stages[-1] == "resolve", uuid
            trace_ids[uuid] = events[0]["trace_id"]
        # distinct requests never share a trace
        assert len(set(trace_ids.values())) == self.N

    def test_microbatch_traces_connected(self, tmp_path, _isolated_obs):
        reg = _isolated_obs
        hps = tiny_hps(serve_max_wait_ms=5.0)
        _, by_uuid = self._run_server(tmp_path, reg, hps,
                                      decoder=StubDecoder())
        self._assert_connected(
            by_uuid, {"enqueue", "admit", "finish", "resolve"})

    def test_continuous_traces_connected_with_slot_events(
            self, tmp_path, _isolated_obs):
        reg = _isolated_obs
        hps = tiny_hps(serve_mode="continuous")
        engine = StubEngine(slots=4, chunk=2,
                            chunks_for=lambda ex: 2)
        _, by_uuid = self._run_server(tmp_path, reg, hps,
                                      decoder=StubDecoder(), engine=engine)
        self._assert_connected(
            by_uuid, {"enqueue", "admit", "slot", "finish", "resolve"})
        # the slot event carries the physical placement (slot @ tick)
        for uuid, events in by_uuid.items():
            slot_ev = next(e for e in events if e["event"] == "slot")
            assert 0 <= slot_ev["attrs"]["slot"] < 4
            assert slot_ev["attrs"]["tick"] >= 1
            fin = next(e for e in events if e["event"] == "finish")
            assert fin["attrs"]["chunks"] >= 1

    def test_eviction_still_closes_the_trace(self, tmp_path, _isolated_obs):
        """A queue-expired request's trace still ends in resolve (with
        the typed error) — evictions cannot orphan a trace."""
        import json

        reg = _isolated_obs
        sink = obs.install_event_sink(str(tmp_path), flush_secs=0.05,
                                      reg=reg)
        hps = tiny_hps(serve_mode="continuous")
        engine = StubEngine(slots=2, chunk=2)
        server = ServingServer(hps, make_vocab(), decoder=StubDecoder(),
                               engine=engine, registry=reg)
        # expired before the server ever starts: refill evicts it typed
        req = make_request(hps, make_vocab(), uuid="late",
                           deadline=Deadline(time.monotonic() - 1.0),
                           registry=reg)
        server._queue.submit(req)
        with server:
            ok = server.submit("the dog ran .", uuid="ok")
            assert ok.result(timeout=30).uuid == "ok"
        with pytest.raises(DeadlineExceededError):
            req.future.result(timeout=1)
        sink.close()
        recs = [json.loads(ln)
                for ln in open(tmp_path / "events.jsonl", encoding="utf-8")]
        late = [r for r in recs if r.get("kind") == "request"
                and r["uuid"] == "late"]
        stages = [e["event"] for e in late]
        assert stages[0] == "enqueue" and stages[-1] == "resolve"
        assert "evict" in stages
        resolve = late[-1]
        assert resolve["attrs"]["error"] == "DeadlineExceededError"
        assert len({e["trace_id"] for e in late}) == 1

    def test_shed_request_emits_shed_event(self, tmp_path, _isolated_obs):
        reg = _isolated_obs
        sink = obs.install_event_sink(str(tmp_path), flush_secs=0.05,
                                      reg=reg)
        q = RequestQueue(1, registry=reg)
        q.submit(make_request(tiny_hps(), make_vocab(), uuid="first"))
        with pytest.raises(ServeOverloadError):
            q.submit(make_request(tiny_hps(), make_vocab(), uuid="second"))
        sink.close()
        import json

        recs = [json.loads(ln)
                for ln in open(tmp_path / "events.jsonl", encoding="utf-8")]
        second = [r for r in recs if r.get("kind") == "request"
                  and r["uuid"] == "second"]
        # an honest timeline: the request reached the queue and bounced
        assert [r["event"] for r in second] == ["enqueue", "shed"]
        assert second[1]["attrs"]["cause"] == "queue_full"


class TestDarkJobTracing:
    def test_disabled_registry_skips_the_trace_mint(self):
        """A dark job (obs=False / TS_OBS=0) must not pay the urandom
        mint per request: no consumer could ever read the ids."""
        from textsummarization_on_flink_tpu.obs import Registry as _Reg

        dark = _Reg(enabled=False)
        req = make_request(tiny_hps(), make_vocab(), uuid="dark",
                           registry=dark)
        assert req.trace is None and req.future.trace is None
        # and resolution still works without a trace
        req.future._resolve("ok")
        assert req.future.result(timeout=1) == "ok"
