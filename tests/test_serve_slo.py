"""The committed serving-SLO regression gate (ISSUE 6; SERVING.md
"Continuous batching").

With the TPU tunnel down, the continuous-batching claim would otherwise
sit unmeasured the way the decode p50 once did.  The claim is about
SCHEDULING — kill the micro-batch dispatch-window barrier so one long
article stops holding its neighbors hostage — so the gate runs the REAL
serving stack (ServingServer dispatch threads, RequestQueue,
MicroBatcher, ContinuousBatcher) over a deterministic VIRTUAL-TIME cost
model instead of a device:

  * a decode dispatch of d steps costs d * step_cost virtual ms, and a
    batch costs max(d_i) — exactly the device's straggler shape;
  * a continuous chunk costs chunk * step_cost regardless of occupancy;
  * every request is enqueued BEFORE the dispatch thread starts, so
    group/slot assignment is pure FIFO and the whole run is replayable.

No sleeps, no wall-clock assertions — CI load cannot flake the gate,
and the numbers in SERVE_SLO.json are exact scheduling facts with
modest headroom (see its _comment for the re-baselining rule).  The
wall-clock story at real-model scale lives in ``bench.py --serve``; the
kernel-level "no per-request recompiles" claim is pinned by
tests/test_serve.py (bounded jit cache) and tests/test_beam_search.py
(slot parity).

Enforced here, in tier-1:
  * continuous-mode p99 enqueue->resolved latency (virtual ms) stays
    under its committed ceiling on the bimodal load;
  * continuous-mode mean slot occupancy stays above its floor;
  * continuous BEATS the micro-batch baseline at equal request load on
    both p99 latency and occupancy/utilization by the committed margins;
  * exactly-once resolution holds for every request in both modes.
"""

import json
import os
import random

import pytest

from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.data.vocab import Vocab
from textsummarization_on_flink_tpu.decode.decoder import DecodedResult
from textsummarization_on_flink_tpu.obs import Registry
from textsummarization_on_flink_tpu.resilience.errors import (
    ArenaExhaustedError,
)
from textsummarization_on_flink_tpu.serve.server import ServingServer

SLO_PATH = os.path.join(os.path.dirname(__file__), "..", "SERVE_SLO.json")

WORDS = ["w"]


@pytest.fixture(scope="module")
def slo():
    with open(SLO_PATH) as f:
        return json.load(f)


def _steps_for(example, wl) -> int:
    """The virtual decode cost of one request, derived from its article
    length — the bimodal mix: short articles decode in few steps, long
    ones run to the horizon (the straggler)."""
    short = example.enc_len <= wl["short_words"]
    return wl["short_steps"] if short else wl["long_steps"]


class _NullDecoder:
    """Continuous mode drives the engine, not the decoder; only the
    between-chunk hot-swap hook is ever called."""

    def maybe_reload_checkpoint(self, last):
        return last


class SimEngine:
    """SlotDecodeEngine protocol over virtual time: each step() advances
    the shared clock by chunk * step_cost and every active slot by
    `chunk` steps.  Records each request's RESOLVE time on the virtual
    clock at unpack — enqueue is t=0 by construction (all requests are
    queued before the dispatch thread starts)."""

    def __init__(self, wl):
        self.slots = wl["slots"]
        self.chunk = wl["chunk"]
        self._wl = wl
        self._cost = wl["step_cost_ms"]
        self._remaining = [0] * self.slots
        self._active = [False] * self.slots
        self.vtime = 0.0
        self.vresolve = {}

    def pack(self, idx, example):
        assert not self._active[idx]
        self._active[idx] = True
        self._remaining[idx] = _steps_for(example, self._wl)

    def step(self):
        self.vtime += self.chunk * self._cost
        fin = []
        for i in range(self.slots):
            if self._active[i]:
                self._remaining[i] -= self.chunk
                if self._remaining[i] <= 0:
                    fin.append(i)
        return fin

    def unpack(self, idx, example):
        assert self._active[idx]
        self._active[idx] = False
        self.vresolve[example.uuid] = self.vtime
        return DecodedResult(
            uuid=example.uuid, article=example.original_article,
            decoded_words=["ok", "."], reference=example.reference,
            abstract_sents=[])

    def release(self, idx):
        self._active[idx] = False


class _Prefilled:
    """The DisaggSimEngine's prefill handle (the PrefilledArticle
    analogue): steps remaining + the bucket the encoder pass ran at +
    the article's true length for the length-masked chunk cost."""

    def __init__(self, example, steps, bucket, words):
        self.example = example
        self.steps = steps
        self.bucket = bucket
        self.words = words


class DisaggSimEngine(SimEngine):
    """The DISAGGREGATED cost model (ISSUE 11) over the same virtual
    clock, driven through the REAL ContinuousBatcher prefill queue:

      * ``prefill(example)`` — the bucketed encoder stage — costs
        bucket(words) * prefill_ms_per_word (encoder work scales with
        the article's bucket, the BYTE_BUDGET.json decode.prefill
        claim);
      * each chunk costs chunk * step_cost * max(floor,
        longest_active_words / long_words) — the length-masked decode
        (per-chunk work follows the longest ACTIVE resident's true
        length, the decode.length_axis claim; `floor` models the
        length-independent share of the step: vocab projection, beam
        bookkeeping).
    """

    def __init__(self, wl):
        super().__init__(wl)
        self._words = [0] * self.slots

    def _bucket(self, words):
        for b in self._wl["buckets"]:
            if words <= b:
                return b
        return self._wl["buckets"][-1]

    def prefill(self, example):
        bucket = self._bucket(example.enc_len)
        self.vtime += bucket * self._wl["prefill_ms_per_word"]
        return _Prefilled(example, _steps_for(example, self._wl), bucket,
                          example.enc_len)

    def pack(self, idx, pre):
        assert not self._active[idx]
        self._active[idx] = True
        self._remaining[idx] = pre.steps
        self._words[idx] = pre.words

    def step(self):
        longest = max((self._words[i] for i in range(self.slots)
                       if self._active[i]), default=0)
        frac = max(self._wl["decode_len_floor"],
                   longest / self._wl["long_words"])
        self.vtime += self.chunk * self._cost * frac
        fin = []
        for i in range(self.slots):
            if self._active[i]:
                self._remaining[i] -= self.chunk
                if self._remaining[i] <= 0:
                    fin.append(i)
        return fin


class UniformSimEngine(SimEngine):
    """The PRE-CHANGE one-resident-shape cost model: every admission
    pays the FULL-width encoder (pack cost = long_words *
    prefill_ms_per_word regardless of article length — what
    pack_slot_jit did before the prefill stage existed) and every chunk
    costs full width (no length mask).  No ``prefill`` surface, so the
    ContinuousBatcher runs its legacy direct-pack path — the baseline
    the disaggregated section's ratios are committed against."""

    def pack(self, idx, example):
        self.vtime += self._wl["long_words"] * \
            self._wl["prefill_ms_per_word"]
        super().pack(idx, example)


class SimDecoder:
    """decode_batch over the same virtual cost model: one dispatch costs
    max(d_i) * step_cost — every member of the batch, short or long,
    resolves when the SLOWEST one does (the barrier this PR removes).
    Also records per-batch utilization sum(d_i)/(B * max(d_i)): the
    fraction of slot-steps doing useful work, the honest micro-batch
    analogue of slot occupancy (batch fill alone hides the straggler
    waste)."""

    def __init__(self, wl):
        self._wl = wl
        self._cost = wl["step_cost_ms"]
        self.vtime = 0.0
        self.vresolve = {}
        self.utilizations = []

    def decode_batch(self, batch, deadline=None):
        steps = [
            _steps_for_len(int(batch.enc_lens[b]), self._wl)
            for b in range(len(batch.uuids)) if batch.real_mask[b]]
        self.vtime += max(steps) * self._cost
        self.utilizations.append(
            sum(steps) / (len(batch.real_mask) * max(steps)))
        out = []
        for b in range(len(batch.uuids)):
            if not batch.real_mask[b]:
                continue
            self.vresolve[batch.uuids[b]] = self.vtime
            out.append(DecodedResult(
                uuid=batch.uuids[b], article=batch.original_articles[b],
                decoded_words=["ok", "."], reference=batch.references[b],
                abstract_sents=[]))
        return out

    def maybe_reload_checkpoint(self, last):
        return last


def _steps_for_len(enc_len: int, wl) -> int:
    return wl["short_steps"] if enc_len <= wl["short_words"] \
        else wl["long_steps"]


def _articles(wl):
    """The seeded bimodal request mix: `requests` articles, every
    `long_every`-th one long, shuffled with the committed seed so the
    arrival order interleaves modes (a straggler lands in most
    micro-batches, like production traffic)."""
    arts = []
    for i in range(wl["requests"]):
        n = wl["long_words"] if i % wl["long_every"] == 0 \
            else wl["short_words"]
        arts.append(" ".join(["w"] * n))
    random.Random(wl["seed"]).shuffle(arts)
    return arts


def _run_mode(wl, mode):
    """Drive the full load through a real ServingServer in `mode`;
    returns (per-uuid virtual resolve times, registry, sim)."""
    vocab = Vocab(words=WORDS)
    hps = HParams(
        mode="decode", batch_size=wl["batch_size"], vocab_size=vocab.size(),
        max_enc_steps=wl["long_words"], max_dec_steps=wl["long_steps"],
        beam_size=2, min_dec_steps=1, max_oov_buckets=4,
        serve_max_queue=max(4 * wl["requests"], 64),
        serve_max_wait_ms=5.0, serve_mode=mode, serve_slots=wl["slots"],
        serve_refill_chunk=wl["chunk"])
    with obs.use_registry(Registry()) as reg:
        if mode == "continuous":
            sim = SimEngine(wl)
            server = ServingServer(hps, vocab, decoder=_NullDecoder(),
                                   engine=sim, registry=reg)
        else:
            sim = SimDecoder(wl)
            server = ServingServer(hps, vocab, decoder=sim, registry=reg)
        # enqueue EVERYTHING before the dispatch thread exists: arrival
        # order is the committed mix, group/slot assignment is pure FIFO
        futs = [server.submit(a, uuid=f"u{i}")
                for i, a in enumerate(_articles(wl))]
        server.start()
        results = [f.result(timeout=120) for f in futs]
        server.stop()
    # exactly-once, every request, in both modes
    assert [r.uuid for r in results] == \
        [f"u{i}" for i in range(wl["requests"])]
    assert set(sim.vresolve) == {f"u{i}" for i in range(wl["requests"])}
    return sim.vresolve, reg, sim


def _p99(latencies):
    xs = sorted(latencies)
    return xs[min(len(xs) - 1, int(len(xs) * 0.99))]


@pytest.fixture(scope="module")
def measured(slo):
    wl = slo["workload"]
    cont_resolve, cont_reg, _ = _run_mode(wl, "continuous")
    micro_resolve, _, micro_sim = _run_mode(wl, "microbatch")
    return {
        "cont_p99": _p99(cont_resolve.values()),
        "cont_occupancy": cont_reg.histogram("serve/slot_occupancy").mean,
        "micro_p99": _p99(micro_resolve.values()),
        "micro_utilization": (sum(micro_sim.utilizations)
                              / len(micro_sim.utilizations)),
    }


def test_continuous_p99_within_committed_ceiling(slo, measured):
    ceiling = slo["continuous"]["p99_virtual_ms_max"]
    assert measured["cont_p99"] <= ceiling, (
        f"continuous p99 rose to {measured['cont_p99']:.0f} virtual ms "
        f"(committed ceiling {ceiling:.0f}) — the slot scheduler "
        f"regressed (see SERVE_SLO.json _comment)")


def test_continuous_occupancy_above_committed_floor(slo, measured):
    floor = slo["continuous"]["occupancy_mean_min"]
    assert measured["cont_occupancy"] >= floor, (
        f"continuous mean slot occupancy fell to "
        f"{measured['cont_occupancy']:.2f} (committed floor {floor:.2f}) "
        f"— refill is not keeping slots busy")


def test_continuous_beats_microbatch_p99(slo, measured):
    ratio_max = slo["vs_microbatch"]["p99_ratio_max"]
    ratio = measured["cont_p99"] / measured["micro_p99"]
    assert ratio <= ratio_max, (
        f"continuous p99 / micro-batch p99 = {ratio:.2f} (committed max "
        f"{ratio_max:.2f}) on the bimodal load — the barrier win eroded")


def test_continuous_beats_microbatch_occupancy(slo, measured):
    adv_min = slo["vs_microbatch"]["occupancy_advantage_min"]
    adv = measured["cont_occupancy"] / measured["micro_utilization"]
    assert adv >= adv_min, (
        f"continuous occupancy / micro-batch utilization = {adv:.2f} "
        f"(committed min {adv_min:.2f}) — slot recycling no longer "
        f"recovers the straggler waste")


# -- prefill/decode disaggregation (ISSUE 11) ------------------------------
#
# Same virtual-time discipline, new claim: under the committed bimodal
# mix, DISAGGREGATION (bucketed prefill + length-masked chunks, the
# DisaggSimEngine cost model, driven through the REAL ContinuousBatcher
# prefill queue) beats the pre-change one-resident-shape cost model
# (UniformSimEngine) on SHORT-request p50 while long-request-dominated
# p99 stays pinned — short articles stop paying long articles' shapes,
# and nobody pays more.


def _run_disagg(slo, engine_cls):
    wl = dict(slo["workload"])
    wl.update(slo["disaggregated"]["workload"])
    vocab = Vocab(words=WORDS)
    hps = HParams(
        mode="decode", batch_size=wl["batch_size"], vocab_size=vocab.size(),
        max_enc_steps=wl["long_words"], max_dec_steps=wl["long_steps"],
        beam_size=2, min_dec_steps=1, max_oov_buckets=4,
        serve_max_queue=max(4 * wl["requests"], 64),
        serve_mode="continuous", serve_slots=wl["slots"],
        serve_refill_chunk=wl["chunk"],
        serve_prefill_depth=wl["prefill_depth"])
    arts = _articles(wl)
    short = {f"u{i}" for i, a in enumerate(arts)
             if len(a.split()) <= wl["short_words"]}
    with obs.use_registry(Registry()) as reg:
        sim = engine_cls(wl)
        server = ServingServer(hps, vocab, decoder=_NullDecoder(),
                               engine=sim, registry=reg)
        futs = [server.submit(a, uuid=f"u{i}") for i, a in enumerate(arts)]
        server.start()
        results = [f.result(timeout=120) for f in futs]
        server.stop()
    assert [r.uuid for r in results] == \
        [f"u{i}" for i in range(wl["requests"])]
    assert set(sim.vresolve) == {f"u{i}" for i in range(wl["requests"])}
    return sim.vresolve, short, reg


@pytest.fixture(scope="module")
def disagg_measured(slo):
    dis_resolve, short, dis_reg = _run_disagg(slo, DisaggSimEngine)
    uni_resolve, _, _ = _run_disagg(slo, UniformSimEngine)

    def p50(resolve, keys):
        xs = sorted(resolve[k] for k in keys)
        return xs[len(xs) // 2]

    return {
        "dis_short_p50": p50(dis_resolve, short),
        "uni_short_p50": p50(uni_resolve, short),
        "dis_p99": _p99(dis_resolve.values()),
        "uni_p99": _p99(uni_resolve.values()),
        "prefills": dis_reg.counter("serve/prefill_total").value,
        "prefill_bucket_mean":
            dis_reg.histogram("serve/prefill_bucket_len").mean,
        "requests": len(dis_resolve),
    }


def test_disagg_short_p50_beats_uniform_baseline(slo, disagg_measured):
    ceiling = slo["disaggregated"]["short_p50_ratio_vs_uniform_max"]
    ratio = disagg_measured["dis_short_p50"] \
        / disagg_measured["uni_short_p50"]
    assert ratio <= ceiling, (
        f"disaggregated short-request p50 / uniform-padding baseline = "
        f"{ratio:.2f} (committed max {ceiling:.2f}) on the bimodal mix — "
        f"short articles are paying long articles' shapes again "
        f"(see SERVE_SLO.json disaggregated._comment)")
    abs_ceiling = slo["disaggregated"]["short_p50_virtual_ms_max"]
    assert disagg_measured["dis_short_p50"] <= abs_ceiling, (
        f"disaggregated short-request p50 rose to "
        f"{disagg_measured['dis_short_p50']:.0f} virtual ms (committed "
        f"ceiling {abs_ceiling:.0f})")


def test_disagg_p99_stays_pinned(slo, disagg_measured):
    """The 'at fixed p99' half of the claim: the tail (long-request
    dominated) must not regress past the committed ratio — prefill
    serialization on the dispatch thread cannot be bought with tail
    latency."""
    ceiling = slo["disaggregated"]["p99_ratio_vs_uniform_max"]
    ratio = disagg_measured["dis_p99"] / disagg_measured["uni_p99"]
    assert ratio <= ceiling, (
        f"disaggregated p99 / uniform baseline p99 = {ratio:.2f} "
        f"(committed max {ceiling:.2f}) — the disaggregated path "
        f"regressed the tail")


def test_disagg_runs_through_the_real_prefill_queue(slo, disagg_measured):
    """The gate drives the REAL ContinuousBatcher: every request went
    through the prefill stage exactly once, and the mean prefill bucket
    sits strictly below the top bucket (short articles really routed to
    short encoder shapes)."""
    wl = dict(slo["workload"])
    wl.update(slo["disaggregated"]["workload"])
    assert disagg_measured["prefills"] == disagg_measured["requests"]
    assert disagg_measured["prefill_bucket_mean"] < wl["long_words"]


# -- paged resident state (ISSUE 20) ---------------------------------------
#
# Memory-capped comparison under the same virtual cost model and bimodal
# mix: a FIXED page budget (paged.workload.arena_pages) either
# provisions dense worst-case slots (arena_pages // pages_per_long
# residents — the pre-change rule: every slot permanently holds a
# full-length article's state) or backs a block-granular arena serving
# `paged_slots` slots admitted by FREE PAGES (the ISSUE 20 engine,
# driven through the REAL ContinuousBatcher's arena admission).  The
# committed claim: at the same memory, the paged run holds >=
# resident_advantage_min x the dense mean resident count AND resolves
# the load with LOWER p99 — capacity bought with paging, not latency
# bought with memory.  The arena is deliberately sized so the mix
# cannot always fit (paged_slots x pages_per_long > arena_pages), so
# the run also proves the backpressure contract end-to-end: allocation
# failures are counted and REQUEUED (exactly-once resolution still
# asserted for all requests), and the arena drains to zero in-use pages
# once the load completes.


class PagedSimEngine(DisaggSimEngine):
    """DisaggSimEngine + the ISSUE 20 arena surface (``paged``,
    ``pages_needed``/``free_pages``/``arena_stats``): pack allocates
    ceil(words / page_words) pages, harvest/release frees them.  pack
    raises the typed ArenaExhaustedError on shortfall — the batcher's
    proactive free-page admission should make that unreachable, and the
    SLO run asserts it stays that way (requeues happen at the admission
    check, never as a failed pack)."""

    paged = True

    def __init__(self, wl):
        super().__init__(wl)
        self._capacity = wl["arena_pages"]
        self._page_words = wl["page_words"]
        self._slot_pages = [0] * self.slots
        self._in_use = 0
        self.pack_shortfalls = 0

    def _pages(self, words: int) -> int:
        return max(1, -(-int(words) // self._page_words))

    def pages_needed(self, pre) -> int:
        return self._pages(pre.example.enc_len)

    def free_pages(self) -> int:
        return self._capacity - self._in_use

    def arena_stats(self):
        return {"capacity": self._capacity, "free": self.free_pages(),
                "in_use": self._in_use,
                "fill": self._in_use / self._capacity}

    def pack(self, idx, pre):
        need = self._pages(pre.words)
        if need > self.free_pages():
            self.pack_shortfalls += 1
            raise ArenaExhaustedError(
                f"sim arena exhausted: need {need}, "
                f"free {self.free_pages()}",
                needed=need, free=self.free_pages())
        self._in_use += need
        self._slot_pages[idx] = need
        super().pack(idx, pre)

    def _free_slot_pages(self, idx):
        self._in_use -= self._slot_pages[idx]
        self._slot_pages[idx] = 0

    def unpack(self, idx, example):
        res = super().unpack(idx, example)
        self._free_slot_pages(idx)
        return res

    def release(self, idx):
        super().release(idx)
        self._free_slot_pages(idx)


def _run_paged(slo, paged: bool):
    """Drive the bimodal load at a fixed page budget: paged=False is
    the dense memory-equivalent (arena_pages // pages_per_long worst-
    case slots, no arena surface), paged=True the block-granular arena
    at paged_slots.  Returns (vresolve, registry, sim, slots)."""
    wl = dict(slo["workload"])
    wl.update(slo["disaggregated"]["workload"])
    wl.update(slo["paged"]["workload"])
    pages_per_long = -(-wl["long_words"] // wl["page_words"])
    slots = wl["paged_slots"] if paged \
        else wl["arena_pages"] // pages_per_long
    wl["slots"] = slots
    vocab = Vocab(words=WORDS)
    hps = HParams(
        mode="decode", batch_size=wl["batch_size"], vocab_size=vocab.size(),
        max_enc_steps=wl["long_words"], max_dec_steps=wl["long_steps"],
        beam_size=2, min_dec_steps=1, max_oov_buckets=4,
        serve_max_queue=max(4 * wl["requests"], 64),
        serve_mode="continuous", serve_slots=slots,
        serve_refill_chunk=wl["chunk"],
        serve_prefill_depth=wl["prefill_depth"])
    arts = _articles(wl)
    with obs.use_registry(Registry()) as reg:
        sim = (PagedSimEngine if paged else DisaggSimEngine)(wl)
        server = ServingServer(hps, vocab, decoder=_NullDecoder(),
                               engine=sim, registry=reg)
        futs = [server.submit(a, uuid=f"u{i}") for i, a in enumerate(arts)]
        server.start()
        results = [f.result(timeout=120) for f in futs]
        server.stop()
    # exactly-once under backpressure: every requeued admission still
    # resolves, once, with its own uuid
    assert [r.uuid for r in results] == \
        [f"u{i}" for i in range(wl["requests"])]
    assert set(sim.vresolve) == {f"u{i}" for i in range(wl["requests"])}
    return sim.vresolve, reg, sim, slots


@pytest.fixture(scope="module")
def paged_measured(slo):
    paged_resolve, paged_reg, paged_sim, paged_slots = _run_paged(slo, True)
    dense_resolve, dense_reg, _, dense_slots = _run_paged(slo, False)
    paged_occ = paged_reg.histogram("serve/slot_occupancy")
    dense_occ = dense_reg.histogram("serve/slot_occupancy")
    return {
        "paged_p99": _p99(paged_resolve.values()),
        "dense_p99": _p99(dense_resolve.values()),
        "paged_peak_residents": paged_occ.percentile(100) * paged_slots,
        "dense_peak_residents": dense_occ.percentile(100) * dense_slots,
        "paged_mean_residents": paged_occ.mean * paged_slots,
        "dense_mean_residents": dense_occ.mean * dense_slots,
        "alloc_failures":
            paged_reg.counter("serve/arena_alloc_failures_total").value,
        "fill_observations": paged_reg.histogram("serve/arena_fill").count,
        "peak_fill": paged_reg.histogram("serve/arena_fill").percentile(100),
        "pack_shortfalls": paged_sim.pack_shortfalls,
        "final_in_use": paged_sim.arena_stats()["in_use"],
    }


def test_paged_resident_advantage_at_fixed_memory(slo, paged_measured):
    """The capacity claim, both edges: the arena actually REACHES >=
    resident_advantage_min x the dense resident ceiling at the same
    page budget (peak concurrent residents — memory the dense layout
    simply cannot hold), and holds the advantage on the run's MEAN
    (drain tail included) above its own floor."""
    floor = slo["paged"]["resident_advantage_min"]
    adv = paged_measured["paged_peak_residents"] \
        / paged_measured["dense_peak_residents"]
    assert adv >= floor, (
        f"paged peak residents / dense peak residents = {adv:.2f} at the "
        f"same page budget (committed min {floor:.2f}) — the arena is no "
        f"longer converting block granularity into resident capacity "
        f"(see SERVE_SLO.json paged._comment)")
    mean_floor = slo["paged"]["mean_resident_advantage_min"]
    mean_adv = paged_measured["paged_mean_residents"] \
        / paged_measured["dense_mean_residents"]
    assert mean_adv >= mean_floor, (
        f"paged mean residents / dense mean residents = {mean_adv:.2f} "
        f"(committed min {mean_floor:.2f}) — the peak is reached but not "
        f"held across the run")


def test_paged_p99_beats_dense_at_fixed_memory(slo, paged_measured):
    ceiling = slo["paged"]["p99_ratio_vs_dense_max"]
    ratio = paged_measured["paged_p99"] / paged_measured["dense_p99"]
    assert ratio <= ceiling, (
        f"paged p99 / dense-memory-equivalent p99 = {ratio:.2f} "
        f"(committed max {ceiling:.2f}) — the extra residents are no "
        f"longer buying latency on the bimodal mix")


def test_paged_backpressure_requeues_and_drains(slo, paged_measured):
    """The arena is sized so the mix cannot always fit: the committed
    minimum of admission-blocked events must fire (each one a REQUEUE —
    exactly-once is asserted inside the run), pack itself must never
    see a shortfall (the proactive admission check catches them all),
    the fill series must be lit with a full-arena episode observed, and
    the arena must drain to zero once the load completes (no leaked
    pages across harvest/recycle churn)."""
    assert paged_measured["alloc_failures"] >= \
        slo["paged"]["min_backpressure_events"]
    assert paged_measured["pack_shortfalls"] == 0
    assert paged_measured["fill_observations"] > 0
    assert paged_measured["peak_fill"] >= \
        slo["paged"]["min_peak_arena_fill"]
    assert paged_measured["final_in_use"] == 0


# -- elastic serving fleet (ISSUE 13) --------------------------------------
#
# Fleet-level virtual time: the REAL FleetRouter + ServingServers +
# ContinuousBatchers, driven single-threaded over a shared round clock
# (one round = every live replica ticks once, in parallel; the clock
# advances chunk * step_cost_ms per round) with deterministic arrivals.
# Routing decisions, hedge timing, the rolling-swap state machine, and
# the replica-kill requeue path are all exact scheduling facts — see
# SERVE_SLO.json "fleet" _comment for the committed scenarios.


class _VClock:
    """The fleet's shared virtual clock, advanced by the round driver
    (replicas run concurrently, so ONE advance per round, not one per
    replica tick)."""

    def __init__(self):
        self.ms = 0.0

    def now(self) -> float:  # seconds, the router's clock unit
        return self.ms / 1000.0


class FleetSimEngine:
    """SlotDecodeEngine-protocol sim over the SHARED fleet clock.
    ``speed`` < 1 models a degraded replica (the hedge scenario's
    straggler source): its residents advance speed * chunk steps per
    round while healthy neighbors advance the full chunk."""

    def __init__(self, wl, vclock, speed: float = 1.0):
        self.slots = wl["slots"]
        self.chunk = wl["chunk"]
        self.speed = speed
        self._wl = wl
        self._vclock = vclock
        self._remaining = [0.0] * self.slots
        self._active = [False] * self.slots
        self.vresolve = {}

    def pack(self, idx, example):
        assert not self._active[idx]
        self._active[idx] = True
        self._remaining[idx] = _steps_for(example, self._wl)

    def step(self):
        fin = []
        for i in range(self.slots):
            if self._active[i]:
                self._remaining[i] -= self.chunk * self.speed
                if self._remaining[i] <= 0:
                    fin.append(i)
        return fin

    def unpack(self, idx, example):
        assert self._active[idx]
        self._active[idx] = False
        # first-wins: a hedged uuid may unpack on two replicas; the
        # caller observed the EARLIER one
        prev = self.vresolve.get(example.uuid)
        if prev is None or self._vclock.ms < prev:
            self.vresolve[example.uuid] = self._vclock.ms
        return DecodedResult(
            uuid=example.uuid, article=example.original_article,
            decoded_words=["ok", "."], reference=example.reference,
            abstract_sents=[])

    def release(self, idx):
        self._active[idx] = False


def _run_fleet(slo, swap: bool = False, kill: bool = False,
               slow: bool = False):
    """Drive the committed fleet workload through the REAL router;
    returns (per-uuid virtual resolve times, fleet registry, captured
    request events, results)."""
    from textsummarization_on_flink_tpu.obs.export import MemorySink
    from textsummarization_on_flink_tpu.serve.fleet import FleetRouter

    wl = slo["fleet"]["workload"]
    vocab = Vocab(words=WORDS)
    vclock = _VClock()
    hps = HParams(
        mode="decode", batch_size=wl["slots"], vocab_size=vocab.size(),
        max_enc_steps=wl["long_words"], max_dec_steps=wl["long_steps"],
        beam_size=2, min_dec_steps=1, max_oov_buckets=4,
        serve_max_queue=max(4 * wl["requests"], 64),
        serve_mode="continuous", serve_slots=wl["slots"],
        serve_refill_chunk=wl["chunk"],
        serve_hedge_ms=wl["hedge_ms"],
        serve_hedge_max_ratio=wl["hedge_max_ratio"])
    fleet_reg = Registry()
    sink = MemorySink()
    fleet_reg.event_sink = sink
    servers, engines = [], []
    for r in range(wl["replicas"]):
        eng = FleetSimEngine(
            wl, vclock,
            speed=wl["slow_factor"] if (slow and r == 0) else 1.0)
        servers.append(ServingServer(
            hps, vocab, decoder=_NullDecoder(), engine=eng,
            registry=Registry()))
        engines.append(eng)
    router = FleetRouter(servers, hps, registry=fleet_reg,
                         clock=vclock.now)
    arts = _articles({**slo["workload"], **wl})
    futs, i, rounds = [], 0, 0
    while True:
        rounds += 1
        assert rounds < 5000, "fleet virtual run did not converge"
        for _ in range(wl["arrive_per_round"]):
            if i < len(arts):
                futs.append(router.submit(arts[i], uuid=f"u{i}"))
                i += 1
        if kill and rounds == wl["kill_round"]:
            alive = [h for h in router.replicas() if not h.killed]
            victim = max(alive, key=lambda h: h.load())
            assert victim.server.load() > 0, \
                "kill scenario must catch the victim mid-decode"
            router.kill_replica(victim.rid)
        if swap and rounds == wl["swap_start_round"] \
                and not router.swap_active() \
                and not fleet_reg.counter("serve/fleet_swaps_total").value:
            router.start_rolling_swap()
        router.tick()
        for srv, h in zip(servers, router.replicas()):
            if not h.killed:
                srv.tick_once(poll=0.0)
        vclock.ms += wl["chunk"] * wl["step_cost_ms"]
        if i >= len(arts) and all(f.done() for f in futs) \
                and not router.swap_active():
            break
    results = [f.result(timeout=0) for f in futs]
    router.stop()
    # exactly-once, fleet-level: one result per admitted uuid, in order
    assert [r.uuid for r in results] == \
        [f"u{k}" for k in range(wl["requests"])]
    resolve = {}
    for eng in engines:
        for u, t in eng.vresolve.items():
            resolve[u] = min(resolve.get(u, t), t)
    assert set(resolve) == {f"u{k}" for k in range(wl["requests"])}
    events = [r for r in sink.records() if r.get("kind") == "request"]
    return resolve, fleet_reg, events, results


@pytest.fixture(scope="module")
def fleet_measured(slo):
    steady_resolve, steady_reg, _, _ = _run_fleet(slo)
    swap_resolve, swap_reg, _, _ = _run_fleet(slo, swap=True)
    return {
        "steady_p99": _p99(steady_resolve.values()),
        "swap_p99": _p99(swap_resolve.values()),
        "swaps": swap_reg.counter("serve/fleet_swaps_total").value,
    }


def test_fleet_steady_p99_within_committed_ceiling(slo, fleet_measured):
    ceiling = slo["fleet"]["steady_p99_virtual_ms_max"]
    assert fleet_measured["steady_p99"] <= ceiling, (
        f"fleet steady-state p99 rose to {fleet_measured['steady_p99']:.0f}"
        f" virtual ms (committed ceiling {ceiling:.0f}) — routing or the "
        f"round scheduler regressed (see SERVE_SLO.json fleet._comment)")


def test_fleet_rolling_swap_p99_within_committed_ratio(slo, fleet_measured):
    """The upgrade tax: a replica-at-a-time drain -> hot-swap -> readmit
    pass must not cost the fleet more than the committed p99 ratio over
    steady state — and the swap must actually visit every replica."""
    ratio_max = slo["fleet"]["swap_p99_ratio_max"]
    ratio = fleet_measured["swap_p99"] / fleet_measured["steady_p99"]
    assert ratio <= ratio_max, (
        f"fleet p99 under rolling swap / steady-state p99 = {ratio:.2f} "
        f"(committed max {ratio_max:.2f}) — draining one replica at a "
        f"time is costing more than the committed upgrade tax")
    assert fleet_measured["swaps"] == slo["fleet"]["swap_count_expected"], (
        f"rolling swap completed {fleet_measured['swaps']:.0f} of "
        f"{slo['fleet']['swap_count_expected']} replica hot-swaps")


def test_fleet_hedge_wins_counted_and_rate_capped(slo):
    """Hedging must PAY (a degraded replica's stragglers resolve from
    their hedge twins) and must stay CAPPED (a hedge is a purchased
    duplicate; spend rides the committed serve_hedge_max_ratio
    ceiling)."""
    _, reg, _, _ = _run_fleet(slo, slow=True)
    hedges = reg.counter("serve/hedges_total").value
    wins = reg.counter("serve/hedge_wins_total").value
    submitted = reg.counter("serve/fleet_submitted_total").value
    assert wins >= slo["fleet"]["hedge_wins_min"], (
        f"only {wins:.0f} hedge wins against the slow replica (committed "
        f"min {slo['fleet']['hedge_wins_min']}) — hedging stopped paying")
    assert hedges >= wins, "a hedge win without a hedge is an accounting bug"
    rate = hedges / submitted
    assert rate <= slo["fleet"]["hedge_rate_max"], (
        f"hedge rate {rate:.3f} exceeds the committed ceiling "
        f"{slo['fleet']['hedge_rate_max']} — the waste cap broke")


# -- the production front door (ISSUE 14) ----------------------------------
#
# Same virtual-time discipline, front-door claims: under a ZIPF request
# mix (the heavy-tailed trending-article shape) the coalescing map and
# the summary cache cut served decodes far below submitted requests at
# a p99 no worse than the uncached baseline, every coalesced/cached
# future resolves exactly once, and the per-tenant token bucket +
# weighted-fair pickup isolate a victim tenant from an attacker
# flooding at 10x its admitted rate.  All three scenarios drive the
# REAL RequestQueue/ContinuousBatcher/ServingServer (and, in the fleet
# scenario, the REAL FleetRouter) — the front door is the only new
# layer in the path.


def _zipf_indices(n: int, pool: int, s: float, seed: int):
    """Deterministic zipf-ish draw: p(k) ~ 1/(k+1)^s over `pool` ranks
    (inverse-CDF over a seeded uniform stream — no numpy, exactly
    replayable)."""
    weights = [1.0 / (k + 1) ** s for k in range(pool)]
    total = sum(weights)
    r = random.Random(seed)
    out = []
    for _ in range(n):
        x = r.random() * total
        acc = 0.0
        pick = pool - 1
        for k, w in enumerate(weights):
            acc += w
            if x <= acc:
                pick = k
                break
        out.append(pick)
    return out


def _door_articles(wl):
    """The zipf article pool: `pool` DISTINCT articles (distinct lead
    token -> distinct content hash), every long_every-th one long."""
    arts = []
    for k in range(wl["pool"]):
        n = wl["long_words"] if k % wl["long_every"] == 0 \
            else wl["short_words"]
        arts.append(f"a{k} " + " ".join(["w"] * (n - 1)))
    return arts


class CountingSimEngine(SimEngine):
    """SimEngine + the decode count the front-door ratio gates on
    (packs == decodes actually served by the engine)."""

    def __init__(self, wl):
        super().__init__(wl)
        self.pack_count = 0

    def pack(self, idx, example):
        super().pack(idx, example)
        self.pack_count += 1


def _run_front_door(slo, door: bool):
    """Drive the zipf mix through a real continuous ServingServer with
    the front door armed (`door`) or off (the uncached baseline);
    returns (per-uuid resolve vtimes, registry, engine, hit count)."""
    wl = {**slo["workload"], **slo["front_door"]["workload"]}
    vocab = Vocab(words=WORDS)
    hps = HParams(
        mode="decode", batch_size=wl["slots"], vocab_size=vocab.size(),
        max_enc_steps=wl["long_words"], max_dec_steps=wl["long_steps"],
        beam_size=2, min_dec_steps=1, max_oov_buckets=4,
        serve_max_queue=max(4 * wl["requests"], 64),
        serve_mode="continuous", serve_slots=wl["slots"],
        serve_refill_chunk=wl["chunk"],
        serve_coalesce=door,
        serve_cache_entries=wl["cache_entries"] if door else 0)
    arts = _door_articles(wl)
    order = _zipf_indices(wl["requests"], wl["pool"], wl["zipf_s"],
                          wl["seed"])
    with obs.use_registry(Registry()) as reg:
        sim = CountingSimEngine(wl)
        server = ServingServer(hps, vocab, decoder=_NullDecoder(),
                               engine=sim, registry=reg)
        resolve_v: dict = {}

        def submit(uid, art):
            fut = server.submit(art, uuid=uid)
            fut.add_done_callback(
                lambda f, u=uid: resolve_v.setdefault(u, sim.vtime))
            return fut

        # wave 1: the whole zipf mix enqueued BEFORE the dispatch
        # thread starts (arrival order committed; duplicates coalesce
        # onto the one queued leader per distinct article)
        futs = [submit(f"u{i}", arts[k]) for i, k in enumerate(order)]
        server.start()
        results = [f.result(timeout=120) for f in futs]
        # exactly-once, every submit — followers included
        assert [r.uuid for r in results] == \
            [f"u{i}" for i in range(wl["requests"])]
        assert set(resolve_v) == {f"u{i}" for i in range(wl["requests"])}
        hits0 = reg.counter("serve/cache_hits_total").value
        if door:
            # wave 2: the same mix again, against a now-warm cache —
            # every request resolves synchronously at submit, zero new
            # decodes (the dispatch thread is idle and stays idle)
            packs0 = sim.pack_count
            futs2 = [submit(f"w{i}", arts[k]) for i, k in enumerate(order)]
            res2 = [f.result(timeout=10) for f in futs2]
            assert [r.uuid for r in res2] == \
                [f"w{i}" for i in range(wl["requests"])]
            assert sim.pack_count == packs0, \
                "a warm-cache wave must not decode"
            assert reg.counter("serve/cache_hits_total").value \
                == hits0 + wl["requests"]
            # a cached summary is the leader's payload verbatim: every
            # duplicate of article k carries identical decoded words
            by_article: dict = {}
            for i, k in enumerate(order):
                by_article.setdefault(k, set()).add(
                    " ".join(res2[i].decoded_words))
            assert all(len(v) == 1 for v in by_article.values())
        server.stop()
    return resolve_v, reg, sim, hits0


@pytest.fixture(scope="module")
def front_door_measured(slo):
    on_resolve, on_reg, on_sim, _ = _run_front_door(slo, door=True)
    off_resolve, _, off_sim, _ = _run_front_door(slo, door=False)
    wl = {**slo["workload"], **slo["front_door"]["workload"]}
    return {
        "decodes_on": on_sim.pack_count,
        "decodes_off": off_sim.pack_count,
        "coalesced": on_reg.counter("serve/coalesced_total").value,
        "p99_on": _p99(on_resolve.values()),
        "p99_off": _p99(off_resolve.values()),
        "requests": wl["requests"],
    }


def test_front_door_decodes_per_submit_under_ceiling(slo,
                                                     front_door_measured):
    """The FastSeq claim, gated: under the committed zipf mix the
    coalescing map alone holds served decodes at the DISTINCT-article
    count — far under the committed <= 0.5x submitted ceiling — while
    the uncached baseline decodes every submit."""
    m = front_door_measured
    ceiling = slo["front_door"]["decodes_per_submit_max"]
    ratio = m["decodes_on"] / m["requests"]
    assert ratio <= ceiling, (
        f"front door served {m['decodes_on']} decodes for "
        f"{m['requests']} submits (ratio {ratio:.2f}, committed max "
        f"{ceiling}) — coalescing/caching stopped deduplicating")
    assert m["decodes_off"] == m["requests"], \
        "the uncached baseline must decode every submit"
    assert m["coalesced"] >= m["requests"] - m["decodes_on"] - \
        slo["front_door"]["workload"]["pool"]


def test_front_door_p99_no_worse_than_uncached(slo, front_door_measured):
    """'Never doing redundant work' must not be bought with tail
    latency: zipf-mix p99 with the door armed stays within the
    committed ratio of the uncached baseline (< 1 in practice — fewer
    decodes drain the slots sooner)."""
    m = front_door_measured
    ratio_max = slo["front_door"]["p99_ratio_vs_uncached_max"]
    ratio = m["p99_on"] / m["p99_off"]
    assert ratio <= ratio_max, (
        f"front-door p99 / uncached p99 = {ratio:.2f} (committed max "
        f"{ratio_max:.2f}) on the zipf mix — the door is adding tail "
        f"latency instead of removing work")


def _run_tenants(slo, attacker: bool):
    """The cross-tenant isolation scenario, tick-driven (no threads):
    a victim tenant trickles short articles while an attacker floods at
    10x its admitted rate; the per-tenant token bucket sheds the excess
    typed BEFORE the queue and weighted-fair pickup keeps the victim's
    latency flat.  Returns (victim latencies vms, sheds, registry)."""
    from textsummarization_on_flink_tpu.serve.errors import (
        TenantThrottledError,
    )

    wl = {**slo["workload"], **slo["front_door"]["tenants"]}
    vocab = Vocab(words=WORDS)
    vclock = _VClock()
    hps = HParams(
        mode="decode", batch_size=wl["slots"], vocab_size=vocab.size(),
        max_enc_steps=wl["long_words"], max_dec_steps=wl["long_steps"],
        beam_size=2, min_dec_steps=1, max_oov_buckets=4,
        serve_max_queue=wl["queue"],
        serve_mode="continuous", serve_slots=wl["slots"],
        serve_refill_chunk=wl["chunk"],
        serve_tenant_rate=wl["tenant_rate"],
        serve_tenant_burst=wl["tenant_burst"],
        serve_fair_weights=wl["fair_weights"])
    with obs.use_registry(Registry()) as reg:
        sim = CountingSimEngine(wl)
        server = ServingServer(hps, vocab, decoder=_NullDecoder(),
                               engine=sim, registry=reg, clock=vclock.now)
        submit_v: dict = {}
        resolve_v: dict = {}
        victim_futs = []
        sheds = 0
        n_v = 0

        def track(fut, uid):
            fut.add_done_callback(
                lambda f, u=uid: resolve_v.setdefault(u, sim.vtime))

        for rnd in range(wl["rounds"]):
            if rnd % wl["victim_every"] == 0:
                uid = f"v{n_v}"
                n_v += 1
                art = f"{uid} " + " ".join(["w"] * (wl["short_words"] - 1))
                fut = server.submit(art, uuid=uid, tenant="victim")
                submit_v[uid] = sim.vtime
                track(fut, uid)
                victim_futs.append((uid, fut))
            if attacker:
                for j in range(wl["attacker_per_round"]):
                    uid = f"x{rnd}_{j}"
                    art = f"{uid} " + \
                        " ".join(["w"] * (wl["short_words"] - 1))
                    try:
                        server.submit(art, uuid=uid, tenant="attacker")
                    except TenantThrottledError:
                        sheds += 1  # the typed outcome: shed at the door
            server.tick_once(poll=0.0)
            vclock.ms += wl["chunk"] * wl["step_cost_ms"]
        # drain: every admitted request must still resolve exactly once
        for _ in range(1000):
            if all(f.done() for _, f in victim_futs):
                break
            server.tick_once(poll=0.0)
            vclock.ms += wl["chunk"] * wl["step_cost_ms"]
        results = [f.result(timeout=0) for _, f in victim_futs]
        server.stop()
    assert [r.uuid for r in results] == [u for u, _ in victim_futs]
    lat = [resolve_v[u] - submit_v[u] for u, _ in victim_futs]
    return lat, sheds, reg


@pytest.fixture(scope="module")
def tenants_measured(slo):
    flood_lat, sheds, flood_reg = _run_tenants(slo, attacker=True)
    solo_lat, _, _ = _run_tenants(slo, attacker=False)
    return {
        "victim_p99_flood": _p99(flood_lat),
        "victim_p99_solo": _p99(solo_lat),
        "sheds": sheds,
        "shed_total": flood_reg.counter("serve/tenant_shed_total").value,
    }


def test_tenant_isolation_victim_p99_flat(slo, tenants_measured):
    """The cross-tenant isolation gate (ISSUE 14 acceptance): with an
    attacker tenant flooding at 10x its admitted rate, the victim
    tenant's p99 stays within the committed ratio of its
    attacker-free steady state."""
    m = tenants_measured
    ratio_max = slo["front_door"]["tenants"]["victim_p99_ratio_max"]
    ratio = m["victim_p99_flood"] / max(m["victim_p99_solo"], 1e-9)
    assert ratio <= ratio_max, (
        f"victim p99 under attacker flood = {m['victim_p99_flood']:.0f} "
        f"vms vs {m['victim_p99_solo']:.0f} steady (ratio {ratio:.2f}, "
        f"committed max {ratio_max}) — tenant isolation broke")


def test_tenant_flood_shed_typed_at_the_door(slo, tenants_measured):
    """The attacker's excess is shed TYPED by its own token bucket
    (TenantThrottledError, counted in serve/tenant_shed_total) before
    ever touching the shared queue — the victim spends nothing on it."""
    m = tenants_measured
    floor = slo["front_door"]["tenants"]["sheds_min"]
    assert m["sheds"] >= floor, (
        f"only {m['sheds']} attacker submits shed (committed min "
        f"{floor}) — the token bucket is not metering the flood")
    assert m["shed_total"] == m["sheds"]


class CountingFleetSimEngine(FleetSimEngine):
    """FleetSimEngine + pack counting for the fleet front-door ratio."""

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self.pack_count = 0

    def pack(self, idx, example):
        super().pack(idx, example)
        self.pack_count += 1


def _run_fleet_door(slo, kill: bool):
    """The zipf mix through the REAL FleetRouter with the front door
    armed at the ROUTER (replica doors disarmed by construction) —
    coalescing dedups ACROSS replicas, and a replica killed mid-
    coalesced-flight requeues the LEADER while every attached follower
    still resolves exactly once from whichever replica wins."""
    from textsummarization_on_flink_tpu.serve.fleet import FleetRouter

    wl = {**slo["fleet"]["workload"], **slo["front_door"]["fleet"]}
    vocab = Vocab(words=WORDS)
    vclock = _VClock()
    hps = HParams(
        mode="decode", batch_size=wl["slots"], vocab_size=vocab.size(),
        max_enc_steps=wl["long_words"], max_dec_steps=wl["long_steps"],
        beam_size=2, min_dec_steps=1, max_oov_buckets=4,
        serve_max_queue=max(4 * wl["requests"], 64),
        serve_mode="continuous", serve_slots=wl["slots"],
        serve_refill_chunk=wl["chunk"],
        serve_hedge_ms=wl["hedge_ms"],
        serve_hedge_max_ratio=wl["hedge_max_ratio"],
        serve_coalesce=True, serve_cache_entries=wl["cache_entries"])
    fleet_reg = Registry()
    servers, engines = [], []
    for _ in range(wl["replicas"]):
        eng = CountingFleetSimEngine(wl, vclock)
        servers.append(ServingServer(
            hps, vocab, decoder=_NullDecoder(), engine=eng,
            registry=Registry()))
        engines.append(eng)
    router = FleetRouter(servers, hps, registry=fleet_reg,
                         clock=vclock.now)
    arts = _door_articles(wl)
    order = _zipf_indices(wl["requests"], wl["pool"], wl["zipf_s"],
                          wl["seed"])
    futs, i, rounds = [], 0, 0
    while True:
        rounds += 1
        assert rounds < 5000, "fleet front-door run did not converge"
        for _ in range(wl["arrive_per_round"]):
            if i < len(order):
                futs.append(router.submit(arts[order[i]], uuid=f"u{i}"))
                i += 1
        if kill and rounds == wl["kill_round"]:
            alive = [h for h in router.replicas() if not h.killed]
            victim = max(alive, key=lambda h: h.load())
            assert victim.server.load() > 0, \
                "kill must catch the victim mid-decode"
            router.kill_replica(victim.rid)
        router.tick()
        for srv, h in zip(servers, router.replicas()):
            if not h.killed:
                srv.tick_once(poll=0.0)
        vclock.ms += wl["chunk"] * wl["step_cost_ms"]
        if i >= len(order) and all(f.done() for f in futs):
            break
    results = [f.result(timeout=0) for f in futs]
    router.stop()
    # fleet-level exactly-once: one RESULT per submitted uuid —
    # leaders, followers, and cache hits alike, kill or no kill
    assert [r.uuid for r in results] == \
        [f"u{k}" for k in range(wl["requests"])]
    decodes = sum(e.pack_count for e in engines)
    return results, fleet_reg, decodes, order


@pytest.fixture(scope="module")
def fleet_door_measured(slo):
    _, reg, decodes, order = _run_fleet_door(slo, kill=False)
    return {
        "decodes": decodes,
        "requests": len(order),
        "coalesced": reg.counter("serve/coalesced_total").value,
        "hits": reg.counter("serve/cache_hits_total").value,
    }


def test_fleet_front_door_dedups_across_replicas(slo, fleet_door_measured):
    """The router-level door is the fleet's ONE dedup point: served
    decodes across ALL replicas stay under the committed ratio, with
    the dedup split between in-flight coalescing and cache hits."""
    m = fleet_door_measured
    ceiling = slo["front_door"]["fleet"]["decodes_per_submit_max"]
    ratio = m["decodes"] / m["requests"]
    assert ratio <= ceiling, (
        f"fleet served {m['decodes']} decodes for {m['requests']} "
        f"submits (ratio {ratio:.2f}, committed max {ceiling}) — "
        f"cross-replica dedup regressed")
    assert m["coalesced"] + m["hits"] >= m["requests"] - m["decodes"]


def test_fleet_front_door_kill_keeps_followers_exactly_once(slo):
    """The chaos composition (ISSUE 14 satellite): serve.replica_kill
    mid-coalesced-flight requeues the LEADER on a survivor and every
    attached follower still resolves exactly once with a RESULT — the
    follower futures ride the router-level leader future, which is
    exactly what the requeue path settles."""
    results, reg, decodes, order = _run_fleet_door(slo, kill=True)
    assert reg.counter("serve/replica_kills_total").value == 1
    assert reg.counter("serve/requeued_total").value >= 1, \
        "the kill landed on an idle replica — not a mid-flight test"
    assert reg.counter("serve/coalesced_total").value >= 1, \
        "no coalesced flight was in the air at the kill"
    assert len({r.uuid for r in results}) == len(order)


def test_fleet_replica_kill_exactly_once_with_requeue(slo):
    """The chaos gate (ISSUE 13 acceptance): a replica killed mid-decode
    under load -> every admitted request still resolves exactly once
    with a RESULT (no lost futures, no double resolution, no
    caller-visible errors), the orphans re-enqueued on survivors through
    the typed path and tagged with `requeued` trace events."""
    resolve, reg, events, results = _run_fleet(slo, kill=True)
    wl = slo["fleet"]["workload"]
    assert reg.counter("serve/replica_kills_total").value == 1
    requeued = reg.counter("serve/requeued_total").value
    assert requeued >= slo["fleet"]["kill_requeued_min"], (
        f"replica death orphaned no requests ({requeued:.0f} requeued) — "
        f"the kill landed on an idle replica, not mid-decode")
    # every requeued request is tagged in the trace stream with the
    # corpse it left and the survivor it landed on
    tags = [e for e in events if e.get("event") == "requeued"]
    assert len(tags) == int(requeued)
    for e in tags:
        assert e["attrs"]["from_replica"] != e["attrs"]["to_replica"]
        assert e["attrs"]["cause"] == "ReplicaKilledError"
    # no admitted request saw the failure: all resolved with results
    assert len(results) == wl["requests"]
    assert len({r.uuid for r in results}) == wl["requests"]


# ---------------------------------------------------------------------------
# Process fleet (ISSUE 17; SERVING.md "Process fleet").  The socket
# transport's costs are BYTE facts, not scheduling facts, so there is
# no virtual clock: the gate prices them analytically off the REAL
# codecs — Message.to_json() frames as the supervisor sends them, reply
# frames out of the real _ReplyHub publish path (seq stamping
# included), and the real obs.http.health() payload at the
# serve_scrape_interval_ms cadence.  Pure construction + arithmetic;
# see SERVE_SLO.json process_fleet._comment for the committed numbers.


def _proc_fleet_requests(wl):
    def words(n, tag):
        return " ".join(f"{tag}{i}" for i in range(n)) + " ."

    reqs = []
    for i in range(wl["requests"]):
        long = (i % wl["long_every"]) == wl["long_every"] - 1
        art = words(wl["long_words"] if long else wl["short_words"], "w")
        reqs.append((f"uuid-{i:04d}", art, f"reference {i} ."))
    return reqs, words(wl["summary_words"], "s")


@pytest.fixture(scope="module")
def proc_fleet_measured(slo):
    from textsummarization_on_flink_tpu.pipeline.io import Message
    from textsummarization_on_flink_tpu.serve import procfleet

    wl = slo["process_fleet"]["workload"]
    reqs, summary = _proc_fleet_requests(wl)
    # ingress: the exact frame RemoteReplica.submit writes (+ newline)
    ingress = [len(Message(u, a, r).to_json().encode()) + 1
               for u, a, r in reqs]
    # reply: through the real hub so the seq envelope is priced too
    hub = procfleet._ReplyHub()
    for u, a, r in reqs:
        hub.publish(Message(u, a, summary=summary, reference=r))
    hub.close()
    reply = [len(frame.encode()) + 1 for frame in hub.stream(0)]
    assert len(reply) == len(ingress)
    payload = [len(u) + len(a) + len(summary) + len(r) for u, a, r in reqs]
    return {"ingress": ingress, "reply": reply, "payload": payload}


def test_proc_fleet_frame_bytes_under_ceilings(slo, proc_fleet_measured):
    """Codec creep gate: the wire frames the process transport actually
    produces (ingress submit + seq-stamped reply) stay under their
    committed per-request byte ceilings on the fleet mix."""
    sec, m = slo["process_fleet"], proc_fleet_measured
    per_req = [i + r for i, r in zip(m["ingress"], m["reply"])]
    assert max(m["ingress"]) <= sec["ingress_frame_bytes_max"], (
        f"ingress frame grew to {max(m['ingress'])} B (ceiling "
        f"{sec['ingress_frame_bytes_max']}) — the submit codec bloated "
        f"(see SERVE_SLO.json process_fleet._comment)")
    assert max(m["reply"]) <= sec["reply_frame_bytes_max"], (
        f"reply frame grew to {max(m['reply'])} B (ceiling "
        f"{sec['reply_frame_bytes_max']}) — the reply-hub envelope bloated")
    assert max(per_req) <= sec["wire_bytes_per_request_max"], (
        f"round-trip wire cost grew to {max(per_req)} B/request "
        f"(ceiling {sec['wire_bytes_per_request_max']})")


def test_proc_fleet_envelope_overhead_under_ceiling(slo,
                                                    proc_fleet_measured):
    """The JSON envelope (framing, escaping, the article echoed back in
    the reply) priced against the payload the caller actually asked to
    move — uuid + article + summary + reference counted once."""
    sec, m = slo["process_fleet"], proc_fleet_measured
    envelope = [i + r - p for i, r, p in
                zip(m["ingress"], m["reply"], m["payload"])]
    assert max(envelope) <= sec["envelope_overhead_bytes_max"], (
        f"wire envelope grew to {max(envelope)} B/request (ceiling "
        f"{sec['envelope_overhead_bytes_max']}) — schema creep or double "
        f"encoding in the socket transport")


def test_proc_fleet_scrape_bandwidth_under_ceiling(slo):
    """The supervisor's health scrape, priced at its real cadence: the
    REAL /healthz payload of a representative replica registry
    (breakers + heartbeats + serve gauges + ISSUE-17 incarnation
    identity), serialized once, multiplied by the scrapes/s the
    serve_scrape_interval_ms default implies."""
    from textsummarization_on_flink_tpu.obs import http as obs_http
    from textsummarization_on_flink_tpu.resilience.policy import \
        CircuitBreaker

    wl = slo["process_fleet"]["workload"]
    reg = Registry()
    reg.replica_id = "p0"
    for name in ("serve.admission", "serve.replica.p0", "io.source"):
        CircuitBreaker(threshold=2, name=name, registry=reg).allow()
    for comp in ("serve.engine", "serve.dispatch", "obs.flush"):
        obs_http.heartbeat(reg, comp)
    reg.gauge("serve/queue_depth").set(3)
    payload = obs_http.health(reg)
    # the incarnation identity the supervisor's readiness check keys on
    assert payload["pid"] == os.getpid()
    assert payload["replica_id"] == "p0"
    assert payload["start_time"] > 0
    scrape_bytes = len(json.dumps(payload).encode())
    scrapes_per_s = 1000.0 / wl["scrape_interval_ms"]
    kib_per_s = scrape_bytes * scrapes_per_s / 1024.0
    ceiling = slo["process_fleet"]["scrape_kib_per_replica_per_s_max"]
    assert kib_per_s <= ceiling, (
        f"health scrape costs {kib_per_s:.2f} KiB/s per replica "
        f"({scrape_bytes} B at {scrapes_per_s:.0f}/s; ceiling {ceiling}) "
        f"— the /healthz payload swelled past its scrape budget")


def test_proc_fleet_reply_ring_covers_inflight_capacity(slo):
    """At-least-once floor: a reply ring smaller than one replica's
    admissible in-flight set could trim frames a reconnecting
    supervisor never saw.  The hub capacity must dominate the
    serve_max_queue + slots bound the transport admits against."""
    from textsummarization_on_flink_tpu.serve import procfleet

    hps = HParams(mode="decode", batch_size=4, vocab_size=8,
                  max_enc_steps=8, max_dec_steps=4, min_dec_steps=1,
                  beam_size=2, max_oov_buckets=2,
                  serve_max_queue=256, serve_slots=8)
    capacity = hps.serve_max_queue + max(hps.serve_slots,
                                         hps.serve_max_batch, 1)
    hub = procfleet._ReplyHub()
    assert hub.capacity >= capacity, (
        f"reply ring ({hub.capacity}) smaller than one replica's "
        f"in-flight capacity ({capacity}) — a reconnect could replay "
        f"past live work")


# ---------------------------------------------------------------------------
# Hierarchical long-document summarization (ISSUE 19; SERVING.md
# "Hierarchical summarization") — the REAL HierarchicalSummarizer over a
# REAL continuous ServingServer with the front door armed, costed by the
# counting sim engine.  Fan-out makespan, the sequential baseline, and
# the append-path dedup are exact scheduling facts on the virtual clock.


def _hier_workload(slo):
    return {**slo["workload"], **slo["hierarchical"]["workload"]}


def _hier_doc(wl):
    """One doc exactly doc_chunks wide whose words are all DISTINCT
    (w0, w1, ...): distinct chunk content -> distinct article_key per
    chunk, so nothing coalesces WITHIN the first pass and the append
    pins measure the front door's dedup, not accidental twins.  The doc
    ends exactly on a chunk boundary (len = chunk + (n-1)*stride), so
    appending leaves every pre-append chunk byte-identical."""
    stride = wl["chunk_words"] - wl["overlap_words"]
    n_words = wl["chunk_words"] + (wl["doc_chunks"] - 1) * stride
    doc = " ".join(f"w{i}" for i in range(n_words))
    tail = " ".join(f"w{n_words + i}"
                    for i in range(wl["append_chunks"] * stride))
    return doc, tail


def _run_hier(slo, slots: int, append: bool):
    """Fan one document through a real continuous server with `slots`
    slots (slots=1 is the sequential baseline); optionally append and
    re-summarize on the warm server.  Returns the measured scheduling
    facts."""
    from textsummarization_on_flink_tpu.serve.hiersum import (
        DocumentSession,
        HierarchicalSummarizer,
    )

    wl = _hier_workload(slo)
    vocab = Vocab(words=WORDS)
    hps = HParams(
        mode="decode", batch_size=slots, vocab_size=vocab.size(),
        max_enc_steps=wl["chunk_words"], max_dec_steps=wl["long_steps"],
        beam_size=2, min_dec_steps=1, max_oov_buckets=4,
        serve_max_queue=256, serve_mode="continuous", serve_slots=slots,
        serve_refill_chunk=wl["chunk"], serve_coalesce=True,
        serve_cache_entries=wl["cache_entries"],
        hier_chunk_words=wl["chunk_words"],
        hier_overlap_words=wl["overlap_words"])
    doc, tail = _hier_doc(wl)
    out = {}
    with obs.use_registry(Registry()) as reg:
        sim = CountingSimEngine({**wl, "slots": slots})
        server = ServingServer(hps, vocab, decoder=_NullDecoder(),
                               engine=sim, registry=reg)
        hs = HierarchicalSummarizer(server, hps, registry=reg)
        sess = DocumentSession("doc", doc)
        marks = {}
        # enqueue the whole fan-out BEFORE the dispatch thread starts
        # (the committed discipline: slot assignment is pure FIFO)
        fut = hs.summarize("", session=sess)
        fut.add_done_callback(lambda f: marks.setdefault("fan", sim.vtime))
        server.start()
        res = fut.result(timeout=120)
        assert res.chunk_count == wl["doc_chunks"]
        out["fan_makespan"] = marks["fan"]
        out["fan_decodes"] = sim.pack_count
        if append:
            hits0 = reg.counter("serve/cache_hits_total").value
            packs0 = sim.pack_count
            t0 = sim.vtime  # idle ticks never step the engine
            sess.append(tail)
            fut2 = hs.summarize("", session=sess)
            fut2.add_done_callback(
                lambda f: marks.setdefault("app", sim.vtime))
            res2 = fut2.result(timeout=120)
            out["append_makespan"] = marks["app"] - t0
            out["append_hits"] = \
                reg.counter("serve/cache_hits_total").value - hits0
            out["append_decodes"] = sim.pack_count - packs0
            out["append_reused"] = res2.reused_chunks
            out["append_chunk_count"] = res2.chunk_count
            out["documents"] = \
                reg.counter("serve/hier_documents_total").value
            out["reduces"] = reg.counter("serve/hier_reduce_total").value
            out["partials"] = \
                reg.counter("serve/hier_partial_failures_total").value
        server.stop()
    return out


@pytest.fixture(scope="module")
def hier_measured(slo):
    wl = _hier_workload(slo)
    fan = _run_hier(slo, slots=wl["slots"], append=True)
    seq = _run_hier(slo, slots=1, append=False)
    return {"fan": fan, "seq": seq}


def test_hier_fanout_makespan_beats_sequential(slo, hier_measured):
    """The map-reduce win, gated: fanning the document's chunks over
    the slots must beat decoding them one after another by the
    committed ratio — and stay under the absolute ceiling."""
    sec = slo["hierarchical"]
    fan = hier_measured["fan"]["fan_makespan"]
    seq = hier_measured["seq"]["fan_makespan"]
    assert fan <= sec["fanout_makespan_virtual_ms_max"], (
        f"hier fan-out makespan {fan} vms (committed max "
        f"{sec['fanout_makespan_virtual_ms_max']}) — chunk scheduling "
        f"regressed")
    ratio = fan / seq
    assert ratio <= sec["fanout_makespan_ratio_max"], (
        f"hier fan-out makespan {fan} vms vs sequential {seq} (ratio "
        f"{ratio:.2f}, committed max {sec['fanout_makespan_ratio_max']}) "
        f"— the fan-out stopped buying parallelism")


def test_hier_append_dedups_by_construction(slo, hier_measured):
    """The append-path floor, pinned EXACTLY: re-summarizing after an
    append must cache-hit every pre-append chunk at submit and decode
    only the appended chunks + one reduce — chunk boundaries are a pure
    function of word index, so this is dedup by construction and any
    drift is a bug, not noise."""
    sec = slo["hierarchical"]
    wl = _hier_workload(slo)
    m = hier_measured["fan"]
    assert m["append_hits"] == sec["append_cache_hits_expected"], (
        f"append pass cache-hit {m['append_hits']} chunks (expected "
        f"exactly {sec['append_cache_hits_expected']}) — a boundary or "
        f"key drifted and the front door re-decoded unchanged content")
    assert m["append_decodes"] == sec["append_decodes_expected"], (
        f"append pass served {m['append_decodes']} decodes (expected "
        f"exactly {sec['append_decodes_expected']}: the appended chunks "
        f"+ one reduce)")
    assert m["append_reused"] == wl["doc_chunks"]
    assert m["append_chunk_count"] == \
        wl["doc_chunks"] + wl["append_chunks"]
    assert m["append_makespan"] <= sec["append_makespan_virtual_ms_max"]
    # bookkeeping: two documents, two reduces, zero partial failures
    assert m["documents"] == 2
    assert m["reduces"] == 2
    assert m["partials"] == 0
