"""The unattended sweep path must work the one time it matters: a brief
tunnel window with nobody watching.  This drills the bash orchestration
(scripts/bench_all.sh row list + run-tag plumbing + single-writer
self-append + the watcher's completeness rule) against a stub bench.py
that honors the real contract, without TPU or slow CPU benches."""

import json
import os
import re
import shutil
import subprocess
import sys

import pytest

REPO = os.path.join(os.path.dirname(__file__), "..")

STUB_BENCH = '''
import datetime, json, os


def _config_fingerprint():
    # part of the real contract: the sweep's incremental-skip check
    # imports bench and compares the banked record's fingerprint to
    # this (so imports must be side-effect free — main guard below)
    return {"mode": os.environ.get("BENCH_MODE", "train")}


if __name__ == "__main__":
    mode = os.environ.get("BENCH_MODE", "train")
    rec = {"metric": "stub_" + mode, "value": 1.0, "unit": "x",
           "vs_baseline": 1.0,
           "captured_at": datetime.datetime.now(datetime.timezone.utc)
           .strftime("%Y-%m-%dT%H:%M:%SZ"),
           "config_fingerprint": _config_fingerprint()}
    if os.environ.get("BENCH_RUN_TAG"):
        rec["run"] = os.environ["BENCH_RUN_TAG"]
    path = os.environ.get(
        "BENCH_STALE_FILE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "BENCH_ALL.jsonl"))
    if not os.environ.get("BENCH_NO_RECORD"):
        with open(path, "a") as f:
            f.write(json.dumps(rec) + "\\n")
    print(json.dumps(rec))
'''


def _scratch_repo(tmp_path):
    scripts = tmp_path / "repo" / "scripts"
    scripts.mkdir(parents=True)
    for name in ("bench_all.sh", "bench_when_up.sh", "bench_latest.py"):
        shutil.copy(os.path.join(REPO, "scripts", name), scripts / name)
    (tmp_path / "repo" / "bench.py").write_text(STUB_BENCH)
    return tmp_path / "repo"


def _run_env():
    # scrub the axon sitecustomize hook (~1.8s per python start, and the
    # stub needs no TPU plugin)
    env = dict(os.environ)
    env["PYTHONPATH"] = ""
    return env


def test_sweep_writes_every_row_once_and_completeness_passes(tmp_path):
    repo = _scratch_repo(tmp_path)
    proc = subprocess.run(["bash", "scripts/bench_all.sh"], cwd=repo,
                          env=_run_env(),
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-1000:]
    lines = [json.loads(s) for s in
             (repo / "BENCH_ALL.jsonl").read_text().strip().splitlines()]
    tags = re.findall(r"^run\s+(\S+)",
                      (repo / "scripts" / "bench_all.sh").read_text(), re.M)
    # one self-appended record per row, no sweep-side duplicates
    assert [r["run"] for r in lines] == tags
    assert all("error" not in r and not r.get("stale") for r in lines)
    # the watcher's completeness rule (verbatim semantics: latest_by_tag
    # live rows must cover the run lines) passes -> BENCH_SWEEP_DONE
    sys.path.insert(0, str(repo / "scripts"))
    try:
        import importlib

        import bench_latest

        importlib.reload(bench_latest)
        live = {tag for tag, rec in
                bench_latest.latest_by_tag(
                    str(repo / "BENCH_ALL.jsonl")).items()
                if "error" not in rec and not rec.get("stale")}
    finally:
        sys.path.pop(0)
    assert set(tags) <= live


def test_sweep_skips_already_live_rows_incrementally(tmp_path):
    """Tunnel windows can be ~2 min; each pass must bank NEW rows, not
    re-measure banked ones.  A pre-seeded live train_b16 with a MATCHING
    fingerprint is skipped (but re-measured once at the paired-denominator
    point, since lever rows banked in this pass); a live seed whose
    fingerprint MISMATCHES the row's current config is re-measured
    (ADVICE r4: a perf-default flip must not serve old-config records
    forever); stale/error seeds are re-run; BENCH_FORCE=1 re-measures
    everything."""
    repo = _scratch_repo(tmp_path)
    seed = [
        {"metric": "stub_train", "value": 9.0, "unit": "x",
         "vs_baseline": 1.0, "captured_at": "2026-07-31T00:00:00Z",
         "config_fingerprint": {"mode": "train"}, "run": "train_b16"},
        {"metric": "stub_train", "value": 0.0, "unit": "x",
         "vs_baseline": 0.0, "captured_at": "2026-07-31T00:00:01Z",
         "stale": True, "run": "train_b64"},
        {"run": "decode_b4", "error": "boom"},
        # live but measured under a different config (fingerprint
        # mismatch) -> must be re-measured, never skipped
        {"metric": "stub_trainer", "value": 7.0, "unit": "x",
         "vs_baseline": 1.0, "captured_at": "2026-07-31T00:00:02Z",
         "config_fingerprint": {"mode": "trainer", "spd": 99},
         "run": "trainer_e2e"},
    ]
    (repo / "BENCH_ALL.jsonl").write_text(
        "".join(json.dumps(r) + "\n" for r in seed))
    proc = subprocess.run(["bash", "scripts/bench_all.sh"], cwd=repo,
                          env=_run_env(),
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-1000:]
    lines = [json.loads(s) for s in
             (repo / "BENCH_ALL.jsonl").read_text().strip().splitlines()]
    per_tag = {}
    for rec in lines:
        per_tag.setdefault(rec.get("run"), []).append(rec)
    # live seed skipped in the main row list... but because lever rows
    # banked in this pass while the denominator was skipped-as-live, one
    # paired train_b16 re-measure lands at the end of the lever section
    assert "skipped" in proc.stderr
    assert per_tag["train_b16"][0]["value"] == 9.0
    assert len(per_tag["train_b16"]) == 2, \
        "expected the seed plus exactly one paired denominator re-measure"
    assert "re-measuring the denominator" in proc.stderr
    # fingerprint-mismatched live seed re-measured (not skipped)
    assert any(r["value"] == 1.0 for r in per_tag["trainer_e2e"])
    # stale and error seeds re-measured live
    assert any(not r.get("stale") for r in per_tag["train_b64"])
    assert any("error" not in r for r in per_tag["decode_b4"])
    # BENCH_FORCE re-measures the live row too
    env = _run_env()
    env["BENCH_FORCE"] = "1"
    proc = subprocess.run(["bash", "scripts/bench_all.sh"], cwd=repo,
                          env=env, capture_output=True, text=True,
                          timeout=120)
    assert proc.returncode == 0, proc.stderr[-1000:]
    lines = [json.loads(s) for s in
             (repo / "BENCH_ALL.jsonl").read_text().strip().splitlines()]
    fresh = [r for r in lines
             if r.get("run") == "train_b16" and r["value"] == 1.0]
    assert fresh, "BENCH_FORCE=1 did not re-measure the live row"


def test_bench_latest_md_table(tmp_path):
    """--md renders the newest-per-tag view as the markdown table
    BASELINE.md embeds (errors and staleness visible, newest wins)."""
    path = tmp_path / "b.jsonl"
    path.write_text("".join(json.dumps(r) + "\n" for r in [
        {"metric": "m", "value": 1.0, "unit": "samples/s", "run": "a",
         "captured_at": "2026-07-31T00:00:00Z"},
        {"metric": "m", "value": 2.0, "unit": "samples/s", "run": "a",
         "captured_at": "2026-07-31T01:00:00Z", "step_time_ms": 13.4},
        {"run": "b", "error": "tunnel down"},
        {"metric": "m", "value": 3.0, "unit": "ms", "run": "c",
         "captured_at": "2026-07-31T00:30:00Z", "stale": True},
    ]))
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import importlib

        import bench_latest

        importlib.reload(bench_latest)
        out = bench_latest._md_table(bench_latest.latest_by_tag(str(path)))
    finally:
        sys.path.pop(0)
    assert "**2.0** samples/s" in out and "**1.0**" not in out
    assert "step 13.4 ms" in out
    assert "| error |" in out and "tunnel down" in out
    assert "| stale |" in out


def test_bench_latest_ratio_view(tmp_path):
    """--ratios pairs each lever row with its denominator and flags
    pairs captured in different tunnel windows (the same-window rule
    pair_denominator enforces; PERF.md verdicts must not be filled from
    a flagged pair)."""
    path = tmp_path / "b.jsonl"
    path.write_text("".join(json.dumps(r) + "\n" for r in [
        {"metric": "m", "value": 1000.0, "unit": "samples/s",
         "run": "train_b16", "captured_at": "2026-07-31T01:00:00Z"},
        {"metric": "m", "value": 900.0, "unit": "samples/s",
         "run": "train_b16_unroll1", "captured_at": "2026-07-31T01:02:00Z"},
        {"metric": "m", "value": 2500.0, "unit": "samples/s",
         "run": "train_b64", "captured_at": "2026-07-31T09:00:00Z"},
        # denominator missing entirely -> row omitted
        {"metric": "m", "value": 5.0, "unit": "ms",
         "run": "decode_while", "captured_at": "2026-07-31T01:00:00Z"},
    ]))
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import importlib

        import bench_latest

        importlib.reload(bench_latest)
        latest = bench_latest.latest_by_tag(str(path))
        rows = {t: (d, r, g, f)
                for t, d, r, _, g, f in bench_latest._ratio_rows(latest)}
    finally:
        sys.path.pop(0)
    assert rows["train_b16_unroll1"][1] == pytest.approx(0.9)
    assert rows["train_b16_unroll1"][2] == 120.0  # same window
    assert rows["train_b16_unroll1"][3] == []
    # 8h apart -> flagged as a likely cross-window pair
    assert rows["train_b64"][3] == ["LIKELY CROSS-WINDOW"]
    # decode_while's denominator (decode_b4) is absent -> no row
    assert "decode_while" not in rows


def test_sweep_appends_error_stub_so_watcher_retries(tmp_path):
    """A failing row must leave a tagged error stub (the watcher's signal
    to retry the pass), and must not abort the remaining rows unless the
    tunnel probe also fails."""
    repo = _scratch_repo(tmp_path)
    # stub that errors for decode modes only, succeeds otherwise
    (repo / "bench.py").write_text(STUB_BENCH.replace(
        '    mode = os.environ.get("BENCH_MODE", "train")',
        '    mode = os.environ.get("BENCH_MODE", "train")\n'
        '    if mode == "decode":\n'
        '        print(json.dumps({"metric": "x", "value": 0.0,\n'
        '                          "unit": "n/a", "vs_baseline": 0.0,\n'
        '                          "error": "boom"}))\n'
        '        raise SystemExit(1)'))
    proc = subprocess.run(["bash", "scripts/bench_all.sh"], cwd=repo,
                          env=_run_env(),
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-1000:]
    lines = [json.loads(s) for s in
             (repo / "BENCH_ALL.jsonl").read_text().strip().splitlines()]
    by_tag = {r.get("run"): r for r in lines}
    assert "error" in by_tag["decode_b4"]
    assert "error" not in by_tag["train_b16"]
    assert "error" not in by_tag["input_pipeline"]  # rows after the failure
