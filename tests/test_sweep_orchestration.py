"""The unattended sweep path must work the one time it matters: a brief
tunnel window with nobody watching.  This drills the bash orchestration
(scripts/bench_all.sh row list + run-tag plumbing + single-writer
self-append + the watcher's completeness rule) against a stub bench.py
that honors the real contract, without TPU or slow CPU benches."""

import json
import os
import re
import shutil
import subprocess
import sys

REPO = os.path.join(os.path.dirname(__file__), "..")

STUB_BENCH = '''
import datetime, json, os
mode = os.environ.get("BENCH_MODE", "train")
rec = {"metric": "stub_" + mode, "value": 1.0, "unit": "x",
       "vs_baseline": 1.0,
       "captured_at": datetime.datetime.now(datetime.timezone.utc)
       .strftime("%Y-%m-%dT%H:%M:%SZ"),
       "config_fingerprint": {"mode": mode}}
if os.environ.get("BENCH_RUN_TAG"):
    rec["run"] = os.environ["BENCH_RUN_TAG"]
path = os.environ.get(
    "BENCH_STALE_FILE",
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 "BENCH_ALL.jsonl"))
if not os.environ.get("BENCH_NO_RECORD"):
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\\n")
print(json.dumps(rec))
'''


def _scratch_repo(tmp_path):
    scripts = tmp_path / "repo" / "scripts"
    scripts.mkdir(parents=True)
    for name in ("bench_all.sh", "bench_when_up.sh", "bench_latest.py"):
        shutil.copy(os.path.join(REPO, "scripts", name), scripts / name)
    (tmp_path / "repo" / "bench.py").write_text(STUB_BENCH)
    return tmp_path / "repo"


def _run_env():
    # scrub the axon sitecustomize hook (~1.8s per python start, and the
    # stub needs no TPU plugin)
    env = dict(os.environ)
    env["PYTHONPATH"] = ""
    return env


def test_sweep_writes_every_row_once_and_completeness_passes(tmp_path):
    repo = _scratch_repo(tmp_path)
    proc = subprocess.run(["bash", "scripts/bench_all.sh"], cwd=repo,
                          env=_run_env(),
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-1000:]
    lines = [json.loads(s) for s in
             (repo / "BENCH_ALL.jsonl").read_text().strip().splitlines()]
    tags = re.findall(r"^run\s+(\S+)",
                      (repo / "scripts" / "bench_all.sh").read_text(), re.M)
    # one self-appended record per row, no sweep-side duplicates
    assert [r["run"] for r in lines] == tags
    assert all("error" not in r and not r.get("stale") for r in lines)
    # the watcher's completeness rule (verbatim semantics: latest_by_tag
    # live rows must cover the run lines) passes -> BENCH_SWEEP_DONE
    sys.path.insert(0, str(repo / "scripts"))
    try:
        import importlib

        import bench_latest

        importlib.reload(bench_latest)
        live = {tag for tag, rec in
                bench_latest.latest_by_tag(
                    str(repo / "BENCH_ALL.jsonl")).items()
                if "error" not in rec and not rec.get("stale")}
    finally:
        sys.path.pop(0)
    assert set(tags) <= live


def test_sweep_appends_error_stub_so_watcher_retries(tmp_path):
    """A failing row must leave a tagged error stub (the watcher's signal
    to retry the pass), and must not abort the remaining rows unless the
    tunnel probe also fails."""
    repo = _scratch_repo(tmp_path)
    # stub that errors for decode modes only, succeeds otherwise
    (repo / "bench.py").write_text(STUB_BENCH.replace(
        'mode = os.environ.get("BENCH_MODE", "train")',
        'mode = os.environ.get("BENCH_MODE", "train")\n'
        'if mode == "decode":\n'
        '    print(json.dumps({"metric": "x", "value": 0.0, "unit": "n/a",\n'
        '                      "vs_baseline": 0.0, "error": "boom"}))\n'
        '    raise SystemExit(1)'))
    proc = subprocess.run(["bash", "scripts/bench_all.sh"], cwd=repo,
                          env=_run_env(),
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-1000:]
    lines = [json.loads(s) for s in
             (repo / "BENCH_ALL.jsonl").read_text().strip().splitlines()]
    by_tag = {r.get("run"): r for r in lines}
    assert "error" in by_tag["decode_b4"]
    assert "error" not in by_tag["train_b16"]
    assert "error" not in by_tag["input_pipeline"]  # rows after the failure
