"""Byte-diet lever tests (ISSUE 5; PERF.md 'Byte diet').

* Streaming chunked vocab loss (--loss_chunk): token-exact forward and
  grad-parity (<1e-6 rel on f32 CPU) vs the materialized path, for BOTH
  model families, pointer and baseline-CE losses, with a chunk size that
  does NOT divide T_dec (the padded-tail path).
* bf16 Adagrad accumulator (--opt_state_dtype=bfloat16): storage dtype,
  f32-update-math single-step closeness, N-step drift tolerance vs f32,
  and checkpoint round trip (npz cannot hold bf16 — widened on save,
  re-narrowed on resume).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.models import pointer_generator as pg
from textsummarization_on_flink_tpu.models import transformer as tfm
from textsummarization_on_flink_tpu.ops import losses as loss_ops
from textsummarization_on_flink_tpu.train import optim
from textsummarization_on_flink_tpu.train import trainer as trainer_lib
from __graft_entry__ import _example_arrays

CHUNK = 2  # deliberately does not divide max_dec_steps=5 below


def family_hps(family: str, **kw) -> HParams:
    base = dict(batch_size=2, max_enc_steps=7, max_dec_steps=5,
                min_dec_steps=1, hidden_dim=8, emb_dim=8, max_oov_buckets=3,
                vocab_size=32, beam_size=2, model_family=family)
    if family == "transformer":
        base.update(num_heads=2, enc_layers=2, dec_layers=2)
    else:
        base.update(coverage=True)
    base.update(kw)
    return HParams(**base)


def _grad_parity(loss_fn, params, hps_a, hps_b, rel=1e-6, atol=0.0):
    ga = jax.grad(loss_fn)(params, hps_a)
    gb = jax.grad(loss_fn)(params, hps_b)
    for a, b in zip(jax.tree_util.tree_leaves(ga),
                    jax.tree_util.tree_leaves(gb)):
        a, b = np.asarray(a), np.asarray(b)
        scale = np.max(np.abs(a)) + 1e-12
        assert np.max(np.abs(a - b)) <= rel * scale + atol


class TestStreamingLossParity:
    @pytest.mark.parametrize("family", ["pointer_generator", "transformer"])
    @pytest.mark.parametrize("pointer_gen", [True, False])
    def test_forward_and_grad_parity(self, family, pointer_gen):
        """--loss_chunk vs materialized: same loss (token-exact math; the
        final scalar mean may reassociate, hence rel 1e-6) and <1e-6 rel
        gradients, including the chunk-does-not-divide-T padded tail."""
        hps = family_hps(family, pointer_gen=pointer_gen)
        mod = tfm if family == "transformer" else pg
        params = mod.init_params(hps, hps.vocab_size, jax.random.PRNGKey(0))
        arrays = _example_arrays(hps, np.random.RandomState(0))

        def loss(p, h):
            return mod.forward_train(p, h, arrays).total_loss

        l_mat = float(loss(params, hps))
        l_chunk = float(loss(params, hps.replace(loss_chunk=CHUNK)))
        assert l_chunk == pytest.approx(l_mat, rel=1e-6)
        _grad_parity(loss, params, hps, hps.replace(loss_chunk=CHUNK))

    @pytest.mark.parametrize("family", ["pointer_generator", "transformer"])
    def test_bf16_compute_dtype_parity(self, family):
        """The chunked path must project through the SAME dtype-aware
        matmul as the materialized one (losses.project_scores), so bf16
        mode stays chunk-invariant too."""
        hps = family_hps(family, compute_dtype="bfloat16")
        mod = tfm if family == "transformer" else pg
        params = mod.init_params(hps, hps.vocab_size, jax.random.PRNGKey(1))
        arrays = _example_arrays(hps, np.random.RandomState(1))

        def loss(p, h):
            return mod.forward_train(p, h, arrays).total_loss

        assert float(loss(params, hps.replace(loss_chunk=CHUNK))) == \
            pytest.approx(float(loss(params, hps)), rel=1e-5)
        # looser than the f32 pin: bf16-rounded operands make the chunked
        # dw accumulation order visible at ~1e-4 rel, and near-zero
        # leaves (max ~1e-6) need an atol floor
        _grad_parity(loss, params, hps, hps.replace(loss_chunk=CHUNK),
                     rel=1e-4, atol=1e-8)

    def test_chunk_larger_than_t_and_chunk_one(self):
        """Degenerate chunk sizes: 1 (maximum streaming) and > T_dec
        (clamped — single chunk, still the streaming code path)."""
        hps = family_hps("pointer_generator")
        params = pg.init_params(hps, hps.vocab_size, jax.random.PRNGKey(2))
        arrays = _example_arrays(hps, np.random.RandomState(2))

        def loss(p, h):
            return pg.forward_train(p, h, arrays).total_loss

        base = float(loss(params, hps))
        for chunk in (1, 999):
            assert float(loss(params, hps.replace(loss_chunk=chunk))) == \
                pytest.approx(base, rel=1e-6)

    def test_streaming_gold_probs_token_exact_unit(self):
        """Direct unit parity: streaming_gold_probs equals the
        materialized gold_mixture_prob_from_scores token for token."""
        rng = np.random.RandomState(3)
        T, B, H, V, Te = 5, 3, 4, 11, 6
        outputs = jnp.asarray(rng.randn(T, B, H), jnp.float32)
        attn = jnp.asarray(rng.rand(T, B, Te), jnp.float32)
        p_gens = jnp.asarray(rng.rand(T, B), jnp.float32)
        targets = jnp.asarray(rng.randint(0, V + 2, (T, B)))
        ext = jnp.asarray(rng.randint(0, V + 2, (B, Te)))
        w = jnp.asarray(rng.randn(H, V), jnp.float32)
        v = jnp.asarray(rng.randn(V), jnp.float32)
        want = loss_ops.gold_mixture_prob_from_scores(
            outputs @ w + v, attn, p_gens, targets, ext)
        for chunk in (1, 2, 5):
            got = loss_ops.streaming_gold_probs(
                outputs, attn, p_gens, targets, ext, w, v, chunk=chunk)
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=1e-7, atol=0)

    def test_no_materialized_scores_in_backward(self):
        """The claim itself: peak temp memory of grad(streaming loss)
        must stay far below one [T, B, V] scores tensor at a scale where
        that tensor dominates, while the materialized path holds ~2x of
        it (value + residual)."""
        T, B, H, V = 64, 4, 16, 2048
        rng = np.random.RandomState(4)
        outputs = jnp.asarray(rng.randn(T, B, H), jnp.float32)
        targets = jnp.asarray(rng.randint(0, V, (T, B)))
        mask = jnp.ones((T, B), jnp.float32)
        w = jnp.asarray(rng.randn(H, V) * 0.02, jnp.float32)
        v = jnp.zeros((V,), jnp.float32)

        def mat_loss(o, w, v):
            scores = o @ w + v
            log_probs = jax.nn.log_softmax(scores, axis=-1)
            nll = -jnp.take_along_axis(
                log_probs, targets[..., None], axis=-1)[..., 0]
            return jnp.sum(nll * mask) / jnp.sum(mask)

        def chunk_loss(o, w, v):
            return loss_ops.streaming_softmax_cross_entropy(
                o, targets, mask, w, v, chunk=8)

        def temp_of(fn):
            c = jax.jit(jax.grad(fn, argnums=(0, 1, 2))).lower(
                outputs, w, v).compile()
            return c.memory_analysis().temp_size_in_bytes

        scores_bytes = T * B * V * 4
        assert temp_of(mat_loss) > 1.5 * scores_bytes
        assert temp_of(chunk_loss) < 0.5 * scores_bytes


class TestBf16OptState:
    def test_init_and_update_dtypes(self):
        hps = family_hps("pointer_generator",
                         opt_state_dtype="bfloat16")
        state = trainer_lib.init_train_state(hps, hps.vocab_size, seed=0)
        for leaf in jax.tree_util.tree_leaves(state.opt_state.accumulators):
            assert leaf.dtype == jnp.bfloat16
        # params stay f32 masters
        for leaf in jax.tree_util.tree_leaves(state.params):
            assert leaf.dtype == jnp.float32
        step = jax.jit(trainer_lib.make_train_step(hps))
        arrays = _example_arrays(hps, np.random.RandomState(0))
        new_state, metrics = step(state, arrays)
        assert np.isfinite(float(metrics.loss))
        for leaf in jax.tree_util.tree_leaves(
                new_state.opt_state.accumulators):
            assert leaf.dtype == jnp.bfloat16
        for leaf in jax.tree_util.tree_leaves(new_state.params):
            assert leaf.dtype == jnp.float32

    def test_f32_path_unchanged_bit_for_bit(self):
        """The dtype-aware update must be a no-op for f32 accumulators:
        widen/narrow casts vanish and the historical formula applies."""
        params = {"w": jnp.asarray([[0.5, -0.25], [1.0, 2.0]], jnp.float32)}
        grads = {"w": jnp.asarray([[0.1, -0.2], [0.3, 0.4]], jnp.float32)}
        state = optim.adagrad_init(params, 0.1)
        new_params, new_state = optim.adagrad_update(grads, state, params,
                                                     lr=0.15)
        acc = 0.1 + np.asarray(grads["w"]) ** 2
        want = np.asarray(params["w"]) - 0.15 * np.asarray(grads["w"]) \
            / np.sqrt(acc)
        np.testing.assert_allclose(np.asarray(new_params["w"]), want,
                                   rtol=1e-6)
        np.testing.assert_array_equal(
            np.asarray(new_state.accumulators["w"], np.float32), acc)

    def test_single_step_update_math_runs_in_f32(self):
        """One step from a FRESH bf16 accumulator: the widen->g^2->rsqrt
        chain runs in f32, so the param update differs from the pure-f32
        update only by the bf16 rounding of the INITIAL accumulator
        value (0.1 rounds to ~0.100098 in bf16: rel ~1e-3), never by
        bf16 arithmetic inside the step."""
        hps = family_hps("pointer_generator")
        state32 = trainer_lib.init_train_state(hps, hps.vocab_size, seed=0)
        state16 = trainer_lib.init_train_state(
            hps.replace(opt_state_dtype="bfloat16"), hps.vocab_size, seed=0)
        arrays = _example_arrays(hps, np.random.RandomState(0))
        step32 = jax.jit(trainer_lib.make_train_step(hps))
        step16 = jax.jit(trainer_lib.make_train_step(
            hps.replace(opt_state_dtype="bfloat16")))
        new32, m32 = step32(state32, arrays)
        new16, m16 = step16(state16, arrays)
        assert float(m16.loss) == pytest.approx(float(m32.loss), rel=1e-6)
        for a, b in zip(jax.tree_util.tree_leaves(new32.params),
                        jax.tree_util.tree_leaves(new16.params)):
            a, b = np.asarray(a), np.asarray(b)
            scale = np.max(np.abs(a)) + 1e-12
            assert np.max(np.abs(a - b)) / scale < 5e-3

    # (N steps, param-drift bound, final-loss rel bound), calibrated
    # 2026-08-02 with 2-3x headroom over measurement.  The transformer's
    # envelope is short and loose by design: its Adagrad dynamics at
    # this scale are chaotic — ANY ~1e-3 perturbation (the bf16 rounding
    # of the 0.1 initial accumulator; equally a scan-unroll change)
    # compounds to O(1) parameter divergence by step ~20 while the LOSS
    # trajectory stays equivalent, so a long tight param pin would test
    # dynamics sensitivity, not the lever.  Measured: pg drift 4.6e-3 at
    # N=30; transformer drift 0.129 at N=10.
    _DRIFT = {"pointer_generator": (30, 2e-2, 1e-2),
              "transformer": (10, 3e-1, 2e-2)}

    @pytest.mark.parametrize("family", ["pointer_generator", "transformer"])
    def test_n_step_drift_vs_f32(self, family):
        """ISSUE 5 acceptance: N-step drift tolerance pinned vs f32 —
        real training with a bf16 accumulator must stay within the
        committed envelope of the f32 run and make the same learning
        progress."""
        n, drift_tol, loss_tol = self._DRIFT[family]
        hps = family_hps(family)
        hps16 = hps.replace(opt_state_dtype="bfloat16")
        arrays = _example_arrays(hps, np.random.RandomState(1))
        s32 = trainer_lib.init_train_state(hps, hps.vocab_size, seed=1)
        s16 = trainer_lib.init_train_state(hps16, hps.vocab_size, seed=1)
        step32 = jax.jit(trainer_lib.make_train_step(hps))
        step16 = jax.jit(trainer_lib.make_train_step(hps16))
        first = None
        for _ in range(n):
            s32, m32 = step32(s32, arrays)
            s16, m16 = step16(s16, arrays)
            if first is None:
                first = float(m32.loss)
        assert float(m16.loss) == pytest.approx(float(m32.loss),
                                                rel=loss_tol)
        assert float(m16.loss) < first  # still learning
        for a, b in zip(jax.tree_util.tree_leaves(s32.params),
                        jax.tree_util.tree_leaves(s16.params)):
            a, b = np.asarray(a), np.asarray(b)
            rel = np.linalg.norm(a - b) / (np.linalg.norm(a) + 1e-12)
            assert rel < drift_tol, f"{family}: param drift {rel}"

    def test_checkpoint_roundtrip_renarrows(self, tmp_path):
        """npz cannot hold bf16: the checkpointer widens accumulators to
        f32 on save, and trainer.cast_opt_state re-narrows on resume —
        the round trip must preserve values exactly (bf16 -> f32 -> bf16
        is lossless) and restore the working dtype."""
        from textsummarization_on_flink_tpu.checkpoint.checkpointer import (
            Checkpointer,
        )

        hps = family_hps("pointer_generator",
                         opt_state_dtype="bfloat16")
        state = trainer_lib.init_train_state(hps, hps.vocab_size, seed=0)
        step = jax.jit(trainer_lib.make_train_step(hps))
        arrays = _example_arrays(hps, np.random.RandomState(0))
        state, _ = step(state, arrays)  # non-trivial accumulator values
        ckpt = Checkpointer(str(tmp_path), hps=hps)
        ckpt.save(state)
        restored = ckpt.restore()
        # on-disk form is f32 (loadable by any consumer)
        for leaf in jax.tree_util.tree_leaves(
                restored.opt_state.accumulators):
            assert np.asarray(leaf).dtype == np.float32
        recast = trainer_lib.cast_opt_state(hps, restored)
        for a, b in zip(
                jax.tree_util.tree_leaves(state.opt_state.accumulators),
                jax.tree_util.tree_leaves(recast.opt_state.accumulators)):
            assert b.dtype == jnp.bfloat16
            np.testing.assert_array_equal(
                np.asarray(a, np.float32), np.asarray(b, np.float32))
        # the Trainer applies the same cast on construction
        trainer = trainer_lib.Trainer(hps, hps.vocab_size, batcher=None,
                                      state=restored,
                                      train_dir=str(tmp_path))
        for leaf in jax.tree_util.tree_leaves(
                trainer.state.opt_state.accumulators):
            assert leaf.dtype == jnp.bfloat16


class TestConfigValidation:
    def test_loss_chunk_and_dtypes_validate(self):
        HParams(loss_chunk=25).validate()
        HParams(opt_state_dtype="bfloat16").validate()
        HParams(grad_allreduce_dtype="bfloat16").validate()
        with pytest.raises(ValueError, match="loss_chunk"):
            HParams(loss_chunk=-1).validate()
        with pytest.raises(ValueError, match="opt_state_dtype"):
            HParams(opt_state_dtype="fp8").validate()
        with pytest.raises(ValueError, match="grad_allreduce_dtype"):
            HParams(grad_allreduce_dtype="fp8").validate()
        # tp now composes with the bf16 wire (ISSUE 8 unification); sp
        # still rejects
        HParams(grad_allreduce_dtype="bfloat16", tp=2).validate()
        with pytest.raises(ValueError, match="sp"):
            HParams(grad_allreduce_dtype="bfloat16", sp=2,
                    max_enc_steps=400).validate()
        with pytest.raises(ValueError, match="pointer_gen"):
            HParams(grad_allreduce_dtype="bfloat16",
                    pointer_gen=False).validate()

    def test_flags_ride_the_reference_argv_surface(self):
        hps = HParams.from_argv(["--loss_chunk=25",
                                 "--opt_state_dtype=bfloat16",
                                 "--grad_allreduce_dtype=bfloat16"])
        assert hps.loss_chunk == 25
        assert hps.opt_state_dtype == "bfloat16"
        assert hps.grad_allreduce_dtype == "bfloat16"


def test_trainer_end_to_end_with_byte_diet_levers(tmp_path):
    """The full single-host Trainer loop with --loss_chunk and bf16
    optimizer state together: runs, learns, checkpoints, resumes."""
    hps = family_hps("pointer_generator", loss_chunk=2,
                     opt_state_dtype="bfloat16",
                     log_root=str(tmp_path), exp_name="bd")

    class FixedBatcher:
        def __init__(self, arrays, n):
            self.arrays, self.n = arrays, n

        def next_batch(self):
            if self.n <= 0:
                return None
            self.n -= 1
            return self  # Batch stand-in: as_arrays below

        def as_arrays(self):
            return self.arrays

    arrays = _example_arrays(hps, np.random.RandomState(0))
    trainer = trainer_lib.Trainer(hps, hps.vocab_size,
                                  FixedBatcher(arrays, 50),
                                  metrics_every=2)
    state = trainer.train(num_steps=4)
    assert int(np.asarray(state.step)) == 4
    events = os.path.join(str(tmp_path), "bd", "train", "events.jsonl")
    assert os.path.exists(events)
    for leaf in jax.tree_util.tree_leaves(state.opt_state.accumulators):
        assert leaf.dtype == jnp.bfloat16
