"""The serving front door (ISSUE 14; SERVING.md "Front door"):
content-hash normalization, the bounded LRU summary cache, in-flight
coalescing, per-tenant token-bucket admission, the params-fingerprint
surface, and the cache-fault chaos contract.

The virtual-time SLO scenarios (zipf decode ratio, tenant isolation,
fleet composition with replica kill) live in tests/test_serve_slo.py;
this file pins the mechanisms one at a time, plus the two real-model
acceptance pins: a cache hit is byte-identical to a fresh decode, and
a checkpoint hot-swap changes the fingerprint and thereby MISSES.
"""

import threading

import numpy as np
import pytest

from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.checkpoint.checkpointer import (
    Checkpointer,
)
from textsummarization_on_flink_tpu.config import (
    HParams,
    parse_fair_weights,
    resolve_tenant_burst,
)
from textsummarization_on_flink_tpu.data.vocab import Vocab
from textsummarization_on_flink_tpu.decode.decoder import DecodedResult
from textsummarization_on_flink_tpu.obs import Registry
from textsummarization_on_flink_tpu.obs.export import MemorySink
from textsummarization_on_flink_tpu.pipeline.io import Message
from textsummarization_on_flink_tpu.serve import (
    ServeOverloadError,
    TenantThrottledError,
)
from textsummarization_on_flink_tpu.serve.frontdoor import (
    FrontDoor,
    SummaryCache,
    article_key,
)
from textsummarization_on_flink_tpu.serve.server import ServingServer
from textsummarization_on_flink_tpu.train import trainer as trainer_lib

WORDS = ("the a cat dog sat ran mat home big small quick brown fox "
         "jumped over lazy it was day night").split()


@pytest.fixture(autouse=True)
def _isolated_obs():
    with obs.use_registry(Registry()) as reg:
        yield reg


def make_vocab():
    return Vocab(words=WORDS)


def tiny_hps(**kw):
    base = dict(mode="decode", batch_size=4, hidden_dim=8, emb_dim=6,
                vocab_size=24, max_enc_steps=16, max_dec_steps=6,
                beam_size=2, min_dec_steps=1, max_oov_buckets=4,
                serve_max_wait_ms=20.0, serve_max_queue=64)
    base.update(kw)
    return HParams(**base)


def make_result(uuid="u0", article="the cat sat .", words=("ok", "."),
                fingerprint=""):
    return DecodedResult(uuid=uuid, article=article,
                         decoded_words=list(words), reference="",
                         abstract_sents=[],
                         params_fingerprint=fingerprint)


class StubDecoder:
    """decode_batch stub with a settable fingerprint — the hot-swap
    invalidation mechanism without a checkpoint dir."""

    def __init__(self, fingerprint="fpA", fail=False):
        self.params_fingerprint = fingerprint
        self.fail = fail
        self.dispatches = 0

    def should_degrade(self, deadline):
        return False

    def decode_batch(self, batch, deadline=None, tier=None):
        self.dispatches += 1
        if self.fail:
            raise RuntimeError("injected decode failure")
        # content-deterministic output, like the real decoder: two
        # decodes of the same article produce identical words
        return [DecodedResult(
                    uuid=batch.uuids[b], article=batch.original_articles[b],
                    decoded_words=["ok"]
                    + batch.original_articles[b].split()[:2],
                    reference=batch.references[b], abstract_sents=[],
                    tier=tier or "beam",
                    params_fingerprint=self.params_fingerprint)
                for b in range(len(batch.uuids)) if batch.real_mask[b]]

    def maybe_reload_checkpoint(self, last):
        return last


# -- content-hash normalization (satellite 1) ------------------------------

class TestArticleKey:
    def test_socket_and_direct_paths_hash_identically(self):
        """The ONE canonical helper: an article round-tripped through
        the SocketSource line codec (Message JSON) hashes exactly like
        the same article submitted directly."""
        article = "the quick brown fox jumped over the lazy dog ."
        wire = Message(uuid="u1", article=article).to_json()
        decoded = Message.from_json(wire).article
        assert article_key(decoded, 16) == article_key(article, 16)

    def test_truncation_happens_before_hashing(self):
        """Two articles identical in the visible max_enc_steps window
        coalesce; a difference INSIDE the window does not."""
        window = "w1 w2 w3 w4"
        assert article_key(window + " tail one", 4) == \
            article_key(window + " a completely different tail", 4)
        assert article_key("w1 w2 XX w4 tail", 4) != \
            article_key(window + " tail", 4)

    def test_whitespace_is_normalized_bytes_level(self):
        assert article_key("a  b\tc\n", 8) == article_key("a b c", 8)

    def test_distinct_content_distinct_keys(self):
        assert article_key("the cat sat .", 16) != \
            article_key("the dog sat .", 16)


# -- the summary cache ------------------------------------------------------

class TestSummaryCache:
    def test_lru_eviction_at_entry_bound(self, _isolated_obs):
        cache = SummaryCache(2, registry=_isolated_obs)
        cache.put(("k1", "beam", ""), make_result("u1"))
        cache.put(("k2", "beam", ""), make_result("u2"))
        assert cache.get(("k1", "beam", "")) is not None  # touch: k1 MRU
        cache.put(("k3", "beam", ""), make_result("u3"))  # evicts k2
        assert cache.get(("k2", "beam", "")) is None
        assert cache.get(("k1", "beam", "")) is not None
        assert cache.get(("k3", "beam", "")) is not None
        assert _isolated_obs.counter(
            "serve/cache_evictions_total").value == 1
        assert _isolated_obs.gauge("serve/cache_entries").value == 2

    def test_byte_bound_evicts_lru_first(self, _isolated_obs):
        big = ["w" * 100] * 10  # ~1 KB payload
        cache = SummaryCache(64, max_bytes=2500, registry=_isolated_obs)
        for i in range(4):
            cache.put((f"k{i}", "beam", ""), make_result(words=big))
        assert len(cache) < 4, "the byte bound never evicted"
        assert cache.nbytes <= 2500
        assert _isolated_obs.counter(
            "serve/cache_evictions_total").value >= 1

    def test_fingerprint_is_part_of_the_key(self, _isolated_obs):
        cache = SummaryCache(8, registry=_isolated_obs)
        cache.put(("k", "beam", "fpA"), make_result())
        assert cache.get(("k", "beam", "fpB")) is None
        assert cache.get(("k", "greedy", "fpA")) is None
        assert cache.get(("k", "beam", "fpA")) is not None

    def test_caller_mutation_cannot_poison_the_cache(self, _isolated_obs):
        """The cache holds its own payload copy: a consumer editing a
        returned result's decoded_words in place must not change what
        the next hit serves (the byte-identical contract)."""
        hps = tiny_hps(serve_cache_entries=8)
        dec = StubDecoder()
        server = ServingServer(hps, make_vocab(), decoder=dec,
                               registry=_isolated_obs)
        with server:
            r1 = server.submit("the cat sat .",
                               uuid="m1").result(timeout=10)
            clean = list(r1.decoded_words)
            r1.decoded_words[0] = "MUTATED"  # a rude caller
            r2 = server.submit("the cat sat .",
                               uuid="m2").result(timeout=10)
            assert r2.decoded_words == clean
            r2.decoded_words.append("ALSO-MUTATED")
            r3 = server.submit("the cat sat .",
                               uuid="m3").result(timeout=10)
            assert r3.decoded_words == clean
        assert dec.dispatches == 1  # both repeats were real hits

    def test_degraded_results_never_cache(self, _isolated_obs):
        """A beam request that fell to greedy under deadline pressure
        is NOT byte-identical to a fresh beam decode — filing it under
        the beam key would poison every later hit, so degraded results
        skip the fill (followers still share them; that is the
        coalescing contract, not the cache's)."""
        hps = tiny_hps(serve_cache_entries=8)
        door = FrontDoor(hps, registry=_isolated_obs)
        kind, flight = door.open("the cat sat .", "beam", "L", "")
        assert kind == "leader"
        from textsummarization_on_flink_tpu.serve.queue import ServeFuture

        fut = ServeFuture("L", registry=_isolated_obs)
        door.commit(flight, fut)
        res = make_result("L")
        res.degraded = True
        fut._resolve(res)
        assert len(door.cache) == 0
        assert _isolated_obs.gauge("serve/cache_entries").value == 0

    def test_hit_observes_entry_age(self, _isolated_obs):
        t = [0.0]
        cache = SummaryCache(8, registry=_isolated_obs,
                             clock=lambda: t[0])
        cache.put(("k", "beam", ""), make_result())
        t[0] = 2.5
        cache.get(("k", "beam", ""))
        h = _isolated_obs.histogram("serve/cache_entry_age_seconds")
        assert h.count == 1 and abs(h.mean - 2.5) < 1e-6


# -- coalescing through the real server ------------------------------------

class TestCoalescing:
    def test_followers_resolve_once_from_one_decode(self, _isolated_obs):
        sink = MemorySink()
        _isolated_obs.event_sink = sink
        hps = tiny_hps(serve_coalesce=True)
        dec = StubDecoder()
        server = ServingServer(hps, make_vocab(), decoder=dec,
                               registry=_isolated_obs)
        futs = [server.submit("the cat sat .", uuid=f"c{i}")
                for i in range(5)]
        futs.append(server.submit("the dog ran .", uuid="d0"))
        server.start()
        results = [f.result(timeout=10) for f in futs]
        server.stop()
        # exactly-once, own identity columns, identical decoded words
        assert [r.uuid for r in results] == \
            ["c0", "c1", "c2", "c3", "c4", "d0"]
        assert len({" ".join(r.decoded_words) for r in results[:5]}) == 1
        assert results[5].decoded_words != results[0].decoded_words
        assert _isolated_obs.counter("serve/coalesced_total").value == 4
        # one decode for the coalesced five: completed counts LEADERS
        assert _isolated_obs.counter("serve/completed_total").value == 2
        events = [r for r in sink.records() if r.get("kind") == "request"]
        co = [e for e in events if e.get("event") == "coalesced"]
        assert len(co) == 4
        assert all(e["attrs"]["leader"] == "c0" for e in co)
        # a follower's timeline closes: coalesced -> resolve, per uuid
        for e in co:
            uid = e["uuid"]
            assert any(r.get("event") == "resolve" and r["uuid"] == uid
                       for r in events)

    def test_leader_failure_fails_followers_typed(self, _isolated_obs):
        hps = tiny_hps(serve_coalesce=True)
        dec = StubDecoder(fail=True)
        server = ServingServer(hps, make_vocab(), decoder=dec,
                               registry=_isolated_obs)
        futs = [server.submit("the cat sat .", uuid=f"c{i}")
                for i in range(3)]
        server.start()
        for f in futs:
            with pytest.raises(RuntimeError, match="injected decode"):
                f.result(timeout=10)
        server.stop()
        # the flight is retired: a NEW submit leads a fresh computation
        dec.fail = False
        server2 = ServingServer(hps, make_vocab(), decoder=dec,
                                registry=_isolated_obs)
        with server2:
            assert server2.submit("the cat sat .",
                                  uuid="n0").result(timeout=10).uuid == "n0"

    def test_abort_rejects_attached_followers(self, _isolated_obs):
        """A leader bounced at admission fails its already-attached
        followers with the same typed cause (never a hang)."""
        door = FrontDoor(tiny_hps(serve_coalesce=True),
                         registry=_isolated_obs)
        kind, flight = door.open("the cat sat .", "beam", "L", "")
        assert kind == "leader"
        kind2, follower = door.open("the cat sat .", "beam", "F", "")
        assert kind2 == "follower"
        door.abort(flight, ServeOverloadError("queue full"))
        with pytest.raises(ServeOverloadError, match="queue full"):
            follower.result(timeout=1)
        assert door.inflight() == 0

    def test_synchronous_submit_error_never_leaks_the_flight(
            self, _isolated_obs):
        """A leader whose submit raises SYNCHRONOUSLY (here: a tier the
        continuous server refuses) must retire its flight — a later
        duplicate leads a FRESH computation instead of attaching to a
        leader that never existed (which would hang forever)."""
        from textsummarization_on_flink_tpu.serve.fleet import FleetRouter

        hps = tiny_hps(serve_coalesce=True, serve_mode="continuous",
                       serve_slots=2, serve_refill_chunk=2)

        class _Eng:
            slots, chunk = 2, 2

            def pack(self, idx, ex):
                pass

            def step(self):
                return []

            def unpack(self, idx, ex):
                raise AssertionError("never reached")

            def release(self, idx):
                pass

        class _Null:
            def maybe_reload_checkpoint(self, last):
                return last

        server = ServingServer(hps, make_vocab(), decoder=_Null(),
                               engine=_Eng(), registry=_isolated_obs)
        router = FleetRouter([server], hps, registry=_isolated_obs)
        # greedy on a continuous fleet: the REPLICA raises ValueError
        # inside router.submit, after the router registered the flight
        with pytest.raises(ValueError, match="beam tier only"):
            router.submit("the cat sat .", uuid="bad0", tier="greedy")
        assert router._door.inflight() == 0, (
            "the failed leader's flight leaked — later duplicates "
            "would hang")
        # and the single-server path: a full queue bounces the leader
        hps2 = tiny_hps(serve_coalesce=True, serve_max_queue=1)
        dec = StubDecoder()
        s2 = ServingServer(hps2, make_vocab(), decoder=dec,
                           registry=_isolated_obs)
        s2.submit("the dog ran .", uuid="fill")  # occupies the queue
        with pytest.raises(ServeOverloadError):
            s2.submit("the cat sat .", uuid="lead0")
        # only the FILL request's (legitimate) flight remains; the
        # bounced leader's was retired
        assert s2._door.inflight() == 1

    def test_coalescing_respects_the_tier_axis(self, _isolated_obs):
        """(content_hash, tier) is the flight key: the same article at
        two tiers never shares a decode (different compiled programs,
        different quality contracts)."""
        hps = tiny_hps(serve_coalesce=True)
        dec = StubDecoder()
        server = ServingServer(hps, make_vocab(), decoder=dec,
                               registry=_isolated_obs)
        f1 = server.submit("the cat sat .", uuid="b0", tier="beam")
        f2 = server.submit("the cat sat .", uuid="g0", tier="greedy")
        server.start()
        r1, r2 = f1.result(timeout=10), f2.result(timeout=10)
        server.stop()
        assert (r1.tier, r2.tier) == ("beam", "greedy")
        assert _isolated_obs.counter("serve/coalesced_total").value == 0


# -- tenant admission -------------------------------------------------------

class TestTenantAdmission:
    def test_bucket_sheds_typed_and_refills_on_the_clock(
            self, _isolated_obs):
        t = [0.0]
        hps = tiny_hps(serve_tenant_rate=2.0, serve_tenant_burst=2)
        door = FrontDoor(hps, registry=_isolated_obs, clock=lambda: t[0])
        door.admit_tenant("acme", "u0")
        door.admit_tenant("acme", "u1")  # burst spent
        with pytest.raises(TenantThrottledError):
            door.admit_tenant("acme", "u2")
        # another tenant's bucket is untouched
        door.admit_tenant("other", "o0")
        assert _isolated_obs.counter("serve/tenant_shed_total").value == 1
        t[0] = 0.5  # 0.5 s at 2/s -> one token back
        door.admit_tenant("acme", "u3")
        with pytest.raises(TenantThrottledError):
            door.admit_tenant("acme", "u4")

    def test_throttled_is_an_overload_subclass(self):
        assert issubclass(TenantThrottledError, ServeOverloadError)

    def test_rate_zero_is_todays_behavior(self, _isolated_obs):
        door = FrontDoor(tiny_hps(), registry=_isolated_obs)
        assert not door.armed
        for i in range(100):
            door.admit_tenant("anyone", f"u{i}")  # never sheds

    def test_burst_resolver_and_weights_parser_validate(self):
        assert resolve_tenant_burst(
            HParams(serve_tenant_rate=0.5)) == 1
        assert parse_fair_weights("a:2, b:0.5") == {"a": 2.0, "b": 0.5}
        with pytest.raises(ValueError, match="tenant:weight"):
            parse_fair_weights("nocolon")
        with pytest.raises(ValueError, match="> 0"):
            parse_fair_weights("a:0")
        with pytest.raises(ValueError, match="names no tenant"):
            parse_fair_weights(":3")
        with pytest.raises(ValueError, match="not a number"):
            HParams(serve_fair_weights="a:x").validate()


# -- cache-fault chaos (satellite 3) ----------------------------------------

class TestCacheFaultChaos:
    def test_cache_fault_degrades_to_miss_and_decode(self, _isolated_obs):
        """With serve.cache_fault armed at p=1, every lookup degrades
        to a miss and every insert drops: requests still decode and
        resolve correctly (never a wrong summary, never a hang), and
        the degradation is counted."""
        hps = tiny_hps(serve_cache_entries=8,
                       faults="serve.cache_fault:1.0:0")
        dec = StubDecoder()
        server = ServingServer(hps, make_vocab(), decoder=dec,
                               registry=_isolated_obs)
        with server:
            r1 = server.submit("the cat sat .",
                               uuid="x1").result(timeout=10)
            r2 = server.submit("the cat sat .",
                               uuid="x2").result(timeout=10)
        assert r1.decoded_words == r2.decoded_words
        assert dec.dispatches == 2, "both must decode (cache dark)"
        assert _isolated_obs.counter("serve/cache_hits_total").value == 0
        assert _isolated_obs.counter(
            "serve/cache_errors_total").value >= 2

    def test_stopped_server_refuses_cached_articles_too(
            self, _isolated_obs):
        """The shutdown contract must not depend on what happens to be
        cached: after stop(), a CACHED article's submit raises the same
        typed ServeClosedError an uncached one does."""
        from textsummarization_on_flink_tpu.serve import ServeClosedError

        hps = tiny_hps(serve_cache_entries=8)
        dec = StubDecoder()
        server = ServingServer(hps, make_vocab(), decoder=dec,
                               registry=_isolated_obs)
        with server:
            server.submit("the cat sat .", uuid="u1").result(timeout=10)
        with pytest.raises(ServeClosedError):
            server.submit("the cat sat .", uuid="u2")  # cached article
        with pytest.raises(ServeClosedError):
            server.submit("the dog ran .", uuid="u3")  # uncached

    def test_healthy_cache_same_workload_hits(self, _isolated_obs):
        """The control run: same workload, no fault — the second
        submit is a hit and must be byte-identical to the first."""
        hps = tiny_hps(serve_cache_entries=8)
        dec = StubDecoder()
        server = ServingServer(hps, make_vocab(), decoder=dec,
                               registry=_isolated_obs)
        with server:
            r1 = server.submit("the cat sat .",
                               uuid="x1").result(timeout=10)
            r2 = server.submit("the cat sat .",
                               uuid="x2").result(timeout=10)
        assert dec.dispatches == 1
        assert r2.as_row()[2] == r1.as_row()[2]  # summary byte-identical
        assert _isolated_obs.counter("serve/cache_hits_total").value == 1


# -- params fingerprint + hot-swap invalidation (satellite 2) ---------------

class TestFingerprintHotSwap:
    def test_hot_swap_changes_fingerprint_and_misses(
            self, _isolated_obs, tmp_path):
        """The acceptance pin on a REAL tiny model: a cache hit is
        byte-identical to its original decode; after a checkpoint
        hot-swap the same article MISSES (new fingerprint) and
        re-decodes under the new params."""
        vocab = make_vocab()
        hps = tiny_hps(vocab_size=vocab.size(), serve_cache_entries=8)
        train_dir = str(tmp_path / "train")
        ck = Checkpointer(train_dir, hps=hps)
        state_a = trainer_lib.init_train_state(hps, vocab.size(), seed=0)
        ck.save(state_a)
        server = ServingServer(
            hps, vocab, train_dir=train_dir,
            decode_root=str(tmp_path / "dec"), registry=_isolated_obs)
        with server:
            fp_a = server.params_fingerprint
            assert fp_a and len(fp_a) == 16
            # /healthz carries the same surface (ISSUE 14 satellite)
            assert _isolated_obs.health_info["params_fingerprint"] == fp_a
            r1 = server.submit("the cat sat on the mat .",
                               uuid="u1").result(timeout=600)
            assert r1.params_fingerprint == fp_a
            done1 = _isolated_obs.counter("serve/completed_total").value
            r2 = server.submit("the cat sat on the mat .",
                               uuid="u2").result(timeout=600)
            # byte-identical hit, no second decode
            assert r2.as_row()[1:] == ("the cat sat on the mat .",
                                       r1.as_row()[2], "")
            assert _isolated_obs.counter(
                "serve/completed_total").value == done1
            assert _isolated_obs.counter(
                "serve/cache_hits_total").value == 1
            # a NEW checkpoint with different params, force-swapped
            state_b = trainer_lib.init_train_state(hps, vocab.size(),
                                                   seed=7)
            state_b = state_b._replace(step=np.asarray(1, np.int32))
            ck.save(state_b)
            assert server.hot_swap()
            fp_b = server.params_fingerprint
            assert fp_b != fp_a, "hot-swap must change the fingerprint"
            assert _isolated_obs.health_info["params_fingerprint"] == fp_b
            r3 = server.submit("the cat sat on the mat .",
                               uuid="u3").result(timeout=600)
            # MISSED and re-decoded under the new snapshot
            assert _isolated_obs.counter(
                "serve/completed_total").value == done1 + 1
            assert r3.params_fingerprint == fp_b

    def test_fingerprint_cached_per_params_object(self, _isolated_obs,
                                                  tmp_path):
        """The sha runs once per swap, not per request: repeated reads
        return the identical object-cached string."""
        from textsummarization_on_flink_tpu.decode.decoder import (
            BeamSearchDecoder,
        )

        vocab = make_vocab()
        hps = tiny_hps(vocab_size=vocab.size())
        params = trainer_lib.init_train_state(hps, vocab.size(),
                                              seed=0).params
        dec = BeamSearchDecoder(hps, vocab, batcher=None, params=params,
                                decode_root=str(tmp_path))
        fp1 = dec.params_fingerprint
        assert dec.params_fingerprint is fp1  # memoized, not recomputed
        # the slot engine reports the SAME surface
        assert dec.slot_engine(slots=2,
                               chunk=2).params_fingerprint == fp1
