"""App driver: train-then-serve end-to-end with pluggable sources/sinks."""

import pytest

from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.data.vocab import Vocab
from textsummarization_on_flink_tpu.pipeline import app as app_lib
from textsummarization_on_flink_tpu.pipeline.io import (
    CollectionSink,
    CollectionSource,
)

WORDS = ("article reference the a quick brown fox jumped over lazy dog "
         "0 1 2 3 4 5 6 7").split()


def rows(n=8):
    return [(f"uuid-{i}", f"article {i} .", "", f"reference {i} .")
            for i in range(n)]


def tiny_hps(tmp_path, mode, **kw):
    base = dict(mode=mode, batch_size=4, hidden_dim=8, emb_dim=6,
                vocab_size=24, max_enc_steps=12, max_dec_steps=6,
                beam_size=2, min_dec_steps=1, max_oov_buckets=4,
                log_root=str(tmp_path), exp_name="exp")
    base.update(kw)
    return HParams(**base)


@pytest.mark.slow
def test_app_main_train_then_serve(tmp_path):
    vocab = Vocab(words=WORDS)
    app = app_lib.App(train_hps=tiny_hps(tmp_path, "train", num_steps=2),
                      inference_hps=tiny_hps(tmp_path, "decode"),
                      vocab=vocab)
    sink = CollectionSink()
    out = app.main(train_source=CollectionSource(rows()),
                   infer_source=CollectionSource(rows(4)),
                   sink=sink)
    assert out is sink
    assert len(sink.rows) == 4
    for uuid, article, summary, reference in sink.rows:
        assert uuid.startswith("uuid-")
        assert isinstance(summary, str)


@pytest.mark.slow
def test_app_inference_from_model_json(tmp_path):
    vocab = Vocab(words=WORDS)
    app = app_lib.App(train_hps=tiny_hps(tmp_path, "train", num_steps=1),
                      inference_hps=tiny_hps(tmp_path, "decode"),
                      vocab=vocab)
    model_json = app.start_training(CollectionSource(rows()))
    assert "inference_selected_cols" in model_json
    sink = app.start_inference(model_json,
                               source=CollectionSource(rows(2)),
                               sink=CollectionSink())
    assert len(sink.rows) == 2


@pytest.mark.slow
def test_app_inference_serving_path(tmp_path):
    """serving=True routes start_inference through the concurrent
    serve/ subsystem (SERVING.md) with the same sources/sinks and the
    same (uuid, article, summary, reference) rows — no API break."""
    from textsummarization_on_flink_tpu import obs
    from textsummarization_on_flink_tpu.obs import Registry

    vocab = Vocab(words=WORDS)
    app = app_lib.App(train_hps=tiny_hps(tmp_path, "train", num_steps=1),
                      inference_hps=tiny_hps(tmp_path, "decode",
                                             serve_max_wait_ms=100.0),
                      vocab=vocab)
    model_json = app.start_training(CollectionSource(rows()))
    with obs.use_registry(Registry()) as reg:
        sink = app.start_inference(model_json,
                                   source=CollectionSource(rows(8)),
                                   sink=CollectionSink(), serving=True)
        assert {r[0] for r in sink.rows} == {f"uuid-{i}" for i in range(8)}
        for uuid, article, summary, reference in sink.rows:
            assert isinstance(summary, str)
        # the serve layer actually ran (and accounted its rows both in
        # its own namespace and the pipeline one)
        assert reg.counter("serve/completed_total").value == 8
        assert reg.counter("pipeline/rows_out_total").value == 8
        assert reg.histogram("serve/batch_fill").count >= 1


def test_default_hps_match_reference_app():
    t = app_lib.default_train_hps("/tmp/x")
    assert (t.batch_size, t.max_enc_steps, t.max_dec_steps) == (2, 50, 10)
    assert t.coverage
    i = app_lib.default_inference_hps("/tmp/x")
    assert (i.batch_size, i.max_enc_steps, i.max_dec_steps,
            i.beam_size, i.min_dec_steps) == (4, 400, 100, 4, 35)
    assert app_lib.TRAIN_TOPIC == "flink_train"
    assert app_lib.INPUT_TOPIC == "flink_input"
    assert app_lib.OUTPUT_TOPIC == "flink_output"


@pytest.mark.slow
def test_streaming_latency_timed_source(tmp_path):
    """SourceSinkTest.java parity: a trickle stream must yield each result
    promptly — a row's summary cannot wait for later rows to arrive
    (the reference's Issue-6 flush bug, Integration Report:879-941)."""
    import time as time_lib

    from textsummarization_on_flink_tpu.pipeline.io import (
        ARTICLE_INPUT_SCHEMA,
        Sink,
        Source,
    )

    vocab = Vocab(words=WORDS)
    app = app_lib.App(train_hps=tiny_hps(tmp_path, "train", num_steps=1),
                      inference_hps=tiny_hps(tmp_path, "decode"),
                      vocab=vocab)
    model_json = app.start_training(CollectionSource(rows(4)))
    # warm the jit cache so the timed phase measures steady-state latency
    app.start_inference(model_json, source=CollectionSource(rows(2)),
                        sink=CollectionSink())

    emit_times = {}
    arrive_times = {}

    class TimedSource(Source):
        schema = ARTICLE_INPUT_SCHEMA

        def rows(self):
            for i, r in enumerate(rows(3)):
                emit_times[r[0]] = time_lib.time()
                yield r
                time_lib.sleep(1.5)

    class TimedSink(Sink):
        def write(self, row):
            arrive_times[row[0]] = time_lib.time()

    app.start_inference(model_json, source=TimedSource(), sink=TimedSink())
    assert len(arrive_times) == 3
    # row 0's summary must land before row 2 was even emitted (3s in):
    assert arrive_times["uuid-0"] < emit_times["uuid-2"], (
        emit_times, arrive_times)
