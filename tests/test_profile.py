"""Performance attribution gate (ISSUE 16 acceptance;
OBSERVABILITY.md "Performance attribution").

Four committed behaviors of obs/profile.py, enforced in tier-1:

  * **phase accounting** — driven over a scripted virtual clock, the
    phase ledger's durations sum EXACTLY to the wall bracket
    (coverage == 1.0), and on the REAL continuous serving stack (sim
    engine over the shared virtual clock, same discipline as
    tests/test_slo_burn.py) the ledger attributes >= 95% of the
    admit -> resolve window;
  * **compile storm** — one compile past a site's committed budget
    dumps the flight ring (``flight_compile_storm.jsonl``) and lands
    on the cached /alerts state;
  * **divergence sentinel** — 10x-the-factor wall inflation on a
    priced shape dumps ``flight_perf_divergence.jsonl``; dispatches at
    the warm baseline stay silent;
  * **null path** — a dark registry gets the shared NULL_PROFILER and
    its per-dispatch record calls allocate nothing (pinned via
    ``sys.getallocatedblocks``).

Plus unit coverage of compiled_call (one shared jit-cache diff,
hit/miss counters + ledger keys) and the /profile HTTP route.
"""

import gc
import json
import sys
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.data.vocab import Vocab
from textsummarization_on_flink_tpu.decode.decoder import DecodedResult
from textsummarization_on_flink_tpu.obs import flightrec
from textsummarization_on_flink_tpu.obs import profile as profile_lib
from textsummarization_on_flink_tpu.obs.registry import Registry


class ScriptClock:
    """A hand-advanced clock: time moves only when the test says so,
    making phase durations exact arithmetic facts."""

    def __init__(self):
        self.t = 0.0

    def now(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class TestPhaseLedger:
    def test_phases_sum_to_wall_exactly_in_virtual_time(self):
        reg = Registry()
        clock = ScriptClock()
        prof = profile_lib.install_profiler(reg, clock=clock.now)
        w0 = prof.start()
        for phase, cost, trace in [("serve/prefill", 0.010, "tr-1"),
                                   ("serve/pack", 0.002, None),
                                   ("serve/dispatch", 0.030, None),
                                   ("serve/harvest", 0.003, None)]:
            t0 = prof.start()
            clock.advance(cost)
            dt = prof.end(phase, t0, trace_id=trace)
            assert dt == pytest.approx(cost)
        wall = prof.end_wall("serve/tick", w0)
        assert wall == pytest.approx(0.045)
        stats = prof.phase_stats()
        assert set(stats) == {"serve/prefill", "serve/pack",
                              "serve/dispatch", "serve/harvest"}
        assert stats["serve/dispatch"] == (1, pytest.approx(0.030),
                                           pytest.approx(0.030))
        # every advanced tick is attributed to a named phase
        assert prof.coverage() == pytest.approx(1.0)
        assert reg.gauge("profile/phase_coverage_ratio").value == \
            pytest.approx(1.0)
        # the ring keeps the trace exemplar for the slowest-dispatch
        # table
        ring = prof.recent_phases()
        assert [r[1] for r in ring] == ["serve/prefill", "serve/pack",
                                        "serve/dispatch", "serve/harvest"]
        assert ring[0][3] == "tr-1"

    def test_unattributed_time_sinks_coverage(self):
        """Clock advance OUTSIDE any phase bracket shows up as missing
        coverage — the accounting check this ledger exists for."""
        prof = profile_lib.install_profiler(Registry(),
                                            clock=(c := ScriptClock()).now)
        w0 = prof.start()
        t0 = prof.start()
        c.advance(0.040)
        prof.end("serve/dispatch", t0)
        c.advance(0.060)  # unattributed: no phase bracket open
        prof.end_wall("serve/tick", w0)
        assert prof.coverage() == pytest.approx(0.4)

    def test_recent_ring_is_bounded(self):
        prof = profile_lib.install_profiler(Registry(),
                                            clock=ScriptClock().now)
        for _ in range(profile_lib.RECENT_PHASES_CAP + 64):
            prof.end("serve/dispatch", prof.start())
        assert len(prof.recent_phases()) == profile_lib.RECENT_PHASES_CAP

    def test_payload_carries_slowest_dispatches_and_notes(self):
        reg = Registry()
        clock = ScriptClock()
        prof = profile_lib.install_profiler(reg, clock=clock.now)
        for dur, trace in [(0.001, "fast"), (0.500, "slow"),
                           (0.002, None)]:
            t0 = prof.start()
            clock.advance(dur)
            prof.end("serve/dispatch", t0, trace_id=trace)
        prof.note("profiler_capture", dir="/tmp/x", start_step=2)
        payload = profile_lib.profile_payload(reg)
        assert payload["installed"]
        slowest = payload["slowest"]
        assert slowest[0]["trace_id"] == "slow"
        assert slowest[0]["dur_s"] == pytest.approx(0.5)
        assert payload["notes"][0]["note"] == "profiler_capture"
        assert payload["notes"][0]["dir"] == "/tmp/x"


class TestCompileLedger:
    def test_compiled_call_diffs_the_jit_cache(self):
        reg = Registry()
        fn = jax.jit(lambda x: x * 2.0)
        out = profile_lib.compiled_call(reg, "decode/step_slots_jit", fn,
                                        jnp.ones((2,)), key="chunk2")
        assert float(out[0]) == 2.0
        profile_lib.compiled_call(reg, "decode/step_slots_jit", fn,
                                  jnp.ones((2,)), key="chunk2")
        site = reg.profile.compile_stats()["decode/step_slots_jit"]
        assert site["compiles"] == 1
        assert site["hits"] == 1
        assert site["keys"] == ["chunk2"]
        assert reg.counter(
            "decode/compile_cache_misses_total").value == 1.0
        assert reg.counter(
            "decode/compile_cache_hits_total").value == 1.0

    def test_compiled_call_books_the_phase_too(self):
        """One timing, both ledgers: `phase=` lands the measured wall
        in the phase ledger alongside the compile event."""
        reg = Registry()
        fn = jax.jit(lambda x: x + 1.0)
        profile_lib.compiled_call(reg, "decode/beam_search_jit", fn,
                                  jnp.ones((2,)), key="scan",
                                  phase="decode/beam_search")
        stats = reg.profile.phase_stats()
        assert stats["decode/beam_search"][0] == 1

    def test_budget_reregistration_keeps_the_max(self):
        prof = profile_lib.install_profiler(Registry())
        prof.set_compile_budget("decode/prefill_jit", 3)
        prof.set_compile_budget("decode/prefill_jit", 2)
        prof.record_compile("decode/prefill_jit", 64, 0.1)
        assert prof.compile_stats()["decode/prefill_jit"]["budget"] == 3

    def test_compile_past_budget_dumps_the_flight_ring(self, tmp_path):
        reg = Registry()
        assert flightrec.install_flight_recorder(
            reg, str(tmp_path)) is not None
        prof = profile_lib.install_profiler(reg)
        prof.set_compile_budget("decode/step_slots_jit", 1)
        prof.record_compile("decode/step_slots_jit", "chunk2", 0.5)
        # within budget: no storm, nothing cached for /alerts
        assert profile_lib.profile_alerts(reg)["compile_storm"] is None
        assert not (tmp_path / "flight_compile_storm.jsonl").exists()
        # the second compile of a budget-1 site IS the storm
        prof.record_compile("decode/step_slots_jit", "chunk4", 0.4)
        dump = tmp_path / "flight_compile_storm.jsonl"
        assert dump.exists(), list(tmp_path.iterdir())
        storm = profile_lib.profile_alerts(reg)["compile_storm"]
        assert storm["site"] == "decode/step_slots_jit"
        assert storm["compiles"] == 2 and storm["budget"] == 1
        assert reg.counter("profile/compile_storms_total").value == 1.0
        # the warm set counts every compile across sites
        assert prof.warm_set_size() == 2
        # the payload serves the same cached storm (scrapes never
        # re-trigger dumps)
        assert profile_lib.profile_payload(
            reg)["compile_ledger"]["storm"]["key"] == "chunk4"


class TestDivergenceSentinel:
    def test_inflated_wall_dumps_silent_at_baseline(self, tmp_path):
        reg = Registry()
        assert flightrec.install_flight_recorder(
            reg, str(tmp_path)) is not None
        prof = profile_lib.install_profiler(reg, divergence_factor=5.0)
        prof.prime_cost("serve/dispatch", "slot_chunk8",
                        flops=1e9, bytes_=1e6)
        # warmup window establishes the baseline (best of the first N)
        for _ in range(profile_lib.BASELINE_SAMPLES):
            prof.observe_dispatch("serve/dispatch", "slot_chunk8", 0.010)
        # judged dispatches at the warm baseline: silent
        prof.observe_dispatch("serve/dispatch", "slot_chunk8", 0.011)
        assert not (tmp_path / "flight_perf_divergence.jsonl").exists()
        assert profile_lib.profile_alerts(reg)["divergence"] == []
        assert reg.counter("profile/divergence_dumps_total").value == 0.0
        # 50x the baseline wall = 10x past the committed 5x factor
        prof.observe_dispatch("serve/dispatch", "slot_chunk8", 0.500,
                              trace_id="tr-div")
        assert (tmp_path / "flight_perf_divergence.jsonl").exists(), \
            list(tmp_path.iterdir())
        assert reg.counter("profile/divergence_dumps_total").value == 1.0
        diverged = profile_lib.profile_alerts(reg)["divergence"]
        assert len(diverged) == 1
        assert diverged[0]["site"] == "serve/dispatch"
        assert diverged[0]["drift"] == pytest.approx(50.0, rel=0.1)
        # achieved-throughput gauges track the LAST dispatch
        assert reg.gauge("profile/achieved_bytes_per_second").labels(
            site="serve/dispatch").value == pytest.approx(1e6 / 0.5)
        assert reg.gauge("profile/achieved_flops_per_second").labels(
            site="serve/dispatch").value == pytest.approx(1e9 / 0.5)

    def test_unpriced_shape_stays_quiet(self):
        reg = Registry()
        prof = profile_lib.install_profiler(reg)
        prof.observe_dispatch("serve/dispatch", "never_priced", 1.0)
        assert profile_lib.profile_payload(reg)["divergence"] == []

    def test_divergence_factor_is_validated(self):
        with pytest.raises(ValueError, match="profile_divergence_factor"):
            HParams(profile_divergence_factor=1.0).validate()


class TestNullPath:
    def test_dark_registry_gets_the_shared_null_profiler(self):
        assert profile_lib.profiler_for(None) is profile_lib.NULL_PROFILER
        assert profile_lib.profiler_for(
            Registry(enabled=False)) is profile_lib.NULL_PROFILER
        assert profile_lib.install_profiler(
            Registry(enabled=False)) is profile_lib.NULL_PROFILER

    def test_null_payload_shape(self):
        payload = profile_lib.profile_payload(None)
        assert payload["installed"] is False
        assert payload["compile_ledger"]["warm_set"] == 0
        alerts = profile_lib.profile_alerts(Registry(enabled=False))
        assert alerts == {"installed": False, "compile_storm": None,
                          "divergence": []}

    def test_null_path_adds_no_per_dispatch_allocation(self):
        """The obs=False pin: a record-path burst through the null
        profiler must not grow the allocated-block count — constants
        out, nothing retained."""
        prof = profile_lib.profiler_for(Registry(enabled=False))
        assert prof is profile_lib.NULL_PROFILER

        def burst(n):
            for _ in range(n):
                t0 = prof.start()
                prof.end("serve/dispatch", t0)
                prof.observe_dispatch("serve/dispatch", "k", 0.001)
                prof.record_hit("decode/step_slots_jit")
                prof.record_compile("decode/step_slots_jit", "k", 0.0)

        burst(64)  # warm any lazy interpreter state first
        gc.collect()
        before = sys.getallocatedblocks()
        burst(512)
        delta = sys.getallocatedblocks() - before
        assert delta <= 16, (
            f"null profiler leaked {delta} blocks over 512 dispatches")


# ---- the real-stack virtual-time gate ---------------------------------

class _VClock:
    def __init__(self):
        self.ms = 0.0

    def now(self) -> float:
        return self.ms / 1000.0


class _NullDecoder:
    def maybe_reload_checkpoint(self, last):
        return last


class _SimEngine:
    """SlotDecodeEngine protocol over the shared virtual clock: pack
    and step are the only operations that cost virtual time, and both
    run inside profiler phase brackets — so whatever fraction the
    ledger fails to attribute is a REAL accounting hole, not jitter."""

    def __init__(self, vclock, slots, chunk, steps_per_req,
                 step_cost_ms, pack_cost_ms):
        self.slots = slots
        self.chunk = chunk
        self._vclock = vclock
        self._steps = steps_per_req
        self._step_cost_ms = step_cost_ms
        self._pack_cost_ms = pack_cost_ms
        self._remaining = [0] * slots
        self._active = [False] * slots

    def pack(self, idx, example):
        assert not self._active[idx]
        self._vclock.ms += self._pack_cost_ms
        self._active[idx] = True
        self._remaining[idx] = self._steps

    def step(self):
        self._vclock.ms += self.chunk * self._step_cost_ms
        fin = []
        for i in range(self.slots):
            if self._active[i]:
                self._remaining[i] -= self.chunk
                if self._remaining[i] <= 0:
                    fin.append(i)
        return fin

    def unpack(self, idx, example):
        assert self._active[idx]
        self._active[idx] = False
        return DecodedResult(
            uuid=example.uuid, article=example.original_article,
            decoded_words=["ok", "."], reference=example.reference,
            abstract_sents=[])

    def release(self, idx):
        self._active[idx] = False


class TestServedRequestCoverage:
    def test_phase_ledger_accounts_admit_to_resolve(self, tmp_path):
        """The acceptance gate: on the real continuous serving stack
        over virtual time, the phase ledger attributes >= 95% of the
        submit -> all-resolved wall window (here it is exact: every
        virtual tick spent belongs to a named phase)."""
        from textsummarization_on_flink_tpu.serve.server import (
            ServingServer,
        )
        vocab = Vocab(words=["w"])
        vclock = _VClock()
        hps = HParams(
            mode="decode", batch_size=2, vocab_size=vocab.size(),
            max_enc_steps=8, max_dec_steps=8, beam_size=2,
            min_dec_steps=1, max_oov_buckets=4, serve_max_queue=16,
            serve_mode="continuous", serve_slots=2,
            serve_refill_chunk=4, log_root=str(tmp_path),
            exp_name="profile_gate")
        reg = Registry()
        sim = _SimEngine(vclock, slots=2, chunk=4, steps_per_req=8,
                         step_cost_ms=5.0, pack_cost_ms=1.0)
        server = ServingServer(hps, vocab, decoder=_NullDecoder(),
                               engine=sim, registry=reg,
                               clock=vclock.now)
        # the server installed the profiler on ITS clock — virtual
        # time in this gate
        assert reg.profile is not None
        t_submit = vclock.now()
        futures = [server.submit("w w w", uuid=f"p{i}")
                   for i in range(4)]
        for _ in range(64):
            if all(f.done() for f in futures):
                break
            server.tick_once(poll=0.0)
        results = [f.result(timeout=0) for f in futures]
        window = vclock.now() - t_submit
        server.stop()
        assert len(results) == 4
        assert all(r.decoded_words == ["ok", "."] for r in results)
        assert window > 0.0
        stats = reg.profile.phase_stats()
        assert {"serve/pack", "serve/dispatch",
                "serve/harvest", "serve/evict"} <= set(stats)
        attributed = sum(total for _, total, _ in stats.values())
        assert attributed >= 0.95 * window, (
            f"phase ledger attributed {attributed:.4f}s of a "
            f"{window:.4f}s admit->resolve window")
        # the wall bracket saw every busy tick, and the committed
        # coverage gauge agrees with the accounting
        assert reg.profile.coverage() >= 0.95
        payload = profile_lib.profile_payload(reg)
        assert [w["wall"] for w in payload["walls"]] == ["serve/tick"]
        # the sim engine never compiles: an empty compile ledger, no
        # storm
        assert payload["compile_ledger"]["warm_set"] == 0
        assert payload["compile_ledger"]["storm"] is None


class TestProfileRoute:
    def _get(self, port, route):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{route}", timeout=10) as resp:
            return resp.status, json.loads(resp.read())

    def test_profile_route_serves_the_payload(self):
        reg = Registry()
        clock = ScriptClock()
        prof = profile_lib.install_profiler(reg, clock=clock.now)
        t0 = prof.start()
        clock.advance(0.010)
        prof.end("serve/dispatch", t0)
        srv = obs.serve_http(0, reg)
        try:
            status, payload = self._get(srv.port, "/profile")
            assert status == 200
            assert payload["installed"]
            assert [p["phase"] for p in payload["phases"]] == \
                ["serve/dispatch"]
            # the profiler's cached state rides /alerts too
            status, alerts = self._get(srv.port, "/alerts")
            assert status == 200
            assert alerts["profile"]["installed"]
            assert alerts["profile"]["compile_storm"] is None
        finally:
            srv.close()

    def test_profile_route_quiet_when_uninstalled(self):
        reg = Registry()
        srv = obs.serve_http(0, reg)
        try:
            status, payload = self._get(srv.port, "/profile")
            assert status == 200
            assert payload["installed"] is False
            assert payload["phases"] == []
        finally:
            srv.close()
