"""Checkpoint lifecycle tests: retention, best-model, surgery, inspector,
TF1 import mapping."""

import json
import os

import jax
import numpy as np
import pytest

from textsummarization_on_flink_tpu.checkpoint import (
    BestModelSaver,
    Checkpointer,
    convert_to_coverage_model,
    latest_checkpoint,
    load_ckpt,
    restore_best_model,
)
from textsummarization_on_flink_tpu.checkpoint import checkpointer as ckpt_lib
from textsummarization_on_flink_tpu.checkpoint.inspect import inspect_arrays
from textsummarization_on_flink_tpu.checkpoint import tf1_import
from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.models import pointer_generator as pg
from textsummarization_on_flink_tpu.train import trainer as trainer_lib


def tiny_hps(**kw):
    base = dict(hidden_dim=8, emb_dim=6, batch_size=4, max_enc_steps=10,
                max_dec_steps=5, beam_size=2, min_dec_steps=2, vocab_size=32,
                max_oov_buckets=4)
    base.update(kw)
    return HParams(**base)


@pytest.fixture()
def state():
    hps = tiny_hps()
    return trainer_lib.init_train_state(hps, hps.vocab_size, seed=3)


def test_save_restore_roundtrip(tmp_path, state):
    ck = Checkpointer(str(tmp_path), hps=tiny_hps())
    path = ck.save(state)
    assert os.path.exists(path)
    restored = ck.restore()
    for a, b in zip(jax.tree_util.tree_leaves(state),
                    jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestShardedRoundTrip:
    """ISSUE 8: checkpoint round-trip of a SHARDED TrainState — save
    from one mesh shape, restore onto a different one against the
    sharding registry's specs, bit-parity after gather; including the
    bf16 opt-state widen (save: npz cannot hold bf16) / narrow
    (restore_sharded re-applies --opt_state_dtype) path."""

    def _mesh_hps(self, **kw):
        # vocab 32 divides tp=2/4; batch 4 divides dp=2/4
        return tiny_hps(**kw)

    @pytest.mark.parametrize("save_mesh,load_mesh",
                             [((4, 2), (2, 2)), ((2, 2), (4, 1))])
    def test_save_sharded_restore_other_mesh_bit_parity(
            self, tmp_path, save_mesh, load_mesh):
        from textsummarization_on_flink_tpu.parallel import mesh as mesh_lib

        hps = self._mesh_hps(dp=save_mesh[0], tp=save_mesh[1])
        state = trainer_lib.init_train_state(hps, hps.vocab_size, seed=3)
        plan_a = mesh_lib.make_mesh(hps)
        sharded = mesh_lib.shard_train_state(plan_a, state)
        ck = Checkpointer(str(tmp_path), hps=hps)
        ck.save(sharded)

        hps_b = self._mesh_hps(dp=load_mesh[0], tp=load_mesh[1])
        plan_b = mesh_lib.make_mesh(hps_b)
        restored = ck.restore_sharded(plan_b)
        assert restored is not None
        # placed against the registry specs on the NEW mesh
        emb = restored.params["embedding"]
        assert emb.sharding.spec == plan_b.registry.param_specs(
            restored.params)["embedding"]
        assert len(emb.sharding.device_set) == load_mesh[0] * load_mesh[1]
        # bit parity with the original host state after gather
        for a, b in zip(jax.tree_util.tree_leaves(state),
                        jax.tree_util.tree_leaves(jax.device_get(restored))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_bf16_opt_state_widen_narrow_round_trip(self, tmp_path):
        """bf16 accumulators widen losslessly to f32 in the npz and
        re-narrow on restore_sharded — bitwise-identical bf16 payloads
        across a mesh-shape change."""
        import jax.numpy as jnp

        from textsummarization_on_flink_tpu.parallel import mesh as mesh_lib

        hps = self._mesh_hps(dp=4, tp=2, opt_state_dtype="bfloat16")
        state = trainer_lib.init_train_state(hps, hps.vocab_size, seed=5)
        acc0 = jax.tree_util.tree_leaves(state.opt_state.accumulators)
        assert all(x.dtype == jnp.bfloat16 for x in acc0)
        plan_a = mesh_lib.make_mesh(hps)
        ck = Checkpointer(str(tmp_path), hps=hps)
        ck.save(mesh_lib.shard_train_state(plan_a, state))
        # the npz holds f32 (npz degrades bf16 to void otherwise)
        flat = ckpt_lib.load_arrays(latest_checkpoint(str(tmp_path)))
        acc_keys = [k for k in flat if k.startswith("opt_state/")]
        assert acc_keys and all(flat[k].dtype == np.float32
                                for k in acc_keys)
        plan_b = mesh_lib.make_mesh(hps.replace(dp=2, tp=2))
        restored = ck.restore_sharded(plan_b)
        for a, b in zip(acc0, jax.tree_util.tree_leaves(
                jax.device_get(restored.opt_state.accumulators))):
            assert b.dtype == jnp.bfloat16
            np.testing.assert_array_equal(
                np.asarray(a, np.float32), np.asarray(b, np.float32))

    def test_restore_sharded_empty_dir_returns_none(self, tmp_path):
        from textsummarization_on_flink_tpu.parallel import mesh as mesh_lib

        hps = self._mesh_hps(dp=2, tp=1)
        ck = Checkpointer(str(tmp_path), hps=hps)
        assert ck.restore_sharded(mesh_lib.make_mesh(hps)) is None


def test_hparams_sidecar_written_on_first_save_not_construction(
        tmp_path, state):
    """ADVICE r3: the constructor is filesystem-only (consulting
    is_chief there would force JAX backend init, which can hang on a
    down TPU tunnel); the provenance sidecar lands with the first
    save."""
    ck = Checkpointer(str(tmp_path), hps=tiny_hps())
    sidecar = os.path.join(str(tmp_path), "hparams.json")
    assert not os.path.exists(sidecar)
    ck.save(state)
    assert os.path.exists(sidecar)
    with open(sidecar, encoding="utf-8") as f:
        assert json.load(f)["hidden_dim"] == tiny_hps().hidden_dim


def test_retention_keeps_three(tmp_path, state):
    ck = Checkpointer(str(tmp_path), max_to_keep=3)
    for step in range(5):
        s = state._replace(step=np.asarray(step, np.int32))
        ck.save(s)
    files = sorted(f for f in os.listdir(tmp_path) if f.endswith(".npz"))
    assert files == ["model.ckpt-2.npz", "model.ckpt-3.npz", "model.ckpt-4.npz"]
    assert latest_checkpoint(str(tmp_path)).endswith("model.ckpt-4.npz")


def test_load_ckpt_raises_when_empty(tmp_path):
    with pytest.raises(FileNotFoundError):
        load_ckpt(str(tmp_path), max_retries=1, retry_secs=0.01)


def test_load_ckpt_finds_latest(tmp_path, state):
    ck = Checkpointer(str(tmp_path))
    ck.save(state._replace(step=np.asarray(7, np.int32)))
    path, flat = load_ckpt(str(tmp_path), max_retries=0)
    assert path.endswith("model.ckpt-7.npz")
    assert "params/embedding" in flat


def test_best_model_saver_keeps_one(tmp_path, state):
    bs = BestModelSaver(str(tmp_path))
    bs(state.params, 3.0, 10)
    bs(state.params, 2.5, 20)
    files = [f for f in os.listdir(tmp_path) if f.startswith("bestmodel")]
    # one checkpoint + its checksum manifest sidecar (RESILIENCE.md)
    assert sorted(files) == ["bestmodel-20.npz", "bestmodel-20.npz.sum"]
    assert latest_checkpoint(
        str(tmp_path), ckpt_lib.BEST_INDEX_FILE).endswith("bestmodel-20.npz")


def test_convert_to_coverage_model(tmp_path, state):
    hps = tiny_hps()
    ck = Checkpointer(str(tmp_path))
    ck.save(state)
    out = convert_to_coverage_model(str(tmp_path), hps, seed=9)
    assert out.endswith("_cov_init.npz")
    new_state = ckpt_lib.arrays_to_state(ckpt_lib.load_arrays(out))
    old_wc = np.asarray(state.params["decoder"]["attention"]["w_c"])
    new_wc = np.asarray(new_state.params["decoder"]["attention"]["w_c"])
    assert not np.allclose(old_wc, new_wc)  # freshly initialized
    np.testing.assert_array_equal(
        np.asarray(new_state.params["embedding"]),
        np.asarray(state.params["embedding"]))
    # fresh accumulator for w_c only
    np.testing.assert_allclose(
        np.asarray(new_state.opt_state.accumulators["decoder"]["attention"]["w_c"]),
        hps.adagrad_init_acc)
    # the index now points at the converted checkpoint
    assert latest_checkpoint(str(tmp_path)) == out


def test_restore_best_model(tmp_path, state):
    hps = tiny_hps()
    eval_dir = str(tmp_path / "eval")
    train_dir = str(tmp_path / "train")
    os.makedirs(train_dir)
    BestModelSaver(eval_dir)(state.params, 1.0, 42)
    out = restore_best_model(eval_dir, train_dir, hps)
    rs = ckpt_lib.arrays_to_state(ckpt_lib.load_arrays(out))
    np.testing.assert_array_equal(np.asarray(rs.params["embedding"]),
                                  np.asarray(state.params["embedding"]))
    np.testing.assert_allclose(
        np.asarray(rs.opt_state.accumulators["embedding"]),
        hps.adagrad_init_acc)
    assert int(rs.step) == 42


def test_inspect_arrays_reports_nans():
    flat = {"good": np.ones(3), "half": np.array([1.0, np.nan]),
            "bad": np.full(2, np.inf), "ints": np.arange(3)}
    rep = inspect_arrays(flat)
    assert rep["finite"] == ["good", "ints"]
    assert rep["some_infnan"] == ["half"]
    assert rep["all_infnan"] == ["bad"]


# ---- TF1 import ----

def _fake_tf1_vars(hps, vsize, include_coverage=True):
    H, E, D = hps.hidden_dim, hps.emb_dim, 2 * hps.hidden_dim
    rng = np.random.RandomState(0)
    dec = tf1_import._DEC
    shapes = {
        "seq2seq/embedding/embedding": (vsize, E),
        "seq2seq/encoder/bidirectional_rnn/fw/lstm_cell/kernel": (E + H, 4 * H),
        "seq2seq/encoder/bidirectional_rnn/fw/lstm_cell/bias": (4 * H,),
        "seq2seq/encoder/bidirectional_rnn/bw/lstm_cell/kernel": (E + H, 4 * H),
        "seq2seq/encoder/bidirectional_rnn/bw/lstm_cell/bias": (4 * H,),
        "seq2seq/reduce_final_st/w_reduce_c": (D, H),
        "seq2seq/reduce_final_st/w_reduce_h": (D, H),
        "seq2seq/reduce_final_st/bias_reduce_c": (H,),
        "seq2seq/reduce_final_st/bias_reduce_h": (H,),
        f"{dec}/W_h": (1, 1, D, D),
        f"{dec}/v": (D,),
        f"{dec}/Attention/Linear/Matrix": (D, D),
        f"{dec}/Attention/Linear/Bias": (D,),
        f"{dec}/Linear/Matrix": (E + D, E),
        f"{dec}/Linear/Bias": (E,),
        f"{dec}/lstm_cell/kernel": (E + H, 4 * H),
        f"{dec}/lstm_cell/bias": (4 * H,),
        f"{dec}/calculate_pgen/Linear/Matrix": (D + H + H + E, 1),
        f"{dec}/calculate_pgen/Linear/Bias": (1,),
        f"{dec}/AttnOutputProjection/Linear/Matrix": (H + D, H),
        f"{dec}/AttnOutputProjection/Linear/Bias": (H,),
        "seq2seq/output_projection/w": (H, vsize),
        "seq2seq/output_projection/v": (vsize,),
        "global_step": (),
    }
    if include_coverage:
        shapes[f"{dec}/coverage/w_c"] = (1, 1, 1, D)
    out = {n: np.asarray(rng.randn(*s), np.float32) for n, s in shapes.items()}
    out["seq2seq/embedding/embedding/Adagrad"] = np.ones((vsize, E), np.float32)
    return out


def test_tf1_import_shapes_match_init(state):
    hps = tiny_hps()
    imported = tf1_import.import_tf1_arrays(
        _fake_tf1_vars(hps, hps.vocab_size))
    ours = state.params
    imp_flat = ckpt_lib._flatten(imported)
    our_flat = ckpt_lib._flatten(ours)
    assert set(imp_flat) == set(our_flat)
    for k in our_flat:
        assert imp_flat[k].shape == our_flat[k].shape, k


def test_tf1_import_runs_forward(state):
    hps = tiny_hps(coverage=True)
    params = tf1_import.import_tf1_arrays(_fake_tf1_vars(hps, hps.vocab_size))
    from __graft_entry__ import _example_arrays
    arrays = _example_arrays(hps, np.random.RandomState(1))
    out = pg.forward_train(params, hps, arrays)
    assert np.isfinite(float(out.total_loss))


def test_tf1_import_missing_coverage_ok(state):
    hps = tiny_hps()
    params = tf1_import.import_tf1_arrays(
        _fake_tf1_vars(hps, hps.vocab_size, include_coverage=False))
    assert "w_c" not in params["decoder"]["attention"]


def test_tf1_import_unmapped_raises():
    with pytest.raises(KeyError):
        tf1_import.import_tf1_arrays({"bogus/var": np.zeros(2)})
