"""Speculative decode tier (ISSUE 10): token-exactness with full-model
greedy decode for BOTH families' verify paths (the transformer's
parallel verify and the adapter-scan fallback), in BOTH disagreement
directions (accept-all and reject-at-0), acceptance-distribution
determinism, compile-once across acceptance patterns, the AAN family's
train/decode consistency and checkpoint-mapped bootstrap, the serving
quality tiers end to end over a real tiny model, and the spec-resident
dispatch-fault chaos contract.

(The AAN beam-adapter parity through all four loop kinds lives in
test_beam_backtrack.py — the family rides the same materialized-history
mirror as the other two.)
"""

import numpy as np
import jax
import pytest

from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.config import HParams, derive_draft_hps
from textsummarization_on_flink_tpu.data.vocab import STOP_ID, Vocab
from textsummarization_on_flink_tpu.decode import beam_search, speculative
from textsummarization_on_flink_tpu.decode.decoder import BeamSearchDecoder
from textsummarization_on_flink_tpu.models import avg_attention, get_family
from textsummarization_on_flink_tpu.obs import Registry
from textsummarization_on_flink_tpu.obs import profile as profile_lib
from textsummarization_on_flink_tpu.serve.server import ServingServer

TF_HPS = HParams(batch_size=3, hidden_dim=8, emb_dim=8, vocab_size=24,
                 max_enc_steps=12, max_dec_steps=8, beam_size=3,
                 min_dec_steps=2, max_oov_buckets=4, mode="decode",
                 model_family="transformer", num_heads=2, enc_layers=2,
                 dec_layers=2, spec_k=3, draft_dec_layers=1)
PG_HPS = TF_HPS.replace(model_family="pointer_generator", emb_dim=6,
                        draft_dec_layers=0)
AAN_HPS = TF_HPS.replace(model_family="avg_attention", draft_dec_layers=0)

FAMILY_CASES = [
    pytest.param(TF_HPS, id="tf-parallel-verify"),
    pytest.param(PG_HPS, id="pg-scan-verify"),
]


@pytest.fixture(autouse=True)
def _isolated_obs():
    with obs.use_registry(Registry()) as reg:
        yield reg


def make_arrays(hps, B, seed=0):
    rng = np.random.RandomState(seed)
    T_enc = hps.max_enc_steps
    enc_lens = rng.randint(T_enc // 2, T_enc + 1, size=(B,)).astype(np.int32)
    mask = (np.arange(T_enc)[None, :] < enc_lens[:, None]).astype(np.float32)
    enc = (rng.randint(0, hps.vocab_size, size=(B, T_enc))
           * mask).astype(np.int32)
    ext = enc.copy()
    oov = rng.rand(B, T_enc) < 0.1
    ext[oov] = hps.vocab_size + rng.randint(0, hps.max_oov_buckets,
                                            size=int(oov.sum()))
    return {"enc_batch": enc, "enc_lens": enc_lens,
            "enc_padding_mask": mask,
            "enc_batch_extend_vocab": ext.astype(np.int32)}


def make_models(hps, seed=0):
    family = get_family(hps.model_family)
    params = family.init_params(hps, hps.vocab_size,
                                jax.random.PRNGKey(seed))
    dhps = derive_draft_hps(hps)
    if hps.model_family == "transformer":
        draft = avg_attention.init_from_transformer(
            params, hps, dhps, jax.random.PRNGKey(seed + 1))
    else:
        draft = avg_attention.init_params(dhps, hps.vocab_size,
                                          jax.random.PRNGKey(seed + 1))
    return params, draft


def assert_spec_matches_greedy(params, draft, hps, arrays):
    """spec output == beam_size=1 beam search (the serving ladder's
    greedy tier) token for token, plus attention/p_gen/score parity."""
    greedy = beam_search.run_beam_search(params, hps.replace(beam_size=1),
                                         arrays)
    spec = speculative.run_spec_decode(params, draft, hps, arrays)
    B = arrays["enc_batch"].shape[0]
    for b in range(B):
        n, ns = int(greedy.length[b]), int(spec.length[b])
        assert n == ns, f"row {b}: greedy len {n} != spec len {ns}"
        gt = list(np.asarray(greedy.tokens[b])[:n])
        st = list(np.asarray(spec.tokens[b])[:n])
        assert gt == st, f"row {b}: {gt} != {st}"
        np.testing.assert_allclose(spec.avg_log_prob[b],
                                   greedy.avg_log_prob[b],
                                   rtol=1e-5, atol=1e-6)
        gen = n - 1
        np.testing.assert_allclose(np.asarray(spec.attn_dists[b])[:gen],
                                   np.asarray(greedy.attn_dists[b])[:gen],
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(spec.p_gens[b])[:gen],
                                   np.asarray(greedy.p_gens[b])[:gen],
                                   rtol=1e-5, atol=1e-6)
    return spec


# -- token exactness --------------------------------------------------------

@pytest.mark.parametrize("hps", FAMILY_CASES)
def test_spec_token_exact_with_greedy(hps):
    """The headline contract: whatever the draft proposes, the emitted
    stream equals full-model greedy decode (several seeds so the
    accept/reject mix varies)."""
    params, draft = make_models(hps)
    for seed in (0, 1, 2):
        assert_spec_matches_greedy(params, draft, hps,
                                   make_arrays(hps, 3, seed=seed))


def test_spec_exact_under_accept_all():
    """Disagreement direction 1: a PERFECT draft (the full model used
    as its own draft — avg_attention full, identical draft params)
    accepts every proposal, and the output is still exactly greedy."""
    hps = AAN_HPS
    family = get_family(hps.model_family)
    params = family.init_params(hps, hps.vocab_size, jax.random.PRNGKey(0))
    arrays = make_arrays(hps, 3)
    spec = assert_spec_matches_greedy(params, params, hps, arrays)
    # every cycle accepted all spec_k proposals
    np.testing.assert_array_equal(spec.accepted, spec.drafted)
    assert int(spec.accept_hist[:, : hps.spec_k].sum()) == 0


def test_spec_exact_under_reject_at_0():
    """Disagreement direction 2: an adversarial draft that always
    proposes one fixed token is rejected at position 0 every cycle —
    one corrected token per cycle, still exactly greedy."""
    hps = TF_HPS
    params, draft = make_models(hps)
    # slam the draft's output bias so it proposes token 7 always; make
    # sure the FULL model never greedily picks 7 by biasing it away
    draft = dict(draft)
    draft["out_bias"] = draft["out_bias"].at[7].set(1e4)
    params = dict(params)
    params["out_bias"] = params["out_bias"].at[7].set(-1e4)
    arrays = make_arrays(hps, 3)
    spec = assert_spec_matches_greedy(params, draft, hps, arrays)
    assert int(spec.accepted.sum()) == 0
    # one emitted token per cycle: cycles == generated token count
    np.testing.assert_array_equal(spec.cycles,
                                  np.asarray(spec.length) - 1)
    np.testing.assert_array_equal(spec.accept_hist[:, 0], spec.cycles)
    assert int(spec.accept_hist[:, 1:].sum()) == 0


# -- determinism + compile discipline ---------------------------------------

def test_spec_acceptance_distribution_deterministic():
    """Fixed seeds in, identical acceptance-length distribution out —
    twice (the speculative loop has no hidden RNG or host state)."""
    hps = TF_HPS
    params, draft = make_models(hps)
    arrays = make_arrays(hps, 3, seed=5)
    one = speculative.run_spec_decode(params, draft, hps, arrays)
    two = speculative.run_spec_decode(params, draft, hps, arrays)
    np.testing.assert_array_equal(one.accept_hist, two.accept_hist)
    np.testing.assert_array_equal(one.tokens, two.tokens)
    np.testing.assert_array_equal(one.cycles, two.cycles)


def test_spec_compiles_once_across_acceptance_patterns(_isolated_obs):
    """Traced accept length (the step_slots_jit discipline): articles
    with different accept/reject patterns — including the adversarial
    reject-everything draft — share ONE compiled program.  Asserted
    through the shared compile ledger (obs/profile.py, ISSUE 16): the
    ledger's per-site miss/hit counts ARE the jit-cache diffs this test
    used to read off run_spec_decode_jit._cache_size() by hand."""
    hps = TF_HPS
    params, draft = make_models(hps)
    jax.clear_caches()  # the ledger counts MISSES; start from cold
    for seed in range(4):
        speculative.run_spec_decode(params, draft, hps,
                                    make_arrays(hps, 3, seed=seed))
    bad_draft = dict(draft)
    bad_draft["out_bias"] = bad_draft["out_bias"].at[7].set(1e4)
    speculative.run_spec_decode(params, bad_draft, hps,
                                make_arrays(hps, 3, seed=9))
    prof = profile_lib.profiler_for(_isolated_obs)
    site = prof.compile_stats()["decode/spec_decode_jit"]
    assert site["compiles"] == 1, (
        "speculative decode recompiled across acceptance patterns: "
        f"{site}")
    assert site["hits"] == 4, site
    assert site["keys"] == [str(int(hps.spec_k))], site


# -- acceptance-adaptive spec_k (ISSUE 12) ----------------------------------

def _budget_adaptive():
    import json
    import os

    path = os.path.join(os.path.dirname(__file__), "..",
                        "BYTE_BUDGET.json")
    with open(path) as f:
        return json.load(f)["spec"]["adaptive"]


class TestAdaptiveSpecK:
    def test_k_never_leaves_committed_bounds(self):
        """Property: whatever histogram stream arrives, k stays in
        [k_min, k_max] (including degenerate all-zero deltas)."""
        ctl = speculative.SpecKController(2, 3, 6, draft_ratio=0.25)
        rng = np.random.RandomState(0)
        for _ in range(200):
            k = ctl.k
            hist = rng.randint(0, 5, size=k + 1)
            if rng.rand() < 0.2:
                hist[:] = 0
            ctl.observe(hist, k)
            assert 2 <= ctl.k <= 6, ctl.k

    def test_trajectory_pinned_deterministic(self):
        """The committed BYTE_BUDGET.json spec.adaptive trajectories:
        the k walk under fixed accept sequences at the committed draft
        ratio is EXACTLY the pinned one, twice (no hidden state, no
        RNG, no clock)."""
        ad = _budget_adaptive()
        cases = {
            "accept_all_trajectory": lambda k, n: [0] * k + [n],
            "reject_at_0_trajectory": lambda k, n: [n] + [0] * k,
            "half_accept_trajectory":
                lambda k, n: [0] * (k // 2) + [n] + [0] * (k - k // 2),
        }
        per = int(ad["cycles_per_round"])
        for name, hist_fn in cases.items():
            want = ad[name]
            for _attempt in range(2):
                ctl = speculative.SpecKController(
                    int(ad["k_min"]), int(ad["k_start"]),
                    int(ad["k_max"]), float(ad["draft_ratio"]))
                got = []
                for _ in range(len(want)):
                    got.append(ctl.observe(hist_fn(ctl.k, per), ctl.k))
                assert got == want, (name, got, want)

    def test_adaptive_exact_and_converges_up_under_accept_all(self):
        """The self-draft harness (perfect draft): output stays exactly
        greedy with k adapting, and over enough batches the controller
        climbs to spec_k_max."""
        hps = AAN_HPS.replace(spec_k_adaptive=True, spec_k=2,
                              spec_k_min=1, spec_k_max=6)
        hps.validate()
        family = get_family(hps.model_family)
        params = family.init_params(hps, hps.vocab_size,
                                    jax.random.PRNGKey(0))
        ctl = speculative.SpecKController.from_hps(hps, draft_ratio=0.25)
        for seed in range(6):
            arrays = make_arrays(hps, 3, seed=seed)
            greedy = beam_search.run_beam_search(
                params, hps.replace(beam_size=1), arrays)
            out = speculative.run_spec_decode(params, params, hps,
                                              arrays, controller=ctl)
            for b in range(3):
                n = int(greedy.length[b])
                assert n == int(out.length[b])
                assert (list(np.asarray(greedy.tokens[b])[:n])
                        == list(np.asarray(out.tokens[b])[:n]))
        assert ctl.k == hps.spec_k_max, (ctl.k, ctl.alpha)

    def test_adaptive_exact_and_converges_down_under_reject_at_0(self):
        """The adversarial out_bias harness (always-rejected draft):
        output stays exactly greedy and the controller settles at
        spec_k_min — never paying more than the minimum draft steps
        for zero expected acceptance."""
        hps = TF_HPS.replace(spec_k_adaptive=True, spec_k=3,
                             spec_k_min=1, spec_k_max=5)
        hps.validate()
        params, draft = make_models(hps)
        draft = dict(draft)
        draft["out_bias"] = draft["out_bias"].at[7].set(1e4)
        params = dict(params)
        params["out_bias"] = params["out_bias"].at[7].set(-1e4)
        ctl = speculative.SpecKController.from_hps(hps, draft_ratio=0.25)
        for seed in range(3):
            arrays = make_arrays(hps, 3, seed=seed)
            greedy = beam_search.run_beam_search(
                params, hps.replace(beam_size=1), arrays)
            out = speculative.run_spec_decode(params, draft, hps,
                                              arrays, controller=ctl)
            for b in range(3):
                n = int(greedy.length[b])
                assert n == int(out.length[b])
                assert (list(np.asarray(greedy.tokens[b])[:n])
                        == list(np.asarray(out.tokens[b])[:n]))
        # (acceptance is NEAR zero, not exactly zero: on some articles
        # the pointer COPY path re-ranks token 7 into the full model's
        # greedy choice despite the vocab bias — the zero-acceptance
        # direction itself is pinned by test_spec_exact_under_reject_at_0)
        assert ctl.k == hps.spec_k_min, (ctl.k, ctl.alpha)

    def test_warm_set_bounded_one_compile_per_distinct_k(
            self, _isolated_obs):
        """The compile discipline: the cycle kernel compiles once per
        DISTINCT k the controller visits (carry shapes ride spec_k_max,
        so k changes never reshape), and repeats at a warm k add
        nothing.  Asserted through the shared compile ledger
        (obs/profile.py, ISSUE 16), whose per-k keys also pin WHICH k's
        compiled — and whose committed budget (one kernel per k in
        [k_min, k_max]) must not have fired a compile storm."""
        hps = TF_HPS.replace(spec_k_adaptive=True, spec_k=3,
                             spec_k_min=1, spec_k_max=5)
        hps.validate()
        params, draft = make_models(hps)
        jax.clear_caches()  # the ledger counts MISSES; start from cold
        ks_seen = set()

        class Spy(speculative.SpecKController):
            def update(self):
                super().update()
                ks_seen.add(self.k)
                return self.k

        ctl = Spy(hps.spec_k_min, hps.spec_k, hps.spec_k_max,
                  draft_ratio=0.25)
        ks_seen.add(ctl.k)
        for seed in range(4):
            speculative.run_spec_decode(params, draft, hps,
                                        make_arrays(hps, 3, seed=seed),
                                        controller=ctl)
        prof = profile_lib.profiler_for(_isolated_obs)
        site = prof.compile_stats()["decode/spec_cycle_jit"]
        budget = hps.spec_k_max - hps.spec_k_min + 1
        assert site["compiles"] == len(ks_seen), (site, sorted(ks_seen))
        assert site["keys"] == sorted(str(k) for k in ks_seen), site
        assert site["compiles"] <= budget
        assert site["budget"] == budget, site
        # within budget => the storm trigger stayed silent
        assert profile_lib.profile_alerts(
            _isolated_obs)["compile_storm"] is None

    def test_decoder_accept_hist_buckets_span_k_max(self, _isolated_obs):
        """The ISSUE-12 satellite fix: the accept-length histogram's
        buckets cover 0..spec_k_max (resolve_spec_bounds), so adaptive
        cycles at k > spec_k can't pile into one overflow bin."""
        import tempfile

        hps = serve_hps(spec_k_adaptive=True, spec_k=2, spec_k_min=1,
                        spec_k_max=7)
        family = get_family(hps.model_family)
        params = family.init_params(hps, hps.vocab_size,
                                    jax.random.PRNGKey(0))
        decoder = BeamSearchDecoder(
            hps, serve_vocab(), batcher=None, params=params,
            decode_root=tempfile.mkdtemp(prefix="spec_bkt_"))
        assert decoder._h_accept.buckets == tuple(
            float(i) for i in range(0, hps.spec_k_max + 1))
        assert decoder._spec_ctl is not None
        assert decoder._spec_ctl.k == hps.spec_k


# -- AAN family: train/decode consistency + mapped bootstrap ----------------

class TestAvgAttentionFamily:
    def test_train_decode_consistency(self):
        """Teacher-forced forward_train and the O(1) decode step agree
        on the same forced tokens (cumsum vs running-sum only differ in
        summation order -> tight tolerance, not bitwise)."""
        hps = AAN_HPS.replace(batch_size=2, mode="train")
        family = get_family("avg_attention")
        params = family.init_params(hps, hps.vocab_size,
                                    jax.random.PRNGKey(0))
        rng = np.random.RandomState(0)
        T_enc, T_dec = hps.max_enc_steps, hps.max_dec_steps
        arrays = make_arrays(hps, 2)
        dec = rng.randint(1, hps.vocab_size, size=(2, T_dec)).astype(np.int32)
        arrays.update({
            "dec_batch": dec,
            "target_batch": np.roll(dec, -1, axis=1),
            "dec_padding_mask": np.ones((2, T_dec), np.float32),
        })
        out = family.forward_train(params, hps, arrays)
        assert np.isfinite(float(out.total_loss))
        # decode path: feed the same forced tokens through the adapter
        enc_view = family.beam_encode(params, hps, arrays)
        init_fn, step_fn = family.beam_adapter(hps.replace(beam_size=1))
        for b in range(2):
            enc_one = jax.tree_util.tree_map(lambda x, b=b: x[b], enc_view)
            state = init_fn(params, enc_one)
            for t in range(T_dec):
                step = step_fn(params, enc_one,
                               arrays["enc_padding_mask"][b],
                               arrays["enc_batch_extend_vocab"][b],
                               np.int32(t), dec[b, t:t + 1], state)
                state = step.state
                np.testing.assert_allclose(
                    np.asarray(step.attn_dist[0]),
                    np.asarray(out.attn_dists[b, t]),
                    rtol=1e-4, atol=1e-5)
                np.testing.assert_allclose(
                    float(step.p_gen[0]), float(out.p_gens[b, t]),
                    rtol=1e-4, atol=1e-5)

    def test_mapped_bootstrap_copies_shared_leaves(self):
        hps = TF_HPS.replace(dec_layers=4, draft_dec_layers=2)
        full = get_family("transformer").init_params(
            hps, hps.vocab_size, jax.random.PRNGKey(0))
        dhps = derive_draft_hps(hps)
        draft = avg_attention.init_from_transformer(
            full, hps, dhps, jax.random.PRNGKey(1))
        np.testing.assert_array_equal(draft["embedding"],
                                      full["embedding"])
        np.testing.assert_array_equal(draft["out_bias"], full["out_bias"])
        assert len(draft["decoder"]["layers"]) == 2
        # evenly strided subset keeps first and last full layers
        keep = avg_attention.draft_layer_indices(4, 2)
        assert keep == [0, 3]
        for dst, src_idx in zip(draft["decoder"]["layers"], keep):
            src = full["decoder"]["layers"][src_idx]
            np.testing.assert_array_equal(dst["cross_attn"]["wq"],
                                          src["cross_attn"]["wq"])
            np.testing.assert_array_equal(dst["ffn"]["w1"],
                                          src["ffn"]["w1"])
            assert "aan_ffn" in dst and "aan_gate" in dst

    def test_narrow_mapped_bootstrap_shares_encoder_only(self):
        """The ISSUE-12 narrow variant: shared H-wide leaves copied
        verbatim (embedding, encoder, out_bias), the H_d decoder side
        fresh (emb_proj adapter, factored vocab_head, H_d blocks) —
        and the spec output is STILL exactly greedy (exactness never
        depended on draft quality)."""
        hps = TF_HPS.replace(draft_hidden=4, draft_vocab_rank=4)
        hps.validate()
        full = get_family("transformer").init_params(
            hps, hps.vocab_size, jax.random.PRNGKey(0))
        dhps = derive_draft_hps(hps)
        draft = avg_attention.init_from_transformer(
            full, hps, dhps, jax.random.PRNGKey(1))
        np.testing.assert_array_equal(draft["embedding"],
                                      full["embedding"])
        np.testing.assert_array_equal(
            draft["encoder"]["layers"][0]["ffn"]["w1"],
            full["encoder"]["layers"][0]["ffn"]["w1"])
        assert draft["emb_proj"]["kernel"].shape == (hps.hidden_dim, 4)
        assert draft["vocab_head"]["w1"].shape == (4, 4)
        assert draft["vocab_head"]["w2"].shape == (4, hps.vocab_size)
        layer = draft["decoder"]["layers"][0]
        assert layer["cross_attn"]["wk"].shape == (hps.hidden_dim, 4)
        assert layer["cross_attn"]["wq"].shape == (4, 4)
        assert_spec_matches_greedy(full, draft, hps,
                                   make_arrays(hps, 3))
        # fresh narrow init keeps exactness too (the other init mode)
        fresh = avg_attention.init_params(dhps, hps.vocab_size,
                                          jax.random.PRNGKey(2))
        assert_spec_matches_greedy(full, fresh, hps,
                                   make_arrays(hps, 3, seed=1))

    def test_narrow_draft_requires_factored_head(self):
        with pytest.raises(ValueError, match="factored vocab head"):
            TF_HPS.replace(draft_hidden=4).validate()

    def test_mapped_bootstrap_rejects_non_transformer(self):
        hps = PG_HPS
        params = get_family("pointer_generator").init_params(
            hps, hps.vocab_size, jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="transformer checkpoints"):
            avg_attention.init_from_transformer(
                params, hps, derive_draft_hps(hps), jax.random.PRNGKey(1))

    def test_trainable(self):
        """The family trains through the shared loss head: finite loss,
        finite grads on both AAN-specific and shared leaves."""
        hps = AAN_HPS.replace(batch_size=2, mode="train", loss_chunk=4)
        family = get_family("avg_attention")
        params = family.init_params(hps, hps.vocab_size,
                                    jax.random.PRNGKey(0))
        arrays = make_arrays(hps, 2)
        rng = np.random.RandomState(1)
        T_dec = hps.max_dec_steps
        dec = rng.randint(1, hps.vocab_size, size=(2, T_dec)).astype(np.int32)
        arrays.update({"dec_batch": dec,
                       "target_batch": np.roll(dec, -1, axis=1),
                       "dec_padding_mask": np.ones((2, T_dec), np.float32)})

        def loss_fn(p):
            return family.forward_train(p, hps, arrays).total_loss

        grads = jax.grad(loss_fn)(params)
        flat = jax.tree_util.tree_leaves(grads)
        assert all(np.all(np.isfinite(np.asarray(g))) for g in flat)
        aan_g = grads["decoder"]["layers"][0]["aan_gate"]["kernel"]
        assert float(np.abs(np.asarray(aan_g)).sum()) > 0


# -- decoder + serving tiers over a real tiny model -------------------------

def serve_vocab():
    return Vocab(words=["the", "a", "cat", "dog", "sat", "ran", "mat",
                        "it", "was", "."])


def serve_hps(**kw):
    base = dict(mode="decode", batch_size=3, hidden_dim=8, emb_dim=8,
                vocab_size=16, max_enc_steps=12, max_dec_steps=6,
                beam_size=2, min_dec_steps=1, max_oov_buckets=4,
                model_family="transformer", num_heads=2, enc_layers=1,
                dec_layers=2, spec_k=2, draft_dec_layers=1,
                spec_draft="map", serve_max_wait_ms=50.0,
                serve_max_queue=32)
    base.update(kw)
    hps = HParams(**base)
    hps.validate()
    return hps


class TestServingTiers:
    def _server(self, reg, **kw):
        hps = serve_hps(**kw)
        vocab = serve_vocab()
        family = get_family(hps.model_family)
        params = family.init_params(hps, vocab.size(),
                                    jax.random.PRNGKey(0))
        import tempfile

        decoder = BeamSearchDecoder(
            hps, vocab, batcher=None, params=params,
            decode_root=tempfile.mkdtemp(prefix="spec_tier_"))
        return ServingServer(hps, vocab, decoder=decoder, registry=reg), \
            decoder

    def test_spec_tier_matches_greedy_tier_rows(self, _isolated_obs):
        server, _ = self._server(_isolated_obs)
        with server:
            greedy = [server.submit(f"the cat sat {i} .", uuid=f"g{i}",
                                    tier="greedy").result(timeout=600)
                      for i in range(3)]
            spec = [server.submit(f"the cat sat {i} .", uuid=f"s{i}",
                                  tier="spec").result(timeout=600)
                    for i in range(3)]
        for g, s in zip(greedy, spec):
            assert g.decoded_words == s.decoded_words, (g.uuid, s.uuid)
            assert s.tier == "spec" and g.tier == "greedy"
        assert _isolated_obs.counter("serve/tier_spec_total").value == 3
        assert _isolated_obs.counter("serve/tier_greedy_total").value == 3
        assert _isolated_obs.counter(
            "decode/spec_cycles_total").value > 0

    def test_spec_tier_adaptive_serves_exact_rows(self, _isolated_obs):
        """The adaptive controller through the FULL serving surface:
        spec-tier rows stay identical to greedy-tier rows, the decoder
        holds one persistent controller across requests, and its pick
        is exported on the decode/spec_k_current gauge."""
        server, decoder = self._server(_isolated_obs,
                                       spec_k_adaptive=True, spec_k=2,
                                       spec_k_min=1, spec_k_max=4)
        with server:
            greedy = [server.submit(f"the cat sat {i} .", uuid=f"g{i}",
                                    tier="greedy").result(timeout=600)
                      for i in range(2)]
            spec = [server.submit(f"the cat sat {i} .", uuid=f"s{i}",
                                  tier="spec").result(timeout=600)
                    for i in range(2)]
        for g, s in zip(greedy, spec):
            assert g.decoded_words == s.decoded_words, (g.uuid, s.uuid)
        ctl = decoder._spec_ctl
        assert ctl is not None and ctl.cycles > 0
        assert 1 <= ctl.k <= 4
        assert _isolated_obs.gauge(
            "decode/spec_k_current").value == float(ctl.k)

    def test_draft_tier_serves_and_counts(self, _isolated_obs):
        server, _ = self._server(_isolated_obs)
        with server:
            res = server.submit("the dog ran .", uuid="d0",
                                tier="draft").result(timeout=600)
        assert res.tier == "draft"
        assert _isolated_obs.counter("serve/tier_draft_total").value == 1

    def test_tier_validation_at_submit(self, _isolated_obs):
        server, _ = self._server(_isolated_obs, spec_draft="")
        with server:
            with pytest.raises(ValueError, match="one of"):
                server.submit("the cat .", tier="warp")
            with pytest.raises(ValueError, match="draft model"):
                server.submit("the cat .", tier="spec")

    def test_spec_resident_dispatch_fault_typed_exactly_once(
            self, _isolated_obs):
        """Chaos (ISSUE 10 satellite): an injected serve.dispatch fault
        while spec-tier requests are resident fails THOSE requests with
        the typed cause, each exactly once; the server lives on and the
        next spec request serves."""
        server, _ = self._server(_isolated_obs,
                                 faults="serve.dispatch:1.0:0:1")
        with server:
            bad = [server.submit(f"the cat {i} .", uuid=f"bad{i}",
                                 tier="spec") for i in range(2)]
            errors = []
            for f in bad:
                with pytest.raises(RuntimeError, match="injected"):
                    f.result(timeout=600)
                errors.append(f.error)
                # exactly-once: the future is terminal; a second resolve
                # would have raised inside the dispatcher (ServeFuture
                # contract) and the error is the typed injected cause
                assert f.done() and isinstance(f.error, RuntimeError)
            ok = server.submit("the dog ran .", uuid="ok",
                               tier="spec").result(timeout=600)
            assert ok.uuid == "ok" and ok.tier == "spec"
        assert _isolated_obs.counter("serve/errors_total").value == 2
        assert _isolated_obs.counter("serve/tier_spec_total").value == 1

    def test_continuous_mode_rejects_non_beam_tiers(self, _isolated_obs):
        hps = serve_hps(serve_mode="continuous", spec_draft="")

        class StubEngine:
            slots = 2

            def release(self, idx):
                pass

        server = ServingServer(hps, serve_vocab(), decoder=object(),
                               engine=StubEngine(), registry=_isolated_obs)
        with pytest.raises(ValueError, match="beam tier only"):
            server.submit("the cat .", tier="spec")


def test_decoder_rejects_spec_without_draft():
    hps = serve_hps(spec_draft="")
    vocab = serve_vocab()
    params = get_family(hps.model_family).init_params(
        hps, vocab.size(), jax.random.PRNGKey(0))
    import tempfile

    from textsummarization_on_flink_tpu.data.batching import (
        Batch,
        SummaryExample,
    )

    decoder = BeamSearchDecoder(hps, vocab, batcher=None, params=params,
                                decode_root=tempfile.mkdtemp(prefix="sd_"))
    assert not decoder.has_draft
    ex = SummaryExample.build("the cat .", [], vocab, hps, uuid="u")
    batch = Batch([ex] * hps.batch_size, hps, vocab)
    with pytest.raises(ValueError, match="draft model"):
        decoder.decode_batch(batch, tier="spec")
    with pytest.raises(ValueError, match="tier must be"):
        decoder.decode_batch(batch, tier="warp")
