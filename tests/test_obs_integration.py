"""End-to-end observability assertions (ISSUE 1 acceptance criteria):
a 50-step CPU training run populates the step-time histogram, the
prefetcher queue-depth gauge, and the examples counter; a decode of one
batch populates the per-request latency histogram; the PrefetchError
and SummaryWriter-rotation satellites behave as specified."""

import json
import os
import shutil

import numpy as np
import pytest

from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.data.batcher import Batcher
from textsummarization_on_flink_tpu.data.batching import Batch, SummaryExample
from textsummarization_on_flink_tpu.data.vocab import Vocab
from textsummarization_on_flink_tpu.decode import decoder as dec_lib
from textsummarization_on_flink_tpu.obs.registry import Registry
from textsummarization_on_flink_tpu.train import trainer as trainer_lib
from textsummarization_on_flink_tpu.train.trainer import (
    DevicePrefetcher,
    PrefetchError,
    SummaryWriter,
    Trainer,
)

WORDS = ("the a cat dog sat ran mat home big small quick brown fox jumped "
         "over lazy it was day night").split()


def hps_tiny(**kw):
    base = dict(batch_size=2, max_enc_steps=8, max_dec_steps=5,
                min_dec_steps=1, hidden_dim=4, emb_dim=3, max_oov_buckets=2,
                vocab_size=0, beam_size=2)
    base.update(kw)
    return HParams(**base)


@pytest.fixture
def vocab():
    return Vocab(words=WORDS)


def make_source(n):
    def src():
        return iter([(f"the quick brown fox {WORDS[i % len(WORDS)]} .",
                      f"<s> the fox {WORDS[i % len(WORDS)]} . </s>")
                     for i in range(n)])
    return src


class TestTrainRunTelemetry:
    def test_50_step_run_populates_registry(self, tmp_path, vocab):
        """The acceptance-criteria run: 50 steps on CPU through the REAL
        threaded Batcher + DevicePrefetcher, then render_text() must
        show a non-zero step-time histogram, the prefetcher queue-depth
        gauge, and the examples counters."""
        hps = hps_tiny(log_root=str(tmp_path), exp_name="t", num_steps=50)
        with obs.use_registry(Registry()):
            batcher = Batcher("", vocab, hps, single_pass=True,
                              example_source=make_source(120))
            trainer = Trainer(hps, vocab.size(), batcher)
            state = trainer.train(num_steps=50)
            assert int(np.asarray(state.step)) == 50
            reg = obs.registry()
            text = reg.render_text()

        # step-time histogram: one sample per step, all positive
        h = reg.get("train/step_time_seconds")
        assert h.count == 50
        assert h.sum > 0 and h.percentile(50) > 0
        # steps/examples counters (examples/sec = counter over wall time)
        assert reg.get("train/steps_total").value == 50
        assert reg.get("train/examples_total").value == 50 * hps.batch_size
        assert reg.get("data/examples_total").value >= 100
        # prefetcher telemetry: the gauge was written, pulls were counted
        assert reg.get("train/prefetch_queue_depth") is not None
        assert reg.get("train/prefetch_batches_total").value >= 50
        # the host-wait and metrics-fetch histograms saw every window
        assert reg.get("train/host_wait_seconds").count >= 50
        assert reg.get("train/metrics_fetch_seconds").count >= 1
        # text exposition carries all of it
        assert "train_step_time_seconds_count 50" in text
        assert "train_prefetch_queue_depth" in text
        assert "train_examples_total 100" in text

    def test_disabled_run_records_nothing(self, tmp_path, vocab):
        """TS_OBS=0-equivalent: hps.obs=False routes the whole job
        through the null registry — zero metrics, same training result
        (the <2%-overhead claim is structural: disabled call sites hold
        shared null singletons; see test_obs.py null-identity tests)."""
        hps = hps_tiny(log_root=str(tmp_path), exp_name="d", obs=False)
        with obs.use_registry(Registry()):
            batcher = Batcher("", vocab, hps, single_pass=True,
                              example_source=make_source(30))
            trainer = Trainer(hps, vocab.size(), batcher)
            assert trainer._m_step_time is obs.NULL_HISTOGRAM
            state = trainer.train(num_steps=5)
            assert int(np.asarray(state.step)) == 5
            assert obs.registry().snapshot(compact=True) == {}

    def test_ts_obs_events_streams_spans_to_events_jsonl(self, tmp_path,
                                                         vocab, monkeypatch):
        """TS_OBS_EVENTS=1: span records share the scalar summaries'
        events.jsonl (the unified format one trace_summary.py reads)."""
        monkeypatch.setenv("TS_OBS_EVENTS", "1")
        hps = hps_tiny(log_root=str(tmp_path), exp_name="ev")
        with obs.use_registry(Registry()):
            batcher = Batcher("", vocab, hps, single_pass=True,
                              example_source=make_source(30))
            trainer = Trainer(hps, vocab.size(), batcher)
            trainer.train(num_steps=4)
            trainer.writer.close()
            sink = obs.registry().event_sink
            assert sink is not None
            sink.close()
        events = os.path.join(str(tmp_path), "ev", "train", "events.jsonl")
        recs = [json.loads(ln) for ln in open(events, encoding="utf-8")]
        kinds = {r.get("kind", "scalar") for r in recs}
        assert "scalar" in kinds and "span" in kinds
        span_names = {r["name"] for r in recs if r.get("kind") == "span"}
        assert "train/metrics_flush" in span_names

    def test_summary_scalars_unaffected_by_obs(self, tmp_path, vocab):
        """The JSONL summaries keep one record per step either way."""
        hps = hps_tiny(log_root=str(tmp_path), exp_name="s",
                       summary_flush_every=4)
        with obs.use_registry(Registry()):
            batcher = Batcher("", vocab, hps, single_pass=True,
                              example_source=make_source(30))
            trainer = Trainer(hps, vocab.size(), batcher)
            trainer.train(num_steps=6)
            trainer.writer.close()
        events = os.path.join(str(tmp_path), "s", "train", "events.jsonl")
        recs = [json.loads(ln) for ln in open(events, encoding="utf-8")]
        assert [r["step"] for r in recs] == list(range(1, 7))


class TestDecodeTelemetry:
    def test_one_batch_decode_populates_latency_histogram(self, vocab,
                                                          tmp_path):
        hps = hps_tiny(mode="decode")
        with obs.use_registry(Registry()):
            state = trainer_lib.init_train_state(hps, vocab.size(), seed=0)
            d = dec_lib.BeamSearchDecoder(hps, vocab, batcher=None,
                                          params=state.params,
                                          decode_root=str(tmp_path))
            exs = [SummaryExample.build(
                f"the quick brown fox {w} .", ["the fox ."], vocab, hps)
                for w in ("sat", "ran")]
            batch = Batch(exs, hps, vocab)
            results = d.decode_batch(batch)
            reg = obs.registry()
        assert len(results) == 2
        h = reg.get("decode/request_latency_seconds")
        assert h.count == 2 and h.percentile(50) > 0
        assert reg.get("decode/requests_total").value == 2
        assert reg.get("decode/tokens_total").value >= 0
        assert reg.get("decode/busy_seconds_total").value > 0
        # the dispatch went through run_beam_search: its first call is a
        # compile-cache miss, and the span was recorded
        misses = reg.get("decode/compile_cache_misses_total")
        assert misses is not None and misses.value >= 1
        names = [s.name for s in obs.tracer_for(reg).finished()]
        assert "decode/batch" in names

    def test_compile_cache_hit_on_second_batch(self, vocab, tmp_path):
        hps = hps_tiny(mode="decode")
        with obs.use_registry(Registry()):
            state = trainer_lib.init_train_state(hps, vocab.size(), seed=0)
            d = dec_lib.BeamSearchDecoder(hps, vocab, batcher=None,
                                          params=state.params,
                                          decode_root=str(tmp_path))
            exs = [SummaryExample.build(
                f"the quick brown fox {w} .", ["the fox ."], vocab, hps)
                for w in ("sat", "ran")]
            d.decode_batch(Batch(exs, hps, vocab))
            d.decode_batch(Batch(exs, hps, vocab))
            hits = obs.registry().get("decode/compile_cache_hits_total")
        # same shapes/config: the second dispatch reuses the executable
        assert hits is not None and hits.value >= 1


class TestPrefetchErrorSatellite:
    class _FailingBatcher:
        def __init__(self, n_good=0):
            self.n_good = n_good

        def next_batch(self):
            if self.n_good > 0:
                self.n_good -= 1
                return object()
            raise IOError("disk gone")

    def test_worker_failure_surfaces_as_typed_error(self):
        with obs.use_registry(Registry()):
            p = DevicePrefetcher(self._FailingBatcher(), transfer=lambda a: a)
            with pytest.raises(PrefetchError) as ei:
                p.next_batch()
            p.stop()
            assert isinstance(ei.value.__cause__, IOError)
            # the failure path feeds the error counter
            assert obs.registry().get(
                "train/prefetch_errors_total").value == 1

    def test_prefetch_error_is_runtime_error(self):
        # pre-existing handlers catch RuntimeError; the typed error must
        # keep flowing through them
        assert issubclass(PrefetchError, RuntimeError)

    def test_trainer_loop_surfaces_prefetch_error(self, tmp_path, vocab):
        class Boom:
            def next_batch(self):
                raise ValueError("stream corrupted")

        hps = hps_tiny(log_root=str(tmp_path), exp_name="x")
        with obs.use_registry(Registry()):
            trainer = Trainer(hps, vocab.size(), Boom())
            with pytest.raises(PrefetchError):
                trainer.train(num_steps=3)

    def test_transfer_failure_also_typed(self):
        class OneBatch:
            def __init__(self):
                self.sent = False

            def next_batch(self):
                if self.sent:
                    return None
                self.sent = True

                class B:
                    def as_arrays(self):
                        return {}
                return B()

        def bad_transfer(arrays):
            raise RuntimeError("H2D failed")

        with obs.use_registry(Registry()):
            p = DevicePrefetcher(OneBatch(), transfer=bad_transfer)
            with pytest.raises(PrefetchError):
                p.next_batch()
            p.stop()


class TestSummaryWriterSatellite:
    def test_rotated_directory_does_not_crash(self, tmp_path):
        reg = Registry()
        d = str(tmp_path / "train")
        w = SummaryWriter(d, flush_every=1, registry=reg)
        w.scalars(1, loss=1.0)
        shutil.rmtree(d)  # rotate the whole job dir away mid-run
        w.scalars(2, loss=0.9)  # must not raise
        w.scalars(3, loss=0.8)
        w.close()
        recs = [json.loads(ln) for ln in
                open(os.path.join(d, "events.jsonl"), encoding="utf-8")]
        assert [r["step"] for r in recs] == [2, 3]
        assert reg.counter("train/summary_write_errors").value == 0

    def test_unwritable_directory_counts_errors(self, tmp_path):
        reg = Registry()
        blocker = tmp_path / "file"
        blocker.write_text("")
        # directory path occupied by a FILE: open/makedirs keeps failing
        w = SummaryWriter(str(blocker / "sub"), registry=reg)
        w.scalars(1, loss=1.0)
        w.scalars(2, loss=0.5)
        assert reg.counter("train/summary_write_errors").value == 2

    def test_flush_cadence_buffers_writes(self, tmp_path):
        d = str(tmp_path / "t")
        w = SummaryWriter(d, flush_every=1000, registry=Registry())
        w.scalars(1, loss=1.0)
        path = os.path.join(d, "events.jsonl")
        # buffered, not yet flushed (small payload < libc buffer)
        assert os.path.getsize(path) == 0
        w.flush()
        assert os.path.getsize(path) > 0
        w.close()


class TestTrainPathTracing:
    """ISSUE 9: the request-scoped trace layer mirrors into the train
    path — every metrics-flush span of one run carries the run's
    TraceContext, and per-step flight frames accumulate."""

    def test_run_spans_share_one_trace_and_frames_record(
            self, tmp_path, vocab):
        hps = hps_tiny(log_root=str(tmp_path), exp_name="t",
                       metrics_every=2, flight_frames=8)
        with obs.use_registry(Registry()) as reg:
            batcher = Batcher("", vocab, hps, single_pass=True,
                              example_source=make_source(64))
            tr = Trainer(hps, vocab.size(), batcher,
                         train_dir=str(tmp_path / "t"))
            assert tr._trace is not None
            tr.train(num_steps=6)
            spans = [s for s in obs.tracer_for(reg).finished()
                     if s.name == "train/metrics_flush"]
            assert spans, "no metrics_flush spans recorded"
            # one run = one trace: every flush span links to the run root
            assert {s.trace_id for s in spans} == {tr._trace.trace_id}
            assert {s.parent_id for s in spans} == {tr._trace.span_id}
            assert all(s.attrs["step"] >= 0 for s in spans)
            # per-step frames rang through the recorder (newest kept)
            frames = reg.flight.frames()
            assert [f["step"] for f in frames] == list(range(6))[-8:]
            assert all(f["kind"] == "train_step" and "loss" in f
                       for f in frames)

    def test_flight_frames_zero_disables_recorder(self, tmp_path, vocab):
        hps = hps_tiny(log_root=str(tmp_path), exp_name="t",
                       flight_frames=0)
        with obs.use_registry(Registry()) as reg:
            batcher = Batcher("", vocab, hps, single_pass=True,
                              example_source=make_source(8))
            Trainer(hps, vocab.size(), batcher,
                    train_dir=str(tmp_path / "t"))
            assert reg.flight is None
