"""Batch packing + threaded batcher tests (reference batcher.py semantics)."""

import numpy as np
import pytest

from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.data import TFExample, Vocab
from textsummarization_on_flink_tpu.data.batching import (
    Batch,
    SummaryExample,
    get_dec_inp_targ_seqs,
)
from textsummarization_on_flink_tpu.data.batcher import Batcher
from textsummarization_on_flink_tpu.data.chunks import write_chunked
from textsummarization_on_flink_tpu.data.vocab import PAD_ID, START_ID, STOP_ID, UNK_ID


def small_hps(**kw):
    base = dict(batch_size=2, max_enc_steps=8, max_dec_steps=6, min_dec_steps=2,
                max_oov_buckets=4, vocab_size=0)
    base.update(kw)
    return HParams(**base)


def make_vocab():
    return Vocab(words=["the", "cat", "sat", "on", "mat", "."])  # size 10


class TestDecInpTarg:
    def test_no_truncation_appends_stop(self):
        inp, tgt = get_dec_inp_targ_seqs([5, 6, 7], 6, START_ID, STOP_ID)
        assert inp == [START_ID, 5, 6, 7]
        assert tgt == [5, 6, 7, STOP_ID]

    def test_truncation_drops_stop(self):
        inp, tgt = get_dec_inp_targ_seqs([5, 6, 7, 8, 9], 4, START_ID, STOP_ID)
        assert inp == [START_ID, 5, 6, 7]
        assert tgt == [5, 6, 7, 8]  # same length, no STOP


class TestSummaryExample:
    def test_truncation_and_oov(self):
        v = make_vocab()
        hps = small_hps(max_enc_steps=4)
        art = "the cat zebra sat on mat"  # truncated to 4 words
        ex = SummaryExample.build(art, ["the zebra ."], v, hps)
        assert ex.enc_len == 4
        assert ex.enc_input == [4, 5, UNK_ID, 6]
        assert ex.enc_input_extend_vocab == [4, 5, v.size(), 6]
        assert ex.article_oovs == ["zebra"]
        # target uses the temp OOV id for the copyable zebra
        assert ex.target == [4, v.size(), 9, STOP_ID]

    def test_dec_truncation(self):
        v = make_vocab()
        hps = small_hps(max_dec_steps=3)
        ex = SummaryExample.build("the cat", ["the cat sat on mat ."], v, hps)
        assert ex.dec_len == 3
        assert ex.dec_input[0] == START_ID
        assert STOP_ID not in ex.target


class TestBatch:
    def test_static_shapes_and_masks(self):
        v = make_vocab()
        hps = small_hps()
        exs = [SummaryExample.build("the cat", ["the ."], v, hps),
               SummaryExample.build("the cat sat on mat", ["cat ."], v, hps)]
        b = Batch(exs, hps, v)
        assert b.enc_batch.shape == (2, 8)
        assert b.dec_batch.shape == (2, 6)
        assert b.enc_batch.dtype == np.int32
        np.testing.assert_array_equal(b.enc_lens, [2, 5])
        assert b.enc_padding_mask[0].sum() == 2 and b.enc_padding_mask[1].sum() == 5
        # padding slots hold PAD
        assert (b.enc_batch[0, 2:] == PAD_ID).all()
        arrays = b.as_arrays()
        assert set(arrays) == {"enc_batch", "enc_lens", "enc_padding_mask",
                               "enc_batch_extend_vocab", "dec_batch",
                               "target_batch", "dec_padding_mask"}

    def test_oov_budget_clamping(self):
        v = make_vocab()
        hps = small_hps(max_oov_buckets=2, batch_size=1)
        art = "z1 z2 z3 z4"  # 4 OOVs, budget 2
        ex = SummaryExample.build(art, ["z1 z3 ."], v, hps)
        b = Batch([ex], hps, v)
        ext = b.enc_batch_extend_vocab[0, :4]
        assert list(ext[:2]) == [v.size(), v.size() + 1]
        assert list(ext[2:]) == [UNK_ID, UNK_ID]  # beyond budget -> UNK
        # target: z1 within budget keeps temp id, z3 clamped
        assert b.target_batch[0, 0] == v.size()
        assert b.target_batch[0, 1] == UNK_ID
        assert b.max_art_oovs == 2

    def test_wrong_batch_size_raises(self):
        v = make_vocab()
        hps = small_hps()
        ex = SummaryExample.build("the", ["the ."], v, hps)
        with pytest.raises(ValueError):
            Batch([ex], hps, v)


def _write_dataset(tmp_path, v, n=20):
    exs = []
    for i in range(n):
        words = ["the", "cat", "sat"][: (i % 3) + 1] * (i % 4 + 1)
        art = " ".join(words)
        exs.append(TFExample().set_bytes("article", art.encode())
                   .set_bytes("abstract", f"<s> the cat . </s>".encode()))
    write_chunked(str(tmp_path / "train"), exs, chunk_size=7)
    return str(tmp_path / "train_*.bin")


class TestBatcher:
    def test_single_pass_yields_all_then_none(self, tmp_path):
        v = make_vocab()
        hps = small_hps(batch_size=4, mode="train")
        pattern = _write_dataset(tmp_path, v, n=10)
        b = Batcher(pattern, v, hps, single_pass=True)
        seen = 0
        batches = 0
        while True:
            batch = b.next_batch()
            if batch is None:
                break
            batches += 1
            seen += int(batch.enc_padding_mask.shape[0])
            assert batch.enc_batch.shape == (4, 8)
            if batches > 10:
                pytest.fail("batcher did not terminate")
        # 10 examples -> 3 batches (last padded by repeating)
        assert batches == 3

    def test_decode_repeat_mode(self, tmp_path):
        v = make_vocab()
        hps = small_hps(batch_size=4, mode="decode")
        pattern = _write_dataset(tmp_path, v, n=3)
        b = Batcher(pattern, v, hps, single_pass=True, decode_batch_mode="repeat")
        batch = b.next_batch()
        # one example repeated across the batch
        assert all(a == batch.original_articles[0] for a in batch.original_articles)

    def test_decode_distinct_mode(self, tmp_path):
        v = make_vocab()
        hps = small_hps(batch_size=2, mode="decode")
        pattern = _write_dataset(tmp_path, v, n=4)
        b = Batcher(pattern, v, hps, single_pass=True, decode_batch_mode="distinct")
        batch = b.next_batch()
        assert len(set(batch.original_articles)) == 2

    def test_empty_article_skipped(self, tmp_path):
        v = make_vocab()
        hps = small_hps(batch_size=1, mode="train")
        exs = [TFExample().set_bytes("article", b"").set_bytes("abstract", b"x"),
               TFExample().set_bytes("article", b"the cat")
               .set_bytes("abstract", b"<s> the . </s>")]
        write_chunked(str(tmp_path / "t"), exs, chunk_size=10)
        b = Batcher(str(tmp_path / "t_*.bin"), v, hps, single_pass=True)
        batch = b.next_batch()
        assert batch.original_articles == ["the cat"]
        assert b.next_batch() is None

    def test_streaming_example_source(self):
        v = make_vocab()
        hps = small_hps(batch_size=2, mode="train")

        def source():
            for i in range(4):
                yield f"the cat {i}", "<s> the . </s>"

        b = Batcher("", v, hps, single_pass=True, example_source=source)
        batch = b.next_batch()
        assert batch is not None
        assert batch.enc_batch.shape == (2, 8)

    def test_tail_padding_rows_tagged(self, tmp_path):
        """Padding repeats carry real_mask=False; real rows sum to the
        dataset size even after length-bucket sorting reorders them."""
        v = make_vocab()
        hps = small_hps(batch_size=4, mode="train")
        pattern = _write_dataset(tmp_path, v, n=10)
        b = Batcher(pattern, v, hps, single_pass=True)
        real = 0
        while True:
            batch = b.next_batch()
            if batch is None:
                break
            assert len(batch.real_mask) == 4
            real += sum(batch.real_mask)
        assert real == 10  # 12 rows shipped, 2 tagged as padding

    def test_decode_repeat_mode_real_mask(self, tmp_path):
        v = make_vocab()
        hps = small_hps(batch_size=4, mode="decode")
        pattern = _write_dataset(tmp_path, v, n=2)
        b = Batcher(pattern, v, hps, single_pass=True,
                    decode_batch_mode="repeat")
        batch = b.next_batch()
        # beam repetition: one real row, B-1 tagged repeats
        assert batch.real_mask == [True, False, False, False]

    def test_decode_distinct_trickle_padding_tagged(self):
        v = make_vocab()
        hps = small_hps(batch_size=4, mode="decode")

        def source():
            yield "the cat sat", "<s> the . </s>"
            yield "the cat sat", "<s> the . </s>"  # identical on purpose

        b = Batcher("", v, hps, single_pass=True,
                    decode_batch_mode="distinct", example_source=source)
        batch = b.next_batch()
        # two REAL identical rows kept distinct; 2 padding rows tagged
        assert batch.real_mask == [True, True, False, False]

    def test_producer_error_propagates_to_next_batch(self):
        v = make_vocab()
        hps = small_hps(batch_size=2, mode="train")

        def bad_source():
            yield "the cat", "<s> the . </s>"
            raise ValueError("stream backend exploded")

        b = Batcher("", v, hps, single_pass=False, watch_interval=0.1,
                    example_source=bad_source)
        with pytest.raises(RuntimeError, match="producer thread failed"):
            for _ in range(50):  # a batch may already be queued
                if b.next_batch() is None:
                    break
        assert isinstance(b._fill_error, ValueError)

    def test_non_single_pass_exhaustion_surfaces(self):
        """An exhausted generator with single_pass off is an error the
        CONSUMER sees (not a silent respawn loop, reference
        batcher.py:343-360)."""
        v = make_vocab()
        hps = small_hps(batch_size=2, mode="train")

        def finite_source():
            yield "the cat", "<s> the . </s>"

        b = Batcher("", v, hps, single_pass=False, watch_interval=0.1,
                    example_source=finite_source)
        with pytest.raises(RuntimeError, match="producer thread failed"):
            for _ in range(50):
                if b.next_batch() is None:
                    break
