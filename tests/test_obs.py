"""Unit tests for the obs/ observability layer: registry semantics,
concurrency, histogram percentile math vs numpy, span nesting, and the
exporter round trip through scripts/trace_summary.py (ISSUE 1
satellite 3)."""

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.obs.registry import Registry

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))
import trace_summary  # noqa: E402


# --------------------------------------------------------------------------
# registry
# --------------------------------------------------------------------------

class TestRegistry:
    def test_counter_gauge_basics(self):
        r = Registry()
        c = r.counter("t/c")
        c.inc()
        c.inc(2.5)
        assert c.value == 3.5
        with pytest.raises(ValueError):
            c.inc(-1)
        g = r.gauge("t/g")
        g.set(7)
        g.inc()
        g.dec(3)
        assert g.value == 5.0

    def test_get_or_create_identity_and_type_conflict(self):
        r = Registry()
        assert r.counter("x") is r.counter("x")
        with pytest.raises(TypeError):
            r.gauge("x")

    def test_threaded_counter_increments(self):
        r = Registry()
        c = r.counter("t/threads")
        h = r.histogram("t/h", buckets=(1.0, 2.0, 3.0))
        n_threads, n_iters = 8, 5000

        def worker(i):
            for k in range(n_iters):
                c.inc()
                h.observe((i + k) % 3 + 0.5)

        ts = [threading.Thread(target=worker, args=(i,))
              for i in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert c.value == n_threads * n_iters
        assert h.count == n_threads * n_iters
        snap = h.snapshot()
        assert sum(snap["counts"]) == h.count

    def test_snapshot_and_compact(self):
        r = Registry()
        r.counter("a/used").inc(2)
        r.counter("a/unused")
        r.histogram("a/h").observe(0.5)
        r.histogram("a/h_empty")
        full = r.snapshot()
        assert set(full) == {"a/used", "a/unused", "a/h", "a/h_empty"}
        compact = r.snapshot(compact=True)
        assert set(compact) == {"a/used", "a/h"}
        assert compact["a/h"]["count"] == 1
        assert compact["a/h"]["p50"] > 0

    def test_disabled_registry_hands_out_shared_nulls(self):
        """The near-zero-cost-when-disabled contract: every call site
        gets the SAME null singletons, whose mutators are no-ops."""
        r = Registry(enabled=False)
        assert r.counter("x") is obs.NULL_COUNTER
        assert r.gauge("x") is obs.NULL_GAUGE
        assert r.histogram("x") is obs.NULL_HISTOGRAM
        obs.NULL_COUNTER.inc(5)
        assert obs.NULL_COUNTER.value == 0.0
        obs.NULL_HISTOGRAM.observe(1.0)
        assert obs.NULL_HISTOGRAM.percentile(50) == 0.0
        # disabled spans are the shared null context manager
        from textsummarization_on_flink_tpu.obs import spans as spans_lib

        assert spans_lib.span(r, "anything") is obs.NULL_SPAN

    def test_ts_obs_env_gate(self, monkeypatch):
        monkeypatch.setenv("TS_OBS", "0")
        assert not obs.enabled_from_env()
        monkeypatch.setenv("TS_OBS", "1")
        assert obs.enabled_from_env()
        monkeypatch.delenv("TS_OBS")
        assert obs.enabled_from_env()

    def test_registry_for_hparams_gate(self):
        from textsummarization_on_flink_tpu.config import HParams

        with obs.use_registry(Registry()):
            assert obs.registry_for(HParams(obs=False)) is obs.NULL_REGISTRY
            assert obs.registry_for(HParams(obs=True)) is obs.registry()
            assert obs.registry_for(None) is obs.registry()


# --------------------------------------------------------------------------
# histogram percentiles vs numpy
# --------------------------------------------------------------------------

class TestHistogramPercentiles:
    def test_uniform_against_numpy(self):
        r = Registry()
        h = r.histogram("t/u", buckets=tuple(np.linspace(0.01, 1.0, 100)))
        rng = np.random.RandomState(0)
        vals = rng.uniform(0, 1, 4000)
        for v in vals:
            h.observe(float(v))
        for q in (10, 50, 90, 99):
            got = h.percentile(q)
            want = float(np.percentile(vals, q))
            # bucket width is 0.01; interpolation keeps us within ~2 widths
            assert abs(got - want) < 0.025, (q, got, want)
        assert h.count == len(vals)
        assert h.sum == pytest.approx(float(vals.sum()), rel=1e-6)
        assert h.mean == pytest.approx(float(vals.mean()), rel=1e-6)

    def test_lognormal_against_numpy_with_exponential_buckets(self):
        r = Registry()
        h = r.histogram(
            "t/ln", buckets=obs.exponential_buckets(1e-4, 1.3, 60))
        rng = np.random.RandomState(1)
        vals = np.exp(rng.normal(-4.0, 1.0, 3000))
        for v in vals:
            h.observe(float(v))
        for q in (50, 90, 99):
            got = h.percentile(q)
            want = float(np.percentile(vals, q))
            # exponential buckets: error bounded by the bucket RATIO
            assert want / 1.35 <= got <= want * 1.35, (q, got, want)

    def test_overflow_bucket_and_edge_quantiles(self):
        r = Registry()
        h = r.histogram("t/o", buckets=(1.0, 2.0))
        for v in (0.5, 1.5, 100.0):
            h.observe(v)
        assert h.snapshot()["counts"] == [1, 1, 1]
        assert h.percentile(100) == pytest.approx(100.0)
        assert h.percentile(0) <= 0.5
        with pytest.raises(ValueError):
            h.percentile(101)

    def test_empty_histogram(self):
        h = Registry().histogram("t/e")
        assert h.percentile(50) == 0.0
        assert h.count == 0

    def test_bad_buckets_rejected(self):
        r = Registry()
        with pytest.raises(ValueError):
            r.histogram("t/bad", buckets=(2.0, 1.0))
        with pytest.raises(ValueError):
            obs.exponential_buckets(0.0, 2.0, 3)


# --------------------------------------------------------------------------
# spans
# --------------------------------------------------------------------------

class TestSpans:
    def test_nesting_order_and_parent(self):
        with obs.use_registry(Registry()):
            with obs.span("outer"):
                time.sleep(0.002)
                with obs.span("inner", step=3):
                    time.sleep(0.002)
            spans = obs.tracer_for(obs.registry()).finished()
        # inner finishes first (recorded in completion order)
        assert [s.name for s in spans] == ["inner", "outer"]
        inner, outer = spans
        assert inner.parent == "outer" and inner.depth == 1
        assert outer.parent is None and outer.depth == 0
        assert inner.attrs == {"step": 3}
        # nested span's duration is contained in the parent's
        assert 0 < inner.duration <= outer.duration
        assert outer.wall_start <= inner.wall_start

    def test_span_survives_exception(self):
        with obs.use_registry(Registry()):
            with pytest.raises(RuntimeError):
                with obs.span("boom"):
                    raise RuntimeError("x")
            spans = obs.tracer_for(obs.registry()).finished()
            assert [s.name for s in spans] == ["boom"]
            # the stack unwound: a following span is top-level again
            with obs.span("after"):
                pass
            assert obs.tracer_for(obs.registry()).finished()[-1].depth == 0

    def test_ring_buffer_bounds_and_drop_counter(self):
        from textsummarization_on_flink_tpu.obs.spans import Tracer

        reg = Registry()
        tracer = Tracer(reg, max_spans=10)
        reg.tracer = tracer
        for i in range(25):
            with tracer.span(f"s{i}"):
                pass
        assert len(tracer.finished()) == 10
        assert reg.counter("obs/spans_dropped_total").value == 15
        # oldest dropped, newest retained
        assert tracer.finished()[-1].name == "s24"

    def test_chrome_trace_events_shape(self):
        with obs.use_registry(Registry()):
            with obs.span("a/b"):
                pass
            events = obs.tracer_for(obs.registry()).chrome_trace_events()
        metas = [e for e in events if e["ph"] == "M"]
        xs = [e for e in events if e["ph"] == "X"]
        assert any(e["name"] == "process_name" for e in metas)
        assert any(e["name"] == "thread_name" for e in metas)
        assert len(xs) == 1 and xs[0]["name"] == "a/b"
        assert xs[0]["dur"] >= 0 and xs[0]["ts"] > 0


# --------------------------------------------------------------------------
# render_text (Prometheus-style exposition)
# --------------------------------------------------------------------------

class TestRenderText:
    def test_exposition_format(self):
        r = Registry()
        r.counter("train/steps_total").inc(5)
        r.gauge("train/prefetch_queue_depth").set(2)
        h = r.histogram("decode/request_latency_seconds",
                        buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(5.0)
        text = r.render_text()
        assert "# TYPE train_steps_total counter" in text
        assert "train_steps_total 5" in text
        assert "train_prefetch_queue_depth 2" in text
        assert ('decode_request_latency_seconds_bucket{le="0.1"} 1'
                in text)
        assert ('decode_request_latency_seconds_bucket{le="+Inf"} 2'
                in text)
        assert "decode_request_latency_seconds_count 2" in text

    def test_empty_registry_renders_empty(self):
        assert Registry().render_text() == ""


# --------------------------------------------------------------------------
# exporters
# --------------------------------------------------------------------------

class TestEventSink:
    def test_jsonl_round_trip(self, tmp_path):
        with obs.use_registry(Registry()):
            sink = obs.install_event_sink(str(tmp_path), flush_secs=0.05)
            with obs.span("train/step"):
                time.sleep(0.001)
            sink.emit({"kind": "snapshot", "metrics": {}})
            sink.close()
            recs = [json.loads(ln) for ln in
                    open(tmp_path / "events.jsonl", encoding="utf-8")]
        kinds = [r["kind"] for r in recs]
        assert "span" in kinds and "snapshot" in kinds
        span_rec = next(r for r in recs if r["kind"] == "span")
        assert span_rec["name"] == "train/step"
        assert span_rec["dur_us"] >= 1000

    def test_bounded_queue_drops_and_counts(self, tmp_path):
        from textsummarization_on_flink_tpu.obs.export import EventSink

        reg = Registry()
        sink = EventSink(str(tmp_path), flush_secs=30.0, max_queue=4,
                         registry=reg)
        # flusher sleeps 30s between drains: overfill deterministically
        sent = [sink.emit({"kind": "span", "i": i}) for i in range(10)]
        assert sum(sent) <= 4
        assert reg.counter("obs/events_dropped_total").value >= 6
        sink.close()

    def test_sink_survives_rotated_directory(self, tmp_path):
        import shutil

        from textsummarization_on_flink_tpu.obs.export import EventSink

        reg = Registry()
        d = tmp_path / "logs"
        sink = EventSink(str(d), flush_secs=0.05, registry=reg)
        sink.emit({"kind": "span", "name": "a"})
        sink.flush()
        shutil.rmtree(d)  # rotate the log dir out from under the sink
        sink.emit({"kind": "span", "name": "b"})
        sink.flush()
        sink.close()
        # the sink recreated the directory and kept writing
        recs = [json.loads(ln)
                for ln in open(d / "events.jsonl", encoding="utf-8")]
        assert [r["name"] for r in recs] == ["b"]
        assert reg.counter("obs/sink_write_errors_total").value == 0

    def test_disabled_registry_install_is_noop(self, tmp_path):
        reg = Registry(enabled=False)
        from textsummarization_on_flink_tpu.obs.export import (
            install_event_sink,
        )

        assert install_event_sink(reg, str(tmp_path)) is None
        assert not (tmp_path / "events.jsonl").exists()


class TestTraceSummaryRoundTrip:
    """One tool, both capture kinds (ISSUE 1 satellite: events.jsonl)."""

    def test_chrome_trace_export_summarized(self, tmp_path, capsys):
        with obs.use_registry(Registry()):
            for _ in range(3):
                with obs.span("decode/batch"):
                    time.sleep(0.001)
            path = str(tmp_path / "cap" / "obs.trace.json")
            n = obs.write_chrome_trace(path)
        assert n == 3
        rc = trace_summary.main([str(tmp_path / "cap"), "--json"])
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        ops = {o["name"]: o for lane in out["lanes"] for o in lane["ops"]}
        assert ops["decode/batch"]["count"] == 3
        assert ops["decode/batch"]["total_us"] >= 3000

    def test_events_jsonl_summarized(self, tmp_path, capsys):
        with obs.use_registry(Registry()):
            sink = obs.install_event_sink(str(tmp_path), flush_secs=0.05)
            for _ in range(2):
                with obs.span("train/metrics_flush"):
                    time.sleep(0.001)
            sink.close()
        # SummaryWriter-style scalar lines share the file and are skipped
        with open(tmp_path / "events.jsonl", "a", encoding="utf-8") as f:
            f.write(json.dumps({"step": 1, "loss": 2.5}) + "\n")
        rc = trace_summary.main([str(tmp_path), "--json"])
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert out["trace"].endswith("events.jsonl")
        ops = {o["name"]: o for lane in out["lanes"] for o in lane["ops"]}
        assert ops["train/metrics_flush"]["count"] == 2

    def test_profiler_trace_preferred_over_events(self, tmp_path):
        (tmp_path / "events.jsonl").write_text("")
        (tmp_path / "x.trace.json").write_text('{"traceEvents": []}')
        files = trace_summary.find_trace_files(str(tmp_path))
        assert files == [str(tmp_path / "x.trace.json")]

    def test_direct_file_argument(self, tmp_path):
        p = tmp_path / "events.jsonl"
        p.write_text(json.dumps({"kind": "span", "name": "a", "ts_us": 1,
                                 "dur_us": 5, "pid": 1, "tid": 1}) + "\n"
                     + "{half-written")
        assert trace_summary.find_trace_files(str(p)) == [str(p)]
        trace = trace_summary.load_events(str(p))
        assert len(trace["traceEvents"]) == 1  # bad tail line skipped


# --------------------------------------------------------------------------
# request-scoped tracing (ISSUE 9 tentpole)
# --------------------------------------------------------------------------

class TestTraceContext:
    def test_new_and_child_linkage(self):
        from textsummarization_on_flink_tpu.obs.spans import TraceContext

        root = TraceContext.new()
        child = root.child()
        grand = child.child()
        assert child.trace_id == root.trace_id == grand.trace_id
        assert child.parent_id == root.span_id
        assert grand.parent_id == child.span_id
        # ids are unique per node
        assert len({root.span_id, child.span_id, grand.span_id}) == 3
        d = child.as_dict()
        assert d == {"trace_id": root.trace_id, "span_id": child.span_id,
                     "parent_id": root.span_id}
        assert "parent_id" not in root.as_dict()

    def test_explicit_parent_links_across_threads(self):
        """The load-bearing property: a span opened on ANOTHER thread
        with parent=ctx joins the trace the submit thread minted —
        exactly what the thread-local stack cannot do."""
        from textsummarization_on_flink_tpu.obs import spans as spans_lib

        reg = Registry()
        ctx = spans_lib.TraceContext.new()

        def dispatch_thread():
            with spans_lib.span(reg, "serve/dispatch", parent=ctx, fill=2):
                with spans_lib.span(reg, "decode/slot_chunk"):
                    pass

        t = threading.Thread(target=dispatch_thread)
        t.start()
        t.join()
        chunk, dispatch = spans_lib.tracer_for(reg).finished()
        assert dispatch.name == "serve/dispatch"
        assert dispatch.trace_id == ctx.trace_id
        assert dispatch.parent_id == ctx.span_id
        # the nested span INHERITS the trace through the stack
        assert chunk.trace_id == ctx.trace_id
        assert chunk.parent_id == dispatch.span_id

    def test_untraced_spans_stay_unstamped(self):
        from textsummarization_on_flink_tpu.obs import spans as spans_lib

        reg = Registry()
        with spans_lib.span(reg, "plain"):
            pass
        (rec,) = spans_lib.tracer_for(reg).finished()
        assert rec.trace_id is None and rec.span_id is None
        ev = rec.as_event()
        assert "trace_id" not in ev and "span_id" not in ev
        assert "trace_id" not in rec.as_chrome_event().get("args", {})

    def test_ids_stamped_into_both_export_shapes(self):
        from textsummarization_on_flink_tpu.obs import spans as spans_lib

        reg = Registry()
        ctx = spans_lib.TraceContext.new()
        with spans_lib.span(reg, "serve/dispatch", parent=ctx):
            pass
        (rec,) = spans_lib.tracer_for(reg).finished()
        ev = rec.as_event()
        assert ev["trace_id"] == ctx.trace_id
        assert ev["parent_id"] == ctx.span_id
        assert ev["span_id"] == rec.span_id
        args = rec.as_chrome_event()["args"]
        assert args["trace_id"] == ctx.trace_id
        assert args["parent_id"] == ctx.span_id

    def test_request_event_round_trip(self):
        from textsummarization_on_flink_tpu.obs import spans as spans_lib
        from textsummarization_on_flink_tpu.obs.export import MemorySink

        reg = Registry()
        ctx = spans_lib.TraceContext.new()
        # no sink installed: quietly refused
        assert not spans_lib.request_event(reg, "enqueue", ctx, "u1")
        sink = MemorySink()
        reg.event_sink = sink
        assert spans_lib.request_event(reg, "enqueue", ctx, "u1", depth=3)
        assert spans_lib.request_event(reg, "resolve", ctx, "u1")
        enq, res = sink.records()
        assert enq["kind"] == "request" and enq["event"] == "enqueue"
        assert enq["uuid"] == "u1" and enq["attrs"] == {"depth": 3}
        assert enq["trace_id"] == res["trace_id"] == ctx.trace_id
        assert enq["span_id"] == ctx.span_id
        assert res["ts_us"] >= enq["ts_us"] > 0
        # disabled registry: no-op
        assert not spans_lib.request_event(
            Registry(enabled=False), "enqueue", ctx, "u1")


class TestEventSinkGapAnnotation:
    def test_drop_episode_leaves_marker_in_stream(self, tmp_path):
        """ISSUE 9 satellite: after drops, the NEXT flushed batch carries
        one {"kind": "drops", "count": N} record — the hole is visible in
        events.jsonl itself, not only in obs/events_dropped_total."""
        from textsummarization_on_flink_tpu.obs.export import EventSink

        reg = Registry()
        # flusher parks for 100s unless kicked: overfill deterministically
        sink = EventSink(str(tmp_path), flush_secs=100.0, max_queue=1,
                         registry=reg)
        assert sink.emit({"kind": "span", "name": "kept"})
        assert not sink.emit({"kind": "span", "name": "lost1"})
        assert not sink.emit({"kind": "span", "name": "lost2"})
        sink.close()
        recs = [json.loads(ln)
                for ln in open(tmp_path / "events.jsonl", encoding="utf-8")]
        assert [r["kind"] for r in recs] == ["span", "drops"]
        assert recs[1]["count"] == 2
        assert recs[1]["ts_us"] > 0
        assert reg.counter("obs/events_dropped_total").value == 2

    def test_no_drops_no_marker(self, tmp_path):
        from textsummarization_on_flink_tpu.obs.export import EventSink

        reg = Registry()
        sink = EventSink(str(tmp_path), flush_secs=0.05, registry=reg)
        sink.emit({"kind": "span", "name": "a"})
        sink.close()
        recs = [json.loads(ln)
                for ln in open(tmp_path / "events.jsonl", encoding="utf-8")]
        assert [r["kind"] for r in recs] == ["span"]


class TestMemorySink:
    def test_emit_and_bound(self):
        from textsummarization_on_flink_tpu.obs.export import MemorySink

        s = MemorySink(max_records=2)
        assert s.emit({"a": 1}) and s.emit({"a": 2})
        assert not s.emit({"a": 3})
        assert [r["a"] for r in s.records()] == [1, 2]
