"""obs/flightrec.py: the failure flight recorder (ISSUE 9 tentpole,
piece 3) — ring semantics, dump contract, and the non-chaos trigger
sites (breaker open, eviction storm).  The TS_FAULTS-driven train/serve
dump acceptance lives in tests/test_chaos.py."""

import json
import time

import pytest

from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.obs import flightrec
from textsummarization_on_flink_tpu.obs.registry import Registry
from textsummarization_on_flink_tpu.resilience.policy import (
    CircuitBreaker,
    Deadline,
)


def _read(path):
    return [json.loads(ln) for ln in open(path, encoding="utf-8")]


class TestFlightRecorder:
    def test_ring_keeps_newest_capacity_frames(self, tmp_path):
        rec = flightrec.FlightRecorder(str(tmp_path), capacity=4,
                                       registry=Registry())
        for i in range(10):
            rec.record("train_step", step=i)
        frames = rec.frames()
        assert [f["step"] for f in frames] == [6, 7, 8, 9]
        # seq is global and monotonic; ts_us is stamped
        assert [f["seq"] for f in frames] == [7, 8, 9, 10]
        assert all(f["ts_us"] > 0 and f["kind"] == "train_step"
                   for f in frames)

    def test_dump_header_plus_frames(self, tmp_path):
        reg = Registry()
        rec = flightrec.FlightRecorder(str(tmp_path), capacity=3,
                                       registry=reg)
        for i in range(5):
            rec.record("serve_tick", tick=i)
        path = rec.dump("serve_dispatch", error="RuntimeError")
        assert path.endswith("flight_serve_dispatch.jsonl")
        lines = _read(path)
        assert lines[0]["kind"] == "flight"
        assert lines[0]["reason"] == "serve_dispatch"
        assert lines[0]["frames"] == 3 and lines[0]["capacity"] == 3
        assert lines[0]["context"] == {"error": "RuntimeError"}
        assert [f["tick"] for f in lines[1:]] == [2, 3, 4]
        assert reg.counter("obs/flight_dumps_total").value == 1

    def test_repeat_dumps_suffixed_and_budgeted(self, tmp_path):
        reg = Registry()
        rec = flightrec.FlightRecorder(str(tmp_path), capacity=2,
                                       registry=reg,
                                       max_dumps_per_reason=2)
        rec.record("serve_tick", tick=1)
        p1 = rec.dump("breaker_x_open")
        p2 = rec.dump("breaker_x_open")
        p3 = rec.dump("breaker_x_open")  # over budget: dropped
        assert p1.endswith("flight_breaker_x_open.jsonl")
        assert p2.endswith("flight_breaker_x_open-2.jsonl")
        assert p3 is None
        assert reg.counter("obs/flight_dumps_total").value == 2
        assert reg.counter("obs/flight_dumps_dropped_total").value == 1

    def test_reason_sanitized_for_filenames(self, tmp_path):
        rec = flightrec.FlightRecorder(str(tmp_path), registry=Registry())
        path = rec.dump("breaker serve.admission/open!")
        assert path.endswith("flight_breaker_serve.admission_open_.jsonl")

    def test_dump_failure_counted_not_raised(self, tmp_path):
        reg = Registry()
        target = tmp_path / "blocked"
        target.write_text("a file where the directory should go")
        rec = flightrec.FlightRecorder(str(target), registry=reg)
        rec.record("train_step", step=1)
        assert rec.dump("train_nan") is None
        assert reg.counter("obs/flight_dump_errors_total").value == 1

    def test_install_first_wins_and_module_helpers(self, tmp_path):
        reg = Registry()
        # unarmed: record/trigger are no-ops
        flightrec.record(reg, "train_step", step=1)
        assert flightrec.trigger(reg, "train_nan") is None
        r1 = flightrec.install_flight_recorder(reg, str(tmp_path),
                                               capacity=8)
        r2 = flightrec.install_flight_recorder(reg, str(tmp_path / "b"))
        assert r1 is r2 and reg.flight is r1
        flightrec.record(reg, "train_step", step=2)
        path = flightrec.trigger(reg, "train_nan", step=3)
        lines = _read(path)
        assert lines[0]["context"] == {"step": 3}
        assert [f["step"] for f in lines[1:]] == [2]
        # disabled registry: no install
        assert flightrec.install_flight_recorder(
            Registry(enabled=False), str(tmp_path)) is None

    def test_capacity_validation(self, tmp_path):
        with pytest.raises(ValueError, match="capacity"):
            flightrec.FlightRecorder(str(tmp_path), capacity=0)


class TestTriggerSites:
    def test_breaker_open_dumps(self, tmp_path):
        """Every breaker-open transition (CLOSED->OPEN and the failed
        HALF_OPEN probe) triggers a flight dump on the breaker's own
        registry."""
        reg = Registry()
        flightrec.install_flight_recorder(reg, str(tmp_path), capacity=4)
        flightrec.record(reg, "serve_tick", tick=1)
        clock = [0.0]
        br = CircuitBreaker(threshold=2, reset_secs=5.0, name="adm",
                            clock=lambda: clock[0], registry=reg)
        br.record_failure()
        assert not (tmp_path / "flight_breaker_adm_open.jsonl").exists()
        br.record_failure()  # trips
        p1 = tmp_path / "flight_breaker_adm_open.jsonl"
        assert p1.exists()
        assert [f["tick"] for f in _read(p1)[1:]] == [1]
        # half-open probe failure re-opens -> second (suffixed) dump
        clock[0] += 10.0
        assert br.allow()  # the half-open probe
        br.record_failure()
        assert (tmp_path / "flight_breaker_adm_open-2.jsonl").exists()

    def test_eviction_storm_dumps(self, tmp_path):
        """Half the slots evicted at one chunk boundary = a storm: the
        ContinuousBatcher leaves the preceding ticks behind."""
        from textsummarization_on_flink_tpu.serve.batcher import (
            ContinuousBatcher,
        )
        from textsummarization_on_flink_tpu.serve.queue import (
            RequestQueue,
            ServeRequest,
        )

        class _Engine:
            slots = 4

            def release(self, idx):
                pass

        reg = Registry()
        with obs.use_registry(reg):
            flightrec.install_flight_recorder(reg, str(tmp_path),
                                              capacity=8)
            hps = HParams(batch_size=4)
            q = RequestQueue(8, registry=reg)
            cb = ContinuousBatcher(hps, q, _Engine(), registry=reg)
            for i in range(4):
                flightrec.record(reg, "serve_tick", tick=i)
            # white-box: park 2 already-expired residents (no sleeps)
            expired = Deadline(time.monotonic() - 1.0)
            for idx in (0, 2):
                req = ServeRequest(f"u{idx}", "a", "", example=None,
                                   deadline=expired, registry=reg)
                cb._resident[idx] = req
            cb._evict_expired()
        dump = tmp_path / "flight_eviction_storm.jsonl"
        assert dump.exists()
        lines = _read(dump)
        assert lines[0]["context"]["evicted"] == 2
        assert [f["tick"] for f in lines[1:]] == [0, 1, 2, 3]
        assert reg.counter("serve/deadline_evictions_total").value == 2
        # single evictions do NOT storm-trigger
        req = ServeRequest("u9", "a", "", example=None, deadline=expired,
                           registry=reg)
        cb._resident[1] = req
        with obs.use_registry(reg):
            cb._evict_expired()
        assert not (tmp_path / "flight_eviction_storm-2.jsonl").exists()


class TestReviewFixes:
    def test_nan_frames_dump_as_strict_json(self, tmp_path):
        """The train_nan dump's whole point is the non-finite loss frame
        — it must still be STRICT JSON (no bare NaN tokens that jq /
        JSON.parse reject)."""
        rec = flightrec.FlightRecorder(str(tmp_path), registry=Registry())
        rec.record("train_step", step=1, loss=float("nan"),
                   global_norm=float("inf"))
        path = rec.dump("train_nan", step=1)
        raw = open(path, encoding="utf-8").read()
        assert "NaN" not in raw and "Infinity" not in raw
        lines = _read(path)
        assert lines[1]["loss"] == "nan"
        assert lines[1]["global_norm"] == "inf"

    def test_facade_capacity_zero_means_disabled(self, tmp_path):
        reg = Registry()
        assert obs.install_flight_recorder(str(tmp_path), capacity=0,
                                           reg=reg) is None
        assert reg.flight is None
        rec = obs.install_flight_recorder(str(tmp_path), reg=reg)
        assert rec is not None
        assert rec.capacity == flightrec.DEFAULT_CAPACITY


class TestHeartbeatRetire:
    def test_finished_component_does_not_pin_healthz(self):
        from textsummarization_on_flink_tpu.obs import http as obs_http

        reg = Registry()
        clock = [0.0]
        board = obs_http.board_for(reg)
        board._clock = lambda: clock[0]
        board.beat("train/loop", period=1.0)
        clock[0] += 100.0  # way past stale
        assert obs_http.health(reg)["status"] == "degraded"
        obs_http.retire_heartbeat(reg, "train/loop")
        payload = obs_http.health(reg)
        assert payload["status"] == "ok"
        assert "train/loop" not in payload["components"]
        # retiring the never-registered / disabled cases is a no-op
        obs_http.retire_heartbeat(reg, "nope")
        obs_http.retire_heartbeat(Registry(enabled=False), "train/loop")

    def test_failed_writes_do_not_burn_the_dump_budget(self, tmp_path):
        """A transiently unwritable directory must not consume the
        per-reason allowance: when the disk recovers, the post-mortem
        still gets written (and never overwrites an earlier success)."""
        import os
        import shutil

        reg = Registry()
        target = tmp_path / "blocked"
        target.write_text("a file where the directory should go")
        rec = flightrec.FlightRecorder(str(target), registry=reg,
                                       max_dumps_per_reason=2)
        rec.record("train_step", step=1)
        for _ in range(3):  # three failed attempts
            assert rec.dump("train_nan") is None
        assert reg.counter("obs/flight_dump_errors_total").value == 3
        assert reg.counter("obs/flight_dumps_dropped_total").value == 0
        os.remove(target)  # the disk recovers
        p = rec.dump("train_nan")
        assert p is not None and os.path.exists(p)
        # attempts drove the NAME (monotonic), successes the budget
        assert p.endswith("flight_train_nan-4.jsonl")
        p2 = rec.dump("train_nan")
        assert p2 is not None  # budget of 2 successes, only 1 spent
        assert rec.dump("train_nan") is None  # now genuinely spent
        assert reg.counter("obs/flight_dumps_dropped_total").value == 1
        shutil.rmtree(target, ignore_errors=True)


class TestReplicaTagging:
    """ISSUE 15 satellite: fleet replicas sharing one log directory
    must not clobber or shadow each other's dumps — every frame and
    dump filename carries the replica id."""

    def test_frames_and_dump_filename_carry_replica_id(self, tmp_path):
        rec = flightrec.FlightRecorder(str(tmp_path), capacity=4,
                                       registry=Registry(),
                                       replica_id="r2")
        rec.record("serve_tick", tick=1)
        path = rec.dump("serve_dispatch", error="X")
        assert path.endswith("flight_serve_dispatch.r2.jsonl")
        lines = _read(path)
        assert lines[0]["replica"] == "r2"
        assert all(f["replica"] == "r2" for f in lines[1:])

    def test_two_replicas_same_reason_distinct_files(self, tmp_path):
        paths = set()
        for rid in ("r0", "r2"):
            rec = flightrec.FlightRecorder(str(tmp_path), capacity=2,
                                           registry=Registry(),
                                           replica_id=rid)
            rec.record("serve_tick", tick=0)
            paths.add(rec.dump("serve_dispatch"))
        assert len(paths) == 2  # no clobber, no -2 shadow suffix
        assert all(p and "flight_serve_dispatch." in p for p in paths)

    def test_set_replica_id_reaches_installed_recorder(self, tmp_path):
        reg = Registry()
        rec = flightrec.install_flight_recorder(reg, str(tmp_path),
                                                capacity=4)
        flightrec.set_replica_id(reg, "r7")
        assert reg.replica_id == "r7"
        assert rec.replica_id == "r7"
        flightrec.record(reg, "serve_tick", tick=1)
        path = flightrec.trigger(reg, "replica_kill")
        assert path.endswith("flight_replica_kill.r7.jsonl")
        assert _read(path)[1]["replica"] == "r7"

    def test_untagged_recorder_unchanged(self, tmp_path):
        rec = flightrec.FlightRecorder(str(tmp_path), capacity=2,
                                       registry=Registry())
        rec.record("serve_tick", tick=0)
        path = rec.dump("serve_dispatch")
        assert path.endswith("flight_serve_dispatch.jsonl")
        assert "replica" not in _read(path)[1]

    def test_hostile_replica_id_sanitized_in_filename(self, tmp_path):
        rec = flightrec.FlightRecorder(str(tmp_path), capacity=2,
                                       registry=Registry(),
                                       replica_id="../evil id")
        rec.record("serve_tick", tick=0)
        path = rec.dump("x")
        # no path separators survive into the filename fragment: a
        # hostile id cannot traverse out of the log directory
        fragment = path.rsplit("flight_", 1)[1]
        assert "/" not in fragment and " " not in fragment
        assert path.startswith(str(tmp_path))
