"""obs/locksan — the runtime lock-order sanitizer (TS_LOCKSAN=1).

What must hold:
  * disabled (the default) the factories hand back PLAIN threading
    primitives — production pays nothing;
  * enabled, an AB/BA inversion raises the typed
    LockOrderInversionError at the second acquire, with the inner lock
    rolled back (the failure is a loud test assert, not a wedge);
  * the inversion writes a ``lock_inversion`` flight dump when a
    recorder is installed;
  * counters mirror into obs (``obs/locksan_*``) and ``snapshot()``
    stays exact;
  * RLock reentrancy records no self-edges, Condition wait/notify runs
    THROUGH the sanitized mutex;
  * the static cross-check (tslint --lock-graph JSON) counts edges the
    analyzer never predicted, transitively closed.

Stdlib + obs only — no jax.
"""

import json
import threading

import pytest

from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.obs import flightrec, locksan


@pytest.fixture(autouse=True)
def _sandbox():
    """Each test starts with an empty order graph and leaves the
    module latched back to the (env-driven, default off) state."""
    locksan.reset()
    locksan._SAN.static_edges = None
    locksan._SAN.static_path = None
    yield
    locksan.configure(enabled=locksan._env_enabled())
    locksan._SAN.static_edges = None
    locksan._SAN.static_path = None
    locksan.reset()


def _enable():
    locksan.configure(enabled=True)


# -- disabled: zero-cost passthrough ---------------------------------------

def test_disabled_factories_return_plain_primitives():
    locksan.configure(enabled=False)
    assert not locksan.active()
    lock = locksan.make_lock("X._lock")
    rlock = locksan.make_rlock("X._rlock")
    cond = locksan.make_condition("X._cv")
    assert not isinstance(lock, locksan.SanitizedLock)
    assert not isinstance(rlock, locksan.SanitizedLock)
    assert isinstance(cond, threading.Condition)
    with lock:
        pass
    with cond:
        cond.notify_all()
    assert locksan.snapshot()["acquisitions"] == 0


# -- enabled: order tracking + inversion -----------------------------------

def test_consistent_order_records_edges_without_raising():
    _enable()
    a = locksan.make_lock("T._a")
    b = locksan.make_lock("T._b")
    assert isinstance(a, locksan.SanitizedLock)
    for _ in range(3):
        with a:
            with b:
                pass
    snap = locksan.snapshot()
    assert snap["active"]
    assert snap["acquisitions"] == 6
    assert snap["inversions"] == 0
    assert snap["order_edges"] == [("T._a", "T._b")]


def test_inversion_raises_typed_error_and_rolls_back():
    _enable()
    a = locksan.make_lock("T._a")
    b = locksan.make_lock("T._b")
    with a:
        with b:
            pass
    b.acquire()
    with pytest.raises(locksan.LockOrderInversionError) as ei:
        a.acquire()
    err = ei.value
    assert err.acquiring == "T._a"
    assert err.held == ["T._b"]
    # the acquire rolled back: a is free for other threads, not wedged
    assert not a.locked()
    assert b.locked()
    b.release()
    assert locksan.snapshot()["inversions"] == 1


def test_inversion_needs_two_threads_only_in_real_life():
    # the WHOLE point: one thread exercising both orders is enough —
    # no adversarial scheduling required to catch the deadlock
    _enable()
    a = locksan.make_lock("D._a")
    b = locksan.make_lock("D._b")

    def order_ab():
        with a:
            with b:
                pass

    t = threading.Thread(target=order_ab)
    t.start()
    t.join(timeout=5.0)
    with b:
        with pytest.raises(locksan.LockOrderInversionError):
            with a:
                pass


def test_inversion_writes_flight_dump(tmp_path):
    _enable()
    reg = obs.registry()
    flightrec.install_flight_recorder(reg, str(tmp_path / "flight"))
    a = locksan.make_lock("F._a")
    b = locksan.make_lock("F._b")
    with a:
        with b:
            pass
    with b:
        with pytest.raises(locksan.LockOrderInversionError) as ei:
            a.acquire()
    dump = ei.value.flight_dump
    assert dump, "no flight dump path on the typed error"
    # JSONL: header line first, then one line per ring frame (the ring
    # may hold frames from whichever recorder won the first install)
    with open(dump, encoding="utf-8") as f:
        payload = json.loads(f.readline())
    assert payload["reason"] == "lock_inversion"
    assert payload["context"]["acquiring"] == "F._a"
    assert payload["context"]["held"] == ["F._b"]


def test_counters_mirror_into_obs():
    _enable()
    acq0 = obs.counter("obs/locksan_acquisitions_total").value
    inv0 = obs.counter("obs/locksan_inversions_total").value
    a = locksan.make_lock("C._a")
    b = locksan.make_lock("C._b")
    with a:
        with b:
            pass
    with b:
        with pytest.raises(locksan.LockOrderInversionError):
            a.acquire()
    assert obs.counter("obs/locksan_acquisitions_total").value - acq0 == 4
    assert obs.counter("obs/locksan_inversions_total").value - inv0 == 1


# -- primitives beyond the plain mutex -------------------------------------

def test_rlock_reentrancy_records_no_self_edge():
    _enable()
    r = locksan.make_rlock("R._lock")
    with r:
        with r:
            pass
    snap = locksan.snapshot()
    assert ("R._lock", "R._lock") not in snap["order_edges"]
    assert snap["inversions"] == 0


def test_condition_wait_notify_through_sanitized_mutex():
    _enable()
    mu = locksan.make_lock("Q._lock")
    cv = locksan.make_condition("Q._not_empty", lock=mu)
    items = []
    got = []

    def consumer():
        with cv:
            while not items:
                cv.wait(timeout=5.0)
            got.append(items.pop())

    t = threading.Thread(target=consumer)
    t.start()
    with cv:
        items.append("x")
        cv.notify()
    t.join(timeout=5.0)
    assert got == ["x"]
    assert locksan.snapshot()["inversions"] == 0
    # the waits/acquires all went through the ONE sanitized mutex
    assert locksan.snapshot()["acquisitions"] >= 2


# -- static cross-check ----------------------------------------------------

def _write_graph(tmp_path, edges):
    p = tmp_path / "lockgraph.json"
    p.write_text(json.dumps(
        {"version": 1, "tool": "tslint",
         "locks": sorted({n for e in edges for n in e}),
         "edges": [list(e) for e in edges]}), encoding="utf-8")
    return str(p)


def test_static_graph_modeled_edges_count_zero(tmp_path):
    _enable()
    locksan.configure(static_graph=_write_graph(
        tmp_path, [("S._a", "S._b"), ("S._b", "S._c")]))
    a = locksan.make_lock("S._a")
    b = locksan.make_lock("S._b")
    c = locksan.make_lock("S._c")
    with a:
        with b:
            pass
    # A -> C is only TRANSITIVELY in the analyzer's graph — the runtime
    # cross-check must close over it, not flag it
    with a:
        with c:
            pass
    snap = locksan.snapshot()
    assert snap["unmodeled_edges"] == 0
    assert snap["static_graph"].endswith("lockgraph.json")


def test_static_graph_unpredicted_edge_counts(tmp_path):
    _enable()
    locksan.configure(static_graph=_write_graph(
        tmp_path, [("S._a", "S._b")]))
    x = locksan.make_lock("S._x")
    y = locksan.make_lock("S._y")
    n0 = obs.counter("obs/locksan_unmodeled_edges_total").value
    with x:
        with y:
            pass
    assert locksan.snapshot()["unmodeled_edges"] == 1
    assert obs.counter("obs/locksan_unmodeled_edges_total").value - n0 == 1
    # the edge is only counted ONCE — re-walking the same order is news
    # to nobody
    with x:
        with y:
            pass
    assert locksan.snapshot()["unmodeled_edges"] == 1


# -- the wired package locks -----------------------------------------------

def test_wired_serve_locks_are_sanitized_when_enabled():
    _enable()
    from textsummarization_on_flink_tpu.serve.queue import ServeFuture
    fut = ServeFuture("u0", registry=obs.Registry())
    assert isinstance(fut._lock, locksan.SanitizedLock)
    assert fut._lock.name == "ServeFuture._lock"
    fut._resolve("done")
    assert fut.result(timeout=1.0) == "done"
    assert locksan.snapshot()["acquisitions"] > 0


def test_wired_locks_are_plain_when_disabled():
    locksan.configure(enabled=False)
    from textsummarization_on_flink_tpu.serve.queue import ServeFuture
    fut = ServeFuture("u0", registry=obs.Registry())
    assert not isinstance(fut._lock, locksan.SanitizedLock)
