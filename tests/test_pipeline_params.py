"""Param system: typing, defaults, required, JSON round-trip, group surface."""

import pytest

from textsummarization_on_flink_tpu.pipeline import params as P


def test_defaults_match_reference():
    # HasClusterConfig.java:15-29 defaults
    c = P.HasClusterConfig()
    assert c.get_coordinator_address() == "127.0.0.1:2181"
    assert c.get_worker_num() == 1
    assert c.get_ps_num() == 0
    # reference-name alias
    assert c.get_zookeeper_connect_str() == "127.0.0.1:2181"


def test_typed_set_rejects_wrong_type():
    c = P.HasClusterConfig()
    with pytest.raises(TypeError):
        c.set_worker_num("two")


def test_validator_rejects_bad_value():
    c = P.HasClusterConfig()
    with pytest.raises(ValueError):
        c.set_worker_num(0)


def test_required_param_raises_when_missing():
    s = P.HasTrainSelectedCols()
    with pytest.raises(KeyError):
        s.get_train_selected_cols()
    s.set_train_selected_cols(["uuid", "article", "reference"])
    assert s.get_train_selected_cols() == ["uuid", "article", "reference"]


def test_non_empty_validator():
    s = P.HasTrainSelectedCols()
    with pytest.raises(ValueError):
        s.set_train_selected_cols([])


def test_params_json_round_trip():
    c = P.HasClusterConfig()
    c.set_worker_num(4).set_coordinator_address("10.0.0.1:1234")
    j = c.params.to_json()
    c2 = P.HasClusterConfig()
    c2.params.load_json(j)
    assert c2.get_worker_num() == 4
    assert c2.get_coordinator_address() == "10.0.0.1:1234"


def test_hyper_params_key_default():
    t = P.HasTrainPythonConfig()
    assert t.get_train_hyper_params_key() == "TF_Hyperparameter"
    i = P.HasInferencePythonConfig()
    assert i.get_inference_hyper_params_key() == "TF_Hyperparameter"


def test_train_inference_groups_are_independent():
    """Train/inference params deliberately duplicated (Integration
    Report:30) so estimator and model can diverge."""

    class Both(P.HasTrainPythonConfig, P.HasInferencePythonConfig):
        pass

    b = Both()
    b.set_train_hyper_params(["--mode=train"])
    b.set_inference_hyper_params(["--mode=decode"])
    assert b.get_train_hyper_params() == ["--mode=train"]
    assert b.get_inference_hyper_params() == ["--mode=decode"]


def test_all_eight_groups_exist():
    for g in (P.HasClusterConfig, P.HasTrainPythonConfig,
              P.HasInferencePythonConfig, P.HasTrainSelectedCols,
              P.HasTrainOutputCols, P.HasTrainOutputTypes,
              P.HasInferenceSelectedCols, P.HasInferenceOutputCols,
              P.HasInferenceOutputTypes):
        assert issubclass(g, P.WithParams)


def test_load_params_json_revalidates_types():
    c = P.HasClusterConfig()
    with pytest.raises(TypeError):
        c.load_params_json('{"worker_num": "three"}')
    with pytest.raises(ValueError):
        c.load_params_json('{"worker_num": 0}')
    c.load_params_json('{"worker_num": 5, "unknown_extra": "kept"}')
    assert c.get_worker_num() == 5


def test_param_infos_collects_over_mro():
    class Both(P.HasClusterConfig, P.HasTrainSelectedCols):
        pass

    infos = Both.param_infos()
    assert "worker_num" in infos and "train_selected_cols" in infos
