"""Driver-hook smoke tests: entry() traces, dryrun_multichip executes."""

import jax

import __graft_entry__ as ge


def test_entry_traces():
    fn, args = ge.entry()
    # Tracing (abstract evaluation) validates shapes/dtypes without paying
    # the full XLA compile; the driver does the real compile check.
    lowered = jax.jit(fn).lower(*args)
    assert lowered is not None


def test_dryrun_multichip_8():
    ge.dryrun_multichip(8)


def test_dryrun_multichip_1():
    ge.dryrun_multichip(1)


def test_factor_mesh():
    assert ge._factor_mesh(8) == (2, 2, 2)
    assert ge._factor_mesh(4) == (1, 2, 2)
    assert ge._factor_mesh(2) == (1, 2, 1)
    assert ge._factor_mesh(1) == (1, 1, 1)
