"""Driver-hook smoke tests: entry() traces, dryrun_multichip executes."""

import jax
import pytest

import __graft_entry__ as ge


def test_entry_traces():
    fn, args = ge.entry()
    # Tracing (abstract evaluation) validates shapes/dtypes without paying
    # the full XLA compile; the driver does the real compile check.
    lowered = jax.jit(fn).lower(*args)
    assert lowered is not None


@pytest.mark.slow
def test_dryrun_multichip_8(monkeypatch):
    # the exact path the driver takes: scrubbed-env subprocess re-exec
    monkeypatch.delenv("TS_DRYRUN_INPROC", raising=False)
    ge.dryrun_multichip(8)


@pytest.mark.slow
def test_dryrun_multichip_1(monkeypatch):
    # in-process body (the conftest already pins the virtual CPU mesh)
    monkeypatch.setenv("TS_DRYRUN_INPROC", "1")
    ge.dryrun_multichip(1)


def test_scrubbed_env_strips_tpu_plugin(monkeypatch):
    monkeypatch.setenv("PYTHONPATH", "/root/.axon_site:/other/path")
    monkeypatch.setenv("XLA_FLAGS",
                       "--xla_force_host_platform_device_count=2 --foo")
    env = ge._scrubbed_cpu_env(8)
    assert ".axon_site" not in env["PYTHONPATH"]
    assert "/other/path" in env["PYTHONPATH"]
    assert env["JAX_PLATFORMS"] == "cpu"
    assert "--xla_force_host_platform_device_count=8" in env["XLA_FLAGS"]
    assert "device_count=2" not in env["XLA_FLAGS"]
    assert "--foo" in env["XLA_FLAGS"]


def test_factor_mesh():
    assert ge._factor_mesh(8) == (2, 2, 2)
    assert ge._factor_mesh(4) == (1, 2, 2)
    assert ge._factor_mesh(2) == (1, 2, 1)
    assert ge._factor_mesh(1) == (1, 1, 1)
