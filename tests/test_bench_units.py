"""Pure-unit tests for bench.py's analytic models and config plumbing.

The MFU number the driver records is only as trustworthy as the FLOPs
model behind it; pin its basic invariants (no child processes spawned
here — the JSON contract is exercised by the driver and the verify
drives)."""

import importlib.util
import os
import sys

import pytest

spec = importlib.util.spec_from_file_location(
    "bench", os.path.join(os.path.dirname(__file__), "..", "bench.py"))
bench = importlib.util.module_from_spec(spec)
sys.modules["bench"] = bench
spec.loader.exec_module(bench)

from textsummarization_on_flink_tpu.config import HParams  # noqa: E402


def test_pg_flops_positive_and_linear_in_batch():
    hps1 = HParams(batch_size=1)
    hps8 = HParams(batch_size=8)
    f1 = bench.train_flops_per_step(hps1)
    f8 = bench.train_flops_per_step(hps8)
    assert f1 > 0
    assert f8 == pytest.approx(8 * f1)


def test_pg_flops_dominated_by_vocab_projection():
    """At reference scale the H x 50k projection dominates (SURVEY §7.2);
    halving the vocab should cut total FLOPs by a large fraction."""
    full = bench.train_flops_per_step(HParams(batch_size=16))
    half = bench.train_flops_per_step(
        HParams(batch_size=16, vocab_size=25000))
    assert half < 0.75 * full


def test_transformer_flops_positive_linear_and_layer_scaled():
    hps = HParams(model_family="transformer", batch_size=4)
    f = bench.transformer_flops_per_step(hps)
    assert f > 0
    assert bench.transformer_flops_per_step(
        hps.replace(batch_size=8)) == pytest.approx(2 * f)
    deeper = bench.transformer_flops_per_step(
        hps.replace(enc_layers=12, dec_layers=12))
    assert deeper > f


def test_peak_flops_env_override(monkeypatch):
    monkeypatch.setenv("BENCH_PEAK_TFLOPS", "123.5")
    assert bench.peak_flops_for(object()) == pytest.approx(123.5e12)


def test_peak_flops_known_device_kinds(monkeypatch):
    monkeypatch.delenv("BENCH_PEAK_TFLOPS", raising=False)

    class Dev:
        def __init__(self, kind):
            self.device_kind = kind

    assert bench.peak_flops_for(Dev("TPU v4")) == pytest.approx(275e12)
    assert bench.peak_flops_for(Dev("TPU v5e")) == pytest.approx(197e12)
    assert bench.peak_flops_for(Dev("Banana9000")) is None


def test_input_mode_child_env_forces_cpu(monkeypatch):
    """BENCH_MODE=input is host-only; the supervisor must scrub the env
    so a down TPU tunnel can never hang the child's jax import."""
    monkeypatch.setenv("BENCH_MODE", "input")
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    monkeypatch.setenv("PYTHONPATH", "/root/.axon_site")
    env = bench._child_env()
    assert env["JAX_PLATFORMS"] == "cpu"
    assert ".axon_site" not in env.get("PYTHONPATH", "")


def test_input_bench_runs_on_host(tmp_path):
    """The input-pipeline bench end to end (tiny scale): one JSON line
    with a positive samples/s.  Runs in a subprocess like the real
    supervisor does — bench_input's Batcher threads are daemon threads
    reaped by process exit, and must not leak into this pytest
    process."""
    import json
    import subprocess

    env = dict(os.environ)
    env.update(TS_BENCH_CHILD="1", BENCH_MODE="input", BENCH_PRESET="tiny",
               BENCH_SECONDS="0.5", BENCH_BATCH="4", JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), "..",
                                      "bench.py")],
        env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "input_pipeline_samples_per_sec"
    assert rec["value"] > 0


def test_preset_overrides_family(monkeypatch):
    monkeypatch.setenv("BENCH_PRESET", "tiny")
    monkeypatch.setenv("BENCH_FAMILY", "transformer")
    o = bench._preset_overrides()
    assert o["model_family"] == "transformer"
    assert o["hidden_dim"] % o["num_heads"] == 0
    # the overrides must build a valid HParams
    HParams(**o).validate()
    monkeypatch.delenv("BENCH_FAMILY")
    o2 = bench._preset_overrides()
    assert "model_family" not in o2
