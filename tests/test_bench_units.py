"""Pure-unit tests for bench.py's analytic models and config plumbing.

The MFU number the driver records is only as trustworthy as the FLOPs
model behind it; pin its basic invariants (no child processes spawned
here — the JSON contract is exercised by the driver and the verify
drives)."""

import importlib.util
import os
import sys

import pytest

spec = importlib.util.spec_from_file_location(
    "bench", os.path.join(os.path.dirname(__file__), "..", "bench.py"))
bench = importlib.util.module_from_spec(spec)
sys.modules["bench"] = bench
spec.loader.exec_module(bench)

from textsummarization_on_flink_tpu.config import HParams  # noqa: E402


def test_pg_flops_positive_and_linear_in_batch():
    hps1 = HParams(batch_size=1)
    hps8 = HParams(batch_size=8)
    f1 = bench.train_flops_per_step(hps1)
    f8 = bench.train_flops_per_step(hps8)
    assert f1 > 0
    assert f8 == pytest.approx(8 * f1)


def test_pg_flops_dominated_by_vocab_projection():
    """At reference scale the H x 50k projection dominates (SURVEY §7.2);
    halving the vocab should cut total FLOPs by a large fraction."""
    full = bench.train_flops_per_step(HParams(batch_size=16))
    half = bench.train_flops_per_step(
        HParams(batch_size=16, vocab_size=25000))
    assert half < 0.75 * full


def test_transformer_flops_positive_linear_and_layer_scaled():
    hps = HParams(model_family="transformer", batch_size=4)
    f = bench.transformer_flops_per_step(hps)
    assert f > 0
    assert bench.transformer_flops_per_step(
        hps.replace(batch_size=8)) == pytest.approx(2 * f)
    deeper = bench.transformer_flops_per_step(
        hps.replace(enc_layers=12, dec_layers=12))
    assert deeper > f


def test_peak_flops_env_override(monkeypatch):
    monkeypatch.setenv("BENCH_PEAK_TFLOPS", "123.5")
    assert bench.peak_flops_for(object()) == pytest.approx(123.5e12)


def test_peak_flops_known_device_kinds(monkeypatch):
    monkeypatch.delenv("BENCH_PEAK_TFLOPS", raising=False)

    class Dev:
        def __init__(self, kind):
            self.device_kind = kind

    assert bench.peak_flops_for(Dev("TPU v4")) == pytest.approx(275e12)
    assert bench.peak_flops_for(Dev("TPU v5e")) == pytest.approx(197e12)
    assert bench.peak_flops_for(Dev("Banana9000")) is None


def test_input_mode_child_env_forces_cpu(monkeypatch):
    """BENCH_MODE=input is host-only; the supervisor must scrub the env
    so a down TPU tunnel can never hang the child's jax import."""
    monkeypatch.setenv("BENCH_MODE", "input")
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    monkeypatch.setenv("PYTHONPATH", "/root/.axon_site")
    env = bench._child_env()
    assert env["JAX_PLATFORMS"] == "cpu"
    assert ".axon_site" not in env.get("PYTHONPATH", "")


def test_input_bench_runs_on_host(tmp_path):
    """The input-pipeline bench end to end (tiny scale): one JSON line
    with a positive samples/s.  Runs in a subprocess like the real
    supervisor does — bench_input's Batcher threads are daemon threads
    reaped by process exit, and must not leak into this pytest
    process."""
    import json
    import subprocess

    env = dict(os.environ)
    env.update(TS_BENCH_CHILD="1", BENCH_MODE="input", BENCH_PRESET="tiny",
               BENCH_SECONDS="0.5", BENCH_BATCH="4", JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), "..",
                                      "bench.py")],
        env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "input_pipeline_samples_per_sec"
    assert rec["value"] > 0


def test_config_fingerprint_distinguishes_sweep_rows(monkeypatch):
    monkeypatch.setenv("BENCH_MODE", "train")
    for var in ("BENCH_BATCH", "BENCH_PRESET", "BENCH_FAMILY",
                "TS_PALLAS", "BENCH_PLATFORM", "BENCH_REMAT", "TS_FLASH"):
        monkeypatch.delenv(var, raising=False)
    base = bench._config_fingerprint()
    assert base == {"mode": "train", "platform": "tpu", "batch": 16,
                    "preset": "ref", "family": "pointer_generator",
                    "pallas": "off", "flash": "off", "unroll": 8,
                    "remat": False}
    # pg never reads TS_FLASH: the RESOLVED axis must not split records
    monkeypatch.setenv("TS_FLASH", "on")
    assert bench._config_fingerprint() == base
    # transformer: env forces the padded kernel -> different program
    monkeypatch.setenv("BENCH_FAMILY", "transformer")
    tf_on = bench._config_fingerprint()
    assert tf_on["flash"] == "on"
    monkeypatch.delenv("TS_FLASH")
    # auto at ref scale (T=400, hd=32 unaligned) resolves to the einsum
    # path — same program as off, so records cross-substitute correctly
    assert bench._config_fingerprint()["flash"] == "off"
    monkeypatch.delenv("BENCH_FAMILY")
    monkeypatch.setenv("BENCH_BATCH", "64")
    assert bench._config_fingerprint() != base
    # a CPU smoke record must never satisfy a TPU ask
    monkeypatch.delenv("BENCH_BATCH")
    monkeypatch.setenv("BENCH_PLATFORM", "cpu")
    assert bench._config_fingerprint() != base
    # remat is a different compiled program: its row must never stand in
    monkeypatch.delenv("BENCH_PLATFORM")
    monkeypatch.setenv("BENCH_REMAT", "1")
    assert bench._config_fingerprint() != base
    # byte-diet lever axes (ISSUE 5): different compiled programs, so
    # lever rows must never cross-substitute — but the axes appear only
    # when NON-default, so pre-existing banked records keep matching
    # default asks (no orphaned history)
    monkeypatch.delenv("BENCH_REMAT")
    monkeypatch.setenv("BENCH_LOSS_CHUNK", "25")
    chunked = bench._config_fingerprint()
    assert chunked != base and chunked["loss_chunk"] == 25
    monkeypatch.delenv("BENCH_LOSS_CHUNK")
    monkeypatch.setenv("BENCH_OPT_DTYPE", "bfloat16")
    opt = bench._config_fingerprint()
    assert opt != base and opt["opt_dtype"] == "bfloat16"
    monkeypatch.delenv("BENCH_OPT_DTYPE")
    assert bench._config_fingerprint() == base


def test_config_fingerprint_arena_axis_non_default_only(monkeypatch):
    """The ISSUE-20 paged-arena axis: an armed arena runs different
    kernels under a different admission policy, so it must split
    records — but only when armed, so banked dense serve records keep
    matching default asks."""
    monkeypatch.setenv("BENCH_MODE", "serve")
    for var in ("BENCH_SERVE_ARENA_PAGES", "BENCH_SERVE_MIX",
                "BENCH_SERVE_TIER", "BENCH_SERVE_REPLICAS",
                "BENCH_SERVE_ZIPF", "BENCH_SERVE_HIER"):
        monkeypatch.delenv(var, raising=False)
    base = bench._config_fingerprint()
    assert "arena" not in base
    monkeypatch.setenv("BENCH_SERVE_ARENA_PAGES", "24")
    armed = bench._config_fingerprint()
    assert armed != base and armed["arena"] == 24
    # 0 is the dense sentinel, not an axis value
    monkeypatch.setenv("BENCH_SERVE_ARENA_PAGES", "0")
    assert bench._config_fingerprint() == base


def _write_jsonl(path, recs):
    import json

    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")


def test_stale_fallback_picks_matching_newest(tmp_path, monkeypatch):
    """VERDICT r2 #1: live-failure must fall back to the newest matching
    BENCH_ALL.jsonl record, marked stale, never a mismatched config."""
    monkeypatch.setenv("BENCH_MODE", "train")
    for var in ("BENCH_BATCH", "BENCH_PRESET", "BENCH_FAMILY",
                "TS_PALLAS", "BENCH_PLATFORM"):
        monkeypatch.delenv(var, raising=False)
    fp = bench._config_fingerprint()
    path = tmp_path / "BENCH_ALL.jsonl"
    _write_jsonl(path, [
        # wrong config (batch 64): must be skipped
        {"metric": "train_samples_per_sec", "value": 999.0,
         "config_fingerprint": dict(fp, batch=64),
         "captured_at": "2026-07-30T09:00:00Z"},
        # older matching record
        {"metric": "train_samples_per_sec", "value": 500.0,
         "config_fingerprint": fp, "captured_at": "2026-07-30T07:00:00Z"},
        # newest matching record: the winner
        {"metric": "train_samples_per_sec", "value": 560.0,
         "config_fingerprint": fp, "captured_at": "2026-07-30T08:00:00Z"},
        # error record: must be skipped even though it matches
        {"metric": "train_samples_per_sec", "value": 0.0,
         "config_fingerprint": fp, "error": "boom",
         "captured_at": "2026-07-30T09:30:00Z"},
    ])
    monkeypatch.setenv("BENCH_STALE_FILE", str(path))
    rec = bench._stale_fallback("train_samples_per_sec", "tunnel down")
    assert rec is not None
    assert rec["value"] == 560.0
    assert rec["stale"] is True
    assert rec["live_error"] == "tunnel down"
    assert rec["captured_at"] == "2026-07-30T08:00:00Z"


def test_stale_fallback_none_without_match(tmp_path, monkeypatch):
    monkeypatch.setenv("BENCH_MODE", "decode")
    path = tmp_path / "BENCH_ALL.jsonl"
    _write_jsonl(path, [{"metric": "train_samples_per_sec", "value": 1.0,
                         "captured_at": "2026-07-30T08:00:00Z",
                         "run": "train_b16"}])
    monkeypatch.setenv("BENCH_STALE_FILE", str(path))
    assert bench._stale_fallback("beam_decode_p50_latency_per_article",
                                 "x") is None
    monkeypatch.setenv("BENCH_STALE_FILE", str(tmp_path / "missing.jsonl"))
    assert bench._stale_fallback("beam_decode_p50_latency_per_article",
                                 "x") is None


def test_stale_fallback_rejects_unfingerprinted_records(tmp_path,
                                                        monkeypatch):
    """A legacy record that cannot prove its config (no fingerprint)
    must never stand in — run tags like train_b64 all contain 'train'
    and would cross-match configs."""
    monkeypatch.setenv("BENCH_MODE", "train")
    for var in ("BENCH_BATCH", "BENCH_PRESET", "BENCH_FAMILY",
                "TS_PALLAS", "BENCH_PLATFORM"):
        monkeypatch.delenv(var, raising=False)
    path = tmp_path / "BENCH_ALL.jsonl"
    _write_jsonl(path, [
        {"metric": "train_samples_per_sec", "value": 1.0,
         "run": "train_b64", "captured_at": "2026-07-30T08:00:00Z"},
        {"metric": "train_samples_per_sec", "value": 2.0,
         "run": "train_b16", "captured_at": "2026-07-30T08:10:00Z"},
    ])
    monkeypatch.setenv("BENCH_STALE_FILE", str(path))
    assert bench._stale_fallback("train_samples_per_sec", "x") is None


def test_stale_fallback_platform_and_stale_guards(tmp_path, monkeypatch):
    """(a) decode fingerprints carry the RESOLVED beam-loop axis (an
    'auto' ask resolves per platform — scan on the proxied tpu, chunked
    on an attached cpu child — so a pre-ISSUE-7 auto=while record can
    never stand in for today's auto); (b) a record whose measured
    platform is cpu never satisfies a tpu ask even if the env-intent
    fingerprint matches; (c) records already marked stale are not
    fallback sources."""
    monkeypatch.setenv("BENCH_MODE", "decode")
    for var in ("BENCH_BATCH", "BENCH_PRESET", "BENCH_FAMILY",
                "TS_PALLAS", "BENCH_PLATFORM", "TS_BEAM_LOOP"):
        monkeypatch.delenv(var, raising=False)
    fp = bench._config_fingerprint()
    assert fp["beam_loop"] == "scan" and fp["platform"] == "tpu"
    monkeypatch.setenv("BENCH_PLATFORM", "cpu")
    assert bench._config_fingerprint()["beam_loop"] == "chunked"
    monkeypatch.delenv("BENCH_PLATFORM")
    monkeypatch.setenv("TS_BEAM_LOOP", "while")
    assert bench._config_fingerprint() != fp
    monkeypatch.delenv("TS_BEAM_LOOP")

    path = tmp_path / "BENCH_ALL.jsonl"
    metric = "beam_decode_p50_latency_per_article"
    _write_jsonl(path, [
        # measured on cpu despite a tpu-intent fingerprint: reject
        {"metric": metric, "value": 1.0, "config_fingerprint": fp,
         "platform": "cpu", "captured_at": "2026-07-30T08:00:00Z"},
        # good record
        {"metric": metric, "value": 2.0, "config_fingerprint": fp,
         "platform": "tpu", "captured_at": "2026-07-30T08:10:00Z"},
        # a prior outage's re-appended stale copy: reject
        {"metric": metric, "value": 3.0, "config_fingerprint": fp,
         "platform": "tpu", "stale": True,
         "captured_at": "2026-07-30T08:20:00Z"},
    ])
    monkeypatch.setenv("BENCH_STALE_FILE", str(path))
    rec = bench._stale_fallback(metric, "x")
    assert rec is not None and rec["value"] == 2.0


def test_stale_fallback_newest_by_captured_at_not_file_order(tmp_path,
                                                             monkeypatch):
    """Interleaved appends (concurrent or interrupted sweeps) can put an
    older record later in the file; captured_at must win over position."""
    monkeypatch.setenv("BENCH_MODE", "train")
    for var in ("BENCH_BATCH", "BENCH_PRESET", "BENCH_FAMILY",
                "TS_PALLAS", "BENCH_PLATFORM"):
        monkeypatch.delenv(var, raising=False)
    fp = bench._config_fingerprint()
    path = tmp_path / "BENCH_ALL.jsonl"
    _write_jsonl(path, [
        {"metric": "train_samples_per_sec", "value": 600.0,
         "config_fingerprint": fp, "captured_at": "2026-07-30T09:00:00Z"},
        # appended later but captured EARLIER: must lose
        {"metric": "train_samples_per_sec", "value": 500.0,
         "config_fingerprint": fp, "captured_at": "2026-07-30T07:00:00Z"},
    ])
    monkeypatch.setenv("BENCH_STALE_FILE", str(path))
    rec = bench._stale_fallback("train_samples_per_sec", "x")
    assert rec is not None and rec["value"] == 600.0


def test_supervisor_records_success_to_jsonl(tmp_path):
    """VERDICT r3 missing#4: a SUCCESSFUL supervised run must append its
    record (fingerprint + captured_at + run tag) to the shared JSONL so
    any tunnel-window measurement becomes permanent fallback material.
    Uses the host-only input mode so no TPU is needed."""
    import json
    import subprocess

    path = tmp_path / "BENCH_ALL.jsonl"
    env = dict(os.environ)
    for var in ("TS_BENCH_CHILD", "BENCH_BATCH", "BENCH_PRESET",
                "BENCH_FAMILY", "TS_PALLAS", "BENCH_NO_RECORD"):
        env.pop(var, None)
    env.update(BENCH_MODE="input", BENCH_PRESET="tiny", BENCH_SECONDS="0.5",
               BENCH_BATCH="4", BENCH_ATTEMPTS="1", BENCH_TIMEOUT="110",
               BENCH_STALE_FILE=str(path), BENCH_RUN_TAG="input_pipeline")
    proc = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), "..",
                                      "bench.py")],
        env=env, capture_output=True, text=True, timeout=150)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    printed = json.loads(proc.stdout.strip().splitlines()[-1])
    assert printed["value"] > 0
    lines = [json.loads(s) for s in
             path.read_text().strip().splitlines()]
    assert len(lines) == 1
    rec = lines[0]
    assert rec == printed
    assert rec["run"] == "input_pipeline"
    assert rec["config_fingerprint"]["mode"] == "input"
    assert "captured_at" in rec


def test_supervisor_emits_stale_record_when_tunnel_down(tmp_path):
    """End to end through the real supervisor: child times out, stale
    record on disk, one parseable JSON line with stale:true on stdout and
    exit code 0 (the driver must get a usable number)."""
    import json
    import subprocess

    fp = {"mode": "train", "platform": "cpu", "batch": 16, "preset": "ref",
          "family": "pointer_generator", "remat": False, "pallas": "off",
          "flash": "off", "unroll": 8}
    path = tmp_path / "BENCH_ALL.jsonl"
    _write_jsonl(path, [
        {"metric": "train_samples_per_sec", "value": 552.8,
         "unit": "samples/s", "vs_baseline": 40.9, "mfu": 0.031,
         "config_fingerprint": fp, "captured_at": "2026-07-30T04:45:00Z"},
    ])
    env = dict(os.environ)
    # ambient sweep/config vars would shift the fingerprint away from
    # the hard-coded record above
    for var in ("TS_BENCH_CHILD", "BENCH_BATCH", "BENCH_PRESET",
                "BENCH_FAMILY", "TS_PALLAS", "BENCH_REMAT", "TS_FLASH"):
        env.pop(var, None)
    # a command that can never finish within the timeout stands in for a
    # hung tunnel; BENCH_SLEEP_FOR_TEST makes the child sleep before work
    env.update(BENCH_MODE="train", BENCH_ATTEMPTS="1", BENCH_TIMEOUT="1",
               BENCH_STALE_FILE=str(path), BENCH_PLATFORM="cpu",
               BENCH_SLEEP_FOR_TEST="30")
    proc = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), "..",
                                      "bench.py")],
        env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["stale"] is True
    assert rec["value"] == 552.8
    assert rec["metric"] == "train_samples_per_sec"
    assert "live_error" in rec


def test_supervisor_no_stale_on_deterministic_failure(tmp_path):
    """retryable:false means a code/config regression, not a tunnel
    flake — an old good record must NOT paper over it (exit 1, error
    JSON, no stale record)."""
    import json
    import subprocess

    path = tmp_path / "BENCH_ALL.jsonl"
    _write_jsonl(path, [
        {"metric": "bench_bogus", "value": 42.0,
         "config_fingerprint": {"mode": "bogus", "platform": "cpu"},
         "captured_at": "2026-07-30T04:45:00Z"},
    ])
    env = dict(os.environ)
    env.pop("TS_BENCH_CHILD", None)
    env.update(BENCH_MODE="bogus", BENCH_ATTEMPTS="2", BENCH_TIMEOUT="60",
               BENCH_STALE_FILE=str(path), BENCH_PLATFORM="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), "..",
                                      "bench.py")],
        env=env, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert "error" in rec and "stale" not in rec
    # only ONE attempt despite BENCH_ATTEMPTS=2: deterministic failures
    # must not retry
    assert "attempt 1/2" in rec["error"]


@pytest.mark.slow
def test_bytes_mode_contract_on_cpu(tmp_path):
    """BENCH_MODE=bytes end to end through the real supervisor+child at
    tiny scale: one JSON line with the lever table, reduction fields,
    and the analytic grad-allreduce bytes — the CPU-verifiable side of
    the byte-diet claims (the committed REGRESSION gate lives in
    tests/test_bytes_gate.py at the calibrated gate scale; this checks
    the bench-row contract only, so no reduction thresholds here: at
    tiny vocab the scores tensor is noise)."""
    import json
    import subprocess

    path = tmp_path / "BENCH_ALL.jsonl"
    env = dict(os.environ)
    for var in ("TS_BENCH_CHILD", "BENCH_BATCH", "BENCH_PRESET",
                "BENCH_FAMILY", "BENCH_LOSS_CHUNK", "BENCH_OPT_DTYPE",
                "BENCH_NO_RECORD"):
        env.pop(var, None)
    env.update(BENCH_MODE="bytes", BENCH_PRESET="tiny", BENCH_BATCH="4",
               BENCH_LOSS_CHUNK="2", BENCH_ATTEMPTS="1",
               BENCH_TIMEOUT="300", BENCH_STALE_FILE=str(path),
               BENCH_RUN_TAG="bytes_cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), "..",
                                      "bench.py")],
        env=env, capture_output=True, text=True, timeout=360)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "train_step_bytes_accessed"
    assert rec["value"] > 0
    assert set(rec["levers"]) == {"baseline", "loss_chunk", "opt_bf16",
                                  "combined"}
    for lever in rec["levers"].values():
        assert lever["bytes"] > 0 and lever["flops"] > 0
    assert rec["levers"]["baseline"]["reduction_vs_baseline"] == 0.0
    assert rec["grad_allreduce_bytes_bf16"] * 2 == \
        rec["grad_allreduce_bytes_f32"]
    assert rec["config_fingerprint"]["mode"] == "bytes"
    assert rec["config_fingerprint"]["platform"] == "cpu"
    assert rec["config_fingerprint"]["chunk"] == 2
    lines = [json.loads(s) for s in path.read_text().strip().splitlines()]
    assert len(lines) == 1 and lines[0] == rec


def test_preset_overrides_family(monkeypatch):
    monkeypatch.setenv("BENCH_PRESET", "tiny")
    monkeypatch.setenv("BENCH_FAMILY", "transformer")
    o = bench._preset_overrides()
    assert o["model_family"] == "transformer"
    assert o["hidden_dim"] % o["num_heads"] == 0
    # the overrides must build a valid HParams
    HParams(**o).validate()
    monkeypatch.delenv("BENCH_FAMILY")
    o2 = bench._preset_overrides()
    assert "model_family" not in o2


@pytest.mark.slow
def test_decode_child_reports_step_usage(tmp_path):
    """BENCH_MODE=decode end to end through the real supervisor+child on
    CPU at tiny scale: the record carries the loop-decision data
    (gen_steps_p50/max vs max_dec_steps — PERF.md's corrected chunked
    rule reads these) and self-appends with a decode fingerprint."""
    import json
    import subprocess

    path = tmp_path / "BENCH_ALL.jsonl"
    env = dict(os.environ)
    for var in ("TS_BENCH_CHILD", "BENCH_BATCH", "BENCH_PRESET",
                "BENCH_FAMILY", "TS_PALLAS", "BENCH_NO_RECORD",
                "TS_BEAM_LOOP", "BENCH_STOP_BIAS", "BENCH_DECODE_FIXTURE"):
        env.pop(var, None)
    env.update(BENCH_MODE="decode", BENCH_PRESET="tiny", BENCH_STEPS="2",
               BENCH_BATCH="2", BENCH_ATTEMPTS="1", BENCH_TIMEOUT="240",
               BENCH_PLATFORM="cpu", BENCH_STALE_FILE=str(path),
               BENCH_RUN_TAG="decode_b4")
    proc = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), "..",
                                      "bench.py")],
        env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["max_dec_steps"] >= rec["gen_steps_max"]
    assert rec["gen_steps_max"] >= rec["gen_steps_p50"] >= 1
    assert rec["config_fingerprint"]["mode"] == "decode"
    # STOP-capable params are the default (VERDICT r4 weak #1): the
    # record and fingerprint both carry the params source so a
    # worst-case random-init measurement can never be cross-substituted
    assert rec["params_source"].startswith("stop_bias:")
    assert rec["config_fingerprint"]["params"] == rec["params_source"]
    lines = [json.loads(s) for s in path.read_text().strip().splitlines()]
    assert len(lines) == 1 and lines[0] == rec


def test_decode_params_spec_fixture_detection(tmp_path, monkeypatch):
    """'fixture' exactly when the family's fixture file exists (or
    BENCH_DECODE_FIXTURE points at one); ''/'0'/'none' disable; else the
    calibrated stop-bias spec with the env-overridable magnitude."""
    monkeypatch.delenv("BENCH_DECODE_FIXTURE", raising=False)
    monkeypatch.delenv("BENCH_STOP_BIAS", raising=False)
    assert bench._decode_params_spec("no_such_family") == "stop_bias:6"
    monkeypatch.setenv("BENCH_STOP_BIAS", "5.5")
    assert bench._decode_params_spec("no_such_family") == "stop_bias:5.5"
    fx = tmp_path / "fx.npz"
    fx.write_bytes(b"one fixture")
    monkeypatch.setenv("BENCH_DECODE_FIXTURE", str(fx))
    spec1 = bench._decode_params_spec("no_such_family")
    assert spec1.startswith("fixture:") and len(spec1.split(":")[1]) == 12
    # a REGENERATED fixture (different content) must change the spec so
    # banked decode rows are invalidated, not cross-substituted
    fx.write_bytes(b"another fixture, retrained")
    os.utime(fx, (1, 1))  # force a distinct (size,mtime) cache key
    spec2 = bench._decode_params_spec("no_such_family")
    assert spec2.startswith("fixture:") and spec2 != spec1
    monkeypatch.setenv("BENCH_DECODE_FIXTURE", "none")
    assert bench._decode_params_spec("no_such_family") == "stop_bias:5.5"
    # an explicitly requested fixture that is missing must fail loudly,
    # never silently degrade to stop-bias params
    monkeypatch.setenv("BENCH_DECODE_FIXTURE", str(tmp_path / "absent.npz"))
    with pytest.raises(ValueError, match="does not exist"):
        bench._decode_params_spec("no_such_family")
    # default-path auto-detection is gated to the reference preset (the
    # fixture is reference-scale; a tiny smoke run must not pick it up)
    monkeypatch.delenv("BENCH_DECODE_FIXTURE")
    monkeypatch.setenv("BENCH_PRESET", "tiny")
    assert bench._decode_params_spec(
        "no_such_family") == "stop_bias:5.5"


def test_stop_biased_bumps_only_vocab_sized_bias_vectors():
    import jax.numpy as jnp

    from textsummarization_on_flink_tpu.data.vocab import STOP_ID

    vsize = 64
    params = {"out_bias": jnp.zeros((vsize,)),
              "w": jnp.zeros((4, vsize)),  # matrix: untouched
              "other": jnp.zeros((vsize + 1,))}
    out = bench._stop_biased(params, vsize, 3.0)
    assert float(out["out_bias"][STOP_ID]) == 3.0
    assert float(jnp.sum(jnp.abs(out["out_bias"]))) == 3.0
    assert float(jnp.sum(jnp.abs(out["w"]))) == 0.0
    assert float(jnp.sum(jnp.abs(out["other"]))) == 0.0


def test_load_decode_fixture_roundtrip_and_shape_guard(tmp_path):
    import jax
    import numpy as np

    init = {"a": {"b": np.zeros((2, 3), np.float32)},
            "c": [np.ones((4,), np.float32)]}
    flat, _ = jax.tree_util.tree_flatten_with_path(init)
    path = tmp_path / "fx.npz"
    np.savez(path, **{jax.tree_util.keystr(k): v * 2 + 1
                      for k, v in flat})
    out = bench._load_decode_fixture(str(path), init)
    assert np.allclose(out["a"]["b"], 1.0) and np.allclose(out["c"][0], 3.0)
    # wrong-scale fixture fails loudly
    bad = {"a": {"b": np.zeros((2, 3), np.float32)},
           "c": [np.ones((5,), np.float32)]}
    with pytest.raises(ValueError, match="shape"):
        bench._load_decode_fixture(str(path), bad)
    # model grew a leaf the fixture lacks -> missing
    grown = dict(init, d=np.zeros((1,), np.float32))
    with pytest.raises(ValueError, match="missing"):
        bench._load_decode_fixture(str(path), grown)
    # fixture holds leaves the model no longer has (different config,
    # e.g. coverage) -> fails loudly instead of silently partial-loading
    with pytest.raises(ValueError, match="keys the model does not"):
        bench._load_decode_fixture(str(path), {"a": {"b": init["a"]["b"]}})
