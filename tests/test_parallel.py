"""Multi-chip sharding tests on the 8-device virtual CPU mesh.

Validates that the pjit-sharded train step (parallel/mesh.py) is
numerically identical to the single-device step — i.e. that dp gradient
psum, tp vocab-matmul collectives, and sp context-parallel reductions are
pure layout changes, not semantic ones.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.data.batching import Batch, SummaryExample
from textsummarization_on_flink_tpu.data.vocab import Vocab
from textsummarization_on_flink_tpu.parallel import mesh as mesh_lib
from textsummarization_on_flink_tpu.train import trainer as trainer_lib


def tiny_hps(**kw) -> HParams:
    base = dict(hidden_dim=8, emb_dim=6, batch_size=8, max_enc_steps=16,
                max_dec_steps=6, beam_size=2, min_dec_steps=2, vocab_size=64,
                max_oov_buckets=8, num_steps=2)
    base.update(kw)
    return HParams(**base)


def tiny_vocab(n: int = 64) -> Vocab:
    return Vocab(words=[f"w{i}" for i in range(n - 4)], max_size=n)


def make_batch(hps, vocab, seed=0):
    rng = np.random.RandomState(seed)
    exs = []
    for i in range(hps.batch_size):
        n_art = rng.randint(5, hps.max_enc_steps)
        n_abs = rng.randint(2, hps.max_dec_steps)
        art = " ".join(rng.choice([f"w{j}" for j in range(50)] + ["zzz_oov"],
                                  n_art))
        abs_ = " ".join(rng.choice([f"w{j}" for j in range(50)], n_abs))
        exs.append(SummaryExample.build(art, [abs_], vocab, hps))
    return Batch(exs, hps, vocab)


@pytest.fixture(scope="module")
def setup():
    hps = tiny_hps()
    vocab = tiny_vocab(hps.vocab_size)
    batch = make_batch(hps, vocab)
    state = trainer_lib.init_train_state(hps, vocab.size(), seed=7)
    single = jax.jit(trainer_lib.make_train_step(hps))
    ref_state, ref_metrics = single(state, batch.as_arrays())
    return hps, vocab, batch, state, ref_state, ref_metrics


@pytest.mark.parametrize("dp,tp,sp", [(8, 1, 1), (4, 2, 1), (2, 2, 2)])
def test_sharded_train_step_matches_single_device(setup, dp, tp, sp):
    hps, vocab, batch, state, ref_state, ref_metrics = setup
    hps_m = hps.replace(dp=dp, tp=tp, sp=sp)
    plan = mesh_lib.make_mesh(hps_m)
    sharded_state = mesh_lib.shard_train_state(plan, state)
    step = mesh_lib.make_sharded_train_step(plan, donate=False)
    new_state, metrics = step(sharded_state, batch.as_arrays())
    np.testing.assert_allclose(float(metrics.loss), float(ref_metrics.loss),
                               rtol=2e-5)
    np.testing.assert_allclose(float(metrics.global_norm),
                               float(ref_metrics.global_norm), rtol=2e-5)
    # parameters after the update agree leaf-by-leaf
    ref_leaves = jax.tree_util.tree_leaves(ref_state.params)
    got_leaves = jax.tree_util.tree_leaves(
        jax.device_get(new_state.params))
    for r, g in zip(ref_leaves, got_leaves):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r), rtol=1e-4,
                                   atol=1e-6)


def test_param_shardings_place_vocab_tensors_on_tp(setup):
    hps, vocab, batch, state, *_ = setup
    plan = mesh_lib.make_mesh(hps.replace(dp=4, tp=2))
    sharded = mesh_lib.shard_train_state(plan, state)
    emb_shard = sharded.params["embedding"].sharding
    w_shard = sharded.params["output_projection"]["w"].sharding
    assert emb_shard.spec == mesh_lib.P("tp", None)
    assert w_shard.spec == mesh_lib.P(None, "tp")
    # LSTM kernels replicated
    assert sharded.params["encoder"]["fw"]["kernel"].sharding.spec == mesh_lib.P()


def test_sharded_eval_step(setup):
    hps, vocab, batch, state, ref_state, ref_metrics = setup
    plan = mesh_lib.make_mesh(hps.replace(dp=8))
    sharded = mesh_lib.shard_train_state(plan, state)
    eval_step = mesh_lib.make_sharded_eval_step(plan)
    metrics = eval_step(sharded.params, batch.as_arrays())
    np.testing.assert_allclose(float(metrics.loss), float(ref_metrics.loss),
                               rtol=2e-5)


def test_mesh_device_count_validation():
    hps = tiny_hps(dp=16)
    with pytest.raises(ValueError):
        mesh_lib.make_mesh(hps)


def test_multi_step_training_loss_decreases(setup):
    hps, vocab, batch, state, *_ = setup
    plan = mesh_lib.make_mesh(hps.replace(dp=8))
    sharded = mesh_lib.shard_train_state(plan, state)
    step = mesh_lib.make_sharded_train_step(plan, donate=False)
    losses = []
    for _ in range(5):
        sharded, metrics = step(sharded, batch.as_arrays())
        losses.append(float(metrics.loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


class TestLowPrecisionGradAllReduce:
    """--grad_allreduce_dtype=bfloat16 (ISSUE 5): the dp gradient psum
    rides the wire in bf16 via the explicit shard_map step.  Parity is
    pinned on the 2-process CPU collective test shape (global batch 8
    over dp=4, tests/_multiproc_worker.py) against the single-device f32
    step: the bf16 cast is the ONLY semantic difference, so losses match
    exactly, the gradient norm to bf16 rounding, and N-step training
    stays in a tight envelope."""

    def _lowp_step(self, setup, dp):
        hps, vocab, batch, state, *_ = setup
        hps_m = hps.replace(dp=dp, grad_allreduce_dtype="bfloat16")
        plan = mesh_lib.make_mesh(hps_m)
        return (plan, mesh_lib.shard_train_state(plan, state),
                mesh_lib.make_sharded_train_step(plan, donate=False))

    @pytest.mark.parametrize("dp", [4, 8])
    def test_single_step_parity(self, setup, dp):
        hps, vocab, batch, state, ref_state, ref_metrics = setup
        plan, sharded, step = self._lowp_step(setup, dp)
        new_state, metrics = step(sharded, batch.as_arrays())
        # forward math untouched: per-shard losses pmean to the exact
        # global mean (pointer losses decompose; validated requirement)
        np.testing.assert_allclose(float(metrics.loss),
                                   float(ref_metrics.loss), rtol=1e-5)
        # the global norm sees the bf16-rounded gradients (~0.4% rel)
        np.testing.assert_allclose(float(metrics.global_norm),
                                   float(ref_metrics.global_norm),
                                   rtol=1e-2)
        # params move by the rounded update: pin each leaf's update
        # vector in L2 against the f32 reference update, with an atol
        # floor for leaves whose per-example grads mostly cancel
        for p0, r, g in zip(
                jax.tree_util.tree_leaves(state.params),
                jax.tree_util.tree_leaves(ref_state.params),
                jax.tree_util.tree_leaves(jax.device_get(new_state.params))):
            ur = np.asarray(r) - np.asarray(p0)
            ul = np.asarray(g) - np.asarray(p0)
            err = np.linalg.norm(ur - ul)
            assert err <= 0.05 * np.linalg.norm(ur) + 1e-4, \
                (err, np.linalg.norm(ur))

    def test_n_step_envelope(self, setup):
        """20 steps on dp=4: losses track the f32 single-device run and
        parameters stay within a small L2 envelope (measured 1.8e-3
        worst-leaf rel; bound 10x)."""
        hps, vocab, batch, state, *_ = setup
        plan, sharded, step = self._lowp_step(setup, 4)
        single = jax.jit(trainer_lib.make_train_step(hps))
        s_ref, s_lowp = state, sharded
        for _ in range(20):
            s_ref, m_ref = single(s_ref, batch.as_arrays())
            s_lowp, m_lowp = step(s_lowp, batch.as_arrays())
        np.testing.assert_allclose(float(m_lowp.loss), float(m_ref.loss),
                                   rtol=1e-3)
        for r, g in zip(jax.tree_util.tree_leaves(s_ref.params),
                        jax.tree_util.tree_leaves(
                            jax.device_get(s_lowp.params))):
            r, g = np.asarray(r), np.asarray(g)
            rel = np.linalg.norm(r - g) / (np.linalg.norm(r) + 1e-12)
            assert rel < 2e-2, rel

    def test_rejects_unsupported_meshes_and_losses(self, setup):
        hps, *_ = setup
        with pytest.raises(ValueError, match="pure-dp"):
            mesh_lib.make_sharded_train_step(mesh_lib.make_mesh(
                hps.replace(dp=4, tp=2, grad_allreduce_dtype="bfloat16")))
        with pytest.raises(ValueError, match="pointer_gen"):
            mesh_lib.make_sharded_train_step(mesh_lib.make_mesh(
                hps.replace(dp=4, pointer_gen=False,
                            grad_allreduce_dtype="bfloat16")))

    def test_bf16_accumulator_composes_with_lowp_allreduce(self, setup):
        """Both byte-diet state levers together on the mesh: bf16 psum +
        bf16 Adagrad accumulator — runs, learns, keeps dtypes."""
        hps, vocab, batch, state, *_ = setup
        hps_m = hps.replace(dp=4, grad_allreduce_dtype="bfloat16",
                            opt_state_dtype="bfloat16")
        state16 = trainer_lib.init_train_state(hps_m, vocab.size(), seed=7)
        plan = mesh_lib.make_mesh(hps_m)
        sharded = mesh_lib.shard_train_state(plan, state16)
        step = mesh_lib.make_sharded_train_step(plan, donate=False)
        losses = []
        for _ in range(5):
            sharded, metrics = step(sharded, batch.as_arrays())
            losses.append(float(metrics.loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]
        for leaf in jax.tree_util.tree_leaves(
                sharded.opt_state.accumulators):
            assert leaf.dtype == jnp.bfloat16


def test_sharded_beam_search_matches_single_device(setup):
    """dp-sharded decode returns the same hypotheses as single-device."""
    from textsummarization_on_flink_tpu.decode import beam_search
    from textsummarization_on_flink_tpu.parallel import mesh as mesh_lib

    hps, vocab, batch, state, _, _ = setup
    dec_hps = hps.replace(mode="decode", dp=4, tp=1, sp=1, beam_size=2,
                          min_dec_steps=1)
    enc_only = {k: v for k, v in batch.as_arrays().items()
                if k.startswith("enc_")}
    single = beam_search.run_beam_search(state.params,
                                         dec_hps.replace(dp=1), enc_only)
    plan = mesh_lib.make_mesh(dec_hps)
    fn = mesh_lib.make_sharded_beam_search(plan)
    sharded_params = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, plan.named(s)), state.params,
        mesh_lib.param_pspecs(state.params),
        is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec))
    out = fn(sharded_params, mesh_lib.shard_batch(plan, enc_only))
    np.testing.assert_array_equal(np.asarray(out.tokens), single.tokens)
    np.testing.assert_array_equal(np.asarray(out.length), single.length)
    np.testing.assert_allclose(np.asarray(out.avg_log_prob),
                               single.avg_log_prob, rtol=1e-5)
