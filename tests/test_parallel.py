"""Multi-chip sharding tests on the 8-device virtual CPU mesh.

Validates that the pjit-sharded train step (parallel/mesh.py) is
numerically identical to the single-device step — i.e. that dp gradient
psum, tp vocab-matmul collectives, and sp context-parallel reductions are
pure layout changes, not semantic ones.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.data.batching import Batch, SummaryExample
from textsummarization_on_flink_tpu.data.vocab import Vocab
from textsummarization_on_flink_tpu.parallel import mesh as mesh_lib
from textsummarization_on_flink_tpu.train import trainer as trainer_lib


def tiny_hps(**kw) -> HParams:
    base = dict(hidden_dim=8, emb_dim=6, batch_size=8, max_enc_steps=16,
                max_dec_steps=6, beam_size=2, min_dec_steps=2, vocab_size=64,
                max_oov_buckets=8, num_steps=2)
    base.update(kw)
    return HParams(**base)


def tiny_vocab(n: int = 64) -> Vocab:
    return Vocab(words=[f"w{i}" for i in range(n - 4)], max_size=n)


def make_batch(hps, vocab, seed=0):
    rng = np.random.RandomState(seed)
    exs = []
    for i in range(hps.batch_size):
        n_art = rng.randint(5, hps.max_enc_steps)
        n_abs = rng.randint(2, hps.max_dec_steps)
        art = " ".join(rng.choice([f"w{j}" for j in range(50)] + ["zzz_oov"],
                                  n_art))
        abs_ = " ".join(rng.choice([f"w{j}" for j in range(50)], n_abs))
        exs.append(SummaryExample.build(art, [abs_], vocab, hps))
    return Batch(exs, hps, vocab)


@pytest.fixture(scope="module")
def setup():
    hps = tiny_hps()
    vocab = tiny_vocab(hps.vocab_size)
    batch = make_batch(hps, vocab)
    state = trainer_lib.init_train_state(hps, vocab.size(), seed=7)
    single = jax.jit(trainer_lib.make_train_step(hps))
    ref_state, ref_metrics = single(state, batch.as_arrays())
    return hps, vocab, batch, state, ref_state, ref_metrics


@pytest.mark.parametrize("dp,tp,sp", [(8, 1, 1), (4, 2, 1), (2, 2, 2)])
def test_sharded_train_step_matches_single_device(setup, dp, tp, sp):
    hps, vocab, batch, state, ref_state, ref_metrics = setup
    hps_m = hps.replace(dp=dp, tp=tp, sp=sp)
    plan = mesh_lib.make_mesh(hps_m)
    sharded_state = mesh_lib.shard_train_state(plan, state)
    step = mesh_lib.make_sharded_train_step(plan, donate=False)
    new_state, metrics = step(sharded_state, batch.as_arrays())
    np.testing.assert_allclose(float(metrics.loss), float(ref_metrics.loss),
                               rtol=2e-5)
    np.testing.assert_allclose(float(metrics.global_norm),
                               float(ref_metrics.global_norm), rtol=2e-5)
    # parameters after the update agree leaf-by-leaf
    ref_leaves = jax.tree_util.tree_leaves(ref_state.params)
    got_leaves = jax.tree_util.tree_leaves(
        jax.device_get(new_state.params))
    for r, g in zip(ref_leaves, got_leaves):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r), rtol=1e-4,
                                   atol=1e-6)


def test_param_shardings_place_vocab_tensors_on_tp(setup):
    hps, vocab, batch, state, *_ = setup
    plan = mesh_lib.make_mesh(hps.replace(dp=4, tp=2))
    sharded = mesh_lib.shard_train_state(plan, state)
    emb_shard = sharded.params["embedding"].sharding
    w_shard = sharded.params["output_projection"]["w"].sharding
    assert emb_shard.spec == mesh_lib.P("tp", None)
    assert w_shard.spec == mesh_lib.P(None, "tp")
    # LSTM kernels replicated
    assert sharded.params["encoder"]["fw"]["kernel"].sharding.spec == mesh_lib.P()


def test_sharded_eval_step(setup):
    hps, vocab, batch, state, ref_state, ref_metrics = setup
    plan = mesh_lib.make_mesh(hps.replace(dp=8))
    sharded = mesh_lib.shard_train_state(plan, state)
    eval_step = mesh_lib.make_sharded_eval_step(plan)
    metrics = eval_step(sharded.params, batch.as_arrays())
    np.testing.assert_allclose(float(metrics.loss), float(ref_metrics.loss),
                               rtol=2e-5)


def test_mesh_device_count_validation():
    hps = tiny_hps(dp=16)
    with pytest.raises(ValueError):
        mesh_lib.make_mesh(hps)


def test_multi_step_training_loss_decreases(setup):
    hps, vocab, batch, state, *_ = setup
    plan = mesh_lib.make_mesh(hps.replace(dp=8))
    sharded = mesh_lib.shard_train_state(plan, state)
    step = mesh_lib.make_sharded_train_step(plan, donate=False)
    losses = []
    for _ in range(5):
        sharded, metrics = step(sharded, batch.as_arrays())
        losses.append(float(metrics.loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_sharded_beam_search_matches_single_device(setup):
    """dp-sharded decode returns the same hypotheses as single-device."""
    from textsummarization_on_flink_tpu.decode import beam_search
    from textsummarization_on_flink_tpu.parallel import mesh as mesh_lib

    hps, vocab, batch, state, _, _ = setup
    dec_hps = hps.replace(mode="decode", dp=4, tp=1, sp=1, beam_size=2,
                          min_dec_steps=1)
    enc_only = {k: v for k, v in batch.as_arrays().items()
                if k.startswith("enc_")}
    single = beam_search.run_beam_search(state.params,
                                         dec_hps.replace(dp=1), enc_only)
    plan = mesh_lib.make_mesh(dec_hps)
    fn = mesh_lib.make_sharded_beam_search(plan)
    sharded_params = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, plan.named(s)), state.params,
        mesh_lib.param_pspecs(state.params),
        is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec))
    out = fn(sharded_params, mesh_lib.shard_batch(plan, enc_only))
    np.testing.assert_array_equal(np.asarray(out.tokens), single.tokens)
    np.testing.assert_array_equal(np.asarray(out.length), single.length)
    np.testing.assert_allclose(np.asarray(out.avg_log_prob),
                               single.avg_log_prob, rtol=1e-5)
