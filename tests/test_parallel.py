"""Multi-chip sharding tests on the 8-device virtual CPU mesh.

Validates that the pjit-sharded train step (parallel/mesh.py) is
numerically identical to the single-device step — i.e. that dp gradient
psum, tp vocab-matmul collectives, and sp context-parallel reductions are
pure layout changes, not semantic ones.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.data.batching import Batch, SummaryExample
from textsummarization_on_flink_tpu.data.vocab import Vocab
from textsummarization_on_flink_tpu.parallel import mesh as mesh_lib
from textsummarization_on_flink_tpu.train import trainer as trainer_lib


def tiny_hps(**kw) -> HParams:
    base = dict(hidden_dim=8, emb_dim=6, batch_size=8, max_enc_steps=16,
                max_dec_steps=6, beam_size=2, min_dec_steps=2, vocab_size=64,
                max_oov_buckets=8, num_steps=2)
    base.update(kw)
    return HParams(**base)


def tiny_vocab(n: int = 64) -> Vocab:
    return Vocab(words=[f"w{i}" for i in range(n - 4)], max_size=n)


def make_batch(hps, vocab, seed=0):
    rng = np.random.RandomState(seed)
    exs = []
    for i in range(hps.batch_size):
        n_art = rng.randint(5, hps.max_enc_steps)
        n_abs = rng.randint(2, hps.max_dec_steps)
        art = " ".join(rng.choice([f"w{j}" for j in range(50)] + ["zzz_oov"],
                                  n_art))
        abs_ = " ".join(rng.choice([f"w{j}" for j in range(50)], n_abs))
        exs.append(SummaryExample.build(art, [abs_], vocab, hps))
    return Batch(exs, hps, vocab)


@pytest.fixture(scope="module")
def setup():
    hps = tiny_hps()
    vocab = tiny_vocab(hps.vocab_size)
    batch = make_batch(hps, vocab)
    state = trainer_lib.init_train_state(hps, vocab.size(), seed=7)
    single = jax.jit(trainer_lib.make_train_step(hps))
    ref_state, ref_metrics = single(state, batch.as_arrays())
    return hps, vocab, batch, state, ref_state, ref_metrics


@pytest.mark.parametrize("dp,tp,sp", [(8, 1, 1), (4, 2, 1), (2, 2, 2)])
def test_sharded_train_step_matches_single_device(setup, dp, tp, sp):
    hps, vocab, batch, state, ref_state, ref_metrics = setup
    hps_m = hps.replace(dp=dp, tp=tp, sp=sp)
    plan = mesh_lib.make_mesh(hps_m)
    sharded_state = mesh_lib.shard_train_state(plan, state)
    step = mesh_lib.make_sharded_train_step(plan, donate=False)
    new_state, metrics = step(sharded_state, batch.as_arrays())
    np.testing.assert_allclose(float(metrics.loss), float(ref_metrics.loss),
                               rtol=2e-5)
    np.testing.assert_allclose(float(metrics.global_norm),
                               float(ref_metrics.global_norm), rtol=2e-5)
    # parameters after the update agree leaf-by-leaf
    ref_leaves = jax.tree_util.tree_leaves(ref_state.params)
    got_leaves = jax.tree_util.tree_leaves(
        jax.device_get(new_state.params))
    for r, g in zip(ref_leaves, got_leaves):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r), rtol=1e-4,
                                   atol=1e-6)


def test_param_shardings_place_vocab_tensors_on_tp(setup):
    hps, vocab, batch, state, *_ = setup
    plan = mesh_lib.make_mesh(hps.replace(dp=4, tp=2))
    sharded = mesh_lib.shard_train_state(plan, state)
    emb_shard = sharded.params["embedding"].sharding
    w_shard = sharded.params["output_projection"]["w"].sharding
    assert emb_shard.spec == mesh_lib.P("tp", None)
    assert w_shard.spec == mesh_lib.P(None, "tp")
    # LSTM kernels replicated
    assert sharded.params["encoder"]["fw"]["kernel"].sharding.spec == mesh_lib.P()


def test_sharded_eval_step(setup):
    hps, vocab, batch, state, ref_state, ref_metrics = setup
    plan = mesh_lib.make_mesh(hps.replace(dp=8))
    sharded = mesh_lib.shard_train_state(plan, state)
    eval_step = mesh_lib.make_sharded_eval_step(plan)
    metrics = eval_step(sharded.params, batch.as_arrays())
    np.testing.assert_allclose(float(metrics.loss), float(ref_metrics.loss),
                               rtol=2e-5)


def test_mesh_device_count_validation():
    hps = tiny_hps(dp=16)
    with pytest.raises(ValueError):
        mesh_lib.make_mesh(hps)


def test_multi_step_training_loss_decreases(setup):
    hps, vocab, batch, state, *_ = setup
    plan = mesh_lib.make_mesh(hps.replace(dp=8))
    sharded = mesh_lib.shard_train_state(plan, state)
    step = mesh_lib.make_sharded_train_step(plan, donate=False)
    losses = []
    for _ in range(5):
        sharded, metrics = step(sharded, batch.as_arrays())
        losses.append(float(metrics.loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


class TestLowPrecisionGradAllReduce:
    """--grad_allreduce_dtype=bfloat16 (ISSUE 5/8): the dp gradient
    all-reduce rides the wire in bf16, now as a registry-level wire
    annotation folded into the unified step (ISSUE 8 — the shard_map
    builder is retired), so it also runs on dp x tp meshes.  Parity is
    pinned on the faked-8-device collective test shape (global batch 8
    over dp, the same shape tests/_multiproc_worker.py runs across two
    real processes) against the single-device f32 step: the bf16 wire
    cast is the ONLY semantic difference, so losses match exactly, the
    gradient norm to bf16 rounding, and N-step training stays in a
    tight envelope."""

    def _lowp_step(self, setup, dp, tp=1):
        hps, vocab, batch, state, *_ = setup
        hps_m = hps.replace(dp=dp, tp=tp, grad_allreduce_dtype="bfloat16")
        plan = mesh_lib.make_mesh(hps_m)
        return (plan, mesh_lib.shard_train_state(plan, state),
                mesh_lib.make_sharded_train_step(plan, donate=False))

    @pytest.mark.parametrize("dp,tp", [(4, 1), (8, 1), (4, 2), (2, 2)])
    def test_single_step_parity(self, setup, dp, tp):
        hps, vocab, batch, state, ref_state, ref_metrics = setup
        plan, sharded, step = self._lowp_step(setup, dp, tp)
        new_state, metrics = step(sharded, batch.as_arrays())
        # forward math untouched: per-shard losses pmean to the exact
        # global mean (pointer losses decompose; validated requirement)
        np.testing.assert_allclose(float(metrics.loss),
                                   float(ref_metrics.loss), rtol=1e-5)
        # the global norm sees the bf16-rounded gradients (~0.4% rel)
        np.testing.assert_allclose(float(metrics.global_norm),
                                   float(ref_metrics.global_norm),
                                   rtol=1e-2)
        # params move by the rounded update: pin each leaf's update
        # vector in L2 against the f32 reference update, with an atol
        # floor for leaves whose per-example grads mostly cancel
        for p0, r, g in zip(
                jax.tree_util.tree_leaves(state.params),
                jax.tree_util.tree_leaves(ref_state.params),
                jax.tree_util.tree_leaves(jax.device_get(new_state.params))):
            ur = np.asarray(r) - np.asarray(p0)
            ul = np.asarray(g) - np.asarray(p0)
            err = np.linalg.norm(ur - ul)
            assert err <= 0.05 * np.linalg.norm(ur) + 1e-4, \
                (err, np.linalg.norm(ur))

    @pytest.mark.parametrize("dp,tp", [(4, 1), (2, 2)])
    def test_n_step_envelope(self, setup, dp, tp):
        """20 steps on dp=4 AND the dp x tp (2x2 faked-device) shape
        (ISSUE 8 satellite): losses track the f32 single-device run and
        parameters stay within a small L2 envelope (measured 1.8e-3
        worst-leaf rel pure-dp, same order at 2x2; bound 10x)."""
        hps, vocab, batch, state, *_ = setup
        plan, sharded, step = self._lowp_step(setup, dp, tp)
        single = jax.jit(trainer_lib.make_train_step(hps))
        s_ref, s_lowp = state, sharded
        for _ in range(20):
            s_ref, m_ref = single(s_ref, batch.as_arrays())
            s_lowp, m_lowp = step(s_lowp, batch.as_arrays())
        np.testing.assert_allclose(float(m_lowp.loss), float(m_ref.loss),
                                   rtol=1e-3)
        for r, g in zip(jax.tree_util.tree_leaves(s_ref.params),
                        jax.tree_util.tree_leaves(
                            jax.device_get(s_lowp.params))):
            r, g = np.asarray(r), np.asarray(g)
            rel = np.linalg.norm(r - g) / (np.linalg.norm(r) + 1e-12)
            assert rel < 2e-2, rel

    def test_rejects_unsupported_meshes_and_losses(self, setup):
        """sp and non-pointer losses still reject; dp x tp no longer
        does (the ISSUE 8 unification — covered by the parity tests
        above)."""
        hps, *_ = setup
        with pytest.raises(ValueError, match="sp"):
            mesh_lib.make_sharded_train_step(mesh_lib.make_mesh(
                hps.replace(dp=2, sp=2, grad_allreduce_dtype="bfloat16")))
        with pytest.raises(ValueError, match="pointer_gen"):
            mesh_lib.make_sharded_train_step(mesh_lib.make_mesh(
                hps.replace(dp=4, pointer_gen=False,
                            grad_allreduce_dtype="bfloat16")))
        with pytest.raises(ValueError, match="sp"):
            hps.replace(dp=2, sp=2,
                        grad_allreduce_dtype="bfloat16").validate()
        # dp x tp validates clean end to end now
        hps.replace(dp=2, tp=2, grad_allreduce_dtype="bfloat16").validate()

    def test_lowp_builder_is_a_deprecation_shim(self, setup):
        """make_lowp_allreduce_train_step (the retired shard_map step)
        aliases the unified builder: same results, DeprecationWarning,
        no separate step body (ISSUE 8 satellite)."""
        hps, vocab, batch, state, *_ = setup
        plan, sharded, unified = self._lowp_step(setup, 4)
        with pytest.warns(DeprecationWarning, match="unified"):
            shim = mesh_lib.make_lowp_allreduce_train_step(
                plan, donate=False)
        s_a, m_a = unified(sharded, batch.as_arrays())
        s_b, m_b = shim(mesh_lib.shard_train_state(plan, state),
                        batch.as_arrays())
        np.testing.assert_array_equal(np.asarray(m_a.loss),
                                      np.asarray(m_b.loss))
        for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(s_a.params)),
                        jax.tree_util.tree_leaves(jax.device_get(s_b.params))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # the shim also forces the wire dtype on for legacy callers whose
        # hps predate the annotation
        plan_f32 = mesh_lib.make_mesh(hps.replace(dp=4))
        with pytest.warns(DeprecationWarning):
            shim2 = mesh_lib.make_lowp_allreduce_train_step(
                plan_f32, donate=False)
        _, m_c = shim2(mesh_lib.shard_train_state(plan_f32, state),
                       batch.as_arrays())
        np.testing.assert_array_equal(np.asarray(m_a.loss),
                                      np.asarray(m_c.loss))

    def test_bf16_accumulator_composes_with_lowp_allreduce(self, setup):
        """Both byte-diet state levers together on the mesh: bf16 psum +
        bf16 Adagrad accumulator — runs, learns, keeps dtypes."""
        hps, vocab, batch, state, *_ = setup
        hps_m = hps.replace(dp=4, grad_allreduce_dtype="bfloat16",
                            opt_state_dtype="bfloat16")
        state16 = trainer_lib.init_train_state(hps_m, vocab.size(), seed=7)
        plan = mesh_lib.make_mesh(hps_m)
        sharded = mesh_lib.shard_train_state(plan, state16)
        step = mesh_lib.make_sharded_train_step(plan, donate=False)
        losses = []
        for _ in range(5):
            sharded, metrics = step(sharded, batch.as_arrays())
            losses.append(float(metrics.loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]
        for leaf in jax.tree_util.tree_leaves(
                sharded.opt_state.accumulators):
            assert leaf.dtype == jnp.bfloat16


def test_sharded_beam_search_matches_single_device(setup):
    """dp-sharded decode returns the same hypotheses as single-device."""
    from textsummarization_on_flink_tpu.decode import beam_search
    from textsummarization_on_flink_tpu.parallel import mesh as mesh_lib

    hps, vocab, batch, state, _, _ = setup
    dec_hps = hps.replace(mode="decode", dp=4, tp=1, sp=1, beam_size=2,
                          min_dec_steps=1)
    enc_only = {k: v for k, v in batch.as_arrays().items()
                if k.startswith("enc_")}
    single = beam_search.run_beam_search(state.params,
                                         dec_hps.replace(dp=1), enc_only)
    plan = mesh_lib.make_mesh(dec_hps)
    fn = mesh_lib.make_sharded_beam_search(plan)
    sharded_params = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, plan.named(s)), state.params,
        mesh_lib.param_pspecs(state.params),
        is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec))
    out = fn(sharded_params, mesh_lib.shard_batch(plan, enc_only))
    np.testing.assert_array_equal(np.asarray(out.tokens), single.tokens)
    np.testing.assert_array_equal(np.asarray(out.length), single.length)
    np.testing.assert_allclose(np.asarray(out.avg_log_prob),
                               single.avg_log_prob, rtol=1e-5)


# --------------------------------------------------------------------------
# ISSUE 8: the sharding-spec registry is the one source of PartitionSpecs
# --------------------------------------------------------------------------

class TestShardingRegistry:
    def test_table_covers_every_role(self, setup):
        from textsummarization_on_flink_tpu.parallel import (
            sharding as sharding_lib,
        )

        hps, *_ = setup
        plan = mesh_lib.make_mesh(hps.replace(dp=4, tp=2))
        reg = plan.registry
        assert {r["role"] for r in reg.table()} == set(sharding_lib.ROLES)

    def test_registry_is_cached_per_mesh(self, setup):
        hps, *_ = setup
        plan = mesh_lib.make_mesh(hps.replace(dp=4, tp=2))
        assert plan.registry is mesh_lib.make_mesh(
            hps.replace(dp=4, tp=2)).registry

    def test_mesh_delegates_match_registry(self, setup):
        """The public mesh_lib helpers answer exactly what the registry
        answers (they are delegates, not parallel rule sets)."""
        from textsummarization_on_flink_tpu.parallel import (
            sharding as sharding_lib,
        )

        hps, vocab, batch, state, *_ = setup
        plan = mesh_lib.make_mesh(hps.replace(dp=4, tp=2))
        reg = plan.registry
        assert mesh_lib.param_pspecs(state.params) == \
            reg.param_specs(state.params)
        for name in sharding_lib.BATCH_NAMES:
            assert mesh_lib.batch_pspec(name) == reg.batch_spec(name)
        assert mesh_lib.state_pspecs(state) == reg.state_specs(state)

    def test_step_builders_construct_no_specs(self):
        """No step builder builds its own PartitionSpecs: every layout
        in the builders' source is a registry lookup (the ISSUE 8
        acceptance criterion, pinned against regression)."""
        import ast
        import inspect
        import textwrap

        for builder in (mesh_lib.make_sharded_train_step,
                        mesh_lib._make_wire_grad_fn,
                        mesh_lib.make_sharded_eval_step,
                        mesh_lib.make_sharded_beam_search,
                        mesh_lib.make_lowp_allreduce_train_step):
            tree = ast.parse(textwrap.dedent(inspect.getsource(builder)))
            calls = [n for n in ast.walk(tree) if isinstance(n, ast.Call)]
            names = {n.func.id for n in calls
                     if isinstance(n.func, ast.Name)}
            attrs = {n.func.attr for n in calls
                     if isinstance(n.func, ast.Attribute)}
            assert "P" not in names and "PartitionSpec" not in (
                names | attrs), \
                f"{builder.__name__} constructs PartitionSpecs directly " \
                f"— route the layout through the sharding registry"

    def test_analytic_comms_ref_scale_pins_43mb(self):
        """The retired lowp path's committed number: at reference scale
        the dp gradient wire carries exactly 43.0 MB/step under the
        bf16 annotation (86.0 at f32) — computed from registry specs
        alone, no compile (the BYTE_BUDGET comms gate re-asserts this
        against the committed JSON)."""
        from textsummarization_on_flink_tpu.parallel import (
            sharding as sharding_lib,
        )

        ref = HParams(batch_size=16, compute_dtype="bfloat16",
                      grad_allreduce_dtype="bfloat16")
        comms = sharding_lib.analytic_comms(ref)
        assert round(comms["dp_wire_bytes"] / 1e6, 1) == 43.0
        assert comms["dp_grad_elements"] == comms["param_elements"]
        f32 = sharding_lib.analytic_comms(
            ref.replace(grad_allreduce_dtype="float32"))
        assert round(f32["dp_wire_bytes"] / 1e6, 1) == 86.0

    def test_analytic_comms_tp_sharding(self, setup):
        """tp-sharded leaves ride the dp wire as shards: dp_grad_elements
        drops by exactly the tp-sharded leaves' saved elements."""
        from textsummarization_on_flink_tpu.parallel import (
            sharding as sharding_lib,
        )

        hps, vocab, batch, state, *_ = setup
        c1 = sharding_lib.analytic_comms(hps, params=state.params)
        c2 = sharding_lib.analytic_comms(hps.replace(tp=2),
                                         params=state.params)
        assert c2["dp_grad_elements"] < c1["dp_grad_elements"]
        assert c1["dp_grad_elements"] == c1["param_elements"]


class TestUnifiedDpTpEndToEnd:
    """The ISSUE 8 acceptance run: dp x tp (faked 8-device) green end to
    end with --loss_chunk and --opt_state_dtype=bfloat16, train and
    serve both."""

    def test_train_dp_tp_with_loss_chunk_and_bf16_state(self, setup):
        hps, vocab, batch, state, *_ = setup
        hps_m = hps.replace(dp=2, tp=2, loss_chunk=3,
                            opt_state_dtype="bfloat16",
                            grad_allreduce_dtype="bfloat16")
        hps_m.validate()
        state16 = trainer_lib.init_train_state(hps_m, vocab.size(), seed=7)
        plan = mesh_lib.make_mesh(hps_m)
        sharded = mesh_lib.shard_train_state(plan, state16)
        step = mesh_lib.make_sharded_train_step(plan, donate=False)
        losses = []
        for _ in range(5):
            sharded, metrics = step(sharded, batch.as_arrays())
            losses.append(float(metrics.loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]
        for leaf in jax.tree_util.tree_leaves(
                sharded.opt_state.accumulators):
            assert leaf.dtype == jnp.bfloat16
        # params kept their registry layout through the update
        assert sharded.params["embedding"].sharding.spec == \
            mesh_lib.P("tp", None)

    def test_serve_slot_engine_runs_sharded(self, setup, tmp_path):
        """Continuous-serving acceptance: the SlotDecodeEngine's resident
        state shards over dp on the faked mesh (registry slot specs) and
        resident trajectories stay token-exact with the unsharded
        engine."""
        from textsummarization_on_flink_tpu.decode.decoder import (
            BeamSearchDecoder,
        )

        hps, vocab, batch, state, *_ = setup
        rng = np.random.RandomState(3)
        exs = []
        for i in range(2):
            art = " ".join(rng.choice([f"w{j}" for j in range(50)],
                                      5 + 3 * i))
            exs.append(SummaryExample.build(art, ["w1 w2"], vocab, hps,
                                            uuid=f"u{i}"))

        def run_engine(dec_hps, root):
            dec = BeamSearchDecoder(dec_hps, vocab, batcher=None,
                                    params=state.params, decode_root=root)
            eng = dec.slot_engine(slots=4, chunk=3)
            for i, ex in enumerate(exs):
                eng.pack(i, ex)
            results = {}
            for _ in range(hps.max_dec_steps + 2):
                for idx in eng.step():
                    results[idx] = eng.unpack(idx, exs[idx])
                if len(results) == len(exs):
                    break
            assert len(results) == len(exs)
            return eng, [results[i] for i in range(len(exs))]

        base_hps = hps.replace(mode="decode", min_dec_steps=1)
        _, want = run_engine(base_hps, str(tmp_path / "single"))
        eng, got = run_engine(base_hps.replace(dp=2, tp=2),
                              str(tmp_path / "mesh"))
        for w, g in zip(want, got):
            assert g.decoded_words == w.decoded_words
        # the resident state really is distributed: a beam leaf spans
        # the mesh with the registry's slots-over-dp spec
        leaf = jax.tree_util.tree_leaves(eng._state)[0]
        assert len(leaf.sharding.device_set) == 4
        assert leaf.sharding.spec[0] == "dp"
