"""Unit tests for the resilience primitives (RESILIENCE.md).

Covers resilience/policy.py (RetryPolicy, Deadline, CircuitBreaker),
resilience/faultinject.py (spec parsing, deterministic seeded firing,
budgets, gating), the typed error vocabulary, and the HParams-level
validation of the new resilience fields.  End-to-end recovery paths are
exercised by the chaos suite (tests/test_chaos.py).
"""

import pytest

from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.obs import Registry
from textsummarization_on_flink_tpu.resilience import (
    CircuitBreaker,
    CircuitOpenError,
    Deadline,
    DeadlineExceededError,
    FaultPlan,
    FaultSpec,
    NULL_PLAN,
    ResilienceError,
    RetriesExhaustedError,
    RetryPolicy,
    StreamIdleError,
    WorkerCrashError,
    faultinject,
)


# -- typed errors ----------------------------------------------------------

def test_error_taxonomy():
    # timeouts stay catchable as TimeoutError, worker crashes as
    # RuntimeError — pre-existing handlers must keep working
    assert issubclass(StreamIdleError, TimeoutError)
    assert issubclass(DeadlineExceededError, TimeoutError)
    assert issubclass(WorkerCrashError, RuntimeError)
    for err in (StreamIdleError, DeadlineExceededError, CircuitOpenError,
                RetriesExhaustedError, WorkerCrashError):
        assert issubclass(err, ResilienceError)


# -- Deadline --------------------------------------------------------------

class TestDeadline:
    def test_never_is_unbounded(self):
        d = Deadline.never()
        assert not d.bounded
        assert d.remaining() == float("inf")
        assert not d.expired()
        d.check()  # never raises

    def test_after_zero_or_none_means_never(self):
        assert not Deadline.after(0).bounded
        assert not Deadline.after(None).bounded
        assert not Deadline.after(-1).bounded

    def test_bounded_expiry(self):
        d = Deadline.after(1000.0)
        assert d.bounded
        assert 0 < d.remaining() <= 1000.0
        d.check()
        expired = Deadline.after(1e-9)
        # the budget is sub-nanosecond: it has expired by the time we ask
        assert expired.expired()
        with pytest.raises(DeadlineExceededError, match="during decode"):
            expired.check("decode")
        assert expired.remaining() == 0.0

    def test_timeout_for_clamps_to_budget(self):
        assert Deadline.never().timeout_for(5.0) == 5.0
        assert Deadline.never().timeout_for(None) is None
        d = Deadline.after(1000.0)
        assert d.timeout_for(5.0) == 5.0  # budget >> default
        assert 0 < d.timeout_for(None) <= 1000.0  # just the budget


# -- RetryPolicy -----------------------------------------------------------

class TestRetryPolicy:
    def test_succeeds_after_transient_failures(self):
        sleeps = []
        policy = RetryPolicy(max_attempts=5, base_delay=0.01, seed=0,
                             sleep=sleeps.append, registry=Registry())
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise OSError("transient")
            return "ok"

        assert policy.call(flaky, retry_on=(OSError,)) == "ok"
        assert calls["n"] == 3
        assert len(sleeps) == 2  # slept before each retry, not the first try

    def test_exhaustion_raises_typed_with_cause(self):
        policy = RetryPolicy(max_attempts=3, base_delay=0.01, seed=0,
                             sleep=lambda d: None, registry=Registry())

        def always_fails():
            raise OSError("dead peer")

        with pytest.raises(RetriesExhaustedError, match="3 attempts") as ei:
            policy.call(always_fails, retry_on=(OSError,))
        assert isinstance(ei.value.__cause__, OSError)

    def test_seeded_backoff_is_deterministic_and_bounded(self):
        def delays(seed):
            p = RetryPolicy(max_attempts=8, base_delay=0.05, max_delay=1.0,
                            seed=seed, registry=Registry())
            return [p.next_delay() for _ in range(7)]

        a, b = delays(42), delays(42)
        assert a == b  # same seed -> same decorrelated-jitter sequence
        assert delays(7) != a  # different seed -> different sequence
        assert all(0.05 <= d <= 1.0 for d in a)  # within [base, cap]

    def test_unexpected_error_not_retried(self):
        policy = RetryPolicy(max_attempts=5, base_delay=0.01,
                             sleep=lambda d: None, registry=Registry())

        def bug():
            raise KeyError("not a transient error")

        with pytest.raises(KeyError):
            policy.call(bug, retry_on=(OSError,))

    def test_deadline_bounds_retrying(self):
        # deadline already expired: the first retry sleep surfaces the
        # typed timeout instead of grinding through all attempts
        policy = RetryPolicy(max_attempts=50, base_delay=0.01, seed=0,
                             sleep=lambda d: None,
                             deadline=Deadline.after(1e-9),
                             registry=Registry())

        def always_fails():
            raise OSError("down")

        with pytest.raises(DeadlineExceededError) as ei:
            policy.call(always_fails, retry_on=(OSError,))
        assert isinstance(ei.value.__cause__, OSError)

    def test_expired_deadline_sleeps_nothing_before_raising(self):
        # the backoff sleep is clamped to the remaining budget: with the
        # deadline already spent it must be ~0, not the full delay
        slept = []
        policy = RetryPolicy(max_attempts=5, base_delay=5.0, max_delay=30.0,
                             seed=0, sleep=slept.append,
                             deadline=Deadline.after(1e-9),
                             registry=Registry())
        with pytest.raises(DeadlineExceededError):
            policy.call(lambda: (_ for _ in ()).throw(OSError("down")),
                        retry_on=(OSError,))
        assert len(slept) == 1 and slept[0] < 0.01, slept

    def test_obs_counters(self):
        reg = Registry()
        policy = RetryPolicy(max_attempts=3, base_delay=0.01, seed=0,
                             name="io.test", sleep=lambda d: None,
                             registry=reg)
        with pytest.raises(RetriesExhaustedError):
            policy.call(lambda: (_ for _ in ()).throw(OSError()),
                        retry_on=(OSError,))
        assert reg.counter("resilience/io.test/retries_total").value == 2
        assert reg.counter(
            "resilience/io.test/retry_exhausted_total").value == 1
        assert reg.counter("resilience/retries_total").value == 2

    def test_validation(self):
        with pytest.raises(ValueError, match="max_attempts"):
            RetryPolicy(max_attempts=0, registry=Registry())
        with pytest.raises(ValueError, match="base_delay"):
            RetryPolicy(base_delay=0.0, registry=Registry())
        with pytest.raises(ValueError, match="base_delay"):
            RetryPolicy(base_delay=1.0, max_delay=0.5, registry=Registry())


# -- CircuitBreaker --------------------------------------------------------

class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class TestCircuitBreaker:
    def make(self, threshold=3, reset_secs=30.0):
        clock = FakeClock()
        reg = Registry()
        br = CircuitBreaker(threshold=threshold, reset_secs=reset_secs,
                            name="t", clock=clock, registry=reg)
        return br, clock, reg

    def test_trips_after_consecutive_failures(self):
        br, _, reg = self.make(threshold=3)
        for _ in range(2):
            br.record_failure()
        assert br.state == CircuitBreaker.CLOSED
        br.record_success()  # resets the consecutive count
        for _ in range(2):
            br.record_failure()
        assert br.state == CircuitBreaker.CLOSED
        br.record_failure()
        assert br.state == CircuitBreaker.OPEN
        assert not br.allow()
        assert reg.counter("resilience/t/breaker_trips_total").value == 1
        assert reg.counter("resilience/t/breaker_shed_total").value == 1
        assert reg.gauge("resilience/t/breaker_state").value == 2

    def test_half_open_probe_recloses_on_success(self):
        br, clock, reg = self.make(threshold=1, reset_secs=30.0)
        br.record_failure()
        assert not br.allow()
        clock.t = 31.0
        assert br.state == CircuitBreaker.HALF_OPEN
        assert br.allow()       # the single probe
        assert not br.allow()   # concurrent callers still shed
        br.record_success()
        assert br.state == CircuitBreaker.CLOSED
        assert br.allow()
        assert reg.gauge("resilience/t/breaker_state").value == 0

    def test_half_open_probe_failure_reopens(self):
        br, clock, _ = self.make(threshold=1, reset_secs=30.0)
        br.record_failure()
        clock.t = 31.0
        assert br.allow()  # probe
        br.record_failure()
        assert br.state == CircuitBreaker.OPEN
        clock.t = 60.0  # 29s after the re-open: clock restarted
        assert br.state == CircuitBreaker.OPEN
        clock.t = 61.5
        assert br.state == CircuitBreaker.HALF_OPEN

    def test_half_open_concurrent_callers_get_exactly_one_probe(self):
        """The ISSUE-13 satellite regression: N threads racing into a
        HALF_OPEN breaker must yield EXACTLY ONE probe grant — every
        loser sees the breaker as open (shed), they do not all probe at
        once."""
        import threading

        br, clock, reg = self.make(threshold=1, reset_secs=30.0)
        br.record_failure()
        clock.t = 31.0  # into the half-open window
        grants = []
        n = 12
        barrier = threading.Barrier(n)

        def caller():
            barrier.wait()
            if br.allow():
                grants.append(threading.get_ident())

        threads = [threading.Thread(target=caller) for _ in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(grants) == 1, \
            f"{len(grants)} concurrent half-open probes granted"
        assert reg.counter("resilience/t/breaker_shed_total").value == n - 1
        br.record_success()
        assert br.state == CircuitBreaker.CLOSED

    def test_lost_probe_lease_expires_and_regrants(self):
        """A probe whose caller vanished without recording an outcome
        must not wedge the breaker half-open forever: after another
        reset_secs the single probe slot re-grants."""
        br, clock, _ = self.make(threshold=1, reset_secs=30.0)
        br.record_failure()
        clock.t = 31.0
        assert br.allow()        # the probe caller then VANISHES
        assert not br.allow()    # the slot is taken
        clock.t = 60.0           # 29s later: lease still live
        assert not br.allow()
        clock.t = 61.5           # lease (reset_secs) expired
        assert br.allow()        # re-granted instead of wedged
        assert not br.allow()    # still exactly one in flight
        br.record_success()
        assert br.state == CircuitBreaker.CLOSED

    def test_context_manager(self):
        br, clock, _ = self.make(threshold=1)
        with pytest.raises(OSError):
            with br:
                raise OSError("down")
        assert br.state == CircuitBreaker.OPEN
        with pytest.raises(CircuitOpenError):
            with br:
                pass
        clock.t = 31.0
        with br:
            pass  # probe succeeds
        assert br.state == CircuitBreaker.CLOSED

    def test_validation(self):
        with pytest.raises(ValueError, match="threshold"):
            CircuitBreaker(threshold=0, registry=Registry())


# -- fault injection -------------------------------------------------------

class TestFaultSpecs:
    def test_parse_full_string(self):
        specs = faultinject.parse("io.read:0.2:42,train.step_nan:1.0:7:3")
        assert specs == [FaultSpec("io.read", 0.2, 42, 0),
                         FaultSpec("train.step_nan", 1.0, 7, 3)]
        assert faultinject.parse("") == []
        assert faultinject.parse(None) == []

    @pytest.mark.parametrize("bad", [
        "io.read",                  # missing fields
        "io.read:0.5",              # missing seed
        "no.such.point:0.5:1",      # unknown point (typo safety)
        "io.read:1.5:1",            # prob out of range
        "io.read:-0.1:1",           # prob out of range
        "io.read:0.5:1:-2",         # negative max
        "io.read:0.5:1:2:9",        # too many fields
    ])
    def test_parse_rejects(self, bad):
        with pytest.raises(ValueError):
            faultinject.parse(bad)

    def test_known_points_cover_the_documented_set(self):
        assert set(faultinject.KNOWN_POINTS) == {
            "io.connect", "io.read", "io.write",
            "ckpt.load", "train.step_nan", "etl.worker",
            "serve.dispatch", "serve.replica_kill", "serve.cache_fault",
            "serve.proc_kill", "serve.arena_full"}


class TestFaultPlan:
    def test_seeded_firing_is_deterministic(self):
        def fire_pattern(seed):
            plan = FaultPlan([FaultSpec("io.read", 0.5, seed, 0)],
                             registry=Registry())
            return [plan.fire("io.read") for _ in range(32)]

        a, b = fire_pattern(42), fire_pattern(42)
        assert a == b                 # same seed -> same call indices fire
        assert any(a) and not all(a)  # p=0.5 over 32 calls: both outcomes
        assert fire_pattern(7) != a   # a different seed fires differently

    def test_max_fires_budget(self):
        plan = FaultPlan([FaultSpec("io.read", 1.0, 0, 3)],
                         registry=Registry())
        fired = [plan.fire("io.read") for _ in range(10)]
        assert fired == [True] * 3 + [False] * 7  # heals after 3 fires
        assert plan.stats() == {"io.read": {"calls": 10, "fires": 3}}

    def test_unarmed_point_never_fires(self):
        reg = Registry()
        plan = FaultPlan([FaultSpec("io.read", 1.0, 0, 0)], registry=reg)
        assert not plan.fire("ckpt.load")
        assert not plan.armed("ckpt.load")
        assert plan.armed("io.read")
        assert plan.fire("io.read")
        assert reg.counter("resilience/fault/io.read").value == 1
        assert reg.counter("resilience/faults_fired_total").value == 1

    def test_null_plan_is_inert(self):
        assert not NULL_PLAN.enabled
        assert not NULL_PLAN.fire("io.read")
        assert not NULL_PLAN.armed("io.read")
        assert NULL_PLAN.stats() == {}

    def test_env_resolution_and_use_plan(self, monkeypatch):
        # unset env -> the null singleton (the disabled-mode fast path)
        monkeypatch.delenv(faultinject.ENV_VAR, raising=False)
        faultinject.set_default_plan(None)
        assert faultinject.plan() is NULL_PLAN
        # armed env -> a real plan
        monkeypatch.setenv(faultinject.ENV_VAR, "io.read:1.0:0:1")
        faultinject.set_default_plan(None)
        p = faultinject.plan()
        assert isinstance(p, FaultPlan) and p.armed("io.read")
        # use_plan scopes an override and restores on exit
        override = FaultPlan([FaultSpec("ckpt.load", 1.0, 0, 0)],
                             registry=Registry())
        with faultinject.use_plan(override):
            assert faultinject.plan() is override
        assert faultinject.plan() is p
        faultinject.set_default_plan(None)  # leave no env plan cached

    def test_plan_for_prefers_hparams(self, monkeypatch):
        monkeypatch.delenv(faultinject.ENV_VAR, raising=False)
        faultinject.set_default_plan(None)
        hps = HParams(faults="etl.worker:1.0:0:1")
        p = faultinject.plan_for(hps)
        assert isinstance(p, FaultPlan) and p.armed("etl.worker")
        # no per-job spec -> the process default
        assert faultinject.plan_for(HParams()) is faultinject.plan()
        assert faultinject.plan_for(None) is faultinject.plan()


# -- HParams validation of the resilience fields ---------------------------

class TestConfigValidation:
    def test_faults_spec_validated(self):
        HParams(faults="io.read:0.5:1").validate()  # valid
        with pytest.raises(ValueError, match="unknown fault point"):
            HParams(faults="no.such:0.5:1").validate()

    def test_nan_fields(self):
        HParams(nan_skip_steps=2, nan_max_rollbacks=1,
                nan_lr_cut=0.5).validate()
        with pytest.raises(ValueError):
            HParams(nan_skip_steps=-1).validate()
        with pytest.raises(ValueError, match="nan_lr_cut"):
            HParams(nan_lr_cut=0.0).validate()
        with pytest.raises(ValueError, match="nan_lr_cut"):
            HParams(nan_lr_cut=1.5).validate()

    def test_decode_deadline(self):
        HParams(decode_deadline_secs=2.5).validate()
        with pytest.raises(ValueError, match="decode_deadline_secs"):
            HParams(decode_deadline_secs=-1.0).validate()
