"""Sources/sinks, message codec, example-coding matrix, bridge queues."""

import json
import socket
import socketserver
import threading
import time

import pytest

from textsummarization_on_flink_tpu.pipeline import bridge as bridge_lib
from textsummarization_on_flink_tpu.pipeline import codec as codec_lib
from textsummarization_on_flink_tpu.pipeline import io as io_lib


# -- Message codec (Message.java parity) --

def test_message_round_trip():
    m = io_lib.Message("u1", "some article", "a summary", "ref text")
    m2 = io_lib.Message.from_json(m.to_json())
    assert m2.to_row() == ("u1", "some article", "a summary", "ref text")


def test_message_missing_fields_default_empty():
    m = io_lib.Message.from_json(json.dumps({"uuid": "x"}))
    assert m.to_row() == ("x", "", "", "")


def test_message_tier_error_ride_the_wire_only_when_set():
    """ISSUE 17: the process-fleet transport extends the codec with
    tier + error, but the classic 4-field wire must stay byte-stable —
    a frame without them encodes exactly the pre-extension shape."""
    classic = io_lib.Message("u1", "art", "sum", "ref")
    assert set(json.loads(classic.to_json())) == {
        "uuid", "article", "summary", "reference"}
    m = io_lib.Message("u2", "art", "", "ref", tier="draft",
                       error="ServeOverloadError: shed")
    m2 = io_lib.Message.from_json(m.to_json())
    assert (m2.tier, m2.error) == ("draft", "ServeOverloadError: shed")
    # missing on the wire -> empty defaults, never a KeyError
    legacy = io_lib.Message.from_json(json.dumps({"uuid": "x"}))
    assert (legacy.tier, legacy.error) == ("", "")


# -- schemas / type matrix (CodingUtils.java:25-129) --

def test_schema_select_and_project():
    s = io_lib.ARTICLE_INPUT_SCHEMA
    sub = s.select(["uuid", "article", "reference"])
    assert sub.names == ["uuid", "article", "reference"]
    row = ("u", "art", "sum", "ref")
    assert s.project_row(row, ["uuid", "reference"]) == ("u", "ref")


def test_unsupported_type_raises():
    with pytest.raises(ValueError, match="Unsupported data type"):
        io_lib.RowSchema(["x"], ["COMPLEX128"])


def test_codec_all_supported_types():
    schema = io_lib.RowSchema(
        ["s", "b", "i8", "i64", "f32", "f64", "arr"],
        [io_lib.DataTypes.STRING, io_lib.DataTypes.BOOL,
         io_lib.DataTypes.INT_8, io_lib.DataTypes.INT_64,
         io_lib.DataTypes.FLOAT_32, io_lib.DataTypes.FLOAT_64,
         io_lib.DataTypes.FLOAT_32_ARRAY])
    row = ("hello", True, 7, 1 << 40, 0.5, 2.25, [1.0, 2.0, 3.0])
    data = codec_lib.encode_row(schema, row)
    back = codec_lib.decode_example(schema, data)
    assert back[0] == "hello"
    assert back[1] is True
    assert back[2] == 7 and back[3] == 1 << 40
    assert back[4] == pytest.approx(0.5) and back[5] == pytest.approx(2.25)
    assert back[6] == pytest.approx([1.0, 2.0, 3.0])


def test_example_coding_matrix():
    """encode+decode / encode-only / decode-only / neither
    (InputOutputTest.java:31-101)."""
    schema = io_lib.RowSchema(["a", "b"], [io_lib.DataTypes.STRING,
                                           io_lib.DataTypes.INT_32])
    row = ("x", 3)
    both = codec_lib.ExampleCoding(schema, schema)
    assert both.decode(both.encode(row)) == row
    enc_only = codec_lib.ExampleCoding(schema, None)
    wire = enc_only.encode(row)
    assert isinstance(wire, bytes)
    assert enc_only.decode(wire) is wire  # decode not configured: passthrough
    dec_only = codec_lib.ExampleCoding(None, schema)
    assert dec_only.encode(row) is row  # encode not configured
    assert dec_only.decode(both.encode(row)) == row
    neither = codec_lib.ExampleCoding(None, None)
    assert neither.encode(row) is row and neither.decode(b"z") == b"z"


# -- collection source/sink --

def test_collection_source_sink():
    rows = [(f"uuid-{i}", f"article {i} .", "", f"reference {i} .")
            for i in range(8)]  # TensorFlowTest.createArticleData shape
    src = io_lib.CollectionSource(rows)
    sink = io_lib.CollectionSink()
    for r in src.rows():
        sink.write(r)
    assert sink.rows == rows


# -- socket source/sink (testInferenceFromSocket) --

def test_socket_source_round_trip():
    rows = [io_lib.Message(f"u{i}", f"art {i}", "", "ref").to_json()
            for i in range(3)]

    class Handler(socketserver.StreamRequestHandler):
        def handle(self):
            for line in rows:
                self.wfile.write((line + "\n").encode())

    server = socketserver.TCPServer(("127.0.0.1", 0), Handler)
    port = server.server_address[1]
    t = threading.Thread(target=server.handle_request, daemon=True)
    t.start()
    src = io_lib.SocketSource("127.0.0.1", port, max_count=3)
    got = list(src.rows())
    server.server_close()
    assert [r[0] for r in got] == ["u0", "u1", "u2"]


def _one_shot_socket_server(lines):
    class Handler(socketserver.StreamRequestHandler):
        def handle(self):
            for line in lines:
                self.wfile.write((line + "\n").encode())

    server = socketserver.TCPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=server.handle_request, daemon=True).start()
    return server, server.server_address[1]


def test_socket_source_schema_mismatch_raises_typed(monkeypatch):
    """ISSUE 4 satellite: a payload that PARSES but cannot project onto
    the declared schema surfaces as the typed SchemaProjectionError
    (counted in pipeline/feeder_errors_total), never a silent stop."""
    from textsummarization_on_flink_tpu import obs
    from textsummarization_on_flink_tpu.obs import Registry

    lines = [io_lib.Message("u0", "art", "", "ref").to_json()]
    server, port = _one_shot_socket_server(lines)
    try:
        with obs.use_registry(Registry()) as reg:
            # a 2-column schema cannot hold the 4-column Message row
            src = io_lib.SocketSource(
                "127.0.0.1", port, max_count=1,
                schema=io_lib.RowSchema(["uuid", "article"],
                                        [io_lib.DataTypes.STRING] * 2))
            with pytest.raises(io_lib.SchemaProjectionError,
                               match="4 column"):
                list(src.rows())
            assert reg.counter("pipeline/feeder_errors_total").value == 1
    finally:
        server.server_close()


def test_socket_source_non_object_payload_raises_typed():
    """Valid JSON that is not a message object (a bare list) is a
    contract violation, not line noise: typed raise, counted."""
    from textsummarization_on_flink_tpu import obs
    from textsummarization_on_flink_tpu.obs import Registry

    server, port = _one_shot_socket_server(['[1, 2, 3]'])
    try:
        with obs.use_registry(Registry()) as reg:
            src = io_lib.SocketSource("127.0.0.1", port, max_count=1)
            with pytest.raises(io_lib.SchemaProjectionError,
                               match="not a message object"):
                list(src.rows())
            assert reg.counter("pipeline/feeder_errors_total").value == 1
    finally:
        server.server_close()


def test_socket_source_malformed_line_still_dropped_and_counted():
    """The pre-existing lossy-producer contract survives the satellite:
    BAD JSON is dropped-and-counted, the stream lives on."""
    from textsummarization_on_flink_tpu import obs
    from textsummarization_on_flink_tpu.obs import Registry

    good = io_lib.Message("u0", "art", "", "ref").to_json()
    server, port = _one_shot_socket_server(["{not json", good])
    try:
        with obs.use_registry(Registry()) as reg:
            src = io_lib.SocketSource("127.0.0.1", port, max_count=1)
            got = list(src.rows())
            assert [r[0] for r in got] == ["u0"]
            assert reg.counter("pipeline/codec_errors_total").value == 1
            assert reg.counter("pipeline/feeder_errors_total").value == 0
    finally:
        server.server_close()


def test_socket_sink_writes_json_lines():
    received = []
    ready = threading.Event()

    class Handler(socketserver.StreamRequestHandler):
        def handle(self):
            for _ in range(2):
                received.append(self.rfile.readline().decode().strip())
            ready.set()

    server = socketserver.TCPServer(("127.0.0.1", 0), Handler)
    port = server.server_address[1]
    t = threading.Thread(target=server.handle_request, daemon=True)
    t.start()
    sink = io_lib.SocketSink("127.0.0.1", port)
    sink.write(("u1", "a", "s", "r"))
    sink.write(("u2", "a2", "s2", "r2"))
    assert ready.wait(5)
    sink.close()
    server.server_close()
    assert json.loads(received[0])["uuid"] == "u1"
    assert json.loads(received[1])["summary"] == "s2"


# -- bridge queues: identical semantics for python and native impls --

@pytest.fixture(params=["py", "native"])
def record_queue(request):
    if request.param == "native":
        if not bridge_lib.native_available():
            pytest.skip("native bridge library not built")
        return bridge_lib.NativeRecordQueue(capacity=4)
    return bridge_lib.PyRecordQueue(capacity=4)


def test_bridge_fifo_and_eos(record_queue):
    q = record_queue
    for i in range(3):
        assert q.put(b"rec%d" % i)
    assert len(q) == 3
    assert q.get() == b"rec0"
    q.close()
    assert q.get() == b"rec1"
    assert q.get() == b"rec2"
    assert q.get(timeout=0.2) is None  # end of stream
    assert q.closed
    assert not q.put(b"late")  # puts after close fail


def test_bridge_immediate_flush(record_queue):
    """A result reaches the consumer without needing a second record
    (the Issue-6 regression test, SourceSinkTest.java's purpose)."""
    q = record_queue
    got = []

    def consume():
        got.append(q.get(timeout=5))

    t = threading.Thread(target=consume)
    t.start()
    time.sleep(0.05)  # consumer parked first
    t0 = time.time()
    q.put(b"only-record")
    t.join(timeout=5)
    assert got == [b"only-record"]
    assert time.time() - t0 < 1.0  # flushed immediately, no follow-up needed


def test_bridge_empty_record(record_queue):
    q = record_queue
    assert q.put(b"")
    assert q.get(timeout=1) == b""


def test_bridge_bounded_put_timeout(record_queue):
    q = record_queue
    for i in range(4):
        assert q.put(b"x")
    assert not q.put(b"overflow", timeout=0.1)  # full


def test_bridge_close_wakes_blocked_producer(record_queue):
    """close() must unblock a producer parked in a full-queue put()
    (semantics parity between native and python implementations)."""
    q = record_queue
    for _ in range(4):
        assert q.put(b"fill")
    result = []

    def producer():
        result.append(q.put(b"blocked"))  # parks: queue full

    t = threading.Thread(target=producer)
    t.start()
    time.sleep(0.1)
    assert t.is_alive()  # parked in put
    q.close()
    t.join(timeout=5)
    assert not t.is_alive()
    assert result == [False]


# --------------------------------------------------------------------------
# Kafka adapters against an in-memory fake broker (VERDICT r2 #7): the
# reference's own Kafka test is 100% commented out
# (KafkaSourceSinkTest.java:1-123); this proves the adapter logic —
# Message JSON -> source rows -> sink -> Message JSON, max_count
# bounding, per-record flush — without a broker process.
# --------------------------------------------------------------------------

class _FakeBroker:
    """Topic -> list of raw message bytes; shared by fake producer+consumer."""

    def __init__(self):
        self.topics = {}
        self.flushes = 0
        self.consumer_kwargs = None
        self.consumers = []

    def make_module(self):
        """A module-like namespace standing in for `kafka` in sys.modules."""
        import types

        broker = self

        class KafkaConsumer:
            def __init__(self, topic, bootstrap_servers=None, group_id=None,
                         value_deserializer=None, **kwargs):
                broker.consumer_kwargs = {
                    "topic": topic, "bootstrap_servers": bootstrap_servers,
                    "group_id": group_id, **kwargs}
                deser = value_deserializer or (lambda b: b)
                self.closed = False
                broker.consumers.append(self)

                class _Msg:
                    def __init__(self, value):
                        self.value = value

                self._msgs = [_Msg(deser(v))
                              for v in broker.topics.get(topic, [])]

            def __iter__(self):
                return iter(self._msgs)

            def close(self):  # the real KafkaConsumer leaves its group
                self.closed = True

        class KafkaProducer:
            def __init__(self, bootstrap_servers=None):
                self.closed = False

            def send(self, topic, value):
                broker.topics.setdefault(topic, []).append(value)

            def flush(self):
                broker.flushes += 1

            def close(self):
                self.closed = True

        mod = types.ModuleType("kafka")
        mod.KafkaConsumer = KafkaConsumer
        mod.KafkaProducer = KafkaProducer
        return mod


@pytest.fixture()
def fake_kafka(monkeypatch):
    import sys

    broker = _FakeBroker()
    monkeypatch.setitem(sys.modules, "kafka", broker.make_module())
    return broker


def test_kafka_roundtrip_through_fake_broker(fake_kafka):
    """Rows written by KafkaSink come back identically via KafkaSource —
    the full Message-JSON wire round trip of App.java's topic plumbing
    (flink_output producer -> flink_input consumer)."""
    rows = [(f"uuid-{i}", f"article {i}.", "", f"reference {i}.")
            for i in range(3)]
    sink = io_lib.KafkaSink("flink_output", "fake:9092")
    for row in rows:
        sink.write(row)
    sink.close()
    # one flush per record: the Issue-6 fix (results must not wait for
    # the NEXT record to arrive before becoming visible)
    assert fake_kafka.flushes == 3
    # the wire format is the reference's JSON Message, not pickled rows
    wire = fake_kafka.topics["flink_output"]
    assert all(isinstance(v, bytes) for v in wire)
    assert json.loads(wire[0].decode("utf-8"))["uuid"] == "uuid-0"

    src = io_lib.KafkaSource("flink_output", "fake:9092", group_id="g1")
    assert list(src.rows()) == rows
    assert fake_kafka.consumer_kwargs["bootstrap_servers"] == "fake:9092"
    assert fake_kafka.consumer_kwargs["group_id"] == "g1"
    # the consumer must leave its group on every exit path (an abandoned
    # one forces a rebalance per reconnect)
    assert all(c.closed for c in fake_kafka.consumers)


def test_kafka_source_max_count_bounds_stream(fake_kafka):
    """max_count parity with MessageDeserializationSchema.java:34-40 (the
    reference's bounded-stream hack): stop after N records even though
    the topic has more."""
    for i in range(5):
        fake_kafka.topics.setdefault("flink_train", []).append(
            io_lib.Message(uuid=f"u{i}", article=f"a{i}").to_json()
            .encode("utf-8"))
    src = io_lib.KafkaSource("flink_train", max_count=2)
    got = list(src.rows())
    assert [r[0] for r in got] == ["u0", "u1"]


def test_kafka_missing_dependency_error(monkeypatch):
    """Without kafka-python the adapters must fail with a clear,
    actionable error at USE time (construction stays cheap)."""
    import builtins
    import sys

    monkeypatch.delitem(sys.modules, "kafka", raising=False)
    real_import = builtins.__import__

    def no_kafka(name, *a, **kw):
        if name == "kafka" or name.startswith("kafka."):
            raise ImportError("No module named 'kafka'")
        return real_import(name, *a, **kw)

    monkeypatch.setattr(builtins, "__import__", no_kafka)
    with pytest.raises(RuntimeError, match="kafka-python"):
        list(io_lib.KafkaSource("t").rows())
    with pytest.raises(RuntimeError, match="kafka-python"):
        io_lib.KafkaSink("t").write(("u", "a", "", "r"))
