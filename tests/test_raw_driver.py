"""Deprecated raw driver (Summarization.java parity): direct train+infer."""

import pytest

from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.data.vocab import Vocab
from textsummarization_on_flink_tpu.pipeline import raw_driver
from textsummarization_on_flink_tpu.pipeline.io import CollectionSource

WORDS = ("article reference the quick brown fox jumped over lazy dog "
         "0 1 2 3 4 5 6 7").split()


@pytest.mark.slow
def test_raw_training_then_inference(tmp_path):
    vocab = Vocab(words=WORDS)
    rows = [(f"uuid-{i}", f"article {i} .", "", f"reference {i} .")
            for i in range(8)]
    hps = HParams(mode="train", num_steps=1, batch_size=4, hidden_dim=8,
                  emb_dim=6, vocab_size=24, max_enc_steps=12, max_dec_steps=6,
                  beam_size=2, min_dec_steps=1, max_oov_buckets=4,
                  log_root=str(tmp_path), exp_name="raw")
    with pytest.warns(DeprecationWarning):
        state = raw_driver.training(hps, CollectionSource(rows), vocab=vocab)
    assert int(state.step) == 1
    with pytest.warns(DeprecationWarning):
        sink = raw_driver.inference(hps, CollectionSource(rows[:3]),
                                    vocab=vocab)
    assert len(sink.rows) == 3
