"""ROUGE scorer: hand-computed values, properties, and file-layout eval."""

import os

import numpy as np
import pytest

from textsummarization_on_flink_tpu.evaluate import rouge


def test_rouge1_exact():
    # peer: "the cat sat" vs model: "the cat ran" -> 2/3 overlap both ways
    s = rouge.rouge_n(["the cat sat"], ["the cat ran"], 1)
    assert s.precision == pytest.approx(2 / 3)
    assert s.recall == pytest.approx(2 / 3)
    assert s.f == pytest.approx(2 / 3)


def test_rouge2_exact():
    # bigrams peer: {the cat, cat sat}; model: {the cat, cat ran} -> 1 hit
    s = rouge.rouge_n(["the cat sat"], ["the cat ran"], 2)
    assert s.precision == pytest.approx(1 / 2)
    assert s.recall == pytest.approx(1 / 2)


def test_rouge1_clipping():
    # repeated peer tokens are clipped by model counts
    s = rouge.rouge_n(["the the the the"], ["the cat"], 1)
    assert s.recall == pytest.approx(1 / 2)  # 1 hit / 2 model tokens
    assert s.precision == pytest.approx(1 / 4)


def test_rouge_l_exact():
    # LCS("the cat sat on the mat", "the cat ate the mat") per Lin 2004
    s = rouge.rouge_l(["the cat sat on the mat"], ["the cat ate the mat"])
    # LCS = the cat the mat (4); model 5 words, peer 6
    assert s.recall == pytest.approx(4 / 5)
    assert s.precision == pytest.approx(4 / 6)


def test_rouge_l_union():
    # union LCS across peer sentences (Lin 2004 §3.2 example):
    # model "w1 w2 w3 w4 w5", peers "w1 w2 6 7 8" and "w1 3 8 9 w5"
    s = rouge.rouge_l(["w1 w2 6 7 8", "w1 3 8 9 w5"], ["w1 w2 w3 w4 w5"])
    assert s.recall == pytest.approx(3 / 5)  # union hits {w1, w2, w5}
    assert s.precision == pytest.approx(3 / 10)


def test_identical_summaries_score_one():
    doc = ["some sentence here", "another one follows"]
    for m, s in rouge.score_document(doc, doc).items():
        assert s.f == pytest.approx(1.0), m


def test_disjoint_summaries_score_zero():
    out = rouge.score_document(["aaa bbb"], ["ccc ddd"])
    for m, s in out.items():
        assert s.f == 0.0, m


def test_tokenize_case_and_punct():
    assert rouge.tokenize("The Cat, sat!") == ["the", "cat", "sat"]


def test_corpus_and_ci_shapes():
    peers = [["the cat sat"], ["a dog ran away"], ["hello world"]]
    models = [["the cat ran"], ["a dog ran home"], ["hello there world"]]
    res = rouge.score_corpus(peers, models, n_bootstrap=200)
    for m in ("rouge_1", "rouge_2", "rouge_l"):
        for stat in ("f_score", "recall", "precision"):
            v = res[m][stat]
            lo, hi = res[m][f"{stat}_cb"], res[m][f"{stat}_ce"]
            assert 0.0 <= lo <= hi <= 1.0
            assert 0.0 <= v <= 1.0
    # mean within its own CI
    assert res["rouge_1"]["f_score_cb"] <= res["rouge_1"]["f_score"] \
        <= res["rouge_1"]["f_score_ce"]


def test_rouge_eval_file_layout(tmp_path):
    ref_dir = tmp_path / "reference"
    dec_dir = tmp_path / "decoded"
    ref_dir.mkdir()
    dec_dir.mkdir()
    docs = [("the cat sat on the mat", "the cat sat on the mat"),
            ("a dog barked loudly", "a dog howled loudly")]
    for i, (ref, dec) in enumerate(docs):
        (ref_dir / f"{i:06d}_reference.txt").write_text(ref + "\n")
        (dec_dir / f"{i:06d}_decoded.txt").write_text(dec + "\n")
    res = rouge.rouge_eval(str(ref_dir), str(dec_dir), n_bootstrap=100)
    assert res["rouge_1"]["f_score"] > 0.8
    text = rouge.rouge_log(res, str(tmp_path / "out"))
    assert "ROUGE-1:" in text and "ROUGE-2:" in text and "ROUGE-l:" in text
    assert "confidence interval" in text
    assert os.path.exists(tmp_path / "out" / "ROUGE_results.txt")


def test_rouge_eval_missing_decoded(tmp_path):
    ref_dir = tmp_path / "reference"
    dec_dir = tmp_path / "decoded"
    ref_dir.mkdir()
    dec_dir.mkdir()
    (ref_dir / "000000_reference.txt").write_text("x\n")
    with pytest.raises(FileNotFoundError):
        rouge.rouge_eval(str(ref_dir), str(dec_dir))
