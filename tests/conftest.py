"""Test harness configuration.

Tests run on CPU with 8 virtual devices so multi-chip sharding
(Mesh/pjit/shard_map) is exercised without TPU hardware; the driver's
dryrun_multichip does the same.  Must run before jax initializes a backend.
"""

import os

# Forced assignment: the shell profile exports JAX_PLATFORMS=axon (TPU);
# tests must run on the virtual CPU mesh regardless.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# The env var alone is not always honored once the axon TPU plugin has
# registered, so force the platform through jax.config as well.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


# Build the native library at test time (a fresh clone + toolchain must
# run the native-queue and native-chunk-reader tests; without a compiler
# the native-parametrized tests skip via native_available()).
try:
    from textsummarization_on_flink_tpu.native import build as _native_build

    _native_build.build()
except Exception:  # noqa: BLE001 — optional dependency, skip-gated tests
    pass


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running test (>=20s: multiprocess runs, dryruns, "
        "full-scale compiles).  Fast iteration: -m 'not slow' (~half the "
        "suite wall clock); the full suite gates round-end.")
