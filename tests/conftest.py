"""Test harness configuration.

Tests run on CPU with 8 virtual devices so multi-chip sharding
(Mesh/pjit/shard_map) is exercised without TPU hardware; the driver's
dryrun_multichip does the same.  Must run before jax initializes a backend.
"""

import os

# Forced assignment: the shell profile exports JAX_PLATFORMS=axon (TPU);
# tests must run on the virtual CPU mesh regardless.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# Share the repo-local persistent compile cache the bench/dryrun
# children already use (__graft_entry__.set_default_compile_cache):
# cache keys include the HLO + backend/compile options, so CPU test
# programs can't collide with TPU bench entries, and repeat suite runs
# skip recompiles.  The 0.5s floor catches this suite's many ~1s model
# compiles that the 1s default would skip.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(__file__), "..", ".jax_cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")

# The env var alone is not always honored once the axon TPU plugin has
# registered, so force the platform through jax.config as well.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# the axon sitecustomize hook imports jax before this file runs, so the
# env vars above can land too late — force the cache through jax.config
jax.config.update("jax_compilation_cache_dir",
                  os.environ["JAX_COMPILATION_CACHE_DIR"])
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


# Build the native library at test time (a fresh clone + toolchain must
# run the native-queue and native-chunk-reader tests; without a compiler
# the native-parametrized tests skip via native_available()).
try:
    from textsummarization_on_flink_tpu.native import build as _native_build

    _native_build.build()
except Exception:  # noqa: BLE001 — optional dependency, skip-gated tests
    pass


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running test (>=20s: multiprocess runs, dryruns, "
        "full-scale compiles).  Fast iteration: -m 'not slow' (~half the "
        "suite wall clock); the full suite gates round-end.")
    config.addinivalue_line(
        "markers",
        "chaos: deterministic fault-injection recovery test "
        "(RESILIENCE.md).  Select with -m chaos (scripts/chaos.sh runs "
        "these under TS_FAULTS sweeps); all are seeded and CPU-fast, so "
        "they also run in the default suite.")
