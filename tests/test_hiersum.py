"""serve/hiersum.py — hierarchical long-document summarization
(ISSUE 19): chunking, the reduce-input budget, document framing, the
fan-out/reduce driver, and the end-to-end long-document pipeline.

The acceptance run (TestHierPipelineEndToEnd) feeds a 50k-token
document through a REAL SocketSource as framed rows, reassembles and
map-reduces it through ``SummarizationModel.transform(hierarchical=
True)`` over a real ServingServer (stub extractive decoder — the
scheduling, dedup, and tracing contracts are decoder-independent),
then APPENDS two chunks' worth of text via a second frame-set of the
same doc id and pins the dedup floor exactly: every pre-append chunk
cache-hits at submit, the engine decodes only the appended chunks +
one reduce.  The whole fan-out tree is then reconstructed from the
run's events.jsonl by scripts/trace_summary.py --request.

The chaos case injects a ``serve.dispatch`` fault under one chunk
mid-fan-out and checks the failure contract: that chunk alone fails
typed, the parent rejects exactly once with HierPartialFailureError
naming it, no reduce is ever submitted, no chunk future is orphaned.
"""

import json
import os
import socketserver
import sys
import threading

import pytest

from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.data.vocab import Vocab
from textsummarization_on_flink_tpu.decode.decoder import DecodedResult
from textsummarization_on_flink_tpu.decode.reduce import (
    assemble_reduce_input,
)
from textsummarization_on_flink_tpu.obs import Registry
from textsummarization_on_flink_tpu.obs.export import MemorySink
from textsummarization_on_flink_tpu.pipeline import codec as codec_lib
from textsummarization_on_flink_tpu.pipeline import estimator as est_lib
from textsummarization_on_flink_tpu.pipeline import io as io_lib
from textsummarization_on_flink_tpu.serve import server as server_mod
from textsummarization_on_flink_tpu.serve.errors import (
    HierPartialFailureError,
)
from textsummarization_on_flink_tpu.serve.frontdoor import article_key
from textsummarization_on_flink_tpu.serve.hiersum import (
    DocumentSession,
    HierarchicalSummarizer,
    chunk_document,
    ngram_containment,
)
from textsummarization_on_flink_tpu.serve.server import ServingServer

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))

import trace_summary  # noqa: E402

WORDS = ["w"]


@pytest.fixture(autouse=True)
def _isolated_obs():
    with obs.use_registry(Registry()) as reg:
        yield reg


# -- chunking --------------------------------------------------------------

class TestChunkDocument:
    def test_no_overlap_splits_on_stride(self):
        assert chunk_document("a b c d e f", 2) == ["a b", "c d", "e f"]

    def test_overlap_repeats_boundary_words(self):
        assert chunk_document("a b c d e f g h", 4, 1) == \
            ["a b c d", "d e f g", "g h"]

    def test_last_chunk_reaches_document_end(self):
        chunks = chunk_document("a b c d e", 2)
        assert chunks[-1] == "e"
        assert " ".join(chunks) == "a b c d e"

    def test_single_chunk_document(self):
        assert chunk_document("a b", 8, 2) == ["a b"]

    def test_empty_document_yields_nothing(self):
        assert chunk_document("   ", 4) == []

    def test_append_keeps_prior_chunks_byte_identical(self):
        """The cache lever: chunk boundaries are a pure function of
        word index, so growing the document leaves every previously
        COMPLETE chunk unchanged (same words -> same article_key)."""
        words = [f"w{i}" for i in range(100)]
        doc = " ".join(words)
        grown = " ".join(words + [f"w{i}" for i in range(100, 180)])
        old = chunk_document(doc, 16, 4)
        new = chunk_document(grown, 16, 4)
        # every old chunk that was full (16 words) survives verbatim
        full = [c for c in old if len(c.split()) == 16]
        assert new[:len(full)] == full
        assert [article_key(c, 16) for c in new[:len(full)]] == \
            [article_key(c, 16) for c in full]

    def test_validation(self):
        with pytest.raises(ValueError, match="chunk_words"):
            chunk_document("a", 0)
        with pytest.raises(ValueError, match="overlap_words"):
            chunk_document("a", 4, 4)


# -- copy fidelity ---------------------------------------------------------

class TestNgramContainment:
    def test_fully_grounded_scores_one(self):
        assert ngram_containment("a b c".split(),
                                 ["x a b c y".split()]) == 1.0

    def test_fabricated_ngrams_lower_the_score(self):
        s = ngram_containment("a b z q".split(), ["a b c d".split()])
        assert 0.0 < s < 1.0

    def test_union_over_sources(self):
        assert ngram_containment(
            "a b c d".split(), ["a b".split(), "c d".split(),
                                "b c".split()]) == 1.0

    def test_short_text_falls_back_to_unigrams(self):
        assert ngram_containment(["a"], [["a", "b"]]) == 1.0
        assert ngram_containment(["z"], [["a", "b"]]) == 0.0

    def test_empty_target_scores_one(self):
        assert ngram_containment([], [["a"]]) == 1.0


# -- reduce-input budgeting ------------------------------------------------

class TestAssembleReduceInput:
    def test_verbatim_when_under_budget(self):
        assert assemble_reduce_input([["a", "b"], ["c"]], 10) == "a b c"

    def test_over_budget_keeps_every_chunk_represented(self):
        out = assemble_reduce_input(
            [["a1", "a2", "a3"], ["b1", "b2", "b3"], ["c1", "c2", "c3"]],
            6).split()
        # equal front-budget per chunk: no chunk is silently deleted
        assert out == ["a1", "a2", "b1", "b2", "c1", "c2"]

    def test_extreme_fanout_hard_cap_drops_trailing_chunks_last(self):
        out = assemble_reduce_input([[f"w{i}"] for i in range(8)], 3)
        assert out.split() == ["w0", "w1", "w2"]

    def test_empty_summaries_skipped_and_all_empty_yields_empty(self):
        assert assemble_reduce_input([[], ["a"], []], 4) == "a"
        assert assemble_reduce_input([[], []], 4) == ""

    def test_validation(self):
        with pytest.raises(ValueError, match="max_words"):
            assemble_reduce_input([["a"]], 0)


# -- document framing (pipeline/codec.py) ----------------------------------

class TestDocumentFraming:
    def test_frame_roundtrip(self, _isolated_obs):
        rows = codec_lib.frame_document_rows("d1", "a b c d e", "ref", 2)
        assert [r[0] for r in rows] == ["d1#1/3", "d1#2/3", "d1#3/3"]
        assert rows[0][2] == "ref" and rows[1][2] == ""
        asm = codec_lib.DocumentAssembler(registry=_isolated_obs)
        out = [asm.feed(r) for r in rows]
        assert out[:2] == [None, None]
        assert out[2] == ("d1", "a b c d e", "ref")

    def test_out_of_order_parts_reassemble(self, _isolated_obs):
        rows = codec_lib.frame_document_rows("d", "a b c d", "r", 2)
        asm = codec_lib.DocumentAssembler(registry=_isolated_obs)
        assert asm.feed(rows[1]) is None
        assert asm.feed(rows[0]) == ("d", "a b c d", "r")

    def test_unframed_rows_pass_through(self, _isolated_obs):
        asm = codec_lib.DocumentAssembler(registry=_isolated_obs)
        row = ("plain-uuid", "article", "ref")
        assert asm.feed(row) == row

    def test_single_frame_document_still_framed(self, _isolated_obs):
        rows = codec_lib.frame_document_rows("d", "a b", "r", 8)
        assert rows == [("d#1/1", "a b", "r")]
        asm = codec_lib.DocumentAssembler(registry=_isolated_obs)
        assert asm.feed(rows[0]) == ("d", "a b", "r")

    def test_doc_id_may_complete_again_as_a_revision(self, _isolated_obs):
        asm = codec_lib.DocumentAssembler(registry=_isolated_obs)
        assert asm.feed(("d#1/1", "first", "r")) == ("d", "first", "r")
        assert asm.feed(("d#1/1", "second", "")) == ("d", "second", "")

    def test_mismatched_total_raises_typed_and_counts(self, _isolated_obs):
        asm = codec_lib.DocumentAssembler(registry=_isolated_obs)
        asm.feed(("d#1/3", "a", ""))
        with pytest.raises(codec_lib.DocumentFramingError,
                           match="part total"):
            asm.feed(("d#2/4", "b", ""))
        assert _isolated_obs.counter(
            "pipeline/codec_errors_total").value == 1

    def test_duplicate_and_out_of_range_raise(self, _isolated_obs):
        asm = codec_lib.DocumentAssembler(registry=_isolated_obs)
        asm.feed(("d#1/2", "a", ""))
        with pytest.raises(codec_lib.DocumentFramingError,
                           match="duplicate"):
            asm.feed(("d#1/2", "a", ""))
        with pytest.raises(codec_lib.DocumentFramingError,
                           match="outside"):
            asm.feed(("e#3/2", "x", ""))

    def test_pending_names_incomplete_docs(self, _isolated_obs):
        asm = codec_lib.DocumentAssembler(registry=_isolated_obs)
        asm.feed(("d#1/2", "a", ""))
        assert asm.pending() == ["d"]

    def test_frame_validation(self):
        with pytest.raises(ValueError, match="frame_words"):
            codec_lib.frame_document_rows("d", "a", "", 0)
        with pytest.raises(ValueError, match="no words"):
            codec_lib.frame_document_rows("d", "  ", "", 4)


# -- fan-out driver over a fake fleet (trace threading) --------------------

class _FakeSubmitSurface:
    """Minimal submit surface: records (uuid, article, tier, trace) and
    hands back unresolved futures the test settles by hand."""

    serve_mode = "microbatch"

    def __init__(self, registry):
        self.registry = registry
        self.submits = []

    def submit(self, article, uuid="", reference="", block=False,
               timeout=None, tier="", trace=None, tenant=""):
        from textsummarization_on_flink_tpu.serve.queue import ServeFuture

        fut = ServeFuture(uuid, registry=self.registry)
        fut.trace = trace
        self.submits.append(
            {"uuid": uuid, "article": article, "tier": tier,
             "trace": trace, "future": fut})
        return fut

    def resolve(self, uuid, words):
        for s in self.submits:
            if s["uuid"] == uuid and not s["future"].done():
                s["future"]._resolve(DecodedResult(
                    uuid=uuid, article=s["article"], decoded_words=words,
                    reference="", abstract_sents=[]))
                return
        raise AssertionError(f"no pending submit {uuid!r}")


def _hier_hps(**kw):
    base = dict(mode="decode", batch_size=4, vocab_size=8,
                max_enc_steps=16, max_dec_steps=6, beam_size=2,
                min_dec_steps=1, max_oov_buckets=4,
                hier_chunk_words=4, hier_overlap_words=0)
    base.update(kw)
    return HParams(**base)


class TestFanOutDriver:
    def test_one_parent_trace_threads_every_sub_request(
            self, _isolated_obs):
        surface = _FakeSubmitSurface(_isolated_obs)
        hs = HierarchicalSummarizer(surface, _hier_hps(),
                                    registry=_isolated_obs)
        parent = hs.summarize("a b c d e f g h", uuid="doc")
        assert [s["uuid"] for s in surface.submits] == \
            ["doc/c0", "doc/c1"]
        assert [s["tier"] for s in surface.submits] == \
            ["greedy", "greedy"]
        for s in surface.submits:
            assert s["trace"].trace_id == parent.trace.trace_id
            assert s["trace"].parent_id == parent.trace.span_id
        surface.resolve("doc/c0", ["s0", "."])
        surface.resolve("doc/c1", ["s1", "."])
        # the reduce fired off the LAST chunk resolution, beam tier,
        # same trace, concatenated chunk summaries as its article
        red = surface.submits[2]
        assert red["uuid"] == "doc/reduce"
        assert red["tier"] == "beam"
        assert red["article"] == "s0 . s1 ."
        assert red["trace"].trace_id == parent.trace.trace_id
        surface.resolve("doc/reduce", ["s0", "."])
        res = parent.result(timeout=1)
        assert res.uuid == "doc"
        assert res.summary == "s0 ."
        assert res.chunk_count == 2
        assert res.copy_fidelity == 1.0  # every bigram came from a chunk
        assert _isolated_obs.counter("serve/hier_documents_total").value \
            == 1
        assert _isolated_obs.counter("serve/hier_chunks_total").value == 2
        assert _isolated_obs.counter("serve/hier_reduce_total").value == 1

    def test_session_requires_empty_article_and_tracks_reuse(
            self, _isolated_obs):
        surface = _FakeSubmitSurface(_isolated_obs)
        hs = HierarchicalSummarizer(surface, _hier_hps(),
                                    registry=_isolated_obs)
        sess = DocumentSession("d", "a b c d")
        with pytest.raises(ValueError, match="session"):
            hs.summarize("explicit text", session=sess)
        fut = hs.summarize("", session=sess)
        assert fut.uuid == "d@r1"
        surface.resolve("d@r1/c0", ["s", "."])
        surface.resolve("d@r1/reduce", ["s", "."])
        assert fut.result(timeout=1).reused_chunks == 0
        sess.append("e f g h")
        fut2 = hs.summarize("", session=sess)
        assert fut2.uuid == "d@r2"
        # chunk 0 unchanged -> reused; chunk 1 is new
        surface.resolve("d@r2/c0", ["s", "."])
        surface.resolve("d@r2/c1", ["t", "."])
        surface.resolve("d@r2/reduce", ["s", ".", "t", "."])
        assert fut2.result(timeout=1).reused_chunks == 1
        assert _isolated_obs.counter(
            "serve/hier_chunks_reused_total").value == 1

    def test_empty_document_raises(self, _isolated_obs):
        surface = _FakeSubmitSurface(_isolated_obs)
        hs = HierarchicalSummarizer(surface, _hier_hps(),
                                    registry=_isolated_obs)
        with pytest.raises(ValueError, match="no words"):
            hs.summarize("   ", uuid="d")

    def test_failed_chunk_rejects_parent_typed_after_all_resolve(
            self, _isolated_obs):
        surface = _FakeSubmitSurface(_isolated_obs)
        hs = HierarchicalSummarizer(surface, _hier_hps(),
                                    registry=_isolated_obs)
        parent = hs.summarize("a b c d e f g h", uuid="doc")
        surface.submits[0]["future"]._reject(RuntimeError("boom"))
        assert not parent.done()  # waits for EVERY outstanding chunk
        surface.resolve("doc/c1", ["s1", "."])
        with pytest.raises(HierPartialFailureError) as ei:
            parent.result(timeout=1)
        assert ei.value.failed.keys() == {0}
        assert ei.value.chunks == 2
        assert len(surface.submits) == 2  # no reduce over a partial map
        assert _isolated_obs.counter(
            "serve/hier_partial_failures_total").value == 1
        assert _isolated_obs.counter("serve/hier_reduce_total").value == 0

    def test_reduce_failure_rejects_parent_typed(self, _isolated_obs):
        surface = _FakeSubmitSurface(_isolated_obs)
        hs = HierarchicalSummarizer(surface, _hier_hps(),
                                    registry=_isolated_obs)
        parent = hs.summarize("a b c d e f g h", uuid="doc")
        surface.resolve("doc/c0", ["s0", "."])
        surface.resolve("doc/c1", ["s1", "."])
        surface.submits[2]["future"]._reject(RuntimeError("boom"))
        with pytest.raises(HierPartialFailureError) as ei:
            parent.result(timeout=1)
        assert ei.value.failed.keys() == {"reduce"}


# -- the OTHER submit surface: hiersum over a FleetRouter ------------------

class TestHierOverFleet:
    def test_fanout_threads_one_trace_through_fleet_replicas(
            self, _isolated_obs):
        """The summarizer is surface-agnostic: the same fan-out runs
        over a FleetRouter, and the parent TraceContext threads through
        the router into every replica-level sub-request."""
        from tests.test_fleet import make_fleet

        router, servers, _ = make_fleet(
            3, registry=_isolated_obs, hier_chunk_words=4,
            max_enc_steps=16)
        hs = HierarchicalSummarizer(router, router._hps,
                                    registry=_isolated_obs)
        parent = hs.summarize("a b c d e f g h i j k l", uuid="doc")
        subs = [(u, f) for s in servers for (u, f) in s.submits]
        assert sorted(u for u, _ in subs) == \
            ["doc/c0", "doc/c1", "doc/c2"]
        for _, f in subs:
            assert f.trace is not None
            assert f.trace.trace_id == parent.trace.trace_id
        for u, f in subs:
            f._resolve(DecodedResult(
                uuid=u, article="", decoded_words=["s", "."],
                reference="", abstract_sents=[]))
        red = [(u, f) for s in servers for (u, f) in s.submits
               if u == "doc/reduce"]
        assert len(red) == 1
        assert red[0][1].trace.trace_id == parent.trace.trace_id
        red[0][1]._resolve(DecodedResult(
            uuid="doc/reduce", article="", decoded_words=["s", "."],
            reference="", abstract_sents=[]))
        res = parent.result(timeout=5)
        assert res.chunk_count == 3
        assert res.summary == "s ."


# -- extractive stub decoder (jax-free) ------------------------------------

class ExtractiveStubDecoder:
    """decode_batch stub whose summary is the article's first
    `summary_words` words — extractive by construction, so the reduce
    output's n-grams are grounded in its inputs and the copy-fidelity
    floor is meaningful, not vacuous."""

    def __init__(self, summary_words: int = 8):
        self.summary_words = summary_words
        self.decoded = 0  # real examples served (the dedup pins)

    def should_degrade(self, deadline):
        return False

    def decode_batch(self, batch, deadline=None, tier=None):
        out = []
        for b in range(len(batch.uuids)):
            if not batch.real_mask[b]:
                continue
            self.decoded += 1
            words = batch.original_articles[b].split()[:self.summary_words]
            out.append(DecodedResult(
                uuid=batch.uuids[b],
                article=batch.original_articles[b],
                decoded_words=words, reference=batch.references[b],
                abstract_sents=[], tier=tier or "beam"))
        return out

    def maybe_reload_checkpoint(self, last):
        return last


# -- chaos: one chunk's dispatch fails mid-fan-out -------------------------

class TestHierChaos:
    def test_dispatch_fault_fails_one_chunk_parent_rejects_once(
            self, _isolated_obs):
        """serve.dispatch fires exactly once (max=1) with every chunk
        dispatching alone (serve_max_batch=1): ONE chunk fails typed,
        the rest complete, the parent rejects exactly once naming the
        failed chunk, the reduce is never submitted, and no chunk
        future is orphaned."""
        vocab = Vocab(words=WORDS)
        hps = _hier_hps(
            vocab_size=vocab.size(), serve_max_queue=64,
            serve_max_batch=1, serve_max_wait_ms=5.0,
            faults="serve.dispatch:1.0:0:1")
        server = ServingServer(hps, vocab,
                               decoder=ExtractiveStubDecoder(),
                               registry=_isolated_obs)
        hs = HierarchicalSummarizer(server, hps, registry=_isolated_obs)
        with server:
            parent = hs.summarize(" ".join(f"w{i}" for i in range(16)),
                                  uuid="doc")
            with pytest.raises(HierPartialFailureError) as ei:
                parent.result(timeout=30)
        err = ei.value
        assert err.chunks == 4
        assert len(err.failed) == 1
        (idx, cause), = err.failed.items()
        assert isinstance(idx, int)
        assert isinstance(cause, RuntimeError)
        assert "injected serve.dispatch fault" in str(cause)
        reg = _isolated_obs
        assert reg.counter("serve/hier_partial_failures_total").value == 1
        assert reg.counter("serve/hier_reduce_total").value == 0
        # no orphans: every chunk resolved (3 completions + 1 error)
        assert reg.counter("serve/completed_total").value == 3
        assert reg.counter("serve/errors_total").value == 1
        # exactly-once on the parent: a second resolution would have
        # tripped ServeFuture's assertion inside the callbacks above


# -- end-to-end: 50k-token doc over a socket, append, fan-out tree ---------

CHUNK_WORDS = 512
OVERLAP_WORDS = 64
STRIDE = CHUNK_WORDS - OVERLAP_WORDS
DOC_CHUNKS = 112
APPEND_CHUNKS = 2
FRAME_WORDS = 2048
#: initial doc ends exactly on a chunk boundary, so every pre-append
#: chunk stays byte-identical after the append (the dedup pin)
DOC_WORDS = CHUNK_WORDS + (DOC_CHUNKS - 1) * STRIDE  # 50240 ~ 50k tokens


def _socket_source(lines, max_count):
    class Handler(socketserver.StreamRequestHandler):
        def handle(self):
            for line in lines:
                self.wfile.write((line + "\n").encode())

    srv = socketserver.TCPServer(("127.0.0.1", 0), Handler)
    port = srv.server_address[1]
    threading.Thread(target=srv.handle_request, daemon=True).start()
    return srv, io_lib.SocketSource("127.0.0.1", port, max_count=max_count)


def _hier_model(tmp_path, argv):
    m = est_lib.SummarizationModel()
    (m.set_inference_selected_cols(["uuid", "article", "reference"])
      .set_inference_output_cols(["uuid", "article", "summary",
                                  "reference"])
      .set_inference_output_types([io_lib.DataTypes.STRING] * 4))
    m.set_inference_hyper_params(argv)
    return m


class TestHierPipelineEndToEnd:
    @pytest.fixture()
    def e2e(self, tmp_path, monkeypatch, _isolated_obs):
        """One full run: 50k-token doc framed over a REAL socket ->
        transform(hierarchical=True) -> append frame-set -> sink; the
        unified event stream lands in a MemorySink and is written out
        as events.jsonl for the trace-tree assertions."""
        import shlex

        vocab = Vocab(words=WORDS)
        decoder = ExtractiveStubDecoder()
        events = MemorySink()
        _isolated_obs.event_sink = events
        real_server = server_mod.ServingServer

        def stub_server(hps, vocab_, train_dir=None, decode_root=None,
                        registry=None):
            # the real ServingServer, minus the checkpoint-backed
            # decoder the transform path would otherwise construct
            return real_server(hps, vocab_, decoder=decoder,
                               registry=registry)

        monkeypatch.setattr(server_mod, "ServingServer", stub_server)
        hps = HParams(
            mode="decode", batch_size=4, vocab_size=vocab.size(),
            max_enc_steps=CHUNK_WORDS, max_dec_steps=8, beam_size=2,
            min_dec_steps=1, max_oov_buckets=4, serve_max_queue=256,
            serve_max_wait_ms=5.0, serve_coalesce=True,
            serve_cache_entries=256, hier_chunk_words=CHUNK_WORDS,
            hier_overlap_words=OVERLAP_WORDS,
            log_root=str(tmp_path), exp_name="exp")
        doc = " ".join(f"w{i}" for i in range(DOC_WORDS))
        tail = " ".join(f"w{DOC_WORDS + i}"
                        for i in range(APPEND_CHUNKS * STRIDE))
        frames = codec_lib.frame_document_rows("doc50k", doc, "the ref",
                                               FRAME_WORDS)
        frames += codec_lib.frame_document_rows("doc50k", tail, "",
                                                FRAME_WORDS)
        lines = [io_lib.Message(u, a, "", r).to_json()
                 for (u, a, r) in frames]
        srv, source = _socket_source(lines, max_count=len(lines))
        model = _hier_model(tmp_path, shlex.split(hps.to_argv()))
        sink = io_lib.CollectionSink()
        try:
            model.with_vocab(vocab).transform(source, sink,
                                              hierarchical=True)
        finally:
            srv.server_close()
        path = os.path.join(str(tmp_path), "events.jsonl")
        with open(path, "w", encoding="utf-8") as f:
            for rec in events.records():
                f.write(json.dumps(rec) + "\n")
        return {"sink": sink, "decoder": decoder, "reg": _isolated_obs,
                "events_path": path, "doc": doc, "tail": tail}

    def test_two_revisions_emitted_with_append_dedup_pinned(self, e2e):
        rows = e2e["sink"].rows
        assert [r[0] for r in rows] == ["doc50k@r1", "doc50k@r2"]
        # revision articles are the accumulated session text
        assert rows[0][1] == e2e["doc"]
        assert rows[1][1] == f"{e2e['doc']} {e2e['tail']}"
        assert rows[0][3] == "the ref"
        for r in rows:
            assert len(r) == 4 and r[2]  # non-empty summary out
        reg = e2e["reg"]
        assert reg.counter("serve/hier_documents_total").value == 2
        assert reg.counter("serve/hier_chunks_total").value == \
            2 * DOC_CHUNKS + APPEND_CHUNKS
        # THE dedup pins (by construction, not by policy): every
        # pre-append chunk cache-hits at submit; the engine decodes
        # only the appended chunks + one reduce on the second pass
        assert reg.counter(
            "serve/hier_chunk_cache_hits_total").value == DOC_CHUNKS
        assert reg.counter(
            "serve/hier_chunks_reused_total").value == DOC_CHUNKS
        assert e2e["decoder"].decoded == \
            (DOC_CHUNKS + 1) + (APPEND_CHUNKS + 1)
        assert reg.counter("serve/hier_partial_failures_total").value == 0

    def test_copy_fidelity_floor(self, e2e):
        h = e2e["reg"].histogram("serve/hier_copy_fidelity")
        assert h.count == 2  # one reduce scored per revision
        assert h.mean >= 0.5, (
            f"reduce output fidelity {h.mean:.3f} below the committed "
            f"0.5 floor — the reduce pass is fabricating n-grams its "
            f"chunk inputs never contained")

    def test_fanout_tree_reconstructs_from_events_jsonl(self, e2e):
        tl = trace_summary.request_timeline([e2e["events_path"]],
                                            "doc50k@r1")
        kids = tl["children"]
        assert len(kids) == DOC_CHUNKS + 1
        chunks = [c for c in kids if c["kind"] == "chunk"]
        assert [c["chunk"] for c in chunks] == list(range(DOC_CHUNKS))
        assert kids[-1]["kind"] == "reduce"
        assert all(c["tier"] == "greedy" for c in chunks)
        assert all(not c["cache_hit"] for c in chunks)  # cold pass
        assert all(c["bucket"] is not None for c in chunks)
        # the append revision: every pre-append chunk is a cache hit
        tl2 = trace_summary.request_timeline([e2e["events_path"]],
                                             "doc50k@r2")
        kids2 = tl2["children"]
        assert len(kids2) == DOC_CHUNKS + APPEND_CHUNKS + 1
        hits = [c for c in kids2 if c["cache_hit"]]
        assert len(hits) == DOC_CHUNKS
        assert [c["chunk"] for c in hits] == list(range(DOC_CHUNKS))

    def test_cli_renders_fanout_tree(self, e2e, capsys):
        rc = trace_summary.main(
            [e2e["events_path"], "--request", "doc50k@r1"])
        out = capsys.readouterr().out
        assert rc == 0
        assert f"fan-out ({DOC_CHUNKS} chunks + 1 reduce):" in out
        assert "doc50k@r1/c0" in out and "doc50k@r1/reduce" in out
        assert "tier greedy" in out and "tier beam" in out


# -- pipeline framing errors surface through the stage ---------------------

class TestHierTransformValidation:
    def test_truncated_frame_stream_fails_the_job(
            self, tmp_path, monkeypatch, _isolated_obs):
        import shlex

        vocab = Vocab(words=WORDS)
        real_server = server_mod.ServingServer
        monkeypatch.setattr(
            server_mod, "ServingServer",
            lambda hps, v, train_dir=None, decode_root=None,
            registry=None: real_server(
                hps, v, decoder=ExtractiveStubDecoder(),
                registry=registry))
        hps = HParams(
            mode="decode", batch_size=4, vocab_size=vocab.size(),
            max_enc_steps=16, max_dec_steps=6, beam_size=2,
            min_dec_steps=1, max_oov_buckets=4, serve_max_queue=16,
            serve_max_wait_ms=5.0, hier_chunk_words=8,
            log_root=str(tmp_path), exp_name="exp")
        model = _hier_model(tmp_path, shlex.split(hps.to_argv()))
        source = io_lib.CollectionSource(
            [("d#1/2", "half a document", "", "")])
        with pytest.raises(RuntimeError, match="incomplete document"):
            model.with_vocab(vocab).transform(source, hierarchical=True)
