"""pipeline/bridge.py close semantics (ISSUE 2 satellite).

The record queues are the driver<->worker data plane; a shutdown race
here either deadlocks the pipeline (a put/get parked forever) or loses
the end-of-stream signal.  These tests pin the contract for BOTH
implementations (PyRecordQueue and, when built, NativeRecordQueue):

  * put() after close() fails (returns False) without blocking;
  * get() after close() drains the backlog, then returns None;
  * a get() timeout and end-of-stream both return None — `closed`
    is the documented disambiguator;
  * concurrent producers/consumers parked in blocking calls are all
    released by a close() from a third thread.
"""

import threading
import time

import pytest

from textsummarization_on_flink_tpu.pipeline import bridge as bridge_lib


@pytest.fixture(params=["py", "native"])
def record_queue(request):
    if request.param == "native":
        if not bridge_lib.native_available():
            pytest.skip("native bridge library not built")
        return bridge_lib.NativeRecordQueue(capacity=4)
    return bridge_lib.PyRecordQueue(capacity=4)


def test_put_after_close_fails_fast(record_queue):
    q = record_queue
    assert q.put(b"before")
    q.close()
    assert not q.put(b"after")           # rejected...
    assert not q.put(b"after", timeout=0.0)  # ...without blocking
    assert len(q) == 1                   # and nothing was enqueued


def test_get_after_close_drains_then_end_of_stream(record_queue):
    q = record_queue
    for i in range(3):
        assert q.put(b"r%d" % i)
    q.close()
    # the backlog survives close() — consumers finish in-flight work
    assert [q.get(timeout=1) for _ in range(3)] == [b"r0", b"r1", b"r2"]
    # then every further get is end-of-stream, immediately
    assert q.get(timeout=0.0) is None
    assert q.get() is None  # even an unbounded get must not block


def test_timeout_vs_end_of_stream_disambiguation(record_queue):
    q = record_queue
    # open + empty: None means TIMEOUT (the stream may still produce)
    assert q.get(timeout=0.05) is None
    assert not q.closed
    q.close()
    # closed + drained: None means END OF STREAM
    assert q.get(timeout=0.05) is None
    assert q.closed


def test_concurrent_producer_consumer_shutdown(record_queue):
    """close() from a third thread must release a producer parked in a
    full-queue put() AND a consumer parked in an empty-queue get(), with
    no deadlock and no spurious records."""
    q = record_queue
    for _ in range(4):
        assert q.put(b"fill")  # capacity reached

    outcomes = {}

    def producer():
        # parked: the queue is full and nobody is draining
        outcomes["put"] = q.put(b"overflow", timeout=10)

    def consumer():
        drained = []
        while True:
            rec = q.get(timeout=10)
            if rec is None:
                break
            drained.append(rec)
        outcomes["drained"] = drained

    threads = [threading.Thread(target=producer),
               threading.Thread(target=consumer)]
    for t in threads:
        t.start()
    # let both park (producer on full-put — the consumer may free it —
    # then both sides block on the close)
    time.sleep(0.2)
    q.close()
    for t in threads:
        t.join(timeout=5)
    assert not any(t.is_alive() for t in threads)  # released, no deadlock
    # the consumer saw only real records (4 fills, plus the producer's
    # overflow record iff its put won the race before close)
    drained = outcomes["drained"]
    assert drained[:4] == [b"fill"] * 4
    assert len(drained) in (4, 5)
    if len(drained) == 5:
        assert drained[4] == b"overflow"
        assert outcomes["put"] is True


def test_close_idempotent_and_stable(record_queue):
    q = record_queue
    q.put(b"x")
    q.close()
    q.close()  # double-close is safe
    assert q.closed
    assert q.get(timeout=0.5) == b"x"
    assert q.get(timeout=0.0) is None
