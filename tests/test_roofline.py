"""Roofline-report tool tests (scripts/roofline.py): the XLA cost-model
numbers must exist, be self-consistent, and agree with bench.py's
analytic FLOPs model to within fusion/backward-counting slack — the
cross-check that keeps the MFU denominator honest."""

import importlib.util
import json
import os
import sys

import pytest

spec = importlib.util.spec_from_file_location(
    "roofline", os.path.join(os.path.dirname(__file__), "..", "scripts",
                             "roofline.py"))
roofline = importlib.util.module_from_spec(spec)
sys.modules["roofline"] = roofline
spec.loader.exec_module(roofline)


def test_tiny_config_costs_are_consistent():
    bench_mod = roofline._load_bench()
    rec = roofline.analyze("train_tiny", "v5e", bench_mod, None)
    assert rec["xla_flops"] > 0
    assert rec["bytes_accessed"] > 0
    # XLA counts every op (elementwise, softmax, full backward as
    # written); the analytic model is matmul MACs x3.  They must agree
    # to within fusion/counting slack, not orders of magnitude.
    assert 0.5 <= rec["flops_ratio_xla_over_analytic"] <= 6.0, rec
    # floors: min_step is the max of the two floors, and samples/s match
    assert rec["min_step_ms"] == max(rec["compute_floor_ms"],
                                     rec["bandwidth_floor_ms"])
    assert rec["max_samples_per_sec"] > 0
    assert rec["bound"] in ("bandwidth", "compute")


def test_byte_diet_lever_configs_resolve():
    """The lever rows (ISSUE 5) must resolve through the SAME env
    mapping the sweep uses — hps_for is the single source, so the
    roofline always describes exactly the config bench.py measures."""
    bench_mod = roofline._load_bench()
    assert roofline.hps_for("train_b16_losschunk", bench_mod).loss_chunk \
        == 25
    assert roofline.hps_for("train_b16_optbf16",
                            bench_mod).opt_state_dtype == "bfloat16"
    both = roofline.hps_for("train_b16_bytediet", bench_mod)
    assert both.loss_chunk == 25 and both.opt_state_dtype == "bfloat16"
    tfc = roofline.hps_for("train_transformer_losschunk", bench_mod)
    assert tfc.model_family == "transformer" and tfc.loss_chunk == 25
    # every lever row's declared baseline is itself a known config
    for tag, base in roofline._BYTE_DIET_BASELINES.items():
        assert tag in roofline.CONFIGS and base in roofline.CONFIGS


def test_measured_join_uses_live_records_only(tmp_path):
    path = tmp_path / "BENCH_ALL.jsonl"
    rows = [
        {"metric": "train_samples_per_sec", "run": "train_b16",
         "value": 600.0, "step_time_ms": 26.7,
         "captured_at": "2026-07-30T10:00:00Z"},
        {"metric": "train_samples_per_sec", "run": "train_b64",
         "value": 0.0, "error": "timed out"},
        {"metric": "train_samples_per_sec", "run": "train_scaled",
         "value": 300.0, "step_time_ms": 50.0, "stale": True,
         "captured_at": "2026-07-30T09:00:00Z"},
    ]
    path.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    m = roofline.measured_rows(str(path))
    assert set(m) == {"train_b16"}  # error + stale rows excluded
    assert m["train_b16"]["step_time_ms"] == 26.7
    assert roofline.measured_rows(str(tmp_path / "missing.jsonl")) == {}


@pytest.mark.slow
def test_cli_json_smoke(capsys):
    rc = roofline.main(["--configs", "train_tiny", "--json",
                        "--bench", "/nonexistent"])
    assert rc == 0
    out = [json.loads(l) for l in
           capsys.readouterr().out.strip().splitlines()]
    assert out and out[0]["config"] == "train_tiny"
    assert "measured_step_ms" not in out[0]


@pytest.mark.slow
def test_attribution_phases_consistent():
    """Phase attribution: forward ⊆ fwd+bwd ⊆ full step in both flops
    and bytes, and the diffs are what the table reports."""
    bench_mod = roofline._load_bench()
    hps = roofline.hps_for("train_tiny", bench_mod)
    att = roofline.attribution_of(hps)
    for k in ("flops", "bytes"):
        assert att["forward"][k] > 0
        assert att["fwd+bwd"][k] > 0
        assert att["full step"][k] > 0
        # the diffs must be exactly what the table reports
        assert att["backward (diff)"][k] == (att["fwd+bwd"][k]
                                             - att["forward"][k])
        assert att["optimizer (diff)"][k] == (att["full step"][k]
                                              - att["fwd+bwd"][k])
    # flop counts are fusion-independent, so phase monotonicity is a
    # real invariant for them; bytes-accessed is fusion-dependent
    # (roofline.py docstring) and only sanity-bounded here
    assert att["fwd+bwd"]["flops"] >= att["forward"]["flops"]
    assert att["full step"]["flops"] >= att["fwd+bwd"]["flops"]
    assert att["fwd+bwd"]["bytes"] >= 0.5 * att["forward"]["bytes"]
    # pg family: the encoder seam splits forward
    assert att["encoder fwd"]["flops"] > 0
    assert att["forward"]["flops"] >= att["encoder fwd"]["flops"]
    assert att["dec+loss fwd (diff)"]["flops"] == (
        att["forward"]["flops"] - att["encoder fwd"]["flops"])
    # bytes diffs may undershoot when fusion overlaps the standalone
    # phases (docstring); bound loosely rather than exactly
    assert att["dec+loss fwd (diff)"]["bytes"] >= \
        -0.25 * att["forward"]["bytes"]
